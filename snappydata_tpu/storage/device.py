"""Manifest → stacked device arrays (the input side of every jitted plan).

TPU equivalent of the reference's ColumnBatchIterator + per-column decoders
feeding whole-stage-codegen (ColumnTableScan.doProduce core/.../columnar/
ColumnTableScan.scala:186): instead of a generated scalar loop pulling one
batch at a time, a table snapshot is materialized as ONE [num_batches,
capacity] device array per referenced column plus a shared validity mask
(row-count + delete-mask + delta merges already applied). Batch count is
padded to a power of two so the jitted plan's input shapes — and therefore
the XLA executable — are stable as the table grows.

Per-batch min/max stats ride along host-side for predicate batch skipping
(ref: stats-row filter codegen, columnBatchesSkipped metric,
ColumnTableScan.scala:115-130).
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import weakref
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from snappydata_tpu import types as T
from snappydata_tpu.storage.table_store import ColumnTableData, Manifest
from snappydata_tpu.utils import locks


def _next_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def batch_bucket(n: int) -> int:
    """Padded BATCH-axis size: the smallest of {2^k, 1.5 * 2^k} >= n.
    Pure pow2 bucketing wasted up to ~50% of every device pass on dead
    padded batches (TPC-H SF4: 184 batches -> 256, +39% rows swept by
    every reduction); the intermediate 1.5x buckets cap the waste at
    ~33% while still bounding executable shapes to two per octave."""
    if n <= 1:
        return 1
    p = 1 << (n - 1).bit_length()
    return p * 3 // 4 if p * 3 // 4 >= n else p


# --- tiled scans: bind a WINDOW of the batch axis ------------------------
# For tables whose decoded columns exceed the HBM budget, the session
# streams scan units (column batches + row-buffer chunks) through the same
# compiled program tile by tile (ref: batch-at-a-time iteration in
# ColumnFormatIterator, SURVEY §5 "long-context" — table ≫ HBM).

_scan_windows: contextvars.ContextVar = contextvars.ContextVar(
    "scan_windows", default=None)


@contextlib.contextmanager
def scan_window(data, lo: int, hi: int, manifest=None, tile_units=None):
    """Restrict build_device_table for `data` to units [lo, hi).
    `manifest` pins one snapshot across a multi-tile pass so concurrent
    mutations can't make tiles disagree about the table version.
    `tile_units` is the NOMINAL window width of the pass — the last
    window may be truncated, and current_scan_scale needs the nominal
    width to compute the true tile count."""
    cur = dict(_scan_windows.get() or {})
    cur[id(data)] = (int(lo), int(hi), manifest,
                     int(tile_units) if tile_units else int(hi - lo))
    tok = _scan_windows.set(cur)
    try:
        yield
    finally:
        _scan_windows.reset(tok)


def scan_window_active() -> bool:
    """True inside any scan_window context (a tiled pass is binding)."""
    return bool(_scan_windows.get())


def scan_unit_count(data, manifest=None) -> int:
    """Number of bindable units (column batches + row-buffer chunks)."""
    if manifest is None:
        from snappydata_tpu.storage import mvcc

        manifest = mvcc.snapshot_of(data)
    n_chunks = -(-manifest.row_count // data.capacity) \
        if manifest.row_count > 0 else 0
    return len(manifest.views) + n_chunks


@dataclasses.dataclass
class DeviceTable:
    schema: T.Schema
    num_batches: int           # padded
    capacity: int
    valid: jnp.ndarray         # bool [B, C]
    # col_idx -> [B, C] decoded plate, OR a compressed-domain plate
    # (device_decode.CodePlate/RlePlate/BitPlate) when the column stays
    # resident encoded — consumers branch structurally
    columns: Dict[int, jnp.ndarray]
    dictionaries: Dict[int, np.ndarray]      # string col -> host values
    stats_min: Dict[int, np.ndarray]         # numeric col -> host [B]
    stats_max: Dict[int, np.ndarray]
    total_rows: int
    nulls: Dict[int, Optional[jnp.ndarray]] = dataclasses.field(
        default_factory=dict)                # col_idx -> bool [B, C] or None
    # col_idx -> (sorted host dicts [B, Dp] f64, sizes [B]) for every
    # column with VALUE_DICT batches — the dictionary-domain batch
    # skipper probes equality literals here at bind time (sizes[i] == 0
    # means batch i carries no dictionary: always keep)
    dict_domains: Dict[int, tuple] = dataclasses.field(default_factory=dict)

    def column(self, idx: int) -> jnp.ndarray:
        return self.columns[idx]


def _compressed_mode(is_str: bool, dec_exact: bool, use_dd: bool,
                     cols_enc, any_delta: bool, has_row_chunks: bool,
                     code_ok: bool, count: bool = False,
                     table=None) -> Optional[str]:
    """Per-column compressed-domain decision: 'dict' | 'rle' | 'bitset'
    when the column can stay resident encoded, None for a decoded bind.
    With count=True (the cache-miss build), every decode-first reroute
    of a compressible column is counted by reason
    (compressed_fallback_*); under scan_compressed_domain='on' even
    never-compressible columns count, so a misconfigured table is
    diagnosable from the dashboard."""
    from snappydata_tpu import config
    from snappydata_tpu.storage.device_decode import compressed_fallback
    from snappydata_tpu.storage.encoding import Encoding

    knob = str(config.global_properties().get(
        "scan_compressed_domain", "auto") or "auto").lower()
    comp = {Encoding.VALUE_DICT: "dict", Encoding.RUN_LENGTH: "rle",
            Encoding.BOOLEAN_BITSET: "bitset"}
    encs = {c.encoding for c in cols_enc}
    compressible = bool(encs & set(comp))
    forced = knob == "on"
    if is_str or not cols_enc:
        return None   # string codes ARE the compressed domain already
    if knob == "off" or knob not in ("on", "auto"):
        if count and compressible:
            compressed_fallback("disabled", table=table)
        return None

    def reject(reason: str, always: bool = False) -> None:
        if count and (compressible or (forced and always)):
            compressed_fallback(reason, table=table)

    if dec_exact:
        reject("decimal_exact")
        return None
    if not use_dd:
        reject("device_decode_off")
        return None
    if not code_ok:
        reject("join_key")
        return None
    if any_delta:
        reject("deltas")
        return None
    if has_row_chunks:
        reject("row_buffer")
        return None
    if len(encs) == 1 and next(iter(encs)) in comp:
        return comp[next(iter(encs))]
    reject("mixed_encoding" if compressible else "not_encoded",
           always=True)
    return None


def build_device_table(data: ColumnTableData, manifest: Optional[Manifest],
                       col_indices: Sequence[int],
                       code_ok: bool = True) -> DeviceTable:
    """Materialize `col_indices` of a snapshot on device, with caching keyed
    on manifest version (so repeated queries over an unchanged table upload
    nothing).  `code_ok=False` (device-join relations, whose cached build
    artifacts index flat decoded layouts) forces decoded plates."""
    from snappydata_tpu.parallel.mesh import MeshContext

    ctx = MeshContext.current()
    # shared unit-splitting contract with the host fallback (_scan_units):
    # pinned snapshot, batches-then-row-chunks order, window slice
    manifest, views, row_chunks, window = _scan_units(data, manifest)
    # cache key includes the mesh token (placement differs under a mesh;
    # token is process-unique, unlike id() which gets reused after GC)
    # and the scan window (tiles of one version coexist under the LRU)
    cache_key = (manifest.version, ctx.token if ctx else None, window)
    cache = data._device_cache.setdefault(cache_key, {})
    # prune stale versions AND stale mesh placements (keep only this exact
    # placement + the previous version of it) so a loop that recreates
    # meshes doesn't pin duplicate device copies of every column —
    # EXCEPT versions an active snapshot pin holds: a long pinned scan
    # re-binding its (old) epoch per tile must not have its plates
    # evicted by concurrent ingest binding newer versions (the
    # degradation ladder can still trim them via mvcc.trim_unpinned)
    from snappydata_tpu.storage import mvcc as _mvcc

    _pinned_vers = _mvcc.pinned_versions(data)
    # list() snapshots are C-atomic under the GIL: a prefetch worker
    # (storage/prefetch.py) inserts window entries concurrently, and a
    # plain comprehension over the live dict would raise RuntimeError
    for k in [k for k in list(data._device_cache)
              if k != cache_key and k[0] not in _pinned_vers
              and not (k[1] == cache_key[1]
                       and k[0] >= manifest.version - 1)]:
        data._device_cache.pop(k, None)
        _cache_budget.forget(data._device_cache, k)
    if window is not None and not _cache_budget.enabled():
        # no byte budget to evict for us: a tile pass must not accumulate
        # every window's arrays (the table is oversized by definition —
        # that would re-materialize it on device); keep only this tile.
        # The session's double-buffered tile pass still holds the
        # PREVIOUS tile's plates alive through its in-flight dispatch —
        # dropping the cache entry here only releases our reference, so
        # peak residency is bounded at two tiles, exactly the pipeline
        # depth the pass throttles to.
        # …EXCEPT windows a live prefetch pass owns (storage/prefetch):
        # evicting the look-ahead tile the worker just uploaded would
        # turn the prefetcher into a strict slowdown
        from snappydata_tpu.storage import prefetch as _prefetch

        _kept = _prefetch.keep_windows(data)
        for k in [k for k in list(data._device_cache)
                  if k != cache_key and k[2] is not None
                  and k[2] not in _kept]:
            data._device_cache.pop(k, None)
            _cache_budget.forget(data._device_cache, k)

    schema = data.schema
    cap = data.capacity
    b_actual = len(views) + len(row_chunks)
    b = batch_bucket(b_actual) if data_pow2() else max(1, b_actual)
    b = max(b, 1)
    if ctx is not None:
        # batch axis is the sharded axis: pad to a MESH-DIVISIBLE ladder
        # size (shard_bucket keeps the padded size on the same
        # {2^k, 1.5·2^k} ladder the single-device bind uses, so a
        # resharded table reuses executable shapes instead of
        # re-specializing every static key)
        from snappydata_tpu.parallel.mesh import round_up_to, shard_bucket

        b = shard_bucket(b, ctx.num_devices) if data_pow2() \
            else round_up_to(b, ctx.num_devices)

    # device.transfer failpoint: one hit per table build (not per column
    # — the build is the unit a caller can retry); an injected raise
    # models a flaky accelerator runtime rejecting the host→HBM upload
    from snappydata_tpu.fault import failpoints

    failpoints.hit("device.transfer")

    def _place(host_array):
        from snappydata_tpu.parallel.mesh import shard_batches

        return shard_batches(host_array, ctx) if ctx is not None \
            else jnp.asarray(host_array)

    if "valid" in cache:
        # a partially-filled entry pins the padded batch shape: a
        # MIGRATED cache (live mesh rebalance) keeps its old-mesh
        # padding, and a column bound fresh into it must match — mixing
        # paddings inside one entry produced (old_b, cap) valid vs
        # (new_b, cap) plates (found by the rebalance-under-traffic
        # test).  Old paddings stay shard-able: migration only runs
        # when the new mesh size divides them.
        b = int(cache["valid"].shape[0])
    if "valid" not in cache:
        valid = np.zeros((b, cap), dtype=np.bool_)
        for i, v in enumerate(views):
            valid[i] = v.live_mask()
        for j, (_, take) in enumerate(row_chunks):
            valid[len(views) + j, :take] = True
        if window is not None:  # tile row count ≠ manifest total
            cache["nrows"] = int(valid.sum())
        cache["valid"] = _place(valid)

    columns: Dict[int, jnp.ndarray] = {}
    dicts: Dict[int, np.ndarray] = {}
    stats_min: Dict[int, np.ndarray] = {}
    stats_max: Dict[int, np.ndarray] = {}
    nulls: Dict[int, Optional[jnp.ndarray]] = {}
    dict_domains: Dict[int, tuple] = {}
    for ci in col_indices:
        f = schema.fields[ci]
        if isinstance(f.dtype, T.StructType) \
                and struct_device_eligible(f.dtype):
            # STRUCT: one [B, C] plate per field (string fields as
            # per-field dictionary codes) — element_at field access
            # becomes a static plate pick in the compiled program
            key = ("scol", ci)
            if key not in cache:
                cache[key] = _build_struct_column(
                    data, manifest, views, row_chunks, ci, f, b, cap,
                    _place)
            columns[ci], stats_min[ci], stats_max[ci], nulls[ci] = cache[key]
            continue
        if isinstance(f.dtype, T.MapType) and map_device_eligible(f.dtype):
            # MAP<STRING, V>: key-code plates + value plates (numeric
            # values as-is, string values as codes) + lengths +
            # value-null bits — feeds the device element_at lowering
            key = ("mcol", ci)
            if key not in cache:
                cache[key] = _build_map_column(
                    data, manifest, views, row_chunks, ci, f, b, cap,
                    _place)
            columns[ci], stats_min[ci], stats_max[ci], nulls[ci] = cache[key]
            continue
        if isinstance(f.dtype, T.ArrayType) and (
                T.is_numeric(f.dtype.element)
                or f.dtype.element.name == "string"):
            # fixed-width device layout for numeric AND string arrays:
            # value plates [B, C, L] (string elements ride as int32
            # dictionary codes, like scalar string columns) + lengths
            # [B, C] + element-null bits — feeds the device lowering of
            # size/element_at/array_contains (ref: SerializedArray
            # fixed-width fast path)
            key = ("acol", ci)
            if key not in cache:
                cache[key] = _build_array_column(
                    data, manifest, views, row_chunks, ci, f, b, cap,
                    _place)
            columns[ci], stats_min[ci], stats_max[ci], nulls[ci] = cache[key]
            continue
        is_str = f.dtype.name == "string"
        if is_str:
            dicts[ci] = data.dictionary(ci)
        from snappydata_tpu import config
        from snappydata_tpu.storage.encoding import (Encoding,
                                                     decode_validity)

        dt = f.dtype.device_dtype()
        # exact decimals: HOST plates are float64 (the SQL value
        # domain — WAL, deltas, stats, hosteval all ride it); the
        # DEVICE plate is the scaled int64 unscaled value, converted
        # here at bind (types.DecimalType docstring)
        dec_exact = f.dtype.name == "decimal" and dt.kind == "i"
        # compressed-domain eligibility is mesh-agnostic: encoded plates
        # are [B, ...]-leading pytrees, so they shard over the mesh the
        # same way decoded plates do (per-device HBM keeps the encoded
        # capacity win — the decoded plate never materializes globally)
        use_dd_col = (not is_str and not dec_exact
                      and config.global_properties().device_decode)
        cols_enc = [v.batch.columns[ci] for v in views]
        # only deltas that target THIS column disqualify its encoded
        # form (update deltas replace values; deletes ride live_mask)
        any_delta = any(any(d[0] == ci for d in v.deltas) for v in views)
        cd_mode = _compressed_mode(is_str, dec_exact, use_dd_col,
                                   cols_enc, any_delta, bool(row_chunks),
                                   code_ok)
        key = ("ccol", ci) if cd_mode else ("col", ci)
        if key not in cache:
            # itemized fallback counting happens exactly once per build
            # (cache miss), decoded OR compressed — so every decode-first
            # reroute of a compressible column shows up
            _compressed_mode(is_str, dec_exact, use_dd_col,
                             cols_enc, any_delta, bool(row_chunks),
                             code_ok, count=True, table=data)
        if cd_mode and key not in cache:
            # compressed-domain bind: the column stays RESIDENT encoded;
            # predicates run on codes/runs, values decode lazily
            # in-trace (engine/exprs.py) — no decoded plate in HBM
            from snappydata_tpu.storage import device_decode as _dd
            from snappydata_tpu.storage import bitmask

            null_mask = np.zeros((b, cap), dtype=np.bool_)
            any_null = False
            smin = np.full(b, np.nan)
            smax = np.full(b, np.nan)
            for i, (v, col) in enumerate(zip(views, cols_enc)):
                nm = v.null_mask(ci)
                if nm is not None:
                    null_mask[i] = nm
                    any_null = True
                st = col.stats
                if st is not None and st.min is not None:
                    smin[i], smax[i] = float(st.min), float(st.max)
                elif cd_mode == "dict" and len(col.dictionary):
                    smin[i] = float(np.min(col.dictionary))
                    smax[i] = float(np.max(col.dictionary))
                elif cd_mode == "rle" and len(col.data):
                    smin[i] = float(np.min(col.data))
                    smax[i] = float(np.max(col.data))
                elif cd_mode == "bitset" and col.num_rows:
                    bits = bitmask.unpack(col.data, col.num_rows)
                    smin[i] = float(bits.min())
                    smax[i] = float(bits.max())
            if cd_mode == "dict":
                plate, host_dicts, dict_sizes = _dd.code_plates(
                    cols_enc, b, cap, dt, place=_place)
                cache[("dictdom", ci)] = (host_dicts, dict_sizes)
            elif cd_mode == "rle":
                plate = _dd.rle_plates(cols_enc, b, cap, dt, place=_place)
            else:
                plate = _dd.bit_plates(cols_enc, b, cap, place=_place)
            cache[key] = (plate, smin, smax,
                          _place(null_mask) if any_null else None)
        if key not in cache:
            stacked = np.zeros((b, cap), dtype=dt)
            null_mask = np.zeros((b, cap), dtype=np.bool_)
            any_null = False
            smin = np.full(b, np.nan)
            smax = np.full(b, np.nan)
            # in-trace decode: RLE / bitset batches without deltas ship
            # their ENCODED arrays to the device and expand there (ref
            # decode-at-scan: ColumnTableScan.scala:684). Mesh binds keep
            # host decode on THIS decoded-plate path (the eager .at[].set
            # assembly below places unsharded) — fully-encoded columns
            # skip it entirely via the sharded compressed plates above.
            # Encoded decimal forms are host-domain floats, so the exact
            # path keeps host decode + scaled conversion.
            use_dd = use_dd_col and ctx is None
            dd_rle: list = []      # (batch row, EncodedColumn)
            dd_bits: list = []
            dd_vd: list = []       # VALUE_DICT: uint8 codes + value dict
            for i, v in enumerate(views):
                col = v.batch.columns[ci]
                device_decodable = (
                    use_dd and not v.deltas
                    and col.encoding in (Encoding.RUN_LENGTH,
                                         Encoding.BOOLEAN_BITSET,
                                         Encoding.VALUE_DICT))
                nm = v.null_mask(ci)  # delta-aware (updates can set/clear)
                if nm is not None:
                    null_mask[i] = nm
                    any_null = True
                st = col.stats
                if st is not None and not v.deltas and not is_str \
                        and st.min is not None:
                    smin[i], smax[i] = float(st.min), float(st.max)
                elif device_decodable:
                    # stats over the compact encoded form: a SUPERSET of
                    # the live range (deletes ignored), so predicate
                    # batch-skipping stays conservative-correct
                    if col.encoding == Encoding.RUN_LENGTH and \
                            len(col.data):
                        smin[i] = float(np.min(col.data))
                        smax[i] = float(np.max(col.data))
                    elif col.encoding == Encoding.BOOLEAN_BITSET and \
                            col.num_rows:
                        from snappydata_tpu.storage import bitmask

                        bits = bitmask.unpack(col.data, col.num_rows)
                        smin[i] = float(bits.min())
                        smax[i] = float(bits.max())
                    elif col.encoding == Encoding.VALUE_DICT and \
                            len(col.dictionary):
                        smin[i] = float(np.min(col.dictionary))
                        smax[i] = float(np.max(col.dictionary))
                if device_decodable:
                    if col.encoding == Encoding.RUN_LENGTH:
                        dd_rle.append((i, col))
                    elif col.encoding == Encoding.VALUE_DICT:
                        dd_vd.append((i, col))
                    else:
                        dd_bits.append((i, col))
                    continue
                decoded = v.decoded_column(ci)
                stacked[i] = T.decimal_to_unscaled(f.dtype, decoded) \
                    if dec_exact else decoded
                if not (st is not None and not v.deltas and not is_str
                        and st.min is not None) \
                        and not is_str and v.batch.num_rows:
                    live = decoded[v.live_mask()]
                    if live.size:
                        smin[i], smax[i] = float(live.min()), float(live.max())
            for j, (pos, take) in enumerate(row_chunks):
                src = manifest.row_arrays[ci][pos:pos + take]
                chunk_nulls = None
                if manifest.row_nulls and manifest.row_nulls[ci] is not None:
                    chunk_nulls = manifest.row_nulls[ci][pos:pos + take]
                if is_str:
                    lookup = data._dict_lookup[ci]
                    # None (SQL NULL) maps to code 0; nullability is carried
                    # by validity, not the code stream
                    vals = np.fromiter(
                        (lookup[x] if x is not None else 0 for x in src),
                        dtype=np.int32, count=take)
                    none_mask = np.fromiter((x is None for x in src),
                                            dtype=np.bool_, count=take)
                    chunk_nulls = none_mask if chunk_nulls is None \
                        else (chunk_nulls | none_mask)
                elif dec_exact:
                    vals = T.decimal_to_unscaled(f.dtype, src)
                else:
                    vals = np.asarray(src).astype(dt)
                if chunk_nulls is not None and chunk_nulls.any():
                    null_mask[len(views) + j, :take] = chunk_nulls
                    any_null = True
                stacked[len(views) + j, :take] = vals
                if not is_str and take:
                    # stats stay in the HOST (unscaled) domain — that's
                    # what sargable predicate literals compare against
                    stat_src = np.asarray(src, dtype=np.float64) \
                        if dec_exact else vals
                    smin[len(views) + j] = float(stat_src.min())
                    smax[len(views) + j] = float(stat_src.max())
            if dd_rle or dd_bits or dd_vd:
                # only the NON-device-decoded rows cross the link as
                # decoded plates: upload them compactly and assemble the
                # full [b, cap] plate on device (HBM-side scatter copies,
                # not PCIe transfer)
                dd_set = {i for i, _ in dd_rle} | {i for i, _ in dd_bits} \
                    | {i for i, _ in dd_vd}
                keep = [i for i in range(b) if i not in dd_set]
                placed = jnp.zeros((b, cap), dtype=dt)
                nonzero_keep = [i for i in keep if i < b_actual]
                if nonzero_keep:
                    placed = placed.at[np.array(nonzero_keep)].set(
                        jnp.asarray(stacked[np.array(nonzero_keep)]))
                if dd_rle:
                    from snappydata_tpu.storage.device_decode import \
                        rle_views_to_plate

                    idxs = np.array([i for i, _ in dd_rle])
                    dec = rle_views_to_plate([c for _, c in dd_rle],
                                             cap, dt)
                    placed = placed.at[idxs].set(dec.astype(dt))
                if dd_bits:
                    from snappydata_tpu.storage.device_decode import \
                        bitset_views_to_plate

                    idxs = np.array([i for i, _ in dd_bits])
                    dec = bitset_views_to_plate([c for _, c in dd_bits],
                                                cap)
                    placed = placed.at[idxs].set(dec.astype(dt))
                if dd_vd:
                    from snappydata_tpu.storage.device_decode import \
                        valdict_views_to_plate

                    idxs = np.array([i for i, _ in dd_vd])
                    dec = valdict_views_to_plate([c for _, c in dd_vd],
                                                 cap, dt)
                    placed = placed.at[idxs].set(dec)
            else:
                placed = _place(stacked)
            cache[key] = (placed, smin, smax,
                          _place(null_mask) if any_null else None)
            if not is_str:
                dom = _dict_domain(views, cols_enc, ci, b)
                if dom is not None:
                    cache[("dictdom", ci)] = dom
        columns[ci], stats_min[ci], stats_max[ci], nulls[ci] = cache[key]
        dom = cache.get(("dictdom", ci))
        if dom is not None:
            dict_domains[ci] = dom

    if _cache_budget.enabled():
        _cache_budget.touch(data._device_cache, cache_key,
                            _entry_bytes(cache), data=data)
    return DeviceTable(schema, b, cap, cache["valid"], columns, dicts,
                       stats_min, stats_max,
                       cache.get("nrows", manifest.total_rows()), nulls,
                       dict_domains)


def _dict_domain(views, cols_enc, ci: int, b: int):
    """(sorted host dicts [b, Dp] f64, sizes [b]) of a column's
    VALUE_DICT batches — the dictionary-domain batch skipper's probe
    surface.  Batches without a usable dictionary (other encodings, or
    update deltas touching this column) report size 0 = always keep."""
    from snappydata_tpu.storage.encoding import Encoding

    vd = [(i, c) for i, (v, c) in enumerate(zip(views, cols_enc))
          if c.encoding == Encoding.VALUE_DICT
          and not any(d[0] == ci for d in v.deltas)
          and c.dictionary is not None and len(c.dictionary)]
    if not vd:
        return None
    d_pad = max(len(c.dictionary) for _, c in vd)
    host = np.zeros((b, d_pad), dtype=np.float64)
    sizes = np.zeros(b, dtype=np.int64)
    for i, c in vd:
        d = np.asarray(c.dictionary, dtype=np.float64)
        host[i, :d.shape[0]] = d
        if d.shape[0] < d_pad:
            host[i, d.shape[0]:] = d[-1]
        sizes[i] = d.shape[0]
    return host, sizes


def numeric_key_domain(data, ci: int, max_card: int):
    """Table-global sorted value domain of a numeric column at the
    current (pinned) snapshot — the code space of the vdict group-by
    lane (engine/executor._emit_aggregate).  A group index computed as
    searchsorted(domain, value) is dense and data-independent across
    batches, so dict-encoded key plates group by PURE CODE ARITHMETIC
    (per-batch codes remapped through this domain) with no gather.

    Returned in the column's DEVICE dtype: the per-batch plate
    dictionaries and decoded plates are cast to the same dtype from the
    same host values, so searchsorted hits are exact even where f32
    rounding collapses distinct f64 inputs (the decoded path would
    merge those groups identically).

    Returns None — the caller's cue to keep the generic hash group-by —
    when the column exceeds `max_card` distinct values or the domain
    contains NaN (NaN breaks searchsorted ordering).  Cached per
    (manifest version, column); stale versions evict on access."""
    from snappydata_tpu.storage import mvcc
    from snappydata_tpu.storage.encoding import Encoding

    man = mvcc.snapshot_of(data)
    cache = data.__dict__.setdefault("_key_domain_cache", {})
    key = (man.version, ci, max_card)
    if key in cache:
        return cache[key]
    dt = data.schema.fields[ci].dtype.device_dtype()
    parts = []
    for v in man.views:
        col = v.batch.columns[ci]
        untouched = not any(d[0] == ci for d in v.deltas)
        if untouched and col.encoding == Encoding.VALUE_DICT \
                and col.dictionary is not None:
            parts.append(np.asarray(col.dictionary))
        elif untouched and col.encoding == Encoding.RUN_LENGTH:
            parts.append(np.asarray(col.data))
        else:
            # mixed encodings / deltas: the domain must still cover the
            # values a decoded fallback bind will group by
            parts.append(np.asarray(v.decoded_column(ci)))
    if man.row_count:
        parts.append(np.asarray(man.row_arrays[ci][:man.row_count]))
    if parts:
        dom = np.unique(np.concatenate(
            [p.astype(dt, copy=False).ravel() for p in parts]))
    else:
        dom = np.zeros(0, dtype=dt)
    if len(dom) > max_card or (dom.dtype.kind == "f" and len(dom)
                               and np.isnan(dom[-1])):
        dom = None
    for k in [k for k in cache if k[0] != man.version]:
        del cache[k]
    cache[key] = dom
    return dom


def map_device_eligible(dt) -> bool:
    """MAP<STRING, numeric|string> gets device plates; other key/value
    types stay host-evaluated."""
    return (getattr(dt, "key", None) is not None
            and dt.key.name == "string"
            and (T.is_numeric(dt.value) or dt.value.name == "string"))


def struct_device_eligible(dt) -> bool:
    """STRUCT with only numeric/string fields gets per-field plates;
    nested complex fields keep the host path."""
    fields = getattr(dt, "fields", ())
    return bool(fields) and all(
        T.is_numeric(ft) or ft.name == "string" for _n, ft in fields)


def _complex_column_sources(manifest, views, row_chunks, ci):
    """(batch row, decoded cells, null mask) triples for a complex
    column — the one assembly all three complex-plate builders share
    (review finding: three diverging copies)."""
    sources = []
    for i, v in enumerate(views):
        sources.append((i, v.decoded_column(ci), v.null_mask(ci)))
    for j, (pos, take) in enumerate(row_chunks):
        src = np.asarray(manifest.row_arrays[ci][pos:pos + take],
                         dtype=object)
        rn = None
        if manifest.row_nulls and manifest.row_nulls[ci] is not None:
            rn = manifest.row_nulls[ci][pos:pos + take]
        sources.append((len(views) + j, src, rn))
    return sources


def _value_plate_dtype(vt) -> np.dtype:
    """Fill dtype for a complex-type VALUE plate: exact decimals fill
    as plain float64 and convert to scaled int64 afterwards — writing
    raw values straight into the int64 device dtype TRUNCATED them
    (review finding, verified: 1.50 decoded as 0.01)."""
    dt = vt.device_dtype()
    if vt.name == "decimal" and dt.kind == "i":
        return np.dtype(np.float64)
    return dt


def _finish_value_plate(vt, plate: np.ndarray) -> np.ndarray:
    """Host-domain fill plate -> device plate (scale exact decimals)."""
    dt = vt.device_dtype()
    if vt.name == "decimal" and dt.kind == "i":
        return T.decimal_to_unscaled(vt, plate)
    return plate


def _build_struct_column(data, manifest, views, row_chunks, ci, f, b,
                         cap, _place):
    """STRUCT column → ((field value plates tuple, field null plates
    tuple) in the dtype's field order, nan-stats, row-null mask).
    String fields encode against per-field append-only dictionaries."""
    import itertools

    from snappydata_tpu.storage.table_store import _struct_get

    sources = _complex_column_sources(manifest, views, row_chunks, ci)
    fnames = [n for n, _t in f.dtype.fields]
    ftypes = [t for _n, t in f.dtype.fields]
    str_fields = [fn for fn, ft in zip(fnames, ftypes)
                  if ft.name == "string"]
    # all string fields intern in ONE pass over the cells (review
    # finding: one full scan per field)
    str_lookups = data.intern_struct_fields(
        ci, str_fields, itertools.chain.from_iterable(
            dec for _bi, dec, _nm in sources)) if str_fields else {}
    lookups = [str_lookups.get(fn) if ft.name == "string" else None
               for fn, ft in zip(fnames, ftypes)]
    fvals = [np.zeros((b, cap), dtype=np.int32 if lk is not None
                      else _value_plate_dtype(ft))
             for lk, ft in zip(lookups, ftypes)]
    fnuls = [np.zeros((b, cap), dtype=np.bool_) for _ in fnames]
    null_mask = np.zeros((b, cap), dtype=np.bool_)
    any_null = False
    for bi, dec, nm in sources:
        for r, x in enumerate(dec):
            if isinstance(x, dict):
                for k, (fn, lk) in enumerate(zip(fnames, lookups)):
                    v = _struct_get(x, fn)
                    if v is None:
                        fnuls[k][bi, r] = True
                    elif lk is not None:
                        fvals[k][bi, r] = lk[str(v)]
                    else:
                        fvals[k][bi, r] = v
            else:
                null_mask[bi, r] = True
                any_null = True
        if nm is not None:
            null_mask[bi, :len(nm)] |= np.asarray(nm, dtype=bool)
            any_null = True
    fvals = [a if lk is not None else _finish_value_plate(ft, a)
             for a, lk, ft in zip(fvals, lookups, ftypes)]
    return ((tuple(_place(a) for a in fvals),
             tuple(_place(a) for a in fnuls)),
            np.full(b, np.nan), np.full(b, np.nan),
            _place(null_mask) if any_null else None)


def _build_map_column(data, manifest, views, row_chunks, ci, f, b, cap,
                      _place):
    """MAP<STRING, V> column → (((kcodes [b,cap,L], vals [b,cap,L],
    lengths [b,cap], value_nulls [b,cap,L])), nan-stats, row-null mask).
    Keys (and string values) encode against the table's append-only
    map dictionaries, so plates from any pinned manifest stay valid."""
    import itertools

    val_is_str = f.dtype.value.name == "string"
    vdt = np.dtype(np.int32) if val_is_str \
        else _value_plate_dtype(f.dtype.value)
    sources = _complex_column_sources(manifest, views, row_chunks, ci)
    klookup, vlookup = data.intern_map_entries(
        ci, itertools.chain.from_iterable(
            dec for _bi, dec, _nm in sources))
    maxlen = 1
    for _bi, dec, _nm in sources:
        for x in dec:
            if isinstance(x, dict) and len(x) > maxlen:
                maxlen = len(x)
    L = _next_pow2(maxlen)
    kcodes = np.full((b, cap, L), -1, dtype=np.int32)
    vals = np.zeros((b, cap, L), dtype=vdt)
    lens = np.zeros((b, cap), dtype=np.int32)
    vnul = np.zeros((b, cap, L), dtype=np.bool_)
    null_mask = np.zeros((b, cap), dtype=np.bool_)
    any_null = False
    for bi, dec, nm in sources:
        for r, x in enumerate(dec):
            if isinstance(x, dict):
                lens[bi, r] = len(x)
                for k, (mk, mv) in enumerate(x.items()):
                    kcodes[bi, r, k] = klookup[str(mk)]
                    if mv is None:
                        vnul[bi, r, k] = True
                    elif val_is_str:
                        vals[bi, r, k] = vlookup[str(mv)]
                    else:
                        vals[bi, r, k] = mv
            else:
                null_mask[bi, r] = True
                any_null = True
        if nm is not None:
            null_mask[bi, :len(nm)] |= np.asarray(nm, dtype=bool)
            any_null = True
    if not val_is_str:
        vals = _finish_value_plate(f.dtype.value, vals)
    return ((_place(kcodes), _place(vals), _place(lens), _place(vnul)),
            np.full(b, np.nan), np.full(b, np.nan),
            _place(null_mask) if any_null else None)


def array_element_dictionary(data, ci: int) -> np.ndarray:
    """Element dictionary of an ARRAY<STRING> column — delegates to the
    table's APPEND-ONLY intern store (same protocol as scalar string
    dictionaries: codes never shift, so plates from any pinned manifest
    version decode correctly against every later dictionary read)."""
    return data.array_element_dictionary(ci)


def _build_array_column(data, manifest, views, row_chunks, ci, f, b, cap,
                        _place):
    """Numeric/string ARRAY column → ((values [b,cap,L], lengths
    [b,cap], element_nulls [b,cap,L]), nan-stats, row-null mask).
    String elements encode as int32 dictionary codes interned into the
    table's append-only element dictionary — size/element_at/
    array_contains then run on device exactly like their numeric forms."""
    is_str = f.dtype.element.name == "string"
    sources = _complex_column_sources(manifest, views, row_chunks, ci)
    if is_str:
        import itertools

        edt = np.dtype(np.int32)
        # intern THIS pinned manifest's cells in ONE call (append-only,
        # cheap once hot) so the bind is self-sufficient across recovery
        # and concurrent mutation — a review finding killed the previous
        # sorted-per-version dictionary whose codes shifted under writes
        lookup = data.intern_array_elements(
            ci, itertools.chain.from_iterable(
                dec for _bi, dec, _nm in sources))
    else:
        edt = _value_plate_dtype(f.dtype.element)
    maxlen = 1
    for _bi, dec, _nm in sources:
        for x in dec:
            if isinstance(x, (list, tuple, np.ndarray)) and \
                    len(x) > maxlen:
                maxlen = len(x)
    L = _next_pow2(maxlen)
    vals = np.zeros((b, cap, L), dtype=edt)
    lens = np.zeros((b, cap), dtype=np.int32)
    enul = np.zeros((b, cap, L), dtype=np.bool_)
    null_mask = np.zeros((b, cap), dtype=np.bool_)
    any_null = False
    for bi, dec, nm in sources:
        for r, x in enumerate(dec):
            if isinstance(x, (list, tuple, np.ndarray)):
                lx = len(x)
                lens[bi, r] = lx
                for k, el in enumerate(x):
                    if el is None:
                        enul[bi, r, k] = True
                    elif is_str:
                        vals[bi, r, k] = lookup[str(el)]
                    else:
                        vals[bi, r, k] = el
            else:
                null_mask[bi, r] = True
                any_null = True
        if nm is not None:
            null_mask[bi, :len(nm)] |= np.asarray(nm, dtype=bool)
            any_null = True
    if not is_str:
        vals = _finish_value_plate(f.dtype.element, vals)
    return ((_place(vals), _place(lens), _place(enul)),
            np.full(b, np.nan), np.full(b, np.nan),
            _place(null_mask) if any_null else None)


def data_pow2() -> bool:
    from snappydata_tpu import config

    return config.global_properties().batches_pow2_bucketing


class _DeviceCacheBudget:
    """Process-wide accounting of cached device arrays with LRU eviction
    (ref: SnappyUnifiedMemoryManager evicting regions to disk under
    memory pressure — here eviction drops device copies back to host,
    from which they rebuild transparently on next bind)."""

    def __init__(self):
        import threading

        self._lock = locks.named_lock("storage.device_cache")
        # (id(table_cache_dict), cache_key) -> (bytes, tick, cache_ref)
        self._entries: Dict = {}
        self._tick = 0

    def _budget(self) -> int:
        from snappydata_tpu import config

        return config.global_properties().device_cache_bytes

    def enabled(self) -> bool:
        return self._budget() > 0

    def forget(self, table_cache: Dict, cache_key) -> None:
        """Version pruning dropped this entry: stop counting its bytes
        (otherwise every rebuild inflated the budget and evicted
        innocents)."""
        with self._lock:
            self._entries.pop((id(table_cache), repr(cache_key)), None)

    def touch(self, table_cache: Dict, cache_key, nbytes: int,
              data=None) -> None:
        budget = self._budget()
        if budget <= 0:
            return
        with self._lock:
            self._tick += 1
            # strong ref to the owning cache dict: it lives with its table
            # anyway, and eviction empties it (bounded residue).  The
            # table itself is a weakref: it is only consulted to spare
            # MVCC-pinned epochs, never kept alive.
            self._entries[(id(table_cache), repr(cache_key))] = (
                nbytes, self._tick, table_cache, cache_key,
                weakref.ref(data) if data is not None else None)
            total = sum(e[0] for e in self._entries.values())
            if total <= budget:
                return
            from snappydata_tpu.observability.metrics import global_registry
            from snappydata_tpu.storage.mvcc import pinned_versions_peek

            for key, (b, _, owner, ck, dref) in sorted(
                    self._entries.items(), key=lambda kv: kv[1][1]):
                if total <= budget:
                    break
                d = dref() if dref is not None else None
                if d is not None:
                    # NEVER evict a pinned epoch's plates out from under
                    # a live scan (the tier ladder's contract) — the
                    # lock-free peek keeps mvcc.clock out from under the
                    # budget lock (no device_cache -> clock edge)
                    pins = pinned_versions_peek(d)
                    if pins is None or ck[0] in pins:
                        global_registry().inc("tier_pinned_skips")
                        continue
                owner.pop(ck, None)  # device arrays released
                self._entries.pop(key, None)
                total -= b
                global_registry().inc("device_cache_evictions")


_cache_budget = _DeviceCacheBudget()


def _entry_bytes(entry) -> int:
    def arr_bytes(v) -> int:
        if isinstance(v, tuple):  # array-column plates nest one level
            return sum(arr_bytes(x) for x in v)
        return int(v.nbytes) if hasattr(v, "nbytes") else 0

    # row tables cache a whole DeviceTable (executor's replicated-bind
    # path), column tables a per-column dict — the tier ladder and the
    # broker ledger walk both shapes
    if isinstance(entry, DeviceTable):
        return (arr_bytes(entry.valid)
                + sum(arr_bytes(v) for v in list(entry.columns.values()))
                + sum(arr_bytes(v) for v in list(entry.nulls.values())
                      if v is not None))
    # list() is a C-atomic snapshot: a prefetch worker may still be
    # filling this entry while a ledger/tier walk measures it
    return sum(arr_bytes(v) for v in list(entry.values()))


def _map_cache_leaves(entry, fn):
    """Apply `fn` to every DEVICE-array leaf of one device-cache entry
    dict, preserving structure (host stats/dictdom tuples pass through).
    The single traversal migrate_mesh_cache and the per-device ledger
    share — cache-entry shapes must not drift between them.  Snapshots
    the items: a concurrent reader may fill the entry mid-walk."""
    out = {}
    for k, v in list(entry.items()):
        if k == "valid":
            out[k] = fn(v)
        elif k == "nrows":
            out[k] = v
        elif isinstance(k, tuple) and k[0] == "dictdom":
            out[k] = v                       # host-side probe surface
        elif isinstance(k, tuple) and isinstance(v, tuple) and len(v) == 4:
            plate, smin, smax, nulls = v

            def leaf(x):
                if x is None:
                    return None
                if isinstance(x, tuple):  # plates nest (CodePlate, acol)
                    parts = [leaf(p) for p in x]
                    return type(x)(*parts) if hasattr(x, "_fields") \
                        else tuple(parts)
                return fn(x)

            out[k] = (leaf(plate), smin, smax, leaf(nulls))
        else:
            out[k] = v
    return out


def migrate_mesh_cache(data, old_token, new_ctx) -> Tuple[int, int]:
    """Live bucket rebalance of one table's resident plates: re-place
    every cache entry bound under `old_token` onto `new_ctx`'s mesh via
    jax.device_put (device-to-device moves — no host rebuild, the world
    is NOT invalidated).  Returns (entries_moved, bytes_moved).  Entries
    whose padded batch axis the new mesh size doesn't divide are left to
    rebuild from host on next bind (counted by the caller)."""
    import jax

    moved = bytes_moved = 0
    nd = new_ctx.num_devices
    for key in [k for k in list(data._device_cache)
                if len(k) >= 2 and k[1] == old_token]:
        entry = data._device_cache.get(key)
        if entry is None:
            continue
        valid = entry.get("valid")
        if valid is None or valid.shape[0] % nd != 0:
            continue
        counted = [0]

        def _replace(x, _c=counted):
            _c[0] += int(getattr(x, "nbytes", 0))
            return jax.device_put(x, new_ctx.sharding_for(x))

        new_entry = _map_cache_leaves(entry, _replace)
        new_key = (key[0], new_ctx.token) + tuple(key[2:])
        data._device_cache[new_key] = new_entry
        data._device_cache.pop(key, None)
        _cache_budget.forget(data._device_cache, key)
        if _cache_budget.enabled():
            _cache_budget.touch(data._device_cache, new_key,
                                _entry_bytes(new_entry), data=data)
        moved += 1
        bytes_moved += counted[0]
    return moved, bytes_moved


def device_cache_bytes_by_device(tables) -> Dict[str, int]:
    """Per-DEVICE resident bytes of every cached plate — the mesh
    dashboard's proof that sharded tables stay encoded per device
    (read off each array's addressable shards, so replicated build
    plates correctly count full bytes on every device)."""
    out: Dict[str, int] = {}

    def leaf(x):
        if x is None or isinstance(x, (int, float)):
            return
        if isinstance(x, tuple):
            for p in x:
                leaf(p)
            return
        try:
            shards = getattr(x, "addressable_shards", None)
            if shards:
                for sh in shards:
                    k = str(sh.device)
                    out[k] = out.get(k, 0) + int(sh.data.nbytes)
            elif hasattr(x, "nbytes"):
                for d in getattr(x.sharding, "device_set", []):
                    out[str(d)] = out.get(str(d), 0) + int(x.nbytes)
        except Exception:
            pass

    for _name, data in tables:
        caches = getattr(data, "_device_cache", None)
        if not caches:
            continue
        for entry in list(caches.values()):
            for k, v in list(entry.items()):
                if k == "valid":
                    leaf(v)
                elif isinstance(k, tuple) and k[0] != "dictdom" \
                        and isinstance(v, tuple) and len(v) == 4:
                    leaf(v[0])
                    leaf(v[3])
    return out


def device_cache_bytes_by_table(tables) -> Dict[str, int]:
    """Device-side ledger for the resource broker: cached decoded plate
    bytes per table, read straight off each table's `_device_cache`
    (pull-based, so dropped tables simply stop appearing — nothing is
    pinned). `tables` is an iterable of (name, data)."""
    out: Dict[str, int] = {}
    for name, data in tables:
        caches = getattr(data, "_device_cache", None)
        if not caches:
            continue
        try:  # same-named tables of different catalogs sum, not replace
            out[name] = out.get(name, 0) + sum(
                _entry_bytes(c) for c in list(caches.values()))
        except Exception:
            out.setdefault(name, 0)
    return out


def current_scan_scale(data) -> float:
    """How many windows the active tile pass splits `data`'s scan into
    (1.0 outside a tile pass). The exact-decimal sum overflow guard
    multiplies its per-tile max|v|·count bound by this so the bound
    covers the MERGED total across tiles, not just each tile (several
    tiles could each pass the per-tile bound while their int64 partial-
    merge total wraps silently — advisor round 5)."""
    wentry = (_scan_windows.get() or {}).get(id(data))
    if wentry is None:
        return 1.0
    lo, hi, manifest = wentry[:3]
    total = scan_unit_count(data, manifest)
    # nominal width, not this window's: the last tile of a pass may be
    # truncated (e.g. 10 units in tiles of 4 → (8,10)), and deriving the
    # count from a truncated width would over-scale the overflow guard,
    # rerouting a safely-summable final tile to the slow host path
    width = max(1, wentry[3] if len(wentry) > 3 else hi - lo)
    return float(max(1, -(-total // width)))


def _scan_units(data, manifest=None):
    """THE unit-splitting contract shared by the device bind and the
    host fallback: (manifest, views, row_chunks, window) honoring the
    active scan window — pinned snapshot, unit order (batches then
    row-buffer chunks of `capacity` rows), [lo, hi) slice. Both sides
    MUST read through this one helper: if they ever disagreed on unit
    order, a tile falling back to host would silently read different
    rows than the device tile it replaces (the double-count bug class).
    row_chunks are (start, take) row-buffer slices."""
    wentry = (_scan_windows.get() or {}).get(id(data))
    window = None
    if wentry is not None:
        window = (wentry[0], wentry[1])
        if wentry[2] is not None:
            manifest = wentry[2]
    if manifest is None:
        # the ambient pinned snapshot (storage/mvcc): EVERY read this
        # contract serves — device bind, host fallback, LIMIT-n scan —
        # resolves the statement's pinned epoch, so concurrent ingest
        # publishing new manifests never changes a query mid-flight
        from snappydata_tpu.storage import mvcc

        manifest = mvcc.snapshot_of(data)
    # (wentry[3], when present, is the pass's nominal tile width — used
    # only by current_scan_scale, never for unit slicing)
    views = list(manifest.views)
    row_chunks = []
    cap = data.capacity
    if manifest.row_count > 0:
        pos = 0
        while pos < manifest.row_count:
            take = min(cap, manifest.row_count - pos)
            row_chunks.append((pos, take))
            pos += take
    if window is not None:
        units = [("v", v) for v in views] + [("r", rc) for rc in row_chunks]
        units = units[window[0]:window[1]]
        views = [u for k, u in units if k == "v"]
        row_chunks = [u for k, u in units if k == "r"]
    return manifest, views, row_chunks, window


def host_scan_units(data, manifest=None):
    """(manifest, views, row_chunks) for a HOST-side scan of `data` —
    the host fallback's view of the same units build_device_table
    binds (see _scan_units)."""
    manifest, views, row_chunks, _window = _scan_units(data, manifest)
    return manifest, views, row_chunks
