"""Cluster runtime: locator / lead / server roles.

Reference topology (docs/architecture/cluster_architecture.md:3-9):
locators do discovery + membership, the lead hosts the (HA) query planner
and job/REST services, data servers host buckets and answer simple queries
directly. Here the same roles over a TCP membership protocol
(locator.py), an Arrow Flight data/query front door per node
(flight_server.py — the thrift/DRDA network-server analogue,
cluster/README-thrift.md), and a REST status/metrics/jobs surface on the
lead (rest.py — the jobserver + /status/api/v1 analogue).
"""

from snappydata_tpu.cluster.locator import Locator, MemberInfo  # noqa: F401
from snappydata_tpu.cluster.node import (  # noqa: F401
    LocatorNode, LeadNode, ServerNode,
)
from snappydata_tpu.cluster.client import SnappyClient  # noqa: F401
