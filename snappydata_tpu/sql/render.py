"""Render (unresolved) expression/plan ASTs back to SQL text.

Used by the distributed scatter-gather router (cluster/distributed.py):
the lead decomposes an aggregate query into per-server partial SQL and a
local merge SQL — both rendered from rewritten ASTs. Covers the
single-block SELECT shape (FROM/JOIN/WHERE/GROUP BY) plus the full
expression grammar.
"""

from __future__ import annotations

import datetime
from typing import List, Optional

from snappydata_tpu import types as T
from snappydata_tpu.sql import ast

_EPOCH = datetime.date(1970, 1, 1)


class RenderError(Exception):
    pass


def render_expr(e: ast.Expr) -> str:
    if isinstance(e, ast.Alias):
        return f"{render_expr(e.child)} AS {e.name}"
    if isinstance(e, ast.Col):
        return f"{e.qualifier}.{e.name}" if e.qualifier else e.name
    if isinstance(e, ast.Star):
        return f"{e.qualifier}.*" if e.qualifier else "*"
    if isinstance(e, ast.Lit):
        return _render_lit(e)
    if isinstance(e, ast.ParamLiteral):
        raise RenderError("tokenized literal in render (render pre-token)")
    if isinstance(e, ast.Param):
        return "?"
    if isinstance(e, ast.BinOp):
        op = {"and": "AND", "or": "OR"}.get(e.op, e.op)
        return f"({render_expr(e.left)} {op} {render_expr(e.right)})"
    if isinstance(e, ast.UnaryOp):
        if e.op == "not":
            return f"(NOT {render_expr(e.child)})"
        return f"(-{render_expr(e.child)})"
    if isinstance(e, ast.IsNull):
        return f"({render_expr(e.child)} IS " \
               f"{'NOT ' if e.negated else ''}NULL)"
    if isinstance(e, ast.InList):
        vals = ", ".join(render_expr(v) for v in e.values)
        neg = "NOT " if e.negated else ""
        return f"({render_expr(e.child)} {neg}IN ({vals}))"
    if isinstance(e, ast.Between):
        neg = "NOT " if e.negated else ""
        return (f"({render_expr(e.child)} {neg}BETWEEN "
                f"{render_expr(e.lo)} AND {render_expr(e.hi)})")
    if isinstance(e, ast.Like):
        neg = "NOT " if e.negated else ""
        pat = e.pattern.replace("'", "''")
        return f"({render_expr(e.child)} {neg}LIKE '{pat}')"
    if isinstance(e, ast.Case):
        parts = ["CASE"]
        for c, v in e.whens:
            parts.append(f"WHEN {render_expr(c)} THEN {render_expr(v)}")
        if e.otherwise is not None:
            parts.append(f"ELSE {render_expr(e.otherwise)}")
        parts.append("END")
        return " ".join(parts)
    if isinstance(e, ast.Cast):
        return f"CAST({render_expr(e.child)} AS {e.to.name})"
    if isinstance(e, ast.Func):
        if e.name == "count" and not e.args:
            return "count(*)"
        if e.name == "count_distinct":
            return f"count(DISTINCT {render_expr(e.args[0])})"
        args = ", ".join(render_expr(a) for a in e.args)
        return f"{e.name}({args})"
    if isinstance(e, ast.WindowFunc):
        if e.name == "count" and not e.args:
            call = "count(*)"
        else:
            call = f"{e.name}(" + \
                ", ".join(render_expr(a) for a in e.args) + ")"
        over = []
        if e.partition_by:
            over.append("PARTITION BY " + ", ".join(
                render_expr(p) for p in e.partition_by))
        if e.order_by:
            def _ord(o):
                sql = render_expr(o[0]) + ("" if o[1] else " DESC")
                nf = o[2] if len(o) > 2 else None
                if nf is not None:
                    sql += " NULLS FIRST" if nf else " NULLS LAST"
                return sql

            over.append("ORDER BY " + ", ".join(_ord(o)
                                                for o in e.order_by))
        return f"{call} OVER ({' '.join(over)})"
    if isinstance(e, ast.ScalarSubquery):
        return f"({render_plan(e.plan)})"
    if isinstance(e, ast.InSubquery):
        neg = "NOT " if e.negated else ""
        return f"({render_expr(e.child)} {neg}IN ({render_plan(e.plan)}))"
    if isinstance(e, ast.ExistsSubquery):
        neg = "NOT " if e.negated else ""
        return f"({neg}EXISTS ({render_plan(e.plan)}))"
    raise RenderError(f"cannot render {type(e).__name__}")


def _render_lit(e: ast.Lit) -> str:
    v = e.value
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if e.dtype is not None and e.dtype.name == "date":
        return f"DATE '{(_EPOCH + datetime.timedelta(days=int(v))).isoformat()}'"
    if isinstance(v, (int, float)):
        return repr(v)
    import decimal as _d

    if isinstance(v, _d.Decimal):
        # numeric literal, NOT a quoted string (subquery substitution
        # yields Decimal objects since the exact-decimal decode)
        return format(v, "f")
    escaped = str(v).replace("'", "''")
    return f"'{escaped}'"


def _desugar_semi_joins(p: ast.Plan) -> ast.Plan:
    """Semi/anti joins (from decorrelation) render as correlated
    [NOT] EXISTS filters — the textual inverse of the rewrite that made
    them, so the receiving server's own decorrelator restores them."""
    import dataclasses as _dc

    if isinstance(p, ast.Join) and p.how in ("semi", "anti"):
        left = _desugar_semi_joins(p.left)
        right = _desugar_semi_joins(p.right)
        inner = ast.Filter(right, p.condition) \
            if p.condition is not None else right
        return ast.Filter(
            left, ast.ExistsSubquery(inner, negated=(p.how == "anti")))
    kids = p.children()
    if not kids:
        return p
    if isinstance(p, (ast.Join, ast.Union, ast.SetOp)):
        return _dc.replace(p, left=_desugar_semi_joins(p.left),
                           right=_desugar_semi_joins(p.right))
    return _dc.replace(p, child=_desugar_semi_joins(kids[0]))


def render_plan(p: ast.Plan) -> str:
    """Render a single-block SELECT tree (Project|Aggregate over
    FROM-chain with optional Filter)."""
    select_list: Optional[List[ast.Expr]] = None
    group_by: List[ast.Expr] = []
    where: Optional[ast.Expr] = None
    having: Optional[ast.Expr] = None
    orders = []
    limit = None
    distinct = False

    node = _desugar_semi_joins(p)
    while True:
        if isinstance(node, ast.Limit):
            limit = node.n
            node = node.child
        elif isinstance(node, ast.Sort):
            orders = list(node.orders)
            node = node.child
        elif isinstance(node, ast.Distinct):
            distinct = True
            node = node.child
        else:
            break
    if isinstance(node, ast.Filter) and isinstance(node.child, ast.Aggregate):
        having = node.condition
        node = node.child
    if isinstance(node, ast.Aggregate):
        if node.grouping_sets:
            raise RenderError("cannot render GROUPING SETS")
        select_list = list(node.agg_exprs)
        group_by = list(node.group_exprs)
        node = node.child
    elif isinstance(node, (ast.Project, ast.WindowProject)):
        select_list = list(node.exprs)
        node = node.child
    while isinstance(node, ast.Filter):
        # stacked filters (decorrelated EXISTS above the base WHERE)
        # collapse into one conjunctive WHERE clause
        where = node.condition if where is None \
            else ast.BinOp("and", where, node.condition)
        node = node.child
    # hoist filters off the join spine into WHERE (decorrelation wraps
    # the original filtered FROM-chain in new joins); commutes for
    # inner/cross both sides and for the PRESERVED side of a left join
    hoisted: List[ast.Expr] = []

    def _hoist(n):
        import dataclasses as _dc

        if not isinstance(n, ast.Join):
            return n
        left, right = _hoist(n.left), _hoist(n.right)
        if n.how in ("inner", "cross", "left"):
            while isinstance(left, ast.Filter):
                hoisted.append(left.condition)
                left = _hoist(left.child)
        if n.how in ("inner", "cross"):
            while isinstance(right, ast.Filter):
                hoisted.append(right.condition)
                right = _hoist(right.child)
        return _dc.replace(n, left=left, right=right)

    node = _hoist(node)
    for c in hoisted:
        where = c if where is None else ast.BinOp("and", where, c)
    from_sql = _render_from(node)
    if select_list is None:
        select_list = [ast.Star()]
    parts = ["SELECT " + ("DISTINCT " if distinct else "") +
             ", ".join(render_expr(e) for e in select_list),
             "FROM " + from_sql]
    if where is not None:
        parts.append("WHERE " + render_expr(where))
    if group_by:
        parts.append("GROUP BY " + ", ".join(render_expr(g)
                                             for g in group_by))
    if having is not None:
        parts.append("HAVING " + render_expr(having))
    if orders:
        def _ord(o):
            sql = render_expr(o[0]) + ("" if o[1] else " DESC")
            nf = o[2] if len(o) > 2 else None
            if nf is not None:
                sql += " NULLS FIRST" if nf else " NULLS LAST"
            return sql

        parts.append("ORDER BY " + ", ".join(_ord(o) for o in orders))
    if limit is not None:
        parts.append(f"LIMIT {limit}")
    return " ".join(parts)


def _render_from(node: ast.Plan) -> str:
    if isinstance(node, ast.UnresolvedRelation):
        return f"{node.name} {node.alias}" if node.alias else node.name
    if isinstance(node, ast.SubqueryAlias):
        return f"({render_plan(node.child)}) {node.alias}"
    if isinstance(node, ast.Filter):
        # filtered factor (from pushdown): render as subquery
        base = node.child
        if isinstance(base, ast.UnresolvedRelation):
            alias = base.alias or base.name.split(".")[-1]
            return (f"(SELECT * FROM {base.name} WHERE "
                    f"{render_expr(node.condition)}) {alias}")
        # non-relation factor: full derived table (bare column names
        # survive; outer QUALIFIED references into it would not — those
        # shapes are hoisted into WHERE by render_plan instead)
        return (f"(SELECT * FROM {_render_from(base)} WHERE "
                f"{render_expr(node.condition)}) __f")
    if isinstance(node, ast.Join):
        left = _render_from(node.left)
        right = _render_from(node.right)
        if node.how == "cross" and node.condition is None:
            return f"{left}, {right}"
        how = {"inner": "JOIN", "left": "LEFT JOIN",
               "right": "RIGHT JOIN", "full": "FULL JOIN",
               "semi": "SEMI JOIN", "anti": "ANTI JOIN"}.get(node.how)
        if how is None or node.how in ("semi", "anti"):
            raise RenderError(f"cannot render join {node.how}")
        cond = f" ON {render_expr(node.condition)}" \
            if node.condition is not None else ""
        return f"{left} {how} {right}{cond}"
    raise RenderError(f"cannot render FROM {type(node).__name__}")
