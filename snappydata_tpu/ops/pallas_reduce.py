"""Pallas kernel: masked compensated (Kahan) reduction.

Motivation (the numerics/bandwidth trade the aggregate accumulators
face): DOUBLE aggregates need ~1e-6-grade accuracy, so the XLA path
widens the accumulator to float64 — which TPUs EMULATE in software at a
large per-op cost. This kernel instead runs ONE pass over the f32
plates keeping a per-lane Kahan compensation term in VMEM: each of the
8x128 vector lanes owns an independent compensated chain over its
~rows/8 elements (error ~eps, not ~n*eps), and the tiny [8,128]
(sum, compensation) partials combine in exact-enough float64 OUTSIDE
the kernel. Accuracy matches the f64 path to <=1e-6 relative while the
hot loop stays entirely in native f32 vector ops.

Used for global (ungrouped) SUM/AVG over float32 plates — the TPC-H
Q6 shape — behind `properties.pallas_reduce` (**default OFF** until
measured on hardware; bench.py records the side-by-side timing when a
TPU is reachable). Scope caveats the gate enforces and the docs own:
only float32 inputs qualify (an f64 input would be truncated — the TPU
storage contract already stores DOUBLE as f32 plates, so on TPU this
loses nothing), and compensated summation bounds error relative to
Σ|v|, not |Σv| — under heavy cancellation (Σ|v| >> |Σv|) the emulated-
f64 segment path remains the accurate choice. CPU runs use the
interpreter (no Mosaic lowering) and exist for correctness tests only.

Ref parity note: the reference leans on JVM codegen'd loops with
double accumulators (SnappyHashAggregateExec); this is the TPU-native
equivalent of "accumulate wider than the data".
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_LANES = 128
_SUBLANES = 8


# rows per grid step: 2048x128 f32 block = 1MB data + 256KB mask in
# VMEM — far under the ~16MB budget, so arbitrarily long columns
# stream block by block instead of requiring the whole array resident
_BLOCK_ROWS = 2048


def _kahan_kernel(x_ref, m_ref, sum_ref, comp_ref):
    """One grid step = one [_BLOCK_ROWS, LANES] f32 block + bool mask.
    Per-lane-element Kahan accumulation over the row axis via
    lax.fori_loop, writing this block's [SUBLANES, LANES] sum +
    compensation tiles."""
    steps = _BLOCK_ROWS // _SUBLANES

    def body(i, carry):
        s, c = carry
        blk = x_ref[pl.ds(i * _SUBLANES, _SUBLANES), :]
        msk = m_ref[pl.ds(i * _SUBLANES, _SUBLANES), :]
        v = jnp.where(msk, blk, 0.0)
        # Kahan: y = v - c; t = s + y; c = (t - s) - y; s = t
        y = v - c
        t = s + y
        c_new = (t - s) - y
        return t, c_new

    zero = jnp.zeros((_SUBLANES, _LANES), dtype=jnp.float32)
    s, c = jax.lax.fori_loop(0, steps, body, (zero, zero))
    sum_ref[:, :, :] = s[None]
    comp_ref[:, :, :] = c[None]


try:  # pallas import is cheap; actual lowering happens at first call
    from jax.experimental import pallas as pl
    _PALLAS = True
except ImportError:  # pragma: no cover - pallas always ships with jax
    _PALLAS = False


@functools.partial(jax.jit, static_argnames=("interpret",))
def _kahan_call(x2d: jnp.ndarray, mask2d: jnp.ndarray,
                interpret: bool = False):
    rows = x2d.shape[0]
    nblocks = rows // _BLOCK_ROWS
    sums, comps = pl.pallas_call(
        _kahan_kernel,
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, _SUBLANES, _LANES), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, _SUBLANES, _LANES), lambda i: (i, 0, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((nblocks, _SUBLANES, _LANES),
                                 jnp.float32),
            jax.ShapeDtypeStruct((nblocks, _SUBLANES, _LANES),
                                 jnp.float32),
        ),
        interpret=interpret,
    )(x2d, mask2d)
    # exact f64 combine of the small per-block partials. Kahan's
    # c = (t - s) - y holds the EXCESS already folded into s, so the
    # true chain total is s - c (review finding: + doubled the residual
    # instead of cancelling it)
    return (jnp.sum(sums.astype(jnp.float64))
            - jnp.sum(comps.astype(jnp.float64)))


def masked_kahan_sum(values: jnp.ndarray, mask: jnp.ndarray,
                     interpret=None) -> jnp.ndarray:
    """Compensated sum of values[mask] -> float64 scalar.

    `values`: any-shape f32/f64 array; `mask`: same-shape bool. The
    flattened data pads to a [rows, 128] layout with rows a multiple of
    8 (TPU native tiling). `interpret=None` auto-selects: compiled on
    TPU, interpreter elsewhere (CPU has no Mosaic lowering)."""
    if not _PALLAS:   # degrade gracefully: plain f64 reduction
        return jnp.sum(jnp.where(mask, values, 0).astype(jnp.float64))
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    flat = values.reshape(-1).astype(jnp.float32)
    m = mask.reshape(-1)
    n = flat.shape[0]
    tile = _BLOCK_ROWS * _LANES
    padded = ((n + tile - 1) // tile) * tile
    if padded != n:
        flat = jnp.pad(flat, (0, padded - n))
        m = jnp.pad(m, (0, padded - n))
    x2d = flat.reshape(-1, _LANES)
    m2d = m.reshape(-1, _LANES)
    return _kahan_call(x2d, m2d, interpret=interpret)


def pallas_reduce_available() -> bool:
    """True when the TPU lowering path is usable on this backend."""
    if not _PALLAS:
        return False
    return jax.default_backend() == "tpu"


# ==========================================================================
# Fused decode+filter+aggregate: the TPC-H Q6 shape over ENCODED batches.
#
# Inputs stay in the compressed domain end to end: the filter columns are
# VALUE_DICT code plates (uint8/uint16) compared against PER-BATCH code
# thresholds (the host translates each literal through the batch's sorted
# dictionary ONCE — out-of-dictionary literals become thresholds that
# match nothing), and the discount factor decodes INSIDE the kernel from
# the batch's tiny dictionary held in SMEM — a decoded plate never exists
# in HBM, and per-row filter traffic is 1-2 bytes/column instead of 8.
#
# Grid is (batch, block): each grid step streams one [_FBLOCK_ROWS, 128]
# block of one batch through VMEM, so per-batch dictionaries/thresholds
# index naturally by the first grid axis.  Sums keep the same per-lane
# Kahan discipline as _kahan_kernel; the count partial rides f32 (exact
# below 2^24 per lane) and combines in int64 outside.
#
# CPU runs use the interpreter (correctness + the opt-in
# SNAPPY_BENCH_PALLAS=1 bench lane); the real Mosaic lowering engages on
# TPU.  Codes load as uint8/uint16 and widen in-register — block rows are
# a multiple of 32 to satisfy the small-int tile shape.
# ==========================================================================

_FBLOCK_ROWS = 512   # multiple of 32 (int8 tiling) and of 8 (f32 tiling)


def _fused_q6_kernel(qty_ref, disc_ref, ship_ref, price_ref, valid_ref,
                     dict_ref, qhi_ref, dlo_ref, dhi_ref, slo_ref, shi_ref,
                     sum_ref, comp_ref, cnt_ref):
    b = pl.program_id(0)
    s = pl.program_id(1)

    @pl.when((b == 0) & (s == 0))
    def _init():
        zero = jnp.zeros((_SUBLANES, _LANES), jnp.float32)
        sum_ref[...] = zero
        comp_ref[...] = zero
        cnt_ref[...] = zero

    steps = _FBLOCK_ROWS // _SUBLANES
    d_pad = dict_ref.shape[1]
    qhi = qhi_ref[0, 0]
    dlo = dlo_ref[0, 0]
    dhi = dhi_ref[0, 0]
    slo = slo_ref[0, 0]
    shi = shi_ref[0, 0]

    def body(i, carry):
        sm, cp, ct = carry
        sl = pl.ds(i * _SUBLANES, _SUBLANES)
        q = qty_ref[0, sl, :].astype(jnp.int32)
        d = disc_ref[0, sl, :].astype(jnp.int32)
        sh = ship_ref[0, sl, :]
        pz = price_ref[0, sl, :]
        ok = (valid_ref[0, sl, :]
              & (q < qhi) & (d >= dlo) & (d <= dhi)
              & (sh >= slo) & (sh < shi))
        # in-register dictionary decode: D selects (D is tiny — the
        # VALUE_DICT acceptance rule caps it at rows/8, and Q6's
        # discount dictionary is 11 entries)
        dval = jnp.zeros_like(pz)

        def dec(k, acc):
            return jnp.where(d == k, dict_ref[0, k], acc)

        dval = jax.lax.fori_loop(0, d_pad, dec, dval)
        v = jnp.where(ok, pz * dval, 0.0)
        y = v - cp
        t = sm + y
        return t, (t - sm) - y, ct + jnp.where(ok, 1.0, 0.0)

    carry0 = (sum_ref[...], comp_ref[...], cnt_ref[...])
    sm, cp, ct = jax.lax.fori_loop(0, steps, body, carry0)
    sum_ref[...] = sm
    comp_ref[...] = cp
    cnt_ref[...] = ct


@functools.partial(jax.jit, static_argnames=("interpret",))
def _fused_q6_call(qty, disc, ship, price, valid, dicts,
                   qhi, dlo, dhi, slo, shi, interpret: bool = False):
    B, capr, _ = price.shape
    S = capr // _FBLOCK_ROWS
    blk = pl.BlockSpec((1, _FBLOCK_ROWS, _LANES), lambda b, s: (b, s, 0))
    from jax.experimental.pallas import tpu as pltpu

    smem_dict = pl.BlockSpec((1, dicts.shape[1]), lambda b, s: (b, 0),
                             memory_space=pltpu.SMEM)
    smem_b = pl.BlockSpec((1, 1), lambda b, s: (b, 0),
                          memory_space=pltpu.SMEM)
    smem_g = pl.BlockSpec((1, 1), lambda b, s: (0, 0),
                          memory_space=pltpu.SMEM)
    out_blk = pl.BlockSpec((_SUBLANES, _LANES), lambda b, s: (0, 0))
    sums, comps, cnts = pl.pallas_call(
        _fused_q6_kernel,
        grid=(B, S),
        in_specs=[blk, blk, blk, blk, blk, smem_dict,
                  smem_b, smem_b, smem_b, smem_g, smem_g],
        out_specs=(out_blk, out_blk, out_blk),
        out_shape=(
            jax.ShapeDtypeStruct((_SUBLANES, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((_SUBLANES, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((_SUBLANES, _LANES), jnp.float32),
        ),
        interpret=interpret,
    )(qty, disc, ship, price, valid, dicts, qhi, dlo, dhi, slo, shi)
    total = (jnp.sum(sums.astype(jnp.float64))
             - jnp.sum(comps.astype(jnp.float64)))
    count = jnp.sum(cnts.astype(jnp.int64))
    return total, count


def fused_code_filter_sum(qty_codes, disc_codes, ship, price, valid,
                          disc_dicts, qty_hi_codes, disc_lo_codes,
                          disc_hi_codes, ship_lo, ship_hi,
                          interpret=None):
    """Fused decode+filter+SUM over encoded batches (the Q6 shape):

        sum(price * disc), count(*)
        WHERE qty_code < qty_hi_code[b]          (code domain)
          AND disc_lo_code[b] <= disc_code <= disc_hi_code[b]
          AND ship_lo <= ship < ship_hi          (value domain, int32)

    qty_codes/disc_codes: [B, cap] uint8/uint16 code plates;
    ship: [B, cap] int32; price: [B, cap] float; valid: [B, cap] bool;
    disc_dicts: [B, D] per-batch sorted dictionaries (decode target);
    *_codes thresholds: [B] int32, translated on HOST through each
    batch's sorted dictionary (one searchsorted per batch — the
    "translate the literal once" contract; a miss yields a threshold
    that matches nothing).  Returns (float64 sum, int64 count)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, cap = price.shape
    capr = cap // _LANES
    pad_r = ((capr + _FBLOCK_ROWS - 1) // _FBLOCK_ROWS) * _FBLOCK_ROWS
    pad_cap = pad_r * _LANES

    def shape3(a, dtype):
        a = jnp.asarray(a)
        if pad_cap != cap:
            a = jnp.pad(a, ((0, 0), (0, pad_cap - cap)))
        return a.reshape(B, pad_r, _LANES).astype(dtype)

    qty = shape3(qty_codes, jnp.asarray(qty_codes).dtype)
    disc = shape3(disc_codes, jnp.asarray(disc_codes).dtype)
    sh = shape3(ship, jnp.int32)
    pz = shape3(price, jnp.float32)
    vd = shape3(valid, jnp.bool_)

    def col_b(a):
        return jnp.asarray(a, dtype=jnp.int32).reshape(B, 1)

    return _fused_q6_call(
        qty, disc, sh, pz, vd,
        jnp.asarray(disc_dicts, dtype=jnp.float32),
        col_b(qty_hi_codes), col_b(disc_lo_codes), col_b(disc_hi_codes),
        jnp.asarray([[int(ship_lo)]], dtype=jnp.int32),
        jnp.asarray([[int(ship_hi)]], dtype=jnp.int32),
        interpret=bool(interpret))
