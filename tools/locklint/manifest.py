"""The committed lock-hierarchy manifest.

``lock_order.toml`` is the machine-readable twin of LOCK_ORDER.md: it
declares which lock-order edges are LEGAL. Both the static pass and the
runtime witness check the graph they observe against it — any edge not
derivable from the manifest fails.

Semantics:

- ``[[order]] chain = [a, b, c]`` — a may be held while acquiring b or
  c, b while acquiring c (consecutive pairs; transitivity comes from the
  closure, so chains sharing a lock compose).
- ``[[edge]] from/to`` — a single extra legal edge.
- ``[leaf] names = [...]`` — terminal locks: ANY lock may be held while
  acquiring a leaf, and a leaf may not be held while acquiring anything
  (unless an explicit chain/edge says so). The metrics-registry lock is
  the canonical leaf: every hot region increments counters.
- ``[self_nesting] names = [...]`` — lock classes whose INSTANCES may
  nest (per-table locks); the witness and static pass skip same-name
  edges for everyone, this section just documents which classes rely on
  it.

The declared graph must itself be acyclic — ``validate()`` enforces it,
so a manifest edit can never quietly legalize an ABBA pair."""

from __future__ import annotations

import os
from typing import Dict, List, Set, Tuple

from . import toml_lite

DEFAULT_PATH = os.path.join(os.path.dirname(__file__), "lock_order.toml")


class ManifestError(ValueError):
    pass


class Manifest:
    def __init__(self, doc: dict):
        self.doc = doc
        self.edges: Set[Tuple[str, str]] = set()
        self.reasons: Dict[Tuple[str, str], str] = {}
        self.leaves: Set[str] = set()
        self.self_nesting: Set[str] = set()
        self.names: Set[str] = set()
        for order in doc.get("order", []):
            chain = order.get("chain", [])
            if not isinstance(chain, list) or len(chain) < 2:
                raise ManifestError("[[order]] needs a chain of >= 2 locks: %r"
                                    % (order,))
            for a, b in zip(chain, chain[1:]):
                self.edges.add((a, b))
                self.reasons.setdefault(
                    (a, b), order.get("reason", order.get("name", "")))
            self.names.update(chain)
        for edge in doc.get("edge", []):
            a, b = edge.get("from"), edge.get("to")
            if not a or not b:
                raise ManifestError("[[edge]] needs from/to: %r" % (edge,))
            self.edges.add((a, b))
            self.reasons.setdefault((a, b), edge.get("reason", ""))
            self.names.update((a, b))
        self.leaves = set(doc.get("leaf", {}).get("names", []))
        self.self_nesting = set(doc.get("self_nesting", {}).get("names", []))
        self.names |= self.leaves | self.self_nesting
        self._closure = self._compute_closure()

    def _compute_closure(self) -> Dict[str, Set[str]]:
        adj: Dict[str, Set[str]] = {}
        for a, b in self.edges:
            adj.setdefault(a, set()).add(b)
        closure: Dict[str, Set[str]] = {}
        for src in adj:
            seen: Set[str] = set()
            stack = [src]
            while stack:
                node = stack.pop()
                for nxt in adj.get(node, ()):
                    if nxt not in seen:
                        seen.add(nxt)
                        stack.append(nxt)
            closure[src] = seen
        return closure

    def validate(self) -> None:
        """The declared hierarchy must be a DAG and leaves terminal."""
        for src, reach in self._closure.items():
            if src in reach:
                raise ManifestError(
                    "declared lock order contains a cycle through %r" % src)
        for a, b in self.edges:
            if a in self.leaves:
                raise ManifestError(
                    "leaf lock %r declared as predecessor of %r — leaves are "
                    "terminal; drop it from [leaf] or drop the edge" % (a, b))

    def allows(self, held: str, acquired: str) -> bool:
        if held == acquired:
            return True          # same lock class: self-nesting policy
        if held in self.leaves:
            return False         # leaves acquire nothing
        if acquired in self.leaves:
            return True
        return acquired in self._closure.get(held, ())

    def allowed_edges(self) -> Set[Tuple[str, str]]:
        out = set(self.edges)
        for src, reach in self._closure.items():
            for dst in reach:
                out.add((src, dst))
        return out


def load(path: str = DEFAULT_PATH) -> Manifest:
    m = Manifest(toml_lite.load(path))
    m.validate()
    return m
