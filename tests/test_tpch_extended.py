"""Extended TPC-H coverage (Q5/Q10/Q12/Q14/Q18) against a pandas oracle —
multi-key joins, dim-chain joins, CASE-in-aggregate, LIKE-in-aggregate,
uncorrelated IN subquery with HAVING."""

import numpy as np
import pandas as pd
import pytest

pytestmark = pytest.mark.slow  # heavy/XLA-compile-bound; deselect with -m 'not slow'

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.utils import tpch


SF = 0.004


@pytest.fixture(scope="module")
def s():
    sess = SnappySession(catalog=Catalog())
    tpch.load_tpch(sess, sf=SF, seed=21, all_tables=True)
    yield sess
    sess.stop()


@pytest.fixture(scope="module")
def dfs():
    n_l = max(1000, int(tpch.LINEITEM_ROWS_PER_SF * SF))
    n_o = max(250, int(tpch.ORDERS_ROWS_PER_SF * SF))
    n_c = max(25, int(tpch.CUSTOMER_ROWS_PER_SF * SF))
    n_s = max(10, int(10_000 * SF))
    n_p = max(50, int(200_000 * SF))
    li = pd.DataFrame(tpch.gen_lineitem(n_l, 21))
    li["l_orderkey"] = np.minimum(li["l_orderkey"], n_o)
    li["l_suppkey"] = (li["l_suppkey"] % n_s) + 1
    li["l_partkey"] = (li["l_partkey"] % n_p) + 1
    return {
        "lineitem": li,
        "orders": pd.DataFrame(tpch.gen_orders(n_o, n_c, 22)),
        "customer": pd.DataFrame(tpch.gen_customer(n_c, 23)),
        "supplier": pd.DataFrame(tpch.gen_supplier(n_s, 24)),
        "part": pd.DataFrame(tpch.gen_part(n_p, 25)),
        "partsupp": pd.DataFrame(tpch.gen_partsupp(n_p, n_s, 27)),
        "nation": pd.DataFrame(tpch.gen_nation()),
        "region": pd.DataFrame(tpch.gen_region()),
    }


def _days(iso):
    import datetime

    return (datetime.date.fromisoformat(iso) - datetime.date(1970, 1, 1)).days


def test_q4_correlated_exists(s, dfs):
    out = s.sql(tpch.Q4).rows()
    li, orders = dfs["lineitem"], dfs["orders"]
    good = set(li[li.l_commitdate < li.l_receiptdate].l_orderkey)
    sel = orders[(orders.o_orderdate >= _days("1993-07-01"))
                 & (orders.o_orderdate < _days("1993-10-01"))
                 & orders.o_orderkey.isin(good)]
    exp = sel.groupby("o_orderpriority").size().sort_index()
    assert [(r[0], r[1]) for r in out] == list(exp.items())


def test_not_exists_correlated(s, dfs):
    q = """SELECT count(*) FROM customer WHERE NOT EXISTS (
        SELECT 1 FROM orders WHERE o_custkey = c_custkey)"""
    out = s.sql(q).rows()[0][0]
    cust, orders = dfs["customer"], dfs["orders"]
    exp = (~cust.c_custkey.isin(set(orders.o_custkey))).sum()
    assert out == exp


def test_q5(s, dfs):
    out = s.sql(tpch.Q5).rows()
    j = (dfs["lineitem"]
         .merge(dfs["orders"], left_on="l_orderkey", right_on="o_orderkey")
         .merge(dfs["customer"], left_on="o_custkey", right_on="c_custkey")
         .merge(dfs["supplier"], left_on="l_suppkey", right_on="s_suppkey"))
    j = j[j.c_nationkey == j.s_nationkey]
    j = j.merge(dfs["nation"], left_on="s_nationkey", right_on="n_nationkey")
    j = j.merge(dfs["region"], left_on="n_regionkey", right_on="r_regionkey")
    j = j[(j.r_name == "ASIA")
          & (j.o_orderdate >= _days("1994-01-01"))
          & (j.o_orderdate < _days("1995-01-01"))]
    j["rev"] = j.l_extendedprice * (1 - j.l_discount)
    exp = j.groupby("n_name").rev.sum().sort_values(ascending=False)
    assert len(out) == len(exp)
    for row, (name, rev) in zip(out, exp.items()):
        assert row[0] == name
        assert row[1] == pytest.approx(rev)


def test_q10(s, dfs):
    out = s.sql(tpch.Q10).rows()
    j = (dfs["lineitem"]
         .merge(dfs["orders"], left_on="l_orderkey", right_on="o_orderkey")
         .merge(dfs["customer"], left_on="o_custkey", right_on="c_custkey")
         .merge(dfs["nation"], left_on="c_nationkey",
                right_on="n_nationkey"))
    j = j[(j.o_orderdate >= _days("1993-10-01"))
          & (j.o_orderdate < _days("1994-01-01"))
          & (j.l_returnflag == "R")]
    j["rev"] = j.l_extendedprice * (1 - j.l_discount)
    g = j.groupby(["c_custkey", "c_name", "c_acctbal", "n_name"],
                  as_index=False).rev.sum()
    g = g.sort_values("rev", ascending=False).head(20)
    assert len(out) == len(g)
    for row, (_, e) in zip(out, g.iterrows()):
        assert row[0] == e.c_custkey
        assert row[2] == pytest.approx(e.rev)


def test_q12(s, dfs):
    out = s.sql(tpch.Q12).rows()
    j = dfs["lineitem"].merge(dfs["orders"], left_on="l_orderkey",
                              right_on="o_orderkey")
    j = j[j.l_shipmode.isin(["MAIL", "SHIP"])
          & (j.l_receiptdate >= _days("1994-01-01"))
          & (j.l_receiptdate < _days("1995-01-01"))]
    high = j.o_orderpriority.isin(["1-URGENT", "2-HIGH"])
    exp = {}
    for mode, grp in j.groupby("l_shipmode"):
        h = high.loc[grp.index]
        exp[mode] = (int(h.sum()), int((~h).sum()))
    assert {r[0]: (r[1], r[2]) for r in out} == exp


def test_q14(s, dfs):
    out = s.sql(tpch.Q14).rows()[0][0]
    j = dfs["lineitem"].merge(dfs["part"], left_on="l_partkey",
                              right_on="p_partkey")
    j = j[(j.l_shipdate >= _days("1995-09-01"))
          & (j.l_shipdate < _days("1995-10-01"))]
    rev = j.l_extendedprice * (1 - j.l_discount)
    promo = rev[j.p_type.str.startswith("PROMO")].sum()
    assert out == pytest.approx(100.0 * promo / rev.sum())


def test_q18(s, dfs):
    out = s.sql(tpch.Q18).rows()
    li = dfs["lineitem"]
    big = li.groupby("l_orderkey").l_quantity.sum()
    big_keys = set(big[big > 150].index)
    j = (li[li.l_orderkey.isin(big_keys)]
         .merge(dfs["orders"], left_on="l_orderkey", right_on="o_orderkey")
         .merge(dfs["customer"], left_on="o_custkey", right_on="c_custkey"))
    g = j.groupby(["c_name", "c_custkey", "o_orderkey", "o_orderdate",
                   "o_totalprice"], as_index=False).l_quantity.sum()
    g = g.sort_values(["o_totalprice", "o_orderdate"],
                      ascending=[False, True]).head(100)
    assert len(out) == len(g)
    for row, (_, e) in zip(out, g.iterrows()):
        assert row[2] == e.o_orderkey
        assert row[5] == pytest.approx(e.l_quantity)


def test_q17_correlated_scalar(s, dfs):
    """Correlated scalar aggregate → aggregate-then-join decorrelation."""
    out = s.sql(tpch.Q17).rows()
    li, part = dfs["lineitem"], dfs["part"]
    p = part[(part.p_brand == "Brand#23") & (part.p_container == "MED BOX")]
    m = li.merge(p[["p_partkey"]], left_on="l_partkey",
                 right_on="p_partkey")
    thresh = li.groupby("l_partkey").l_quantity.mean() * 0.2
    m = m[m.l_quantity < m.l_partkey.map(thresh)]
    exp = m.l_extendedprice.sum() / 7.0
    got = out[0][0]
    if len(m) == 0:
        assert got is None or got == 0
    else:
        assert got == pytest.approx(exp, rel=1e-9)


def test_q2_correlated_min(s, dfs):
    out = s.sql(tpch.Q2).rows()
    ps, su = dfs["partsupp"], dfs["supplier"]
    na, re_, pa = dfs["nation"], dfs["region"], dfs["part"]
    eu = na.merge(re_[re_.r_name == "EUROPE"], left_on="n_regionkey",
                  right_on="r_regionkey")
    inner = ps.merge(su, left_on="ps_suppkey", right_on="s_suppkey") \
        .merge(eu, left_on="s_nationkey", right_on="n_nationkey")
    mincost = inner.groupby("ps_partkey").ps_supplycost.min()
    m = pa[pa.p_size == 15].merge(
        inner, left_on="p_partkey", right_on="ps_partkey")
    m = m[m.ps_supplycost == m.p_partkey.map(mincost)]
    exp = m.sort_values(
        ["s_acctbal", "n_name", "s_name", "p_partkey"],
        ascending=[False, True, True, True]).head(100)
    assert len(out) == len(exp)
    for row, (_, e) in zip(out, exp.iterrows()):
        assert row[0] == pytest.approx(e.s_acctbal)
        assert row[1] == e.s_name and row[2] == e.n_name
        assert row[3] == e.p_partkey


def test_q20_nested_correlated(s, dfs):
    out = [r[0] for r in s.sql(tpch.Q20).rows()]
    li, ps = dfs["lineitem"], dfs["partsupp"]
    su, na, pa = dfs["supplier"], dfs["nation"], dfs["part"]
    d0, d1 = _days("1994-01-01"), _days("1995-01-01")
    parts = set(pa[pa.p_type.str.startswith("STANDARD")].p_partkey)
    lw = li[(li.l_shipdate >= d0) & (li.l_shipdate < d1)]
    halfsum = lw.groupby(["l_partkey", "l_suppkey"]).l_quantity.sum() * 0.5
    cand = ps[ps.ps_partkey.isin(parts)].copy()
    key = list(zip(cand.ps_partkey, cand.ps_suppkey))
    thr = [halfsum.get(k, None) for k in key]
    keep = [t is not None and q > t for q, t in zip(cand.ps_availqty, thr)]
    supps = set(cand[keep].ps_suppkey)
    nk = na[na.n_name == "CANADA"].n_nationkey.iloc[0]
    exp = sorted(su[(su.s_suppkey.isin(supps))
                    & (su.s_nationkey == nk)].s_name)
    assert out == exp


def test_q21_exists_with_nonequi_correlation(s, dfs):
    out = s.sql(tpch.Q21).rows()
    li, su, od, na = (dfs["lineitem"], dfs["supplier"], dfs["orders"],
                      dfs["nation"])
    nk = na[na.n_name == "SAUDI ARABIA"].n_nationkey.iloc[0]
    l1 = li[li.l_receiptdate > li.l_commitdate]
    m = l1.merge(od[od.o_orderstatus == "F"], left_on="l_orderkey",
                 right_on="o_orderkey")
    m = m.merge(su[su.s_nationkey == nk], left_on="l_suppkey",
                right_on="s_suppkey")
    by_order = li.groupby("l_orderkey").l_suppkey.agg(set)
    late = li[li.l_receiptdate > li.l_commitdate]
    late_by_order = late.groupby("l_orderkey").l_suppkey.agg(set)

    def keeps(r):
        others = by_order.get(r.l_orderkey, set()) - {r.l_suppkey}
        if not others:
            return False
        late_others = late_by_order.get(r.l_orderkey, set()) - {r.l_suppkey}
        return not late_others

    m = m[[keeps(r) for _, r in m.iterrows()]]
    exp = m.groupby("s_name").size().reset_index(name="numwait") \
        .sort_values(["numwait", "s_name"], ascending=[False, True]) \
        .head(100)
    assert len(out) == len(exp)
    for row, (_, e) in zip(out, exp.iterrows()):
        assert row[0] == e.s_name and row[1] == e.numwait
