"""Partial-aggregate decomposition shared by the distributed scatter path
and the session's tiled scans.

One aggregate plan splits into (a) a PARTIAL plan — per-shard / per-tile
group-by emitting decomposable slots (sum/count/min/max/sumsq) — and (b) a
MERGE select re-combining the slots (avg = sum/count, stddev from the
moments). This is the reference's partial/final aggregation planning
(SnappyAggregationStrategy partial/final planning, SnappyStrategies.scala:
464) re-usable wherever partials come from: data servers over Flight, or
HBM-sized tiles of one oversized table.

Contract the tiled scan's ON-DEVICE merge additionally relies on: every
partial item is either a bare `__g<i>` group alias or a single
decomposable aggregate `__p<i>` — never a composite expression — so a
partial-raw compile (executor.Compiler(partial_raw=True)) can tag each
output with its merge op (sum/min/max) and fold per-tile [G] partials
elementwise on device.  The merge select stays valid over ALREADY-MERGED
partials too: re-running sum/min/max over one row per group is the
identity, which is how the device-merged path reuses the same merge SQL.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from snappydata_tpu.sql import ast


class NotDecomposableError(Exception):
    """Aggregate cannot be split into partial + merge phases."""


def merge_ref(slot: int, merge_fn: str) -> ast.Expr:
    return ast.Func(merge_fn, (ast.Col(f"__p{slot}"),))


def decompose_aggregate(agg: ast.Aggregate, having=None,
                        distinct_ok_cols=frozenset()):
    """→ (partial_plan, merged_select, n_slots, merged_having).

    `partial_plan` evaluates per shard/tile, emitting group exprs as
    __g0..__gN and slots as __p0..__pM; `merged_select` re-aggregates the
    gathered partials (referencing __g/__p columns) into the original
    output expressions. A HAVING predicate decomposes through the same
    slot table, so aggregates appearing only in HAVING get partial slots
    too.

    `distinct_ok_cols`: which count(DISTINCT col) arguments decompose —
    either a callable `Col -> bool` (preferred: the distributed layer
    resolves the column to its source table and answers True only when it
    is THAT table's hash partition key, so a replicated table's column
    merely sharing a name with a partition key is rejected), or a legacy
    set of lowercase bare column names. Decomposition is valid because
    equal partition-key values share a shard, so per-shard distinct sets
    are disjoint and their counts sum. Tiled scans must NOT pass this
    (a value can recur across tiles).
    """
    if callable(distinct_ok_cols):
        distinct_col_ok = distinct_ok_cols
    else:
        _names = {c.lower() for c in distinct_ok_cols}
        distinct_col_ok = lambda col: col.name.lower() in _names  # noqa: E731
    groups = list(agg.group_exprs)
    partial_items: List[ast.Expr] = []
    for gi, g in enumerate(groups):
        partial_items.append(ast.Alias(g, f"__g{gi}"))
    slots: List[Tuple[str, Optional[ast.Expr]]] = []

    def slot_of(kind, arg) -> int:
        for i, (k, a) in enumerate(slots):
            if k == kind and a == arg:
                return i
        slots.append((kind, arg))
        return len(slots) - 1

    def decompose(e: ast.Expr) -> ast.Expr:
        if isinstance(e, ast.Func) and e.name in ast.AGG_FUNCS:
            arg = e.args[0] if e.args else None
            if e.name == "count" and arg is None:
                return merge_ref(slot_of("count_star", None), "sum")
            if e.name == "count":
                return merge_ref(slot_of("count", arg), "sum")
            if e.name == "sum":
                return merge_ref(slot_of("sum", arg), "sum")
            if e.name == "min":
                return merge_ref(slot_of("min", arg), "min")
            if e.name == "max":
                return merge_ref(slot_of("max", arg), "max")
            if e.name == "avg":
                s = merge_ref(slot_of("sum", arg), "sum")
                c = merge_ref(slot_of("count", arg), "sum")
                return ast.BinOp("/", s, c)
            if e.name == "count_distinct":
                if isinstance(arg, ast.Col) and distinct_col_ok(arg):
                    return merge_ref(slot_of("count_distinct", arg),
                                     "sum")
                raise NotDecomposableError(
                    "count(DISTINCT x) only decomposes when the data is "
                    "hash-partitioned on x")
            if e.name in ("stddev", "variance"):
                s = merge_ref(slot_of("sum", arg), "sum")
                s2 = merge_ref(slot_of("sumsq", arg), "sum")
                c = merge_ref(slot_of("count", arg), "sum")
                mean = ast.BinOp("/", s, c)
                var = ast.BinOp("-", ast.BinOp("/", s2, c),
                                ast.BinOp("*", mean, mean))
                return var if e.name == "variance" else \
                    ast.Func("sqrt", (var,))
            raise NotDecomposableError(
                f"aggregate {e.name} not decomposable")
        for gi, g in enumerate(groups):
            if e == g:
                return ast.Col(f"__g{gi}")
        return e.map_children(decompose)

    merged_select: List[ast.Expr] = []
    for e in agg.agg_exprs:
        name = e.name if isinstance(e, ast.Alias) else None
        base = e.child if isinstance(e, ast.Alias) else e
        rewritten = decompose(base)
        merged_select.append(ast.Alias(rewritten, name)
                             if name else rewritten)

    merged_having = decompose(having) if having is not None else None

    for si, (kind, arg) in enumerate(slots):
        if kind == "count_star":
            partial_items.append(ast.Alias(ast.Func("count", ()),
                                           f"__p{si}"))
        elif kind == "sumsq":
            partial_items.append(ast.Alias(
                ast.Func("sum", (ast.BinOp("*", arg, arg),)),
                f"__p{si}"))
        elif kind == "count_distinct":
            partial_items.append(ast.Alias(
                ast.Func("count_distinct", (arg,)), f"__p{si}"))
        else:
            partial_items.append(ast.Alias(ast.Func(kind, (arg,)),
                                           f"__p{si}"))

    partial_plan = ast.Aggregate(agg.child, tuple(groups),
                                 tuple(partial_items))
    return partial_plan, merged_select, len(slots), merged_having



def ddl_type(dt) -> str:
    """T dtype → DDL string for scratch partial tables."""
    return {"string": "STRING", "int": "INT", "long": "BIGINT",
            "double": "DOUBLE", "float": "REAL", "boolean": "BOOLEAN",
            "date": "DATE", "timestamp": "TIMESTAMP", "short": "SMALLINT",
            "byte": "TINYINT", "decimal": "DOUBLE"}.get(dt.name, "DOUBLE")
