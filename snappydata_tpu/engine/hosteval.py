"""Host (numpy/pandas) expression & plan evaluation.

Three jobs:
1. Post-ops over small materialized results (HAVING / ORDER BY / LIMIT /
   DISTINCT / outer projects) — the reference does the same driver-side
   (CollectAggregateExec, ExistingPlans.scala:106; executeTake,
   CachedDataFrame.scala:766).
2. Full-plan fallback when device lowering hits an unsupported construct
   (ref: CodegenSparkFallback.scala:33-88 retries with the vanilla path).
3. Mutation predicates/assignments over decoded host columns (UPDATE/
   DELETE run host-side; they are OLTP-sized by design, §3.3).
"""

from __future__ import annotations

import datetime
import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from snappydata_tpu import types as T
from snappydata_tpu.sql import ast
from snappydata_tpu.sql.analyzer import expr_type, _expr_name


class HostEvalError(Exception):
    pass


# --------------------------------------------------------------------------
# Expression evaluation: (values, nullmask) over host arrays
# --------------------------------------------------------------------------

def eval_expr(e: ast.Expr, cols: Sequence[np.ndarray],
              nulls: Sequence[Optional[np.ndarray]], params: Tuple,
              n: int) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    if isinstance(e, ast.Alias):
        return eval_expr(e.child, cols, nulls, params, n)
    if isinstance(e, ast.Col):
        return cols[e.index], nulls[e.index]
    if isinstance(e, ast.Lit):
        if e.value is None:
            return np.zeros(n), np.ones(n, dtype=bool)
        return np.broadcast_to(np.asarray(e.value), (n,)), None
    if isinstance(e, (ast.ParamLiteral, ast.Param)):
        v = params[e.pos]
        if v is None:
            return np.zeros(n), np.ones(n, dtype=bool)
        return np.broadcast_to(np.asarray(v), (n,)), None
    if isinstance(e, ast.Cast):
        v, nl = eval_expr(e.child, cols, nulls, params, n)
        if e.to.name == "string":
            return np.asarray([_to_str(x) for x in v], dtype=object), nl
        return np.asarray(v).astype(e.to.np_dtype), nl
    if isinstance(e, ast.UnaryOp):
        v, nl = eval_expr(e.child, cols, nulls, params, n)
        if e.op == "not":
            return ~v.astype(bool), nl
        return -v, nl
    if isinstance(e, ast.IsNull):
        v, nl = eval_expr(e.child, cols, nulls, params, n)
        isn = nl if nl is not None else np.zeros(n, dtype=bool)
        if v.dtype == object:
            isn = isn | np.array([x is None for x in v])
        return (~isn if e.negated else isn), None
    if isinstance(e, ast.Between):
        return eval_expr(_between_to_and(e), cols, nulls, params, n)
    if isinstance(e, ast.InList):
        v, nl = eval_expr(e.child, cols, nulls, params, n)
        acc = np.zeros(n, dtype=bool)
        for val in e.values:
            vv, vn = eval_expr(val, cols, nulls, params, n)
            acc |= _safe_cmp(v, vv, "=")
        if e.negated:
            acc = ~acc
        return acc, nl
    if isinstance(e, ast.Like):
        v, nl = eval_expr(e.child, cols, nulls, params, n)
        regex = re.compile(
            "^" + re.escape(e.pattern).replace("%", ".*").replace("_", ".")
            + "$", re.DOTALL)
        hit = np.array([x is not None and regex.match(str(x)) is not None
                        for x in v])
        if e.negated:
            hit = ~hit
        return hit, nl
    if isinstance(e, ast.Case):
        out_v = None
        out_n = np.ones(n, dtype=bool)
        if e.otherwise is not None:
            out_v, out_n = eval_expr(e.otherwise, cols, nulls, params, n)
            out_v = np.array(out_v, copy=True)
            out_n = np.array(out_n, copy=True) if out_n is not None \
                else np.zeros(n, dtype=bool)
        done = np.zeros(n, dtype=bool)
        branches = []
        for c, val in e.whens:
            cv, cn = eval_expr(c, cols, nulls, params, n)
            take = cv.astype(bool) & ~done
            if cn is not None:
                take &= ~cn
            vv, vn = eval_expr(val, cols, nulls, params, n)
            branches.append((take, vv, vn))
            done |= take
        if out_v is None:
            proto = branches[0][1] if branches else np.zeros(n)
            out_v = np.zeros(n, dtype=proto.dtype if proto.dtype != object
                             else object)
            out_n = np.ones(n, dtype=bool)
        for take, vv, vn in branches:
            out_v[take] = np.broadcast_to(vv, (n,))[take]
            out_n[take] = (np.broadcast_to(vn, (n,))[take]
                           if vn is not None else False)
        return out_v, out_n
    if isinstance(e, ast.BinOp):
        return _eval_binop(e, cols, nulls, params, n)
    if isinstance(e, ast.Func):
        return _eval_func(e, cols, nulls, params, n)
    raise HostEvalError(f"cannot evaluate {type(e).__name__} on host")


def _between_to_and(e: ast.Between) -> ast.Expr:
    both = ast.BinOp("and", ast.BinOp(">=", e.child, e.lo),
                     ast.BinOp("<=", e.child, e.hi))
    return ast.UnaryOp("not", both) if e.negated else both


def _safe_cmp(a, b, op):
    if a.dtype == object or (hasattr(b, "dtype") and b.dtype == object):
        a_l = [x if x is not None else "" for x in np.broadcast_to(a, a.shape)]
        b_arr = np.broadcast_to(b, a.shape)
        b_l = [x if x is not None else "" for x in b_arr]
        pairs = zip(a_l, b_l)
        fn = {"=": lambda x, y: x == y, "!=": lambda x, y: x != y,
              "<": lambda x, y: x < y, "<=": lambda x, y: x <= y,
              ">": lambda x, y: x > y, ">=": lambda x, y: x >= y}[op]
        return np.array([fn(str(x), str(y)) for x, y in pairs])
    fn = {"=": np.equal, "!=": np.not_equal, "<": np.less,
          "<=": np.less_equal, ">": np.greater, ">=": np.greater_equal}[op]
    return fn(a, b)


def _eval_binop(e: ast.BinOp, cols, nulls, params, n):
    a, an = eval_expr(e.left, cols, nulls, params, n)
    b, bn = eval_expr(e.right, cols, nulls, params, n)
    nl = _or_null(an, bn)
    op = e.op
    if op == "and":
        av, bv = a.astype(bool), b.astype(bool)
        v = av & bv
        if nl is not None:
            anx = an if an is not None else np.zeros(n, bool)
            bnx = bn if bn is not None else np.zeros(n, bool)
            nl = (anx & bnx) | (anx & bv) | (bnx & av)
            v = v & ~nl
        return v, nl
    if op == "or":
        av, bv = a.astype(bool), b.astype(bool)
        v = av | bv
        if nl is not None:
            anx = an if an is not None else np.zeros(n, bool)
            bnx = bn if bn is not None else np.zeros(n, bool)
            nl = (anx & bnx) | (anx & ~bv) | (bnx & ~av)
        return v, nl
    if op in ("=", "!=", "<", "<=", ">", ">="):
        return _safe_cmp(np.broadcast_to(a, (n,)),
                         np.broadcast_to(b, (n,)), op), nl
    if op == "/":
        af = a.astype(np.float64)
        bf = b.astype(np.float64)
        zero = bf == 0
        nl = _or_null(nl, zero if zero.any() else None)
        return af / np.where(zero, 1, bf), nl
    fn = {"+": np.add, "-": np.subtract, "*": np.multiply,
          "%": np.mod}[op]
    return fn(a, b), nl



def _np_to_days(v, dt_in):
    v = np.asarray(v)
    if dt_in is not None and dt_in.name == "timestamp":
        return (v.astype(np.int64) // 86_400_000_000).astype(np.int64)
    return v.astype(np.int64)


def _np_civil_from_days(days):
    """Vectorized Hinnant civil_from_days (numpy twin of exprs.py)."""
    z = np.asarray(days, dtype=np.int64) + 719468
    era = np.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = np.where(mp < 10, mp + 3, mp - 9)
    y = np.where(m <= 2, y + 1, y)
    return y.astype(np.int64), m.astype(np.int64), d.astype(np.int64)


def _np_days_from_civil(y, m, d):
    y = np.asarray(y, dtype=np.int64) - (np.asarray(m) <= 2)
    era = np.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    mp = np.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return (era * 146097 + doe - 719468).astype(np.int64)


def _np_days_in_month(y, m):
    dim = np.asarray([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31],
                     dtype=np.int64)[np.asarray(m, dtype=np.int64) - 1]
    leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
    return np.where((np.asarray(m) == 2) & leap, 29, dim)


def _eval_func(e: ast.Func, cols, nulls, params, n):
    name = e.name
    args = [eval_expr(a, cols, nulls, params, n) for a in e.args]
    if name == "coalesce":
        out_v = np.array(np.broadcast_to(args[-1][0], (n,)), copy=True)
        out_n = args[-1][1]
        out_n = np.array(np.broadcast_to(out_n, (n,)), copy=True) \
            if out_n is not None else np.zeros(n, dtype=bool)
        for v, nl in reversed(args[:-1]):
            use = ~nl if nl is not None else np.ones(n, dtype=bool)
            out_v[use] = np.broadcast_to(v, (n,))[use]
            out_n[use] = False
        return out_v, (out_n if out_n.any() else None)
    if name == "abs":
        return np.abs(args[0][0]), args[0][1]
    if name in ("sqrt", "exp", "ln", "log"):
        fn = {"sqrt": np.sqrt, "exp": np.exp, "ln": np.log,
              "log": np.log}[name]
        return fn(args[0][0].astype(np.float64)), args[0][1]
    if name == "round":
        digits = int(e.args[1].value) if len(e.args) > 1 and \
            isinstance(e.args[1], ast.Lit) else 0
        return np.round(args[0][0].astype(np.float64), digits), args[0][1]
    if name in ("pow", "power"):
        return np.power(args[0][0].astype(np.float64), args[1][0]), \
            _or_null(args[0][1], args[1][1])
    if name in ("year", "month", "day", "dayofmonth", "quarter",
                "dayofyear", "dayofweek", "weekofyear"):
        v, nl = args[0]
        days = _np_to_days(v, expr_type(e.args[0]))
        y, m, d = _np_civil_from_days(days)
        if name in ("year",):
            part = y
        elif name == "month":
            part = m
        elif name in ("day", "dayofmonth"):
            part = d
        elif name == "quarter":
            part = (m + 2) // 3
        elif name == "dayofyear":
            part = days - _np_days_from_civil(y, np.ones_like(m),
                                              np.ones_like(d)) + 1
        elif name == "dayofweek":
            part = (days + 4) % 7 + 1
        else:  # weekofyear (ISO)
            wd = (days + 3) % 7 + 1
            thu = days + (4 - wd)
            ty, _, _ = _np_civil_from_days(thu)
            jan1 = _np_days_from_civil(ty, np.ones_like(ty),
                                       np.ones_like(ty))
            part = (thu - jan1) // 7 + 1
        return part.astype(np.int32), nl
    if name in ("hour", "minute", "second"):
        v, nl = args[0]
        divisor, modulo = {"hour": (3_600_000_000, 24),
                           "minute": (60_000_000, 60),
                           "second": (1_000_000, 60)}[name]
        if expr_type(e.args[0]).name == "timestamp":
            out = (np.asarray(v, dtype=np.int64) // divisor) % modulo
        else:
            out = np.zeros_like(np.asarray(v, dtype=np.int64))
        return out.astype(np.int32), nl
    if name in ("date_add", "date_sub"):
        sign = 1 if name == "date_add" else -1
        a, an = args[0]
        b, bn = args[1]
        days = _np_to_days(a, expr_type(e.args[0]))
        out = days + sign * np.asarray(b, dtype=np.int64)
        return out.astype(np.int32), _or_null(an, bn)
    if name == "datediff":
        a, an = args[0]
        b, bn = args[1]
        out = _np_to_days(a, expr_type(e.args[0])) - \
            _np_to_days(b, expr_type(e.args[1]))
        return out.astype(np.int32), _or_null(an, bn)
    if name == "add_months":
        a, an = args[0]
        b, bn = args[1]
        y, m, d = _np_civil_from_days(_np_to_days(a, expr_type(e.args[0])))
        m0 = y * 12 + (m - 1) + np.asarray(b, dtype=np.int64)
        y2, m2 = m0 // 12, m0 % 12 + 1
        d2 = np.minimum(d, _np_days_in_month(y2, m2))
        return _np_days_from_civil(y2, m2, d2).astype(np.int32), \
            _or_null(an, bn)
    if name == "last_day":
        v, nl = args[0]
        y, m, _d = _np_civil_from_days(_np_to_days(v, expr_type(e.args[0])))
        return _np_days_from_civil(y, m, _np_days_in_month(y, m)) \
            .astype(np.int32), nl
    if name == "trunc":
        v, nl = args[0]
        if len(e.args) < 2 or not isinstance(e.args[1], ast.Lit):
            raise HostEvalError("trunc needs a literal format")
        fmt = str(e.args[1].value).upper()
        days = _np_to_days(v, expr_type(e.args[0]))
        y, m, d = _np_civil_from_days(days)
        one = np.ones_like(m)
        if fmt in ("YEAR", "YYYY", "YY"):
            out = _np_days_from_civil(y, one, one)
        elif fmt in ("MONTH", "MM", "MON"):
            out = _np_days_from_civil(y, m, one)
        elif fmt in ("QUARTER", "Q"):
            out = _np_days_from_civil(y, ((m - 1) // 3) * 3 + 1, one)
        elif fmt == "WEEK":
            out = days - (days + 3) % 7
        else:
            raise ValueError(f"trunc format {fmt!r}")
        return out.astype(np.int32), nl
    if name == "months_between":
        a, an = args[0]
        b, bn = args[1]
        y1, m1, d1 = _np_civil_from_days(_np_to_days(a, expr_type(e.args[0])))
        y2, m2, d2 = _np_civil_from_days(_np_to_days(b, expr_type(e.args[1])))
        whole = ((y1 - y2) * 12 + (m1 - m2)).astype(np.float64)
        same = (d1 == d2) | ((d1 == _np_days_in_month(y1, m1))
                             & (d2 == _np_days_in_month(y2, m2)))
        frac = np.where(same, 0.0, (d1 - d2).astype(np.float64) / 31.0)
        return whole + frac, _or_null(an, bn)
    if name == "unix_timestamp":
        v, nl = args[0]
        if expr_type(e.args[0]).name == "timestamp":
            out = np.asarray(v, dtype=np.int64) // 1_000_000
        else:
            out = np.asarray(v, dtype=np.int64) * 86_400
        return out, nl
    if name == "to_date":
        v, nl = args[0]
        dt_in = expr_type(e.args[0])
        if dt_in.name in ("date", "timestamp"):
            return _np_to_days(v, dt_in).astype(np.int32), nl
        epoch = datetime.date(1970, 1, 1).toordinal()
        out = np.zeros(len(v), dtype=np.int32)
        bad = np.zeros(len(v), dtype=bool)
        for i, x in enumerate(v):
            if x is None:
                bad[i] = True
                continue
            try:
                out[i] = datetime.date.fromisoformat(
                    str(x)[:10]).toordinal() - epoch
            except ValueError:
                bad[i] = True
        return out, _or_null(nl, bad if bad.any() else None)
    if name == "ascii":
        v, nl = args[0]
        return np.array([ord(str(x)[0]) if x is not None and str(x)
                         else 0 for x in v], dtype=np.int32), nl
    if name in ("upper", "lower", "trim", "ltrim", "rtrim", "initcap",
                "reverse"):
        fn = {"upper": str.upper, "lower": str.lower, "trim": str.strip,
              "ltrim": str.lstrip, "rtrim": str.rstrip,
              "initcap": lambda s: " ".join(
                  p[:1].upper() + p[1:].lower() for p in s.split(" ")),
              "reverse": lambda s: s[::-1]}[name]
        v, nl = args[0]
        return np.array([fn(str(x)) if x is not None else None for x in v],
                        dtype=object), nl
    if name in ("lpad", "rpad"):
        v, nl = args[0]
        n2 = int(np.asarray(args[1][0]).flat[0])
        pad = str(np.asarray(args[2][0]).flat[0]) if len(args) > 2 else " "

        def padfn(x):
            if x is None:
                return None
            if n2 <= 0:
                return ""
            sx = str(x)
            if len(sx) >= n2:
                return sx[:n2]
            fill = (pad * n2)[:n2 - len(sx)] if pad else ""
            return fill + sx if name == "lpad" else sx + fill

        return np.array([padfn(x) for x in v], dtype=object), nl
    if name == "repeat":
        v, nl = args[0]
        times = int(np.asarray(args[1][0]).flat[0])
        return np.array([str(x) * max(0, times) if x is not None else None
                         for x in v], dtype=object), nl
    if name == "translate":
        v, nl = args[0]
        frm = str(np.asarray(args[1][0]).flat[0])
        to = str(np.asarray(args[2][0]).flat[0]) if len(args) > 2 else ""
        table = {ord(f): (to[i] if i < len(to) else None)
                 for i, f in enumerate(frm)}
        return np.array([str(x).translate(table) if x is not None else None
                         for x in v], dtype=object), nl
    if name == "split_part":
        v, nl = args[0]
        delim = str(np.asarray(args[1][0]).flat[0])
        idx = int(np.asarray(args[2][0]).flat[0])
        if idx == 0:
            raise HostEvalError("split_part index must not be 0")

        def part(x):
            if x is None:
                return None
            parts = str(x).split(delim) if delim else [str(x)]
            pos = idx - 1 if idx > 0 else len(parts) + idx
            return parts[pos] if 0 <= pos < len(parts) else ""

        return np.array([part(x) for x in v], dtype=object), nl
    if name in ("substr", "substring"):
        v, nl = args[0]
        start = int(np.asarray(args[1][0]).flat[0]) - 1 if len(args) > 1 else 0
        ln = int(np.asarray(args[2][0]).flat[0]) if len(args) > 2 else None
        def sub(x):
            if x is None:
                return None
            s = str(x)
            return s[start:start + ln] if ln is not None else s[start:]
        return np.array([sub(x) for x in v], dtype=object), nl
    if name == "length":
        v, nl = args[0]
        return np.array([len(str(x)) if x is not None else 0 for x in v],
                        dtype=np.int32), nl
    if name == "nullif":
        a_v, a_n = args[0]
        b_v, b_n = args[1]
        av = np.broadcast_to(a_v, (n,))
        eq = _safe_cmp(av, np.broadcast_to(b_v, (n,)), "=")
        if b_n is not None:
            eq = eq & ~np.broadcast_to(b_n, (n,))
        out_n = np.array(eq, copy=True)
        if a_n is not None:
            out_n |= np.broadcast_to(a_n, (n,))
        return np.array(av, copy=True), (out_n if out_n.any() else None)
    if name in ("floor", "ceil", "ceiling"):
        fn = np.floor if name == "floor" else np.ceil
        return fn(np.asarray(args[0][0]).astype(np.float64)) \
            .astype(np.int64), args[0][1]
    if name in ("mod", "pmod"):
        a_v = np.broadcast_to(args[0][0], (n,))
        b_v = np.broadcast_to(args[1][0], (n,))
        nl = _or_null(args[0][1], args[1][1])
        zero = b_v == 0
        if zero.any():
            nl = _or_null(nl, zero)
        b_safe = np.where(zero, 1, b_v)
        # mod keeps the dividend's sign (Spark %); pmod is non-negative
        out = np.fmod(a_v, b_safe) if name == "mod" \
            else np.mod(np.mod(a_v, b_safe) + b_safe, b_safe)
        return out, nl
    if name in ("greatest", "least"):
        vs = np.stack([np.asarray(np.broadcast_to(a[0], (n,)))
                       for a in args])
        nls = np.stack([np.broadcast_to(a[1], (n,)) if a[1] is not None
                        else np.zeros(n, dtype=bool) for a in args])
        masked = np.ma.masked_array(vs, mask=nls)
        picked = masked.max(axis=0) if name == "greatest" \
            else masked.min(axis=0)
        out_n = nls.all(axis=0)   # NULL only when every argument is NULL
        return np.asarray(picked.filled(0)), (out_n if out_n.any()
                                              else None)
    if name == "replace":
        v, nl = args[0]
        if args[1][1] is not None or \
                (len(args) > 2 and args[2][1] is not None):
            # Spark: NULL search/replacement → NULL result
            return np.full(n, None, dtype=object), np.ones(n, dtype=bool)
        search = str(np.asarray(args[1][0]).flat[0])
        repl = str(np.asarray(args[2][0]).flat[0]) if len(args) > 2 else ""
        return np.array([str(x).replace(search, repl)
                         if x is not None else None for x in v],
                        dtype=object), nl
    if name == "sign":
        return np.sign(np.asarray(args[0][0]).astype(np.float64)), \
            args[0][1]
    if name == "instr":
        v, nl = args[0]
        sub = str(np.asarray(args[1][0]).flat[0])
        return np.array([str(x).find(sub) + 1 if x is not None else 0
                         for x in v], dtype=np.int32), nl
    if name == "array":
        vs = [np.broadcast_to(a[0], (n,)) for a in args]
        nls = [a[1] for a in args]
        out = np.empty(n, dtype=object)
        for i in range(n):
            out[i] = [None if (nls[j] is not None
                               and np.broadcast_to(nls[j], (n,))[i])
                      else _plain(vs[j][i]) for j in range(len(vs))]
        return out, None
    if name == "map":
        vs = [np.broadcast_to(a[0], (n,)) for a in args]
        out = np.empty(n, dtype=object)
        for i in range(n):
            out[i] = {_plain(vs[j][i]): _plain(vs[j + 1][i])
                      for j in range(0, len(vs), 2)}
        return out, None
    if name == "named_struct":
        out = np.empty(n, dtype=object)
        keys = [np.broadcast_to(args[i][0], (n,))
                for i in range(0, len(args) - 1, 2)]
        vals = [np.broadcast_to(args[i][0], (n,))
                for i in range(1, len(args), 2)]
        vnulls = [np.broadcast_to(args[i][1], (n,))
                  if args[i][1] is not None else None
                  for i in range(1, len(args), 2)]
        for r in range(n):
            out[r] = {str(k[r]): (None if vn is not None and vn[r]
                                  else _plain(v[r]))
                      for k, v, vn in zip(keys, vals, vnulls)}
        return out, None
    if name in ("map_keys", "map_values"):
        v, nl = args[0]
        out = np.empty(n, dtype=object)
        for i, x in enumerate(np.broadcast_to(v, (n,))):
            if isinstance(x, dict):
                out[i] = list(x.keys()) if name == "map_keys" \
                    else list(x.values())
            else:
                out[i] = None
        return out, nl
    if name == "size":
        v, nl = args[0]
        out = np.array(
            [len(x) if isinstance(x, (list, tuple, dict)) else -1
             for x in np.broadcast_to(v, (n,))], dtype=np.int32)
        return out, nl
    if name == "array_contains":
        v, nl = args[0]
        needle = np.broadcast_to(args[1][0], (n,))
        needle_null = args[1][1]
        out = np.array(
            [isinstance(x, (list, tuple)) and _plain(needle[i]) in x
             for i, x in enumerate(np.broadcast_to(v, (n,)))])
        combined = nl
        if needle_null is not None:
            nn = np.broadcast_to(needle_null, (n,))
            combined = nn if combined is None else (combined | nn)
        return out, combined
    if name == "element_at":
        v, nl = args[0]
        idx = np.broadcast_to(args[1][0], (n,))
        vals = []
        nulls_out = np.zeros(n, dtype=bool)
        for i, x in enumerate(np.broadcast_to(v, (n,))):
            if isinstance(x, dict):  # map/struct lookup by key
                k = _plain(idx[i])
                got = x.get(k)
                if got is None and isinstance(k, str):
                    # struct field names resolve case-insensitively, like
                    # the analyzer's StructType.field_type
                    for kk, vv in x.items():
                        if isinstance(kk, str) and kk.lower() == k.lower():
                            got = vv
                            break
                vals.append(got)
                nulls_out[i] = got is None
                continue
            if not isinstance(x, (list, tuple)):  # NULL map/array row
                vals.append(None)
                nulls_out[i] = True
                continue
            k = int(idx[i]) - 1  # element_at on arrays is 1-based
            if 0 <= k < len(x):
                vals.append(x[k])
                nulls_out[i] = x[k] is None
            else:
                vals.append(None)
                nulls_out[i] = True
        out = np.array(vals, dtype=object)
        if nl is not None:
            nulls_out |= np.broadcast_to(nl, (n,))
        return out, (nulls_out if nulls_out.any() else None)
    if name == "concat":
        vs = [np.broadcast_to(a[0], (n,)) for a in args]
        nl = None
        for a in args:
            nl = _or_null(nl, a[1])
        return np.array(["".join(str(x) for x in row)
                         for row in zip(*vs)], dtype=object), nl
    from snappydata_tpu.sql import udf as _udf

    u = _udf.lookup(name)
    if u is not None:
        vals = [np.broadcast_to(v, (n,)) for v, _ in args]
        try:
            out = np.asarray(u.fn(*vals))
        except Exception as ex:
            raise HostEvalError(f"UDF {name} failed: {ex}")
        if out.shape != (n,):
            out = np.broadcast_to(out, (n,))
        nl = None
        for _, a_nl in args:
            nl = _or_null(nl, a_nl)
        return out, nl

    raise HostEvalError(f"unsupported host function {name}")


def _to_str(x):
    return None if x is None else str(x)


def _plain(x):
    return x.item() if hasattr(x, "item") else x


def _or_null(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a | b


# --------------------------------------------------------------------------
# Result-level ops
# --------------------------------------------------------------------------

from snappydata_tpu.engine.result import Result  # noqa: E402
from snappydata_tpu.engine.result import \
    unscale_decimal_col as _unscale_decimal_col  # noqa: E402


def limit(result: Result, k: int) -> Result:
    return Result(result.names,
                  [c[:k] for c in result.columns],
                  [nm[:k] if nm is not None else None for nm in result.nulls],
                  result.dtypes)


def _hashable(row):
    return tuple(tuple(v) if isinstance(v, list) else v for v in row)


def distinct(result: Result) -> Result:
    seen = set()
    keep = []
    for i, row in enumerate(result.rows()):
        key = _hashable(row)
        if key not in seen:
            seen.add(key)
            keep.append(i)
    idx = np.array(keep, dtype=np.int64)
    return _take(result, idx)


def _take(result: Result, idx: np.ndarray) -> Result:
    return Result(result.names,
                  [c[idx] for c in result.columns],
                  [nm[idx] if nm is not None else None for nm in result.nulls],
                  result.dtypes)


def _float_domain_columns(result: Result) -> List[np.ndarray]:
    """Result columns with exact-decimal scaled-int64 columns (the
    compiled engine's representation) unscaled to plain float64 — what
    result-level EXPRESSIONS (sort keys, HAVING predicates, projected
    arithmetic) must consume. `_take`-style passthroughs keep the
    original scaled columns, so exactness survives sort/limit/filter."""
    return [_unscale_decimal_col(c, dt)
            for c, dt in zip(result.columns, result.dtypes)]


def sort(result: Result, orders, params) -> Result:
    n = result.num_rows
    if n == 0:
        return result
    fcols = _float_domain_columns(result)
    keys = []
    for item in reversed(list(orders)):
        e, asc = item[0], item[1]
        nulls_first = item[2] if len(item) > 2 and item[2] is not None \
            else asc   # Spark default: ASC → NULLS FIRST, DESC → LAST
        v, nl = eval_expr(e, fcols, result.nulls, params, n)
        v = np.broadcast_to(v, (n,))
        isnull = np.broadcast_to(nl, (n,)).copy() if nl is not None \
            else np.zeros(n, dtype=bool)
        if v.dtype == object:
            isnull = isnull | np.array([x is None for x in v])
            v = np.array([("" if x is None else str(x)) for x in v])
        if not asc:
            if v.dtype.kind in "OUS":
                # lexsort is ascending-only: invert via rank
                order_idx = np.argsort(v, kind="stable")
                rank = np.empty(n, dtype=np.int64)
                rank[order_idx] = np.arange(n)
                v = -rank
            else:
                v = -v
        keys.append(v)
        # null indicator sorts ascending: False before True
        keys.append(~isnull if nulls_first else isnull)
    idx = np.lexsort(keys) if keys else np.arange(n)
    return _take(result, idx)


def filter_result(result: Result, cond: ast.Expr, params) -> Result:
    n = result.num_rows
    v, nl = eval_expr(cond, _float_domain_columns(result), result.nulls,
                      params, n)
    keep = np.broadcast_to(v, (n,)).astype(bool)
    if nl is not None:
        keep = keep & ~nl
    return _take(result, np.nonzero(keep)[0])


def project_result(result: Result, exprs, params) -> Result:
    n = result.num_rows
    fcols = _float_domain_columns(result)
    names, cols, nulls, dtypes = [], [], [], []
    for e in exprs:
        base = e.child if isinstance(e, ast.Alias) else e
        if isinstance(base, ast.Col) and base.index is not None:
            # bare column pass-through keeps the ORIGINAL representation
            # (exact-decimal scaled ints survive a result-level SELECT)
            v = result.columns[base.index]
            nl = result.nulls[base.index]
        else:
            v, nl = eval_expr(e, fcols, result.nulls, params, n)
        names.append(_expr_name(e))
        cols.append(np.broadcast_to(v, (n,)))
        nulls.append(np.broadcast_to(nl, (n,)) if nl is not None else None)
        dtypes.append(expr_type(e))
    return Result(names, cols, nulls, dtypes)


# _unscale_decimal_col binds at module bottom (the established
# cycle-avoiding import spot) to engine.result.unscale_decimal_col


def union(a: Result, b: Result) -> Result:
    cols = []
    nulls = []
    dtypes = list(a.dtypes)
    for i in range(len(a.columns)):
        ca, cb = a.columns[i], b.columns[i]
        if (a.dtypes[i] is not None and a.dtypes[i].name == "decimal") \
                or (b.dtypes[i] is not None
                    and b.dtypes[i].name == "decimal"):
            # branches may sit in different domains (scaled int vs
            # float) or at different scales: normalize both through
            # each branch's OWN dtype before concatenating, and WIDEN
            # the declared type over both branches so a finer right-
            # branch scale survives the decode quantization (Spark
            # widens union types the same way; review finding)
            ca = _unscale_decimal_col(ca, a.dtypes[i])
            cb = _unscale_decimal_col(cb, b.dtypes[i])
            if a.dtypes[i] != b.dtypes[i] and b.dtypes[i] is not None \
                    and a.dtypes[i] is not None:
                try:
                    dtypes[i] = T.common_type(a.dtypes[i], b.dtypes[i])
                except TypeError:
                    pass
        if ca.dtype != cb.dtype:
            ca = ca.astype(object)
            cb = cb.astype(object)
        cols.append(np.concatenate([ca, cb]))
        na = a.nulls[i] if a.nulls[i] is not None else np.zeros(
            a.num_rows, dtype=bool)
        nb = b.nulls[i] if b.nulls[i] is not None else np.zeros(
            b.num_rows, dtype=bool)
        merged = np.concatenate([na, nb])
        nulls.append(merged if merged.any() else None)
    return Result(a.names, cols, nulls, dtypes)


def set_op(a: Result, b: Result, op: str) -> Result:
    """INTERSECT / EXCEPT with SQL set semantics: DISTINCT output, and
    NULLs compare EQUAL (unlike joins) — row-tuples with None make that
    free in Python. Exact-decimal columns compare through each branch's
    own unscaled domain (the same alignment union() applies), so a
    scaled-int branch can intersect a float branch."""
    def row_tuples(r: Result):
        rcols = [_unscale_decimal_col(c, dt)
                 for c, dt in zip(r.columns, r.dtypes)]
        out = []
        for i in range(r.num_rows):
            row = []
            for c, nm in zip(rcols, r.nulls):
                if (nm is not None and nm[i]) or \
                        (c.dtype == object and c[i] is None):
                    row.append(None)
                else:
                    v = c[i]
                    row.append(v.item() if hasattr(v, "item") else v)
            out.append(tuple(row))
        return out

    right = set(row_tuples(b))
    seen = set()
    keep_idx = []
    for i, row in enumerate(row_tuples(a)):
        if row in seen:
            continue
        seen.add(row)
        if (op == "intersect") == (row in right):
            keep_idx.append(i)
    idx = np.asarray(keep_idx, dtype=np.int64)
    # output decimal columns leave in the UNSCALED domain with the
    # dtype widened over both branches — the analyzer's SetOp scope is
    # widened the same way, so a left-branch scaled column must not be
    # decoded at the (possibly finer) widened scale (review finding)
    cols = []
    dtypes = list(a.dtypes)
    for i, c in enumerate(a.columns):
        if (a.dtypes[i] is not None and a.dtypes[i].name == "decimal") \
                or (b.dtypes[i] is not None
                    and b.dtypes[i].name == "decimal"):
            c = _unscale_decimal_col(c, a.dtypes[i])
            if a.dtypes[i] != b.dtypes[i] and a.dtypes[i] is not None \
                    and b.dtypes[i] is not None:
                try:
                    dtypes[i] = T.common_type(a.dtypes[i], b.dtypes[i])
                except TypeError:
                    pass
        cols.append(c[idx])
    nulls = [nm[idx] if nm is not None else None for nm in a.nulls]
    return Result(a.names, cols, nulls, dtypes)


def eval_values(node: ast.Values, params) -> Result:
    nrows = len(node.rows)
    ncols = len(node.rows[0])
    names = [f"col{i + 1}" for i in range(ncols)]
    cols, nulls, dtypes = [], [], []
    for c in range(ncols):
        vals = []
        nmask = np.zeros(nrows, dtype=bool)
        dt = expr_type(node.rows[0][c])
        for r in range(nrows):
            e = node.rows[r][c]
            if isinstance(e, (ast.ParamLiteral, ast.Param)):
                v = params[e.pos]
            elif isinstance(e, ast.Lit):
                v = e.value
            else:
                v, nl = eval_expr(e, [], [], params, 1)
                v = v[0]
            if v is None:
                nmask[r] = True
                vals.append(None)
            else:
                vals.append(v)
        if dt.name in ("string", "array", "map") or dt.np_dtype == object:
            # element-wise: np.array() would turn equal-length lists
            # into a 2-D array and strip their list-ness
            arr = np.empty(len(vals), dtype=object)
            for j, v in enumerate(vals):
                arr[j] = v
        else:
            arr = np.array([0 if v is None else v for v in vals],
                           dtype=dt.np_dtype)
        cols.append(arr)
        nulls.append(nmask if nmask.any() else None)
        dtypes.append(dt)
    return Result(names, cols, nulls, dtypes)


# --------------------------------------------------------------------------
# Window functions (host fallback for shapes the device window path in
# engine/executor.py does not cover — e.g. exotic frames / ntile)
# --------------------------------------------------------------------------

def eval_window(plan, params, executor) -> Result:
    """WindowProject: materialize the child, then evaluate each select
    expression; WindowFunc nodes compute per-partition with pandas.
    Default frames: whole partition without ORDER BY; running frame
    (unbounded preceding → current row) with it."""
    import pandas as pd

    cols, nulls, names, dtypes, n = _eval_rel(plan.child, params, executor)

    def eval_any(e, depth=0):
        """Returns (values, nullmask); recurses through WindowFunc."""
        if isinstance(e, ast.Alias):
            return eval_any(e.child)
        if isinstance(e, ast.WindowFunc):
            return _window_values(e, cols, nulls, params, n)
        # ordinary expression, but it may CONTAIN window funcs: substitute
        # their computed values as pseudo-columns
        subs = {}

        def find(node):
            if isinstance(node, ast.WindowFunc):
                subs[id(node)] = node
            for c in node.children():
                find(c)

        find(e)
        if not subs:
            return eval_expr(e, cols, nulls, params, n)
        ext_cols = list(cols)
        ext_nulls = list(nulls)

        def replace(node):
            if isinstance(node, ast.WindowFunc):
                v, nl = _window_values(node, cols, nulls, params, n)
                idx = len(ext_cols)
                ext_cols.append(v)
                ext_nulls.append(nl)
                return ast.Col(f"__w{idx}", None, idx,
                               expr_type(node))
            return node.map_children(replace)

        return eval_expr(replace(e), ext_cols, ext_nulls, params, n)

    out_c, out_n, out_names, out_t = [], [], [], []
    for e in plan.exprs:
        v, nl = eval_any(e)
        v = np.broadcast_to(v, (n,))
        dt = expr_type(e)
        # pandas paths float-promote ints (NaN machinery): restore the
        # declared integer dtype so values and Result.dtypes agree
        if T.is_integral(dt) and v.dtype.kind == "f":
            filler = np.where(np.isnan(v), 0, v) if v.dtype.kind == "f" \
                else v
            v = filler.astype(dt.np_dtype)
        out_c.append(v)
        out_n.append(np.broadcast_to(nl, (n,)) if nl is not None else None)
        out_names.append(_expr_name(e))
        out_t.append(dt)
    return Result(out_names, list(out_c), list(out_n), out_t)


def _window_values(w, cols, nulls, params, n):
    import pandas as pd

    # partition keys
    if w.partition_by:
        keys = []
        for p in w.partition_by:
            v, _ = eval_expr(p, cols, nulls, params, n)
            keys.append(np.broadcast_to(v, (n,)))
        part_df = pd.DataFrame({f"k{i}": k for i, k in enumerate(keys)})
        group_ids = part_df.groupby(list(part_df.columns), sort=False
                                    ).ngroup().to_numpy()
    else:
        group_ids = np.zeros(n, dtype=np.int64)
    # intra-partition order
    if w.order_by:
        order_keys = []
        for item in reversed(list(w.order_by)):
            e, asc = item[0], item[1]
            nulls_first = item[2] if len(item) > 2 and item[2] is not None \
                else asc   # Spark: ASC → NULLS FIRST, DESC → NULLS LAST
            v, nl = eval_expr(e, cols, nulls, params, n)
            v = np.broadcast_to(v, (n,))
            isnull = np.broadcast_to(nl, (n,)).copy() if nl is not None \
                else np.zeros(n, dtype=bool)
            if v.dtype == object:
                isnull = isnull | np.array([x is None for x in v])
                v = np.array([str(x) if x is not None else "" for x in v])
            order_keys.append(v if asc else _desc_key(v))
            order_keys.append(~isnull if nulls_first else isnull)
        order_keys.append(group_ids)
        sorted_idx = np.lexsort(order_keys)
    else:
        sorted_idx = np.argsort(group_ids, kind="stable")

    g_sorted = group_ids[sorted_idx]
    s = pd.Series(np.arange(n)[sorted_idx])
    grp = s.groupby(g_sorted)

    name = w.name
    if name == "row_number":
        out_sorted = grp.cumcount().to_numpy() + 1
        return _unsort(out_sorted, sorted_idx, np.int64), None
    if name in ("rank", "dense_rank"):
        # tie groups: consecutive sorted rows equal on ALL order keys
        ok_sorted = []
        for e, *_ in w.order_by:
            v, _ = eval_expr(e, cols, nulls, params, n)
            v = np.broadcast_to(v, (n,))
            if v.dtype == object:
                v = np.array([str(x) if x is not None else "" for x in v])
            ok_sorted.append(v[sorted_idx])
        same = np.ones(n, dtype=bool)
        if n:
            same[0] = False
        same[1:] &= g_sorted[1:] == g_sorted[:-1]
        for k in ok_sorted:
            same[1:] &= k[1:] == k[:-1]
        pos_in_part = grp.cumcount().to_numpy()
        start = pd.Series(np.where(same, np.nan, pos_in_part)).ffill()
        if name == "rank":
            out_sorted = start.to_numpy().astype(np.int64) + 1
        else:
            out_sorted = pd.Series(
                (~same).astype(np.int64)).groupby(g_sorted).cumsum() \
                .to_numpy()
        return _unsort(out_sorted, sorted_idx, np.int64), None
    if name == "ntile":
        k = int(params[w.args[0].pos]
                if isinstance(w.args[0], ast.ParamLiteral)
                else w.args[0].value)
        pos = grp.cumcount().to_numpy()
        size = s.groupby(g_sorted).transform("size").to_numpy()
        out_sorted = (pos * k // size) + 1
        return _unsort(out_sorted, sorted_idx, np.int64), None
    if name in ("lag", "lead"):
        v, nl = eval_expr(w.args[0], cols, nulls, params, n)
        v = np.broadcast_to(v, (n,))
        offset = 1
        if len(w.args) > 1 and isinstance(w.args[1],
                                          (ast.Lit, ast.ParamLiteral)):
            offset = int(params[w.args[1].pos]
                         if isinstance(w.args[1], ast.ParamLiteral)
                         else w.args[1].value)
        shift = offset if name == "lag" else -offset
        ser = pd.Series(v[sorted_idx])
        # a NULL input must shift in as NULL, not as its filler value
        if nl is not None:
            in_null = np.broadcast_to(nl, (n,))[sorted_idx]
            ser = ser.where(~pd.Series(in_null), np.nan)
        shifted = ser.groupby(g_sorted).shift(shift)
        out_nulls_sorted = shifted.isna().to_numpy()
        filled = shifted.fillna(0 if v.dtype != object else "").to_numpy()
        out = _unsort(filled, sorted_idx, None)
        out_nl = _unsort(out_nulls_sorted, sorted_idx, np.bool_)
        return out, (out_nl if out_nl.any() else None)
    if name in ("sum", "avg", "min", "max", "count", "first_value",
                "last_value"):
        if w.args:
            v, nl = eval_expr(w.args[0], cols, nulls, params, n)
            v = np.broadcast_to(v, (n,))
            isnull = np.broadcast_to(nl, (n,)).copy() if nl is not None \
                else np.zeros(n, dtype=bool)
            if v.dtype == object:
                isnull = isnull | np.array([x is None for x in v])
            # NULLs → NaN so pandas skips them (SQL aggregate semantics)
            vf = v.astype(np.float64) if v.dtype != object else v
            if isnull.any() and v.dtype != object:
                vf = vf.copy()
                vf[isnull] = np.nan
        else:
            vf = np.ones(n)
            isnull = np.zeros(n, dtype=bool)
        ser = pd.Series(vf[sorted_idx])
        if isnull.any() and vf.dtype == object:
            ser = ser.where(~pd.Series(isnull[sorted_idx]), np.nan)
        g = ser.groupby(g_sorted)
        if w.order_by:
            # SQL default frame with ORDER BY is RANGE → peers (tied
            # order keys) share the frame: compute running values, then
            # take the LAST value of each tie group
            ok_sorted = []
            for e, *_ in w.order_by:
                vv, _ = eval_expr(e, cols, nulls, params, n)
                vv = np.broadcast_to(vv, (n,))
                if vv.dtype == object:
                    vv = np.array([str(x) if x is not None else ""
                                   for x in vv])
                ok_sorted.append(vv[sorted_idx])
            same = np.ones(n, dtype=bool)
            if n:
                same[0] = False
            same[1:] &= g_sorted[1:] == g_sorted[:-1]
            for k in ok_sorted:
                same[1:] &= k[1:] == k[:-1]
            tie_gid = np.cumsum(~same)
            if name == "avg":
                run = (g.cumsum() /
                       ser.notna().groupby(g_sorted).cumsum()).to_numpy()
            elif name == "count":
                run = ser.notna().groupby(g_sorted).cumsum().to_numpy()
            elif name == "first_value":
                run = g.transform("first").to_numpy()
            elif name == "last_value":
                run = ser.to_numpy()
            else:
                run = getattr(g, {"sum": "cumsum", "min": "cummin",
                                  "max": "cummax"}[name])().to_numpy()
            out_sorted = pd.Series(run).groupby(tie_gid).transform(
                "last").to_numpy()
        else:  # whole partition
            agg = {"sum": "sum", "avg": "mean", "min": "min", "max": "max",
                   "count": "count", "first_value": "first",
                   "last_value": "last"}[name]
            out_sorted = g.transform(agg).to_numpy()
        out = _unsort(out_sorted, sorted_idx, None)
        if name == "count":
            return out.astype(np.int64), None
        out_null = pd.isna(out)
        if out_null.any():
            return np.where(out_null, 0, out), np.asarray(out_null)
        return out, None
    raise HostEvalError(f"window function {name}")


def _desc_key(v: np.ndarray):
    if v.dtype.kind in "OUS":
        order_idx = np.argsort(v, kind="stable")
        rank = np.empty(len(v), dtype=np.int64)
        rank[order_idx] = np.arange(len(v))
        return -rank
    return -v


def _unsort(sorted_vals, sorted_idx, dtype):
    out = np.empty(len(sorted_vals),
                   dtype=sorted_vals.dtype if dtype is None else dtype)
    out[sorted_idx] = sorted_vals
    return out


# --------------------------------------------------------------------------
# Full-plan host fallback (pandas-based relational interpreter)
# --------------------------------------------------------------------------

def eval_plan(plan: ast.Plan, params, executor) -> Result:
    from snappydata_tpu.resource.context import check_current

    check_current()  # host fallback entry = cancellation point
    cols, nulls, names, dtypes, n = _eval_rel(plan, params, executor)
    return Result(names, cols, nulls, dtypes)


def _eval_rel(plan: ast.Plan, params, executor):
    """Returns (cols, nulls, names, dtypes, n) with host arrays."""
    if isinstance(plan, ast.Relation):
        info = executor.catalog.lookup_table(plan.name)
        from snappydata_tpu.storage.table_store import RowTableData

        if isinstance(info.data, RowTableData):
            from snappydata_tpu.storage import mvcc

            # pinned statements read their captured host snapshot (row
            # tables mutate in place; repeatable reads within the query)
            arrays, col_nulls, cnt, _ver = mvcc.row_snapshot_of(info.data)
            cols = [np.asarray(a) for a in arrays]
        else:
            from snappydata_tpu.resource.context import check_current
            from snappydata_tpu.storage.device import host_scan_units

            # honor the active scan window (same pinned snapshot and
            # unit slice as build_device_table): when a tile of a
            # scan_tile_bytes pass falls back to host — e.g. the exact-
            # decimal overflow guard fired — it must read ITS tile only,
            # or the merge would double-count every other tile
            m, views, row_chunks = host_scan_units(info.data)
            chunks: List[List[np.ndarray]] = [[] for _ in info.schema.fields]
            nchunks: List[List[np.ndarray]] = [[] for _ in info.schema.fields]
            for view in views:
                check_current()  # batch boundary = cancellation point
                live = view.live_mask()
                lazy = info.data._decode_all(view)
                for i, f in enumerate(info.schema.fields):
                    chunks[i].append(lazy[f.name][live])
                    nm = view.null_mask(i)
                    nchunks[i].append(
                        nm[live] if nm is not None
                        else np.zeros(int(live.sum()), dtype=np.bool_))
            for pos, take in row_chunks:
                sl = slice(pos, pos + take)
                for i, f in enumerate(info.schema.fields):
                    chunks[i].append(np.asarray(m.row_arrays[i])[sl])
                    rn = m.row_nulls[i][sl] if m.row_nulls and \
                        m.row_nulls[i] is not None else \
                        np.zeros(take, dtype=np.bool_)
                    nchunks[i].append(rn)
            cols = [np.concatenate(ch) if ch else
                    np.empty(0, dtype=f.dtype.np_dtype)
                    for ch, f in zip(chunks, info.schema.fields)]
            col_nulls = []
            for i, nc in enumerate(nchunks):
                merged = np.concatenate(nc) if nc else \
                    np.empty(0, dtype=np.bool_)
                col_nulls.append(merged if merged.any() else None)
        n = int(cols[0].shape[0]) if cols else 0
        names = info.schema.names()
        dtypes = [f.dtype for f in info.schema.fields]
        return cols, col_nulls, names, dtypes, n

    if isinstance(plan, ast.SubqueryAlias):
        return _eval_rel(plan.child, params, executor)

    if isinstance(plan, ast.Filter):
        cols, nulls, names, dtypes, n = _eval_rel(plan.child, params, executor)
        v, nl = eval_expr(plan.condition, cols, nulls, params, n)
        keep = np.broadcast_to(v, (n,)).astype(bool)
        if nl is not None:
            keep &= ~nl
        idx = np.nonzero(keep)[0]
        return ([c[idx] for c in cols],
                [nm[idx] if nm is not None else None for nm in nulls],
                names, dtypes, len(idx))

    if isinstance(plan, ast.Project):
        cols, nulls, names, dtypes, n = _eval_rel(plan.child, params, executor)
        out_c, out_n, out_names, out_t = [], [], [], []
        for e in plan.exprs:
            v, nl = eval_expr(e, cols, nulls, params, n)
            out_c.append(np.broadcast_to(v, (n,)))
            out_n.append(np.broadcast_to(nl, (n,)) if nl is not None else None)
            out_names.append(_expr_name(e))
            out_t.append(expr_type(e))
        return out_c, out_n, out_names, out_t, n

    if isinstance(plan, ast.Join):
        return _eval_join(plan, params, executor)

    if isinstance(plan, ast.Aggregate):
        return _eval_aggregate(plan, params, executor)

    if isinstance(plan, (ast.Sort, ast.Limit, ast.Distinct, ast.Union,
                         ast.SetOp, ast.Values, ast.WindowProject)):
        r = executor.execute(plan, params)
        # the compiled engine's exact-decimal columns are scaled int64;
        # the host interpreter's expressions/joins above this node work
        # in the plain float domain
        return (_float_domain_columns(r), r.nulls, r.names, r.dtypes,
                r.num_rows)

    raise HostEvalError(f"host fallback: {type(plan).__name__}")


def _eval_join(plan: ast.Join, params, executor):
    import pandas as pd

    lc, ln, lnames, lt, nl_ = _eval_rel(plan.left, params, executor)
    rc, rn, rnames, rt, nr_ = _eval_rel(plan.right, params, executor)
    ldf = pd.DataFrame({f"l{i}": c for i, c in enumerate(lc)})
    rdf = pd.DataFrame({f"r{i}": c for i, c in enumerate(rc)})
    nleft = len(lc)

    def _null_mask_of(df, name, arr, mask):
        isnull = np.zeros(len(df), dtype=bool)
        if mask is not None:
            isnull |= np.asarray(mask)
        isnull |= df[name].isna().to_numpy()
        if hasattr(arr, "dtype") and arr.dtype == object:
            isnull |= np.array([v is None for v in arr])
        return isnull

    def _null_proof_pair(li, rj):
        """SQL: NULL join keys never match — but pandas merge matches
        NaN==NaN. Replace null-key entries with side-unique sentinels
        (and move both sides to object dtype so the merge still works).
        Output values are taken from the ORIGINAL arrays by row index,
        so sentinels never leak into results."""
        lname, rname = f"l{li}", f"r{rj}"
        lmask = _null_mask_of(ldf, lname, lc[li], ln[li])
        rmask = _null_mask_of(rdf, rname, rc[rj], rn[rj])
        if not lmask.any() and not rmask.any():
            return
        lobj = ldf[lname].astype(object).copy()
        lobj[lmask] = [f"__Lnull{i}" for i in np.flatnonzero(lmask)]
        ldf[lname] = lobj
        robj = rdf[rname].astype(object).copy()
        robj[rmask] = [f"__Rnull{i}" for i in np.flatnonzero(rmask)]
        rdf[rname] = robj

    equi = []
    residual = None

    def flatten(e):
        nonlocal residual
        if e is None:
            return
        if isinstance(e, ast.BinOp) and e.op == "and":
            flatten(e.left)
            flatten(e.right)
            return
        if isinstance(e, ast.BinOp) and e.op == "=" \
                and isinstance(e.left, ast.Col) and isinstance(e.right, ast.Col):
            li, ri = e.left.index, e.right.index
            if li < nleft <= ri:
                equi.append((li, ri - nleft))
                return
            if ri < nleft <= li:
                equi.append((ri, li - nleft))
                return
        residual = e if residual is None else ast.BinOp("and", residual, e)

    flatten(plan.condition)
    for li, rj in equi:
        _null_proof_pair(li, rj)
    nl_rows, nr_rows = len(ldf), len(rdf)

    # 1) candidate (left,right) ROW-INDEX pairs: equi keys via pandas
    #    inner merge, otherwise the cross product. Values are then taken
    #    from the ORIGINAL arrays by index, so merge dtype mangling and
    #    sentinel restoration never touch the output.
    if equi:
        ldf["__lrow"] = np.arange(nl_rows)
        rdf["__rrow"] = np.arange(nr_rows)
        rmerge = rdf
        if residual is None and plan.how in ("semi", "anti"):
            # only existence matters: dedup the build side so a hot key
            # doesn't materialize the full many-to-many pair table
            rmerge = rdf.drop_duplicates(subset=[f"r{j}" for _, j in equi])
        pairs = ldf.merge(rmerge, left_on=[f"l{i}" for i, _ in equi],
                          right_on=[f"r{j}" for _, j in equi], how="inner")
        lpair = pairs["__lrow"].to_numpy()
        rpair = pairs["__rrow"].to_numpy()
    else:
        lpair = np.repeat(np.arange(nl_rows), nr_rows)
        rpair = np.tile(np.arange(nr_rows), nl_rows)

    # 2) residual ON-condition applied PER PAIR — an outer join's
    #    failing pairs must NULL-extend, not drop (ON-clause semantics)
    if residual is not None and len(lpair):
        mn = len(lpair)
        mcols = [c[lpair] for c in lc] + [c[rpair] for c in rc]
        mnulls = [nm[lpair] if nm is not None else None for nm in ln] + \
                 [nm[rpair] if nm is not None else None for nm in rn]
        v, nl2 = eval_expr(residual, mcols, mnulls, params, mn)
        ok = np.broadcast_to(v, (mn,)).astype(bool)
        if nl2 is not None:
            ok = ok & ~np.broadcast_to(nl2, (mn,))
        lpair, rpair = lpair[ok], rpair[ok]

    # 3) dispatch on join kind
    if plan.how in ("semi", "anti"):
        hit = np.zeros(nl_rows, dtype=bool)
        hit[lpair] = True
        keep = hit if plan.how == "semi" else ~hit
        idx = np.nonzero(keep)[0]
        return ([c[idx] for c in lc],
                [nm[idx] if nm is not None else None for nm in ln],
                lnames, lt, len(idx))
    l_idx, r_idx = lpair, rpair
    if plan.how in ("left", "full"):
        miss = np.setdiff1d(np.arange(nl_rows), lpair)
        l_idx = np.concatenate([l_idx, miss])
        r_idx = np.concatenate([r_idx, np.full(len(miss), -1)])
    if plan.how in ("right", "full"):
        miss = np.setdiff1d(np.arange(nr_rows), rpair)
        l_idx = np.concatenate([l_idx, np.full(len(miss), -1)])
        r_idx = np.concatenate([r_idx, miss])

    def take(arr, nm, idx, dt):
        """arr[idx] with idx == -1 meaning the NULL-extended side."""
        ext = idx < 0
        if len(arr) == 0:
            vals = np.zeros(len(idx), dtype=dt.np_dtype)
        else:
            vals = np.asarray(arr)[np.where(ext, 0, idx)]
        null = ext.copy()
        if nm is not None:
            null |= np.where(ext, True, np.asarray(nm)[np.where(ext, 0,
                                                               idx)])
        if vals.dtype == object:
            vals = vals.copy()
            vals[null] = None
        elif ext.any():
            vals = np.where(ext, np.zeros(1, dtype=vals.dtype), vals)
        return vals, (null if null.any() else None)

    cols, nulls = [], []
    for i, dt in enumerate(lt):
        v, nm2 = take(lc[i], ln[i], l_idx, dt)
        cols.append(v)
        nulls.append(nm2)
    for j, dt in enumerate(rt):
        v, nm2 = take(rc[j], rn[j], r_idx, dt)
        cols.append(v)
        nulls.append(nm2)
    return cols, nulls, lnames + rnames, lt + rt, len(l_idx)


def _eval_aggregate(plan: ast.Aggregate, params, executor):
    import pandas as pd

    cols, nulls, names, dtypes, n = _eval_rel(plan.child, params, executor)

    groups = list(plan.group_exprs)
    gvals = []
    for g in groups:
        v, nl = eval_expr(g, cols, nulls, params, n)
        v = np.broadcast_to(v, (n,))
        out = np.empty(n, dtype=object)
        for i in range(n):
            if nl is not None and np.broadcast_to(nl, (n,))[i]:
                out[i] = None
            else:
                x = v[i]
                # lists are unhashable: group by their tuple form (output
                # converts back)
                out[i] = tuple(x) if isinstance(x, list) else x
        gvals.append(out)

    if groups:
        df = pd.DataFrame({f"g{i}": g for i, g in enumerate(gvals)})
        grouped = df.groupby([f"g{i}" for i in range(len(groups))],
                             sort=True, dropna=False)
        group_indices = [idx.to_numpy() if hasattr(idx, "to_numpy")
                         else np.asarray(idx)
                         for _, idx in grouped.indices.items()]
        group_keys = list(grouped.indices.keys())
        if len(groups) == 1:
            group_keys = [(k,) for k in group_keys]
    else:
        group_indices = [np.arange(n)]
        group_keys = [()]

    out_names, out_cols, out_nulls, out_types = [], [], [], []
    for e in plan.agg_exprs:
        out_names.append(_expr_name(e))
        out_types.append(expr_type(e))
        vals, nmask = [], []
        for key, idx in zip(group_keys, group_indices):
            v = _agg_one(e, key, groups, idx, cols, nulls, params, n)
            if isinstance(v, tuple):  # array group key: back to list form
                v = list(v)
            nmask.append(v is None)
            vals.append(v)
        dt = out_types[-1]
        if dt.name in ("string", "array", "map"):
            arr = np.empty(len(vals), dtype=object)
            for j, v in enumerate(vals):
                arr[j] = v
        else:
            arr = np.array([0 if v is None else v for v in vals],
                           dtype=dt.np_dtype if dt.name != "decimal"
                           else np.float64)
        out_cols.append(arr)
        nm = np.array(nmask)
        out_nulls.append(nm if nm.any() else None)
    return out_cols, out_nulls, out_names, out_types, len(group_indices)


def _agg_one(e: ast.Expr, key, groups, idx, cols, nulls, params, n):
    """Evaluate one select-list expression for one group (host, exact)."""
    import pandas as pd

    if isinstance(e, ast.Alias):
        return _agg_one(e.child, key, groups, idx, cols, nulls, params, n)
    for gi, g in enumerate(groups):
        if e == g:
            v = key[gi]
            # pandas groupby(dropna=False) hands a NULL group key back
            # as NaN/NaT — restore SQL NULL or the key loses its null
            # mask downstream (a NULL-extended string key would render
            # as nan and sort as the string "nan", breaking NULLS FIRST)
            if v is not None and not isinstance(v, (tuple, list)) \
                    and pd.isna(v):
                return None
            return v
    if isinstance(e, ast.Func) and e.name in ast.AGG_FUNCS:
        if e.name == "count" and not e.args:
            return len(idx)
        v, nl = eval_expr(e.args[0], cols, nulls, params, n)
        v = np.broadcast_to(v, (n,))[idx]
        if nl is not None:
            keep = ~np.broadcast_to(nl, (n,))[idx]
            v = v[keep]
        if v.dtype == object:
            v = np.array([x for x in v if x is not None], dtype=object)
        if len(v) == 0:
            return 0 if e.name.startswith("count") else None
        if e.name == "count":
            return len(v)
        if e.name == "count_distinct":
            return len(set(v.tolist()))
        if e.name == "approx_count_distinct":
            return len(set(v.tolist()))
        if e.name == "sum":
            return v.sum()
        if e.name == "avg":
            return v.astype(np.float64).mean() if v.dtype != object else None
        if e.name == "min" or e.name == "first":
            return v.min() if v.dtype != object else min(v.tolist())
        if e.name == "max" or e.name == "last":
            return v.max() if v.dtype != object else max(v.tolist())
        if e.name == "stddev":
            return float(np.std(v.astype(np.float64)))
        if e.name == "variance":
            return float(np.var(v.astype(np.float64)))
        raise HostEvalError(e.name)
    if isinstance(e, ast.Lit):
        return e.value
    if isinstance(e, (ast.ParamLiteral, ast.Param)):
        return params[e.pos]
    if isinstance(e, ast.BinOp):
        a = _agg_one(e.left, key, groups, idx, cols, nulls, params, n)
        b = _agg_one(e.right, key, groups, idx, cols, nulls, params, n)
        if a is None or b is None:
            return None
        return {"+": lambda: a + b, "-": lambda: a - b, "*": lambda: a * b,
                "/": lambda: a / b if b else None,
                "%": lambda: a % b}[e.op]()
    if isinstance(e, ast.Func):
        a = [_agg_one(x, key, groups, idx, cols, nulls, params, n)
             for x in e.args]
        if e.name == "sqrt":
            return float(np.sqrt(a[0])) if a[0] is not None else None
        if e.name == "round":
            return round(a[0], int(a[1]) if len(a) > 1 else 0) \
                if a[0] is not None else None
    if isinstance(e, ast.Cast):
        v = _agg_one(e.child, key, groups, idx, cols, nulls, params, n)
        return T.python_value(e.to, v)
    raise HostEvalError(f"post-agg expression {type(e).__name__}")
