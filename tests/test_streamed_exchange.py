"""Streamed exchanges (round-3 verdict Weak #5 / task 6): table export,
broadcast, and repartition move data one scan unit at a time — no
full-table materialization on the lead or any server (ref:
SparkSQLExecuteImpl.packRows:109, CachedDataFrame.scala:766)."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.cluster import LocatorNode, ServerNode
from snappydata_tpu.cluster.client import SnappyClient
from snappydata_tpu.cluster.distributed import DistributedSession
from snappydata_tpu.cluster.flight_server import iter_table_chunks


def test_iter_table_chunks_bounded_and_complete():
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE big (k BIGINT, name STRING, v DOUBLE) "
          "USING column")
    rng = np.random.default_rng(0)
    total = 0
    data = s.catalog.describe("big").data
    for _ in range(3):                      # 3 batches + a row tail
        n = 40_000
        s.insert_arrays("big", [
            np.arange(total, total + n, dtype=np.int64),
            np.array([f"s{i % 11}" for i in range(n)], dtype=object),
            rng.random(n)])
        total += n
        data.force_rollover()
    s.sql("INSERT INTO big VALUES (999999, 'tail', 0.5)")
    total += 1

    chunks = list(iter_table_chunks(s, "big"))
    assert len(chunks) >= 4                 # one per scan unit, streamed
    assert sum(c.num_rows for c in chunks) == total
    cap = data.capacity
    assert all(c.num_rows <= cap for c in chunks)
    seen = np.concatenate([np.asarray(c.columns[0]) for c in chunks])
    assert len(np.unique(seen)) == total
    # deletes must not leak into the export
    s.sql("DELETE FROM big WHERE k < 100")
    total2 = sum(c.num_rows for c in iter_table_chunks(s, "big"))
    assert total2 == total - 100
    s.stop()


@pytest.fixture(scope="module")
def cluster():
    locator = LocatorNode().start()
    servers = [ServerNode(locator.address, SnappySession(catalog=Catalog()))
               .start() for _ in range(3)]
    ds = DistributedSession(
        server_addresses=[s.flight_address for s in servers])
    yield ds, servers
    ds.close()
    for s in servers:
        s.stop()
    locator.stop()


def test_scan_table_streams_record_batches(cluster):
    ds, servers = cluster
    ds.sql("CREATE TABLE exp_t (k BIGINT, v DOUBLE) USING column "
           "OPTIONS (partition_by 'k')")
    n = 50_000
    ds.insert_arrays("exp_t", [np.arange(n, dtype=np.int64),
                               np.ones(n)])
    got = 0
    for s in servers:
        client = SnappyClient(address=s.flight_address)
        try:
            reader = client.scan_table("exp_t")
            for batch in reader:
                got += batch.num_rows
        finally:
            client.close()
    assert got == n


def test_streamed_broadcast_join_correct(cluster):
    ds, _ = cluster
    # bj_small is partitioned on a NON-join column and tiny → the
    # planner broadcasts it via the streamed export action
    ds.sql("CREATE TABLE bj_big (z BIGINT, y BIGINT) USING column "
           "OPTIONS (partition_by 'z')")
    ds.sql("CREATE TABLE bj_small (k BIGINT, x BIGINT, lbl STRING) "
           "USING column OPTIONS (partition_by 'k')")
    rng = np.random.default_rng(3)
    nb = 20_000
    ds.insert_arrays("bj_big", [rng.integers(0, 5000, nb).astype(np.int64),
                                rng.integers(0, 50, nb).astype(np.int64)])
    ks = np.arange(50, dtype=np.int64)
    ds.insert_arrays("bj_small", [ks, ks, np.array(
        [f"l{int(v)}" for v in ks], dtype=object)])
    r = ds.sql("SELECT count(*), sum(b.y) FROM bj_big b JOIN bj_small s "
               "ON b.y = s.x")
    # every big row joins exactly once (x is unique 0..49)
    big_y = None
    r_single = None
    # oracle from per-server shards
    total = ds.sql("SELECT count(*), sum(y) FROM bj_big").rows()[0]
    assert r.rows()[0][0] == total[0]
    assert r.rows()[0][1] == total[1]


def test_streamed_shuffle_join_correct(cluster):
    ds, _ = cluster
    ds.sql("CREATE TABLE sj_a (pk BIGINT, jk BIGINT, v DOUBLE) "
           "USING column OPTIONS (partition_by 'pk')")
    ds.sql("CREATE TABLE sj_b (pk2 BIGINT, jk2 BIGINT, w DOUBLE) "
           "USING column OPTIONS (partition_by 'pk2')")
    rng = np.random.default_rng(4)
    n = 30_000
    jk = rng.integers(0, 997, n).astype(np.int64)
    ds.insert_arrays("sj_a", [np.arange(n, dtype=np.int64), jk,
                              np.ones(n)])
    m = 20_000
    jk2 = rng.integers(0, 997, m).astype(np.int64)
    ds.insert_arrays("sj_b", [np.arange(m, dtype=np.int64), jk2,
                              np.full(m, 2.0)])
    r = ds.sql("SELECT count(*) FROM sj_a a JOIN sj_b b "
               "ON a.jk = b.jk2")
    # oracle: join cardinality via numpy histogram product
    ca = np.bincount(jk, minlength=997)
    cb = np.bincount(jk2, minlength=997)
    assert r.rows()[0][0] == int((ca.astype(np.int64) * cb).sum())
