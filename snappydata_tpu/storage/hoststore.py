"""Host memory eviction: spill cold column batches to disk as memmaps.

The reference evicts region entries to disk when heap crosses
eviction-heap-percentage (SnappyUnifiedMemoryManager.scala:379-401;
SnappyStorageEvictor). TPU-first shape of the same idea: when a table's
RESIDENT batch bytes exceed `host_store_bytes`, the OLDEST batches'
numeric arrays are rewritten into a spill file and replaced by
`np.memmap` views — semantically identical arrays whose residency the OS
page cache manages, so reload is transparent (a later scan simply pages
the bytes back in). Dictionaries and object-typed arrays stay resident
(small / not memmappable).

Spilling republishes the manifest, which (by design) invalidates the
table's device caches for the spilled version — trading a device
re-upload for host RAM, the same trade the reference makes on eviction.
"""

from __future__ import annotations

import atexit
import dataclasses
import itertools
import os
import shutil
import tempfile
import threading
from snappydata_tpu.utils import locks
import weakref
from typing import Optional, Tuple

import numpy as np

_spill_dir: Optional[str] = None
_spill_ids = itertools.count()  # unique filenames (id() values recycle)
_spill_lock = locks.named_lock("storage.spill")
_spill_bytes = 0                # live spill-file bytes (broker ledger)


def spill_file_bytes() -> int:
    """Total bytes currently held in live spill files — the spill side
    of the resource broker's unified host ledger."""
    with _spill_lock:
        return _spill_bytes


def _dir() -> str:
    global _spill_dir
    if _spill_dir is None:
        _spill_dir = tempfile.mkdtemp(prefix="snappy_hoststore_")
        atexit.register(shutil.rmtree, _spill_dir, ignore_errors=True)
    return _spill_dir


class CriticalMemoryError(MemoryError):
    """Raised when process RSS crosses critical_host_bytes: new writes
    are refused so the member stays alive to serve reads (ref:
    critical-heap-percentage LowMemoryException fail-fast)."""


def process_rss_bytes() -> int:
    """Resident set size of this process (Linux /proc, no psutil)."""
    try:
        with open("/proc/self/statm") as fh:
            return int(fh.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return 0


def check_critical_memory() -> None:
    from snappydata_tpu import config

    crit = config.global_properties().critical_host_bytes
    if crit and process_rss_bytes() > crit:
        raise CriticalMemoryError(
            f"host memory critical: RSS {process_rss_bytes() >> 20}MiB "
            f"exceeds critical_host_bytes ({crit >> 20}MiB); insert "
            f"refused (reads still served — free memory or raise the "
            f"limit)")


def resident_bytes(arr: Optional[np.ndarray]) -> int:
    """SPILLABLE bytes an array keeps in host RAM. memmaps count 0 (the
    page cache owns them); object-dtype arrays count 0 too — they CANNOT
    spill, and counting them would make the budget unreachable (the
    spiller would rewrite the same batches on every insert forever)."""
    if arr is None or isinstance(arr, np.memmap) or arr.dtype == object:
        return 0
    return arr.nbytes


def batch_resident_bytes(batch) -> int:
    total = 0
    for col in batch.columns:
        for a in (col.data, col.runs, col.validity):
            total += resident_bytes(a)
    return total


def spill_batch(batch) -> Tuple[int, object]:
    """Write one batch's numeric arrays to disk; returns (bytes_freed,
    new ColumnBatch with memmap-backed columns). The spill file is
    unlinked when the new batch object is garbage-collected (Linux keeps
    the inode alive for any still-mapped views)."""
    path = os.path.join(_dir(),
                        f"batch_{next(_spill_ids)}_{batch.batch_id}.bin")
    freed = 0
    new_cols = []
    # file must exist and carry all bytes BEFORE memmaps are constructed
    with open(path, "wb") as fh:
        staged = []
        for col in batch.columns:
            offs = {}
            for name in ("data", "runs", "validity"):
                a = getattr(col, name)
                if a is None or isinstance(a, np.memmap) or \
                        a.dtype == object:
                    offs[name] = None
                    continue
                ac = np.ascontiguousarray(a)
                offs[name] = (fh.tell(), ac.dtype, ac.shape)
                fh.write(ac.tobytes())
                freed += ac.nbytes
            staged.append(offs)
        fh.flush()
        # locklint: blocking-under-lock spill runs on the degradation
        # ladder under the table lock BY DESIGN: the manifest swap must
        # be atomic vs mutation, and the write IS the memory relief
        os.fsync(fh.fileno())
    if freed == 0:
        os.unlink(path)
        return 0, batch
    for col, offs in zip(batch.columns, staged):
        repl = {}
        for name, spec in offs.items():
            if spec is not None:
                off, dt, shape = spec
                repl[name] = np.memmap(path, dtype=dt, mode="r",
                                       offset=off, shape=shape)
        new_cols.append(dataclasses.replace(col, **repl) if repl else col)
    new_batch = dataclasses.replace(batch, columns=tuple(new_cols))
    global _spill_bytes
    with _spill_lock:
        _spill_bytes += freed
    weakref.finalize(new_batch, _unlink_quiet, path, freed)
    return freed, new_batch


def _unlink_quiet(path: str, nbytes: int = 0) -> None:
    global _spill_bytes
    if nbytes:
        with _spill_lock:
            _spill_bytes -= nbytes
    try:
        os.unlink(path)
    except OSError:
        pass


def spill_to_budget(data, budget: int) -> int:
    """Spill `data`'s oldest resident batches until the table fits the
    budget. Returns batches spilled."""
    from snappydata_tpu.observability.metrics import global_registry

    spilled = 0
    # locklint: lock=storage.column_table (only column tables spill)
    with data._lock:
        m = data._manifest
        per_view = [batch_resident_bytes(v.batch) for v in m.views]
        total = sum(per_view)
        if total <= budget:
            return 0
        new_views = list(m.views)
        freed_total = 0
        for i, v in enumerate(new_views):  # oldest (lowest index) first
            if total - freed_total <= budget:
                break
            if per_view[i] == 0:
                continue
            freed, nb = spill_batch(v.batch)
            if freed == 0:
                continue
            freed_total += freed
            new_views[i] = dataclasses.replace(v, batch=nb)
            spilled += 1
        if spilled:
            data._publish(tuple(new_views))
    if spilled:
        reg = global_registry()
        reg.inc("host_batches_spilled", spilled)
    return spilled
