"""Background-thread exception lint.

A long-lived loop (heartbeat, WAL flusher, stats poller, auto-rejoin)
that catches broadly and swallows silently turns an infrastructure
failure into a thread that is still "running" but doing nothing — the
locator-heartbeat bug class PR 8 fixed by hand. The rule: inside any
``while`` loop, a handler for ``except:`` / ``except Exception`` /
``except BaseException`` must do at least one of:

- log (a call whose name mentions log/warn/error/exception/debug/info,
  or a ``logging``/``logger``/``log`` receiver),
- bump a counter (``.inc(...)`` / ``record_time``),
- re-raise, or leave the loop (``raise`` / ``return`` / ``break``).

A handler that only sleeps/continues is the finding. Waive with
``# locklint: swallowed-exception <invariant>`` when silence is the
contract (e.g. best-effort cleanup)."""

from __future__ import annotations

import ast
import re
from typing import List

from .common import Finding, dotted, load_sources

_LOGGISH_RE = re.compile(
    r"(log|warn|error|exception|debug|info|print_exc)", re.IGNORECASE)
_BROAD = (None, "Exception", "BaseException")


def _handler_is_broad(h: ast.ExceptHandler) -> bool:
    t = h.type
    if t is None:
        return True
    names = []
    if isinstance(t, (ast.Name, ast.Attribute)):
        names = [dotted(t)]
    elif isinstance(t, ast.Tuple):
        names = [dotted(e) for e in t.elts]
    return any(n and n.split(".")[-1] in ("Exception", "BaseException")
               for n in names)


def _handler_handles(h: ast.ExceptHandler) -> bool:
    for node in ast.walk(h):
        if isinstance(node, (ast.Raise, ast.Return, ast.Break)):
            return True
        if isinstance(node, ast.Call):
            d = dotted(node.func) or ""
            term = d.split(".")[-1]
            if not term and isinstance(node.func, ast.Attribute):
                term = node.func.attr    # reg-returning call: x().inc(...)
            if term in ("inc", "record_time"):
                return True
            if term == "print":
                return True      # REPL/CLI loops surface to the human
            if _LOGGISH_RE.search(term):
                return True
            head = d.split(".")[0]
            if head in ("logging", "logger", "log", "LOG", "_log"):
                return True
    return False


def run(paths: List[str]) -> List[Finding]:
    findings: List[Finding] = []
    for path, src in sorted(load_sources(paths).items()):
        loops = [n for n in ast.walk(src.tree) if isinstance(n, ast.While)]
        for loop in loops:
            for node in ast.walk(loop):
                if not isinstance(node, ast.Try):
                    continue
                for h in node.handlers:
                    if not _handler_is_broad(h):
                        continue
                    if _handler_handles(h):
                        continue
                    line = h.lineno
                    if src.waived(line, "swallowed-exception"):
                        continue
                    findings.append(Finding(
                        "swallowed-exception", path, line,
                        "broad except inside a loop swallows the error "
                        "silently — log it and bump a counter (or break/"
                        "re-raise); a dead background loop must be "
                        "visible"))
    return findings
