"""Pallas compensated-reduction kernel (ops/pallas_reduce.py): accuracy
vs exact f64 oracles at adversarial magnitudes, and the engine's
global-sum integration behind properties.pallas_reduce. On CPU the
kernel runs in interpreter mode — correctness only; the TPU timing
story is recorded by bench.py when hardware is reachable."""

import numpy as np
import pytest

from snappydata_tpu import SnappySession, config
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.ops import masked_kahan_sum


def test_kernel_accuracy_same_sign_large():
    # worst case for plain f32 accumulation: 4M same-sign values of
    # magnitude ~1e4 (plain f32 keeps ~3 digits; Kahan keeps ~8+)
    rng = np.random.default_rng(1)
    v = (rng.random(4_000_000) * 2e4).astype(np.float32)
    m = np.ones(v.shape, dtype=bool)
    got = float(masked_kahan_sum(v, m))
    exact = float(v.astype(np.float64).sum())
    # the s - c combine leaves ~eps-level error; 1e-7 would regress to
    # ~1e-6+ if the compensation sign ever flips back (review finding)
    assert abs(got - exact) / exact <= 1e-7
    plain = float(v.sum(dtype=np.float32))
    assert abs(got - exact) <= abs(plain - exact) / 100


def test_kernel_mask_and_padding():
    rng = np.random.default_rng(2)
    for n in (1, 7, 1024, 1025, 131072, 131073):
        v = (rng.random(n) * 100 - 50).astype(np.float32)
        m = rng.random(n) < 0.5
        got = float(masked_kahan_sum(v, m))
        exact = float(v.astype(np.float64)[m].sum())
        assert got == pytest.approx(exact, rel=1e-6, abs=1e-6), n


def test_engine_global_sum_via_pallas():
    # the gate requires f32 plates (the TPU storage policy) — force it
    # on CPU so the pallas path actually engages
    old = config.global_properties().pallas_reduce
    old_f64 = config.global_properties().decimal_as_float64
    config.global_properties().decimal_as_float64 = False
    try:
        s = SnappySession(catalog=Catalog())
        s.sql("CREATE TABLE pr (v DOUBLE, q DOUBLE) USING column")
        rng = np.random.default_rng(3)
        n = 500_000
        v = np.round(rng.random(n) * 2e4, 2)
        q = rng.integers(1, 50, n).astype(np.float64)
        s.insert_arrays("pr", [v, q])
        baseline = s.sql("SELECT sum(v), avg(v), sum(v * q) FROM pr "
                         "WHERE q < 25").rows()[0]

        config.global_properties().pallas_reduce = True
        s2 = SnappySession(catalog=Catalog())
        s2.sql("CREATE TABLE pr (v DOUBLE, q DOUBLE) USING column")
        s2.insert_arrays("pr", [v, q])
        got = s2.sql("SELECT sum(v), avg(v), sum(v * q) FROM pr "
                     "WHERE q < 25").rows()[0]
        for a, b in zip(got, baseline):
            assert a == pytest.approx(b, rel=2e-6)
        # grouped sums keep the segment path (kernel is global-only)
        g = s2.sql("SELECT q, sum(v) FROM pr WHERE q < 4 GROUP BY q "
                   "ORDER BY q").rows()
        for qv, sv in g:
            exact = v[(q == qv)].sum()
            assert sv == pytest.approx(exact, rel=1e-9)
        s.stop()
        s2.stop()
    finally:
        config.global_properties().pallas_reduce = old
        config.global_properties().decimal_as_float64 = old_f64


def test_cancellation_caveat_documented():
    """The compensated f32 path bounds error vs sum(|v|), NOT |sum(v)| —
    the documented reason the engine keeps it opt-in and f32-scoped.
    This pins the bound (absolute error stays ~eps * sum(|v|))."""
    v = np.array([1.6e7] * 1000 + [-1.6e7] * 1000 + [1.0],
                 dtype=np.float32)
    m = np.ones(v.shape, dtype=bool)
    got = float(masked_kahan_sum(v, m))
    abs_scale = float(np.abs(v.astype(np.float64)).sum())
    assert abs(got - 1.0) <= 1e-7 * abs_scale
