"""Round-4 regression battery for the round-3 advisor findings:

1. (high) _plan_exchanges must never broadcast the PRESERVED side of a
   semi/anti join — each server would semi/anti-join the full outer
   table against only its local shard and the concatenation
   over/under-counts.
2. (high) count(DISTINCT x) only decomposes into summed per-server
   counts when x resolves to THE table hash-partitioned on x; a
   replicated table's column sharing a name with a partition key must
   take the exact (gather) path.
3. (low) murmur3 over numpy 'S' (bytes) arrays hashes UTF-8 content,
   not the "b'...'" repr.
4. (low) x NOT IN (subquery with NULL) keeps three-valued semantics in
   a projected context (NULL, not FALSE, for non-matching rows).
5. (low) correlated count-scalar subqueries coalesce COUNT terms
   individually, so count(*)+sum(v) stays NULL and count(*)+1 stays 1
   for empty groups.
"""

import numpy as np
import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.parallel.hashing import murmur3_hash_np


def test_bytes_and_str_hash_identically():
    sb = murmur3_hash_np(np.array([b"abc", b"", b"snappy"], dtype="S"))
    ss = murmur3_hash_np(np.array(["abc", "", "snappy"], dtype=object))
    assert (sb == ss).all()


class TestCountScalarMixedExpressions:
    """Advisor low #3: the LEFT-join rewrite for correlated count
    subqueries must reconstruct the select expression from per-aggregate
    slots, coalescing only the COUNT terms."""

    @pytest.fixture(scope="class")
    def sess(self):
        s = SnappySession(catalog=Catalog())
        s.sql("CREATE TABLE o_t (id BIGINT, lim BIGINT) USING column")
        s.sql("CREATE TABLE d_t (oid BIGINT, v BIGINT) USING column")
        s.sql("INSERT INTO o_t VALUES (1, 10), (2, 20), (3, 30)")
        s.sql("INSERT INTO d_t VALUES (1, 10), (1, 20)")
        yield s
        s.stop()

    def test_mixed_count_plus_sum_is_null_for_empty_group(self, sess):
        # id=1: 2 + 30 = 32 < 100 → kept. id=2/3: 0 + NULL = NULL → dropped.
        r = sess.sql(
            "SELECT id FROM o_t WHERE (SELECT count(*) + sum(v) FROM d_t "
            "WHERE d_t.oid = o_t.id) < 100 ORDER BY id")
        assert [row[0] for row in r.rows()] == [1]

    def test_count_plus_literal_for_empty_group(self, sess):
        # empty group: count(*)+1 = 1, not coalesce(whole, 0) = 0
        r = sess.sql(
            "SELECT id FROM o_t WHERE (SELECT count(*) + 1 FROM d_t "
            "WHERE d_t.oid = o_t.id) = 1 ORDER BY id")
        assert [row[0] for row in r.rows()] == [2, 3]

    def test_bare_count_zero_still_matches(self, sess):
        r = sess.sql(
            "SELECT id FROM o_t WHERE (SELECT count(*) FROM d_t "
            "WHERE d_t.oid = o_t.id) = 0 ORDER BY id")
        assert [row[0] for row in r.rows()] == [2, 3]

    def test_matched_counts_unchanged(self, sess):
        r = sess.sql(
            "SELECT id FROM o_t WHERE (SELECT count(*) FROM d_t "
            "WHERE d_t.oid = o_t.id) = 2 ORDER BY id")
        assert [row[0] for row in r.rows()] == [1]


@pytest.mark.slow
class TestDistributedAdvisorFindings:
    """Cluster-backed repros for the two high-severity findings plus the
    projected NOT-IN NULL semantics."""

    @pytest.fixture(scope="class")
    def dist(self):
        from snappydata_tpu.cluster import LocatorNode, ServerNode
        from snappydata_tpu.cluster.distributed import DistributedSession

        locator = LocatorNode().start()
        servers = [
            ServerNode(locator.address, SnappySession(catalog=Catalog()))
            .start() for _ in range(3)]
        ds = DistributedSession(
            server_addresses=[s.flight_address for s in servers])
        yield ds
        ds.close()
        for s in servers:
            s.stop()
        locator.stop()

    @pytest.fixture(scope="class")
    def semi_tables(self, dist):
        ds = dist
        # outer_t is SMALL (broadcast-eligible by size) and partitioned on
        # a NON-join column; inner_t is big. The only wrong plan is
        # broadcasting outer_t — the preserved side of the semi/anti join.
        ds.sql("CREATE TABLE outer_t (k BIGINT, x BIGINT) USING column "
               "OPTIONS (partition_by 'k')")
        ds.sql("CREATE TABLE inner_t (z BIGINT, y BIGINT, pad STRING) "
               "USING column OPTIONS (partition_by 'z')")
        rng = np.random.default_rng(7)
        ok = np.arange(20, dtype=np.int64)
        ox = np.arange(20, dtype=np.int64) % 10   # x in 0..9
        ds.insert_arrays("outer_t", [ok, ox])
        n = 6000
        iz = rng.integers(0, 997, n).astype(np.int64)
        iy = rng.integers(0, 5, n).astype(np.int64)  # y covers 0..4 only
        pad = np.array(["p" * 32] * n, dtype=object)
        ds.insert_arrays("inner_t", [iz, iy, pad])
        matched = int(np.isin(ox, np.unique(iy)).sum())
        return ds, matched, len(ok)

    def test_exists_not_broadcast_duplicated(self, semi_tables):
        ds, matched, total = semi_tables
        r = ds.sql("SELECT count(*) FROM outer_t o WHERE EXISTS "
                   "(SELECT 1 FROM inner_t i WHERE i.y = o.x)")
        assert r.rows()[0][0] == matched

    def test_not_exists_not_broadcast_leaked(self, semi_tables):
        ds, matched, total = semi_tables
        r = ds.sql("SELECT count(*) FROM outer_t o WHERE NOT EXISTS "
                   "(SELECT 1 FROM inner_t i WHERE i.y = o.x)")
        assert r.rows()[0][0] == total - matched

    @pytest.fixture(scope="class")
    def distinct_tables(self, dist):
        ds = dist
        ds.sql("CREATE TABLE pa (k BIGINT, x BIGINT) USING column "
               "OPTIONS (partition_by 'k')")
        ds.sql("CREATE TABLE rr (k BIGINT, lbl STRING) USING column")
        n = 900
        k = np.arange(n, dtype=np.int64)
        x = (k % 5).astype(np.int64)              # x covers 0..4
        ds.insert_arrays("pa", [k, x])
        ds.sql("INSERT INTO rr VALUES (0,'a'), (1,'b'), (2,'c'), "
               "(3,'d'), (4,'e'), (99,'z')")
        return ds

    def test_count_distinct_replicated_column_exact(self, distinct_tables):
        ds = distinct_tables
        # r.k shares its NAME with pa's partition key but belongs to the
        # replicated table: per-server distinct counts overlap and must
        # NOT be summed. Correct answer: 5 (99 never joins).
        r = ds.sql("SELECT count(DISTINCT r.k) FROM pa a JOIN rr r "
                   "ON a.x = r.k")
        assert r.rows()[0][0] == 5

    def test_count_distinct_partition_key_still_decomposes(
            self, distinct_tables):
        ds = distinct_tables
        r = ds.sql("SELECT count(DISTINCT a.k) FROM pa a JOIN rr r "
                   "ON a.x = r.k")
        assert r.rows()[0][0] == 900

    def test_count_distinct_same_named_partition_keys(self, dist):
        # k exists in BOTH tables (both hash-partitioned on it, joined
        # on it): the QUALIFIED reference resolves to its table and
        # decomposes; a bare ambiguous reference errors exactly like the
        # single-node analyzer would
        ds = dist
        ds.sql("CREATE TABLE amb_a (k BIGINT, v BIGINT) USING column "
               "OPTIONS (partition_by 'k')")
        ds.sql("CREATE TABLE amb_b (k BIGINT, w BIGINT) USING column "
               "OPTIONS (partition_by 'k', colocate_with 'amb_a')")
        n = 600
        k = np.arange(n, dtype=np.int64) % 97
        ds.insert_arrays("amb_a", [k, k * 2])
        ds.insert_arrays("amb_b", [k, k * 3])
        dedup = len(np.unique(k))
        r = ds.sql("SELECT count(DISTINCT amb_a.k) FROM amb_a "
                   "JOIN amb_b ON amb_a.k = amb_b.k")
        assert r.rows()[0][0] == dedup
        with pytest.raises(Exception, match="ambiguous"):
            ds.sql("SELECT count(DISTINCT k) FROM amb_a JOIN amb_b "
                   "ON amb_a.k = amb_b.k")

    def test_not_in_with_null_projected(self, dist):
        ds = dist
        ds.sql("CREATE TABLE t_main (id BIGINT, x BIGINT) USING column "
               "OPTIONS (partition_by 'id')")
        ds.sql("CREATE TABLE t_set (y BIGINT) USING column")
        ds.sql("INSERT INTO t_main VALUES (1, 10), (2, 20), (3, 30)")
        ds.sql("INSERT INTO t_set VALUES (10), (NULL)")
        r = ds.sql("SELECT id, x NOT IN (SELECT y FROM t_set) AS f "
                   "FROM t_main ORDER BY id")
        got = {row[0]: row[1] for row in r.rows()}
        # x=10 matches → FALSE; 20/30 don't match a set containing NULL
        # → NULL (never TRUE)
        assert got[1] is False or got[1] == 0
        assert got[2] is None and got[3] is None


def test_mutation_params_bind_positionally():
    """Round-4 engine finding: UPDATE/DELETE with multiple '?' markers
    bound every marker to params[-1] (positions were never assigned on
    the mutation path)."""
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE pt (a STRING, b BIGINT, c DOUBLE, "
          "PRIMARY KEY (a, b)) USING row")
    s.sql("INSERT INTO pt VALUES ('x', 1, 0.0), ('x', 2, 0.0), "
          "('y', 3, 0.0)")
    r = s.sql("DELETE FROM pt WHERE a = ? AND b < ?", ["x", 2])
    assert r.rows()[0][0] == 1
    assert s.sql("SELECT count(*) FROM pt").rows()[0][0] == 2
    r2 = s.sql("UPDATE pt SET c = ? WHERE a = ? AND b >= ?",
               [7.5, "x", 2])
    assert r2.rows()[0][0] == 1
    got = {(row[0], row[1]): row[2] for row in
           s.sql("SELECT a, b, c FROM pt").rows()}
    assert got[("x", 2)] == 7.5 and got[("y", 3)] == 0.0
    s.stop()


@pytest.mark.slow
def test_with_error_distributed_estimation():
    """WITH ERROR over a cluster: phase aggregates fan per server (each
    reservoir samples its shard — a stratum of the global population)
    and the lead merges the moments. Bounds must cover the exact answer
    and behaviors must work distributed."""
    from snappydata_tpu.cluster import LocatorNode, ServerNode
    from snappydata_tpu.cluster.distributed import DistributedSession

    locator = LocatorNode().start()
    servers = [ServerNode(locator.address, SnappySession(catalog=Catalog()))
               .start() for _ in range(3)]
    ds = DistributedSession(
        server_addresses=[s.flight_address for s in servers])
    try:
        ds.sql("CREATE TABLE we_t (k BIGINT, g STRING, v DOUBLE) "
               "USING column OPTIONS (partition_by 'k')")
        rng = np.random.default_rng(31)
        n = 60_000
        k = rng.integers(0, 50_000, n).astype(np.int64)
        g = np.array(["a", "b", "c"], dtype=object)[rng.integers(0, 3, n)]
        v = rng.normal(50, 8, n)
        ds.insert_arrays("we_t", [k, g, v])
        ds.sql("CREATE SAMPLE TABLE we_s ON we_t OPTIONS "
               "(baseTable 'we_t', qcs 'g', reservoir_size '250')")

        r = ds.sql("SELECT g, avg(v) AS av, absolute_error(av) AS ae, "
                   "lower_bound(av) AS lb, upper_bound(av) AS ub "
                   "FROM we_t GROUP BY g ORDER BY g "
                   "WITH ERROR 0.5 CONFIDENCE 0.95")
        exact = {row[0]: row[1] for row in
                 ds.sql("SELECT g, avg(v) FROM we_t GROUP BY g").rows()}
        assert len(r.rows()) == 3
        inside = 0
        for gi, av, ae, lb, ub in r.rows():
            assert ae > 0 and lb < av < ub
            if lb <= exact[gi] <= ub:
                inside += 1
        assert inside >= 2   # 95% intervals: 3 misses is implausible

        # count(*) with no filter: stratified HT knows every N_h exactly
        c, cae = ds.sql("SELECT count(*) AS c, absolute_error(c) "
                        "FROM we_t WITH ERROR 0.5").rows()[0]
        assert c == n and cae == pytest.approx(0.0)

        # behavior runs the exact query DISTRIBUTED on violation
        r2 = ds.sql("SELECT g, avg(v) AS av, absolute_error(av) AS ae "
                    "FROM we_t GROUP BY g "
                    "WITH ERROR 0.00001 BEHAVIOR 'run_on_full_table'")
        for gi, av, ae in r2.rows():
            assert av == pytest.approx(exact[gi])
            assert ae == 0.0
    finally:
        ds.close()
        for s in servers:
            s.stop()
        locator.stop()
