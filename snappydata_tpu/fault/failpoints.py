"""Deterministic failpoint fault injection.

The reference tests its HA story with a fault-injection battery (SURVEY.md
§399 "Failure detection / elastic recovery / fault injection": forced
disconnects, oplog corruption, member kills under load). This module is
the process-wide registry those tests need: named fault points threaded
through the storage, cluster, streaming, and device layers, armed at
runtime (tests, REST `POST /faults`), via env (`SNAPPY_TPU_FAULTS`), or
programmatically.

A fault point is a NAME the production code calls `hit()` on; arming a
spec under that name decides what happens at the next hit(s):

actions
  raise       raise an exception (`exc`: io | conn | runtime | timeout)
  latency     sleep `param` seconds, then continue
  torn_write  return the spec to the hook site, which truncates `param`
              bytes mid-record and simulates a crash (storage paths)
  drop        raise FaultConnectionDropped (a ConnectionError — the
              client failover paths treat it exactly like a lost peer)

arming modes (combinable with `phase`: before | after the guarded op)
  count=N     fire at most N times (one-shot: count=1), then lie dormant
  every=N     fire on every Nth eligible hit
  p=0.25      fire probabilistically — the registry RNG is SEEDED
              (constructor / SNAPPY_TPU_FAULT_SEED / reseed()), so a
              chaos schedule replays byte-for-byte

Wired fault points (grep `failpoints.hit` for the live list):
  wal.append (per RECORD, at append time), wal.group_commit (per GROUP,
  at the batched write+fsync drain — torn_write tears the group's tail,
  the mid-group crash shape), checkpoint.write, flight.rpc (client
  side), flight.serve (server side), locator.heartbeat, kafka.fetch,
  device.transfer

Every fired fault bumps `fault_injected` and `fault_injected_<name>` in
the global metrics registry, so a chaos harness can assert its schedule
actually executed.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import threading
from snappydata_tpu.utils import locks
import time
from typing import Dict, List, Optional


class FaultError(IOError):
    """Injected I/O-shaped failure (action `raise` with exc='io', and the
    crash half of `torn_write`)."""


class FaultConnectionDropped(ConnectionError):
    """Injected connection loss (action `drop`): flows through the same
    failover handling as a genuinely dead peer."""


_EXC = {
    "io": FaultError,
    "conn": FaultConnectionDropped,
    "runtime": RuntimeError,
    "timeout": TimeoutError,
}

ACTIONS = ("raise", "latency", "torn_write", "drop")

# canonical points wired into the engine — arming other names is allowed
# (new hook sites don't need a registry edit), these are documentation
KNOWN_POINTS = (
    "wal.append", "wal.group_commit", "checkpoint.write", "flight.rpc",
    "flight.serve", "locator.heartbeat", "kafka.fetch", "device.transfer",
)


@dataclasses.dataclass
class FaultSpec:
    name: str
    action: str
    param: float = 0.0          # latency seconds / torn-write bytes
    exc: str = "io"             # exception family for `raise`
    phase: str = "before"       # before | after the guarded operation
    count: Optional[int] = None  # fire at most N times
    every: Optional[int] = None  # fire on every Nth hit
    p: Optional[float] = None   # fire with probability p (seeded RNG)
    hits: int = 0               # eligible hit() evaluations
    fired: int = 0              # times the action actually ran

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        return {k: v for k, v in d.items() if v is not None}


class FailpointRegistry:
    def __init__(self, seed: Optional[int] = None):
        self._lock = locks.named_rlock("fault.registry")
        self._specs: Dict[str, List[FaultSpec]] = {}
        if seed is None:
            seed = int(os.environ.get("SNAPPY_TPU_FAULT_SEED", "0") or 0)
            if not seed:
                seed = self._config_int("fault_seed")
        self._seed = seed
        self._rng = random.Random(seed)
        env = os.environ.get("SNAPPY_TPU_FAULTS")
        if env:
            self.arm_from_spec(env)
        conf_spec = self._config_str("faults")
        if conf_spec:
            self.arm_from_spec(conf_spec)

    @staticmethod
    def _config_int(key: str) -> int:
        try:
            from snappydata_tpu import config

            return int(config.global_properties().get(key) or 0)
        except Exception:
            return 0

    @staticmethod
    def _config_str(key: str) -> str:
        try:
            from snappydata_tpu import config

            return str(config.global_properties().get(key) or "")
        except Exception:
            return ""

    # -- arming --------------------------------------------------------

    def arm(self, name: str, action: str, param: float = 0.0,
            exc: str = "io", phase: str = "before",
            count: Optional[int] = None, every: Optional[int] = None,
            p: Optional[float] = None) -> FaultSpec:
        if action not in ACTIONS:
            raise ValueError(f"unknown fault action {action!r}; "
                             f"one of {ACTIONS}")
        if exc not in _EXC:
            raise ValueError(f"unknown exc family {exc!r}; "
                             f"one of {tuple(_EXC)}")
        if phase not in ("before", "after"):
            raise ValueError("phase must be 'before' or 'after'")
        if action == "torn_write" and phase == "after":
            # no hook site interprets a torn_write AFTER the guarded op
            # — arming one would count as injected without ever firing,
            # giving a chaos schedule false coverage
            raise ValueError("torn_write only supports phase='before'")
        spec = FaultSpec(name, action, float(param), exc, phase,
                         count, every, p)
        with self._lock:
            self._specs.setdefault(name, []).append(spec)
        return spec

    def arm_from_spec(self, text: str) -> List[FaultSpec]:
        """Arm from a compact string (env/REST):

            name=action[:param][@trigger][!exc][#after][;...]

        trigger: bare int N → count=N (one-shot: @1); eN → every=N;
        pX → probability X. A JSON list of spec objects is also
        accepted: '[{"name": "wal.append", "action": "raise"}]'.
        """
        text = text.strip()
        out: List[FaultSpec] = []
        if text.startswith("[") or text.startswith("{"):
            items = json.loads(text)
            if isinstance(items, dict):
                items = [items]
            for it in items:
                out.append(self.arm(**it))
            return out
        for entry in text.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            name, _, rest = entry.partition("=")
            phase = "before"
            if rest.endswith("#after"):
                phase, rest = "after", rest[:-len("#after")]
            exc = "io"
            if "!" in rest:
                rest, _, exc = rest.partition("!")
            count = every = p = None
            if "@" in rest:
                rest, _, trig = rest.partition("@")
                if trig.startswith("p"):
                    p = float(trig[1:])
                elif trig.startswith("e"):
                    every = int(trig[1:])
                else:
                    count = int(trig)
            action, _, param = rest.partition(":")
            out.append(self.arm(name.strip(), action.strip(),
                                param=float(param) if param else 0.0,
                                exc=exc, phase=phase, count=count,
                                every=every, p=p))
        return out

    def disarm(self, name: str) -> bool:
        with self._lock:
            return self._specs.pop(name, None) is not None

    def clear(self) -> None:
        with self._lock:
            self._specs.clear()

    def reseed(self, seed: int) -> None:
        """Restart the probabilistic-arming RNG — a chaos schedule with
        the same seed and the same hit sequence replays exactly."""
        with self._lock:
            self._seed = seed
            self._rng = random.Random(seed)

    def list(self) -> List[dict]:
        with self._lock:
            return [s.to_dict() for specs in self._specs.values()
                    for s in specs]

    # -- the hook ------------------------------------------------------

    def hit(self, name: str, phase: str = "before") -> Optional[FaultSpec]:
        """Called by production code at a fault point. Fast no-op when
        nothing is armed. Returns the triggering spec for `torn_write`
        (the site interprets `param` = bytes to cut); raises/sleeps for
        the other actions."""
        if not self._specs:          # hot-path guard, no lock
            return None
        triggered: Optional[FaultSpec] = None
        with self._lock:
            for spec in self._specs.get(name, ()):
                if spec.phase != phase:
                    continue
                if spec.count is not None and spec.fired >= spec.count:
                    continue
                spec.hits += 1
                if spec.p is not None:
                    fire = self._rng.random() < spec.p
                elif spec.every is not None:
                    fire = spec.hits % spec.every == 0
                else:
                    fire = True
                if not fire:
                    continue
                spec.fired += 1
                triggered = spec
                break
        if triggered is None:
            return None
        from snappydata_tpu.observability.metrics import global_registry

        reg = global_registry()
        reg.inc("fault_injected")
        reg.inc(f"fault_injected_{name.replace('.', '_')}")
        if triggered.action == "latency":
            time.sleep(triggered.param)
            return None
        if triggered.action == "drop":
            raise FaultConnectionDropped(
                f"failpoint {name}: injected connection drop")
        if triggered.action == "raise":
            raise _EXC[triggered.exc](
                f"failpoint {name}: injected failure")
        return triggered             # torn_write: site applies it


_global = FailpointRegistry()


def registry() -> FailpointRegistry:
    return _global


def hit(name: str, phase: str = "before") -> Optional[FaultSpec]:
    return _global.hit(name, phase)


def arm(name: str, action: str, **kw) -> FaultSpec:
    return _global.arm(name, action, **kw)


def disarm(name: str) -> bool:
    return _global.disarm(name)


def clear() -> None:
    _global.clear()


def reseed(seed: int) -> None:
    _global.reseed(seed)
