"""Streamed scan-shaped query results through the Flight `sql` ticket
(round-4 verdict Weak #7 / task 5): project/filter queries over a
column table stream per scan unit — peak host rows bounded by one
column batch — with LIMIT early-exit, while aggregates/sorts keep the
materialized path. Ref: CachedDataFrame.executeTake:766,
SparkSQLExecuteImpl.packRows:109."""

import threading

import numpy as np
import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.cluster.client import SnappyClient
from snappydata_tpu.cluster.flight_server import (SnappyFlightServer,
                                                  try_stream_scan)
from snappydata_tpu.observability.metrics import global_registry


@pytest.fixture()
def served():
    s = SnappySession(catalog=Catalog())
    # small batches -> many scan units, so streaming is observable
    s.sql("CREATE TABLE big (k BIGINT, tag STRING, v DOUBLE) "
          "USING column OPTIONS (column_batch_rows '1000', column_max_delta_rows '1000')")
    n = 12_000
    rng = np.random.default_rng(9)
    s.insert_arrays("big", [
        np.arange(n, dtype=np.int64),
        np.array(["t%d" % (i % 3) for i in range(n)], dtype=object),
        np.round(rng.random(n) * 100, 3)])
    srv = SnappyFlightServer(s)
    threading.Thread(target=srv.serve, daemon=True).start()
    srv.wait_ready()
    client = SnappyClient(address=f"127.0.0.1:{srv.actual_port}")
    yield s, client, n
    client.close()
    srv.shutdown()
    s.stop()


def _metric(name):
    return global_registry().counter(name)


def test_select_star_streams_per_scan_unit(served):
    s, client, n = served
    before = _metric("stream_scan_chunks")
    t = client.sql("SELECT k, tag, v FROM big")
    assert t.num_rows == n
    assert sorted(t.column("k").to_pylist()) == list(range(n))
    chunks = _metric("stream_scan_chunks") - before
    # 12k rows / 1k-row batches: the server must have produced MANY
    # bounded chunks, never one materialized result
    assert chunks >= 10, chunks


def test_filter_and_projection_stream(served):
    s, client, n = served
    before = _metric("stream_scan_chunks")
    t = client.sql("SELECT k, v * 2 AS v2 FROM big "
                   "WHERE tag = 't1' AND k < 6000")
    exact = [k for k in range(6000) if k % 3 == 1]
    assert sorted(t.column("k").to_pylist()) == exact
    local = {r[0]: r[1] for r in s.sql(
        "SELECT k, v * 2 FROM big WHERE tag = 't1' AND k < 6000").rows()}
    got = dict(zip(t.column("k").to_pylist(),
                   t.column("v2").to_pylist()))
    for k in exact[:50]:
        assert got[k] == pytest.approx(local[k])
    assert _metric("stream_scan_chunks") > before


def test_limit_early_exit(served):
    s, client, n = served
    before_chunks = _metric("stream_scan_chunks")
    before_stops = _metric("stream_scan_early_stops")
    t = client.sql("SELECT k FROM big LIMIT 500")
    assert t.num_rows == 500
    assert _metric("stream_scan_early_stops") == before_stops + 1
    # one batch satisfies the limit: remaining units never decoded
    assert _metric("stream_scan_chunks") - before_chunks <= 2


def test_question_mark_params_bind_positionally(served):
    """'?' placeholders must get positions before streamed eval —
    unassigned Param(pos=-1) read params[-1] for EVERY placeholder
    (review finding; the round-4 UPDATE/DELETE bug class)."""
    s, client, n = served
    t = client.sql("SELECT k FROM big WHERE k >= ? AND k < ?",
                   params=[100, 103])
    assert sorted(t.column("k").to_pylist()) == [100, 101, 102]


def test_aggregates_and_sorts_keep_materialized_path(served):
    s, client, n = served
    assert try_stream_scan(s, "SELECT count(*) FROM big") is None
    assert try_stream_scan(s, "SELECT k FROM big ORDER BY k") is None
    assert try_stream_scan(s, "SELECT DISTINCT tag FROM big") is None
    assert try_stream_scan(
        s, "SELECT b1.k FROM big b1 JOIN big b2 ON b1.k = b2.k") is None
    # and the materialized path still answers them correctly
    t = client.sql("SELECT tag, count(*) AS c FROM big GROUP BY tag "
                   "ORDER BY tag")
    assert t.column("c").to_pylist() == [4000, 4000, 4000]


def test_stream_respects_row_level_policy(served):
    """Policy predicates inject during analyze_plan — the streamed path
    must enforce them exactly like the materialized path."""
    s, client, n = served
    s.sql("CREATE POLICY p_big ON big USING k < 100")
    try:
        t = client.sql("SELECT k FROM big")
        assert t.num_rows == 100  # policy filtered, streamed or not
    finally:
        s.sql("DROP POLICY p_big")
    assert client.sql("SELECT k FROM big").num_rows == n
