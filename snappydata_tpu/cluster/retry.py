"""Retry policies for the cluster plane: exponential backoff with
(seeded) jitter, and a per-peer circuit breaker.

The reference's driver retries failover with bounded attempts against the
locator's member view (jdbc failover, cluster/README-thrift.md:20-35);
its membership layer stops hammering a departed peer until the view says
it rejoined. Here the same two ideas as explicit, testable objects:

- ExponentialBackoff: delay(attempt) grows base * multiplier^attempt up
  to a cap, scaled down by up to `jitter` fraction with a SEEDED rng so
  chaos schedules replay deterministically (thundering-herd avoidance
  without losing reproducibility).
- CircuitBreaker: after `failure_threshold` consecutive failures the
  breaker OPENs and allow() answers False (callers skip the peer
  instead of eating a connect timeout); after `reset_timeout_s` it
  half-opens, letting exactly one probe through — success re-closes it,
  failure re-opens it. Transitions to open bump `breaker_open`.
"""

from __future__ import annotations

import random
import threading
from snappydata_tpu.utils import locks
import time
from typing import Optional


class ExponentialBackoff:
    def __init__(self, base_s: float = 0.05, max_s: float = 2.0,
                 multiplier: float = 2.0, jitter: float = 0.5,
                 rng: Optional[random.Random] = None):
        self.base_s = base_s
        self.max_s = max_s
        self.multiplier = multiplier
        self.jitter = min(max(jitter, 0.0), 1.0)
        self._rng = rng or random.Random(0)
        self._lock = locks.named_lock("retry.backoff_rng")

    def delay(self, attempt: int) -> float:
        """Delay before retry number `attempt` (0-based), jittered
        downward so concurrent retriers de-synchronize."""
        d = min(self.max_s, self.base_s * (self.multiplier ** attempt))
        with self._lock:   # Random() is not thread-safe for our replay
            scale = 1.0 - self.jitter * self._rng.random()
        return d * scale

    def sleep(self, attempt: int, metric: Optional[str] = None) -> float:
        d = self.delay(attempt)
        if metric is not None:
            from snappydata_tpu.observability.metrics import global_registry

            # locklint: metric-dynamic callers pass a declared timer
            # name ("failover_backoff"); the .time()-site lint covers
            # literals, this pass-through keeps the API generic
            global_registry().record_time(metric, d)
        time.sleep(d)
        return d


class CircuitBreaker:
    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, failure_threshold: int = 3,
                 reset_timeout_s: float = 5.0, clock=time.monotonic):
        self.failure_threshold = max(1, failure_threshold)
        self.reset_timeout_s = reset_timeout_s
        self._clock = clock
        self._lock = locks.named_lock("retry.breaker")
        self._state = self.CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._half_open_at = 0.0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May the caller attempt the peer right now? OPEN answers False
        until the reset timeout elapses, then exactly one caller gets a
        half-open probe slot."""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                if self._clock() - self._opened_at >= self.reset_timeout_s:
                    self._state = self.HALF_OPEN
                    self._half_open_at = self._clock()
                    return True
                return False
            # HALF_OPEN: one probe is in flight — hold others off. But a
            # probe whose caller never recorded an outcome (an exception
            # path that re-raises, a crashed thread) must not wedge the
            # breaker shut forever: grant a fresh probe slot once the
            # outstanding one has aged past the reset timeout.
            if self._clock() - self._half_open_at >= self.reset_timeout_s:
                self._half_open_at = self._clock()
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._state = self.CLOSED
            self._failures = 0

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            trip = self._state == self.HALF_OPEN or \
                self._failures >= self.failure_threshold
            if trip and self._state != self.OPEN:
                self._state = self.OPEN
                self._opened_at = self._clock()
                opened = True
            elif trip:
                self._opened_at = self._clock()
                opened = False
            else:
                opened = False
        if opened:
            from snappydata_tpu.observability.metrics import global_registry

            global_registry().inc("breaker_open")
