"""AQP tests: stratified samples with unbiased estimates, approx rewrite,
CMS/TopK sketches (ref analogue: the aqp module's sample/TopK surface via
SnappyContextFunctions; docs/aqp.md scope)."""

import numpy as np
import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.aqp import CountMinSketch, TopKSummary, StratifiedReservoir


@pytest.fixture()
def s():
    sess = SnappySession(catalog=Catalog())
    yield sess
    sess.stop()


def _load_base(s, n=20000, seed=4):
    s.sql("CREATE TABLE tx (region STRING, amount DOUBLE) USING column")
    rng = np.random.default_rng(seed)
    regions = np.array(["east", "west", "north", "rare"], dtype=object)
    probs = np.array([0.5, 0.3, 0.198, 0.002])
    reg = regions[rng.choice(4, n, p=probs)]
    amt = np.round(rng.random(n) * 100, 2)
    s.insert_arrays("tx", [reg, amt])
    return reg, amt


def test_stratified_reservoir_keeps_rare_strata():
    r = StratifiedReservoir([0], 2, reservoir_size=20)
    rng = np.random.default_rng(0)
    keys = np.array(["common"] * 9990 + ["rare"] * 10, dtype=object)
    vals = rng.random(10000)
    r.observe([keys, vals])
    stats = r.stats()
    assert stats[("rare",)][0] == 10      # all rare rows kept
    assert stats[("common",)] == (20, 9990)


def test_create_sample_table_and_weighted_estimates(s):
    reg, amt = _load_base(s)
    s.sql("CREATE SAMPLE TABLE tx_sample ON tx OPTIONS "
          "(qcs 'region', reservoir_size '200')")
    exact = s.sql("SELECT count(*), sum(amount) FROM tx").rows()[0]
    approx = s.approx_sql("SELECT count(*), sum(amount) FROM tx").rows()[0]
    assert approx[0] == pytest.approx(exact[0], rel=0.05)
    assert approx[1] == pytest.approx(exact[1], rel=0.1)
    # rare stratum survives in the grouped estimate
    grouped = dict((r[0], r[1]) for r in s.approx_sql(
        "SELECT region, count(*) FROM tx GROUP BY region").rows())
    exact_g = dict((r[0], r[1]) for r in s.sql(
        "SELECT region, count(*) FROM tx GROUP BY region").rows())
    assert set(grouped) == set(exact_g)
    assert grouped["rare"] == exact_g["rare"]  # fully-kept stratum is exact


def test_sample_table_direct_query_and_avg_rewrite(s):
    _load_base(s)
    s.sql("CREATE SAMPLE TABLE tx_sample ON tx OPTIONS "
          "(qcs 'region', reservoir_size '100')")
    direct = s.sql("SELECT count(*) FROM tx_sample").rows()[0][0]
    assert 0 < direct <= 500
    exact_avg = s.sql("SELECT avg(amount) FROM tx").rows()[0][0]
    approx_avg = s.approx_sql("SELECT avg(amount) FROM tx").rows()[0][0]
    assert approx_avg == pytest.approx(exact_avg, rel=0.15)


def test_sample_follows_new_inserts(s):
    _load_base(s, n=5000)
    s.sql("CREATE SAMPLE TABLE tx_sample ON tx OPTIONS (qcs 'region')")
    before = s.approx_sql("SELECT count(*) FROM tx").rows()[0][0]
    s.insert_arrays("tx", [np.array(["south"] * 5000, dtype=object),
                           np.ones(5000)])
    after = s.approx_sql("SELECT count(*) FROM tx").rows()[0][0]
    assert after == pytest.approx(10000, rel=0.05)
    assert after > before


def test_count_min_sketch():
    cms = CountMinSketch(depth=5, width=4096)
    rng = np.random.default_rng(1)
    keys = rng.zipf(1.5, 50000).astype(np.int64)
    keys = keys[keys < 1000]
    cms.add(keys)
    from collections import Counter

    truth = Counter(keys.tolist())
    for k in list(truth)[:50]:
        est = int(cms.estimate(np.array([k], dtype=np.int64))[0])
        assert est >= truth[k]                  # never undercounts
        assert est <= truth[k] + 0.02 * cms.total
    merged = cms.merge(cms)
    k0 = list(truth)[0]
    assert int(merged.estimate(np.array([k0], dtype=np.int64))[0]) >= \
        2 * truth[k0]


def test_topk_summary_and_session_api(s):
    s.sql("CREATE TABLE clicks (page STRING, n INT) USING column")
    rng = np.random.default_rng(2)
    pages = np.array([f"page{i}" for i in range(100)], dtype=object)
    weights = 1.0 / np.arange(1, 101)
    weights /= weights.sum()
    data = pages[rng.choice(100, 30000, p=weights)]
    s.create_topk("hot_pages", "clicks", "page", k=10)
    s.insert_arrays("clicks", [data, np.ones(len(data), dtype=np.int32)])
    top = s.query_topk("hot_pages", 5).rows()
    assert len(top) == 5
    from collections import Counter

    truth = [k for k, _ in Counter(data.tolist()).most_common(5)]
    got = [r[0] for r in top]
    assert set(got[:3]) <= set(truth[:6])  # heavy hitters found
