"""locklint: static lock-order analysis + metrics/exception hygiene.

Run ``python -m tools.locklint snappydata_tpu/`` — exits nonzero on any
unwaived finding. See LOCK_ORDER.md for the declared hierarchy and
README "Concurrency invariants & static analysis" for how to read a
report and extend the manifest."""

from .common import Finding                      # noqa: F401
from .manifest import Manifest, load as load_manifest  # noqa: F401
