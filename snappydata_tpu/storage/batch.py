"""ColumnBatch — the immutable unit of columnar storage.

Equivalent of the reference's column batch (key=(batchId, bucketId,
columnIndex) region entries, encoders/.../impl/ColumnFormatEntry.scala:61-97
with meta columns statsRow=-1, deltaStatsRow=-2, deleteMask=-3). Here a
batch is a single host object holding every encoded column plus the stats
row; deltas and delete masks are NOT stored inside it — they live in the
manifest's BatchView so that snapshots are immutable (MVCC, see
table_store.py).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from snappydata_tpu import types as T
from snappydata_tpu.storage.encoding import ColumnStats, EncodedColumn, encode_column


@dataclasses.dataclass(frozen=True)
class ColumnBatch:
    batch_id: int
    bucket_id: int
    num_rows: int
    capacity: int
    columns: tuple  # Tuple[EncodedColumn], one per schema field

    @property
    def stats(self) -> List[Optional[ColumnStats]]:
        return [c.stats for c in self.columns]

    @property
    def nbytes(self) -> int:
        return sum(c.nbytes for c in self.columns)

    @staticmethod
    def from_arrays(batch_id: int, bucket_id: int, schema: T.Schema,
                    arrays: List[np.ndarray], capacity: int,
                    validities: Optional[List[Optional[np.ndarray]]] = None,
                    dictionaries: Optional[dict] = None,
                    precoded: Optional[dict] = None) -> "ColumnBatch":
        """Encode one batch from per-column host arrays (ref
        ColumnInsertExec's per-column encoder loop, ColumnInsertExec.scala:92).

        `dictionaries` maps column index → shared table-level dictionary for
        string columns (codes comparable across batches); `precoded` maps
        column index → ready EncodedColumn (fused native encode path)."""
        n = int(arrays[0].shape[0])
        assert n <= capacity, (n, capacity)
        cols = []
        for i, (f, arr) in enumerate(zip(schema.fields, arrays)):
            if precoded and i in precoded:
                cols.append(precoded[i])
                continue
            validity = validities[i] if validities else None
            hint = dictionaries.get(i) if dictionaries else None
            cols.append(encode_column(np.asarray(arr), f.dtype, validity,
                                      dictionary_hint=hint))
        return ColumnBatch(batch_id, bucket_id, n, capacity, tuple(cols))
