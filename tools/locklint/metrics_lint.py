"""Metrics-hygiene lint.

Every counter/timer/gauge name used anywhere in the tree must resolve
to a name declared in ``snappydata_tpu/observability/metric_names.py``
(parsed as literals — this lint never imports the package), and no two
declared-or-used names may collide after Prometheus sanitization (the
PR 10 ``_prom_name`` collision class: ``a.b`` vs ``a_b`` silently
merged before the crc-suffix fix; the lint keeps new collisions from
entering the tree at all).

Dynamic names (f-strings / ``"prefix_" + x``) are legal when their
literal prefix is declared in ``DYNAMIC_PREFIXES``; a fully-opaque
variable name needs a ``# locklint: metric=<prefix>`` hint or a
``metric-dynamic`` waiver."""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .common import Finding, SourceFile, load_sources, str_const

_KIND_OF = {"inc": "counter", "time": "timer", "record_time": "timer",
            "gauge": "gauge"}
_METRIC_HINT_RE = re.compile(r"#\s*locklint:\s*metric=([A-Za-z0-9_.\-]+)")


def _sanitize(name: str) -> str:
    return "".join(c if c.isalnum() else "_" for c in name)


def load_declared(decl_path: str) -> Dict[str, Set[str]]:
    """Parse metric_names.py WITHOUT importing it: COUNTERS / TIMERS /
    GAUGES / DYNAMIC_PREFIXES must be literal set/list of strings."""
    with open(decl_path, "r", encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=decl_path)
    out: Dict[str, Set[str]] = {"counter": set(), "timer": set(),
                                "gauge": set(), "prefix": set()}
    keymap = {"COUNTERS": "counter", "TIMERS": "timer", "GAUGES": "gauge",
              "DYNAMIC_PREFIXES": "prefix"}
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1 \
                or not isinstance(node.targets[0], ast.Name):
            continue
        key = keymap.get(node.targets[0].id)
        if key is None:
            continue
        if not isinstance(node.value, (ast.Set, ast.List, ast.Tuple)):
            raise ValueError("%s: %s must be a literal set/list"
                             % (decl_path, node.targets[0].id))
        for el in node.value.elts:
            s = str_const(el)
            if s is None:
                raise ValueError("%s: non-literal element in %s"
                                 % (decl_path, node.targets[0].id))
            out[key].add(s)
    return out


def _name_arg(node: ast.AST) -> Tuple[Optional[str], Optional[str]]:
    """(literal_name, dynamic_prefix) for a metric-name argument."""
    s = str_const(node)
    if s is not None:
        return s, None
    if isinstance(node, ast.JoinedStr) and node.values:
        first = node.values[0]
        p = str_const(first)
        if p:
            return None, p
        return None, ""
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        p = str_const(node.left)
        if p is not None:
            return None, p
        # nested concat: leftmost literal
        inner = _name_arg(node.left)
        if inner[0] is not None:
            return None, inner[0]
        if inner[1] is not None:
            return None, inner[1]
        return None, ""
    return None, None


def _is_metric_call(call: ast.Call) -> Optional[str]:
    fn = call.func
    if not isinstance(fn, ast.Attribute):
        return None
    kind = _KIND_OF.get(fn.attr)
    if kind is None:
        return None
    if not call.args:
        return None            # time.time() etc.
    if kind == "gauge" and len(call.args) < 2 and not call.keywords:
        return None
    # require a string-shaped first arg: literal, f-string, concat, or a
    # plain variable (the dynamic case)
    a0 = call.args[0]
    if isinstance(a0, (ast.Constant,)) and not isinstance(
            getattr(a0, "value", None), str):
        return None            # .time(2.0) is not a metric call
    return kind


def run(paths: List[str], decl_path: str) -> List[Finding]:
    declared = load_declared(decl_path)
    findings: List[Finding] = []
    used: Dict[str, Tuple[str, str, int]] = {}   # sanitized -> (raw, f, l)

    def check_collision(raw: str, src_path: str, line: int):
        s = _sanitize(raw)
        prev = used.get(s)
        if prev is None:
            used[s] = (raw, src_path, line)
        elif prev[0] != raw:
            findings.append(Finding(
                "metric-collision", src_path, line,
                "metric %r sanitizes to %r which %r (declared/used at "
                "%s:%d) already occupies — rename one; the runtime "
                "crc-suffix keeps exposition valid but splits the series"
                % (raw, s, prev[0], prev[1], prev[2])))

    decl_file = os.path.relpath(decl_path)
    for kind in ("counter", "timer", "gauge"):
        for name in sorted(declared[kind]):
            check_collision(name, decl_file, 1)

    sources = load_sources(paths)
    for path, src in sorted(sources.items()):
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _is_metric_call(node)
            if kind is None:
                continue
            line = node.lineno
            literal, prefix = _name_arg(node.args[0])
            if literal is not None:
                if literal not in declared[kind]:
                    # names are frequently shared across kinds (a
                    # counter mirrored by a gauge); accept any kind
                    # before failing
                    if not any(literal in declared[k]
                               for k in ("counter", "timer", "gauge")):
                        if not src.waived(line, "metric-undeclared"):
                            findings.append(Finding(
                                "metric-undeclared", path, line,
                                "%s %r is not declared in "
                                "observability/metric_names.py — add it "
                                "(and grep for near-miss spellings first)"
                                % (kind, literal)))
                check_collision(literal, path, line)
            elif prefix:
                # the site's literal chunk must extend a declared family
                # prefix (never the reverse — "f" + x matching declared
                # "fault_injected_" would void the bounded-family gate)
                if not any(prefix.startswith(p)
                           for p in declared["prefix"]):
                    if not src.waived(line, "metric-dynamic"):
                        findings.append(Finding(
                            "metric-dynamic", path, line,
                            "dynamic %s name with undeclared prefix %r — "
                            "add it to DYNAMIC_PREFIXES" % (kind, prefix)))
            else:
                hint = None
                for ln in (line, line - 1):
                    if 1 <= ln <= len(src.lines):
                        m = _METRIC_HINT_RE.search(src.lines[ln - 1])
                        if m:
                            hint = m.group(1)
                            break
                if hint is not None:
                    if hint not in declared["prefix"] and not any(
                            hint in declared[k]
                            for k in ("counter", "timer", "gauge")):
                        findings.append(Finding(
                            "metric-dynamic", path, line,
                            "metric hint %r is neither a declared name "
                            "nor a declared prefix" % hint))
                elif not src.waived(line, "metric-dynamic"):
                    findings.append(Finding(
                        "metric-dynamic", path, line,
                        "%s name is an opaque expression — add a "
                        "`# locklint: metric=<name-or-prefix>` hint "
                        "naming what flows here" % kind))
    return findings


def collect_used(paths: List[str]) -> Dict[str, Set[str]]:
    """All literal metric names in the tree, by kind — the generator the
    initial metric_names.py was seeded from (kept for re-syncing)."""
    out: Dict[str, Set[str]] = {"counter": set(), "timer": set(),
                                "gauge": set()}
    for path, src in sorted(load_sources(paths).items()):
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                kind = _is_metric_call(node)
                if kind:
                    literal, _ = _name_arg(node.args[0])
                    if literal is not None:
                        out[kind].add(literal)
    return out
