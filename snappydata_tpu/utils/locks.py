"""Named locks + an opt-in runtime lockdep witness.

Every lock in the engine is created through ``named_lock`` /
``named_rlock`` / ``named_condition`` so that (a) the static analyzer in
``tools/locklint`` can resolve each acquisition site to a stable,
human-reviewed name, and (b) an opt-in runtime witness
(``SNAPPY_TPU_LOCKDEP=1``, or ``enable()`` before the locks are built)
can track each thread's held-lock stack, accumulate the observed
acquisition-order graph across a whole test run, and fail FAST — with
both acquisition stacks — the moment an acquisition would close a
cycle, instead of letting two threads deadlock silently.

Names are lock CLASSES, not instances (lockdep's hash classes): every
per-table ``storage.column_table`` lock shares one name. Acquiring two
instances of the same class while one is held does not record an edge —
an instance-level order inside one class is the class's own documented
business (see LOCK_ORDER.md "self nesting").

When the witness is disabled (the default), the constructors return the
plain ``threading`` primitives — zero wrapper overhead on hot paths
(the metrics registry lock is taken per counter increment). Enablement
is therefore decided at LOCK CREATION time: set the env var, or call
``enable()`` before the process builds its sessions/stores (the test
conftest does this at import).
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple


class LockdepViolation(RuntimeError):
    """An acquisition would close a cycle in the observed lock-order
    graph (potential ABBA deadlock). Raised in the acquiring thread
    BEFORE it blocks on the lock, and recorded on the global state so a
    session-end check catches it even if the thread swallowed it."""


class _State:
    """Process-wide witness state. Its own lock (`_g`) is internal
    plumbing and deliberately NOT part of the witnessed graph — it is a
    leaf acquired only inside the witness itself, never while calling
    out."""

    def __init__(self) -> None:
        self.enabled = False
        # locklint: unnamed-lock witness-internal: the graph lock cannot
        # itself be witnessed (infinite regress); it is a leaf held only
        # inside this module, never while calling out
        self._g = threading.Lock()
        # (held_name, acquired_name) -> (held_stack, acquire_stack)
        # captured at FIRST observation — the evidence pair a cycle
        # report prints for the reverse direction.
        self.edges: Dict[Tuple[str, str], Tuple[str, str]] = {}
        self.adj: Dict[str, Set[str]] = {}
        self.violations: List[str] = []
        self.names_seen: Set[str] = set()

    def reset(self) -> None:
        with self._g:
            self.edges.clear()
            self.adj.clear()
            self.violations.clear()
            self.names_seen.clear()


_state = _State()
_tls = threading.local()


def _held_stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def enabled() -> bool:
    return _state.enabled


def enable() -> None:
    """Turn the witness on for locks created AFTER this call."""
    _state.enabled = True


def disable() -> None:
    _state.enabled = False


def reset() -> None:
    """Drop the accumulated graph + violations (test isolation)."""
    _state.reset()


def snapshot_state():
    """Copy of the witness state, for save/restore around tests that
    deliberately create violations — a global reset() would also wipe
    the real edges/violations a lockdep-enabled SESSION accumulated,
    blinding the conftest end-of-run check."""
    with _state._g:
        return (dict(_state.edges),
                {k: set(v) for k, v in _state.adj.items()},
                list(_state.violations),
                set(_state.names_seen))


def restore_state(snap) -> None:
    edges, adj, violations, names = snap
    with _state._g:
        _state.edges = dict(edges)
        _state.adj = {k: set(v) for k, v in adj.items()}
        _state.violations = list(violations)
        _state.names_seen = set(names)


def violations() -> List[str]:
    with _state._g:
        return list(_state.violations)


def observed_edges() -> Set[Tuple[str, str]]:
    with _state._g:
        return set(_state.edges.keys())


def observed_names() -> Set[str]:
    with _state._g:
        return set(_state.names_seen)


def _fmt_stack(skip: int = 3, limit: int = 14) -> str:
    frames = traceback.extract_stack()[:-skip]
    return "".join(traceback.format_list(frames[-limit:]))


def _path_exists(src: str, dst: str) -> Optional[List[str]]:
    """DFS over the observed graph; returns a src→dst name path or None.
    Caller holds _state._g."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        for nxt in _state.adj.get(node, ()):
            if nxt == dst:
                return path + [dst]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _before_acquire(lock: "_DepLockBase") -> None:
    held = _held_stack()
    for ent in held:
        if ent[0] is lock:
            if not lock.reentrant:
                # same-thread re-acquire of a plain Lock: guaranteed
                # self-deadlock (the PR 10 gauge shape) — report it
                # instead of hanging
                stack = _fmt_stack()
                msg = (
                    "lockdep: thread re-acquires non-reentrant lock '%s' "
                    "it already holds — guaranteed self-deadlock\n%s"
                    % (lock.name, stack))
                with _state._g:
                    _state.violations.append(msg)
                raise LockdepViolation(msg)
            ent[2] += 1             # reentrant re-acquire (RLock)
            return
    name = lock.name
    acquire_stack = None
    with _state._g:
        _state.names_seen.add(name)
        for obj, held_name, _n in held:
            if held_name == name:
                continue            # same lock class: self-nesting
            key = (held_name, name)
            if key in _state.edges:
                continue
            cyc = _path_exists(name, held_name)
            if cyc is not None:
                if acquire_stack is None:
                    acquire_stack = _fmt_stack()
                # evidence for the reverse direction: the first edge on
                # the name→…→held_name path, with the stacks captured
                # when it was first observed
                rev = (cyc[0], cyc[1])
                rheld, racq = _state.edges.get(rev, ("<unknown>", "<unknown>"))
                msg = (
                    "lockdep: acquiring '%s' while holding '%s' closes the "
                    "cycle %s\n--- this thread (holding '%s', acquiring "
                    "'%s'):\n%s--- reverse edge '%s' -> '%s' first observed "
                    "while holding:\n%s--- acquiring:\n%s"
                    % (name, held_name, " -> ".join(cyc + [name]), held_name,
                       name, acquire_stack, rev[0], rev[1], rheld, racq)
                )
                _state.violations.append(msg)
                raise LockdepViolation(msg)
            if acquire_stack is None:
                acquire_stack = _fmt_stack()
            held_stack = "".join(
                "  held: %s\n" % h for _o, h, _c in held)
            _state.edges[key] = (held_stack, acquire_stack)
            _state.adj.setdefault(held_name, set()).add(name)
    held.append([lock, name, 1])


def _after_acquire_failed(lock: "_DepLockBase") -> None:
    held = _held_stack()
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] is lock:
            held[i][2] -= 1
            if held[i][2] == 0:
                del held[i]
            return


def _after_release(lock: "_DepLockBase") -> None:
    held = _held_stack()
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] is lock:
            held[i][2] -= 1
            if held[i][2] == 0:
                del held[i]
            return


class _DepLockBase:
    __slots__ = ("_lock", "name", "reentrant")

    def __init__(self, name: str, lock, reentrant: bool = False) -> None:
        self._lock = lock
        self.name = name
        self.reentrant = reentrant

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        _before_acquire(self)
        # locklint: unresolved-acquisition witness-internal: self._lock
        # is the wrapped primitive itself — its name is self.name
        ok = self._lock.acquire(blocking, timeout)
        if not ok:
            _after_acquire_failed(self)
        return ok

    def release(self) -> None:
        self._lock.release()
        _after_release(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def locked(self) -> bool:
        return self._lock.locked()

    # threading.Condition(lock) integration: it probes these when the
    # caller supplies the lock object.
    def _is_owned(self) -> bool:
        for obj, _n, _c in _held_stack():
            if obj is self:
                return True
        return False

    def _release_save(self):
        # Condition.wait() releases the lock FULLY (all reentrant
        # counts); drop the whole held entry and remember its count.
        if hasattr(self._lock, "_release_save"):
            st = self._lock._release_save()
        else:
            self._lock.release()
            st = None
        held = _held_stack()
        count = 1
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                count = held[i][2]
                del held[i]
                break
        return (st, count)

    def _acquire_restore(self, state) -> None:
        st, count = state
        _before_acquire(self)
        try:
            if hasattr(self._lock, "_acquire_restore"):
                self._lock._acquire_restore(st)
            else:
                # locklint: unresolved-acquisition witness-internal (the
                # wrapped primitive; named by self.name)
                self._lock.acquire()
        except BaseException:
            _after_acquire_failed(self)
            raise
        held = _held_stack()
        for ent in held:
            if ent[0] is self:
                ent[2] = count
                break


class _DepLock(_DepLockBase):
    __slots__ = ()


class _DepRLock(_DepLockBase):
    __slots__ = ()


def named_lock(name: str):
    """A mutex named `name` (a lock CLASS name from LOCK_ORDER.md).
    Plain threading.Lock when the witness is off."""
    if not _state.enabled:
        return threading.Lock()
    return _DepLock(name, threading.Lock())


def named_rlock(name: str):
    if not _state.enabled:
        return threading.RLock()
    return _DepRLock(name, threading.RLock(), reentrant=True)


def named_condition(name: str, lock=None):
    """A condition variable over `lock` (or a fresh named lock). Waits
    release the underlying lock, so the witness pops/repushes the held
    entry across the wait exactly like a release/acquire pair."""
    if lock is None:
        lock = named_rlock(name)
    return threading.Condition(lock)


def assert_subgraph(allowed, *, allow_names=None) -> List[str]:
    """Return the observed edges NOT covered by `allowed` — a callable
    (a, b) -> bool, normally `Manifest.allows` from tools.locklint.
    Used by the conftest session-end check: the graph the run actually
    exercised must be a subgraph of the declared hierarchy."""
    bad = []
    for a, b in sorted(observed_edges()):
        try:
            ok = allowed(a, b)
        except Exception:
            ok = False
        if not ok:
            bad.append("undeclared observed lock-order edge: %s -> %s" % (a, b))
    return bad


if os.environ.get("SNAPPY_TPU_LOCKDEP", "").strip() in ("1", "true", "on"):
    enable()
