"""Device mesh execution: shard stacked column batches, let GSPMD insert
the collectives.

TPU-first replacement for the reference's executor fan-out + GemFire P2P
exchange (SURVEY.md §5 "Distributed communication backend"): instead of
shipping serialized rows between JVMs, the stacked [num_batches, capacity]
column arrays are laid out across a `jax.sharding.Mesh` along the batch
axis (batch ≈ bucket: the unit of data placement). The SAME compiled
query function then runs under jit with sharded inputs — XLA GSPMD
partitions the scan/filter locally and inserts psum/all_gather for the
aggregate/join exchange, which is exactly the CollectAggregateExec partial
merge and the replicated-table HashJoinExec build-side broadcast
(SnappyStrategies.scala:347, joins/HashJoinExec.scala:63) done by the
compiler instead of hand-written messaging.
"""

from __future__ import annotations

import threading
from snappydata_tpu.utils import locks
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class MeshContext:
    """Process-wide data mesh. When active, device tables bind with their
    batch axis sharded over 'data' and query jits produce SPMD programs.

    Each context carries a process-unique `token` (monotonic counter) used
    by device caches instead of id(mesh) — ids get reused after GC, which
    would let a 4-device run hit arrays placed for a dead 8-device mesh."""

    _current: Optional["MeshContext"] = None
    _stack: list = []          # supports nested/reentrant `with`
    _lock = locks.named_lock("parallel.mesh")
    _next_token = 0

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self.batch_sharding = NamedSharding(mesh, P("data", None))
        self.replicated = NamedSharding(mesh, P())
        with MeshContext._lock:
            MeshContext._next_token += 1
            self.token = MeshContext._next_token

    @property
    def num_devices(self) -> int:
        return self.mesh.devices.size

    @classmethod
    def current(cls) -> Optional["MeshContext"]:
        return cls._current

    @classmethod
    def activate(cls, mesh: Optional[Mesh]) -> Optional["MeshContext"]:
        with cls._lock:
            cls._current = MeshContext(mesh) if mesh is not None else None
            return cls._current

    def __enter__(self):
        with MeshContext._lock:
            MeshContext._stack.append(MeshContext._current)
            MeshContext._current = self
        return self

    def __exit__(self, *exc):
        with MeshContext._lock:
            MeshContext._current = MeshContext._stack.pop() \
                if MeshContext._stack else None
        return False


def data_mesh(num_devices: Optional[int] = None) -> Mesh:
    devices = jax.devices()
    n = num_devices or len(devices)
    return Mesh(np.array(devices[:n]), ("data",))


def submesh(device_indices) -> Mesh:
    """Mesh over an explicit device subset — the composed topology's
    per-server plane (each ServerNode owns a disjoint slice of the
    host's chips; ref: one embedded executor per store JVM,
    ExecutorInitiator.scala:45-105)."""
    devices = jax.devices()
    return Mesh(np.array([devices[i] for i in device_indices]), ("data",))


def shard_batches(array, ctx: Optional[MeshContext]):
    """Place a stacked [B, C] array: batch-sharded under a mesh, default
    placement otherwise. B is padded to a multiple of the mesh size by the
    device builder (pow2 bucketing covers pow2 meshes)."""
    if ctx is None:
        return array
    return jax.device_put(array, ctx.batch_sharding)


def round_up_to(n: int, mult: int) -> int:
    return ((n + mult - 1) // mult) * mult
