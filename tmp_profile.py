"""Round-3 perf scratch: where does per-query time go? (not committed)"""
import time

import numpy as np

from snappydata_tpu import SnappySession, config
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.utils import tpch
from snappydata_tpu.sql.parser import parse
from snappydata_tpu.sql import ast

import jax

print("devices:", jax.devices())
platform = jax.devices()[0].platform
config.global_properties().decimal_as_float64 = platform == "cpu"

s = SnappySession(catalog=Catalog())
t0 = time.time()
tpch.load_tpch(s, sf=2.0, seed=17)
print(f"load: {time.time()-t0:.1f}s")
n_rows = s.catalog.lookup_table("lineitem").data.snapshot().total_rows()
print("rows:", n_rows)

for name, q in (("q1", tpch.Q1), ("q6", tpch.Q6)):
    s.sql(q)  # warm
    # 1. end-to-end
    best = min(
        (lambda t: (s.sql(q), time.time() - t)[1])(time.time())
        for _ in range(8))
    print(f"{name}: end-to-end {best*1e3:.2f}ms  "
          f"({n_rows/best/1e9:.2f}B rows/s)")

    # 2. parse only
    t0 = time.time()
    for _ in range(20):
        stmt = parse(q)
    print(f"{name}: parse {1e3*(time.time()-t0)/20:.2f}ms")

    # 3. front half of _run_query (rewrites..tokenize)
    from snappydata_tpu.sql.optimizer import optimize
    from snappydata_tpu.sql.analyzer import tokenize_plan
    plan0 = stmt.plan

    def front():
        plan = s._rewrite_stream_windows(plan0)
        plan = s._decorrelate(plan)
        plan = s._rewrite_subqueries(plan, ())
        plan = optimize(plan, s.catalog)
        resolved, _ = s.analyzer.analyze_plan(plan)
        return tokenize_plan(resolved)

    t0 = time.time()
    for _ in range(20):
        tokenized, lit_params = front()
    print(f"{name}: front-half {1e3*(time.time()-t0)/20:.2f}ms")

    # 4. executor.execute on pre-tokenized plan
    t0 = time.time()
    for _ in range(8):
        s.executor.execute(tokenized, tuple(lit_params))
    print(f"{name}: executor.execute {1e3*(time.time()-t0)/8:.2f}ms")

    # 5. compiled.execute directly
    from snappydata_tpu.engine.executor import _plan_key
    host_ops = []
    node = tokenized
    while isinstance(node, (ast.Sort, ast.Limit, ast.Distinct)):
        host_ops.append(node)
        node = node.children()[0]
    key = (_plan_key(node, s.catalog), s.catalog.generation)
    compiled = s.executor._plan_cache.get(key)
    print(f"{name}: compiled found: {compiled is not None}")
    if compiled is None:
        continue
    t0 = time.time()
    for _ in range(8):
        compiled.execute(tuple(lit_params))
    print(f"{name}: compiled.execute {1e3*(time.time()-t0)/8:.2f}ms")

    # 6. device-only: rebuild the exact args once, then time fn alone
    params = tuple(lit_params)
    import jax.numpy as jnp
    from snappydata_tpu.engine.executor import _param_scalar
    tables = [r.bind() for r in compiled.relations]
    arrays = []
    for r, dt in zip(compiled.relations, tables):
        keep = r.keep_mask(dt, params)
        for ci in r.used:
            arrays.append((dt.columns[ci], dt.nulls.get(ci)))
        arrays.append(dt.valid)
    aux = [jnp.asarray(b(params)) for b in compiled.aux_builders]
    static = tuple(p() for p in compiled.static_providers)
    pvals = tuple(_param_scalar(v) for v in params)
    fn = compiled._jitted.get(static)
    print(f"{name}: jitted found: {fn is not None}, keep={keep}")
    outs = fn(tuple(arrays), tuple(aux), pvals)
    jax.block_until_ready(outs)
    t0 = time.time()
    for _ in range(8):
        outs = fn(tuple(arrays), tuple(aux), pvals)
        jax.block_until_ready(outs)
    dev = (time.time() - t0) / 8
    print(f"{name}: device-only {dev*1e3:.2f}ms  "
          f"({n_rows/dev/1e9:.2f}B rows/s)")

    # 7. bind-only cost
    t0 = time.time()
    for _ in range(8):
        tables = [r.bind() for r in compiled.relations]
        arrays = []
        for r, dt in zip(compiled.relations, tables):
            keep = r.keep_mask(dt, params)
            for ci in r.used:
                arrays.append((dt.columns[ci], dt.nulls.get(ci)))
            arrays.append(dt.valid)
        aux = [jnp.asarray(b(params)) for b in compiled.aux_builders]
    print(f"{name}: bind-only {1e3*(time.time()-t0)/8:.2f}ms")

    # 8. device_get cost
    t0 = time.time()
    for _ in range(8):
        jax.device_get(outs)
    print(f"{name}: device_get {1e3*(time.time()-t0)/8:.2f}ms")
