"""Disk persistence: checkpointed column batches + statement WAL.

The reference persists regions as oplogs/krfs in disk stores with crash
recovery on boot, plus backup/restore CLI (SURVEY.md §5 checkpoint/resume;
CREATE DISKSTORE DDL SnappyDDLParser ddl:1051; OpLogRdd reads raw oplog
bytes core/.../execution/oplog/impl/OpLogRdd.scala). TPU-first shape of
the same guarantees:

- Column batches are immutable → persisted once as self-describing files
  (JSON header + raw little-endian array bytes; string dictionaries as
  UTF-8 blob + offsets). A checkpoint only writes batches that aren't on
  disk yet.
- A manifest JSON per checkpoint pins (batch ids, delete masks, deltas,
  row-buffer rows) — the durable twin of the in-memory MVCC manifest.
- Between checkpoints, a statement WAL (length-prefixed records of DML
  SQL + params, or raw insert arrays) makes mutations durable; recovery =
  load last checkpoint + replay WAL tail. This is the deterministic-replay
  design SURVEY.md §5 prescribes in place of the reference's physical
  oplogs.
- `recover_catalog` doubles as the data-extractor recovery mode
  (RecoveryService analogue): it reconstructs tables from disk bytes alone,
  no running engine needed.
"""

from __future__ import annotations

import dataclasses
import io
import json
import logging
import os
import struct
import threading
from snappydata_tpu.utils import locks
import time
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from snappydata_tpu import types as T
from snappydata_tpu.fault import failpoints
from snappydata_tpu.reliability import failpoints as rfail
from snappydata_tpu.storage.batch import ColumnBatch
from snappydata_tpu.storage.encoding import (ColumnStats, EncodedColumn,
                                             Encoding)
from snappydata_tpu.storage.table_store import (BatchView, ColumnTableData,
                                                RowTableData)

_MAGIC = b"SNTP"    # legacy records: no checksum (read-compat only)
_MAGIC2 = b"SNT2"   # checksummed records: trailing CRC32 over head+parts

_log = logging.getLogger("snappydata_tpu.storage.persistence")


class CorruptRecordError(IOError):
    """A record whose bytes are provably damaged (bad magic, CRC mismatch,
    garbled checksummed header) — as opposed to a torn TAIL, which is the
    expected shape of a crash mid-append and is simply where replay stops.
    Callers on the recovery path salvage the valid prefix and quarantine
    the rest (salvage_file) instead of failing boot."""


import contextlib


@contextlib.contextmanager
def _no_journal(session):
    """Detach the session's disk store so statements executed during
    recovery are not re-journaled (they came FROM the journal/catalog)."""
    saved = session.disk_store
    session.disk_store = None
    try:
        yield
    finally:
        session.disk_store = saved


def _np_json(v):
    """json serializer for numpy scalars/arrays inside ARRAY cells."""
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, np.bool_):
        return bool(v)
    raise TypeError(f"not JSON serializable: {type(v)}")


# --------------------------------------------------------------------------
# array (de)serialization — no pickle, self-describing
# --------------------------------------------------------------------------

def _arr_to_parts(arr: Optional[np.ndarray]) -> Tuple[dict, List[bytes]]:
    if arr is None:
        return {"kind": "none"}, []
    if arr.dtype == object and any(
            isinstance(v, (list, tuple, dict, np.ndarray))
            for v in arr.tolist()):
        payload = json.dumps(arr.tolist(),
                             default=_np_json).encode("utf-8")
        return {"kind": "json", "n": len(arr)}, [payload]
    if arr.dtype == object:  # string values → utf8 blob + offsets
        blobs = [(v if v is not None else "").encode("utf-8")
                 for v in arr.tolist()]
        offsets = np.zeros(len(blobs) + 1, dtype=np.int64)
        np.cumsum([len(b) for b in blobs], out=offsets[1:])
        nulls = np.array([v is None for v in arr.tolist()], dtype=np.uint8)
        return ({"kind": "utf8", "n": len(blobs)},
                [offsets.tobytes(), b"".join(blobs), nulls.tobytes()])
    a = np.ascontiguousarray(arr)
    return ({"kind": "raw", "dtype": a.dtype.str, "shape": list(a.shape)},
            [a.tobytes()])


def _arr_from_parts(meta: dict, parts: List[bytes]) -> Optional[np.ndarray]:
    if meta["kind"] == "none":
        return None
    if meta["kind"] == "json":
        out = np.empty(meta["n"], dtype=object)
        for i, v in enumerate(json.loads(parts[0].decode("utf-8"))):
            out[i] = v
        return out
    if meta["kind"] == "utf8":
        n = meta["n"]
        offsets = np.frombuffer(parts[0], dtype=np.int64)
        blob = parts[1]
        nulls = np.frombuffer(parts[2], dtype=np.uint8)
        out = np.empty(n, dtype=object)
        for i in range(n):
            out[i] = None if nulls[i] else \
                blob[offsets[i]:offsets[i + 1]].decode("utf-8")
        return out
    return np.frombuffer(parts[0], dtype=np.dtype(meta["dtype"])) \
        .reshape(meta["shape"]).copy()


def frame_record(header: dict, arrays: List[Optional[np.ndarray]],
                 codec: Optional[str] = None) -> bytes:
    """Assemble one record as a single contiguous buffer: magic, head
    length, JSON head, parts, trailing CRC32. The CRC is computed in ONE
    pass over the assembled head+parts region (no per-part incremental
    loop) and callers issue ONE write for the whole record — the group
    commit drain concatenates these frames and syncs them with one
    write+fsync per group.

    `codec` overrides the global at-rest codec for THIS record.  The
    disk tier (storage/tier.py) frames demoted column batches with
    codec="none" so raw numeric parts land at computable offsets and can
    be memmapped back without a decompress pass — the batch arrays are
    already the encoded (compressed-domain) form, so framing them raw
    loses nothing."""
    from snappydata_tpu import config
    from snappydata_tpu.storage.encoding import compress_bytes

    if codec is None:
        codec = config.global_properties().compression_codec
    metas = []
    parts: List[bytes] = []
    codecs: List[str] = []
    for a in arrays:
        m, ps = _arr_to_parts(a)
        m["nparts"] = len(ps)
        metas.append(m)
        for p in ps:
            # at-rest compression ON by default (ref: LZ4'd oplogs);
            # stored only when it actually shrinks the part
            if codec != "none" and len(p) > 512:
                used, blob = compress_bytes(p, codec)
                if len(blob) < len(p):
                    parts.append(blob)
                    codecs.append(used)
                    continue
            parts.append(p)
            codecs.append("none")
    head_obj = {"h": header, "arrays": metas,
                "sizes": [len(p) for p in parts]}
    if any(c != "none" for c in codecs):
        head_obj["codecs"] = codecs
    head = json.dumps(head_obj).encode("utf-8")
    buf = bytearray()
    buf += _MAGIC2
    buf += struct.pack("<I", len(head))
    buf += head
    for p in parts:
        buf += p
    # CRC32 over head + stored (possibly compressed) parts, trailing the
    # record: verify-on-read catches bit rot that is the right LENGTH (a
    # torn tail is caught by short reads; a flipped byte was not, and
    # used to replay silently — the whole point of the checksum)
    crc = zlib.crc32(memoryview(buf)[8:])
    buf += struct.pack("<I", crc & 0xFFFFFFFF)
    return bytes(buf)


def write_record(fh, header: dict, arrays: List[Optional[np.ndarray]]) -> None:
    fh.write(frame_record(header, arrays))


def read_records(fh):
    """Yield (header, arrays) until EOF or a torn tail (crash mid-append:
    stop cleanly). Raise CorruptRecordError on provable mid-file damage —
    bad magic or a CRC mismatch on a checksummed record."""
    while True:
        magic = fh.read(4)
        if len(magic) < 4:
            return
        if magic == _MAGIC2:
            checksummed = True
        elif magic == _MAGIC:
            checksummed = False
        else:
            raise CorruptRecordError("corrupt record (bad magic)")
        lenbytes = fh.read(4)
        if len(lenbytes) < 4:
            return  # torn tail
        (hlen,) = struct.unpack("<I", lenbytes)
        raw_head = fh.read(hlen)
        if len(raw_head) < hlen:
            return  # torn tail
        try:
            head = json.loads(raw_head.decode("utf-8"))
            sizes = list(head["sizes"])
        except (ValueError, UnicodeDecodeError, KeyError, TypeError):
            if checksummed:
                # a checksummed record's header was fully present but
                # does not parse: damage, not a tear
                raise CorruptRecordError("corrupt record (garbled header)")
            return  # legacy torn/garbled tail record (crash mid-write)
        # ONE read for all parts (+ the CRC when checksummed) and ONE
        # CRC pass over the contiguous body — the read-side twin of the
        # zero-copy frame assembly on the write side
        total = sum(sizes)
        body = fh.read(total + (4 if checksummed else 0))
        if len(body) < total + (4 if checksummed else 0):
            return  # torn tail write (crash mid-record / mid-group)
        if checksummed:
            crc = zlib.crc32(memoryview(body)[:total],
                             zlib.crc32(raw_head))
            if (crc & 0xFFFFFFFF) != \
                    struct.unpack("<I", body[total:total + 4])[0]:
                raise CorruptRecordError("corrupt record (CRC mismatch)")
        raw_parts = []
        pos0 = 0
        for size in sizes:
            raw_parts.append(body[pos0:pos0 + size])
            pos0 += size
        parts = []
        codecs = head.get("codecs")
        for pi, p in enumerate(raw_parts):
            if codecs is not None and codecs[pi] != "none":
                from snappydata_tpu.storage.encoding import decompress_bytes

                try:
                    p = decompress_bytes(codecs[pi], p)
                except ImportError:
                    # codec module missing on THIS machine (e.g. a zstd
                    # record read where only zlib exists): a config
                    # problem — never quarantine sound data over it
                    raise
                except Exception:
                    if checksummed:
                        # CRC passed yet the codec rejects it: damage in
                        # a shape the checksum covered — impossible
                        # without a writer bug, but never replay it
                        raise CorruptRecordError(
                            "corrupt record (undecodable part)")
                    return  # garbled legacy tail: stop cleanly
            parts.append(p)
        arrays: List[Optional[np.ndarray]] = []
        pos = 0
        for m in head["arrays"]:
            ps = parts[pos:pos + m["nparts"]]
            pos += m["nparts"]
            arrays.append(_arr_from_parts(m, ps))
        yield head["h"], arrays


def _read_first_header(path: str) -> Optional[dict]:
    """First record's user header (the `h` field) WITHOUT reading or
    decoding the payload parts — for boot-time metadata peeks. Returns
    None on an empty/torn/damaged head; no CRC verification (callers
    that consume the payload go through read_records)."""
    with open(path, "rb") as fh:
        magic = fh.read(4)
        if magic not in (_MAGIC, _MAGIC2):
            return None
        lenbytes = fh.read(4)
        if len(lenbytes) < 4:
            return None
        (hlen,) = struct.unpack("<I", lenbytes)
        raw_head = fh.read(hlen)
        if len(raw_head) < hlen:
            return None
        try:
            return json.loads(raw_head.decode("utf-8")).get("h")
        except (ValueError, UnicodeDecodeError, AttributeError):
            return None


def salvage_scan(path: str) -> Tuple[int, Optional[CorruptRecordError]]:
    """Walk `path`'s records; return (byte offset past the last fully
    valid record, the CorruptRecordError if damage stopped the walk —
    None for a clean file or a plain torn tail)."""
    with open(path, "rb") as fh:
        valid_end = 0
        gen = read_records(fh)
        while True:
            try:
                next(gen)
            except StopIteration:
                return valid_end, None
            except CorruptRecordError as e:
                return valid_end, e
            valid_end = fh.tell()


def salvage_file(path: str, counter: str = "wal_corrupt_records") -> int:
    """Repair a record file in place: quarantine everything past the last
    valid record to `path + '.corrupt'` and truncate the file to the
    valid prefix, so recovery keeps every intact record AND subsequent
    appends land at a readable position (an un-truncated torn tail would
    strand later appends behind unreadable bytes). Bumps `counter` when
    the cut was provable corruption rather than a crash tear. Returns
    the number of quarantined bytes (0 = file was clean/absent)."""
    if not os.path.exists(path):
        return 0
    rfail.hit("wal.salvage")
    valid_end, err = salvage_scan(path)
    size = os.path.getsize(path)
    if valid_end >= size:
        return 0
    with open(path, "rb") as fh:
        fh.seek(valid_end)
        bad = fh.read()
    with open(path + ".corrupt", "ab") as out:
        out.write(bad)
        out.flush()
        # locklint: blocking-under-lock salvage runs at boot/first-touch
        # under the io lock BY DESIGN: no write may land on an unsalvaged
        # tail, and nothing serves traffic during recovery
        os.fsync(out.fileno())
    with open(path, "rb+") as fh:
        fh.truncate(valid_end)
        fh.flush()
        # locklint: blocking-under-lock same salvage invariant as above
        os.fsync(fh.fileno())
    if err is not None:
        from snappydata_tpu.observability.metrics import global_registry

        # locklint: metric-dynamic counter is one of the two declared
        # names "wal_corrupt_records" (default) / "batch_corrupt_records"
        global_registry().inc(counter)
        _log.warning(
            "%s: %s at byte %d — salvaged %d-byte prefix, quarantined "
            "%d bytes to %s", path, err, valid_end, valid_end, len(bad),
            path + ".corrupt")
    else:
        _log.info("%s: torn tail (%d bytes) truncated after crash; "
                  "quarantined to %s", path, len(bad), path + ".corrupt")
    return len(bad)


# --------------------------------------------------------------------------
# schema / type JSON
# --------------------------------------------------------------------------

def _dtype_to_json(dt: T.DataType) -> dict:
    out = {"name": dt.name}
    if isinstance(dt, T.DecimalType):
        out["precision"] = dt.precision
        out["scale"] = dt.scale
    elif isinstance(dt, T.ArrayType):
        out["element"] = _dtype_to_json(dt.element)
    elif isinstance(dt, T.MapType):
        out["key"] = _dtype_to_json(dt.key)
        out["value"] = _dtype_to_json(dt.value)
    elif isinstance(dt, T.StructType):
        out["fields"] = [[n, _dtype_to_json(t)] for n, t in dt.fields]
    return out


def _dtype_from_json(d: dict) -> T.DataType:
    if d["name"] == "decimal":
        return T.DecimalType("decimal", d.get("precision", 38),
                             d.get("scale", 2))
    if d["name"] == "array":
        # legacy records (pre element-type persistence) default to STRING:
        # a non-numeric element keeps the column on the always-correct
        # host path instead of guessing it onto the numeric device build
        return T.ArrayType("array", _dtype_from_json(
            d.get("element", {"name": "string"})))
    if d["name"] == "map":
        return T.MapType("map",
                         _dtype_from_json(d.get("key", {"name": "string"})),
                         _dtype_from_json(d.get("value",
                                                {"name": "double"})))
    if d["name"] == "struct":
        return T.StructType("struct", tuple(
            (n, _dtype_from_json(t)) for n, t in d.get("fields", [])))
    return T.parse_type(d["name"])


def schema_to_json(schema: T.Schema) -> list:
    return [{"name": f.name, "type": _dtype_to_json(f.dtype),
             "nullable": f.nullable} for f in schema.fields]


def schema_from_json(cols: list) -> T.Schema:
    return T.Schema([T.Field(c["name"], _dtype_from_json(c["type"]),
                             c.get("nullable", True)) for c in cols])


# --------------------------------------------------------------------------
# DiskStore
# --------------------------------------------------------------------------

class DiskStore:
    """One durable store directory (ref: CREATE DISKSTORE / sys-disk-dir).

    Layout:
      catalog.json                      table metadata (+ views, topks)
      wal.log                           ONE global ordered WAL (all tables)
      tables/<name>/batch-<id>.col      immutable encoded batch
      tables/<name>/manifest.json       checkpointed manifest (+ wal_seq)
      tables/<name>/rows.dat|rowbuf.dat row-table / row-buffer snapshot

    Durability contract:
    - Every WAL record carries a global monotone `seq`. Each checkpoint
      records the `wal_seq` it folded per table; recovery replays only
      records with seq > that table's folded seq — so a crash between
      manifest write and WAL rotation can never double-apply (review
      finding: truncation used to race the checkpoint).
    - The log is global and replayed in order, so cross-table statements
      (INSERT INTO a SELECT FROM b) see the b-state they saw originally.
    - Writers journal BEFORE applying (see SnappySession.mutation paths),
      under `mutation_lock`, and checkpoints take the same lock — the
      classic WAL invariant.
    - DROP TABLE writes a `drop` marker; replay ignores records older than
      the last drop marker of their table (recreated tables can't
      resurrect a dead incarnation's records).
    """

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.join(path, "tables"), exist_ok=True)
        self._lock = locks.named_lock("storage.wal_buffer")
        self.mutation_lock = locks.named_rlock("storage.mutation_lock")
        # serializes WAL file writes/rotation; lock order is always
        # _io_lock -> _lock, never the reverse
        self._io_lock = locks.named_rlock("storage.wal_io")
        self._wal_fh: Optional[io.BufferedWriter] = None
        # boot-time repair: quarantine damaged/torn suffixes BEFORE the
        # first append — appending after a torn tail would strand the new
        # (acked!) records behind bytes replay can never traverse
        salvage_file(self._wal_path())
        # the log stays clean across ordinary appends (whole records,
        # flushed+fsynced); only a torn-write crash dirties it again —
        # this flag lets replay/reopen skip redundant full-file rescans
        self._wal_clean = True
        self._wal_seq = self._scan_last_seq()
        # --- group commit state (wal_fsync_mode group|interval) --------
        # appends land here as (seq, framed bytes); a drain concatenates
        # the group and issues ONE write+fsync. Acks go through wal_sync,
        # which blocks until the covering fsync — the PR 2 no-acked-row-
        # lost invariant is preserved by gating the ack, not the append.
        self._commit_buf: List[Tuple[int, bytes]] = []
        self._commit_bytes = 0
        self._commit_first_t: Optional[float] = None
        self._buffered_seq = self._wal_seq    # highest seq in the buffer
        self._durable_seq = self._wal_seq     # highest fsync-covered seq
        # seq ranges whose group drain failed (torn/IO error): waiters on
        # them must raise their ack instead of hanging forever. The
        # durable watermark is advanced PAST a lost range when it is
        # poisoned (nothing will ever make those records durable), so
        # barrier syncs and later waiters don't wedge on it — the
        # specific-seq lost check still fails the lost records' own acks.
        self._lost: List[Tuple[int, int, BaseException]] = []
        # highest seq whose wal_append RETURNED (its statement went on
        # to apply): losing a record at or below this watermark means
        # memory may exceed the journal; losing one above it cannot
        # (the append raised before the caller applied anything)
        self._returned_seq = self._wal_seq
        # set when a drain failure left APPLIED-but-unjournaled state in
        # memory (the mutation raised at ack time, after apply): the
        # store is crash-shaped — checkpoints refuse to fold that state
        # into durable artifacts until the store is reopened/recovered
        self._wal_damaged = False
        # torn wal.append groups waiting for their crash write: FIFO,
        # flushed under _io_lock by WHOEVER writes next, so no other
        # bytes can reach the log before them (file order == seq order)
        self._pending_torn: List[Tuple[List[Tuple[int, bytes]], int]] = []
        self._commit_cond = locks.named_condition("storage.wal_buffer", self._lock)
        self._flusher: Optional[threading.Thread] = None
        self._closed = False

    def _wal_path(self) -> str:
        return os.path.join(self.path, "wal.log")

    @staticmethod
    def _durable_replace(tmp: str, dst: str) -> None:
        """fsync(tmp) → rename → fsync(dir): a checkpoint artifact must be
        on stable storage BEFORE anything (like WAL rotation) assumes it is
        — the reference's oplog stores fsync before truncating. A power
        loss right after os.replace without these leaves an empty/partial
        file whose covering WAL records were already discarded."""
        rfail.hit("checkpoint.write")
        spec = failpoints.hit("checkpoint.write")
        if spec is not None and spec.action == "torn_write":
            # crash mid-write of the checkpoint artifact: the tmp file
            # loses its tail and the replace never happens — the previous
            # artifact (and the un-rotated WAL) stay authoritative
            with open(tmp, "rb+") as fh:
                fh.truncate(max(0, os.path.getsize(tmp)
                                - max(1, int(spec.param))))
            raise failpoints.FaultError(
                "failpoint checkpoint.write: injected torn write")
        with open(tmp, "rb") as fh:
            # locklint: blocking-under-lock checkpoints hold mutation_lock
            # across their durable-replace fsyncs BY DESIGN: the fold must
            # be atomic vs committers (journal >= state invariant); rare,
            # operator-paced
            os.fsync(fh.fileno())
        # the PUBLISH seam: a fault here models a crash between the
        # artifact fsync and the atomic rename — the previous artifact
        # stays authoritative and the un-rotated WAL still covers it
        rfail.hit("checkpoint.publish")
        os.replace(tmp, dst)
        dfd = os.open(os.path.dirname(dst) or ".", os.O_RDONLY)
        try:
            # locklint: blocking-under-lock same checkpoint invariant
            os.fsync(dfd)
        finally:
            os.close(dfd)

    def _scan_last_seq(self) -> int:
        """Next-seq floor = max over the WAL *and* every checkpoint's
        folded wal_seq. The checkpoint fences are load-bearing: rotation
        can leave the WAL EMPTY while manifests hold the high-water
        mark — seeding from the WAL alone made a post-rotation reboot
        mint seqs BELOW the fence, and recovery silently skipped those
        acked records (found by the seeded chaos harness)."""
        last = 0
        if os.path.exists(self._wal_path()):
            with open(self._wal_path(), "rb") as fh:
                for header, _ in read_records(fh):
                    last = max(last, header.get("seq", 0))
        tdir = os.path.join(self.path, "tables")
        for name in (os.listdir(tdir) if os.path.isdir(tdir) else ()):
            mpath = os.path.join(tdir, name, "manifest.json")
            if os.path.exists(mpath):
                try:
                    with open(mpath) as fh:
                        last = max(last,
                                   int(json.load(fh).get("wal_seq", 0)))
                except (OSError, ValueError, TypeError):
                    pass   # damaged manifest: recovery handles it
            rpath = os.path.join(tdir, name, "rows.dat")
            if os.path.exists(rpath):
                try:
                    # header-only read: the folded wal_seq sits in the
                    # first record's JSON head — decoding the full row
                    # snapshot here would double recovery's boot cost
                    head = _read_first_header(rpath)
                    if head is not None:
                        last = max(last, int(head.get("wal_seq", 0)))
                except (OSError, IOError, ValueError, TypeError):
                    pass
        return last

    # -- catalog ---------------------------------------------------------

    def save_catalog(self, catalog) -> None:
        tables = []
        for info in catalog.list_tables():
            if info.options.get("materialized_view"):
                # materialized-view backing tables rebuild from the view
                # STATE checkpoint (views/<name>.state) + DDL — persisting
                # them as ordinary tables would collide with the DDL
                # replay recreating them
                continue
            tables.append({
                "name": info.name, "provider": info.provider,
                "schema": schema_to_json(info.schema),
                "options": info.options,
                "key_columns": list(info.key_columns),
                "partition_by": list(info.partition_by),
                "buckets": info.buckets,
                "colocate_with": info.colocate_with,
                "redundancy": info.redundancy,
                "base_table": info.base_table,
            })
        # views persist as their DDL text, re-executed on recovery (the
        # reference stores view text in its metastore the same way)
        views = dict(getattr(catalog, "_view_ddl", {}))
        matviews = dict(getattr(catalog, "_matview_ddl", {}))
        topks = dict(getattr(catalog, "_topk_defs", {}))
        aux = dict(getattr(catalog, "_aux_ddl", {}))  # policies/indexes
        grants = [[user, table, sorted(privs)] for (user, table), privs
                  in getattr(catalog, "_grants", {}).items()]
        tmp = os.path.join(self.path, "catalog.json.tmp")
        with open(tmp, "w") as fh:
            json.dump({"version": 1, "tables": tables, "views": views,
                       "matviews": matviews, "topks": topks,
                       "aux_ddl": aux,
                       "grants": grants}, fh, indent=1)
        self._durable_replace(tmp, os.path.join(self.path, "catalog.json"))

    # -- materialized-view state ------------------------------------------

    @staticmethod
    def _live_row_count_of(data) -> int:
        if hasattr(data, "snapshot"):          # column table: manifest sum
            return int(data.snapshot().total_rows())
        return int(data.count())               # row table

    def _views_dir(self) -> str:
        return os.path.join(self.path, "views")

    def _view_state_path(self, name: str) -> str:
        return os.path.join(self._views_dir(), f"{name}.state")

    def checkpoint_matview(self, mv, wal_seq: int, catalog=None) -> None:
        """Persist one view's [G] partial state with its WAL fence: a
        CRC-framed record (same framing/salvage machinery as the WAL),
        durable-replaced so a crash mid-write keeps the previous state
        authoritative.  Caller holds mutation_lock — the state is
        consistent with everything journaled up to `wal_seq`.  With a
        catalog, the base table's live row count rides the header so
        recovery can detect a base that lost unjournaled rows (state
        claiming rows the WAL can never replay degrades to STALE)."""
        mv.wal_seq = wal_seq
        base_rows = None
        if catalog is not None:
            base = catalog.lookup_table(mv.base_table)
            if base is not None:
                base_rows = self._live_row_count_of(base.data)
        header, arrays = mv.state_record(base_rows=base_rows)
        os.makedirs(self._views_dir(), exist_ok=True)
        tmp = os.path.join(self._views_dir(), f"{mv.name}.tmp")
        with open(tmp, "wb") as fh:
            write_record(fh, header, arrays)
        self._durable_replace(tmp, self._view_state_path(mv.name))

    def drop_matview_state(self, name: str) -> None:
        try:
            os.remove(self._view_state_path(name))
        except FileNotFoundError:
            pass

    # -- checkpoint ------------------------------------------------------

    def checkpoint_table(self, info, wal_seq: int) -> None:
        tdir = os.path.join(self.path, "tables", info.name)
        os.makedirs(tdir, exist_ok=True)
        if isinstance(info.data, RowTableData):
            arrays, masks, n = info.data.to_arrays_with_nulls()
            with open(os.path.join(tdir, "rows.tmp"), "wb") as fh:
                write_record(fh, {"kind": "rowtable", "n": n,
                                  "ncols": len(arrays),
                                  "columns": [f.name.lower() for f in
                                              info.schema.fields],
                                  "wal_seq": wal_seq},
                             list(arrays) + list(masks))
            self._durable_replace(os.path.join(tdir, "rows.tmp"),
                                  os.path.join(tdir, "rows.dat"))
            return
        data: ColumnTableData = info.data
        m = data.snapshot()
        batch_entries = []
        for view in m.views:
            b = view.batch
            fname = f"batch-{b.batch_id}.col"
            fpath = os.path.join(tdir, fname)
            if not os.path.exists(fpath):  # immutable → write once
                self._write_batch(fpath, b, info.schema)
            entry = {"file": fname, "batch_id": b.batch_id,
                     "num_rows": b.num_rows, "capacity": b.capacity}
            if view.delete_mask is not None:
                entry["delete_mask"] = _b64(view.delete_mask)
            if view.deltas:
                entry["deltas"] = [
                    {"col": ci, "hit": _b64(hit), "values": _b64(values),
                     "nulls": _b64(vnulls) if vnulls is not None else None}
                    for ci, hit, values, vnulls in view.deltas]
            batch_entries.append(entry)
        manifest = {
            "version": m.version,
            # epoch fence: recovery advances the mvcc clock past it so
            # post-recovery commit epochs stay monotone with pre-crash
            # ones (the per-table version vector resumes, never rewinds)
            "epoch": int(getattr(m, "epoch", 0)),
            "batches": batch_entries,
            "row_count": m.row_count,
            # schema as of this checkpoint: ALTER TABLE between checkpoints
            # makes load align columns by NAME (missing → NULL, extra →
            # dropped), then the fenced WAL replays the ALTER itself
            "columns": [f.name.lower() for f in info.schema.fields],
            "wal_seq": wal_seq,   # replay fence: records ≤ this are folded
        }
        with open(os.path.join(tdir, "rowbuf.tmp"), "wb") as fh:
            write_record(fh, {"kind": "rowbuf", "n": m.row_count},
                         list(m.row_arrays) + [
                             nm for nm in (m.row_nulls or
                                           [None] * len(m.row_arrays))])
        self._durable_replace(os.path.join(tdir, "rowbuf.tmp"),
                              os.path.join(tdir, "rowbuf.dat"))
        tmp = os.path.join(tdir, "manifest.json.tmp")
        with open(tmp, "w") as fh:
            json.dump(manifest, fh)
        self._durable_replace(tmp, os.path.join(tdir, "manifest.json"))
        # GC batches dropped from the manifest (deletes/truncate)
        live = {e["file"] for e in batch_entries}
        for f in os.listdir(tdir):
            if f.startswith("batch-") and f not in live:
                os.remove(os.path.join(tdir, f))

    def checkpoint(self, catalog) -> None:
        # crash fence: after a failed group drain, in-memory state can
        # hold rows whose statements RAISED at ack time (applied, then
        # the covering fsync failed). Folding that state into a durable
        # checkpoint would silently persist rows the client was told
        # failed — the Postgres fsync-panic lesson. Recovery (reopen)
        # rebuilds memory from the journal alone and clears the fence.
        if self._wal_damaged:
            raise IOError(
                "WAL group drain failed earlier; in-memory state may "
                "exceed the journal — reopen/recover the store before "
                "checkpointing")
        # mutation_lock: no writer can be between journal and apply, so
        # every snapshot state == everything journaled up to wal_seq
        with self.mutation_lock:
            # locklint: blocking-under-lock checkpoint must drain+fsync
            # INSIDE its mutation hold (see below) — rare, operator-paced
            # drain the commit buffer BEFORE folding anything: the
            # snapshot below must only ever fold rows whose WAL records
            # are already fsynced — folding a buffered record and THEN
            # failing its drain would durably persist a statement whose
            # ack raised (the fence above can't catch a failure that
            # happens after folding). A failed drain aborts the
            # checkpoint here, before any durable artifact is touched.
            self.wal_sync(force=True)
            if self._wal_damaged:
                raise IOError(
                    "WAL group drain failed; store must be reopened "
                    "before checkpointing")
            self.save_catalog(catalog)
            seq = self.current_wal_seq()
            folded = {}
            for info in catalog.list_tables():
                if info.options.get("materialized_view"):
                    continue   # rebuilt from the view state below
                self.checkpoint_table(info, seq)
                folded[info.name] = seq
            from snappydata_tpu.views.matview import matviews

            for mv in matviews(catalog).values():
                self.checkpoint_matview(mv, seq, catalog=catalog)
            self._rotate_wal(folded)

    def _write_batch(self, fpath: str, batch: ColumnBatch,
                     schema: Optional[T.Schema] = None) -> None:
        with open(fpath + ".tmp", "wb") as fh:
            for i, col in enumerate(batch.columns):
                stats = col.stats
                header = {
                    "col": i, "encoding": int(col.encoding),
                    "dtype": _dtype_to_json(col.dtype),
                    # column NAME at write time: batch files are
                    # write-once, so a later ALTER leaves them with a
                    # different column set than the manifest — load
                    # aligns by these names (legacy files without them
                    # fall back to the manifest's positional remap)
                    "name": (schema.fields[i].name.lower()
                             if schema is not None
                             and i < len(schema.fields) else None),
                    "num_rows": col.num_rows,
                    "stats": None if stats is None else {
                        "min": _json_safe(stats.min),
                        "max": _json_safe(stats.max),
                        "null_count": stats.null_count,
                        "count": stats.count},
                }
                write_record(fh, header,
                             [col.data, col.dictionary, col.runs,
                              col.validity])
        self._durable_replace(fpath + ".tmp", fpath)

    # -- WAL (group commit) ----------------------------------------------

    @staticmethod
    def _wal_policy() -> Tuple[str, float, int]:
        """(mode, group window seconds, buffer bytes) parsed from config.
        Modes (`wal_fsync_mode`):

        always        every append drains+fsyncs before returning (the
                      pre-group-commit behavior; one fsync per record);
        group         appends buffer; the ACK (wal_sync) drains the whole
                      group with one write+fsync — concurrent committers
                      coalesce, a lone committer pays one fsync that the
                      background flusher usually starts while the caller
                      is still applying/encoding (pipelined);
        interval:<ms> appends buffer and acks return WITHOUT waiting; the
                      flusher fsyncs every <ms>. Relaxed durability: a
                      crash may lose up to <ms> of ACKED local writes
                      (network surfaces still force a covering fsync)."""
        from snappydata_tpu import config

        props = config.global_properties()
        raw = str(props.get("wal_fsync_mode") or "group").strip().lower()
        group_s = max(0.0, float(props.get("wal_group_ms") or 0.0)) / 1e3
        buffer_bytes = int(props.get("wal_buffer_bytes") or (8 << 20))
        if raw.startswith("interval"):
            _, _, ms = raw.partition(":")
            try:
                if ms:
                    group_s = max(0.0, float(ms)) / 1e3
            except ValueError:
                pass
            return "interval", group_s, buffer_bytes
        if raw not in ("always", "group"):
            raw = "group"
        return raw, group_s, buffer_bytes

    def _ensure_fh(self) -> io.BufferedWriter:
        """Open (and, after a torn-write crash, salvage) the log for
        appending. Caller holds _io_lock."""
        if self._wal_fh is None:
            # reopen-time repair: if a tear was left since the log was
            # last open (torn-write fault paths), appending after it
            # would strand new records behind bytes replay can never
            # traverse
            if not self._wal_clean:
                salvage_file(self._wal_path())
                self._wal_clean = True
            self._wal_fh = open(self._wal_path(), "ab")
        return self._wal_fh

    def wal_append(self, table: str, kind: str, sql: Optional[str] = None,
                   params: Optional[tuple] = None,
                   arrays: Optional[List[np.ndarray]] = None,
                   nulls: Optional[List[Optional[np.ndarray]]] = None,
                   extra: Optional[dict] = None) -> int:
        """Append one record to the global log. kinds:
        'sql' (statement text + scalar params), 'insert'/'put' (raw column
        arrays), 'delete_keys' (key-tuple arrays + key column names),
        'drop' (incarnation marker). Returns the record's seq.

        Group commit: the framed record lands in the commit buffer; the
        covering fsync is released by wal_sync(seq) — callers MUST gate
        their ack on it (session/_journal_then/flight do_put all do)."""
        mode, _group_s, buffer_bytes = self._wal_policy()
        rfail.hit("wal.append")
        spec = failpoints.hit("wal.append")   # per-RECORD failpoint:
        # raise/latency fire here with the same hit cadence as before
        # group commit existed, so seeded chaos schedules keep coverage
        with self._lock:
            self._wal_seq += 1
            seq = self._wal_seq
            header = {"kind": kind, "table": table, "seq": seq}
            if extra:
                header.update(extra)
            payload: List[Optional[np.ndarray]] = []
            if kind == "sql":
                header["sql"] = sql
                header["params"] = [_json_safe(p) for p in (params or ())]
            elif kind in ("insert", "put", "delete_keys"):
                payload = list(arrays or [])
                header["ncols"] = len(payload)
                payload += list(nulls or [None] * len(payload))
            # frame through the module-level frame_record (the seam the
            # disk-full tests patch) so injected write failures surface
            # HERE, before the caller applies — an encode/frame error
            # must fail the statement synchronously, never the
            # background drain. One buffer, no intermediate copies.
            raw = frame_record(header, payload)
            torn = spec is not None and spec.action == "torn_write"
            if torn:
                cut = max(1, int(spec.param))
                raw = raw[:max(0, len(raw) - cut)]
            self._commit_buf.append((seq, raw))
            self._commit_bytes += len(raw)
            self._buffered_seq = seq
            if self._commit_first_t is None:
                self._commit_first_t = time.monotonic()
            full = self._commit_bytes >= buffer_bytes
            if torn:
                # swap the group out IN THIS critical section so no
                # concurrent append can land BEHIND the torn bytes (it
                # would be fsynced yet truncated by salvage — an acked
                # row lost), and queue it as a PENDING torn write: the
                # next writer to hold _io_lock (us, a concurrent drain,
                # or the flusher) writes it FIRST, so no higher-seq
                # record can reach the file before this group and
                # replay order stays seq order
                group, self._commit_buf = self._commit_buf, []
                self._commit_bytes = 0
                self._commit_first_t = None
                self._pending_torn.append((group, seq))
            elif mode != "always":
                self._ensure_flusher_locked()
                self._commit_cond.notify_all()
        if torn:
            # crash mid-append: earlier buffered records reach disk whole
            # (they were never at fault — their acks still release), THIS
            # record loses its tail, and the store must be reopened like
            # a real crash — boot-time salvage then truncates the tear.
            with self._io_lock:
                self._flush_pending_torn()
            raise failpoints.FaultError(
                f"failpoint wal.append: injected torn write "
                f"({max(1, int(spec.param))} bytes cut)")
        if mode == "always" or full:
            # always: per-record durability (the legacy contract);
            # full: backpressure — the buffer bound is wal_buffer_bytes
            self._drain_upto(seq)
        failpoints.hit("wal.append", phase="after")
        with self._lock:
            # from here the caller applies: losing this record later
            # (failed drain) means memory-exceeds-journal divergence
            self._returned_seq = max(self._returned_seq, seq)
        return seq

    def _flush_pending_torn(self) -> None:
        """Write queued torn groups (crash mid-append). Caller holds
        _io_lock — called by every writer before it touches the file, so
        torn bytes always precede later records. Each group's LAST
        record is torn; it is written, fsynced, and the log is closed
        dirty (boot/reopen salvage truncates the tear). Complete records
        keep their acks (durable watermark advances over them); the torn
        record's seq is poisoned so any other waiter on it raises
        instead of hanging."""
        while True:
            with self._lock:
                if not self._pending_torn:
                    return
                group, torn_seq = self._pending_torn.pop(0)
            try:
                fh = self._ensure_fh()
                fh.write(b"".join(raw for _, raw in group))
                fh.flush()
                # locklint: blocking-under-lock the pending-torn FIFO must
                # flush under the io lock before ANY later write so file
                # order == seq order after a crash-shaped tear; rare path
                os.fsync(fh.fileno())
                covered = group[-2][0] if len(group) > 1 else None
                with self._lock:
                    if covered is not None:
                        self._durable_seq = max(self._durable_seq,
                                                covered)
                    self._lost.append((torn_seq, torn_seq,
                                       failpoints.FaultError(
                                           "wal.append: torn write")))
                    # the torn record never returned from wal_append
                    # (never applied): no divergence/fence — and the
                    # watermark moves past it so barriers don't wedge
                    # on a seq that can never drain
                    self._durable_seq = max(self._durable_seq, torn_seq)
                    self._commit_cond.notify_all()
            # locklint: swallowed-exception not swallowed: the error
            # object itself is routed to EVERY waiter through the
            # poisoned seq range (_lost) and the _wal_damaged fence —
            # strictly louder than a log line
            except Exception as e:
                # a REAL I/O failure on top of the injected tear: nothing
                # in this group is provably durable — poison it all so no
                # waiter hangs on an unreachable watermark
                with self._lock:
                    self._lost.append((group[0][0], torn_seq, e))
                    if group[0][0] <= self._returned_seq:
                        # earlier records in the group were applied but
                        # are now unjournaled — crash-shaped divergence
                        self._wal_damaged = True
                    self._durable_seq = max(self._durable_seq, torn_seq)
                    self._commit_cond.notify_all()
            finally:
                if self._wal_fh is not None:
                    try:
                        self._wal_fh.close()
                    # locklint: swallowed-exception best-effort close on
                    # an already-failing handle; the tear itself is
                    # recorded via _lost/_wal_damaged above
                    except Exception:
                        pass
                    self._wal_fh = None
                self._wal_clean = False   # tear on disk until salvaged

    def wal_sync(self, seq: Optional[int] = None,
                 force: bool = False) -> None:
        """Block until every record with seq ≤ `seq` is covered by an
        fsync — THE ack gate of the group-commit write path. `seq=None`
        targets everything appended so far. In `interval` mode the ack is
        relaxed (returns immediately) unless `force=True` — network
        surfaces (Flight do_put, replica fan-out) force it so a remote
        ack always implies durability."""
        mode, _group_s, _bb = self._wal_policy()
        with self._lock:
            barrier = seq is None
            if barrier:
                seq = self._buffered_seq
            else:
                # a specific record's ack: raise if IT was lost
                self._check_lost_locked(seq)
            if self._durable_seq >= seq:
                return
        if mode == "interval" and not force:
            return
        if barrier:
            # barrier semantics (checkpoint, /wal/flush, wal_sync
            # action): make everything still PENDING durable. Records
            # lost to an EARLIER failed drain are gone — their own acks
            # already raised — and must not fail every future barrier;
            # only a failure of the drain we perform NOW propagates.
            while True:
                self._drain()
                with self._lock:
                    if self._durable_seq >= seq:
                        return
        else:
            self._drain_upto(seq)

    def _check_lost_locked(self, seq: int) -> None:
        for lo, hi, exc in self._lost:
            if lo <= seq <= hi:
                raise exc

    def _drain(self) -> None:
        """Flush the commit buffer as ONE contiguous write + ONE fsync
        (the group). Serialized on _io_lock: while one drainer fsyncs,
        later appends pile into the fresh buffer and the next drain
        covers them all — the classic leader-based group commit."""
        with self._io_lock:
            # torn crash writes queued ahead of us go to the file FIRST
            # (their seqs are lower), then _ensure_fh below salvages the
            # tear before this group lands
            self._flush_pending_torn()
            with self._lock:
                if not self._commit_buf:
                    return
                group, self._commit_buf = self._commit_buf, []
                nbytes, self._commit_bytes = self._commit_bytes, 0
                self._commit_first_t = None
            first, last = group[0][0], group[-1][0]
            lost_from = first
            t0 = time.monotonic()
            try:
                # per-GROUP failpoint: torn-write tears the group's tail
                # (the mid-group crash shape); raise fails the whole
                # drain — INSIDE the try so the swapped-out group is
                # poisoned like any real drain failure (a waiter must
                # never spin on records that left the buffer unwritten)
                spec = failpoints.hit("wal.group_commit")
                data = group[0][1] if len(group) == 1 else \
                    b"".join(raw for _, raw in group)
                if spec is not None and spec.action == "torn_write":
                    cut = max(1, int(spec.param))
                    keep = max(0, len(data) - cut)
                    fh = self._ensure_fh()
                    fh.write(data[:keep])
                    fh.flush()
                    # locklint: blocking-under-lock the drain IS the group
                    # fsync (PR 3): wal_io exists to serialize it; acks
                    # wait on _commit_cond, never on wal_io
                    os.fsync(fh.fileno())
                    # records whose frames lie ENTIRELY inside the
                    # written-and-fsynced prefix are durable — their acks
                    # must still release; only the torn tail's waiters
                    # fail (salvage truncates exactly that tail on boot)
                    end = 0
                    covered = first - 1
                    for s_, raw_ in group:
                        end += len(raw_)
                        if end <= keep:
                            covered = s_
                    with self._lock:
                        self._durable_seq = max(self._durable_seq,
                                                covered)
                    lost_from = covered + 1
                    raise failpoints.FaultError(
                        f"failpoint wal.group_commit: injected torn "
                        f"group write ({cut} bytes cut, "
                        f"{len(group)} records)")
                fh = self._ensure_fh()
                fh.write(data)
                fh.flush()
                # the fsync seam: a raise here is the fsync-failure
                # crash shape (Postgres fsync-gate lesson) — INSIDE the
                # try, so the group is poisoned and _wal_damaged fences
                # checkpoints exactly like a real EIO from the kernel
                rfail.hit("wal.fsync")
                # locklint: blocking-under-lock the drain IS the group
                # fsync (PR 3); see the torn-branch note above
                os.fsync(fh.fileno())
            except BaseException as e:
                # the group's records may be torn or absent on disk: the
                # store is crash-shaped. Poison the seq range so every
                # waiter's ack RAISES (instead of hanging on a durable
                # watermark that will never cover it), and force a
                # reopen-salvage before the next append.
                with self._lock:
                    self._lost.append((lost_from, last, e))
                    if lost_from <= self._returned_seq:
                        # a RETURNED record was lost: its statement went
                        # on to apply, so memory now exceeds the journal
                        # — fence checkpoints until reopen. (A record
                        # lost before its append returned — always-mode
                        # inline drain — never applied: no divergence.)
                        self._wal_damaged = True
                    # nothing will ever make the lost range durable:
                    # advance the watermark past it so barriers and
                    # later waiters don't wedge (the lost records' own
                    # acks still raise via _check_lost_locked)
                    self._durable_seq = max(self._durable_seq, last)
                    self._commit_cond.notify_all()
                if self._wal_fh is not None:
                    try:
                        self._wal_fh.close()
                    except Exception:
                        pass
                    self._wal_fh = None
                self._wal_clean = False
                raise
            from snappydata_tpu.observability.metrics import global_registry

            reg = global_registry()
            reg.inc("wal_fsync_count")
            reg.inc("wal_group_commit_batches")
            reg.inc("wal_records_written", len(group))
            reg.inc("wal_bytes_written", len(data))
            reg.record_time("wal_group_flush", time.monotonic() - t0)
            with self._lock:
                self._durable_seq = max(self._durable_seq, last)
                self._commit_cond.notify_all()

    def _drain_upto(self, seq: int) -> None:
        while True:
            with self._lock:
                self._check_lost_locked(seq)
                if self._durable_seq >= seq:
                    return
            self._drain()
            with self._lock:
                self._check_lost_locked(seq)
                if self._durable_seq >= seq:
                    return

    def _ensure_flusher_locked(self) -> None:
        """Start (or restart) the background flusher. It drains groups
        that aged past the group window / interval, which (a) overlaps
        the fsync with the caller's encode/apply work — the pipelined
        ingest lane — and (b) bounds the relaxed-ack window of interval
        mode. Caller holds _lock."""
        self._closed = False
        if self._flusher is None or not self._flusher.is_alive():
            t = threading.Thread(target=self._flusher_loop, daemon=True,
                                 name=f"wal-flusher-{id(self):x}")
            self._flusher = t
            t.start()

    def _flusher_loop(self) -> None:
        while True:
            with self._lock:
                idle = 0
                while not self._commit_buf and not self._closed:
                    self._commit_cond.wait(timeout=0.5)
                    idle += 1
                    if idle >= 10 and not self._commit_buf:
                        # park after ~5s idle; respawned on demand
                        self._flusher = None
                        return
                if self._closed:
                    self._flusher = None
                    return
                mode, group_s, buffer_bytes = self._wal_policy()
                age = time.monotonic() - (self._commit_first_t
                                          or time.monotonic())
                if age < group_s and self._commit_bytes < buffer_bytes:
                    self._commit_cond.wait(timeout=group_s - age)
                    continue   # re-evaluate: an ack drain may have run
            try:
                self._drain()
            except Exception:
                # the failed seq range is poisoned — every waiter RAISES
                # it as its ack — but count the event too: a flusher
                # failing every tick should show on the dashboard, not
                # only on whichever request happens to wait
                from snappydata_tpu.observability.metrics import \
                    global_registry

                global_registry().inc("wal_flusher_errors")

    def current_wal_seq(self) -> int:
        with self._lock:
            return self._wal_seq

    def _rotate_wal(self, folded: Dict[str, int]) -> None:
        """Drop records already folded into every table's checkpoint.
        Safe because replay fences on per-table wal_seq anyway — rotation
        is pure space reclamation."""
        self._drain()   # the file we rewrite must hold every append
        with self._io_lock:
            with self._lock:
                if not os.path.exists(self._wal_path()):
                    return
                if self._wal_fh is not None:
                    self._wal_fh.close()
                    self._wal_fh = None
            # a mid-file corrupt record must not abort the checkpoint:
            # salvage the prefix, quarantine the damage, rotate what's
            # readable (the damaged record's mutation was acked against
            # bytes that no longer exist — quarantine + counter is the
            # honest response, failing every future checkpoint is not)
            salvage_file(self._wal_path())
            keep: List[Tuple[dict, list]] = []
            with open(self._wal_path(), "rb") as fh:
                for header, arrays in read_records(fh):
                    t = header.get("table")
                    if header.get("seq", 0) > folded.get(t, 0):
                        keep.append((header, arrays))
            tmp = self._wal_path() + ".tmp"
            with open(tmp, "wb") as fh:
                for header, arrays in keep:
                    write_record(fh, header, arrays)
            self._durable_replace(tmp, self._wal_path())

    def drop_table_dir(self, table: str) -> None:
        """DROP TABLE: journal a drop marker, remove the on-disk dir (a
        recreate must not resurrect old batches — review finding)."""
        import shutil

        seq = self.wal_append(table, "drop")
        # the marker must be ON DISK before the table dir disappears —
        # force past interval mode's relaxed ack
        self.wal_sync(seq, force=True)
        tdir = os.path.join(self.path, "tables", table)
        if os.path.isdir(tdir):
            shutil.rmtree(tdir)

    def close(self) -> None:
        try:
            # a clean shutdown must not lose interval-mode acked tails
            self._drain()
        except Exception:
            pass   # crash-shaped close: salvage handles it on reboot
        with self._lock:
            self._closed = True
            self._commit_cond.notify_all()
        with self._io_lock:
            if self._wal_fh is not None:
                self._wal_fh.close()
                self._wal_fh = None

    # -- recovery --------------------------------------------------------

    def recover_catalog(self, session=None):
        """Rebuild a Catalog (+ table data) from disk: checkpointed batches
        and row buffers, then ONE ordered replay of the global WAL fenced
        per table on the checkpoint's wal_seq, then views and AQP
        registrations."""
        from snappydata_tpu.catalog import Catalog
        from snappydata_tpu.storage import mvcc

        # the WAL seq floor doubles as the epoch floor (seqs ARE commit
        # timestamps): the mvcc clock resumes past everything this store
        # ever acked, before any replay publishes
        mvcc.advance_to(self._wal_seq)
        cat_path = os.path.join(self.path, "catalog.json")
        catalog = Catalog()
        if not os.path.exists(cat_path):
            return catalog
        with open(cat_path) as fh:
            meta = json.load(fh)
        folded: Dict[str, int] = {}
        sample_tables = []
        for t in meta["tables"]:
            schema = schema_from_json(t["schema"])
            info = catalog.create_table(
                t["name"], schema, t["provider"], t.get("options", {}),
                key_columns=t.get("key_columns", ()))
            folded[info.name] = self._load_table_data(info)
            if t["provider"] == "sample":
                sample_tables.append(info)
        # replay session over the recovered catalog
        if session is None:
            from snappydata_tpu.session import SnappySession

            session = SnappySession(catalog=catalog)
        else:
            # the caller's analyzer/executor bound the pre-recovery
            # catalog at construction — rebind BEFORE replay executes any
            # statement against the recovered one
            from snappydata_tpu.engine.executor import Executor
            from snappydata_tpu.sql.analyzer import Analyzer

            session.catalog = catalog
            session.analyzer = Analyzer(catalog)
            session.executor = Executor(catalog, session.conf)
        # Views must exist BEFORE WAL replay: a journaled statement may read
        # one (INSERT INTO t SELECT ... FROM some_view) and replay swallows
        # statement errors, silently dropping committed rows otherwise. A
        # view over a table only created later in the WAL can't restore yet
        # — retry those after replay.
        pending_views = {}
        with _no_journal(session):  # recovery DDL must not re-journal
            for name, ddl in (meta.get("views") or {}).items():
                try:
                    session.sql(ddl)
                except Exception:
                    pending_views[name] = ddl
        # materialized views restore BEFORE WAL replay so the tail past
        # each view's checkpointed high-watermark re-folds exactly once:
        # a loaded state at fence W skips records <= W (already folded at
        # checkpoint time); a missing/damaged state or a fence that does
        # not match the base table's means the cheap path is gone — the
        # view comes up STALE and re-aggregates at its first read
        matview_ddl = dict(meta.get("matviews") or {})
        if matview_ddl:
            session._mv_recovering = True
            try:
                with _no_journal(session):
                    for name, ddl in matview_ddl.items():
                        try:
                            session.sql(ddl)
                        except Exception:
                            continue
                        mv = getattr(catalog, "_matviews", {}).get(name)
                        if mv is None:
                            continue
                        loaded = False
                        ckpt_base_rows = None
                        spath = self._view_state_path(name)
                        if os.path.exists(spath):
                            try:
                                salvage_file(
                                    spath,
                                    counter="batch_corrupt_records")
                                with open(spath, "rb") as fh:
                                    for header, arrays in \
                                            read_records(fh):
                                        mv.load_state(header, arrays)
                                        ckpt_base_rows = header.get(
                                            "base_rows")
                                        loaded = True
                            except Exception:
                                loaded = False
                        base_fence = folded.get(mv.base_table, 0)
                        base = catalog.lookup_table(mv.base_table)
                        if not loaded:
                            mv.stale = True
                        elif mv.wal_seq != base_fence:
                            mv.mark_stale("recovery fence mismatch")
                        elif (ckpt_base_rows is not None
                              and base is not None
                              and self._live_row_count_of(base.data)
                              != ckpt_base_rows):
                            # the restored base holds a different row
                            # set than the one the state aggregated —
                            # unjournaled writes (raw data-layer loads)
                            # are gone and the WAL can never replay
                            # them; serving the state would be wrong
                            mv.mark_stale(
                                "recovery base-rows mismatch")
            finally:
                session._mv_recovering = False
            catalog._matview_ddl = matview_ddl
        self._replay_wal(catalog, session, folded)
        with _no_journal(session):
            for name, ddl in pending_views.items():
                try:
                    session.sql(ddl)
                except Exception:
                    pass  # view over a dropped table: skip, like stale view
        catalog._view_ddl = dict(meta.get("views") or {})
        # policies/indexes: re-execute their DDL. A failing POLICY is a
        # security regression (the table would come up unfiltered) — fail
        # recovery loudly; a failing index only loses a fast path: warn.
        for name, ddl in (meta.get("aux_ddl") or {}).items():
            try:
                session.sql(ddl)
            except Exception as e:
                if name.startswith("policy:"):
                    raise RuntimeError(
                        f"recovery could not restore row-level policy "
                        f"{name!r} ({e}); refusing to come up without it")
                import sys

                print(f"warning: recovery skipped {name!r}: {e}",
                      file=sys.stderr)
        catalog._aux_ddl = dict(meta.get("aux_ddl") or {})
        catalog._grants = {(u, t): set(p)
                           for u, t, p in (meta.get("grants") or [])}
        # AQP re-registration (review finding: maintainers/TopKs froze
        # silently after restart)
        for info in sample_tables:
            session.register_sample(info)
        for name, d in (meta.get("topks") or {}).items():
            session.create_topk(name, d["base_table"], d["key_column"],
                                k=d.get("k", 50),
                                time_column=d.get("time_column"),
                                bucket_seconds=d.get("bucket_seconds", 60))
        return catalog

    def _load_table_data(self, info) -> int:
        """Load checkpointed state; returns the folded wal_seq (0 = no
        checkpoint on disk)."""
        tdir = os.path.join(self.path, "tables", info.name)
        if isinstance(info.data, RowTableData):
            rpath = os.path.join(tdir, "rows.dat")
            seq = 0
            if os.path.exists(rpath):
                salvage_file(rpath, counter="batch_corrupt_records")
                with open(rpath, "rb") as fh:
                    for header, arrays in read_records(fh):
                        seq = header.get("wal_seq", 0)
                        if header["n"]:
                            ncols = header.get("ncols", len(arrays))
                            cols, masks = arrays[:ncols], arrays[ncols:]
                            if masks:
                                from snappydata_tpu.session import \
                                    _restore_none_arrays

                                cols = _restore_none_arrays(cols, masks)
                            cols = _align_by_name(
                                cols, header.get("columns"),
                                info.schema, header["n"])
                            info.data.insert_arrays(cols)
            return seq
        mpath = os.path.join(tdir, "manifest.json")
        if not os.path.exists(mpath):
            return 0
        with open(mpath) as fh:
            manifest = json.load(fh)
        data: ColumnTableData = info.data
        cur_names = [f.name.lower() for f in info.schema.fields]
        saved_names = manifest.get("columns", cur_names)
        remap = None          # saved col idx -> current col idx (or None)
        if saved_names != cur_names:
            remap = [cur_names.index(nm) if nm in cur_names else None
                     for nm in saved_names]
        views = []
        for entry in manifest["batches"]:
            fpath = os.path.join(tdir, entry["file"])
            try:
                # FileNotFoundError covers the boot AFTER a quarantine:
                # the manifest still names the file until the next
                # checkpoint rewrites it — a missing batch must skip the
                # same way the corrupt one did, not fail boot
                batch, file_names = self._read_batch(fpath, entry,
                                                     info.schema)
            except (CorruptRecordError, FileNotFoundError) as e:
                # a damaged immutable batch cannot be partially used (a
                # missing column would desync the columnar views):
                # quarantine the whole file, count it, keep booting —
                # the reference's disk stores quarantine bad oplogs the
                # same way rather than refusing to start
                from snappydata_tpu.observability.metrics import \
                    global_registry

                global_registry().inc("batch_corrupt_records")
                _log.error(
                    "%s: %s — quarantining batch file (%d rows lost) "
                    "and continuing recovery", fpath, e,
                    entry.get("num_rows", -1))
                if os.path.exists(fpath):
                    os.replace(fpath, fpath + ".corrupt")
                continue
            delete_mask = _unb64(entry.get("delete_mask"), np.bool_)
            deltas = tuple(
                (d["col"], _unb64(d["hit"], np.bool_),
                 _unb64_any(d["values"]),
                 _unb64(d["nulls"], np.bool_) if d.get("nulls") else None)
                for d in entry.get("deltas", ()))
            import dataclasses as _dc

            # align the batch's columns to the CURRENT schema. Batch
            # files are write-once, so their column set reflects the
            # schema at WRITE time — which may predate both the
            # manifest's saved_names and today's schema (ALTERs in
            # between). Files that recorded names align exactly; legacy
            # files fall back to the manifest's positional remap.
            if file_names is not None:
                align_names = file_names if file_names != cur_names \
                    else None
            else:
                align_names = saved_names if remap is not None else None
            if align_names is not None:
                by_name = dict(zip(align_names, batch.columns))
                batch = _dc.replace(batch, columns=tuple(
                    by_name[nm] if nm in by_name
                    else data._all_null_column(ci, f.dtype, batch.num_rows)
                    for ci, (nm, f) in enumerate(
                        zip(cur_names, info.schema.fields))))
            if remap is not None:
                deltas = tuple((remap[ci], hit, vals, vn)
                               for ci, hit, vals, vn in deltas
                               if remap[ci] is not None)
            views.append(BatchView(batch, delete_mask, deltas))
        # locklint: lock=storage.column_table (batch recovery is
        # column-table only; row tables restore through their own path)
        with data._lock:
            # re-intern dictionaries so table-level codes match batch codes
            for ci in data._dicts:
                for v in views:
                    col = v.batch.columns[ci]
                    if col.dictionary is not None:
                        data._intern_strings(
                            ci, np.asarray(col.dictionary, dtype=object))
            rb = os.path.join(tdir, "rowbuf.dat")
            if os.path.exists(rb):
                salvage_file(rb, counter="batch_corrupt_records")
                with open(rb, "rb") as fh:
                    for header, arrays in read_records(fh):
                        n_cols = len(saved_names)
                        if header["n"]:
                            cols = list(arrays[:n_cols])
                            nls = list(arrays[n_cols:]) or [None] * n_cols
                            if remap is not None:
                                cols, nls = _align_rowbuf(
                                    cols, nls, saved_names, info.schema,
                                    header["n"])
                            # row-buffer strings must re-enter the shared
                            # dictionary (batches carry their own dict;
                            # buffer rows don't)
                            for ci in data._dicts:
                                data._intern_strings(
                                    ci, np.asarray(cols[ci], dtype=object))
                            data._row_buffer.append(cols, nls)
            # advance batch id counter past recovered ids
            import itertools

            max_id = max((e["batch_id"] for e in manifest["batches"]),
                         default=-1)
            data._batch_ids = itertools.count(max_id + 1)
            from snappydata_tpu.storage import mvcc

            # rebuild the version vector: the clock resumes past the
            # checkpointed epoch, and the recovered manifest is stamped
            # with the checkpoint's wal_seq (its commit fence)
            mvcc.advance_to(int(manifest.get("epoch", 0)))
            with mvcc.commit_scope(int(manifest.get("wal_seq", 0))):
                data._publish(tuple(views))
        return manifest.get("wal_seq", 0)

    def load_batch(self, table: str, batch_id: int
                   ) -> Optional[ColumnBatch]:
        """Re-read ONE checkpointed batch by id — the tier quarantine's
        WAL+checkpoint rebuild source (storage/tier.py).  Batch files
        are write-once immutable, so a clean read IS the batch as of
        its last checkpoint; None when the table/batch has no durable
        artifact (or that artifact is itself damaged — the caller's
        typed-error path takes over)."""
        tdir = os.path.join(self.path, "tables", table)
        mpath = os.path.join(tdir, "manifest.json")
        if not os.path.exists(mpath):
            return None
        try:
            with open(mpath) as fh:
                manifest = json.load(fh)
        except (OSError, ValueError):
            return None
        for entry in manifest.get("batches", ()):
            if int(entry.get("batch_id", -1)) != int(batch_id):
                continue
            fpath = os.path.join(tdir, entry["file"])
            try:
                batch, _names = self._read_batch(fpath, entry, None)
            except (CorruptRecordError, OSError):
                return None
            return batch
        return None

    def _read_batch(self, fpath: str, entry: dict, schema: T.Schema
                    ) -> Tuple[ColumnBatch, Optional[List[str]]]:
        """Read a batch file; returns (batch, column names recorded at
        write time — None for legacy files without them). Quarantine-
        worthy damage (CRC mismatch, bad magic, unreadable trailing
        bytes) raises CorruptRecordError; a CLEAN file with a different
        column set than today's schema is NOT damage — batch files are
        write-once and may predate an ALTER (the caller aligns by
        name)."""
        cols = []
        names: List[Optional[str]] = []
        with open(fpath, "rb") as fh:
            gen = read_records(fh)
            last_good = 0
            while True:
                try:
                    rec = next(gen)       # CorruptRecordError propagates
                except StopIteration:
                    break
                header, arrays = rec
                data_arr, dictionary, runs, validity = arrays
                st = header.get("stats")
                stats = None if st is None else ColumnStats(
                    st["min"], st["max"], st["null_count"], st["count"])
                cols.append(EncodedColumn(
                    Encoding(header["encoding"]),
                    _dtype_from_json(header["dtype"]),
                    header["num_rows"], data_arr, dictionary=dictionary,
                    runs=runs, validity=validity, stats=stats))
                names.append(header.get("name"))
                last_good = fh.tell()
        size = os.path.getsize(fpath)
        if last_good < size:
            # the file ends in bytes no record accounts for: a tear,
            # not a schema-drift artifact
            raise CorruptRecordError(
                f"batch file torn: {size - last_good} unreadable "
                f"trailing bytes after {len(cols)} columns")
        if not cols:
            raise CorruptRecordError("batch file holds no records")
        file_names = [n for n in names] \
            if all(n is not None for n in names) else None
        return (ColumnBatch(entry["batch_id"], 0, entry["num_rows"],
                            entry["capacity"], tuple(cols)), file_names)

    def _replay_wal(self, catalog, session, folded: Dict[str, int]) -> None:
        wal = self._wal_path()
        if not os.path.exists(wal):
            return
        # the store may have been dirtied since construction (torn-write
        # crash): re-salvage so the tear is quarantined instead of
        # aborting boot mid-replay; skipped when the log is known clean
        # (construction salvaged it and only whole records followed)
        if not getattr(self, "_wal_clean", False):
            salvage_file(wal)
            self._wal_clean = True
        # replay must not re-journal (records already ARE the journal);
        # the managed scope keeps the unmanaged-write guard from marking
        # views stale for the replay's own data-layer applies
        from snappydata_tpu.views import matview as _mv_guard

        with _no_journal(session), _mv_guard.managed_base_write():
            self._replay_wal_inner(catalog, session, folded, wal)

    def _replay_wal_inner(self, catalog, session, folded: Dict[str, int],
                          wal: str) -> None:
        # pre-scan: last drop marker per table — records of a previous
        # incarnation (before the drop) must not be applied
        last_drop: Dict[str, int] = {}
        with open(wal, "rb") as fh:
            for header, _ in read_records(fh):
                if header["kind"] == "drop":
                    last_drop[header["table"]] = header["seq"]
        def reseed_dedup(header, n_rows):
            # a client-stamped statement id in the record header means
            # this mutation was acked (or at least journaled) before the
            # crash: re-seed the at-most-once window so a lost-ack retry
            # arriving AFTER recovery returns the recorded result
            # instead of double-applying (reliability.MutationDedup)
            sid = header.get("stmt_id")
            if not sid:
                return
            from snappydata_tpu.reliability import dedup_for

            dedup_for(catalog).record(
                sid, {"names": ["count"], "rows": [[int(n_rows)]],
                      "replayed": True})

        from snappydata_tpu.storage import mvcc

        # every replayed record re-applies under its ORIGINAL seq as the
        # commit timestamp, so re-published manifests carry the same
        # epoch fences the pre-crash ones did (one token pair brackets
        # the whole loop; the replay is single-threaded)
        _seq_tok = mvcc._commit_seq.set(0)
        try:
            self._replay_records(catalog, session, folded, wal,
                                 last_drop, reseed_dedup, mvcc)
        finally:
            mvcc._commit_seq.reset(_seq_tok)

    def _replay_records(self, catalog, session, folded, wal, last_drop,
                        reseed_dedup, mvcc) -> None:
        with open(wal, "rb") as fh:
            for header, arrays in read_records(fh):
                table = header.get("table")
                seq = header.get("seq", 0)
                kind = header["kind"]
                mvcc._commit_seq.set(int(seq))
                if kind == "drop":
                    continue
                if seq <= folded.get(table, 0) or \
                        seq < last_drop.get(table, 0):
                    # already folded into a checkpoint — the mutation
                    # still APPLIED, so its dedup id must survive too
                    reseed_dedup(header, 0)
                    continue
                info = catalog.lookup_table(table)
                if info is None:
                    continue  # table dropped for good
                if kind == "sql":
                    n = 0
                    try:
                        res = session.sql(header["sql"],
                                          params=tuple(
                                              header.get("params", ())))
                        if res.num_rows and res.columns:
                            v = res.rows()[0][0]
                            n = int(v) if isinstance(v, (int, float)) else 0
                    except Exception:
                        # a statement that failed originally fails the same
                        # way on replay — same end state, keep going
                        pass
                    reseed_dedup(header, n)
                    continue
                from snappydata_tpu.views import matview as _mv

                ncols = header["ncols"]
                cols, nulls = arrays[:ncols], arrays[ncols:]
                if kind == "delete_keys":
                    key_cols = header["key_columns"]
                    keys = {tuple(c[i] for c in cols)
                            for i in range(len(cols[0]))}

                    def pred(batch_cols, _kc=key_cols, _keys=keys):
                        stacked = [np.asarray(batch_cols[k]) for k in _kc]
                        n = stacked[0].shape[0]
                        hits = np.zeros(n, dtype=bool)
                        for r in range(n):
                            if tuple(c[r] for c in stacked) in _keys:
                                hits[r] = True
                        return hits

                    wrapped, captured = _mv.wrap_delete_predicate(
                        catalog, table, pred)
                    deleted = info.data.delete(wrapped)
                    if captured:
                        _mv.replay_fold_deleted(catalog, table, captured,
                                                seq)
                    reseed_dedup(header, deleted)
                    continue
                reseed_dedup(header,
                             int(cols[0].shape[0]) if cols else 0)
                any_nulls = any(nm is not None for nm in nulls)
                if isinstance(info.data, RowTableData):
                    if kind == "put":
                        info.data.put_arrays(cols)
                        if info.key_columns:
                            _mv.mark_stale(catalog, table, "replay put")
                        else:
                            _mv.replay_fold(catalog, table, cols, None,
                                            seq)
                    else:
                        info.data.insert_arrays(cols)
                        _mv.replay_fold(catalog, table, cols, None, seq)
                elif kind == "put":
                    # _column_put subtracts/folds through the live hooks;
                    # replayed records sit past every fence by the replay
                    # filter, so those folds are exactly the tail folds
                    session._column_put(info, cols)
                else:
                    info.data.insert_arrays(
                        cols, nulls=nulls if any_nulls else None)
                    _mv.replay_fold(catalog, table, cols,
                                    nulls if any_nulls else None, seq)


def _json_safe(v):
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, np.bool_):
        return bool(v)
    return v


def _b64(arr: np.ndarray) -> dict:
    import base64

    a = np.ascontiguousarray(arr)
    return {"dtype": a.dtype.str, "shape": list(a.shape),
            "b64": base64.b64encode(a.tobytes()).decode("ascii")}


def _unb64(d: Optional[dict], dtype=None) -> Optional[np.ndarray]:
    if d is None:
        return None
    return _unb64_any(d)


def _unb64_any(d: dict) -> np.ndarray:
    import base64

    return np.frombuffer(base64.b64decode(d["b64"]),
                         dtype=np.dtype(d["dtype"])).reshape(d["shape"]).copy()

def _align_by_name(cols, saved_names, schema, n):
    """Row-table checkpoint → current schema: match columns by name; a
    column added since the checkpoint reads NULL, a dropped one is skipped
    (the fenced WAL then replays the ALTER itself, which no-ops)."""
    cur = [f.name.lower() for f in schema.fields]
    if saved_names is None or list(saved_names) == cur:
        return cols
    by_name = dict(zip(saved_names, cols))
    out = []
    for nm in cur:
        if nm in by_name:
            out.append(by_name[nm])
        else:
            out.append(np.full(n, None, dtype=object))
    return out


def _align_rowbuf(cols, nls, saved_names, schema, n):
    """Column-table row-buffer checkpoint → current schema (see
    _align_by_name); missing columns read NULL via an all-set mask."""
    cur_fields = [(f.name.lower(), f) for f in schema.fields]
    by_name = dict(zip(saved_names, zip(cols, nls)))
    out_c, out_n = [], []
    for nm, f in cur_fields:
        if nm in by_name:
            c, m = by_name[nm]
            out_c.append(c)
            out_n.append(m)
        else:
            npd = f.dtype.np_dtype
            out_c.append(np.full(n, None, dtype=object) if npd == object
                         else np.zeros(n, dtype=npd))
            out_n.append(np.ones(n, dtype=np.bool_))
    return out_c, out_n
