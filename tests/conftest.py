"""Test fixture: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's tier-1 strategy (SnappyFunSuite boots a real
embedded engine in one JVM — no mocks; core/src/test/scala/io/snappydata/
SnappyFunSuite.scala:51-88): tests run the real engine in-process, with
multi-"chip" behavior exercised via XLA host devices instead of real TPUs.

Note: this machine's TPU bootstrap (sitecustomize) force-selects the
`axon` platform at interpreter start, overriding JAX_PLATFORMS env — so we
override the *config* after import, before any backend initializes.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
assert jax.default_backend() == "cpu", jax.default_backend()

import pytest  # noqa: E402

# ---- runtime lockdep witness (SNAPPY_TPU_LOCKDEP=1) -------------------
# snappydata_tpu.utils.locks enables itself from the env var at import
# (before any engine lock exists, since this conftest imports before the
# test modules import the package). Here we add the END-OF-SESSION
# check: zero cycle violations, and the observed acquisition-order graph
# must be a subgraph of the declared manifest (tools/locklint/
# lock_order.toml) — an edge tests actually exercised that the manifest
# does not allow fails the run.

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

_LOCKDEP = os.environ.get("SNAPPY_TPU_LOCKDEP", "").strip() in (
    "1", "true", "on")


def pytest_sessionfinish(session, exitstatus):
    if not _LOCKDEP:
        return
    from snappydata_tpu.utils import locks
    from tools.locklint import load_manifest

    problems = list(locks.violations())
    try:
        man = load_manifest()
    except Exception as e:
        problems.append("lockdep: cannot load lock_order.toml: %s" % e)
        man = None
    if man is not None:
        problems.extend(locks.assert_subgraph(man.allows))
    if problems:
        sys.stderr.write(
            "\n=== lockdep witness failures (%d) ===\n" % len(problems))
        for p in problems:
            sys.stderr.write(p + "\n")
        raise RuntimeError(
            "lockdep witness: %d problem(s); see stderr above — extend "
            "LOCK_ORDER.md + lock_order.toml only with a reviewed "
            "invariant" % len(problems))


@pytest.fixture()
def session():
    from snappydata_tpu import SnappySession
    from snappydata_tpu.catalog import Catalog

    s = SnappySession(catalog=Catalog())
    yield s
    s.stop()
