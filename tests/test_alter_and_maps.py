"""ALTER TABLE ADD/DROP COLUMN (ref SnappyDDLParser.scala:697-713,
SnappySession.alterTable:1628), MAP<K,V> columns, and NULL group-key
segregation (SQL GROUP BY puts NULL keys in their own group)."""

import numpy as np
import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog


@pytest.fixture()
def s():
    sess = SnappySession(catalog=Catalog())
    yield sess
    sess.stop()


# --- ALTER TABLE ---------------------------------------------------------

def test_alter_add_column_column_table(s):
    s.sql("CREATE TABLE c (id INT, x DOUBLE) USING column "
          "OPTIONS (column_max_delta_rows '3')")
    for i in range(7):  # forces batches to exist before the ALTER
        s.sql(f"INSERT INTO c VALUES ({i}, {i * 1.5})")
    s.sql("ALTER TABLE c ADD COLUMN tag STRING")
    assert s.sql("SELECT count(*) FROM c WHERE tag IS NULL").rows() == [(7,)]
    s.sql("INSERT INTO c VALUES (7, 10.5, 'new')")
    assert s.sql("SELECT id, tag FROM c WHERE id >= 6 ORDER BY id").rows() \
        == [(6, None), (7, 'new')]
    # the added column is updatable
    s.sql("ALTER TABLE c ADD COLUMN w DOUBLE")
    s.sql("UPDATE c SET w = x * 2 WHERE id = 1")
    assert s.sql("SELECT w FROM c WHERE id = 1").rows() == [(3.0,)]
    assert s.sql("SELECT w FROM c WHERE id = 2").rows()[0][0] is None


def test_alter_drop_column(s):
    s.sql("CREATE TABLE c (id INT, x DOUBLE, y INT) USING column")
    s.sql("INSERT INTO c VALUES (1, 1.5, 10), (2, 2.5, 20)")
    s.sql("ALTER TABLE c DROP COLUMN x")
    assert s.sql("DESCRIBE c").rows() == [
        ("id", "int", True), ("y", "int", True)]
    assert s.sql("SELECT * FROM c ORDER BY id").rows() == [(1, 10), (2, 20)]


def test_alter_row_table_and_guards(s):
    s.sql("CREATE TABLE r (k INT PRIMARY KEY, v STRING) USING row")
    s.sql("INSERT INTO r VALUES (1, 'a')")
    s.sql("ALTER TABLE r ADD COLUMN extra INT")
    s.sql("INSERT INTO r VALUES (2, 'b', 42)")
    assert s.sql("SELECT k, extra FROM r ORDER BY k").rows() == \
        [(1, None), (2, 42)]
    with pytest.raises(Exception, match="primary key"):
        s.sql("ALTER TABLE r DROP COLUMN k")
    with pytest.raises(Exception, match="already exists"):
        s.sql("ALTER TABLE r ADD COLUMN extra INT")


def test_alter_is_admin_only(s):
    s.sql("CREATE TABLE t (id INT) USING column")
    user = SnappySession(catalog=s.catalog, user="bob")
    with pytest.raises(PermissionError):
        user.sql("ALTER TABLE t ADD COLUMN z INT")


def test_alter_persistence(tmp_path):
    s = SnappySession(catalog=Catalog(), data_dir=str(tmp_path),
                      recover=False)
    s.sql("CREATE TABLE t (id INT) USING column")
    s.sql("INSERT INTO t VALUES (1)")
    s.checkpoint()
    s.sql("ALTER TABLE t ADD COLUMN v DOUBLE")  # WAL tail
    s.sql("INSERT INTO t VALUES (2, 9.5)")
    s.disk_store.close()
    s2 = SnappySession(data_dir=str(tmp_path))
    assert s2.sql("SELECT id, v FROM t ORDER BY id").rows() == \
        [(1, None), (2, 9.5)]


def test_alter_checkpoint_then_drop_in_wal_tail(tmp_path):
    # checkpoint carries 3 cols; the WAL tail drops one — load aligns the
    # checkpointed batches by NAME, then replay applies the DROP
    s = SnappySession(catalog=Catalog(), data_dir=str(tmp_path),
                      recover=False)
    s.sql("CREATE TABLE t (id INT, x DOUBLE) USING column "
          "OPTIONS (column_max_delta_rows '2')")
    for i in range(5):
        s.sql(f"INSERT INTO t VALUES ({i}, {i * 1.0})")
    s.sql("ALTER TABLE t ADD COLUMN tag STRING")
    s.sql("INSERT INTO t VALUES (5, 5.0, 'z')")
    s.checkpoint()
    s.sql("ALTER TABLE t DROP COLUMN x")
    s.sql("INSERT INTO t VALUES (6, 'w')")
    s.disk_store.close()
    s2 = SnappySession(data_dir=str(tmp_path))
    assert s2.sql("DESCRIBE t").rows() == [
        ("id", "int", True), ("tag", "string", True)]
    rows = s2.sql("SELECT id, tag FROM t ORDER BY id").rows()
    assert rows[5:] == [(5, "z"), (6, "w")]
    assert all(tag is None for _, tag in rows[:5])


def test_alter_row_table_recovery(tmp_path):
    s = SnappySession(catalog=Catalog(), data_dir=str(tmp_path),
                      recover=False)
    s.sql("CREATE TABLE r (k INT PRIMARY KEY, v STRING) USING row")
    s.sql("INSERT INTO r VALUES (1, 'a'), (2, 'b')")
    s.checkpoint()
    s.sql("ALTER TABLE r ADD COLUMN w DOUBLE")
    s.sql("INSERT INTO r VALUES (3, 'c', 1.5)")
    s.disk_store.close()
    s2 = SnappySession(data_dir=str(tmp_path))
    assert s2.sql("SELECT k, w FROM r ORDER BY k").rows() == \
        [(1, None), (2, None), (3, 1.5)]
    assert s2.sql("SELECT v FROM r WHERE k = 3").rows() == [("c",)]


# --- MAP<K,V> ------------------------------------------------------------

def test_map_create_insert_select(s):
    s.sql("CREATE TABLE t (id INT, m MAP<STRING, INT>) USING column")
    s.sql("INSERT INTO t VALUES (1, map('a', 1, 'b', 2)), "
          "(2, map('c', 3)), (3, NULL)")
    rows = s.sql("SELECT id, m FROM t ORDER BY id").rows()
    assert rows[0] == (1, {"a": 1, "b": 2})
    assert rows[2][1] is None
    assert s.sql("SELECT id, element_at(m, 'a') FROM t ORDER BY id").rows() \
        == [(1, 1), (2, None), (3, None)]
    assert s.sql("SELECT size(m) FROM t WHERE id = 1").rows() == [(2,)]
    assert s.sql("SELECT map_keys(m) FROM t WHERE id = 1").rows() == \
        [(["a", "b"],)]
    assert s.sql("SELECT map_values(m) FROM t WHERE id = 2").rows() == [([3],)]
    assert s.sql("SELECT id FROM t WHERE element_at(m, 'b') = 2").rows() == \
        [(1,)]


def test_map_persistence(tmp_path):
    s = SnappySession(catalog=Catalog(), data_dir=str(tmp_path),
                      recover=False)
    s.sql("CREATE TABLE t (id INT, m MAP<STRING, INT>) USING column "
          "OPTIONS (column_max_delta_rows '2')")
    for i in range(5):  # rolls over into batches
        s.sql(f"INSERT INTO t VALUES ({i}, map('k', {i * 10}))")
    s.checkpoint()
    s.sql("INSERT INTO t VALUES (5, NULL)")
    s.disk_store.close()
    s2 = SnappySession(data_dir=str(tmp_path))
    assert s2.sql("SELECT id, element_at(m, 'k') FROM t ORDER BY id").rows() \
        == [(0, 0), (1, 10), (2, 20), (3, 30), (4, 40), (5, None)]


def test_map_queries_leave_plain_columns_on_device(s):
    from snappydata_tpu.observability.metrics import global_registry

    s.sql("CREATE TABLE t (k INT, m MAP<STRING, INT>) USING column")
    s.sql("INSERT INTO t VALUES (1, map('a', 1)), (2, map('b', 2))")
    before = global_registry().counter("host_fallbacks")
    assert s.sql("SELECT sum(k) FROM t").rows() == [(3,)]
    assert global_registry().counter("host_fallbacks") == before


# --- NULL group keys -----------------------------------------------------

def test_null_group_keys_string(s):
    s.sql("CREATE TABLE t (id INT, tag STRING) USING column")
    s.sql("INSERT INTO t VALUES (1, 'a'), (2, NULL), (3, NULL), (4, 'b')")
    assert s.sql("SELECT tag, count(*) FROM t GROUP BY tag ORDER BY tag"
                 ).rows() == [(None, 2), ("a", 1), ("b", 1)]


def test_null_group_keys_numeric_and_bool(s):
    s.sql("CREATE TABLE n (id INT, v INT) USING column")
    s.sql("INSERT INTO n VALUES (1, 5), (2, NULL), (3, NULL), (4, 7)")
    assert s.sql("SELECT v, count(*) FROM n GROUP BY v ORDER BY v").rows() \
        == [(None, 2), (5, 1), (7, 1)]
    s.sql("CREATE TABLE b (f BOOLEAN, x INT)")
    s.sql("INSERT INTO b VALUES (true, 1), (NULL, 2), (false, 3), (NULL, 4)")
    assert s.sql("SELECT f, count(*) FROM b GROUP BY f ORDER BY f").rows() \
        == [(None, 2), (False, 1), (True, 1)]


def test_null_group_keys_multi_and_agg(s):
    s.sql("CREATE TABLE m (g STRING, x DOUBLE)")
    s.sql("INSERT INTO m VALUES ('a', 1.0), (NULL, 2.0), (NULL, 4.0)")
    assert s.sql("SELECT g, avg(x) FROM m GROUP BY g ORDER BY g").rows() == \
        [(None, 3.0), ("a", 1.0)]
    assert s.sql("SELECT g, count(*) c FROM m GROUP BY g "
                 "HAVING count(*) > 1").rows() == [(None, 2)]


def test_map_device_element_at():
    """MAP<STRING, V> binds as key-code + value plates: size and
    literal-key element_at run ON DEVICE (round-5; previously every
    map query took the host path)."""
    from snappydata_tpu.observability.metrics import global_registry

    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE md (id INT, m MAP<STRING, INT>, "
          "sm MAP<STRING, STRING>) USING column")
    s.sql("INSERT INTO md VALUES "
          "(1, map('a', 10, 'b', 20), map('x', 'hello')), "
          "(2, map('b', 5), map('x', 'world', 'y', 'z')), "
          "(3, NULL, NULL)")
    before = global_registry().counter("host_fallbacks")
    r = s.sql("SELECT id, element_at(m, 'b'), size(m), "
              "element_at(sm, 'x') FROM md ORDER BY id").rows()
    assert r[0] == (1, 20, 2, "hello")
    assert r[1] == (2, 5, 1, "world")
    assert r[2][1] is None and r[2][3] is None
    # filters over element_at run in the same compiled program
    assert s.sql("SELECT count(*) FROM md WHERE "
                 "element_at(m, 'a') = 10").rows()[0][0] == 1
    # missing key -> NULL; NULL key -> NULL
    assert s.sql("SELECT element_at(m, 'nope') FROM md "
                 "WHERE id = 1").rows() == [(None,)]
    assert s.sql("SELECT element_at(m, NULL) FROM md "
                 "WHERE id = 1").rows() == [(None,)]
    assert global_registry().counter("host_fallbacks") == before
    # append-only key codes survive later inserts
    s.sql("INSERT INTO md VALUES (4, map('aa', 7), map('q', 'r'))")
    assert s.sql("SELECT element_at(m, 'b') FROM md WHERE id = 1"
                 ).rows() == [(20,)]
    assert s.sql("SELECT element_at(m, 'aa') FROM md WHERE id = 4"
                 ).rows() == [(7,)]
    # non-literal key and whole-map SELECT keep the host path (correct,
    # just not device)
    assert s.sql("SELECT m FROM md WHERE id = 1").rows() \
        == [({"a": 10, "b": 20},)]
    s.stop()


def test_map_device_persistence(tmp_path):
    d = str(tmp_path / "store")
    s = SnappySession(data_dir=d)
    s.sql("CREATE TABLE mp (id INT, m MAP<STRING, DOUBLE>) USING column")
    s.sql("INSERT INTO mp VALUES (1, map('k', 1.5)), (2, map('k', 2.5))")
    s.checkpoint()
    s.stop()
    s2 = SnappySession(data_dir=d)
    assert s2.sql("SELECT sum(element_at(m, 'k')) FROM mp"
                  ).rows()[0][0] == pytest.approx(4.0)
    s2.stop()


def test_alter_add_drop_complex_columns_keep_device_dicts():
    """ALTER-added ARRAY<STRING>/MAP columns must have dictionary
    state, and dropping a preceding column must remap it (review
    findings: raw KeyError at bind / survivor column decoding through
    its neighbour's stale dictionary)."""
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE ac (id INT) USING column")
    s.sql("INSERT INTO ac VALUES (1)")
    s.sql("ALTER TABLE ac ADD COLUMN tags ARRAY<STRING>")
    s.sql("ALTER TABLE ac ADD COLUMN m MAP<STRING, INT>")
    s.sql("INSERT INTO ac VALUES (2, array('p', 'q'), map('k', 9))")
    r = s.sql("SELECT id, size(tags), element_at(m, 'k') FROM ac "
              "ORDER BY id").rows()
    assert r[0] == (1, None, None)
    assert r[1] == (2, 2, 9)

    s.sql("CREATE TABLE dc (x INT, tags ARRAY<STRING>, "
          "m MAP<STRING, STRING>) USING column")
    s.sql("INSERT INTO dc VALUES (1, array('a'), map('u', 'v'))")
    assert s.sql("SELECT element_at(m, 'u') FROM dc").rows() == [("v",)]
    s.sql("ALTER TABLE dc DROP COLUMN x")
    # ordinals shifted: the complex dictionaries must follow
    s.sql("INSERT INTO dc VALUES (array('b'), map('u', 'w'))")
    got = sorted(r[0] for r in
                 s.sql("SELECT element_at(m, 'u') FROM dc").rows())
    assert got == ["v", "w"]
    assert s.sql("SELECT count(*) FROM dc "
                 "WHERE array_contains(tags, 'b')").rows()[0][0] == 1
    s.stop()


def test_struct_device_field_access():
    """Flat STRUCTs bind as per-field plates (string fields as codes):
    element_at field access is a static plate pick in the compiled
    program — filters and aggregates over fields run on device."""
    from snappydata_tpu.observability.metrics import global_registry

    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE sd (id INT, "
          "loc STRUCT<city: STRING, pop: INT>) USING column")
    s.sql("INSERT INTO sd VALUES "
          "(1, named_struct('city', 'oslo', 'pop', 700000)), "
          "(2, named_struct('city', 'bergen', 'pop', 290000)), "
          "(3, NULL)")
    before = global_registry().counter("host_fallbacks")
    r = s.sql("SELECT id, element_at(loc, 'city'), "
              "element_at(loc, 'pop') FROM sd ORDER BY id").rows()
    assert r[0] == (1, "oslo", 700000)
    assert r[1] == (2, "bergen", 290000)
    assert r[2][1] is None and r[2][2] is None
    # field names resolve case-insensitively, like the analyzer
    assert s.sql("SELECT sum(element_at(loc, 'POP')) FROM sd"
                 ).rows()[0][0] == 990000
    assert s.sql("SELECT count(*) FROM sd WHERE "
                 "element_at(loc, 'pop') > 500000").rows()[0][0] == 1
    assert global_registry().counter("host_fallbacks") == before
    # appended values keep stable field-dictionary codes
    s.sql("INSERT INTO sd VALUES "
          "(4, named_struct('city', 'alta', 'pop', 21000))")
    got = s.sql("SELECT element_at(loc, 'city') FROM sd WHERE id IN "
                "(1, 4) ORDER BY id").rows()
    assert [g[0] for g in got] == ["oslo", "alta"]
    # whole-struct SELECT keeps the host path (correct, just not device)
    assert s.sql("SELECT loc FROM sd WHERE id = 1").rows() \
        == [({"city": "oslo", "pop": 700000},)]
    s.stop()


def test_struct_device_persistence(tmp_path):
    d = str(tmp_path / "store")
    s = SnappySession(data_dir=d)
    s.sql("CREATE TABLE sp (id INT, "
          "v STRUCT<name: STRING, x: DOUBLE>) USING column")
    s.sql("INSERT INTO sp VALUES (1, named_struct('name', 'a', 'x', 1.5)),"
          " (2, named_struct('name', 'b', 'x', 2.5))")
    s.checkpoint()
    s.stop()
    s2 = SnappySession(data_dir=d)
    assert s2.sql("SELECT sum(element_at(v, 'x')) FROM sp"
                  ).rows()[0][0] == pytest.approx(4.0)
    assert s2.sql("SELECT element_at(v, 'name') FROM sp ORDER BY id"
                  ).rows() == [("a",), ("b",)]
    s2.stop()


def test_decimal_values_in_complex_types_device():
    """Exact-decimal fields/elements/values inside STRUCT/ARRAY/MAP
    must scale into their int64 plates (review finding, verified:
    1.50 decoded as 0.01 when the raw value truncated into int64)."""
    from decimal import Decimal

    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE dcx (id INT, "
          "st STRUCT<price: DECIMAL(10,2), name: STRING>, "
          "ar ARRAY<DECIMAL(10,2)>, "
          "mp MAP<STRING, DECIMAL(10,2)>) USING column")
    s.sql("INSERT INTO dcx VALUES "
          "(1, named_struct('price', 1.50, 'name', 'a'), "
          "array(1.25, 2.50), map('k', 10.01)), "
          "(2, named_struct('price', 2.25, 'name', 'b'), "
          "array(3.75), map('k', 0.99))")
    r = s.sql("SELECT element_at(st, 'price'), element_at(ar, 1), "
              "element_at(mp, 'k') FROM dcx ORDER BY id").rows()
    assert r[0] == (Decimal("1.50"), Decimal("1.25"), Decimal("10.01"))
    assert r[1] == (Decimal("2.25"), Decimal("3.75"), Decimal("0.99"))
    assert s.sql("SELECT sum(element_at(st, 'price')) FROM dcx"
                 ).rows()[0][0] == Decimal("3.75")
    assert s.sql("SELECT sum(element_at(mp, 'k')) FROM dcx"
                 ).rows()[0][0] == Decimal("11.00")
    # decimal needle in array_contains scales like the elements
    assert s.sql("SELECT count(*) FROM dcx WHERE "
                 "array_contains(ar, 2.50)").rows()[0][0] == 1
    assert s.sql("SELECT count(*) FROM dcx WHERE "
                 "array_contains(ar, 2.51)").rows()[0][0] == 0
    s.stop()
