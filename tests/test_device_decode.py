"""Device decode (round-3 verdict Missing #5 / task 8): RLE and
boolean-bitset batches bind by shipping the ENCODED arrays to the device
and expanding in-trace, with results identical to the host-decode path
and a measured transfer reduction (ref: decode-at-scan generated code,
ColumnTableScan.scala:684 genCodeColumnBuffer)."""

import numpy as np
import pytest

from snappydata_tpu import SnappySession, config
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.storage import device_decode
from snappydata_tpu.storage.encoding import Encoding


def _rle_session():
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE rle_t (k BIGINT, grp BIGINT, flag BOOLEAN, "
          "v DOUBLE) USING column")
    n = 60_000
    rng = np.random.default_rng(3)
    k = np.arange(n, dtype=np.int64)
    grp = np.sort(rng.integers(0, 5, n)).astype(np.int64)   # RLE-friendly
    flag = (k % 3 == 0)
    v = np.round(rng.random(n) * 100, 2)
    s.insert_arrays("rle_t", [k, grp, flag, v])
    data = s.catalog.describe("rle_t").data
    data.force_rollover()            # cut the batch so encodings apply
    return s, k, grp, flag, v, data


def test_rle_batches_decode_on_device_and_match():
    s, k, grp, flag, v, data = _rle_session()
    m = data.snapshot()
    encs = {m.views[0].batch.columns[i].encoding for i in (1, 2)}
    assert Encoding.RUN_LENGTH in encs, "grp should be RLE at rest"
    assert Encoding.BOOLEAN_BITSET in encs, "flag should be bitset at rest"

    device_decode.reset_counters()
    r = s.sql("SELECT grp, count(*), sum(v) FROM rle_t GROUP BY grp "
              "ORDER BY grp")
    c = device_decode.counters()
    assert c["batches_device_decoded"] >= 1
    assert c["bytes_encoded"] < c["bytes_decoded_equiv"] / 4, \
        "encoded transfer should be far below the decoded plate size"
    for gi, cnt, sv in r.rows():
        mm = grp == gi
        assert cnt == int(mm.sum())
        assert sv == pytest.approx(float(v[mm].sum()))

    r2 = s.sql("SELECT count(*) FROM rle_t WHERE flag")
    assert r2.rows()[0][0] == int(flag.sum())
    s.stop()


def test_rle_predicate_pushdown_still_correct():
    s, k, grp, flag, v, _ = _rle_session()
    r = s.sql("SELECT count(*), sum(v) FROM rle_t WHERE grp = 2")
    mm = grp == 2
    assert r.rows()[0][0] == int(mm.sum())
    assert r.rows()[0][1] == pytest.approx(float(v[mm].sum()))
    s.stop()


def test_deltas_fall_back_to_host_decode():
    s, k, grp, flag, v, data = _rle_session()
    s.sql("UPDATE rle_t SET v = 0.0 WHERE k < 100")
    r = s.sql("SELECT sum(v) FROM rle_t")
    expect = float(v[k >= 100].sum())
    assert r.rows()[0][0] == pytest.approx(expect)
    # grouping column updates create deltas on grp itself
    s.sql("UPDATE rle_t SET grp = 99 WHERE k < 50")
    r2 = s.sql("SELECT count(*) FROM rle_t WHERE grp = 99")
    assert r2.rows()[0][0] == 50
    s.stop()


def _valdict_session(n=60_000):
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE vd_t (k BIGINT, qty DOUBLE, price DOUBLE) "
          "USING column")
    rng = np.random.default_rng(7)
    k = np.arange(n, dtype=np.int64)
    qty = rng.integers(1, 51, n).astype(np.float64)   # 50 distinct
    price = np.round(rng.uniform(900.0, 105_000.0, n), 2)  # high-card
    s.insert_arrays("vd_t", [k, qty, price])
    data = s.catalog.describe("vd_t").data
    data.force_rollover()
    return s, k, qty, price, data


def test_value_dict_encodes_low_cardinality_numerics():
    s, k, qty, price, data = _valdict_session()
    m = data.snapshot()
    cols = m.views[0].batch.columns
    assert cols[1].encoding == Encoding.VALUE_DICT
    assert cols[1].data.dtype == np.uint8
    assert sorted(cols[1].dictionary.tolist()) == \
        sorted(set(qty[:cols[1].num_rows].tolist()))
    assert cols[2].encoding == Encoding.PLAIN, "high-card stays plain"
    # ≥4x at-rest shrink vs the plain plate
    assert cols[1].nbytes * 4 <= cols[1].num_rows * 8
    s.stop()


def test_value_dict_decodes_on_device_and_matches():
    s, k, qty, price, _ = _valdict_session()
    device_decode.reset_counters()
    r = s.sql("SELECT qty, count(*), sum(price) FROM vd_t GROUP BY qty "
              "ORDER BY qty")
    c = device_decode.counters()
    assert c["batches_device_decoded"] >= 1
    assert c["bytes_encoded"] < c["bytes_decoded_equiv"] / 4
    for q, cnt, sp in r.rows():
        mm = qty == q
        assert cnt == int(mm.sum())
        assert sp == pytest.approx(float(price[mm].sum()), rel=1e-9)
    # stats-based batch skipping over the dictionary min/max
    r2 = s.sql("SELECT count(*) FROM vd_t WHERE qty = 17.0")
    assert r2.rows()[0][0] == int((qty == 17.0).sum())
    s.stop()


def test_value_dict_update_delta_falls_back_to_host():
    s, k, qty, price, _ = _valdict_session()
    s.sql("UPDATE vd_t SET qty = 999.0 WHERE k < 25")
    r = s.sql("SELECT count(*) FROM vd_t WHERE qty = 999.0")
    assert r.rows()[0][0] == 25
    r2 = s.sql("SELECT sum(qty) FROM vd_t")
    expect = float(qty[25:].sum()) + 25 * 999.0
    assert r2.rows()[0][0] == pytest.approx(expect)
    s.stop()


def test_value_dict_sample_miss_repair_and_nan_guard():
    from snappydata_tpu import types as T
    from snappydata_tpu.storage.encoding import (decode_to_numpy,
                                                 encode_column)

    rng = np.random.default_rng(11)
    # one rare value the stride sample will miss → repair pass catches it
    v = rng.integers(0, 200, 100_000).astype(np.float64)
    v[54_321] = 777.0
    c = encode_column(v, T.DOUBLE)
    assert c.encoding == Encoding.VALUE_DICT
    assert (decode_to_numpy(c) == v).all()
    # NaN is not code-assignable: stays PLAIN
    vn = np.where(rng.random(10_000) < 0.5, np.nan, 1.0)
    assert encode_column(vn, T.DOUBLE).encoding == Encoding.PLAIN
    # >256 distinct 8-byte values: WIDENS to uint16 codes (still a 4x
    # shrink) instead of falling back to PLAIN
    vh = rng.integers(0, 5000, 100_000).astype(np.float64)
    ch = encode_column(vh, T.DOUBLE)
    assert ch.encoding == Encoding.VALUE_DICT
    assert ch.data.dtype == np.uint16
    assert (decode_to_numpy(ch) == vh).all()
    # ...but 4-byte values keep the uint8-only cap (uint16 codes would
    # only halve them, below the 4x bar)
    v4 = rng.integers(0, 5000, 100_000).astype(np.int32)
    assert encode_column(v4, T.INT).encoding == Encoding.PLAIN
    # dictionary too large relative to the rows (n < 8*D): stays PLAIN
    vsmall = rng.integers(0, 5000, 20_000).astype(np.float64)
    assert encode_column(vsmall, T.DOUBLE).encoding == Encoding.PLAIN


def test_value_dict_persists_and_recovers(tmp_path):
    d = str(tmp_path / "vd_store")
    s = SnappySession(catalog=Catalog(), data_dir=d, recover=False)
    s.sql("CREATE TABLE vd_p (k BIGINT, qty DOUBLE) USING column")
    rng = np.random.default_rng(13)
    qty = rng.integers(1, 21, 30_000).astype(np.float64)
    s.insert_arrays("vd_p", [np.arange(30_000, dtype=np.int64), qty])
    s.catalog.describe("vd_p").data.force_rollover()
    s.disk_store.checkpoint(s.catalog)
    s.stop()
    s.disk_store.close()

    s2 = SnappySession(data_dir=d, recover=True)
    m = s2.catalog.describe("vd_p").data.snapshot()
    assert m.views[0].batch.columns[1].encoding == Encoding.VALUE_DICT
    r = s2.sql("SELECT sum(qty), count(*) FROM vd_p").rows()
    assert r[0][1] == 30_000
    assert r[0][0] == pytest.approx(float(qty.sum()))
    s2.stop()
    s2.disk_store.close()


def test_disabled_flag_matches():
    old = config.global_properties().device_decode
    try:
        config.global_properties().device_decode = False
        s, k, grp, flag, v, _ = _rle_session()
        device_decode.reset_counters()
        r = s.sql("SELECT grp, sum(v) FROM rle_t GROUP BY grp ORDER BY grp")
        assert device_decode.counters()["batches_device_decoded"] == 0
        for gi, sv in r.rows():
            assert sv == pytest.approx(float(v[grp == gi].sum()))
        s.stop()
    finally:
        config.global_properties().device_decode = old
