"""Column encodings: PLAIN / DICTIONARY / RUN_LENGTH / BOOLEAN_BITSET.

Behavioral contract follows the reference decoder registry
(encoders/.../encoding/ColumnEncoding.scala:766-774 — Uncompressed,
RunLength, Dictionary, BigDictionary, BooleanBitSet) and the per-batch
stats row (ColumnStatsSchema: min/max/nullCount per column used for
predicate batch-skipping in ColumnTableScan filter codegen).

TPU-first physical design: the encoded form lives on host as numpy; decode
targets a fixed `capacity`-row device plate so XLA compiles one kernel per
table shape. `decode_to_numpy` here is the host decode path (mutation
predicates, mesh binds, delta-bearing batches); cold single-device binds
of RLE/bitset batches instead ship the encoded arrays and expand in-trace
(`storage/device_decode.py`), so compressed bytes — not decoded plates —
cross the host→device link. Strings never reach the device: they stay
dictionary codes (int32) with the dictionary host-side —
group-by/join on strings runs on codes, mirroring the reference's
dictionary fast path (DictionaryOptimizedMapAccessor).
"""

from __future__ import annotations

import dataclasses
import enum
import zlib
from typing import Any, Optional, Tuple

import numpy as np

from snappydata_tpu import types as T


class Encoding(enum.IntEnum):
    PLAIN = 0
    DICTIONARY = 1
    RUN_LENGTH = 2
    BOOLEAN_BITSET = 3
    OBJECT = 4  # raw python objects (ARRAY columns; host-evaluated)
    # low-cardinality NUMERIC columns: uint8 (≤256 distinct) or uint16
    # (≤64K distinct, 8-byte values only — codes stay 4× smaller) codes
    # into a SORTED value dictionary (ref IntDictionary/BigDictionary
    # typeIds) — device binds ship the codes + tiny dictionary and
    # either gather in-trace (device_decode.valdict_views_to_plate) or
    # stay resident as a code plate under compressed-domain execution
    # (device_decode.CodePlate), where predicates compare codes against
    # literals translated through the sorted dictionary
    VALUE_DICT = 5


@dataclasses.dataclass(frozen=True)
class ColumnStats:
    """Per-batch column stats (ref stats row, meta column index -1)."""

    min: Any
    max: Any
    null_count: int
    count: int

    @staticmethod
    def of(values: np.ndarray, validity: Optional[np.ndarray]) -> "ColumnStats":
        if validity is not None:
            valid = values[validity]
            nulls = int(values.shape[0] - valid.shape[0])
        else:
            valid = values
            nulls = 0
        if valid.size == 0:
            return ColumnStats(None, None, nulls, int(values.shape[0]))
        if valid.dtype == object:
            if valid.shape[0] > 1024:
                import pandas as pd

                s = pd.Series(valid, dtype=object).dropna()
                nulls += int(valid.shape[0] - s.shape[0])
                if s.empty:
                    return ColumnStats(None, None, nulls,
                                       int(values.shape[0]))
                lo, hi = s.min(), s.max()
                return ColumnStats(lo, hi, nulls, int(values.shape[0]))
            non_null = [v for v in valid.tolist() if v is not None]
            nulls += len(valid) - len(non_null)
            if not non_null:
                return ColumnStats(None, None, nulls, int(values.shape[0]))
            lo, hi = min(non_null), max(non_null)
        else:
            lo, hi = valid.min(), valid.max()
            lo = lo.item() if hasattr(lo, "item") else lo
            hi = hi.item() if hasattr(hi, "item") else hi
        return ColumnStats(lo, hi, nulls, int(values.shape[0]))


@dataclasses.dataclass(frozen=True)
class EncodedColumn:
    """Host-resident encoded column of one batch. Immutable."""

    encoding: Encoding
    dtype: T.DataType
    num_rows: int
    # PLAIN: data = values (device dtype); DICTIONARY: data = int32 codes
    # RUN_LENGTH: data = run values, runs = int32 run lengths
    # BOOLEAN_BITSET: data = packed uint8 bits
    data: np.ndarray
    dictionary: Optional[np.ndarray] = None   # DICTIONARY only (host values)
    runs: Optional[np.ndarray] = None         # RUN_LENGTH only
    validity: Optional[np.ndarray] = None     # packed uint8 bits; None = no nulls
    stats: Optional[ColumnStats] = None

    @property
    def nbytes(self) -> int:
        n = self.data.nbytes if self.data.dtype != object else self.data.size * 16
        for a in (self.dictionary, self.runs, self.validity):
            if a is not None and a.dtype != object:
                n += a.nbytes
        return n


def _device_np_dtype(dtype: T.DataType) -> np.dtype:
    if dtype.name == "decimal":
        # at-rest decimal bytes stay in the HOST (plain float64) domain:
        # the exact path's scaled-int64 form is produced at device bind
        # (types.DecimalType docstring) — encoding at device_dtype here
        # would TRUNCATE values through the int64 cast
        return dtype.np_dtype
    return dtype.device_dtype()


def encode_column(values: np.ndarray, dtype: T.DataType,
                  validity: Optional[np.ndarray] = None,
                  dictionary_hint: Optional[np.ndarray] = None) -> EncodedColumn:
    """Pick an encoding the way the reference's ColumnEncoder typeId
    selection does: strings always dictionary; low-cardinality fixed-width →
    RLE when it actually shrinks; booleans → bitset; else plain.

    `dictionary_hint` forces a shared (table-level) dictionary so codes are
    comparable across batches without re-mapping — the property the
    reference gets from its per-batch dictionaries plus codegen string
    compare, and that we need globally for device-side group-by on codes.
    """
    n = int(values.shape[0])
    if dtype.name in ("array", "map"):
        # raw object storage; queries over complex columns run host-side
        obj = np.asarray(values, dtype=object)
        nulls_mask = np.fromiter((v is None for v in obj), dtype=np.bool_,
                                 count=n)
        packed = None
        if validity is not None:
            nulls_mask |= ~np.asarray(validity)
        if nulls_mask.any():
            from snappydata_tpu.storage import bitmask

            packed = bitmask.pack(~nulls_mask)
        return EncodedColumn(Encoding.OBJECT, dtype, n, obj,
                             validity=packed,
                             stats=ColumnStats(None, None,
                                               int(nulls_mask.sum()), n))
    if dtype.name == "string" and validity is None:
        # derive validity from SQL NULL (None) values (vectorized)
        nulls = np.asarray(values) == None  # noqa: E711 elementwise
        if nulls.any():
            validity = ~nulls
    packed_validity = None
    if validity is not None and not validity.all():
        from snappydata_tpu.storage import bitmask

        packed_validity = bitmask.pack(validity)
    else:
        validity = None
    if dtype.name in ("string", "array", "map", "struct"):
        # no min/max for strings (predicates run through dictionary LUTs)
        # or complex values (dicts aren't even orderable) — stats-based
        # batch skipping never applies to them
        nulls = int((~validity).sum()) if validity is not None else 0
        stats = ColumnStats(None, None, nulls, n)
    else:
        stats = ColumnStats.of(values, validity)

    if dtype.name == "string":
        if dictionary_hint is not None:
            dictionary = dictionary_hint
            if n > 1024:
                # vectorized code assignment (C-side hash join)
                import pandas as pd

                obj = np.asarray(values, dtype=object)
                codes = pd.Categorical(
                    obj, categories=dictionary).codes.astype(np.int32)
                missing = codes < 0
                if missing.any():
                    # only NULLs may be absent from the hint; a real value
                    # missing means a broken interning invariant — fail
                    # loudly like the small-batch path (review finding)
                    bad = missing & ~pd.isna(obj)
                    if bad.any():
                        raise KeyError(
                            f"value not in dictionary hint: "
                            f"{obj[bad][:3].tolist()}")
                    codes = np.where(missing, 0, codes)
            else:
                lookup = {v: i for i, v in enumerate(dictionary.tolist())}
                codes = np.fromiter(
                    (lookup[v] if v is not None else 0 for v in values),
                    dtype=np.int32, count=n)
        else:
            vals_list = values.tolist()
            filler = next((v for v in vals_list if v is not None), "")
            cleaned = np.array([filler if v is None else v for v in vals_list],
                               dtype=object)
            dictionary, codes = np.unique(cleaned, return_inverse=True)
            codes = codes.astype(np.int32)
        return EncodedColumn(Encoding.DICTIONARY, dtype, n, codes,
                             dictionary=dictionary, validity=packed_validity,
                             stats=stats)

    if dtype.name == "boolean":
        from snappydata_tpu.storage import bitmask

        return EncodedColumn(Encoding.BOOLEAN_BITSET, dtype, n,
                             bitmask.pack(values.astype(np.bool_)),
                             validity=packed_validity, stats=stats)

    dev = values.astype(_device_np_dtype(dtype), copy=False)
    # RLE probe: cheap run-length count; accept if ≥4x shrink (ref
    # RunLengthEncoding targets low-cardinality columns).
    if n > 64:
        changes = np.flatnonzero(dev[1:] != dev[:-1])
        num_runs = changes.size + 1
        if num_runs * 2 <= n // 4:
            starts = np.concatenate(([0], changes + 1))
            ends = np.concatenate((changes + 1, [n]))
            return EncodedColumn(
                Encoding.RUN_LENGTH, dtype, n, dev[starts].copy(),
                runs=(ends - starts).astype(np.int32),
                validity=packed_validity, stats=stats)
        vd = _try_value_dict(dev, dtype, n, packed_validity, stats)
        if vd is not None:
            return vd
    return EncodedColumn(Encoding.PLAIN, dtype, n, np.ascontiguousarray(dev),
                         validity=packed_validity, stats=stats)


# value-dict acceptance: codes must stay ≥4x smaller than the values
# they replace — uint8 codes for any ≥4-byte value (≤256 distinct), and
# uint16 codes (≤64K distinct) only for 8-byte values (f64/i64: 2-byte
# codes keep the 4x shrink).  A SAMPLE probe rejects high-cardinality
# columns in O(sample) so the ingest hot lane never pays a full-column
# unique for columns that won't encode.
_VALUE_DICT_MAX_U8 = 256
_VALUE_DICT_MAX = 1 << 16
_VALUE_DICT_SAMPLE = 4096


def _value_dict_cap(itemsize: int) -> int:
    """Distinct-value ceiling keeping the ≥4x code shrink."""
    return _VALUE_DICT_MAX if itemsize >= 8 else _VALUE_DICT_MAX_U8


def _value_dict_code_dtype(num_distinct: int) -> np.dtype:
    return np.dtype(np.uint8 if num_distinct <= _VALUE_DICT_MAX_U8
                    else np.uint16)


def _try_value_dict(dev: np.ndarray, dtype: T.DataType, n: int,
                    packed_validity, stats) -> Optional["EncodedColumn"]:
    if dev.dtype.itemsize < 4 or dev.dtype.kind not in "iuf":
        return None   # sub-4-byte values wouldn't shrink 4x
    cap = _value_dict_cap(dev.dtype.itemsize)
    sample = dev[::max(1, n // _VALUE_DICT_SAMPLE)]
    cand = np.unique(sample)
    # the dictionary must be SMALL relative to the rows (n ≥ 8·D) or the
    # dict bytes eat the shrink; the sample's distinct count is a lower
    # bound on D, so this also rejects early
    if cand.size > cap or n < 8 * cand.size:
        return None
    if dev.dtype.kind == "f" and np.isnan(cand).any():
        return None   # NaN breaks searchsorted code assignment
    # code against the sample dictionary, then repair the (rare) values
    # the sample missed — for a truly low-cardinality column the repair
    # set is tiny, so total cost stays O(n log D)
    for _ in range(2):
        codes = np.searchsorted(cand, dev)
        codes_c = np.minimum(codes, cand.size - 1)
        missed = cand[codes_c] != dev
        if not missed.any():
            return EncodedColumn(
                Encoding.VALUE_DICT, dtype, n,
                codes_c.astype(_value_dict_code_dtype(cand.size)),
                dictionary=cand,
                validity=packed_validity, stats=stats)
        extra = np.unique(dev[missed])
        if dev.dtype.kind == "f" and np.isnan(extra).any():
            return None
        cand = np.union1d(cand, extra)
        if cand.size > cap or n < 8 * cand.size:
            return None
    return None   # pragma: no cover - two passes always converge


def decode_to_numpy(col: EncodedColumn, capacity: Optional[int] = None,
                    strings: bool = False) -> np.ndarray:
    """Decode to a host array padded to `capacity` rows (device dtype).

    With strings=True a DICTIONARY string column decodes to the actual
    object values (host-side paths: mutation predicates, result assembly);
    otherwise it yields int32 codes, the on-device representation.
    """
    n = col.num_rows
    cap = capacity if capacity is not None else n
    if col.encoding == Encoding.PLAIN:
        out = col.data
    elif col.encoding == Encoding.DICTIONARY:
        out = col.dictionary[col.data] if strings else col.data
    elif col.encoding == Encoding.VALUE_DICT:
        out = col.dictionary[col.data]
    elif col.encoding == Encoding.RUN_LENGTH:
        out = np.repeat(col.data, col.runs)
    elif col.encoding == Encoding.OBJECT:
        out = col.data
    elif col.encoding == Encoding.BOOLEAN_BITSET:
        from snappydata_tpu.storage import bitmask

        out = bitmask.unpack(col.data, n)
    else:  # pragma: no cover
        raise ValueError(f"unknown encoding {col.encoding}")
    if cap > n:
        if out.dtype == object:
            pad = np.full(cap - n, None, dtype=object)
        else:
            pad = np.zeros(cap - n, dtype=out.dtype)
        out = np.concatenate([out, pad])
    return out


def decode_validity(col: EncodedColumn, capacity: Optional[int] = None) -> Optional[np.ndarray]:
    if col.validity is None:
        return None
    from snappydata_tpu.storage import bitmask

    v = bitmask.unpack(col.validity, col.num_rows)
    cap = capacity if capacity is not None else col.num_rows
    if cap > col.num_rows:
        v = np.concatenate([v, np.zeros(cap - col.num_rows, dtype=np.bool_)])
    return v


# --- at-rest compression (ref: CompressionUtils LZ4/Snappy; env has zlib) ---

_zstd_available: Optional[bool] = None


def _have_zstd() -> bool:
    global _zstd_available
    if _zstd_available is None:
        try:
            import zstandard  # noqa: F401

            _zstd_available = True
        except ImportError:
            _zstd_available = False
    return _zstd_available


def compress_bytes(raw: bytes, codec: str) -> Tuple[str, bytes]:
    if codec == "zstd":
        if _have_zstd():
            import zstandard

            return "zstd", zstandard.ZstdCompressor(level=1).compress(raw)
        # zstandard not installed: degrade to the stdlib codec instead of
        # failing every WAL append / checkpoint on this machine (each
        # record tags the codec actually used, so mixed files read fine)
        codec = "zlib"
    if codec == "zlib":
        return "zlib", zlib.compress(raw, level=1)
    return "none", raw


def decompress_bytes(codec: str, blob: bytes) -> bytes:
    if codec == "zstd":
        import zstandard

        return zstandard.ZstdDecompressor().decompress(blob)
    if codec == "zlib":
        return zlib.decompress(blob)
    return blob
