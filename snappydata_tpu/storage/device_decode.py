"""In-trace device decode: encoded bytes cross the PCIe/DMA link, the
decode to capacity-row plates happens on the accelerator.

Reference parity: the reference decodes dictionary/RLE/delta INSIDE the
generated scan code at batch-read time (ColumnTableScan.scala:684
genCodeColumnBuffer), so encodings save memory end to end. Here the
equivalents are vectorized XLA programs applied at cold bind:

* RUN_LENGTH: upload (run_values [R], run_end_offsets [R]) and expand to
  the plate with a vmapped searchsorted-gather — the batched form of
  `jnp.repeat(values, runs, total_repeat_length=cap)`. Transfer shrinks
  from cap×itemsize to 2×R×itemsize (R = #runs).
* BOOLEAN_BITSET: upload the packed bits (uint8 [cap/8]) and unpack with
  shift/mask ops — an 8× transfer reduction.
* VALUE_DICT: low-cardinality numeric columns upload uint8 codes [cap]
  plus the tiny value dictionary [D] and gather on device — an
  itemsize× (≥4×) transfer reduction. This is the encoding the default
  TPC-H scan engages (l_quantity/l_discount/l_tax are 50/11/9 distinct
  f64 values), so the bench's device_decode counters are nonzero on the
  stock workload.

Dictionary string columns need no device decode: their int32 codes ARE
the on-device representation (group-by/join run on codes). Batches with
update deltas take the host decode path — the delta merge is host-side
state.

Lanes past a batch's last run decode to the final run's value rather
than zero; every consumer masks by the table validity plate, so padding
content is unobservable (same contract as the zero padding of host
decode).
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

# bind-transfer accounting (powers the bench/device-decode metric and the
# tests' "compressed bytes actually crossed the link" assertion)
_counters: Dict[str, int] = {"bytes_encoded": 0, "bytes_decoded_equiv": 0,
                             "batches_device_decoded": 0}


def counters() -> Dict[str, int]:
    return dict(_counters)


def reset_counters() -> None:
    for k in _counters:
        _counters[k] = 0


@functools.partial(jax.jit, static_argnames=("cap",))
def _rle_expand(values: jnp.ndarray, ends: jnp.ndarray, cap: int):
    """values/ends: [N, R] (R padded; unused runs carry end=last_end).
    Returns [N, cap] plates: lane j takes values[searchsorted(ends, j,
    'right')] — the run whose half-open [prev_end, end) interval holds j.
    """
    pos = jnp.arange(cap, dtype=ends.dtype)

    def one(vals, end):
        seg = jnp.searchsorted(end, pos, side="right")
        seg = jnp.minimum(seg, vals.shape[0] - 1)
        return vals[seg]

    return jax.vmap(one)(values, ends)


@functools.partial(jax.jit, static_argnames=("cap",))
def _bitset_expand(packed: jnp.ndarray, cap: int):
    """packed: [N, ceil(cap/8)] uint8 (LSB-first, numpy packbits
    bitorder='little') → bool [N, cap]."""
    idx = jnp.arange(cap)
    byte = packed[:, idx // 8]
    return ((byte >> (idx % 8).astype(jnp.uint8)) & 1).astype(jnp.bool_)


def rle_views_to_plate(rle_cols, cap: int, dt) -> jnp.ndarray:
    """Stack N encoded RLE columns into device plates [N, cap].

    `rle_cols`: list of EncodedColumn with .data (run values) and .runs
    (run lengths). Returns the decoded [N, cap] device array."""
    r_max = max(1, max(len(c.data) for c in rle_cols))
    n = len(rle_cols)
    vals = np.zeros((n, r_max), dtype=dt)
    ends = np.zeros((n, r_max), dtype=np.int64)
    for i, c in enumerate(rle_cols):
        r = len(c.data)
        vals[i, :r] = c.data
        e = np.cumsum(c.runs, dtype=np.int64)
        ends[i, :r] = e
        if r < r_max:
            vals[i, r:] = vals[i, r - 1] if r else 0
            ends[i, r:] = e[-1] if r else 0
        _counters["bytes_encoded"] += int(vals[i].nbytes + ends[i].nbytes)
        _counters["bytes_decoded_equiv"] += int(cap * vals.dtype.itemsize)
        _counters["batches_device_decoded"] += 1
    return _rle_expand(jnp.asarray(vals), jnp.asarray(ends), cap)


@jax.jit
def _valdict_expand(codes: jnp.ndarray, dicts: jnp.ndarray):
    """codes: [N, cap] uint8; dicts: [N, D] (D padded per call).  Lane j
    of row i takes dicts[i, codes[i, j]] — a per-batch device gather."""
    return jnp.take_along_axis(dicts, codes.astype(jnp.int32), axis=1)


def valdict_views_to_plate(vd_cols, cap: int, dt) -> jnp.ndarray:
    """Stack N value-dict columns into decoded plates [N, cap]: the
    uint8 codes and the (padded) dictionaries cross the link, the
    values-gather runs in-trace."""
    d_max = max(1, max(len(c.dictionary) for c in vd_cols))
    n = len(vd_cols)
    codes = np.zeros((n, cap), dtype=np.uint8)
    dicts = np.zeros((n, d_max), dtype=dt)
    for i, c in enumerate(vd_cols):
        codes[i, :c.data.shape[0]] = c.data
        d = np.asarray(c.dictionary, dtype=dt)
        dicts[i, :d.shape[0]] = d
        _counters["bytes_encoded"] += int(c.data.nbytes + d.nbytes)
        _counters["bytes_decoded_equiv"] += int(cap * dicts.dtype.itemsize)
        _counters["batches_device_decoded"] += 1
    return _valdict_expand(jnp.asarray(codes), jnp.asarray(dicts))


def bitset_views_to_plate(bit_cols, cap: int) -> jnp.ndarray:
    """Stack N boolean-bitset columns into decoded bool plates [N, cap]."""
    nbytes = (cap + 7) // 8
    n = len(bit_cols)
    packed = np.zeros((n, nbytes), dtype=np.uint8)
    for i, c in enumerate(bit_cols):
        raw = np.asarray(c.data, dtype=np.uint8)
        packed[i, :raw.shape[0]] = raw
        _counters["bytes_encoded"] += int(raw.nbytes)
        _counters["bytes_decoded_equiv"] += int(cap)
        _counters["batches_device_decoded"] += 1
    return _bitset_expand(jnp.asarray(packed), cap)
