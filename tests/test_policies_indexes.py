"""Row-level security policies + secondary indexes (ref: CREATE POLICY /
RowLevelSecurity rule; CreateIndexTest; ExecutionEngineArbiter point
routing)."""

import numpy as np
import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.observability.metrics import global_registry


@pytest.fixture()
def s():
    sess = SnappySession(catalog=Catalog())
    yield sess
    sess.stop()


def test_policy_filters_scans(s):
    s.sql("CREATE TABLE accounts (id INT, region STRING, bal DOUBLE) "
          "USING column")
    s.sql("INSERT INTO accounts VALUES (1, 'us', 10.0), (2, 'eu', 20.0), "
          "(3, 'us', 30.0)")
    assert s.sql("SELECT count(*) FROM accounts").rows()[0][0] == 3
    s.sql("CREATE POLICY us_only ON accounts USING region = 'us'")
    assert s.sql("SELECT count(*) FROM accounts").rows()[0][0] == 2
    assert s.sql("SELECT sum(bal) FROM accounts").rows()[0][0] == 40.0
    # applies through joins and aliases too
    s.sql("CREATE TABLE regions (r STRING) USING column")
    s.sql("INSERT INTO regions VALUES ('us'), ('eu')")
    out = s.sql("SELECT count(*) FROM accounts a JOIN regions g "
                "ON a.region = g.r")
    assert out.rows()[0][0] == 2
    s.sql("DROP POLICY us_only")
    assert s.sql("SELECT count(*) FROM accounts").rows()[0][0] == 3


def test_policy_composition(s):
    s.sql("CREATE TABLE t (a INT) USING column")
    s.sql("INSERT INTO t VALUES (1), (5), (9)")
    s.sql("CREATE POLICY p1 ON t USING a > 2")
    s.sql("CREATE POLICY p2 ON t USING a < 8")
    assert s.sql("SELECT a FROM t").rows() == [(5,)]


def test_secondary_index_point_path(s):
    s.sql("CREATE TABLE users (id INT PRIMARY KEY, email STRING, "
          "org INT) USING row")
    s.sql("INSERT INTO users VALUES (1, 'a@x.com', 10), (2, 'b@x.com', 10), "
          "(3, 'c@y.com', 20)")
    s.sql("CREATE INDEX by_org ON users (org)")
    before = global_registry().counter("point_lookups")
    out = s.sql("SELECT id, email FROM users WHERE org = 10")
    assert sorted(r[0] for r in out.rows()) == [1, 2]
    # PK equality also routes through the fast path
    out = s.sql("SELECT email FROM users WHERE id = 3")
    assert out.rows() == [("c@y.com",)]
    assert global_registry().counter("point_lookups") >= before + 2
    # index stays correct across mutations
    s.sql("PUT INTO users VALUES (4, 'd@y.com', 20)")
    s.sql("DELETE FROM users WHERE id = 3")
    out = s.sql("SELECT id FROM users WHERE org = 20")
    assert [r[0] for r in out.rows()] == [4]
    s.sql("DROP INDEX by_org")
    out = s.sql("SELECT id FROM users WHERE org = 10")  # engine path now
    assert sorted(r[0] for r in out.rows()) == [1, 2]


def test_index_on_column_table_rejected(s):
    s.sql("CREATE TABLE c (a INT) USING column")
    with pytest.raises(Exception, match="row tables"):
        s.sql("CREATE INDEX i ON c (a)")


def test_policy_applies_through_views(s):
    s.sql("CREATE TABLE t (k INT, region STRING) USING row")
    s.sql("INSERT INTO t VALUES (1, 'east'), (2, 'west')")
    s.sql("CREATE VIEW v AS SELECT * FROM t")
    s.sql("CREATE POLICY p ON t USING region = 'east'")
    assert s.sql("SELECT k FROM t").rows() == [(1,)]
    assert s.sql("SELECT k FROM v").rows() == [(1,)]  # no view bypass
    s.sql("DROP POLICY p")
    assert len(s.sql("SELECT k FROM v").rows()) == 2  # applies at query time


def test_point_path_contradictory_equalities(s):
    s.sql("CREATE TABLE pt (k INT PRIMARY KEY, v STRING) USING row")
    s.sql("INSERT INTO pt VALUES (1, 'a'), (2, 'b')")
    assert s.sql("SELECT * FROM pt WHERE k = 1 AND k = 2").rows() == []
    assert s.sql("SELECT * FROM pt WHERE k = 1 AND k = 1").rows() == \
        [(1, "a")]


def test_drop_table_cascades_policies_and_indexes(s):
    s.sql("CREATE TABLE dt (a INT, b INT) USING row")
    s.sql("CREATE POLICY dp ON dt USING a < 5")
    s.sql("CREATE INDEX di ON dt (b)")
    s.sql("DROP TABLE dt")
    s.sql("CREATE TABLE dt (c INT) USING column")
    s.sql("INSERT INTO dt VALUES (9)")
    assert s.sql("SELECT * FROM dt").rows() == [(9,)]  # no ghost policy
    assert "di" not in getattr(s.catalog, "_indexes", {})


def test_policy_index_name_collision_persists_both(tmp_path):
    s = SnappySession(catalog=Catalog(), data_dir=str(tmp_path),
                      recover=False)
    s.sql("CREATE TABLE t (a INT, region STRING) USING row")
    s.sql("INSERT INTO t VALUES (1, 'east'), (2, 'west')")
    s.sql("CREATE POLICY shared ON t USING region = 'east'")
    s.sql("CREATE INDEX shared ON t (a)")
    s.disk_store.close()
    s2 = SnappySession(data_dir=str(tmp_path))
    assert s2.sql("SELECT count(*) FROM t").rows()[0][0] == 1  # policy alive
    assert "shared" in s2.catalog._indexes


def test_policy_and_index_survive_restart(tmp_path):
    s = SnappySession(catalog=Catalog(), data_dir=str(tmp_path),
                      recover=False)
    s.sql("CREATE TABLE t (a INT, region STRING) USING row")
    s.sql("INSERT INTO t VALUES (1, 'us'), (2, 'eu')")
    s.sql("CREATE POLICY p ON t USING region = 'us'")
    s.sql("CREATE INDEX i ON t (a)")
    s.disk_store.close()
    s2 = SnappySession(data_dir=str(tmp_path))
    assert s2.sql("SELECT count(*) FROM t").rows()[0][0] == 1
    assert "i" in s2.catalog._indexes
