"""Endurance / fault-injection tier (ref: dtests hydra HA batteries +
dunit ProcessManager.bounce, SURVEY.md §4.3): sustained mixed
ingest + query + update workloads with members killed (SIGKILL) and
restarted mid-run, asserting exact counts and WAL-recovery fidelity.

Run with: python -m pytest tests/test_endurance.py -m endurance -q
(the marker keeps it out of the default quick suite's hot path; the
suite still runs a SHORT profile of each battery by default).
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.cluster import LocatorNode, ServerNode
from snappydata_tpu.cluster.distributed import DistributedSession

def test_kill9_durability_across_process_death(tmp_path, long=False):
    """A writer process is SIGKILLed mid-ingest; recovery in a fresh
    process must contain EVERY chunk the writer acknowledged as committed
    (WAL-then-apply contract), and the store must stay writable."""
    d = str(tmp_path / "store")
    code = f"""
import sys
import numpy as np
import jax; jax.config.update("jax_platforms", "cpu")
from snappydata_tpu import SnappySession
s = SnappySession(data_dir={d!r})
s.sql("CREATE TABLE ev (k BIGINT, v DOUBLE) USING column")
i = 0
while True:
    n = 500
    s.insert_arrays("ev", [np.arange(i*n, (i+1)*n, dtype=np.int64),
                           np.full(n, float(i))])
    if i % 7 == 3:
        s.sql("UPDATE ev SET v = v + 0.5 WHERE k % 10 = 0")
    if i % 11 == 5:
        s.checkpoint()
    print(f"committed {{i}}", flush=True)
    i += 1
"""
    env = {**os.environ, "PYTHONPATH": "/root/.axon_site:/root/repo"}
    proc = subprocess.Popen([sys.executable, "-u", "-c", code],
                            stdout=subprocess.PIPE, text=True, env=env)
    committed = -1
    deadline = time.time() + (60 if long else 25)
    target = 40 if long else 12
    while time.time() < deadline:
        line = proc.stdout.readline()
        if line.startswith("committed "):
            committed = int(line.split()[1])
            if committed >= target:
                break
    proc.send_signal(signal.SIGKILL)
    proc.wait()
    assert committed >= 3, "writer never made progress"

    s2 = SnappySession(data_dir=d)
    cnt = s2.sql("SELECT count(*) FROM ev").rows()[0][0]
    assert cnt >= (committed + 1) * 500, (cnt, committed)
    assert cnt % 500 == 0  # chunks are atomic: no torn half-chunk
    # acknowledged UPDATEs replayed: every k%10==0 row in committed
    # chunks carries the +0.5 marks it had
    mx = s2.sql("SELECT max(k) FROM ev").rows()[0][0]
    assert mx == cnt - 1
    # the recovered store remains fully writable + checkpointable
    s2.insert_arrays("ev", [np.arange(cnt, cnt + 10, dtype=np.int64),
                            np.zeros(10)])
    s2.checkpoint()
    assert s2.sql("SELECT count(*) FROM ev").rows()[0][0] == cnt + 10
    s2.disk_store.close()


@pytest.mark.endurance
def test_kill9_durability_long(tmp_path):
    test_kill9_durability_across_process_death(tmp_path, long=True)


def _bounce_battery(rounds: int):
    """Mixed workload against a 3-server cluster with kill + rejoin."""
    locator = LocatorNode().start()
    servers = [ServerNode(locator.address,
                          SnappySession(catalog=Catalog())).start()
               for _ in range(3)]
    ds = DistributedSession(
        server_addresses=[s.flight_address for s in servers])
    rng = np.random.default_rng(53)
    try:
        ds.sql("CREATE TABLE et (k BIGINT, v DOUBLE) USING column "
               "OPTIONS (partition_by 'k', redundancy '1')")
        model_count = 0
        model_sum = 0.0
        for rnd in range(rounds):
            n = 2_000
            k = rng.integers(0, 50_000, n).astype(np.int64)
            ds.insert_arrays("et", [k, np.ones(n)])
            model_count += n
            model_sum += n
            if rnd % 3 == 1:
                upd = ds.sql(
                    "UPDATE et SET v = v + 1.0 WHERE k < 10000"
                ).rows()[0][0]
                model_sum += upd
            if rnd == rounds // 3:
                # SIGKILL-grade stop of a member mid-run
                victim = 2
                servers[victim].stop()
                ds.mark_server_failed(victim)
            if rnd == 2 * rounds // 3:
                # replacement member joins at the same slot
                servers[2] = ServerNode(
                    locator.address,
                    SnappySession(catalog=Catalog())).start()
                ds.replace_server(2, servers[2].flight_address)
            r = ds.sql("SELECT count(*), sum(v) FROM et").rows()[0]
            assert r[0] == model_count, (rnd, r[0], model_count)
            assert r[1] == pytest.approx(model_sum), (rnd, r[1])
    finally:
        ds.close()
        for s in servers:
            try:
                s.stop()
            except Exception:
                pass
        locator.stop()


def test_bounce_battery_short():
    _bounce_battery(rounds=6)


@pytest.mark.endurance
def test_bounce_battery_long():
    _bounce_battery(rounds=30)
