"""Seeded chaos harness (the tentpole's acceptance battery): randomized
fault schedules driven by the failpoint registry against (a) a durable
single-node store and (b) a real 3-node cluster, asserting the core
invariants the hardening must hold:

  - no acknowledged row is lost across crash-recovery;
  - no mutation is ever double-applied (blind retry is forbidden on the
    at-most-once paths);
  - replicas converge after failover — queries stay complete;
  - recovery is idempotent (boot twice → identical state);
  - fan-out retries are bounded and separated by backoff;
  - ≥ 50 faults actually fire across WAL, RPC, and heartbeat failpoints
    on the 3-node cluster (asserted via the fault_injected counter).

Schedules are SEEDED (registry RNG + python Random) so a failing run
replays exactly. The quick schedules here run in tier-1; the long
randomized battery at the bottom is additionally marked `slow`.
"""

import random
import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.chaos

from snappydata_tpu import SnappySession, fault
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.observability.metrics import global_registry


@pytest.fixture(autouse=True)
def _clean_registry():
    fault.clear()
    yield
    fault.clear()


# -----------------------------------------------------------------------
# single-node durability chaos
# -----------------------------------------------------------------------

def _run_durability_schedule(tmp_path, seed: int, n_ops: int):
    """Seeded insert/checkpoint stream with torn-write / raise faults on
    wal.append and checkpoint.write; every fault is treated as a crash
    (store reopened). Returns the set of ACKED keys."""
    rng = random.Random(seed)
    fault.reseed(seed)
    d = str(tmp_path)
    s = SnappySession(catalog=Catalog(), data_dir=d, recover=False)
    s.sql("CREATE TABLE t (k BIGINT, v DOUBLE) USING column")
    acked = []

    def crash_and_recover(old):
        try:
            old.disk_store.close()
        except Exception:
            pass
        return SnappySession(data_dir=d, recover=True)

    for i in range(n_ops):
        r = rng.random()
        if r < 0.15:
            fault.arm("wal.append", "torn_write",
                      param=rng.randint(1, 40), count=1)
        elif r < 0.25:
            fault.arm("wal.append", "raise", count=1)
        elif r < 0.33:
            fault.arm("checkpoint.write", "torn_write",
                      param=rng.randint(1, 60), count=1)
        try:
            s.sql(f"INSERT INTO t VALUES ({i}, {i}.5)")
            acked.append(i)
        except Exception:
            s = crash_and_recover(s)
            got = {r0[0] for r0 in s.sql("SELECT k FROM t").rows()}
            assert set(acked) <= got, \
                f"acked rows lost mid-schedule: {set(acked) - got}"
        if rng.random() < 0.2:
            try:
                s.checkpoint()
            except Exception:
                s = crash_and_recover(s)
    fault.clear()
    s.disk_store.close()
    # final recovery: exactly the acked set — nothing lost, nothing
    # double-applied (count equality catches duplicates)
    s2 = SnappySession(data_dir=d, recover=True)
    rows = s2.sql("SELECT k FROM t ORDER BY k").rows()
    assert [r[0] for r in rows] == sorted(acked)
    s2.disk_store.close()
    # recovery is idempotent: a second boot sees the identical state
    s3 = SnappySession(data_dir=d, recover=True)
    assert s3.sql("SELECT k FROM t ORDER BY k").rows() == rows
    s3.disk_store.close()
    return set(acked)


def test_chaos_durability_quick(tmp_path):
    before = global_registry().counter("fault_injected")
    acked = _run_durability_schedule(tmp_path, seed=20260803, n_ops=60)
    injected = global_registry().counter("fault_injected") - before
    assert injected >= 10, f"schedule only injected {injected} faults"
    assert len(acked) >= 20       # the system made real progress too


def test_chaos_mid_group_commit_schedule(tmp_path):
    """Seeded chaos over the GROUP COMMIT drain: torn-write / raise
    faults on wal.group_commit (the mid-group crash shape) and torn
    writes on wal.append, while 4 concurrent committers stream inserts
    in `group` mode. Invariants after every crash-recovery:

      - every ACKED key survives (acks gate on the covering fsync);
      - nothing double-applies (count == count distinct);
      - the unacked group tail truncates as a crash TEAR, never counted
        as corruption (wal_corrupt_records untouched)."""
    from snappydata_tpu import config
    from snappydata_tpu.catalog import Catalog as _Cat

    seed = 20260803
    rng = random.Random(seed)
    fault.reseed(seed)
    props = config.global_properties()
    saved_mode = props.get("wal_fsync_mode")
    props.set("wal_fsync_mode", "group")
    d = str(tmp_path)
    corrupt_before = global_registry().counter("wal_corrupt_records")
    injected_before = global_registry().counter("fault_injected")
    acked = set()
    lock = threading.Lock()
    try:
        s = SnappySession(catalog=_Cat(), data_dir=d, recover=False)
        s.sql("CREATE TABLE t (k BIGINT) USING column")
        for rnd in range(6):
            sess = s
            stop = threading.Event()

            def committer(w, sess=sess, rnd=rnd):
                i = rnd * 100_000 + w * 10_000
                while not stop.is_set():
                    i += 1
                    try:
                        sess.sql(f"INSERT INTO t VALUES ({i})")
                        with lock:
                            acked.add(i)
                    except Exception:
                        return   # crash-shaped failure: worker stops
            threads = [threading.Thread(target=committer, args=(w,))
                       for w in range(4)]
            base_acked = len(acked)
            for t in threads:
                t.start()
            # progress-based window (not a fixed sleep): arm the fault
            # only after real commits landed, so the ≥-progress floor
            # below holds even on a heavily contended machine
            deadline = time.time() + 10.0
            while len(acked) < base_acked + 8 and time.time() < deadline:
                time.sleep(0.005)
            r = rng.random()
            if r < 0.4:
                fault.arm("wal.group_commit", "torn_write",
                          param=rng.randint(1, 80), count=1)
            elif r < 0.7:
                fault.arm("wal.group_commit", "raise", count=1)
            else:
                fault.arm("wal.append", "torn_write",
                          param=rng.randint(1, 40), count=1)
            time.sleep(0.05)
            stop.set()
            for t in threads:
                t.join(timeout=10)
            assert not any(t.is_alive() for t in threads), \
                "a committer hung on its ack"
            fault.clear()
            # crash + recover; every acked key must be there, exactly once
            try:
                s.disk_store.close()
            except Exception:
                pass
            s = SnappySession(data_dir=d, recover=True)
            got = {r0[0] for r0 in s.sql("SELECT k FROM t").rows()}
            assert acked <= got, \
                f"acked rows lost mid-schedule: {sorted(acked - got)[:5]}"
            n_all = s.sql("SELECT count(*) FROM t").rows()[0][0]
            n_dst = s.sql("SELECT count(DISTINCT k) FROM t").rows()[0][0]
            assert n_all == n_dst, "double-applied rows after recovery"
        assert len(acked) >= 40, "schedule starved every committer"
        assert global_registry().counter("fault_injected") > \
            injected_before, "no fault actually fired"
        assert global_registry().counter("wal_corrupt_records") == \
            corrupt_before, "a crash tear was miscounted as corruption"
        s.disk_store.close()
    finally:
        fault.clear()
        props.set("wal_fsync_mode", saved_mode)


# -----------------------------------------------------------------------
# 3-node cluster chaos
# -----------------------------------------------------------------------

def test_chaos_cluster_schedule(tmp_path):
    from snappydata_tpu.cluster import LocatorNode, ServerNode
    from snappydata_tpu.cluster.distributed import DistributedSession

    injected_before = global_registry().counter("fault_injected")
    seed = 424242
    rng = random.Random(seed)
    fault.reseed(seed)

    locator = LocatorNode().start()
    sessions = [SnappySession(catalog=Catalog(),
                              data_dir=str(tmp_path / f"srv{i}"),
                              recover=False) for i in range(3)]
    servers = [ServerNode(locator.address, s).start() for s in sessions]
    ds = DistributedSession(
        server_addresses=[s.flight_address for s in servers])
    try:
        ds.sql("CREATE TABLE tx (k BIGINT, v DOUBLE) USING column "
               "OPTIONS (partition_by 'k', redundancy '1')")
        ds.sql("CREATE TABLE mut (k BIGINT) USING column "
               "OPTIONS (partition_by 'k')")
        expected = 0

        def insert_batch(n):
            nonlocal expected
            ks = np.arange(expected, expected + n, dtype=np.int64)
            ds.insert_arrays("tx", [ks, ks * 0.5])
            expected += n   # acked

        insert_batch(200)

        # ---- phase A: fault storm over reads + routed inserts --------
        # latency + connection drops on client RPC, app-level raises on
        # the server's Flight handler, heartbeat failures, slow WAL
        fault.arm("flight.rpc", "latency", param=0.002, p=0.35)
        fault.arm("flight.rpc", "drop", p=0.15)
        fault.arm("flight.serve", "raise", exc="runtime", every=9)
        fault.arm("locator.heartbeat", "raise", exc="conn", every=2)
        fault.arm("wal.append", "latency", param=0.001, p=0.6)
        hb_before = global_registry().counter("member_heartbeat_failures")
        ok_reads = 0
        for i in range(24):
            try:
                got = ds.sql("SELECT count(*) FROM tx").rows()[0][0]
                # correctness under chaos: a SUCCESSFUL read is EXACT
                assert got == expected, (i, got, expected)
                ok_reads += 1
            except Exception:
                pass   # availability may suffer; correctness may not
            if rng.random() < 0.5:
                try:
                    insert_batch(rng.randint(1, 8))
                except Exception:
                    pass   # un-acked: excluded from `expected` by design
        assert ok_reads >= 3, "storm starved every read — schedule too hot"
        # storm over: the injected connection drops can have failed over
        # members that are actually HEALTHY (false-positive member
        # death) — re-admit them via the watermark rejoin so the rest of
        # the schedule keeps the designed redundancy shape, and assert
        # the re-admitted cluster still answers exactly
        fault.disarm("flight.rpc")
        fault.disarm("flight.serve")
        fault.disarm("locator.heartbeat")
        fault.disarm("wal.append")
        for i in range(3):
            if not ds.alive[i]:
                out = ds.rejoin_server(i)
                assert out["rejoined"], out
        assert all(ds.alive)
        assert ds.sql("SELECT count(*) FROM tx").rows()[0][0] == expected

        # ---- phase B: at-most-once mutation (response lost AFTER the
        # server applied — the blind-retry trap). The client now stamps
        # mutations with a statement id and retries; the server's dedup
        # window turns the re-send into a recorded-result replay, so the
        # lost ack is TRANSPARENT to the caller and still applies
        # exactly once (this used to raise ConnectionError to the
        # caller by design — the dedup window made the retry safe) ----
        retries0 = global_registry().counter("mutation_retries")
        dedup0 = global_registry().counter("mutation_dedup_hits")
        fault.arm("flight.rpc", "drop", phase="after", count=1)
        out = ds.servers[1].execute("INSERT INTO mut VALUES (7)")
        assert out.get("deduped"), out   # the retry hit the window
        fault.disarm("flight.rpc")
        assert global_registry().counter("mutation_retries") > retries0
        assert global_registry().counter("mutation_dedup_hits") > dedup0
        time.sleep(0.05)
        got = ds.sql("SELECT count(*) FROM mut").rows()[0][0]
        assert got == 1, f"mutation applied {got} times (must be exactly 1)"

        # ---- phase C: injected server-side WAL tear mid-load →
        # failover; redundancy keeps the acked rows complete -----------
        fault.arm("wal.append", "torn_write", param=11, count=1)
        insert_batch(120)   # survives the member dying mid-load
        fault.clear()
        got = ds.sql("SELECT count(*) FROM tx").rows()[0][0]
        assert got == expected, (got, expected)
        # app-level faults during the failover's redundancy restoration
        # may have degraded buckets HONESTLY (counted, never phantom) —
        # heal them so the next death cannot lose data
        healed = ds.restore_redundancy()
        assert healed["degraded_buckets"] == 0, healed

        # ---- phase D: hard member kill → replicas converge ----------
        victim = next(i for i in range(3) if ds.alive[i])
        servers[victim].stop()
        got = ds.sql("SELECT count(*) FROM tx").rows()[0][0]
        assert got == expected, \
            f"replicas did not converge after failover: {got} != {expected}"
        # bounded retries with backoff actually happened
        snap = global_registry().snapshot()
        assert snap["counters"].get("failover_member_failed", 0) >= 1
        assert snap["counters"].get("failover_retries", 0) >= 1 or \
            snap["timers"].get("failover_backoff", {}).get("count", 0) >= 1

        # heartbeat faults fired and were survived + counted
        assert global_registry().counter(
            "member_heartbeat_failures") > hb_before

        # ---- the acceptance bar: ≥ 50 faults across WAL, RPC and
        # heartbeat failpoints on this 3-node cluster ------------------
        snap = global_registry().snapshot()["counters"]
        injected = snap.get("fault_injected", 0) - injected_before
        assert injected >= 50, f"only {injected} faults injected"
        for point in ("fault_injected_wal_append",
                      "fault_injected_flight_rpc",
                      "fault_injected_locator_heartbeat"):
            assert snap.get(point, 0) >= 1, f"{point} never fired"
    finally:
        fault.clear()
        ds.close()
        for s in servers:
            try:
                s.stop()
            except Exception:
                pass
        locator.stop()


# -----------------------------------------------------------------------
# materialized-view maintenance under chaos
# -----------------------------------------------------------------------

@pytest.mark.views
def test_chaos_matview_no_double_fold(tmp_path):
    """Seeded insert/delete/checkpoint schedule with torn-write / raise
    faults on wal.append, wal.group_commit and checkpoint.write while a
    materialized view delta-folds every mutation.  After EVERY
    crash-recovery (and at the end), the maintained view state must
    equal a cold re-aggregation of the recovered base table — the PR 2
    invariant extended to view state: a WAL record past the view's
    checkpoint fence folds exactly once (replay), one at/below it never
    re-folds (no double-fold), and a record whose ack was lost never
    folds at all."""
    from snappydata_tpu.views import matviews

    seed = 20260803
    rng = random.Random(seed)
    fault.reseed(seed)
    d = str(tmp_path)
    s = SnappySession(catalog=Catalog(), data_dir=d, recover=False)
    s.sql("CREATE TABLE t (k BIGINT, v DOUBLE) USING column")
    s.sql("CREATE MATERIALIZED VIEW mv AS SELECT k, sum(v) AS sv, "
          "count(*) AS c FROM t GROUP BY k")

    def view_equals_cold_aggregate(sess):
        got = sess.sql("SELECT * FROM mv ORDER BY k").rows()
        cold = sess.sql("SELECT k, sum(v), count(*) FROM t GROUP BY k "
                        "ORDER BY k").rows()
        assert len(got) == len(cold), (got, cold)
        for g, c in zip(got, cold):
            assert g[0] == c[0] and g[2] == c[2], (g, c)
            assert abs(g[1] - c[1]) <= 1e-9 * max(abs(c[1]), 1.0), (g, c)

    recoveries = 0
    injected_before = global_registry().counter("fault_injected")
    for i in range(80):
        r = rng.random()
        if r < 0.12:
            fault.arm("wal.append", "torn_write",
                      param=rng.randint(1, 40), count=1)
        elif r < 0.2:
            fault.arm("wal.group_commit", "raise", count=1)
        elif r < 0.27:
            fault.arm("checkpoint.write", "torn_write",
                      param=rng.randint(1, 60), count=1)
        try:
            if rng.random() < 0.2 and i > 5:
                s.sql(f"DELETE FROM t WHERE k = {rng.randint(0, 7)}")
            else:
                s.sql(f"INSERT INTO t VALUES ({i % 8}, {i}.25)")
        except Exception:
            fault.clear()
            try:
                s.disk_store.close()
            except Exception:
                pass
            s = SnappySession(data_dir=d, recover=True)
            recoveries += 1
            view_equals_cold_aggregate(s)
        if rng.random() < 0.15:
            try:
                s.checkpoint()
            except Exception:
                fault.clear()
                try:
                    s.disk_store.close()
                except Exception:
                    pass
                s = SnappySession(data_dir=d, recover=True)
                recoveries += 1
                view_equals_cold_aggregate(s)
    fault.clear()
    view_equals_cold_aggregate(s)
    assert recoveries >= 3, f"schedule only crashed {recoveries} times"
    assert global_registry().counter("fault_injected") > injected_before
    s.disk_store.close()
    # final recovery + idempotence: two boots, identical fresh view state
    s2 = SnappySession(data_dir=d, recover=True)
    view_equals_cold_aggregate(s2)
    rows = s2.sql("SELECT * FROM mv ORDER BY k").rows()
    assert "mv" in matviews(s2.catalog)
    s2.disk_store.close()
    s3 = SnappySession(data_dir=d, recover=True)
    assert s3.sql("SELECT * FROM mv ORDER BY k").rows() == rows
    view_equals_cold_aggregate(s3)
    s3.disk_store.close()


# -----------------------------------------------------------------------
# long randomized battery (slow tier)
# -----------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("seed", [11, 23, 47])
def test_chaos_durability_long(tmp_path, seed):
    _run_durability_schedule(tmp_path, seed=seed, n_ops=250)
