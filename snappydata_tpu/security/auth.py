"""Pluggable authentication: BUILTIN (user/password table) and LDAP
(simple bind, with optional search-then-bind DN resolution).

Reference surface: the gemfirexd `auth-provider` property accepts
BUILTIN or LDAP, with `auth-ldap-server` and `auth-ldap-search-base`
(cluster/src/dunit/scala/io/snappydata/cluster/ClusterManagerLDAPTestBase.scala:97-102;
core/src/main/scala/org/apache/spark/sql/execution/SecurityUtils.scala).
Network servers authenticate a principal once per connection and every
statement then runs under that principal's session so GRANT/REVOKE and
row-level policies apply.

The LDAP client here is a self-contained LDAPv3 implementation of the
two operations authentication needs — BindRequest and a single-entry
SearchRequest — speaking BER directly over a TCP socket (no external
LDAP library in the image). Because search filters are transmitted
*structurally* in BER (the assertion value is a raw OCTET STRING, never
spliced into a filter string), LDAP-injection via the username is not
possible by construction.
"""

from __future__ import annotations

import hashlib
import hmac
import socket
from typing import Dict, Optional, Tuple

# ---------------------------------------------------------------------------
# Minimal BER codec (the subset LDAPv3 messages use)
# ---------------------------------------------------------------------------


def ber(tag: int, content: bytes) -> bytes:
    """One tag-length-value element (definite length, short or long form)."""
    n = len(content)
    if n < 0x80:
        return bytes([tag, n]) + content
    lb = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([tag, 0x80 | len(lb)]) + lb + content


def ber_int(value: int, tag: int = 0x02) -> bytes:
    """INTEGER (0x02) / ENUMERATED (0x0A): minimal two's complement."""
    if value == 0:
        body = b"\x00"
    else:
        body = value.to_bytes((value.bit_length() + 8) // 8, "big",
                              signed=True)
    return ber(tag, body)


def ber_read(buf: bytes, off: int = 0) -> Tuple[int, bytes, int]:
    """-> (tag, content, next_offset). Raises on truncated input."""
    if off + 2 > len(buf):
        raise ValueError("truncated BER element")
    tag, ln = buf[off], buf[off + 1]
    off += 2
    if ln & 0x80:
        n = ln & 0x7F
        if n == 0 or off + n > len(buf):
            raise ValueError("bad BER length")
        ln = int.from_bytes(buf[off:off + n], "big")
        off += n
    if off + ln > len(buf):
        raise ValueError("truncated BER content")
    return tag, buf[off:off + ln], off + ln


def ber_children(content: bytes):
    """All TLV children of a constructed element's content."""
    out, off = [], 0
    while off < len(content):
        tag, body, off = ber_read(content, off)
        out.append((tag, body))
    return out


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n > 0:
        part = sock.recv(n)
        if not part:
            raise ConnectionError("LDAP server closed the connection")
        chunks.append(part)
        n -= len(part)
    return b"".join(chunks)


def read_ber_message(sock: socket.socket) -> Tuple[int, bytes]:
    """Read exactly one top-level BER element from a socket."""
    header = _recv_exact(sock, 2)
    tag, ln = header[0], header[1]
    if ln & 0x80:
        ln = int.from_bytes(_recv_exact(sock, ln & 0x7F), "big")
    return tag, _recv_exact(sock, ln)


# LDAP protocol tags
LDAP_BIND_REQUEST = 0x60
LDAP_BIND_RESPONSE = 0x61
LDAP_UNBIND_REQUEST = 0x42
LDAP_SEARCH_REQUEST = 0x63
LDAP_SEARCH_ENTRY = 0x64
LDAP_SEARCH_DONE = 0x65
LDAP_AUTH_SIMPLE = 0x80

RESULT_SUCCESS = 0
RESULT_INVALID_CREDENTIALS = 49


def escape_dn_value(value: str) -> str:
    """RFC 4514 escaping for a value substituted into a DN template."""
    out = []
    for i, ch in enumerate(value):
        if ch in ',+"\\<>;=':
            out.append("\\" + ch)
        elif ch in (" ", "#") and (i == 0 or i == len(value) - 1):
            out.append("\\" + ch)
        elif ord(ch) < 0x20:
            out.append("\\%02x" % ord(ch))
        else:
            out.append(ch)
    return "".join(out)


# ---------------------------------------------------------------------------
# Providers
# ---------------------------------------------------------------------------


class AuthProvider:
    """authenticate(user, password) -> True iff the credential is valid."""

    name = "none"

    def authenticate(self, user: str, password: str) -> bool:
        raise NotImplementedError


class BuiltinAuthProvider(AuthProvider):
    """BUILTIN: a user/password table from configuration (ref: the
    gemfirexd BUILTIN provider's `gemfirexd.user.<name>=<password>`
    boot properties). Passwords may be stored plaintext or as
    "sha256:<hex>"."""

    name = "builtin"

    def __init__(self, users: Dict[str, str]):
        self.users = {str(u).lower(): str(p) for u, p in users.items()}

    @staticmethod
    def hash_password(password: str) -> str:
        return "sha256:" + hashlib.sha256(password.encode("utf-8")).hexdigest()

    def authenticate(self, user: str, password: str) -> bool:
        stored = self.users.get(str(user).lower())
        if stored is None or password is None:
            return False
        if stored.startswith("sha256:"):
            candidate = hashlib.sha256(password.encode("utf-8")).hexdigest()
            return hmac.compare_digest(stored[len("sha256:"):], candidate)
        # compare as bytes: compare_digest(str, str) raises on non-ASCII
        return hmac.compare_digest(stored.encode("utf-8"),
                                   password.encode("utf-8"))


class LdapAuthProvider(AuthProvider):
    """LDAP simple bind. Two DN-resolution modes, mirroring the
    reference's knobs:

    - template: `user_dn_template` e.g. "uid={user},ou=people,dc=ex,dc=com"
      (the common `auth-ldap-search-dn` shortcut) — bind directly.
    - search: bind as `bind_dn` (or anonymously), search `search_base`
      for `search_filter` (default "(uid={user})"), then bind as the
      found entry's DN (ref: auth-ldap-search-base behavior).
    """

    name = "ldap"

    def __init__(self, server: str,
                 user_dn_template: Optional[str] = None,
                 search_base: Optional[str] = None,
                 search_filter: str = "(uid={user})",
                 bind_dn: Optional[str] = None,
                 bind_password: str = "",
                 timeout: float = 5.0):
        if server.startswith("ldaps://"):
            raise ValueError("ldaps:// is not supported; use ldap:// "
                             "(optionally over a local stunnel)")
        hostport = server[len("ldap://"):] if server.startswith("ldap://") \
            else server
        host, _, port = hostport.partition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port) if port else 389
        if not user_dn_template and not search_base:
            raise ValueError("LDAP auth needs auth_ldap_user_template or "
                             "auth_ldap_search_base")
        self.user_dn_template = user_dn_template
        self.search_base = search_base
        self.search_filter = search_filter
        self.bind_dn = bind_dn
        self.bind_password = bind_password
        self.timeout = timeout

    # -- wire operations --------------------------------------------------

    def _connect(self) -> socket.socket:
        return socket.create_connection((self.host, self.port),
                                        timeout=self.timeout)

    @staticmethod
    def _bind(sock: socket.socket, msg_id: int, dn: str,
              password: str) -> int:
        """Send a simple BindRequest, return the resultCode."""
        req = ber(LDAP_BIND_REQUEST,
                  ber_int(3) +
                  ber(0x04, dn.encode("utf-8")) +
                  ber(LDAP_AUTH_SIMPLE, password.encode("utf-8")))
        sock.sendall(ber(0x30, ber_int(msg_id) + req))
        _, content = read_ber_message(sock)
        children = ber_children(content)
        if len(children) < 2 or children[1][0] != LDAP_BIND_RESPONSE:
            raise ValueError("unexpected LDAP response to bind")
        result = ber_children(children[1][1])
        return int.from_bytes(result[0][1], "big", signed=True)

    def _search_dn(self, sock: socket.socket, msg_id: int,
                   user: str) -> Optional[str]:
        """SearchRequest for the user's entry; returns its DN or None.
        The filter must be a single equality like "(uid={user})" — the
        assertion value travels as a raw OCTET STRING (no injection)."""
        flt = self.search_filter.strip()
        if not (flt.startswith("(") and flt.endswith(")") and "=" in flt):
            raise ValueError(f"unsupported LDAP filter {flt!r} "
                             "(single equality only)")
        attr, _, val_tpl = flt[1:-1].partition("=")
        value = val_tpl.replace("{user}", user).replace("%u", user)
        req = ber(LDAP_SEARCH_REQUEST,
                  ber(0x04, self.search_base.encode("utf-8")) +
                  ber_int(2, 0x0A) +          # scope: wholeSubtree
                  ber_int(0, 0x0A) +          # derefAliases: never
                  ber_int(1) +                 # sizeLimit: 1 entry
                  ber_int(max(1, int(self.timeout))) +
                  b"\x01\x01\x00" +            # typesOnly: FALSE
                  ber(0xA3,                    # equalityMatch filter
                      ber(0x04, attr.strip().encode("utf-8")) +
                      ber(0x04, value.encode("utf-8"))) +
                  ber(0x30, ber(0x04, b"1.1")))  # attributes: none
        sock.sendall(ber(0x30, ber_int(msg_id) + req))
        dn = None
        while True:
            _, content = read_ber_message(sock)
            children = ber_children(content)
            op_tag, op_body = children[1]
            if op_tag == LDAP_SEARCH_ENTRY:
                if dn is None:
                    dn = ber_children(op_body)[0][1].decode("utf-8")
            elif op_tag == LDAP_SEARCH_DONE:
                code = int.from_bytes(ber_children(op_body)[0][1], "big",
                                      signed=True)
                # sizeLimitExceeded(4) with an entry in hand is fine
                if code not in (RESULT_SUCCESS, 4):
                    return None
                return dn
            else:
                raise ValueError("unexpected LDAP search response")

    # -- AuthProvider -----------------------------------------------------

    def authenticate(self, user: str, password: str) -> bool:
        if not password:
            # RFC 4513 §5.1.2: an empty password is an UNauthenticated
            # bind that servers report as "success" — must be refused
            return False
        try:
            sock = self._connect()
        except OSError:
            return False
        try:
            msg_id = 1
            if self.user_dn_template:
                dn = self.user_dn_template \
                    .replace("{user}", escape_dn_value(user)) \
                    .replace("%u", escape_dn_value(user))
            else:
                # bind before searching: as the service account when
                # configured, anonymously otherwise (RFC 4513 §5.1.1)
                if self._bind(sock, msg_id, self.bind_dn or "",
                              self.bind_password if self.bind_dn
                              else "") != RESULT_SUCCESS:
                    return False
                msg_id += 1
                dn = self._search_dn(sock, msg_id, user)
                msg_id += 1
                if dn is None:
                    return False
            code = self._bind(sock, msg_id, dn, password)
            try:
                sock.sendall(ber(0x30, ber_int(msg_id + 1) +
                                 ber(LDAP_UNBIND_REQUEST, b"")))
            except OSError:
                pass
            return code == RESULT_SUCCESS
        except (OSError, ValueError, ConnectionError, IndexError):
            return False
        finally:
            sock.close()


# ---------------------------------------------------------------------------
# Configuration entry point
# ---------------------------------------------------------------------------


def make_provider(conf) -> Optional[AuthProvider]:
    """Build the configured provider from session properties (None when
    authentication is not enabled). Keys mirror the reference's:

      auth_provider            BUILTIN | LDAP   (auth-provider)
      auth_builtin_users       {user: pw|"sha256:<hex>"} or "u:pw,u2:pw2"
      auth_ldap_server         ldap://host:port (auth-ldap-server)
      auth_ldap_user_template  "uid={user},ou=people,..."
      auth_ldap_search_base    subtree base DN  (auth-ldap-search-base)
      auth_ldap_search_filter  default "(uid={user})"
      auth_ldap_bind_dn / auth_ldap_bind_password
    """
    kind = str(conf.get("auth_provider") or "").strip().lower()
    if kind in ("", "none"):
        return None
    if kind == "builtin":
        users = conf.get("auth_builtin_users") or {}
        if isinstance(users, str):
            users = dict(pair.split(":", 1)
                         for pair in users.split(",") if ":" in pair)
        return BuiltinAuthProvider(users)
    if kind == "ldap":
        server = conf.get("auth_ldap_server")
        if not server:
            raise ValueError("auth_provider=LDAP requires auth_ldap_server")
        return LdapAuthProvider(
            server,
            user_dn_template=conf.get("auth_ldap_user_template"),
            search_base=conf.get("auth_ldap_search_base"),
            search_filter=conf.get("auth_ldap_search_filter")
            or "(uid={user})",
            bind_dn=conf.get("auth_ldap_bind_dn"),
            bind_password=conf.get("auth_ldap_bind_password") or "",
            timeout=float(conf.get("auth_ldap_timeout") or 5.0))
    raise ValueError(f"unknown auth_provider {kind!r} "
                     "(supported: BUILTIN, LDAP)")
