"""Minimal TOML-subset parser (this container's Python predates
stdlib tomllib, and locklint must not grow third-party deps).

Supported: ``[table]``, ``[[array-of-tables]]``, ``key = value`` with
string / integer / boolean / array-of-strings values (arrays may span
lines), and ``#`` comments. That is exactly the shape of
``lock_order.toml``; anything else raises."""

from __future__ import annotations

import re
from typing import Any, Dict, List

_KEY_RE = re.compile(r"^([A-Za-z0-9_.-]+)\s*=\s*(.*)$")
_TABLE_RE = re.compile(r"^\[([A-Za-z0-9_.-]+)\]$")
_ARRAY_TABLE_RE = re.compile(r"^\[\[([A-Za-z0-9_.-]+)\]\]$")


class TomlError(ValueError):
    pass


def _strip_comment(line: str) -> str:
    out = []
    in_str = False
    for ch in line:
        if ch == '"':
            in_str = not in_str
        if ch == "#" and not in_str:
            break
        out.append(ch)
    return "".join(out).strip()


def _parse_scalar(tok: str, lineno: int) -> Any:
    tok = tok.strip()
    if tok.startswith('"') and tok.endswith('"') and len(tok) >= 2:
        return tok[1:-1].replace('\\"', '"').replace("\\\\", "\\")
    if tok in ("true", "false"):
        return tok == "true"
    if re.fullmatch(r"-?[0-9]+", tok):
        return int(tok)
    raise TomlError("line %d: unsupported value %r" % (lineno, tok))


def _split_array_items(body: str, lineno: int) -> List[Any]:
    items, cur, in_str = [], [], False
    for ch in body:
        if ch == '"':
            in_str = not in_str
            cur.append(ch)
        elif ch == "," and not in_str:
            tok = "".join(cur).strip()
            if tok:
                items.append(_parse_scalar(tok, lineno))
            cur = []
        else:
            cur.append(ch)
    tok = "".join(cur).strip()
    if tok:
        items.append(_parse_scalar(tok, lineno))
    return items


def loads(text: str) -> Dict[str, Any]:
    root: Dict[str, Any] = {}
    target = root
    lines = text.split("\n")
    i = 0
    while i < len(lines):
        raw = lines[i]
        line = _strip_comment(raw)
        i += 1
        if not line:
            continue
        m = _ARRAY_TABLE_RE.match(line)
        if m:
            root.setdefault(m.group(1), [])
            if not isinstance(root[m.group(1)], list):
                raise TomlError("line %d: %s is not an array of tables"
                                % (i, m.group(1)))
            target = {}
            root[m.group(1)].append(target)
            continue
        m = _TABLE_RE.match(line)
        if m:
            target = root.setdefault(m.group(1), {})
            if not isinstance(target, dict):
                raise TomlError("line %d: %s is not a table" % (i, m.group(1)))
            continue
        m = _KEY_RE.match(line)
        if not m:
            raise TomlError("line %d: cannot parse %r" % (i, raw))
        key, val = m.group(1), m.group(2).strip()
        if val.startswith("["):
            body = val[1:]
            start = i
            # accumulate until the body (quotes balanced) ends with "]"
            while not (body.count('"') % 2 == 0
                       and body.rstrip().endswith("]")):
                if i >= len(lines):
                    raise TomlError("line %d: unterminated array" % start)
                body += " " + _strip_comment(lines[i])
                i += 1
            body = body.rstrip()
            target[key] = _split_array_items(body[:-1], start)
        else:
            target[key] = _parse_scalar(val, i)
    return root


def load(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as fh:
        return loads(fh.read())
