"""Create a COLUMN table, bulk-load it, query it (ref example:
examples/.../CreateColumnTable.scala).

Run: PYTHONPATH=. python examples/create_column_table.py
"""

import numpy as np

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog


def main():
    s = SnappySession(catalog=Catalog())

    s.sql("""CREATE TABLE customer (
        c_custkey BIGINT, c_name STRING, c_nationkey INT,
        c_acctbal DOUBLE
    ) USING column OPTIONS (partition_by 'c_custkey', buckets '32')""")

    n = 100_000
    rng = np.random.default_rng(0)
    s.insert_arrays("customer", [
        np.arange(n, dtype=np.int64),
        np.array([f"Customer#{i:09d}" for i in range(n)], dtype=object),
        rng.integers(0, 25, n).astype(np.int32),
        np.round(rng.uniform(-999, 9999, n), 2),
    ])

    print(s.sql("SELECT count(*), avg(c_acctbal) FROM customer").to_pandas())
    print(s.sql("""
        SELECT c_nationkey, count(*) AS customers, sum(c_acctbal) AS total
        FROM customer WHERE c_acctbal > 0
        GROUP BY c_nationkey ORDER BY total DESC LIMIT 5""").to_pandas())

    # mutability: column tables take updates and deletes
    s.sql("UPDATE customer SET c_acctbal = 0 WHERE c_acctbal < 0")
    print("negative balances after update:",
          s.sql("SELECT count(*) FROM customer WHERE c_acctbal < 0")
          .rows()[0][0])


if __name__ == "__main__":
    main()
