"""Tiled scans: aggregate queries over a column table whose decoded bind
exceeds `scan_tile_bytes` stream the batch axis through the compiled
partial program tile by tile and merge partials.

Reference behavior being matched: the store never materializes a table to
scan it — batches stream through generated code with disk read-ahead
(ColumnFormatIterator, core/.../columnar/impl/ColumnFormatIterator.scala:
60-162); SURVEY.md §5 maps "long context" → "table ≫ HBM".
"""

import numpy as np
import pytest

from snappydata_tpu import SnappySession, config
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.observability.metrics import global_registry


@pytest.fixture
def small_batches():
    """Tiny batch capacity so a few thousand rows span many scan units."""
    props = config.global_properties()
    old_rows, old_tile = props.column_batch_rows, props.scan_tile_bytes
    props.column_batch_rows = 256
    yield props
    props.column_batch_rows = old_rows
    props.scan_tile_bytes = old_tile


def _load(sess, n=4000, seed=7):
    rng = np.random.default_rng(seed)
    sess.sql("CREATE TABLE big (k STRING, v DOUBLE, w BIGINT) USING column")
    k = rng.choice(np.array(["a", "b", "c", "d"], dtype=object), n)
    v = rng.normal(100.0, 10.0, n)
    w = rng.integers(0, 1000, n, dtype=np.int64)
    data = sess.catalog.describe("big").data
    data.insert_arrays([k, v, w])
    return k, v, w


def _tiles() -> int:
    return global_registry().counter("scan_tiles")


def test_tiled_matches_untiled(small_batches):
    sess = SnappySession(catalog=Catalog())
    _load(sess)
    q = ("SELECT k, count(*), sum(v), avg(v), min(w), max(w) "
         "FROM big GROUP BY k ORDER BY k")
    expected = sess.sql(q).rows()

    small_batches.scan_tile_bytes = 3 * 256 * 32  # ~3 units per tile
    t0 = _tiles()
    got = sess.sql(q).rows()
    assert _tiles() > t0, "expected the tiled path to run"
    assert len(got) == len(expected) == 4
    for (ek, ec, es, ea, emn, emx), (gk, gc, gs, ga, gmn, gmx) in zip(
            expected, got):
        assert ek == gk and ec == gc and emn == gmn and emx == gmx
        assert es == pytest.approx(gs, rel=1e-9)
        assert ea == pytest.approx(ga, rel=1e-9)


def test_tiled_global_aggregate_and_filter(small_batches):
    sess = SnappySession(catalog=Catalog())
    _, v, w = _load(sess)
    q = "SELECT count(*), sum(v), avg(w) FROM big WHERE w >= 500"
    expected = sess.sql(q).rows()[0]
    small_batches.scan_tile_bytes = 2 * 256 * 32
    t0 = _tiles()
    got = sess.sql(q).rows()[0]
    assert _tiles() > t0
    assert got[0] == expected[0]
    assert got[1] == pytest.approx(expected[1], rel=1e-9)
    assert got[2] == pytest.approx(expected[2], rel=1e-9)
    # oracle
    sel = w >= 500
    assert got[0] == int(sel.sum())
    assert got[1] == pytest.approx(float(v[sel].sum()), rel=1e-9)


def test_tiled_having_and_limit(small_batches):
    sess = SnappySession(catalog=Catalog())
    _load(sess)
    q = ("SELECT k, count(*) AS n FROM big GROUP BY k "
         "HAVING count(*) > 0 ORDER BY n DESC, k LIMIT 2")
    expected = sess.sql(q).rows()
    small_batches.scan_tile_bytes = 2 * 256 * 32
    t0 = _tiles()
    got = sess.sql(q).rows()
    assert _tiles() > t0
    assert got == expected and len(got) == 2


def test_tiled_stddev_variance(small_batches):
    sess = SnappySession(catalog=Catalog())
    _, v, _ = _load(sess)
    q = "SELECT stddev(v), variance(v) FROM big"
    expected = sess.sql(q).rows()[0]
    small_batches.scan_tile_bytes = 2 * 256 * 32
    got = sess.sql(q).rows()[0]
    assert got[0] == pytest.approx(expected[0], rel=1e-6)
    assert got[1] == pytest.approx(expected[1], rel=1e-6)


def test_tiled_with_nulls(small_batches):
    sess = SnappySession(catalog=Catalog())
    sess.sql("CREATE TABLE nt (g STRING, x DOUBLE) USING column")
    n = 2000
    rng = np.random.default_rng(3)
    g = rng.choice(np.array(["p", "q"], dtype=object), n)
    x = rng.normal(0, 1, n)
    nulls = rng.random(n) < 0.2
    data = sess.catalog.describe("nt").data
    data.insert_arrays([g, x], nulls=[None, nulls])
    q = "SELECT g, count(x), sum(x) FROM nt GROUP BY g ORDER BY g"
    expected = sess.sql(q).rows()
    small_batches.scan_tile_bytes = 2 * 256 * 32
    got = sess.sql(q).rows()
    for (eg, ec, es), (gg, gc, gs) in zip(expected, got):
        assert eg == gg and ec == gc
        assert es == pytest.approx(gs, rel=1e-9)
    # count excludes NULLs — verify against the oracle too
    for gg, gc, gs in got:
        sel = (g == gg) & ~nulls
        assert gc == int(sel.sum())


def test_tiling_leaves_joins_alone(small_batches):
    """Plans tiling can't handle fall back to the untiled path, exactly."""
    sess = SnappySession(catalog=Catalog())
    _load(sess)
    sess.sql("CREATE TABLE d (k STRING, label STRING) USING column")
    sess.sql("INSERT INTO d VALUES ('a','A'),('b','B'),('c','C'),('d','D')")
    small_batches.scan_tile_bytes = 2 * 256 * 32
    r = sess.sql("SELECT d.label, count(*) FROM big JOIN d ON big.k = d.k "
                 "GROUP BY d.label ORDER BY d.label")
    assert [x[0] for x in r.rows()] == ["A", "B", "C", "D"]
    assert sum(x[1] for x in r.rows()) == 4000


def test_tiled_snapshot_consistency(small_batches):
    """Tiles pin ONE manifest: a mutation between tiles must not mix
    versions. (Simulated by checking the pinned-manifest plumbing: the
    result equals the pre-mutation oracle even though an insert landed
    while the pass ran.)"""
    sess = SnappySession(catalog=Catalog())
    _load(sess, n=3000)
    small_batches.scan_tile_bytes = 2 * 256 * 32
    # run once tiled to warm; then mutate and re-run — new rows visible
    before = sess.sql("SELECT count(*) FROM big").rows()[0][0]
    assert before == 3000
    sess.sql("INSERT INTO big VALUES ('a', 1.0, 1)")
    after = sess.sql("SELECT count(*) FROM big").rows()[0][0]
    assert after == 3001


def test_tiles_do_not_accumulate_on_device(small_batches):
    """Without a device-cache budget, a tile pass must keep at most ONE
    windowed entry resident (the table is oversized by definition)."""
    sess = SnappySession(catalog=Catalog())
    _load(sess)
    small_batches.scan_tile_bytes = 2 * 256 * 32
    sess.sql("SELECT k, count(*) FROM big GROUP BY k")
    data = sess.catalog.describe("big").data
    windowed = [k for k in data._device_cache if k[2] is not None]
    assert len(windowed) <= 1, windowed
