"""Torn-WAL recovery fuzz (satellite of the failpoint PR):

- truncation fuzz: cut wal.log at EVERY byte offset of the final record
  and assert the recovered prefix is exactly the preceding records —
  a crash mid-append may only ever cost the un-acked tail record;
- bit-flip sweep: flip every single byte of a MIDDLE record and assert
  the CRC path never yields that record (detected + quarantined), while
  every record before it still recovers.
"""

import os
import shutil

import numpy as np
import pytest

from snappydata_tpu.observability.metrics import global_registry
from snappydata_tpu.storage.persistence import (read_records, salvage_file,
                                                write_record)


def _make_wal(path, n_records):
    """n_records checksummed records; returns list of start offsets."""
    starts = []
    with open(path, "wb") as fh:
        for i in range(n_records):
            starts.append(fh.tell())
            write_record(fh, {"seq": i, "kind": "insert", "table": "t"},
                         [np.arange(6, dtype=np.int64) + i])
    return starts


def _recovered_seqs(path):
    salvage_file(path)
    with open(path, "rb") as fh:
        return [h["seq"] for h, _ in read_records(fh)]


def test_truncation_fuzz_every_offset_of_final_record(tmp_path):
    base = tmp_path / "wal.base"
    starts = _make_wal(str(base), 4)
    size = os.path.getsize(base)
    final_start = starts[-1]
    assert size - final_start > 40   # the sweep is a real sweep
    for cut in range(final_start, size):
        p = str(tmp_path / "wal.log")
        shutil.copyfile(base, p)
        with open(p, "rb+") as fh:
            fh.truncate(cut)
        got = _recovered_seqs(p)
        # prefix recovered EXACTLY: all full records, never a torn one
        assert got == [0, 1, 2], f"cut at byte {cut} recovered {got}"
        os.remove(p)
        if os.path.exists(p + ".corrupt"):
            os.remove(p + ".corrupt")
    # sanity: the untouched file recovers everything
    shutil.copyfile(base, str(tmp_path / "wal.log"))
    assert _recovered_seqs(str(tmp_path / "wal.log")) == [0, 1, 2, 3]


def test_bit_flip_sweep_crc_rejects_every_single_byte_corruption(tmp_path):
    base = tmp_path / "wal.base"
    starts = _make_wal(str(base), 3)
    raw = base.read_bytes()
    lo, hi = starts[1], starts[2]     # every byte of the MIDDLE record
    corrupt_counter_before = global_registry().counter(
        "wal_corrupt_records")
    for ofs in range(lo, hi):
        bad = bytearray(raw)
        bad[ofs] ^= 0xFF
        p = str(tmp_path / "wal.log")
        with open(p, "wb") as fh:
            fh.write(bytes(bad))
        got = _recovered_seqs(p)
        # the flipped record must NEVER be replayed (CRC/structure catch
        # it); the record before it always survives
        assert 1 not in got, f"flip at byte {ofs} replayed the record"
        assert got[:1] == [0], f"flip at byte {ofs} lost the prefix"
        os.remove(p)
        if os.path.exists(p + ".corrupt"):
            os.remove(p + ".corrupt")
    # most flips are PROVABLE corruption (CRC mismatch etc.) and were
    # counted + quarantined, not silently dropped
    assert global_registry().counter("wal_corrupt_records") > \
        corrupt_counter_before + (hi - lo) // 2


def _make_group_wal(tmp_path, records_per_group=3, groups=3):
    """A wal.log written by the GROUP COMMIT path: each group of records
    lands as one contiguous write+fsync. Returns (path, per-record start
    offsets) — on disk the framing is identical to per-record writes,
    which is exactly what keeps salvage/replay working unchanged."""
    from snappydata_tpu import config
    from snappydata_tpu.storage.persistence import DiskStore

    props = config.global_properties()
    saved_mode, saved_ms = props.get("wal_fsync_mode"), \
        props.get("wal_group_ms")
    props.set("wal_fsync_mode", "group")
    props.set("wal_group_ms", 10_000.0)
    try:
        d = str(tmp_path / "gstore")
        ds = DiskStore(d)
        for g in range(groups):
            for r in range(records_per_group):
                i = g * records_per_group + r
                ds.wal_append("t", "insert",
                              arrays=[np.arange(6, dtype=np.int64) + i])
            ds.wal_sync()          # ONE drain per group
        ds.close()
    finally:
        props.set("wal_fsync_mode", saved_mode)
        props.set("wal_group_ms", saved_ms)
    path = os.path.join(d, "wal.log")
    starts = []
    with open(path, "rb") as fh:
        while True:
            starts.append(fh.tell())
            try:
                next(iter(read_records(fh)))
            except StopIteration:
                starts.pop()
                break
    return path, starts


def test_group_framed_log_truncation_sweep(tmp_path):
    """Truncate a group-committed log at EVERY byte of the final GROUP:
    recovery keeps exactly the records whose frames fully survive —
    a mid-group crash only ever costs the (un-acked) torn tail."""
    base, starts = _make_group_wal(tmp_path)
    raw = open(base, "rb").read()
    final_group_start = starts[-3]           # last group = 3 records
    assert len(starts) == 9
    for cut in range(final_group_start, len(raw)):
        p = str(tmp_path / "wal.log")
        with open(p, "wb") as fh:
            fh.write(raw[:cut])
        got = _recovered_seqs(p)
        # every fully-written record survives, partial frames never do
        n_whole = sum(1 for s0 in starts[6:] if
                      (starts + [len(raw)])[starts.index(s0) + 1] <= cut)
        assert got == list(range(1, 7 + n_whole)), \
            f"cut at {cut} recovered {got}"
        os.remove(p)
        if os.path.exists(p + ".corrupt"):
            os.remove(p + ".corrupt")


def test_group_framed_log_bit_flip_sweep(tmp_path):
    """Flip one byte in the MIDDLE group of a group-committed log: the
    damaged record must never replay; the prefix always survives."""
    base, starts = _make_group_wal(tmp_path)
    raw = open(base, "rb").read()
    lo, hi = starts[3], starts[6]            # the middle group's bytes
    step = max(1, (hi - lo) // 64)           # sampled sweep: keep tier-1 fast
    for ofs in range(lo, hi, step):
        bad = bytearray(raw)
        bad[ofs] ^= 0xFF
        p = str(tmp_path / "wal.log")
        with open(p, "wb") as fh:
            fh.write(bytes(bad))
        got = _recovered_seqs(p)
        assert all(q <= 3 for q in got) or got[:3] == [1, 2, 3], \
            f"flip at {ofs} recovered {got}"
        # records 4..6 overlap the flip region: whichever record holds
        # the flipped byte must not replay
        flipped_rec = 4 + max(i for i, s0 in enumerate(starts[3:6])
                              if s0 <= ofs)
        assert flipped_rec not in got, \
            f"flip at {ofs} replayed damaged record {flipped_rec}"
        os.remove(p)
        if os.path.exists(p + ".corrupt"):
            os.remove(p + ".corrupt")


def test_session_level_torn_tail_recovery(tmp_path):
    """End-to-end: a crash mid-append of the LAST insert loses only that
    (un-acked) insert; recovery is idempotent across repeated boots."""
    from snappydata_tpu import SnappySession
    from snappydata_tpu.catalog import Catalog

    s = SnappySession(catalog=Catalog(), data_dir=str(tmp_path),
                      recover=False)
    s.sql("CREATE TABLE t (k BIGINT, v DOUBLE) USING column")
    for i in range(5):
        s.sql(f"INSERT INTO t VALUES ({i}, {i}.5)")
    s.disk_store.close()
    wal = os.path.join(str(tmp_path), "wal.log")
    size = os.path.getsize(wal)
    for cut_back in (1, 7, 23):
        shutil.copyfile(wal, wal + ".orig")
        with open(wal, "rb+") as fh:
            fh.truncate(size - cut_back)
        s2 = SnappySession(data_dir=str(tmp_path), recover=True)
        rows = s2.sql("SELECT k FROM t ORDER BY k").rows()
        # the tear is inside the final record: only row 4 may be lost
        assert rows == [(0,), (1,), (2,), (3,)], (cut_back, rows)
        s2.disk_store.close()
        # idempotent: a second recovery sees the identical state
        s3 = SnappySession(data_dir=str(tmp_path), recover=True)
        assert s3.sql("SELECT k FROM t ORDER BY k").rows() == rows
        s3.disk_store.close()
        shutil.copyfile(wal + ".orig", wal)
        for side in (wal + ".corrupt",):
            if os.path.exists(side):
                os.remove(side)


def test_post_rotation_reboot_keeps_wal_seq_above_fence(tmp_path):
    """Regression for a chaos-harness find: checkpoint rotation empties
    the WAL; a reboot then re-seeded the seq counter from the (empty)
    WAL alone, so new mutations minted seqs BELOW the manifests' replay
    fence and the next recovery silently skipped them — acked rows
    lost with no fault injected at all."""
    from snappydata_tpu import SnappySession
    from snappydata_tpu.catalog import Catalog

    s = SnappySession(catalog=Catalog(), data_dir=str(tmp_path),
                      recover=False)
    s.sql("CREATE TABLE t (k BIGINT) USING column")
    for i in range(10):
        s.sql(f"INSERT INTO t VALUES ({i})")
    s.checkpoint()                       # folds + rotates: WAL now empty
    s.disk_store.close()                 # crash right after rotation
    s2 = SnappySession(data_dir=str(tmp_path), recover=True)
    s2.sql("INSERT INTO t VALUES (100)")  # must mint seq ABOVE the fence
    s2.sql("INSERT INTO t VALUES (101)")
    s2.disk_store.close()                # crash again, no checkpoint
    s3 = SnappySession(data_dir=str(tmp_path), recover=True)
    rows = [r[0] for r in s3.sql("SELECT k FROM t ORDER BY k").rows()]
    assert rows == list(range(10)) + [100, 101]
    s3.disk_store.close()


def test_pre_alter_batch_files_recover_by_name(tmp_path):
    """Batch files are write-once: one checkpointed before an ALTER
    legitimately holds a different column set than today's schema.
    Recovery must align it by the names recorded in the file — never
    quarantine it as torn (review find: a column-count check destroyed
    healthy pre-ALTER batches)."""
    from snappydata_tpu import SnappySession
    from snappydata_tpu.catalog import Catalog

    d = str(tmp_path / "add")
    s = SnappySession(catalog=Catalog(), data_dir=d, recover=False)
    s.sql("CREATE TABLE t (a BIGINT, b DOUBLE) USING column "
          "OPTIONS (column_max_delta_rows '4')")
    s.sql("INSERT INTO t VALUES (1,1.0),(2,2.0),(3,3.0),(4,4.0),(5,5.0)")
    s.checkpoint()                       # batch-0.col has 2 columns
    s.sql("ALTER TABLE t ADD COLUMN c DOUBLE")
    s.sql("INSERT INTO t VALUES (6,6.0,6.5)")
    s.checkpoint()                       # manifest now lists 3 columns
    s.disk_store.close()
    before = global_registry().counter("batch_corrupt_records")
    s2 = SnappySession(data_dir=d, recover=True)
    rows = s2.sql("SELECT a, c FROM t ORDER BY a").rows()
    assert [r[0] for r in rows] == [1, 2, 3, 4, 5, 6]
    assert rows[0][1] is None and rows[-1][1] == 6.5
    assert global_registry().counter("batch_corrupt_records") == before
    s2.disk_store.close()

    d2 = str(tmp_path / "drop")
    s = SnappySession(catalog=Catalog(), data_dir=d2, recover=False)
    s.sql("CREATE TABLE t (a BIGINT, b DOUBLE, c STRING) USING column "
          "OPTIONS (column_max_delta_rows '4')")
    s.sql("INSERT INTO t VALUES (1,1.0,'x'),(2,2.0,'y'),(3,3.0,'z'),"
          "(4,4.0,'w'),(5,5.0,'v')")
    s.checkpoint()                       # 3-column batch file
    s.sql("ALTER TABLE t DROP COLUMN b")
    s.checkpoint()
    s.disk_store.close()
    s3 = SnappySession(data_dir=d2, recover=True)
    rows = s3.sql("SELECT a, c FROM t ORDER BY a").rows()
    assert rows == [(1, 'x'), (2, 'y'), (3, 'z'), (4, 'w'), (5, 'v')]
    s3.disk_store.close()


def test_boot_after_batch_quarantine_boots_again(tmp_path):
    """The boot AFTER a batch-file quarantine must also succeed: the
    manifest still names the quarantined file until the next checkpoint,
    so a missing batch skips like the corrupt one did (review find:
    FileNotFoundError used to fail that second boot)."""
    import glob

    from snappydata_tpu import SnappySession
    from snappydata_tpu.catalog import Catalog

    s = SnappySession(catalog=Catalog(), data_dir=str(tmp_path),
                      recover=False)
    s.sql("CREATE TABLE t (k BIGINT) USING column "
          "OPTIONS (column_max_delta_rows '4')")
    s.sql("INSERT INTO t VALUES (1), (2), (3), (4), (5), (6)")
    s.checkpoint()
    s.disk_store.close()
    (bpath,) = glob.glob(str(tmp_path / "tables" / "t" / "batch-0.col"))
    raw = bytearray(open(bpath, "rb").read())
    raw[len(raw) // 2] ^= 0x04
    open(bpath, "wb").write(bytes(raw))
    s2 = SnappySession(data_dir=str(tmp_path), recover=True)   # quarantines
    n2 = s2.sql("SELECT count(*) FROM t").rows()[0][0]
    s2.disk_store.close()
    # second boot: manifest still references the quarantined file
    s3 = SnappySession(data_dir=str(tmp_path), recover=True)
    assert s3.sql("SELECT count(*) FROM t").rows()[0][0] == n2
    s3.disk_store.close()


def test_session_level_bit_flip_quarantine(tmp_path):
    """A bit-flipped MIDDLE record is detected, quarantined to the
    .corrupt sidecar, counted — and boot still succeeds with every
    record before the damage."""
    from snappydata_tpu import SnappySession
    from snappydata_tpu.catalog import Catalog

    s = SnappySession(catalog=Catalog(), data_dir=str(tmp_path),
                      recover=False)
    s.sql("CREATE TABLE t (k BIGINT) USING column")
    for i in range(6):
        s.sql(f"INSERT INTO t VALUES ({i})")
    s.disk_store.close()
    wal = os.path.join(str(tmp_path), "wal.log")
    raw = bytearray(open(wal, "rb").read())
    raw[len(raw) // 2] ^= 0x10          # middle of the log
    open(wal, "wb").write(bytes(raw))
    before = global_registry().counter("wal_corrupt_records")
    s2 = SnappySession(data_dir=str(tmp_path), recover=True)
    rows = [r[0] for r in s2.sql("SELECT k FROM t ORDER BY k").rows()]
    # a strict prefix survived; the damaged record did not replay garbled
    assert rows == list(range(len(rows))) and 1 <= len(rows) < 6
    assert global_registry().counter("wal_corrupt_records") == before + 1
    assert os.path.exists(wal + ".corrupt")
    s2.disk_store.close()
