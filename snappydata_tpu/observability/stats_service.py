"""Periodic table stats service.

Reference: SnappyTableStatsProviderService gathers per-table/member row
counts and sizes on a 5s cadence via store function execution
(io/snappydata/SnappyTableStatsProviderService.scala:59-185, interval
Constant.DEFAULT_CALC_TABLE_SIZE_SERVICE_INTERVAL) and feeds the
dashboard/metrics. Here: a daemon thread snapshotting the catalog.
"""

from __future__ import annotations

import logging
import threading
from snappydata_tpu.utils import locks
import time
from typing import Dict, Optional

from snappydata_tpu import config
from snappydata_tpu.observability.metrics import global_registry
# tracing_snapshot lives with the trace ring; re-exported here so every
# status surface reads off one module like the other *_snapshot helpers
from snappydata_tpu.observability.tracing import tracing_snapshot  # noqa: F401,E501
from snappydata_tpu.storage.device_decode import table_fallbacks
from snappydata_tpu.storage.table_store import RowTableData


def durability_snapshot() -> dict:
    """WAL group-commit stats: live policy knobs + the write-path
    counters (wal_fsync_count, wal_group_commit_batches,
    wal_bytes_written, wal_group_flush timings) for REST
    `/status/api/v1/wal` and the dashboard's Durability section.
    records_per_fsync is the amortization the group commit buys — 1.0
    means always-mode behavior, higher means grouped."""
    from snappydata_tpu import config

    snap = global_registry().snapshot()
    c = snap["counters"]
    t = snap["timers"].get("wal_group_flush", {})
    props = config.global_properties()
    fsyncs = c.get("wal_fsync_count", 0)
    records = c.get("wal_records_written", 0)
    return {
        "wal_fsync_mode": props.get("wal_fsync_mode"),
        "wal_buffer_bytes": props.get("wal_buffer_bytes"),
        "wal_group_ms": props.get("wal_group_ms"),
        "wal_fsync_count": fsyncs,
        "wal_group_commit_batches": c.get("wal_group_commit_batches", 0),
        "wal_records_written": records,
        "wal_bytes_written": c.get("wal_bytes_written", 0),
        "wal_records_per_fsync":
            round(records / fsyncs, 2) if fsyncs else None,
        "wal_group_flush_ms": {
            "count": t.get("count", 0),
            "mean_ms": round(t.get("mean_s", 0.0) * 1e3, 3),
            "max_ms": round(t.get("max_s", 0.0) * 1e3, 3),
        },
        "wal_corrupt_records": c.get("wal_corrupt_records", 0),
    }


def scan_snapshot(catalog=None) -> dict:
    """Aggregation-engine / tiled-scan / compressed-domain stats: live
    knobs + the read-path counters for REST `/status/api/v1/scan` and
    the dashboard's Scan sections.  agg_reduce_passes counts fused
    reduction dispatches (O(1) in slot count by construction — the CI
    perf guard asserts it), agg_strategy_* which strategy the
    backend-aware table picked, gidx_cache_* whether repeated queries
    skipped group-index recomputation, scan_tile_* whether tile partials
    merged on device, and the compressed-domain block reports how much
    of the scan path ran over ENCODED batches: code_domain_predicates /
    rle_run_predicates (predicates served on codes/runs),
    batches_code_bound (columns resident encoded — the capacity lever),
    batches_skipped_dict (equality literals that missed a sorted
    dictionary), and every decode-first reroute itemized by reason
    (compressed_fallback_*).  The aggregate-lane block reports how much
    of the AGGREGATE path ran compressed (agg_code_domain /
    agg_dict_space / agg_rle_runs) and the background compaction
    progress that keeps those lanes hot (passes, batches rewritten,
    bytes reclaimed, itemized compaction_skip_* declines).  With
    `catalog`, per-table encoding mix and at-rest vs decoded bytes ride
    along (including each table's own compressed_fallbacks tally — the
    compaction trigger)."""
    from snappydata_tpu import config
    from snappydata_tpu.storage import device_decode

    snap = global_registry().snapshot()
    c = snap["counters"]
    props = config.global_properties()
    hits = c.get("gidx_cache_hits", 0)
    misses = c.get("gidx_cache_misses", 0)
    dd = device_decode.counters()
    out = {
        "agg_reduce_strategy": props.get("agg_reduce_strategy"),
        "gidx_cache_bytes": props.get("gidx_cache_bytes"),
        "scan_tile_bytes": props.get("scan_tile_bytes"),
        "agg_reduce_passes": c.get("agg_reduce_passes", 0),
        "agg_strategies": {
            s: c.get(f"agg_strategy_{s}", 0)
            for s in ("unroll", "scatter", "matmul", "pallas")
            if c.get(f"agg_strategy_{s}", 0)},
        "gidx_cache_hits": hits,
        "gidx_cache_misses": misses,
        "gidx_cache_hit_rate":
            round(hits / (hits + misses), 3) if hits + misses else None,
        "scan_tiles": c.get("scan_tiles", 0),
        "scan_tile_device_merges": c.get("scan_tile_device_merges", 0),
        "scan_tile_host_merges": c.get("scan_tile_host_merges", 0),
        "scan_tile_prefetch_overlap":
            c.get("scan_tile_prefetch_overlap", 0),
        # --- compressed-domain execution -------------------------------
        "scan_compressed_domain": props.get("scan_compressed_domain"),
        "code_domain_predicates": c.get("code_domain_predicates", 0),
        "rle_run_predicates": c.get("rle_run_predicates", 0),
        "batches_skipped_dict": c.get("batches_skipped_dict", 0),
        "batches_code_bound": dd.get("batches_code_bound", 0),
        "batches_device_decoded": dd.get("batches_device_decoded", 0),
        "bytes_encoded": dd.get("bytes_encoded", 0),
        "bytes_decoded_equiv": dd.get("bytes_decoded_equiv", 0),
        "compressed_fallbacks": c.get("compressed_fallbacks", 0),
        "compressed_fallback_reasons": {
            k[len("compressed_fallback_"):]: v for k, v in sorted(c.items())
            if k.startswith("compressed_fallback_")},
        # --- aggregate-on-codes lanes ----------------------------------
        "agg_on_codes": props.get("agg_on_codes"),
        "agg_code_domain": c.get("agg_code_domain", 0),
        "agg_dict_space": c.get("agg_dict_space", 0),
        "agg_rle_runs": c.get("agg_rle_runs", 0),
        # --- background compaction (keeps the fast paths hot) ----------
        "compaction_enabled": props.get("compaction_enabled"),
        "compaction_passes": c.get("compaction_passes", 0),
        "compaction_batches_rewritten":
            c.get("compaction_batches_rewritten", 0),
        "compaction_bytes_reclaimed":
            c.get("compaction_bytes_reclaimed", 0),
        "compaction_skips": {
            k[len("compaction_skip_"):]: v for k, v in sorted(c.items())
            if k.startswith("compaction_skip_")},
    }
    if catalog is not None:
        try:
            out["tables"] = encoding_mix(catalog)
        except Exception:   # a racing DROP must not kill the dashboard
            out["tables"] = {}
    return out


def encoding_mix(catalog) -> Dict[str, dict]:
    """Per-table encoding mix and at-rest vs fully-decoded bytes — the
    capacity story behind compressed-domain execution.  decoded_bytes is
    what the live rows would occupy as dense device-dtype plates;
    at_rest_bytes is what the encoded batches actually hold; the
    device-resident bytes (cached plates, compressed or not) come from
    the device cache ledger."""
    from snappydata_tpu.storage.device import device_cache_bytes_by_table

    out: Dict[str, dict] = {}
    tables = [(info.name, info.data) for info in catalog.list_tables()
              if not isinstance(info.data, RowTableData)]
    resident = device_cache_bytes_by_table(tables)
    for info in catalog.list_tables():
        if isinstance(info.data, RowTableData):
            continue
        try:
            m = info.data.snapshot()
        except Exception:
            continue
        mix: Dict[str, int] = {}
        at_rest = 0
        decoded = 0
        for v in m.views:
            for f, col in zip(info.schema.fields, v.batch.columns):
                mix[col.encoding.name] = mix.get(col.encoding.name, 0) + 1
                at_rest += col.nbytes
                try:
                    width = 4 if f.dtype.name == "string" \
                        else max(1, col.data.dtype.itemsize) \
                        if col.encoding.name == "PLAIN" \
                        else f.dtype.device_dtype().itemsize
                except Exception:
                    width = 8
                decoded += col.num_rows * width
        rows = m.total_rows()
        out[info.name] = {
            "rows": rows,
            "batches": len(m.views),
            "encoding_mix": mix,
            "at_rest_bytes": at_rest,
            "decoded_bytes": decoded,
            "at_rest_ratio": round(at_rest / decoded, 4) if decoded
            else None,
            "device_resident_bytes": resident.get(info.name, 0),
            "resident_bytes_per_row":
                round(resident.get(info.name, 0) / rows, 2) if rows
                else None,
            # per-TABLE decode-first reroutes since the last compaction
            # pass over this table — the triage view: which table keeps
            # leaving the compressed domain, and WHY
            "compressed_fallbacks": table_fallbacks(info.data),
        }
    return out


def join_snapshot() -> dict:
    """Join-engine stats: live knobs + the device/host path counters for
    REST `/status/api/v1/join` and the dashboard's Join section.
    join_device_joins counts binds that stayed on device,
    join_host_fallbacks the reroutes to the pandas host join — itemized
    BY REASON STRING so a perf cliff is diagnosable from the dashboard;
    join_build_sorts vs join_build_cache_hits shows whether repeated
    joins skip the build argsort; join_expand_factor is expanded output
    rows per probe row on the one-to-many path."""
    from snappydata_tpu import config
    from snappydata_tpu.ops.join import join_build_cache_nbytes

    snap = global_registry().snapshot()
    c = snap["counters"]
    props = config.global_properties()
    hits = c.get("join_build_cache_hits", 0)
    misses = c.get("join_build_cache_misses", 0)
    out_rows = c.get("join_expand_out_rows", 0)
    in_rows = c.get("join_expand_probe_rows", 0)
    return {
        "device_join": props.get("device_join"),
        "join_expand_max_bytes": props.get("join_expand_max_bytes"),
        "join_build_cache_bytes": props.get("join_build_cache_bytes"),
        "join_device_joins": c.get("join_device_joins", 0),
        "join_host_fallbacks": c.get("join_host_fallbacks", 0),
        "join_fallback_reasons": {
            k[len("join_fallback_"):]: v for k, v in sorted(c.items())
            if k.startswith("join_fallback_")},
        "join_build_sorts": c.get("join_build_sorts", 0),
        "join_build_cache_hits": hits,
        "join_build_cache_misses": misses,
        "join_build_cache_hit_rate":
            round(hits / (hits + misses), 3) if hits + misses else None,
        "join_build_cache_nbytes": join_build_cache_nbytes(),
        "join_trans_cache_hits": c.get("join_trans_cache_hits", 0),
        "join_expand_out_rows": out_rows,
        "join_expand_probe_rows": in_rows,
        "join_expand_factor":
            round(out_rows / in_rows, 3) if in_rows else None,
    }


def mesh_snapshot(catalog=None, session=None) -> dict:
    """Mesh-execution stats for `/status/api/v1/mesh` and the
    dashboard's Mesh section: the active mesh + bucket→device placement,
    PER-DEVICE resident plate bytes (the proof sharded tables stay
    encoded per device), exchange/psum evidence, and the join
    distribution strategy counters — observable like the join engine's
    fallback reasons."""
    from snappydata_tpu import config
    from snappydata_tpu.engine.mesh_exec import mesh_layout_cache_nbytes
    from snappydata_tpu.parallel.mesh import MeshContext

    snap = global_registry().snapshot()
    c = snap["counters"]
    props = config.global_properties()
    ctx = MeshContext.current()
    if ctx is None and session is not None \
            and getattr(session, "_mesh_ctx", None) is not None:
        ctx = session._mesh_ctx
    out = {
        "mesh_shard_exec": props.get("mesh_shard_exec"),
        "mesh_join_strategy": props.get("mesh_join_strategy"),
        "mesh_broadcast_build_bytes":
            props.get("mesh_broadcast_build_bytes"),
        "active": ctx is not None,
        "mesh_shard_execs": c.get("mesh_shard_execs", 0),
        "mesh_psum_merges": c.get("mesh_psum_merges", 0),
        "mesh_join_broadcast": c.get("mesh_join_broadcast", 0),
        "mesh_join_shuffle": c.get("mesh_join_shuffle", 0),
        "mesh_shuffle_fallback_reasons": {
            k[len("mesh_join_shuffle_fallback_"):]: v
            for k, v in sorted(c.items())
            if k.startswith("mesh_join_shuffle_fallback_")},
        "mesh_fallback_reasons": {
            k[len("mesh_fallback_"):]: v for k, v in sorted(c.items())
            if k.startswith("mesh_fallback_")},
        "mesh_exchange_bytes": c.get("mesh_exchange_bytes", 0),
        "mesh_exchange_rows": c.get("mesh_exchange_rows", 0),
        "mesh_exchange_cache_hits": c.get("mesh_exchange_cache_hits", 0),
        "mesh_broadcast_bytes": c.get("mesh_broadcast_bytes", 0),
        "mesh_broadcast_cache_hits":
            c.get("mesh_broadcast_cache_hits", 0),
        "mesh_layout_cache_nbytes": mesh_layout_cache_nbytes(),
        "rebalances": c.get("mesh_rebalances", 0),
        "buckets_moved": c.get("mesh_buckets_moved", 0),
        "cache_entries_moved": c.get("mesh_cache_moves", 0),
        "bytes_moved": c.get("mesh_moved_bytes", 0),
    }
    if ctx is not None:
        out["num_devices"] = ctx.num_devices
        out["token"] = ctx.token
        out["placement"] = {
            "generation": ctx.placement.generation,
            "num_buckets": ctx.placement.num_buckets,
            "bucket_map": {str(k): v for k, v in
                           ctx.placement.bucket_map().items()},
        }
    if catalog is not None:
        from snappydata_tpu.storage.device import \
            device_cache_bytes_by_device

        try:
            per_dev = device_cache_bytes_by_device(
                (i.name, i.data) for i in catalog.list_tables())
        except Exception:
            per_dev = {}
        out["resident_bytes_by_device"] = {
            k: per_dev[k] for k in sorted(per_dev)}
    return out


def mvcc_snapshot(catalog=None) -> dict:
    """Snapshot-isolation stats for `/status/api/v1/mvcc` and the
    dashboard's MVCC section: the epoch clock, active pins, per-table
    version vector (current version/epoch/commit-seq + the retained-
    epoch list with pin counts and bytes), and the pin/conflict/trim
    counters every isolation claim is observable through."""
    from snappydata_tpu import config
    from snappydata_tpu.storage import mvcc

    snap = global_registry().snapshot()
    c = snap["counters"]
    out = {
        "enabled": bool(config.global_properties().get(
            "snapshot_isolation", True)),
        "retained_epochs_max": config.global_properties().get(
            "mvcc_retained_epochs"),
        "current_epoch": mvcc.current_epoch(),
        "active_pins": mvcc.active_pin_count(),
        "pins": c.get("mvcc_pins", 0),
        "pin_releases": c.get("mvcc_pin_releases", 0),
        "repins": c.get("mvcc_repins", 0),
        "ddl_conflicts": c.get("mvcc_ddl_conflicts", 0),
        "epoch_trims": c.get("mvcc_epoch_trims", 0),
        "view_pending_folds": c.get("view_pending_folds", 0),
        "view_pending_replays": c.get("view_pending_replays", 0),
        "retained_epoch_bytes": 0,
        "tables": {},
    }
    if catalog is not None:
        for info in catalog.list_tables():
            data = info.data
            if not hasattr(data, "_manifest"):
                continue
            try:
                m = data.snapshot()
                epochs = mvcc.retained_epochs_of(data)
            except Exception:
                continue
            retained_bytes = sum(e["bytes"] for e in epochs)
            out["retained_epoch_bytes"] += retained_bytes
            out["tables"][info.name] = {
                "version": int(m.version),
                "epoch": int(getattr(m, "epoch", 0)),
                "wal_seq": int(getattr(m, "wal_seq", 0)),
                "retained_epochs": epochs,
                "retained_bytes": retained_bytes,
            }
    return out


def storage_snapshot() -> dict:
    """Tiered-storage health for `/status/api/v1/storage` and the
    dashboard's Storage section: bytes resident at each tier rung, the
    self-healing ledger (quarantined tier files, rebuilds, bounded EIO
    re-reads, pressure demotions), prefetch-worker liveness (restarts
    vs silent degrade), and the failpoint registry's armed/fired state
    — the observable surface of the fault-injection story."""
    from snappydata_tpu.reliability import failpoints
    from snappydata_tpu.storage import prefetch, tier

    snap = global_registry().snapshot()
    c = snap["counters"]
    out = {"tier": tier.tier_snapshot(),
           "prefetch": prefetch.worker_snapshot(),
           "demotions_hbm": c.get("tier_demotions_hbm", 0),
           "demotions_host": c.get("tier_demotions_host", 0),
           "promotions": c.get("tier_promotions", 0),
           "crc_verifies": c.get("tier_crc_verifies", 0),
           "pressure_wakeups": c.get("tier_pressure_wakeups", 0),
           "failpoints": {"armed": failpoints.snapshot(),
                          "fires": c.get("failpoint_fires", 0)}}
    return out


def ha_snapshot(catalog=None, distributed=None) -> dict:
    """End-to-end request-reliability stats for `/status/api/v1/ha` and
    the dashboard's High-availability section: failovers, hedged reads,
    mutation-retry dedup, member rejoins, deadline expiries and the
    heartbeat health an operator alarms on — every reliability claim as
    an observable number. `distributed` (the lead's cluster view, when
    one exists) adds live membership and bucket-redundancy state."""
    from snappydata_tpu import config

    snap = global_registry().snapshot()
    c = snap["counters"]
    g = snap["gauges"]
    props = config.global_properties()
    out = {
        # knobs (what the policy IS, next to what it did)
        "client_timeout_s": props.get("client_timeout_s"),
        "query_timeout_s": props.get("query_timeout_s"),
        "hedge_reads": props.get("hedge_reads"),
        "hedge_after_ms": props.get("hedge_after_ms"),
        "mutation_dedup_entries_max": props.get("mutation_dedup_entries"),
        # failover plane
        "failover_member_failed": c.get("failover_member_failed", 0),
        "failover_retries": c.get("failover_retries", 0),
        "failover_redundancy_degraded":
            c.get("failover_redundancy_degraded", 0),
        "failover_redundancy_restored":
            c.get("failover_redundancy_restored", 0),
        "breaker_open": c.get("breaker_open", 0),
        # idempotent mutation retry (the lost-ack evidence pair)
        "mutation_retries": c.get("mutation_retries", 0),
        "mutation_dedup_hits": c.get("mutation_dedup_hits", 0),
        # hedged replica reads
        "hedged_reads_fired": c.get("hedged_reads_fired", 0),
        "hedged_reads_won": c.get("hedged_reads_won", 0),
        # member rejoin with resync
        "member_rejoins": c.get("member_rejoins", 0),
        "rejoin_clean_buckets": c.get("rejoin_clean_buckets", 0),
        "rejoin_copied_buckets": c.get("rejoin_copied_buckets", 0),
        "rejoin_partial_errors": c.get("rejoin_partial_errors", 0),
        # deadlines (client-side cutoffs + server-side cooperative stops)
        "deadline_exceeded": c.get("client_deadline_exceeded", 0),
        "governor_timeouts": c.get("governor_timeouts", 0),
        # membership health
        "member_heartbeat_failures": c.get("member_heartbeat_failures", 0),
        "heartbeats_stopped": g.get("heartbeats_stopped", 0.0) or 0.0,
    }
    if catalog is not None:
        dedup = getattr(catalog, "_mutation_dedup", None)
        out["mutation_dedup_entries"] = len(dedup) if dedup else 0
    if distributed is not None:
        try:
            out["members_total"] = len(distributed.alive)
            out["alive_members"] = sum(distributed.alive)
            out["degraded_buckets"] = len(distributed.degraded_buckets())
        except Exception:
            pass
    return out


class TableStatsService:
    def __init__(self, catalog, interval_s: Optional[float] = None,
                 registry=None):
        self.catalog = catalog
        self.interval_s = interval_s or \
            config.global_properties().stats_interval_s
        self.registry = registry or global_registry()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stats: Dict[str, dict] = {}
        self._lock = locks.named_lock("observability.stats")

    def collect_once(self) -> Dict[str, dict]:
        stats: Dict[str, dict] = {}
        for info in self.catalog.list_tables():
            if isinstance(info.data, RowTableData):
                rows = info.data.count()
                batches = 0
                in_memory_bytes = 0
                version = info.data.version
            else:
                m = info.data.snapshot()
                rows = m.total_rows()
                batches = len(m.views)
                in_memory_bytes = sum(v.batch.nbytes for v in m.views)
                version = m.version
            stats[info.name] = {
                "provider": info.provider,
                "row_count": rows,
                "batches": batches,
                "in_memory_bytes": in_memory_bytes,
                "buckets": info.buckets,
                "redundancy": info.redundancy,
                # mutation version: exchange caches key on this, NOT on row
                # count (updates that keep the count constant must still
                # invalidate — review finding). data_id distinguishes table
                # INCARNATIONS: a DROP/CREATE resets the version counter on
                # a fresh object, and (data_id, version) must not collide
                # with the old incarnation's token.
                "version": version,
                "data_id": id(info.data),
            }
        with self._lock:
            self._stats = stats
        self.registry.gauge("tables_total",
                            lambda c=len(stats): float(c))
        total_rows = sum(s["row_count"] for s in stats.values())
        self.registry.gauge("rows_total",
                            lambda r=total_rows: float(r))
        return stats

    def current(self) -> Dict[str, dict]:
        with self._lock:
            return dict(self._stats)

    def start(self) -> "TableStatsService":
        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.collect_once()
                except Exception as e:
                    # keep polling, but a permanently-failing collector
                    # must not look like a healthy idle thread
                    logging.getLogger(__name__).warning(
                        "stats poll failed: %s", e)
                    self.registry.inc("stats_poll_errors")

        self.collect_once()
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
