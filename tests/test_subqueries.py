"""Subquery support + review regressions (float-vs-int IN lists, NOT IN
with NULL, DML subqueries, NaN string normalization)."""

import numpy as np
import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog


@pytest.fixture()
def s():
    sess = SnappySession(catalog=Catalog())
    yield sess
    sess.stop()


def test_scalar_subquery(s):
    s.sql("CREATE TABLE t (a INT) USING column")
    s.sql("INSERT INTO t VALUES (1), (5), (9)")
    r = s.sql("SELECT a FROM t WHERE a = (SELECT max(a) FROM t)")
    assert r.rows() == [(9,)]
    r = s.sql("SELECT a FROM t WHERE a > (SELECT avg(a) FROM t)")
    assert r.rows() == [(9,)]


def test_in_and_exists_subqueries(s):
    s.sql("CREATE TABLE a (x INT) USING column")
    s.sql("CREATE TABLE b (y INT) USING column")
    s.sql("INSERT INTO a VALUES (1), (2), (3)")
    s.sql("INSERT INTO b VALUES (2), (3), (4)")
    assert sorted(r[0] for r in s.sql(
        "SELECT x FROM a WHERE x IN (SELECT y FROM b)").rows()) == [2, 3]
    assert s.sql("SELECT x FROM a WHERE x NOT IN (SELECT y FROM b)"
                 ).rows() == [(1,)]
    assert s.sql("SELECT count(*) FROM a WHERE EXISTS (SELECT 1 FROM b)"
                 ).rows()[0][0] == 3
    s.sql("DELETE FROM b WHERE y IS NOT NULL")
    assert s.sql("SELECT count(*) FROM a WHERE EXISTS (SELECT 1 FROM b)"
                 ).rows()[0][0] == 0


def test_not_in_with_null_is_never_true(s):
    s.sql("CREATE TABLE a (x INT) USING column")
    s.sql("CREATE TABLE b (y INT) USING column")
    s.sql("INSERT INTO a VALUES (1), (2)")
    s.sql("INSERT INTO b VALUES (1), (NULL)")
    assert s.sql("SELECT x FROM a WHERE x NOT IN (SELECT y FROM b)"
                 ).rows() == []


def test_float_column_in_large_int_list(s):
    s.sql("CREATE TABLE t (id INT, d DOUBLE) USING column")
    s.sql("INSERT INTO t VALUES (1, 1.5), (2, 2.0), (3, 9.5)")
    r = s.sql("SELECT id FROM t WHERE d IN (1,2,3,4,5,6,7,8,9)")
    assert r.rows() == [(2,)]  # 1.5/9.5 must NOT truncate-match


def test_large_in_list_sorted_lowering(s):
    s.sql("CREATE TABLE t (k BIGINT) USING column")
    s.insert_arrays("t", [np.arange(2000, dtype=np.int64)])
    vals = ",".join(str(v) for v in range(0, 2000, 7))
    r = s.sql(f"SELECT count(*) FROM t WHERE k IN ({vals})")
    assert r.rows()[0][0] == len(range(0, 2000, 7))
    r = s.sql(f"SELECT count(*) FROM t WHERE k NOT IN ({vals})")
    assert r.rows()[0][0] == 2000 - len(range(0, 2000, 7))


def test_dml_where_subquery(s):
    s.sql("CREATE TABLE a (x INT) USING column")
    s.sql("CREATE TABLE b (y INT) USING column")
    s.sql("INSERT INTO a VALUES (1), (2), (3)")
    s.sql("INSERT INTO b VALUES (1), (2)")
    n = s.sql("DELETE FROM a WHERE x IN (SELECT y FROM b)").rows()[0][0]
    assert n == 2
    n = s.sql("UPDATE a SET x = (SELECT max(y) FROM b) WHERE x = 3"
              ).rows()[0][0]
    assert n == 1
    assert s.sql("SELECT x FROM a").rows() == [(2,)]


def test_view_with_subquery_rejected(s):
    s.sql("CREATE TABLE a (x INT) USING column")
    with pytest.raises(Exception, match="view definitions"):
        s.sql("CREATE VIEW v AS SELECT x FROM a "
              "WHERE x IN (SELECT x FROM a)")


def test_nan_strings_normalize_to_null(s):
    from snappydata_tpu.native import fast_encode_strings

    lookup, store = {}, []
    vals = np.array(["a", np.nan, None, "b"], dtype=object)
    codes, nulls = fast_encode_strings(vals, lookup, store)
    assert store == ["a", "b"]
    assert nulls.tolist() == [False, True, True, False]
