"""Distributed scatter-gather over real server members: partitioned
ingest routing, partial aggregation + merge, collocated joins, replicated
dims (ref: partitioned regions + partial agg + CollectAggregateExec +
CollapseCollocatedPlans, exercised over Arrow Flight)."""

import numpy as np
import pandas as pd
import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.cluster import LocatorNode, ServerNode
from snappydata_tpu.cluster.distributed import (DistributedError,
                                                DistributedSession)


@pytest.fixture(scope="module")
def dist():
    locator = LocatorNode().start()
    servers = [ServerNode(locator.address, SnappySession(catalog=Catalog()))
               .start() for _ in range(3)]
    ds = DistributedSession(
        server_addresses=[s.flight_address for s in servers])
    yield ds, servers
    ds.close()
    for s in servers:
        s.stop()
    locator.stop()


@pytest.fixture(scope="module")
def loaded(dist):
    ds, servers = dist
    ds.sql("CREATE TABLE tx (k BIGINT, region STRING, amt DOUBLE) "
           "USING column OPTIONS (partition_by 'k')")
    ds.sql("CREATE TABLE dim (code STRING, label STRING) USING column")
    rng = np.random.default_rng(11)
    n = 30_000
    k = rng.integers(0, 5000, n).astype(np.int64)
    region = np.array(["e", "w", "n"], dtype=object)[rng.integers(0, 3, n)]
    amt = np.round(rng.random(n) * 100, 2)
    ds.insert_arrays("tx", [k, region, amt])
    ds.sql("INSERT INTO dim VALUES ('e', 'east'), ('w', 'west'), "
           "('n', 'north')")
    df = pd.DataFrame({"k": k, "region": region, "amt": amt})
    return ds, servers, df


def test_rows_sharded_across_servers(loaded):
    ds, servers, df = loaded
    counts = []
    for s in servers:
        r = s.session.sql("SELECT count(*) FROM tx").rows()[0][0]
        counts.append(r)
    assert sum(counts) == len(df)
    assert all(c > 0 for c in counts)          # every shard participates
    assert max(counts) < len(df)               # no server holds everything


def test_distributed_global_aggregate(loaded):
    ds, _, df = loaded
    r = ds.sql("SELECT count(*), sum(amt), avg(amt), min(amt), max(amt) "
               "FROM tx").rows()[0]
    assert r[0] == len(df)
    assert r[1] == pytest.approx(df.amt.sum())
    assert r[2] == pytest.approx(df.amt.mean())
    assert r[3] == pytest.approx(df.amt.min())
    assert r[4] == pytest.approx(df.amt.max())


def test_distributed_group_by_with_filter(loaded):
    ds, _, df = loaded
    r = ds.sql("SELECT region, count(*) AS c, sum(amt) AS total FROM tx "
               "WHERE amt > 50 GROUP BY region ORDER BY region")
    sel = df[df.amt > 50]
    exp = sel.groupby("region").agg(c=("amt", "size"), total=("amt", "sum"))
    for row, (reg, e) in zip(r.rows(), exp.sort_index().iterrows()):
        assert row[0] == reg
        assert row[1] == e.c
        assert row[2] == pytest.approx(e.total)


def test_distributed_scan_concat(loaded):
    ds, _, df = loaded
    r = ds.sql("SELECT k, amt FROM tx WHERE amt > 99.5")
    exp = df[df.amt > 99.5]
    assert r.num_rows == len(exp)


def test_distributed_replicated_join(loaded):
    ds, _, df = loaded
    r = ds.sql("SELECT d.label, sum(t.amt) AS total FROM tx t "
               "JOIN dim d ON t.region = d.code GROUP BY d.label "
               "ORDER BY d.label")
    exp = df.groupby("region").amt.sum()
    label_of = {"e": "east", "w": "west", "n": "north"}
    got = {row[0]: row[1] for row in r.rows()}
    for reg, total in exp.items():
        assert got[label_of[reg]] == pytest.approx(total)


def test_distributed_update_delete(loaded):
    ds, _, df = loaded
    ds.sql("CREATE TABLE mut (k BIGINT, v DOUBLE) USING column "
           "OPTIONS (partition_by 'k')")
    ds.insert_arrays("mut", [np.arange(100, dtype=np.int64),
                             np.ones(100)])
    n = ds.sql("UPDATE mut SET v = 5.0 WHERE k < 10").rows()[0][0]
    assert n == 10
    n = ds.sql("DELETE FROM mut WHERE k >= 90").rows()[0][0]
    assert n == 10
    r = ds.sql("SELECT count(*), sum(v) FROM mut").rows()[0]
    assert r[0] == 90
    assert r[1] == pytest.approx(10 * 5.0 + 80 * 1.0)


def test_collocated_join_allowed_non_collocated_rejected(loaded):
    ds, _, _ = loaded
    ds.sql("CREATE TABLE orders2 (ok BIGINT, cust BIGINT) USING column "
           "OPTIONS (partition_by 'ok')")
    ds.sql("CREATE TABLE items2 (ok BIGINT, price DOUBLE) USING column "
           "OPTIONS (partition_by 'ok', colocate_with 'orders2')")
    ds.insert_arrays("orders2", [np.arange(50, dtype=np.int64),
                                 np.arange(50, dtype=np.int64) % 7])
    ds.insert_arrays("items2", [np.arange(50, dtype=np.int64),
                                np.full(50, 2.0)])
    r = ds.sql("SELECT count(*), sum(i.price) FROM orders2 o "
               "JOIN items2 i ON o.ok = i.ok").rows()[0]
    assert r[0] == 50 and r[1] == pytest.approx(100.0)
    # non-collocated partitioned join: small side broadcasts automatically
    ds.sql("CREATE TABLE other (x BIGINT, tag STRING) USING column "
           "OPTIONS (partition_by 'x')")
    ds.insert_arrays("other", [np.arange(0, 50, 2, dtype=np.int64),
                               np.array(["t"] * 25, dtype=object)])
    r = ds.sql("SELECT count(*) FROM orders2 o JOIN other t ON o.ok = t.x")
    assert r.rows()[0][0] == 25  # broadcast exchange made it complete


def test_broadcast_exchange_group_by(loaded):
    ds, _, df = loaded
    # tx is partitioned by k; make a small partitioned dim on another key
    ds.sql("CREATE TABLE kdim (kk BIGINT, bucket_name STRING) USING column "
           "OPTIONS (partition_by 'kk')")
    kk = np.arange(0, 5000, dtype=np.int64)
    ds.insert_arrays("kdim", [kk, np.array(
        [f"b{k % 3}" for k in kk], dtype=object)])
    r = ds.sql("SELECT d.bucket_name, count(*) FROM tx t JOIN kdim d "
               "ON t.k = d.kk GROUP BY d.bucket_name ORDER BY d.bucket_name")
    exp = df.assign(b=[f"b{k % 3}" for k in df.k]).groupby("b").size()
    assert [(x[0], x[1]) for x in r.rows()] == list(exp.items())
