"""Distributed (multi-device) execution tests on the virtual 8-CPU mesh:
the same compiled query under batch-sharded inputs must produce identical
results, with GSPMD inserting the collectives (ref parity: partial
aggregation + CollectAggregateExec merge; replicated-table joins)."""

import jax
import numpy as np
import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.parallel import data_mesh, MeshContext
from snappydata_tpu.parallel.hashing import bucket_of_np, murmur3_hash_np
from snappydata_tpu.parallel.buckets import BucketMap
from snappydata_tpu.utils import tpch


def test_murmur3_matches_spark_vectors():
    # Spark: SELECT hash(1) == -559580957 (Murmur3_x86_32, seed 42)
    assert murmur3_hash_np(np.array([1], dtype=np.int32))[0] == -559580957
    h32 = murmur3_hash_np(np.arange(1000, dtype=np.int32))
    h64 = murmur3_hash_np(np.arange(1000, dtype=np.int64))
    assert len(np.unique(h32)) > 990  # well-distributed
    assert not (h32 == h64).all()     # int vs long hash differently (Spark)


def test_bucket_map_redundancy():
    bm = BucketMap(num_buckets=16, num_members=4, redundancy=1)
    for b in range(16):
        members = bm.members_of(b)
        assert len(members) == 2 and len(set(members)) == 2
    owned = [bm.buckets_of_member(m) for m in range(4)]
    assert sorted(sum(owned, [])) == sorted(list(range(16)) * 2)
    keys = np.arange(1000, dtype=np.int64)
    assert (bm.bucket_for_rows(keys) == bucket_of_np(keys, 16)).all()


@pytest.fixture(scope="module")
def loaded():
    sess = SnappySession(catalog=Catalog())
    tpch.load_tpch(sess, sf=0.002, seed=3)
    sess.sql("CREATE TABLE dim (id INT PRIMARY KEY, name STRING) USING row")
    sess.sql("INSERT INTO dim VALUES (0, 'zero'), (1, 'one')")
    return sess


def _rows(result):
    return result.rows()


def test_distributed_q1_matches_single_device(loaded):
    s = loaded
    single = _rows(s.sql(tpch.Q1))
    mesh = data_mesh(8)
    with MeshContext(mesh):
        s.executor.clear_cache()
        dist = _rows(s.sql(tpch.Q1))
    s.executor.clear_cache()
    assert len(single) == len(dist)
    for a, b in zip(single, dist):
        assert a[0] == b[0] and a[1] == b[1]
        for x, y in zip(a[2:], b[2:]):
            assert x == pytest.approx(y, rel=1e-9)


def test_distributed_q3_join_matches(loaded):
    s = loaded
    single = _rows(s.sql(tpch.Q3))
    with MeshContext(data_mesh(8)):
        s.executor.clear_cache()
        dist = _rows(s.sql(tpch.Q3))
    s.executor.clear_cache()
    assert len(single) == len(dist)
    for a, b in zip(single, dist):
        assert a[0] == b[0]
        assert a[1] == pytest.approx(b[1], rel=1e-9)


def test_distributed_row_table_replicated_join(loaded):
    s = loaded
    q = ("SELECT d.name, count(*) AS c FROM orders o JOIN dim d "
         "ON o.o_shippriority = d.id GROUP BY d.name ORDER BY d.name")
    single = _rows(s.sql(q))
    with MeshContext(data_mesh(8)):
        s.executor.clear_cache()
        dist = _rows(s.sql(q))
    s.executor.clear_cache()
    assert single == dist


def test_sharded_inputs_actually_span_devices(loaded):
    s = loaded
    info = s.catalog.lookup_table("lineitem")
    from snappydata_tpu.storage.device import build_device_table

    with MeshContext(data_mesh(8)) as ctx:
        dt = build_device_table(info.data, None, [4])
        col = dt.columns[4]
        # l_quantity is VALUE_DICT: under a mesh it stays RESIDENT
        # encoded — the CodePlate leaves shard on the batch axis (the
        # decoded capacity-row plate never materializes globally)
        arr = col.codes if hasattr(col, "codes") else col
        assert arr.shape[0] % 8 == 0
        assert len(arr.sharding.device_set) == 8
