"""Cluster runtime tests: membership + failure detection, lead election +
failover, Flight SQL/ingest, client failover, REST API (ref analogue:
ClusterManagerTestBase dunit tier — a real embedded cluster in-process;
QueryRoutingDUnitTest; ExecutorInitiator lead-failover)."""

import json
import time
import urllib.request

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # heavy/XLA-compile-bound; deselect with -m 'not slow'

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.cluster import (LeadNode, LocatorNode, ServerNode,
                                    SnappyClient)
from snappydata_tpu.cluster.locator import LocatorClient


@pytest.fixture()
def cluster():
    catalog = Catalog()
    locator = LocatorNode().start()
    lead_sess = SnappySession(catalog=catalog)
    server_sess = SnappySession(catalog=catalog)
    lead = LeadNode(locator.address, lead_sess, lease_s=1.0).start(
        wait_for_primary=True)
    server = ServerNode(locator.address, server_sess).start()
    yield locator, lead, server, catalog
    server.stop()
    lead.stop()
    locator.stop()


def test_membership_and_failure_detection():
    locator = LocatorNode().start()
    try:
        a = LocatorClient(locator.address, "m-a", "server", port=1)
        a.register()
        b = LocatorClient(locator.address, "m-b", "server", port=2)
        b.register()
        assert {m.member_id for m in a.members()} == {"m-a", "m-b"}
        # b stops heartbeating → departs after member-timeout
        locator.locator.state.timeout_s = 0.3
        a.start_heartbeats(interval_s=0.1)
        deadline = time.time() + 5
        ids = set()
        while time.time() < deadline:
            ids = {m.member_id for m in a.members()}
            if ids == {"m-a"}:
                break
            time.sleep(0.1)
        assert ids == {"m-a"}
        a.close()
    finally:
        locator.stop()


def test_lead_election_and_failover():
    catalog = Catalog()
    locator = LocatorNode().start()
    try:
        locator.locator.state.timeout_s = 0.5
        s1 = SnappySession(catalog=catalog)
        s2 = SnappySession(catalog=catalog)
        primary = LeadNode(locator.address, s1, lease_s=0.5).start(
            wait_for_primary=True)
        standby = LeadNode(locator.address, s2, lease_s=0.5).start()
        time.sleep(0.8)
        assert primary.is_primary and not standby.is_primary
        # primary dies → standby takes the lock (ref: __PRIMARY_LEADER_LS)
        primary.stop()
        deadline = time.time() + 10
        while not standby.is_primary and time.time() < deadline:
            time.sleep(0.1)
        assert standby.is_primary
        standby.stop()
    finally:
        locator.stop()


def test_flight_sql_roundtrip(cluster):
    locator, lead, server, catalog = cluster
    client = SnappyClient(address=server.flight_address)
    client.execute("CREATE TABLE t (a INT, b STRING) USING column")
    client.execute("INSERT INTO t VALUES (1, 'x'), (2, 'y')")
    table = client.sql("SELECT a, b FROM t ORDER BY a")
    assert table.column("a").to_pylist() == [1, 2]
    assert table.column("b").to_pylist() == ["x", "y"]
    client.close()


def test_flight_bulk_ingest(cluster):
    locator, lead, server, catalog = cluster
    client = SnappyClient(address=server.flight_address)
    client.execute("CREATE TABLE metrics (id BIGINT, v DOUBLE) USING column")
    client.insert("metrics", {"id": np.arange(10000, dtype=np.int64),
                              "v": np.linspace(0, 1, 10000)})
    out = client.sql("SELECT count(*), sum(v) FROM metrics")
    assert out.column(0).to_pylist() == [10000]
    assert out.column(1).to_pylist()[0] == pytest.approx(5000.0)
    stats = client.stats()
    assert stats["metrics"]["row_count"] == 10000
    client.close()


def test_client_failover_between_members(cluster):
    locator, lead, server, catalog = cluster
    client = SnappyClient(locator=locator.address)
    client.execute("CREATE TABLE ft (a INT) USING column")
    client.execute("INSERT INTO ft VALUES (1)")
    # kill whichever member the client is talking to; next call fails over
    server.stop()
    time.sleep(0.2)
    out = client.sql("SELECT count(*) FROM ft")
    assert out.column(0).to_pylist() == [1]
    client.close()


def test_rest_status_metrics_jobs(cluster):
    locator, lead, server, catalog = cluster
    lead.session.sql("CREATE TABLE rt (a INT) USING column")
    lead.session.sql("INSERT INTO rt VALUES (1), (2)")
    lead.stats_service.collect_once()
    base = f"http://{lead.rest_address}"

    cluster_info = json.loads(urllib.request.urlopen(
        base + "/status/api/v1/cluster").read())
    assert "rt" in cluster_info["tables"]
    roles = {m["role"] for m in cluster_info["members"]}
    assert {"lead", "server"} <= roles

    metrics = json.loads(urllib.request.urlopen(
        base + "/metrics/json").read())
    assert metrics["counters"].get("queries", 0) >= 1
    prom = urllib.request.urlopen(base + "/metrics/prometheus").read()
    assert b"snappy_tpu_queries_total" in prom

    # job API
    req = urllib.request.Request(
        base + "/jobs", data=json.dumps(
            {"sql": "SELECT sum(a) FROM rt"}).encode(),
        headers={"Content-Type": "application/json"})
    job = json.loads(urllib.request.urlopen(req).read())
    deadline = time.time() + 10
    status = {}
    while time.time() < deadline:
        status = json.loads(urllib.request.urlopen(
            base + f"/jobs/{job['jobId']}").read())
        if status["status"] in ("FINISHED", "ERROR"):
            break
        time.sleep(0.05)
    assert status["status"] == "FINISHED"
    assert status["rows"] == [[3]]


def test_flight_schema_without_execution_and_paging(cluster):
    """get_flight_info derives the schema from the analyzer (no query
    execution); do_get pages results as record batches."""
    import pyarrow as pa
    import pyarrow.flight as pafl

    locator, lead, server, catalog = cluster
    client = SnappyClient(address=server.flight_address)
    client.execute("CREATE TABLE fs (a BIGINT, s STRING, d DOUBLE) "
                   "USING column")
    client.insert("fs", {"a": np.arange(200_000, dtype=np.int64),
                         "s": np.array(["x"] * 200_000, dtype=object),
                         "d": np.ones(200_000)})
    desc = pafl.FlightDescriptor.for_command(
        json.dumps({"sql": "SELECT a, s, sum(d) AS t FROM fs "
                           "GROUP BY a, s"}).encode())
    info = client._client().get_flight_info(desc)
    assert info.schema.field("a").type == pa.int64()
    assert info.schema.field("s").type == pa.string()
    assert info.schema.field("t").type in (pa.float64(), pa.float32())

    reader = client._client().do_get(pafl.Ticket(
        json.dumps({"sql": "SELECT a FROM fs", "page_rows": 4096}
                   ).encode()))
    batches = [b for b in reader]
    assert len(batches) > 10  # paged, not one monolith
    total = sum(len(b.data) for b in batches)
    assert total == 200_000
    client.close()


def test_query_log_and_plan_ui(cluster):
    """Live query log + on-demand plan view (ref: SnappySQLListener SQL
    tab) and member version handshake."""
    locator, lead, server, catalog = cluster
    lead.session.sql("CREATE TABLE ql (a INT) USING column")
    lead.session.sql("INSERT INTO ql VALUES (1), (2), (3)")
    lead.session.sql("SELECT count(*) FROM ql")
    base = f"http://{lead.rest_address}"
    qs = json.loads(urllib.request.urlopen(
        base + "/status/api/v1/queries").read())
    assert any("count(*)" in q["sql"] for q in qs)
    qid = max(q["id"] for q in qs if "count(*)" in q["sql"])
    plan = json.loads(urllib.request.urlopen(
        base + f"/status/api/v1/queries/plan?id={qid}").read())
    assert any("Aggregate" in line or "Relation" in line
               for line in plan["plan"])
    # dashboard renders the recent-query table
    html = urllib.request.urlopen(base + "/dashboard").read().decode()
    assert "Recent queries" in html

    # protocol handshake: a member speaking another generation is refused
    from snappydata_tpu.cluster.locator import PROTOCOL_VERSION

    bad = LocatorClient(locator.address, "bad-member", "server", port=9)
    try:
        resp = bad._request({
            "op": "register", "member_id": "bad-member", "role": "server",
            "host": "127.0.0.1", "port": 9,
            "protocol": PROTOCOL_VERSION + 1})
        assert resp.get("ok") is False
        assert "protocol version mismatch" in resp.get("error", "")
        assert "bad-member" not in {
            m.member_id for m in bad.members()}
    finally:
        bad.close()
