"""Query results: named host columns with null masks."""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from snappydata_tpu import types as T


@dataclasses.dataclass
class Result:
    names: List[str]
    columns: List[np.ndarray]          # host arrays (strings materialized)
    nulls: List[Optional[np.ndarray]]  # bool masks or None
    dtypes: List[T.DataType]

    @property
    def num_rows(self) -> int:
        return int(self.columns[0].shape[0]) if self.columns else 0

    def rows(self) -> List[tuple]:
        out = []
        for i in range(self.num_rows):
            row = []
            for c, nmask in zip(self.columns, self.nulls):
                if nmask is not None and nmask[i]:
                    row.append(None)
                else:
                    v = c[i]
                    row.append(v.item() if hasattr(v, "item") else v)
            out.append(tuple(row))
        return out

    def column(self, name: str) -> np.ndarray:
        return self.columns[[n.lower() for n in self.names].index(name.lower())]

    def to_pandas(self):
        import pandas as pd

        data = {}
        for name, c, nmask in zip(self.names, self.columns, self.nulls):
            if nmask is not None and nmask.any():
                obj = c.astype(object)
                obj[nmask] = None
                data[name] = obj
            else:
                data[name] = c
        return pd.DataFrame(data)

    def __repr__(self):
        head = self.rows()[:20]
        return (f"Result({self.num_rows} rows: {', '.join(self.names)})\n"
                + "\n".join(str(r) for r in head))


def unscale_decimal_col(c: np.ndarray, dt) -> np.ndarray:
    """One column out of the exact-decimal scaled-int64 domain into
    plain float64 (no-op for anything else) — the SINGLE implementation
    every host consumer shares."""
    if dt is not None and dt.name == "decimal" \
            and getattr(dt, "is_exact", False) \
            and np.issubdtype(np.asarray(c).dtype, np.integer):
        return np.asarray(c, dtype=np.float64) / (10 ** dt.scale)
    return c


def to_host_domain(res: Result) -> Result:
    """Result with exact-decimal scaled-int64 columns unscaled to the
    plain float64 HOST domain — what ingest consumers (CTAS /
    INSERT..SELECT coercion into host plates) and host numeric code
    expect. Without this, a scaled column would be stored verbatim and
    read back 10^scale too large (review finding)."""
    cols = [unscale_decimal_col(c, dt)
            for c, dt in zip(res.columns, res.dtypes)]
    if all(a is b for a, b in zip(cols, res.columns)):
        return res
    return Result(res.names, cols, res.nulls, res.dtypes)


def finalize_decimals(res: Result) -> Result:
    """User-boundary decode of DECIMAL columns to decimal.Decimal
    objects (the JDBC-BigDecimal analogue; ref readDecimal,
    encoders/.../encoding/ColumnEncoding.scala:137-140). Inside the
    engine decimals ride as scaled int64 (exact path) or plain floats
    (host fallback / p>18); both decode here:

    - integer column + exact DecimalType -> Decimal(v) * 10^-s, EXACT;
    - float column + DecimalType -> Decimal quantized at the column
      scale (exact whenever the f64 faithfully held the value).

    Applied once, by the session/front-door layers — never
    mid-pipeline, where numeric host ops still need numpy domains."""
    changed = False
    cols = list(res.columns)
    for i, (c, dt) in enumerate(zip(res.columns, res.dtypes)):
        if dt is None or dt.name != "decimal":
            continue
        arr = np.asarray(c)
        if arr.dtype == object:
            continue  # already decoded (or host objects)
        if np.issubdtype(arr.dtype, np.integer) \
                and getattr(dt, "is_exact", False):
            out = np.array([T.unscaled_to_python(dt, v) for v in arr],
                           dtype=object)
        elif np.issubdtype(arr.dtype, np.floating):
            out = np.array([T.float_to_python_decimal(dt, v)
                            for v in arr], dtype=object)
        else:
            continue
        cols[i] = out
        changed = True
    if not changed:
        return res
    return Result(res.names, cols, res.nulls, res.dtypes)


def empty_result(names, dtypes) -> Result:
    cols = [np.empty(0, dtype=dt.np_dtype if dt.name != "string" else object)
            for dt in dtypes]
    return Result(list(names), cols, [None] * len(names), list(dtypes))
