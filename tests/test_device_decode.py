"""Device decode (round-3 verdict Missing #5 / task 8): RLE and
boolean-bitset batches bind by shipping the ENCODED arrays to the device
and expanding in-trace, with results identical to the host-decode path
and a measured transfer reduction (ref: decode-at-scan generated code,
ColumnTableScan.scala:684 genCodeColumnBuffer)."""

import numpy as np
import pytest

from snappydata_tpu import SnappySession, config
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.storage import device_decode
from snappydata_tpu.storage.encoding import Encoding


def _rle_session():
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE rle_t (k BIGINT, grp BIGINT, flag BOOLEAN, "
          "v DOUBLE) USING column")
    n = 60_000
    rng = np.random.default_rng(3)
    k = np.arange(n, dtype=np.int64)
    grp = np.sort(rng.integers(0, 5, n)).astype(np.int64)   # RLE-friendly
    flag = (k % 3 == 0)
    v = np.round(rng.random(n) * 100, 2)
    s.insert_arrays("rle_t", [k, grp, flag, v])
    data = s.catalog.describe("rle_t").data
    data.force_rollover()            # cut the batch so encodings apply
    return s, k, grp, flag, v, data


def test_rle_batches_decode_on_device_and_match():
    s, k, grp, flag, v, data = _rle_session()
    m = data.snapshot()
    encs = {m.views[0].batch.columns[i].encoding for i in (1, 2)}
    assert Encoding.RUN_LENGTH in encs, "grp should be RLE at rest"
    assert Encoding.BOOLEAN_BITSET in encs, "flag should be bitset at rest"

    device_decode.reset_counters()
    r = s.sql("SELECT grp, count(*), sum(v) FROM rle_t GROUP BY grp "
              "ORDER BY grp")
    c = device_decode.counters()
    assert c["batches_device_decoded"] >= 1
    assert c["bytes_encoded"] < c["bytes_decoded_equiv"] / 4, \
        "encoded transfer should be far below the decoded plate size"
    for gi, cnt, sv in r.rows():
        mm = grp == gi
        assert cnt == int(mm.sum())
        assert sv == pytest.approx(float(v[mm].sum()))

    r2 = s.sql("SELECT count(*) FROM rle_t WHERE flag")
    assert r2.rows()[0][0] == int(flag.sum())
    s.stop()


def test_rle_predicate_pushdown_still_correct():
    s, k, grp, flag, v, _ = _rle_session()
    r = s.sql("SELECT count(*), sum(v) FROM rle_t WHERE grp = 2")
    mm = grp == 2
    assert r.rows()[0][0] == int(mm.sum())
    assert r.rows()[0][1] == pytest.approx(float(v[mm].sum()))
    s.stop()


def test_deltas_fall_back_to_host_decode():
    s, k, grp, flag, v, data = _rle_session()
    s.sql("UPDATE rle_t SET v = 0.0 WHERE k < 100")
    r = s.sql("SELECT sum(v) FROM rle_t")
    expect = float(v[k >= 100].sum())
    assert r.rows()[0][0] == pytest.approx(expect)
    # grouping column updates create deltas on grp itself
    s.sql("UPDATE rle_t SET grp = 99 WHERE k < 50")
    r2 = s.sql("SELECT count(*) FROM rle_t WHERE grp = 99")
    assert r2.rows()[0][0] == 50
    s.stop()


def test_disabled_flag_matches():
    old = config.global_properties().device_decode
    try:
        config.global_properties().device_decode = False
        s, k, grp, flag, v, _ = _rle_session()
        device_decode.reset_counters()
        r = s.sql("SELECT grp, sum(v) FROM rle_t GROUP BY grp ORDER BY grp")
        assert device_decode.counters()["batches_device_decoded"] == 0
        for gi, sv in r.rows():
            assert sv == pytest.approx(float(v[grp == gi].sum()))
        s.stop()
    finally:
        config.global_properties().device_decode = old
