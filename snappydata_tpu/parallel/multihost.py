"""Multi-host initialization: the jax.distributed path (SURVEY §7 step 4).

The reference scales past one machine through its store's P2P membership
plus NCCL-style transports; the TPU-native equivalent is
`jax.distributed.initialize` — after it, `jax.devices()` spans every
host in the slice and GSPMD collectives ride ICI within a pod (DCN
across pods), so the SAME `Mesh`/`pjit` code the single-host path uses
scales to multi-host with no query-engine changes (the "pick a mesh,
annotate shardings, let XLA insert collectives" recipe).

Topology composition with the cluster plane:
- one snappydata server process per HOST, each joining the locator;
- each process calls `initialize_multihost()` at boot (before any jax
  API) with the shared coordinator address;
- the server's submesh (`ServerNode(mesh_devices=...)`) then selects
  its LOCAL devices out of the global device list (`local_devices()`),
  while cross-server exchanges keep riding Arrow Flight.

Configuration (flags or environment):
  SNAPPY_COORDINATOR=host:port   the process-0 coordinator endpoint
  SNAPPY_NUM_PROCESSES=N         world size
  SNAPPY_PROCESS_ID=i            this process's rank

Tested two ways: unit tests cover the argument plumbing / env
precedence (monkeypatched initialize), and tests/test_multihost_real.py
EXECUTES `jax.distributed.initialize` across two real OS processes on
the CPU backend — cross-process GSPMD collective value-asserted, plus
the full `python -m snappydata_tpu server --coordinator ...` composed
topology with per-process submeshes.
"""

from __future__ import annotations

import os
from typing import Optional

_initialized = False


def initialize_multihost(coordinator: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> bool:
    """Initialize the jax multi-host runtime from args or SNAPPY_* env.
    Returns False (no-op) when no coordinator is configured — single-host
    deployments need nothing. Must run before the first jax API call.
    Safe to call twice (second call is a no-op)."""
    global _initialized
    if _initialized:
        return True
    coordinator = coordinator or os.environ.get("SNAPPY_COORDINATOR")
    if not coordinator:
        return False
    num_processes = num_processes if num_processes is not None else \
        int(os.environ.get("SNAPPY_NUM_PROCESSES", "1"))
    process_id = process_id if process_id is not None else \
        int(os.environ.get("SNAPPY_PROCESS_ID", "0"))
    import jax

    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True
    return True


def local_device_indices() -> list:
    """Indices (into the GLOBAL jax.devices() list) of THIS process's
    devices — what a per-host ServerNode passes as `mesh_devices` so its
    submesh covers exactly the chips it hosts."""
    import jax

    all_devices = jax.devices()
    local = set(id(d) for d in jax.local_devices())
    return [i for i, d in enumerate(all_devices) if id(d) in local]


