"""Prepared-statement registry: compile-once parameterized plans.

The reference ships prepared statements through its thrift/DRDA network
layer because per-query parse+plan dominates short queries
(cluster/README-thrift.md; SnappySession's plan cache keyed on the
tokenized plan is the other half).  Here a `PreparedPlan` runs the whole
front half of the pipeline ONCE — parse → optimize → analyze →
tokenize → host-op peel → (lazily) device compile — and every execute
binds `?` values as RUNTIME arguments of the already-jitted XLA program:
zero re-parse, zero re-tokenization, zero recompiles across bind values.

Registry entries are shared across principals (analysis is
user-independent — row-level-security predicates bake in at resolution
and any CREATE/DROP POLICY bumps `catalog.generation`, which forces a
re-prepare); authorization against the EXECUTING principal's grants
happens per execute.  Entries are LRU-bounded by `serving_max_handles`
and their (host) bytes ride the resource broker's unified ledger.
"""

from __future__ import annotations

import threading
from snappydata_tpu.utils import locks
import time
import weakref
from collections import OrderedDict
from typing import Optional, Sequence, Tuple

from snappydata_tpu import config
from snappydata_tpu.observability.metrics import global_registry
from snappydata_tpu.sql import ast


class ServingError(Exception):
    """Statement can't be held by the serving registry (not a query),
    or a prepared execute was malformed (bind arity mismatch, unknown
    EXECUTE name)."""


def _plan_nodes(plan: ast.Plan) -> int:
    """Rough node count (plans + their expressions) for the registry's
    ledger estimate."""
    n = 1
    for e in ast.plan_exprs(plan):
        n += sum(1 for _ in ast.walk(e))
    for k in plan.children():
        n += _plan_nodes(k)
    return n


def _has_window(plan: ast.Plan) -> bool:
    if isinstance(plan, ast.WindowedRelation):
        return True
    for e in ast.plan_exprs(plan):
        for x in ast.walk(e):
            if isinstance(x, (ast.ScalarSubquery, ast.InSubquery,
                              ast.ExistsSubquery)) and _has_window(x.plan):
                return True
    return any(_has_window(k) for k in plan.children())


def _has_subquery(plan: ast.Plan) -> bool:
    for e in ast.plan_exprs(plan):
        for x in ast.walk(e):
            if isinstance(x, (ast.ScalarSubquery, ast.InSubquery,
                              ast.ExistsSubquery)):
                return True
    return any(_has_subquery(k) for k in plan.children())


class PreparedPlan:
    """One registry entry: the prepared (analyzed + tokenized) form of a
    query, shared by every session/principal executing that SQL shape.
    Revalidates itself against `catalog.generation` (DDL, policy and UDF
    changes all bump it)."""

    def __init__(self, session, sql_text: str):
        self.sql = sql_text
        self.catalog = session.catalog
        self._lock = locks.named_lock("serving.plan")
        # per-compiled-plan micro-batch queue lives on the entry so it
        # dies with it (see batcher.BatchQueue)
        self.batch_queue = None
        self._used = False          # first execute is the 'miss' execute
        self.executes = 0
        self._build(session)

    # -- prepare pipeline (runs once; again only on generation change) --

    def _build(self, session) -> None:
        """Run the prepare pipeline and publish the result ATOMICALLY:
        every derived field lands in one `__dict__.update` at the end,
        so (a) a failed build publishes nothing — the stale generation
        makes the next execute retry and re-raise the real error instead
        of running a half-built plan, and (b) an execute racing a
        DDL-triggered rebuild reads either the complete old state or the
        complete new state, never a torn mix (e.g. old tokenized under a
        new core_key, which would poison the plan cache)."""
        from snappydata_tpu.sql.parser import parse

        stmt = parse(self.sql)
        if not isinstance(stmt, ast.Query):
            raise ServingError(
                "only queries can be prepared (PREPARE name AS SELECT ...)")
        st = {
            "stmt": stmt,
            "tokenized": None,
            "lit_params": (),
            "param_count": _count_params(stmt.plan),
            "host_ops": [],
            "core": None,
            "core_key": None,
            "schema": None,
            "_compiled": None,
            "_compiled_gen": -1,
            "_estimate": None,
            "_is_point": False,
            "_batchable": False,
            "_batchable_gen": -1,
            "point_exec": None,
            "generation": self.catalog.generation,
        }
        # shapes the prepared fast path can't serve run the full session
        # pipeline per execute (still a handle: arity checks, governor
        # admission and the registry's observability all apply)
        st["passthrough"] = self._passthrough_reason(session, stmt)
        if st["passthrough"] is not None:
            st["nbytes"] = len(self.sql) * 2 + 512
            self.__dict__.update(st)
            return
        from snappydata_tpu.engine.executor import _plan_key, peel_host_ops
        from snappydata_tpu.session import _output_schema
        from snappydata_tpu.sql.analyzer import (assign_param_positions,
                                                 tokenize_plan)
        from snappydata_tpu.sql.optimizer import optimize

        plan = optimize(stmt.plan, self.catalog)
        resolved, _ = session.analyzer.analyze_plan(plan)
        if session.conf.tokenize and session.conf.plan_caching:
            st["tokenized"], st["lit_params"] = tokenize_plan(resolved)
        else:
            st["tokenized"], st["lit_params"] = \
                assign_param_positions(resolved, 0), ()
        st["param_count"] = _count_params(st["tokenized"])
        st["schema"] = _output_schema(resolved)
        st["host_ops"], st["core"] = peel_host_ops(st["tokenized"])
        st["core_key"] = _plan_key(st["core"], self.catalog)
        st["nbytes"] = len(self.sql) * 2 \
            + 96 * _plan_nodes(st["tokenized"])
        st["_is_point"] = _is_row_point_shape(st["core"], self.catalog)
        # PK/index point shapes pre-extract the probe ONCE: the engine's
        # per-execute _try_point_lookup walks the AST and rebuilds the
        # projection metadata on every call — measurable on the serving
        # profile at thousands of lookups per second
        if st["_is_point"] and not st["host_ops"]:
            st["point_exec"] = _build_point_exec(st["core"], self.catalog)
        self.__dict__.update(st)

    def _passthrough_reason(self, session, stmt) -> Optional[str]:
        if stmt.with_error is not None:
            return "error_clause"       # AQP estimation surface
        if _has_window(stmt.plan):
            return "stream_window"      # cutoff literal computed per read
        if _has_subquery(stmt.plan):
            return "subquery"           # rewritten per execution
        # (a session-level mesh is NOT baked here: entries are shared
        # across sessions of the catalog, so mesh routing is decided by
        # the EXECUTING session in _execute_inner)
        if _count_params(stmt.plan) == 0:
            # a 0-param prepared BIG aggregate must keep the tiled-scan
            # path (it only engages without user params)
            try:
                if session._tile_budget() > 0 and \
                        session._tilable_agg_shape(stmt.plan) is not None:
                    return "tiled_scan"
            except Exception:
                return "tiled_scan"
        return None

    # -- execute-time helpers -------------------------------------------

    def revalidate(self, session) -> None:
        """Re-prepare when DDL/policies/UDFs changed the catalog since
        this entry was built (generation bump)."""
        if self.generation == self.catalog.generation:
            return
        with self._lock:
            if self.generation != self.catalog.generation:
                self._build(session)
                global_registry().inc("serving_reprepares")

    def compiled_for(self, session):
        """The core node's CompiledPlan (None when it has no device
        lowering) — resolved through the executor's plan cache once per
        generation, then pinned here so fused dispatches and
        straight-through executes skip even the cache lookup."""
        gen = self.catalog.generation
        if self._compiled_gen != gen:
            with self._lock:
                if self._compiled_gen != gen:
                    self._compiled = session.executor.compiled_core(
                        self.core, self.core_key)
                    self._compiled_gen = gen
        return self._compiled

    def estimate_bytes(self, session) -> int:
        if self._estimate is None:
            from snappydata_tpu import resource

            try:
                self._estimate = resource.estimate_statement_bytes(
                    self.catalog, self.stmt)
            except Exception:
                self._estimate = 0
        return self._estimate

    def batchable(self, session) -> bool:
        """Fusable into a vmapped multi-request dispatch: has runtime
        params, compiles to a device region, and isn't a row-table
        point-lookup shape (index probes are O(1) on host already).
        Cached per generation — this sits on the per-execute path."""
        if self._batchable_gen == self.catalog.generation:
            return self._batchable
        if self.passthrough is not None or self.param_count == 0 \
                or self._is_point:
            self._batchable = False
        else:
            self._batchable = self.compiled_for(session) is not None
        self._batchable_gen = self.catalog.generation
        return self._batchable

    def assemble_batched(self, session, outs, tables, index: int,
                         params: Tuple):
        """Slice request `index` out of a fused dispatch's outs and run
        it through assemble + this plan's host post-ops.  Returns None
        when that request overflowed its static bounds (the caller
        reroutes it through the engine's normal path, which reraises the
        documented loud fallback)."""
        import numpy as np

        mask, pairs, overflow = outs
        if bool(np.asarray(overflow[index])):
            return None
        sliced = (mask[index],
                  [(v[index], nl[index] if nl is not None else None)
                   for v, nl in pairs],
                  overflow[index])
        compiled = self._compiled
        result = compiled._assemble(sliced, tables)
        for op in reversed(self.host_ops):
            result = session.executor._apply_host_op(op, result, params)
        return result


def _count_params(plan: ast.Plan) -> int:
    n = 0
    for e in ast.plan_exprs(plan):
        for x in ast.walk(e):
            if isinstance(x, ast.Param):
                n += 1
            elif isinstance(x, (ast.ScalarSubquery, ast.InSubquery,
                                ast.ExistsSubquery)) \
                    and x.plan is not None:
                # '?' inside subqueries count toward bind arity too —
                # expr walks don't descend into nested plans
                n += _count_params(x.plan)
    for k in plan.children():
        n += _count_params(k)
    return n


def _build_point_exec(core, catalog):
    """Pre-extract a row-table point probe from a Project?/Filter/
    Relation core whose conjuncts are all `col = Lit|ParamLiteral|Param`:
    returns probe(params) -> Result | None (None = shape needs the
    engine after all — e.g. no usable index, contradictory binds get the
    engine's own semantics).  Everything _try_point_lookup derives per
    call (conjunct walk, projection ordinals, dtypes) is resolved HERE,
    once, at prepare time."""
    import numpy as np

    from snappydata_tpu.engine.result import Result
    from snappydata_tpu.sql.analyzer import _expr_name

    node = core
    proj = None
    if isinstance(node, ast.Project):
        proj, node = node, node.child
    while isinstance(node, ast.SubqueryAlias):
        node = node.child
    if not isinstance(node, ast.Filter):
        return None
    rel = node.child
    while isinstance(rel, ast.SubqueryAlias):
        rel = rel.child
    if not isinstance(rel, ast.Relation):
        return None
    info = catalog.lookup_table(rel.name)
    if info is None:
        return None

    getters: dict = {}      # col name -> [value getter per conjunct]

    def flatten(e) -> bool:
        if isinstance(e, ast.BinOp) and e.op == "and":
            return flatten(e.left) and flatten(e.right)
        if isinstance(e, ast.BinOp) and e.op == "=" \
                and isinstance(e.left, ast.Col) \
                and isinstance(e.right, (ast.Lit, ast.ParamLiteral,
                                         ast.Param)):
            g = (lambda p, v=e.right.value: v) \
                if isinstance(e.right, ast.Lit) \
                else (lambda p, i=e.right.pos: p[i])
            getters.setdefault(e.left.name.lower(), []).append(g)
            return True
        return False

    if not flatten(node.condition):
        return None
    if proj is not None and not all(
            isinstance(e.child if isinstance(e, ast.Alias) else e, ast.Col)
            for e in proj.exprs):
        return None
    schema = info.schema
    if proj is not None:
        names = [_expr_name(e) for e in proj.exprs]
        idxs = [(e.child if isinstance(e, ast.Alias) else e).index
                for e in proj.exprs]
        dtypes = [schema.fields[i].dtype for i in idxs]
    else:
        names = schema.names()
        idxs = list(range(len(schema.fields)))
        dtypes = [f.dtype for f in schema.fields]
    key_set = frozenset(getters)
    pk = bool(info.key_columns) and key_set == frozenset(info.key_columns)
    sorted_cols = sorted(key_set)

    def probe(params):
        from snappydata_tpu.observability.metrics import global_registry

        vals = {}
        for name, gs in getters.items():
            v = gs[0](params)
            for g in gs[1:]:
                if g(params) != v:
                    return None     # contradictory k=1 AND k=2: engine
            vals[name] = v
        data = info.data
        if pk:
            got = data.get(tuple(vals[k] for k in info.key_columns))
            rows = [got] if got is not None else []
        else:
            # index existence re-checked per probe: CREATE INDEX does
            # not bump the catalog generation
            idx = data.index_for_columns(sorted_cols)
            if idx is None:
                return None
            rows = data.index_lookup(
                idx, tuple(vals[c] for c in data._indexes[idx]))
        global_registry().inc("point_lookups")
        cols, nulls = [], []
        for j, dt in zip(idxs, dtypes):
            cell = [r[j] for r in rows]
            nmask = np.array([v is None for v in cell]) if cell else None
            if dt.name == "string":
                cols.append(np.array(cell, dtype=object))
            else:
                cols.append(np.array(
                    [0 if v is None else v for v in cell],
                    dtype=dt.np_dtype))
            nulls.append(nmask if nmask is not None and nmask.any()
                         else None)
        return Result(names, cols, nulls, dtypes)

    return probe


def _is_row_point_shape(core, catalog) -> bool:
    """Project?/Filter/Relation over a ROW table — the shape
    executor._try_point_lookup answers from the PK/secondary index
    without entering the XLA engine."""
    from snappydata_tpu.storage.table_store import RowTableData

    node = core
    if isinstance(node, ast.Project):
        node = node.child
    while isinstance(node, ast.SubqueryAlias):
        node = node.child
    if isinstance(node, ast.Filter):
        node = node.child
    while isinstance(node, ast.SubqueryAlias):
        node = node.child
    if not isinstance(node, ast.Relation):
        return False
    info = catalog.lookup_table(node.name)
    return info is not None and isinstance(info.data, RowTableData)


class PreparedStatement:
    """Per-session façade over a shared PreparedPlan: `execute(binds)`
    runs with THIS session's principal (authorization, query log,
    governor context) while the compiled program is shared."""

    def __init__(self, session, entry: PreparedPlan):
        self._session = session
        self._entry = entry

    @property
    def sql(self) -> str:
        return self._entry.sql

    @property
    def param_count(self) -> int:
        return self._entry.param_count

    @property
    def schema(self):
        if self._entry.schema is None:       # passthrough shapes
            return self._session.query_schema(self._entry.sql)
        return self._entry.schema

    def warm_batches(self, params: Sequence,
                     sizes: Optional[Sequence[int]] = None) -> int:
        """Pre-compile the vmapped dispatch variants an N-client serving
        load will hit (inference-server warmup): one fused dispatch per
        padded batch-size bucket up to serving_batch_max.  Returns how
        many variants were compiled."""
        from snappydata_tpu.serving.batcher import bucket_ladder

        entry, sess = self._entry, self._session
        entry.revalidate(sess)
        if not entry.batchable(sess):
            return 0
        full = entry.lit_params + tuple(params)
        compiled = entry.compiled_for(sess)
        done = 0
        for b in (sizes or bucket_ladder(
                int(sess.conf.serving_batch_max or 1))):
            tables, outs = compiled.execute_batched([full] * b)
            entry.assemble_batched(sess, outs, tables, 0, full)
            done += 1
        return done

    def execute(self, params: Sequence = (), query_ctx=None):
        """Run with the given bind values.  Admission (fair-share per
        principal), statement timeouts and CANCEL all apply per request,
        exactly as for session.sql — including inside a fused batch."""
        from snappydata_tpu.observability import tracing

        with tracing.request_scope(self._entry.sql,
                                   user=self._session.user,
                                   kind="serving"):
            return self._execute_governed(params, query_ctx)

    def _execute_governed(self, params: Sequence = (), query_ctx=None):
        from snappydata_tpu import resource

        entry, sess = self._entry, self._session
        if len(params) != entry.param_count:
            raise ServingError(
                f"prepared statement expects {entry.param_count} "
                f"parameter(s), got {len(params)}")
        if resource.current_query() is not None:
            return self._execute_inner(tuple(params),
                                       resource.current_query())
        broker = resource.global_broker()
        ctx = query_ctx or resource.new_query(entry.sql, sess.user)
        if not ctx.sql:
            ctx.sql = entry.sql
        estimate = entry.estimate_bytes(sess) \
            if broker.accounting_enabled() else 0
        try:
            broker.admit(ctx, estimate,
                         float(sess.conf.query_timeout_s or 0.0))
            with resource.query_scope(ctx):
                return self._execute_inner(tuple(params), ctx)
        finally:
            broker.release(ctx)

    def _execute_inner(self, params: Tuple, ctx):
        from snappydata_tpu.engine.result import finalize_decimals

        from snappydata_tpu.observability import tracing

        entry, sess = self._entry, self._session
        reg = global_registry()
        t0 = time.time()
        sess._authorize(entry.stmt)   # grants can change under a handle
        entry.revalidate(sess)
        if entry._used:
            reg.inc("serving_prepared_hits")
            tracing.annotate("serving_registry", "hit")
        else:
            entry._used = True
            tracing.annotate("serving_registry", "miss")
        entry.executes += 1
        if entry.passthrough is not None or sess.default_mesh is not None:
            # full session pipeline (subqueries, windows, AQP, tiling,
            # and mesh-sharded sessions — a per-session property that
            # must not be baked into the shared entry); we're already
            # inside the governor scope, so this does not re-admit
            reg.inc("serving_passthrough")
            return finalize_decimals(
                sess.execute_statement(entry.stmt, params))
        if getattr(sess.catalog, "_sample_maintainers", None):
            # AQP samples registered AFTER prepare: the error-surface
            # check lives in execute_statement
            reg.inc("serving_passthrough")
            return finalize_decimals(
                sess.execute_statement(entry.stmt, params))
        if getattr(sess.catalog, "_matviews", None):
            sess._sync_referenced_matviews(entry.tokenized)
        full = entry.lit_params + params
        if getattr(sess.catalog, "_functions", None):
            from snappydata_tpu.sql import udf as _udf

            with _udf.using(sess.catalog):
                result = self._dispatch(full, ctx)
        else:
            result = self._dispatch(full, ctx)
        result = finalize_decimals(result)
        sess._log_query(entry.sql, (time.time() - t0) * 1000.0,
                        result.num_rows)
        return result

    def _dispatch(self, full: Tuple, ctx):
        from snappydata_tpu.observability import tracing

        entry, sess = self._entry, self._session
        if entry.point_exec is not None:
            # prepare-time-extracted PK/index probe: no AST walk, no
            # device work, no transfer — the O(1) serving fast lane
            result = entry.point_exec(full)
            if result is not None:
                # keep the engine's dashboard counters honest: this lane
                # bypasses executor.execute entirely
                reg = global_registry()
                reg.inc("queries")
                reg.inc("rows_returned", result.num_rows)
                tracing.annotate("serving_lane", "point")
                return result
        props = sess.conf
        if int(props.serving_batch_max or 1) > 1 and entry.batchable(sess):
            from snappydata_tpu.serving.batcher import global_batcher

            tracing.annotate("serving_lane", "batched")
            return global_batcher().submit(entry, sess, full, ctx)
        # straight path: the executor keeps its point-lookup/index fast
        # lane and all engine counters; the prepared core key skips the
        # per-execute plan-repr walk
        return sess.executor.execute(entry.tokenized, full,
                                     plan_key=entry.core_key)


class ServingRegistry:
    """Per-catalog LRU of PreparedPlans, shared by every session of that
    catalog (network front doors prepare under per-request principals
    and still hit one entry)."""

    def __init__(self, catalog):
        self.catalog = catalog
        self._lock = locks.named_lock("serving.registry")
        self._entries: "OrderedDict[str, PreparedPlan]" = OrderedDict()
        _REGISTRIES.add(self)

    @staticmethod
    def _key(sql_text: str) -> str:
        return " ".join(sql_text.split())

    def prepare(self, session, sql_text: str) -> PreparedStatement:
        key = self._key(sql_text)
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
        if entry is None:
            entry = PreparedPlan(session, sql_text)   # may raise
            reg = global_registry()
            reg.inc("serving_prepared_misses")
            with self._lock:
                cur = self._entries.get(key)
                if cur is not None:         # lost a build race: keep theirs
                    entry = cur
                    self._entries.move_to_end(key)
                else:
                    cap = max(1, int(config.global_properties()
                                     .serving_max_handles or 1))
                    while len(self._entries) >= cap:
                        self._entries.popitem(last=False)
                        reg.inc("serving_handle_evictions")
                    self._entries[key] = entry
        # authorize on hit AND miss: PREPARE must deny deterministically,
        # not only when this principal happens to build the entry
        # (executes re-check anyway — grants can change under a handle)
        session._authorize(entry.stmt)
        return PreparedStatement(session, entry)

    def peek(self, session, sql_text: str) -> Optional[PreparedStatement]:
        """Existing entry or None — NEVER builds/registers.  Metadata
        surfaces (FlightSQL GetFlightInfo) use this so ad-hoc one-shot
        SQL texts don't churn real prepared handles out of the LRU."""
        with self._lock:
            entry = self._entries.get(self._key(sql_text))
        return PreparedStatement(session, entry) \
            if entry is not None else None

    def deallocate(self, sql_text: str) -> bool:
        with self._lock:
            return self._entries.pop(self._key(sql_text), None) is not None

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def nbytes(self) -> int:
        with self._lock:
            return sum(e.nbytes for e in self._entries.values())

    def describe(self) -> list:
        with self._lock:
            entries = list(self._entries.values())
        return [{
            "sql": e.sql[:120],
            "params": e.param_count,
            "executes": e.executes,
            "passthrough": e.passthrough,
            "nbytes": e.nbytes,
        } for e in entries]


# every live registry, for the broker's unified ledger
_REGISTRIES: "weakref.WeakSet" = weakref.WeakSet()
_REG_LOCK = locks.named_lock("serving.registry_global")


def registry_for(catalog) -> ServingRegistry:
    reg = getattr(catalog, "_serving_registry", None)
    if reg is None:
        with _REG_LOCK:
            reg = getattr(catalog, "_serving_registry", None)
            if reg is None:
                reg = catalog._serving_registry = ServingRegistry(catalog)
    return reg


def serving_registry_nbytes() -> int:
    """Host bytes pinned by prepared-plan registries — one line of the
    resource broker's unified ledger."""
    return sum(r.nbytes() for r in list(_REGISTRIES))
