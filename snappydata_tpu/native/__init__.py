"""Native (C++) kernels with build-on-first-use and pure-Python fallback.

Compiles native/_fastingest.cpp with the system compiler on first import
(cached under native/build/). Everything keeps working without a compiler:
`fast_encode_strings` falls back to a vectorized pandas implementation.
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sys
import sysconfig
import threading
from snappydata_tpu.utils import locks
from typing import Optional, Tuple

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "_fastingest.cpp")
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")

_lock = locks.named_lock("native.loader")
_native = None
_tried = False


def _build() -> Optional[str]:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    so_path = os.path.join(
        _BUILD_DIR,
        f"_fastingest.cpython-{sys.version_info.major}"
        f"{sys.version_info.minor}.so")
    if os.path.exists(so_path) and \
            os.path.getmtime(so_path) >= os.path.getmtime(_SRC):
        return so_path
    cc = os.environ.get("CXX", "g++")
    cmd = [
        cc, "-O3", "-shared", "-fPIC", "-std=c++17",
        f"-I{sysconfig.get_paths()['include']}",
        f"-I{np.get_include()}",
        _SRC, "-o", so_path,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (subprocess.CalledProcessError, FileNotFoundError,
            subprocess.TimeoutExpired):
        return None
    return so_path


def _load():
    global _native, _tried
    with _lock:
        if _tried:
            return _native
        _tried = True
        if not os.path.exists(_SRC):
            return None
        so_path = _build()
        if so_path is None:
            return None
        try:
            spec = importlib.util.spec_from_file_location("_fastingest",
                                                          so_path)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _native = mod
        except Exception:
            _native = None
        return _native


def native_available() -> bool:
    return _load() is not None


def fast_encode_strings(values: np.ndarray, lookup: dict, store: list
                        ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """One pass: intern `values` into (lookup, store) and return
    (int32 codes, null mask | None)."""
    values = np.ascontiguousarray(np.asarray(values, dtype=object))
    # normalize pandas-style missing markers (float NaN, pd.NA) to None so
    # native and fallback paths agree (NaN != NaN would otherwise mint one
    # dictionary entry per NaN object in the C kernel)
    import pandas as pd

    na = pd.isna(values)
    if na.any():
        values = values.copy()
        values[na] = None
    mod = _load()
    if mod is not None:
        return mod.encode_strings(values, lookup, store)
    # vectorized fallback: factorize in C, walk only the uniques in Python
    import pandas as pd

    inverse, uniques = pd.factorize(values, use_na_sentinel=True)
    trans = np.empty(max(1, len(uniques)), dtype=np.int32)
    for j, v in enumerate(uniques.tolist()):
        code = lookup.get(v)
        if code is None:
            code = len(store)
            lookup[v] = code
            store.append(v)
        trans[j] = code
    nulls = inverse < 0
    codes = trans[np.maximum(inverse, 0)].astype(np.int32)
    if nulls.any():
        codes = np.where(nulls, 0, codes)
        return codes, nulls
    return codes, None
