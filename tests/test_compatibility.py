"""SQL compatibility battery (ref tier-4: compatibilityTests/ re-runs
Spark's SQL suites against SnappySession). A broad sweep of SQL surface
cross-checked against pandas on one dataset."""

import numpy as np
import pandas as pd
import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog


@pytest.fixture(scope="module")
def s():
    sess = SnappySession(catalog=Catalog())
    sess.sql("CREATE TABLE emp (id INT, name STRING, dept STRING, "
             "salary DOUBLE, age INT, hired DATE) USING column")
    rng = np.random.default_rng(42)
    n = 3000
    depts = np.array(["eng", "ops", "sales", "hr"], dtype=object)
    sess.insert_arrays("emp", [
        np.arange(n, dtype=np.int32),
        np.array([f"emp{i}" for i in range(n)], dtype=object),
        depts[rng.integers(0, 4, n)],
        np.round(rng.uniform(40_000, 200_000, n), 2),
        rng.integers(21, 65, n).astype(np.int32),
        rng.integers(10_000, 20_000, n).astype(np.int32),
    ])
    yield sess
    sess.stop()


@pytest.fixture(scope="module")
def df(s):
    r = s.sql("SELECT * FROM emp")
    return pd.DataFrame({n: c for n, c in zip(r.names, r.columns)})


def test_arithmetic_and_comparison_ops(s, df):
    r = s.sql("SELECT count(*) FROM emp WHERE salary * 1.1 + 5 > 100000 "
              "AND age % 2 = 0 AND id - 1 < 2998")
    exp = ((df.salary * 1.1 + 5 > 100000) & (df.age % 2 == 0)
           & (df.id - 1 < 2998)).sum()
    assert r.rows()[0][0] == exp


def test_string_functions(s, df):
    r = s.sql("SELECT count(*) FROM emp WHERE upper(dept) = 'ENG'")
    assert r.rows()[0][0] == (df.dept == "eng").sum()
    r = s.sql("SELECT count(*) FROM emp WHERE substr(name, 1, 4) = 'emp1'")
    assert r.rows()[0][0] == df.name.str.startswith("emp1").sum()
    r = s.sql("SELECT count(*) FROM emp WHERE length(dept) = 3")
    assert r.rows()[0][0] == (df.dept.str.len() == 3).sum()
    r = s.sql("SELECT count(*) FROM emp WHERE dept LIKE '%s'")
    assert r.rows()[0][0] == df.dept.str.endswith("s").sum()


def test_math_functions(s, df):
    r = s.sql("SELECT sum(round(salary, -3)), sum(abs(age - 40)), "
              "round(sum(sqrt(salary)), 0) FROM emp")
    row = r.rows()[0]
    assert row[0] == pytest.approx(np.round(df.salary, -3).sum())
    assert row[1] == np.abs(df.age - 40).sum()
    assert row[2] == pytest.approx(round(np.sqrt(df.salary).sum()), abs=1)


def test_aggregates_stddev_variance(s, df):
    r = s.sql("SELECT stddev(salary), variance(age) FROM emp").rows()[0]
    assert r[0] == pytest.approx(df.salary.std(ddof=0), rel=1e-6)
    assert r[1] == pytest.approx(df.age.var(ddof=0), rel=1e-6)


def test_count_distinct(s, df):
    r = s.sql("SELECT count(DISTINCT dept), count(DISTINCT age) FROM emp")
    assert r.rows()[0] == (df.dept.nunique(), df.age.nunique())


def test_group_by_expression(s, df):
    r = s.sql("SELECT age / 10, count(*) FROM emp GROUP BY age / 10")
    exp = df.groupby(df.age / 10).size()
    got = {row[0]: row[1] for row in r.rows()}
    assert got == {k: v for k, v in exp.items()}


def test_case_insensitive_identifiers(s):
    r = s.sql("SELECT COUNT(*) FROM EMP WHERE DEPT = 'eng'")
    assert r.rows()[0][0] > 0


def test_order_by_multiple_directions(s, df):
    r = s.sql("SELECT dept, age FROM emp ORDER BY dept ASC, age DESC, id "
              "LIMIT 50")
    exp = df.sort_values(["dept", "age", "id"],
                         ascending=[True, False, True]).head(50)
    assert [x[0] for x in r.rows()] == exp.dept.tolist()
    assert [x[1] for x in r.rows()] == exp.age.tolist()


def test_union_and_distinct(s, df):
    r = s.sql("SELECT dept FROM emp WHERE age < 30 UNION "
              "SELECT dept FROM emp WHERE age > 60")
    under = set(df[df.age < 30].dept)
    over = set(df[df.age > 60].dept)
    assert set(x[0] for x in r.rows()) == under | over


def test_between_and_in(s, df):
    r = s.sql("SELECT count(*) FROM emp WHERE age BETWEEN 30 AND 40 "
              "AND dept IN ('eng', 'hr')")
    exp = ((df.age >= 30) & (df.age <= 40)
           & df.dept.isin(["eng", "hr"])).sum()
    assert r.rows()[0][0] == exp


def test_case_when_nested(s, df):
    r = s.sql("SELECT sum(CASE WHEN age < 30 THEN 1 WHEN age < 50 THEN 2 "
              "ELSE 3 END) FROM emp")
    exp = np.where(df.age < 30, 1, np.where(df.age < 50, 2, 3)).sum()
    assert r.rows()[0][0] == exp


def test_simple_case_operand_form(s, df):
    r = s.sql("SELECT sum(CASE dept WHEN 'eng' THEN 1 ELSE 0 END) FROM emp")
    assert r.rows()[0][0] == (df.dept == "eng").sum()


def test_coalesce_and_nullif_style(s):
    s.sql("CREATE TABLE nn (a INT, b INT) USING column")
    s.sql("INSERT INTO nn VALUES (1, NULL), (NULL, 2), (3, 4)")
    r = s.sql("SELECT sum(coalesce(a, b, 0)) FROM nn")
    assert r.rows()[0][0] == 1 + 2 + 3


def test_date_parts_group(s, df):
    r = s.sql("SELECT year(hired), count(*) FROM emp GROUP BY year(hired)")
    years = 1970 + (df.hired // 365.2425).astype(int)  # approx check only
    assert len(r.rows()) >= len(set(years)) - 2


def test_self_join_with_aliases(s, df):
    r = s.sql("SELECT count(*) FROM emp a JOIN emp b ON a.id = b.id")
    assert r.rows()[0][0] == len(df)


def test_derived_table_chain(s, df):
    r = s.sql("""
        SELECT dept, mx - mn AS spread FROM (
            SELECT dept, max(salary) AS mx, min(salary) AS mn
            FROM emp GROUP BY dept) t
        ORDER BY dept""")
    g = df.groupby("dept").salary.agg(["max", "min"]).sort_index()
    for row, (_, e) in zip(r.rows(), g.iterrows()):
        assert row[1] == pytest.approx(e["max"] - e["min"])


def test_limit_zero_and_empty_result(s):
    assert s.sql("SELECT * FROM emp LIMIT 0").num_rows == 0
    assert s.sql("SELECT * FROM emp WHERE age > 1000").num_rows == 0
    assert s.sql("SELECT sum(age) FROM emp WHERE age > 1000"
                 ).rows()[0][0] == 0  # empty-input global agg


def test_prepared_params_mixed_with_literals(s, df):
    r = s.sql("SELECT count(*) FROM emp WHERE age > ? AND dept = 'eng'",
              params=(50,))
    assert r.rows()[0][0] == ((df.age > 50) & (df.dept == "eng")).sum()
