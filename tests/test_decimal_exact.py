"""Exact DECIMAL(p<=18) semantics (round-4 verdict Missing #1 / task 3):
scaled-int64 device plates, integer aggregation, scale tracking through
+,-,*,% and comparisons, Decimal results at the user boundary.

The done-gate: money columns declared DECIMAL(12,2) produce sums
BYTE-IDENTICAL to a Python decimal.Decimal oracle — including on the
f32-plate TPU storage config (decimal plates are int64 either way).
Ref: real fixed-point decimals via BigDecimal,
/root/reference/encoders/src/main/scala/org/apache/spark/sql/execution/
columnar/encoding/ColumnEncoding.scala:137-140 (readDecimal).
"""

from decimal import Decimal

import numpy as np
import pytest

from snappydata_tpu import SnappySession, config
from snappydata_tpu.catalog import Catalog


@pytest.fixture(params=[False, True], ids=["f64-plates", "f32-plates"])
def session(request):
    """Both float storage policies: decimal exactness must not depend
    on the DOUBLE plate dtype (the TPU config is f32 plates)."""
    old = config.global_properties().decimal_as_float64
    config.global_properties().decimal_as_float64 = not request.param
    s = SnappySession(catalog=Catalog())
    yield s
    s.stop()
    config.global_properties().decimal_as_float64 = old


def _money(n, seed=0):
    rng = np.random.default_rng(seed)
    cents = rng.integers(-10_000_000, 10_000_000, n)  # +/- 100k.00
    return cents, cents.astype(np.float64) / 100.0


def test_sum_byte_identical_to_decimal_oracle(session):
    n = 200_000
    cents, vals = _money(n, seed=1)
    session.sql("CREATE TABLE m (k BIGINT, price DECIMAL(12,2)) "
                "USING column")
    session.insert_arrays("m", [np.arange(n, dtype=np.int64), vals])
    got = session.sql("SELECT sum(price), min(price), max(price), "
                      "count(price) FROM m").rows()[0]
    oracle = sum(Decimal(int(c)) for c in cents) / Decimal(100)
    assert isinstance(got[0], Decimal)
    assert got[0] == oracle                      # byte-identical
    assert got[1] == Decimal(int(cents.min())) / Decimal(100)
    assert got[2] == Decimal(int(cents.max())) / Decimal(100)
    assert got[3] == n


def test_grouped_sum_and_avg_exact(session):
    n = 120_000
    cents, vals = _money(n, seed=2)
    g = (np.arange(n) % 7).astype(np.int64)
    session.sql("CREATE TABLE gm (g BIGINT, price DECIMAL(12,2)) "
                "USING column")
    session.insert_arrays("gm", [g, vals])
    rows = session.sql("SELECT g, sum(price), avg(price), count(*) "
                       "FROM gm GROUP BY g ORDER BY g").rows()
    assert len(rows) == 7
    for gi, sv, av, cnt in rows:
        sel = g == gi
        oracle = sum(Decimal(int(c)) for c in cents[sel]) / Decimal(100)
        assert sv == oracle, gi
        assert cnt == int(sel.sum())
        # avg = exact sum / exact count, computed (and typed) as DOUBLE
        assert av == pytest.approx(float(oracle) / cnt, rel=1e-12)


def test_arithmetic_scale_tracking(session):
    session.sql("CREATE TABLE a (x DECIMAL(6,2), y DECIMAL(6,3)) "
                "USING column")
    session.sql("INSERT INTO a VALUES (1.25, 2.125), (10.50, 0.375),"
                " (-3.75, 1.005)")
    rows = session.sql(
        "SELECT x + y, x - y, x * y, x / y FROM a ORDER BY x").rows()
    oracle = [(Decimal("-3.75"), Decimal("1.005")),
              (Decimal("1.25"), Decimal("2.125")),
              (Decimal("10.50"), Decimal("0.375"))]
    for (ax, sx, mx, dx), (x, y) in zip(rows, oracle):
        assert ax == x + y            # exact: scale 3
        assert sx == x - y
        assert mx == x * y            # exact: scale 5
        assert dx == pytest.approx(float(x) / float(y), rel=1e-12)


def test_comparison_boundaries_exact(session):
    session.sql("CREATE TABLE c (v DECIMAL(10,2)) USING column")
    session.sql("INSERT INTO c VALUES (24.04), (24.05), (24.06)")
    assert session.sql(
        "SELECT count(*) FROM c WHERE v < 24.05").rows()[0][0] == 1
    assert session.sql(
        "SELECT count(*) FROM c WHERE v <= 24.05").rows()[0][0] == 2
    assert session.sql(
        "SELECT count(*) FROM c WHERE v = 24.05").rows()[0][0] == 1
    # decimal vs integer literal
    session.sql("INSERT INTO c VALUES (25.00)")
    assert session.sql(
        "SELECT count(*) FROM c WHERE v = 25").rows()[0][0] == 1


def test_casts(session):
    session.sql("CREATE TABLE t (d DOUBLE, x DECIMAL(10,3)) USING column")
    session.sql("INSERT INTO t VALUES (1.2345, 12.3456), (-1.2355, -0.9)")
    r = session.sql("SELECT CAST(d AS DECIMAL(8,3)), CAST(x AS INT), "
                    "CAST(x AS DECIMAL(8,1)), CAST(x AS DOUBLE) "
                    "FROM t ORDER BY d").rows()
    assert r[1][0] == Decimal("1.234") or r[1][0] == Decimal("1.235")
    assert r[0][0] == Decimal("-1.236") or r[0][0] == Decimal("-1.235")
    assert r[1][1] == 12 and r[0][1] == 0          # truncation toward 0
    assert r[1][2] == Decimal("12.3")              # HALF_UP at scale 1
    assert r[1][3] == pytest.approx(12.3456, abs=5e-4)


def test_nulls_update_delete(session):
    session.sql("CREATE TABLE u (k BIGINT, v DECIMAL(10,2)) USING column")
    session.sql("INSERT INTO u VALUES (1, 1.10), (2, NULL), (3, 3.30),"
                " (4, 4.40)")
    assert session.sql("SELECT sum(v) FROM u").rows()[0][0] \
        == Decimal("8.80")
    session.sql("UPDATE u SET v = 9.99 WHERE k = 3")
    assert session.sql("SELECT sum(v) FROM u").rows()[0][0] \
        == Decimal("15.49")
    session.sql("DELETE FROM u WHERE k = 4")
    assert session.sql("SELECT sum(v) FROM u").rows()[0][0] \
        == Decimal("11.09")
    rows = session.sql("SELECT k, v FROM u ORDER BY k").rows()
    assert rows == [(1, Decimal("1.10")), (2, None), (3, Decimal("9.99"))]


def test_order_by_having_group_key(session):
    n = 50_000
    cents, vals = _money(n, seed=3)
    g = (np.arange(n) % 5).astype(np.int64)
    session.sql("CREATE TABLE oh (g BIGINT, v DECIMAL(12,2)) USING column")
    session.insert_arrays("oh", [g, vals])
    rows = session.sql(
        "SELECT g, sum(v) AS s FROM oh GROUP BY g "
        "HAVING sum(v) > -100000000 ORDER BY s DESC LIMIT 3").rows()
    assert len(rows) == 3
    oracle = sorted(
        (sum(Decimal(int(c)) for c in cents[g == gi]) / Decimal(100)
         for gi in range(5)), reverse=True)[:3]
    assert [r[1] for r in rows] == oracle
    # GROUP BY a decimal column (exact int64 grouping keys)
    session.sql("CREATE TABLE gk (v DECIMAL(6,2)) USING column")
    session.sql("INSERT INTO gk VALUES (1.10), (1.10), (2.20)")
    rows = session.sql("SELECT v, count(*) FROM gk GROUP BY v "
                       "ORDER BY v").rows()
    assert rows == [(Decimal("1.10"), 2), (Decimal("2.20"), 1)]


def test_sum_overflow_falls_back_not_wraps(session):
    # DECIMAL(18,0) near int64: the in-trace bound check must reroute to
    # the host path (approximate f64) instead of wrapping silently
    n = 64
    session.sql("CREATE TABLE big (v DECIMAL(18,0)) USING column")
    session.insert_arrays(
        "big", [np.full(n, 9.0e17, dtype=np.float64)])
    got = session.sql("SELECT sum(v) FROM big").rows()[0][0]
    exact = 9.0e17 * n          # 5.76e19 — far beyond int64
    assert float(got) == pytest.approx(exact, rel=1e-9)
    assert float(got) > 0       # int64 wraparound would go negative


def test_sum_overflow_guard_covers_merged_total_across_tiles(session):
    """Under scan_tile_bytes tiling, each tile can pass the per-tile
    max|v|*count < 2^62 bound while the merged total wraps int64 — the
    guard must scale its bound by the tile count so the MERGED total is
    covered (advisor round 5). 5 tiles x 4 rows x 9e17: per-tile sum
    3.6e18 < 2^62, merged 1.8e19 > int64 max."""
    saved = session.conf.scan_tile_bytes
    try:
        session.sql("CREATE TABLE tile_big (v DECIMAL(18,0)) USING column "
                    "OPTIONS (column_batch_rows '4', "
                    "column_max_delta_rows '4')")
        session.insert_arrays(
            "tile_big", [np.full(20, 9.0e17, dtype=np.float64)])
        session.conf.scan_tile_bytes = 60   # one 4-row batch per tile
        got = session.sql("SELECT sum(v) FROM tile_big").rows()[0][0]
        exact = 9.0e17 * 20                 # 1.8e19
        # rel covers f32-plate rounding of the approximate fallback
        # (~2e-8); a silent int64 wrap would be negative / off by >2x
        assert float(got) == pytest.approx(exact, rel=1e-6)
        assert float(got) > 0               # int64 wrap would go negative
    finally:
        session.conf.scan_tile_bytes = saved


def test_tile_host_fallback_reads_only_its_tile(session):
    """When ONE tile reroutes to the host path (its per-tile bound
    fires), the host evaluation must honor the scan window: reading the
    whole table from inside a tile made the merge double-count every
    other tile (observed 3.96e19 for an exact total of 1.8e19)."""
    saved = session.conf.scan_tile_bytes
    try:
        # 8-row tiles: per-tile 8 x 9e17 = 7.2e18 >= 2^62 -> every tile
        # falls back to host, which must see ONLY its own 8 rows
        session.sql("CREATE TABLE tile_hf (v DECIMAL(18,0)) USING column "
                    "OPTIONS (column_batch_rows '8', "
                    "column_max_delta_rows '8')")
        session.insert_arrays(
            "tile_hf", [np.full(20, 9.0e17, dtype=np.float64)])
        session.conf.scan_tile_bytes = 100
        got = session.sql("SELECT sum(v) FROM tile_hf").rows()[0][0]
        # rel covers f32-plate rounding (~2e-8); the whole-table
        # double-count bug this guards against was off by 2.2x
        assert float(got) == pytest.approx(9.0e17 * 20, rel=1e-6)
    finally:
        session.conf.scan_tile_bytes = saved


def test_scan_scale_uses_nominal_tile_width(session):
    """The overflow-guard tile scale must come from the pass's NOMINAL
    window width: the last tile may be truncated (10 units in tiles of
    4 → window (8,10)) and a width of 2 would claim 5 tiles where 3
    exist, over-scaling the guard into spurious host fallbacks."""
    from snappydata_tpu.storage.device import (current_scan_scale,
                                               scan_window)

    session.sql("CREATE TABLE ts_w (v BIGINT) USING column OPTIONS "
                "(column_batch_rows '4', column_max_delta_rows '4')")
    session.insert_arrays("ts_w", [np.arange(40, dtype=np.int64)])
    data = session.catalog.describe("ts_w").data
    m = data.snapshot()
    assert len(m.views) == 10
    with scan_window(data, 8, 10, m, tile_units=4):
        assert current_scan_scale(data) == 3.0
    with scan_window(data, 0, 4, m, tile_units=4):
        assert current_scan_scale(data) == 3.0
    assert current_scan_scale(data) == 1.0   # outside any pass


def test_wide_precision_keeps_float_path(session):
    session.sql("CREATE TABLE wp (v DECIMAL(28,2)) USING column")
    session.sql("INSERT INTO wp VALUES (1.25), (2.50)")
    got = session.sql("SELECT sum(v) FROM wp").rows()[0][0]
    assert got == Decimal("3.75")   # float path, still Decimal-decoded


def test_row_table_decimal(session):
    session.sql("CREATE TABLE rt (k INT PRIMARY KEY, v DECIMAL(10,2)) "
                "USING row")
    session.sql("INSERT INTO rt VALUES (1, 10.01), (2, 20.02)")
    assert session.sql("SELECT sum(v) FROM rt").rows()[0][0] \
        == Decimal("30.03")
    # PK point lookup path decodes decimals too
    r = session.sql("SELECT v FROM rt WHERE k = 2").rows()
    assert r == [(Decimal("20.02"),)]


def test_persistence_roundtrip(tmp_path):
    d = str(tmp_path / "store")
    s = SnappySession(data_dir=d)
    s.sql("CREATE TABLE p (k BIGINT, v DECIMAL(12,2)) USING column")
    n = 5000
    cents, vals = _money(n, seed=4)
    s.insert_arrays("p", [np.arange(n, dtype=np.int64), vals])
    oracle = sum(Decimal(int(c)) for c in cents) / Decimal(100)
    assert s.sql("SELECT sum(v) FROM p").rows()[0][0] == oracle
    s.checkpoint()
    s.stop()
    s2 = SnappySession(data_dir=d)
    assert s2.sql("SELECT sum(v) FROM p").rows()[0][0] == oracle
    f = s2.catalog.lookup_table("p").schema.field("v")
    assert f.dtype.precision == 12 and f.dtype.scale == 2
    s2.stop()


@pytest.mark.slow
def test_distributed_sum_exact():
    """Decimal exactness across the cluster plane: per-server partial
    sums are exact int64, ship as Arrow decimal128, and re-enter the
    merge through the float64 host domain — so the merged total equals
    the Decimal oracle while every partial fits 15 significant digits
    (~9e13 at scale 2; beyond that the merge degrades to f64 like the
    host fallback, a documented bound in types.DecimalType)."""
    from snappydata_tpu.cluster import LocatorNode, ServerNode
    from snappydata_tpu.cluster.distributed import DistributedSession

    locator = LocatorNode().start()
    servers = [ServerNode(locator.address,
                          SnappySession(catalog=Catalog())).start()
               for _ in range(2)]
    ds = DistributedSession(
        server_addresses=[s.flight_address for s in servers])
    try:
        ds.sql("CREATE TABLE dm (k BIGINT, g BIGINT, v DECIMAL(12,2)) "
               "USING column OPTIONS (partition_by 'k')")
        n = 40_000
        cents, vals = _money(n, seed=5)
        k = np.arange(n, dtype=np.int64)
        g = (k % 3).astype(np.int64)
        ds.insert_arrays("dm", [k, g, vals])
        rows = ds.sql("SELECT g, sum(v), count(*) FROM dm GROUP BY g "
                      "ORDER BY g").rows()
        assert len(rows) == 3
        for gi, sv, cnt in rows:
            sel = g == gi
            oracle = sum(Decimal(int(c))
                         for c in cents[sel]) / Decimal(100)
            assert Decimal(str(sv)) == oracle, (gi, sv, oracle)
            assert cnt == int(sel.sum())
    finally:
        ds.close()
        for sv in servers:
            sv.stop()
        locator.stop()


def test_mesh_sharded_sum_exact():
    """Under the 8-device virtual mesh, decimal plates shard on the
    batch axis and the psum stays in int64 — exactness survives GSPMD."""
    from snappydata_tpu.parallel import MeshContext, data_mesh

    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE mm (k BIGINT, v DECIMAL(12,2)) USING column")
    n = 30_000
    cents, vals = _money(n, seed=6)
    s.insert_arrays("mm", [np.arange(n, dtype=np.int64), vals])
    oracle = sum(Decimal(int(c)) for c in cents) / Decimal(100)
    assert s.sql("SELECT sum(v) FROM mm").rows()[0][0] == oracle
    with MeshContext(data_mesh(8)):
        s.executor.clear_cache()
        assert s.sql("SELECT sum(v) FROM mm").rows()[0][0] == oracle
    s.executor.clear_cache()
    s.stop()


def test_tiled_scan_sum_exact():
    """Tiny scan_tile_bytes forces the multi-tile partial-merge path:
    per-tile int64 partials must re-combine exactly."""
    old = config.global_properties().scan_tile_bytes
    s = SnappySession(catalog=Catalog())
    try:
        s.sql("CREATE TABLE ts (k BIGINT, v DECIMAL(12,2)) USING column "
              "OPTIONS (column_max_delta_rows '2000')")
        n = 20_000
        cents, vals = _money(n, seed=7)
        s.insert_arrays("ts", [np.arange(n, dtype=np.int64), vals])
        oracle = sum(Decimal(int(c)) for c in cents) / Decimal(100)
        config.global_properties().scan_tile_bytes = 64 * 1024
        s.executor.clear_cache()
        got = s.sql("SELECT sum(v), count(*) FROM ts").rows()[0]
        assert got[1] == n
        assert got[0] == oracle
    finally:
        config.global_properties().scan_tile_bytes = old
        s.stop()


def test_subquery_literal_substitution(session):
    """Scalar-subquery results substitute as Decimal literals — they
    must scale into the exact domain, not truncate to int (review
    finding: Lit(24.05, DECIMAL) cast straight to int64 became 0.24)."""
    session.sql("CREATE TABLE sq (k BIGINT, v DECIMAL(10,2)) USING column")
    session.sql("INSERT INTO sq VALUES (1, 24.05), (2, 10.00), (3, 24.05)")
    rows = session.sql(
        "SELECT k FROM sq WHERE v = (SELECT max(v) FROM sq) "
        "ORDER BY k").rows()
    assert [r[0] for r in rows] == [1, 3]


def test_union_and_intersect_mixed_scales(session):
    session.sql("CREATE TABLE ua (v DECIMAL(10,2)) USING column")
    session.sql("CREATE TABLE ub (v DECIMAL(10,3)) USING column")
    session.sql("INSERT INTO ua VALUES (24.05), (1.10)")
    session.sql("INSERT INTO ub VALUES (24.050), (2.200)")
    got = sorted(float(r[0]) for r in session.sql(
        "SELECT v FROM ua UNION ALL SELECT v FROM ub").rows())
    assert got == pytest.approx([1.10, 2.20, 24.05, 24.05])
    inter = session.sql(
        "SELECT v FROM ua INTERSECT SELECT v FROM ub").rows()
    assert len(inter) == 1 and float(inter[0][0]) == pytest.approx(24.05)
    # the union type widens over both branches: a finer right-branch
    # scale must survive decode (review finding — left-anchored dtype
    # quantized 1.005 to 1.00/1.01)
    session.sql("INSERT INTO ub VALUES (1.005)")
    got2 = sorted(str(r[0]) for r in session.sql(
        "SELECT v FROM ua UNION ALL SELECT v FROM ub").rows())
    assert "1.005" in got2
    # set_op output decodes at the widened scale too: a scaled left
    # branch must not be re-read at the finer right-branch scale
    # (review finding: 24.05 decoded as 2.405)
    inter2 = session.sql(
        "SELECT v FROM ua INTERSECT SELECT v FROM ub").rows()
    assert [float(r[0]) for r in inter2] == pytest.approx([24.05])


def test_ctas_and_insert_select_keep_values(session):
    """CTAS / INSERT..SELECT from an exact-decimal column must store
    the VALUE, not the scaled representation (review finding: 24.05
    stored as 2405.00)."""
    session.sql("CREATE TABLE src (k BIGINT, v DECIMAL(10,2)) USING column")
    session.sql("INSERT INTO src VALUES (1, 24.05), (2, 1.10)")
    session.sql("CREATE TABLE ct AS SELECT k, v FROM src")
    assert session.sql("SELECT sum(v) FROM ct").rows()[0][0] \
        == Decimal("25.15")
    session.sql("CREATE TABLE tgt (k BIGINT, v DECIMAL(10,2)) USING column")
    session.sql("INSERT INTO tgt SELECT k, v FROM src")
    assert session.sql("SELECT v FROM tgt WHERE k = 1").rows() \
        == [(Decimal("24.05"),)]


def test_half_up_rounding_ties(session):
    # 0.125 at scale 2: HALF_UP -> 0.13 (np.round's half-even would
    # give 0.12 and disagree with the BigDecimal contract)
    session.sql("CREATE TABLE hu (v DECIMAL(6,2)) USING column")
    session.insert_arrays("hu", [np.array([0.125, -0.125])])
    rows = session.sql("SELECT v FROM hu ORDER BY v").rows()
    assert rows == [(Decimal("-0.13"),), (Decimal("0.13"),)]


def test_decimal_in_scalar_functions_unscales(session):
    session.sql("CREATE TABLE sf (v DECIMAL(8,2)) USING column")
    session.sql("INSERT INTO sf VALUES (2.25), (-3.50)")
    rows = session.sql("SELECT round(v), abs(v), sqrt(abs(v)) FROM sf "
                       "ORDER BY v").rows()
    assert rows[0][0] == pytest.approx(-4.0)   # Spark round half up? -3.5 → -4
    assert rows[0][1] == pytest.approx(3.5)
    assert rows[1][2] == pytest.approx(1.5)
