"""All 22 TPC-H queries through DistributedSession on a 3-server
cluster, results asserted EQUAL to the same queries single-node (ref:
the reference runs its full SQL surface distributed because the lead
plans over real executors — SparkSQLExecuteImpl.scala:75,
SnappyStrategies.scala:80-128; harness TPCHDUnitTest). Exercises every
distributed strategy: partial-agg merge, broadcast/shuffle exchanges,
decorrelated semi/anti scatter, count-distinct alignment, uncorrelated
subquery pre-evaluation, view expansion, and the bounded gather-to-lead
fallback — plus the no-raw-errors contract."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # heavy/XLA-compile-bound; deselect with -m 'not slow'

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.cluster import LocatorNode, ServerNode
from snappydata_tpu.cluster.distributed import (DistributedSession,
                                                DistributedUnsupported)
from snappydata_tpu.utils import tpch

SF = 0.004


@pytest.fixture(scope="module")
def cluster():
    locator = LocatorNode().start()
    servers = [ServerNode(locator.address,
                          SnappySession(catalog=Catalog())).start()
               for _ in range(3)]
    ds = DistributedSession(
        server_addresses=[s.flight_address for s in servers])
    tpch.load_tpch(ds, sf=SF, seed=77, all_tables=True)
    ds.sql(tpch.Q15_VIEW)
    oracle = SnappySession(catalog=Catalog())
    tpch.load_tpch(oracle, sf=SF, seed=77, all_tables=True)
    oracle.sql(tpch.Q15_VIEW)
    yield ds, servers, oracle
    ds.close()
    oracle.stop()
    for s in servers:
        s.stop()
    locator.stop()


def _norm(rows):
    out = []
    for r in rows:
        out.append(tuple(
            round(v, 3) if isinstance(v, float) else v for v in r))
    return out


@pytest.mark.parametrize("qnum", sorted(tpch.ALL_QUERIES))
def test_tpch_query_distributed_equals_single_node(cluster, qnum):
    ds, _servers, oracle = cluster
    q = tpch.ALL_QUERIES[qnum]
    got = _norm(ds.sql(q).rows())
    want = _norm(oracle.sql(q).rows())
    # unordered compare unless the query pins a total order: distributed
    # concat may produce a different (equally valid) tie order
    assert sorted(got, key=repr) == sorted(want, key=repr), (
        f"Q{qnum}: distributed != single-node\n"
        f"got:  {got[:5]}\nwant: {want[:5]}")


def test_unsupported_over_budget_is_explicit(cluster):
    """A query with no scatter strategy whose gather exceeds the budget
    must raise DistributedUnsupported with a hint — never a raw
    RenderError/internal error."""
    ds, _servers, _oracle = cluster
    old = ds.planner.conf.dist_gather_bytes
    ds.planner.conf.dist_gather_bytes = 1   # force over-budget
    try:
        with pytest.raises(DistributedUnsupported) as ei:
            # median() has no partial decomposition and the groups are
            # not alignable (expression grouping)
            ds.sql("SELECT max(c) FROM (SELECT l_partkey + l_suppkey AS "
                   "g, count(DISTINCT l_quantity) AS c FROM lineitem "
                   "GROUP BY l_partkey + l_suppkey) t")
        assert "dist_gather_bytes" in str(ei.value)
    finally:
        ds.planner.conf.dist_gather_bytes = old


def test_gather_cache_invalidates_on_mutation(cluster):
    """The gather fallback caches lead-local copies by mutation version:
    a write must invalidate them."""
    ds, _servers, oracle = cluster
    q = ("SELECT count(DISTINCT o_totalprice) FROM orders "
         "WHERE o_orderkey < 0")  # empty but exercises the gather path
    assert ds.sql(q).rows() == oracle.sql(q).rows()
    ds.sql("INSERT INTO orders VALUES (-1, 1, 'F', 1.0, DATE "
           "'1995-01-01', '1-URGENT', 0)")
    oracle.sql("INSERT INTO orders VALUES (-1, 1, 'F', 1.0, DATE "
               "'1995-01-01', '1-URGENT', 0)")
    assert ds.sql(q).rows() == oracle.sql(q).rows()
    ds.sql("DELETE FROM orders WHERE o_orderkey < 0")
    oracle.sql("DELETE FROM orders WHERE o_orderkey < 0")
    assert ds.sql(q).rows() == oracle.sql(q).rows()


def test_distributed_windows_equal_single_node(cluster):
    ds, _servers, oracle = cluster
    q = ("SELECT o_custkey, o_totalprice, rank() OVER (PARTITION BY "
         "o_custkey ORDER BY o_totalprice DESC) AS r FROM orders "
         "WHERE o_custkey < 20 ORDER BY o_custkey, o_totalprice DESC")
    assert _norm(ds.sql(q).rows()) == _norm(oracle.sql(q).rows())


def test_distributed_rollup_equals_single_node(cluster):
    ds, _servers, oracle = cluster
    q = ("SELECT l_returnflag, l_linestatus, sum(l_quantity), count(*) "
         "FROM lineitem GROUP BY ROLLUP (l_returnflag, l_linestatus)")
    got = sorted(_norm(ds.sql(q).rows()), key=repr)
    want = sorted(_norm(oracle.sql(q).rows()), key=repr)
    assert got == want
