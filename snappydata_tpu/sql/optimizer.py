"""Pre-analysis logical optimizer.

Plays the role of the reference's planning rules (OrderJoinConditions,
SnappySessionState.scala:326, splicing ReorderJoin :151; predicate
pushdown comes from Catalyst in the reference): operates on the UNRESOLVED
tree, using catalog row counts, so that name resolution needn't be redone:

1. Flatten comma/cross-join chains + WHERE conjuncts.
2. Push single-table conjuncts down to their relation (Filter-over-scan).
3. Left-deep join tree ordered by estimated size descending — the biggest
   table becomes the probe side, small (dimension) tables become build
   sides, matching the reference's replicated/broadcast hash join choice
   (HashJoinExec, HashJoinStrategies size threshold 100MB).
4. Attach each equi conjunct at the lowest join covering its tables.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from snappydata_tpu.sql import ast


def optimize(plan: ast.Plan, catalog) -> ast.Plan:
    if isinstance(plan, ast.Sort):
        return dataclasses.replace(plan, child=optimize(plan.child, catalog),
                                   orders=plan.orders)
    if isinstance(plan, ast.Limit):
        return ast.Limit(optimize(plan.child, catalog), plan.n)
    if isinstance(plan, ast.Distinct):
        return ast.Distinct(optimize(plan.child, catalog))
    if isinstance(plan, ast.SetOp):
        return ast.SetOp(optimize(plan.left, catalog),
                         optimize(plan.right, catalog), plan.op)
    if isinstance(plan, ast.Union):
        return ast.Union(optimize(plan.left, catalog),
                         optimize(plan.right, catalog), plan.all)
    if isinstance(plan, ast.Aggregate):
        return ast.Aggregate(optimize(plan.child, catalog),
                             plan.group_exprs, plan.agg_exprs,
                             grouping_sets=plan.grouping_sets)
    if isinstance(plan, ast.Project):
        return ast.Project(optimize(plan.child, catalog), plan.exprs)
    if isinstance(plan, ast.WindowProject):
        return ast.WindowProject(optimize(plan.child, catalog), plan.exprs)
    if isinstance(plan, ast.Filter):
        return _optimize_filter(plan, catalog)
    if isinstance(plan, ast.Join):
        return dataclasses.replace(
            plan, left=optimize(plan.left, catalog),
            right=optimize(plan.right, catalog))
    if isinstance(plan, ast.SubqueryAlias):
        return ast.SubqueryAlias(optimize(plan.child, catalog), plan.alias)
    return plan


def _optimize_filter(plan: ast.Filter, catalog) -> ast.Plan:
    got = _join_factors(plan.child)
    if got is None:
        return ast.Filter(optimize(plan.child, catalog), plan.condition)
    factors, join_conds = got

    conjuncts: List[ast.Expr] = list(join_conds)
    _flatten_and(plan.condition, conjuncts)

    # name map: alias → set of column names (lowered)
    col_map: Dict[str, Set[str]] = {}
    sizes: Dict[str, int] = {}
    for f in factors:
        alias, cols, size = _factor_info(f, catalog)
        if alias is None or alias in col_map:
            # unknown factor or duplicate alias (self-join without distinct
            # aliases) — leave the tree alone rather than collapse factors
            return ast.Filter(optimize(plan.child, catalog), plan.condition)
        col_map[alias] = cols
        sizes[alias] = size

    def tables_of(e: ast.Expr) -> Optional[Set[str]]:
        out: Set[str] = set()
        for node in ast.walk(e):
            if isinstance(node, ast.Col):
                if node.qualifier:
                    q = node.qualifier.lower()
                    if q not in col_map:
                        return None
                    out.add(q)
                    continue
                hits = [a for a, cols in col_map.items()
                        if node.name.lower() in cols]
                if len(hits) != 1:
                    return None
                out.add(hits[0])
        return out

    single: Dict[str, List[ast.Expr]] = {}
    multi: List[Tuple[Set[str], ast.Expr]] = []
    residual: List[ast.Expr] = []
    for c in conjuncts:
        tabs = tables_of(c)
        if tabs is None:
            residual.append(c)
        elif len(tabs) == 1:
            single.setdefault(next(iter(tabs)), []).append(c)
        else:
            multi.append((tabs, c))

    # build filtered factors, order by size descending (probe side first)
    by_alias = {}
    for f in factors:
        alias, _, _ = _factor_info(f, catalog)
        # derived-table factors carry their own filter/join trees:
        # optimize them in their own scope before placement
        node: ast.Plan = f if isinstance(f, ast.UnresolvedRelation) \
            else optimize(f, catalog)
        if alias in single:
            cond = _and_all(single[alias])
            node = ast.Filter(node, cond)
        by_alias[alias] = node
    order = sorted(by_alias, key=lambda a: -sizes[a])

    tree = by_alias[order[0]]
    placed: Set[str] = {order[0]}
    pending = list(multi)
    for alias in order[1:]:
        placed.add(alias)
        cond_here: List[ast.Expr] = []
        rest = []
        for tabs, c in pending:
            if tabs <= placed:
                cond_here.append(c)
            else:
                rest.append((tabs, c))
        pending = rest
        if cond_here:
            tree = ast.Join(tree, by_alias[alias], "inner",
                            _and_all(cond_here))
        else:
            tree = ast.Join(tree, by_alias[alias], "cross", None)
    leftover = [c for _, c in pending] + residual
    if leftover:
        tree = ast.Filter(tree, _and_all(leftover))
    return tree


def _join_factors(plan: ast.Plan):
    """Flatten a cross/INNER join chain into (factors, lifted ON
    conditions); None when the subtree isn't such a chain (outer/semi
    trees are kept intact). Inner-join ON conditions are safe to lift
    into the conjunct pool — inner join ≡ cross + filter — which lets
    `FROM a, b, c JOIN (subquery) s ON …` shapes reorder too (round-4
    finding: Q2's re-rendered distributed plan kept a 5-way cross join
    under the WHERE, exploding the host fallback)."""
    if isinstance(plan, ast.Join) and plan.how in ("cross", "inner"):
        left = _join_factors(plan.left)
        right = _join_factors(plan.right)
        if left is not None and right is not None:
            conds = left[1] + right[1]
            if plan.condition is not None:
                _flatten_and(plan.condition, conds)
            return left[0] + right[0], conds
        return None
    if isinstance(plan, (ast.UnresolvedRelation, ast.SubqueryAlias)):
        return [plan], []
    return None


def _factor_info(f: ast.Plan, catalog):
    if isinstance(f, ast.UnresolvedRelation):
        info = catalog.lookup_table(f.name)
        if info is None:
            return None, set(), 0
        alias = (f.alias or f.name.split(".")[-1]).lower()
        from snappydata_tpu.storage.table_store import RowTableData

        size = info.data.count() if isinstance(info.data, RowTableData) \
            else info.data.snapshot().total_rows()
        return alias, {n.lower() for n in info.schema.names()}, size
    if isinstance(f, ast.SubqueryAlias):
        # derived table: alias + output columns are known; size is not —
        # rank it smallest so it lands on the build side
        cols = _subquery_out_cols(f.child)
        if cols is not None:
            return f.alias.lower(), cols, 0
        return None, set(), 0
    return None, set(), 0


def _subquery_out_cols(node: ast.Plan) -> Optional[Set[str]]:
    """Output column names of a derived table's top project/aggregate."""
    while isinstance(node, (ast.Sort, ast.Limit, ast.Distinct,
                            ast.SubqueryAlias)):
        node = node.children()[0]
    exprs = None
    if isinstance(node, ast.Project) or isinstance(node, ast.WindowProject):
        exprs = node.exprs
    elif isinstance(node, ast.Aggregate):
        exprs = node.agg_exprs
    if exprs is None:
        return None
    out: Set[str] = set()
    for e in exprs:
        if isinstance(e, ast.Alias):
            out.add(e.name.lower())
        elif isinstance(e, ast.Col):
            out.add(e.name.lower())
        else:
            return None  # unnamed computed column: bail on reordering
    return out


def _flatten_and(e: ast.Expr, out: List[ast.Expr]) -> None:
    if isinstance(e, ast.BinOp) and e.op == "and":
        _flatten_and(e.left, out)
        _flatten_and(e.right, out)
    else:
        out.append(e)


def _and_all(conds: List[ast.Expr]) -> ast.Expr:
    acc = conds[0]
    for c in conds[1:]:
        acc = ast.BinOp("and", acc, c)
    return acc
