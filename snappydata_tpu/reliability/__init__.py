"""End-to-end request reliability primitives: deadlines + idempotent
mutation retry, shared by the client, the lead's scatter plane, the
Flight server and WAL recovery.

Reference: the SnappyData thrift/JDBC layer carries a per-statement
query timeout that cancels server-side work (`queryTimeout` on
StatementAttrs, SnappyDataService.thrift) and its drivers retry
failover transparently against the locator's member view — but a
mutation whose ack was lost could not be blindly re-sent.  The two
pieces here close both gaps for this engine:

- ``deadline_scope`` / ``current_deadline`` / ``remaining``: one
  per-request ABSOLUTE deadline (``time.monotonic`` domain) riding a
  contextvar, so every layer sees the same budget shrink — the lead's
  fan-out loop checks it between failover attempts, ``SnappyClient``
  turns the remainder into a Flight call-option timeout (client-side
  enforcement: a hung member cannot hold the caller) AND ships it in
  the request body (server-side enforcement: the remote QueryContext
  stops work cooperatively when the caller has given up).

- ``MutationDedup``: a server-side at-most-once window keyed on
  client-stamped statement ids.  A mutation whose response is lost in
  flight is safe to re-send: the server remembers (id → result) and a
  retry returns the recorded result without re-applying.  The ids ride
  the WAL record headers (``stmt_scope`` threads them into
  ``wal_append``), so crash-recovery replay repopulates the window and
  a retry that races a server restart still dedups.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
from snappydata_tpu.utils import locks
import time
from collections import OrderedDict
from typing import Optional

# -----------------------------------------------------------------------
# per-request deadline (time.monotonic domain)
# -----------------------------------------------------------------------

_deadline: contextvars.ContextVar = contextvars.ContextVar(
    "snappy_request_deadline", default=None)


def current_deadline() -> Optional[float]:
    """The ambient absolute deadline (monotonic seconds), or None."""
    return _deadline.get()


def remaining() -> Optional[float]:
    """Seconds left on the ambient deadline — None when no deadline is
    set; may be <= 0 when it already expired (callers decide whether to
    raise or clamp)."""
    d = _deadline.get()
    if d is None:
        return None
    return d - time.monotonic()


@contextlib.contextmanager
def deadline_scope(deadline: Optional[float]):
    """Install `deadline` (absolute monotonic, or None) for the scope.
    Threads do NOT inherit contextvars — a worker acting on behalf of a
    deadlined request must re-enter the scope with the captured value
    (the hedged-read threads in cluster/distributed.py do)."""
    tok = _deadline.set(deadline)
    try:
        yield
    finally:
        _deadline.reset(tok)


# -----------------------------------------------------------------------
# client-stamped statement ids (the WAL threading seam)
# -----------------------------------------------------------------------

_stmt_id: contextvars.ContextVar = contextvars.ContextVar(
    "snappy_stmt_id", default=None)


def current_stmt_id() -> Optional[str]:
    return _stmt_id.get()


@contextlib.contextmanager
def stmt_scope(stmt_id: Optional[str]):
    """Carry the client's statement id down to ``wal_append`` so the
    journal record persists it (recovery replay re-seeds the dedup
    window from these headers)."""
    tok = _stmt_id.set(stmt_id)
    try:
        yield
    finally:
        _stmt_id.reset(tok)


# -----------------------------------------------------------------------
# server-side at-most-once mutation window
# -----------------------------------------------------------------------

class MutationDedup:
    """Bounded (id → recorded result) window with in-flight tracking.

    ``begin(sid)`` returns the recorded result for an id already seen
    (the retry path — caller must NOT re-apply), blocks briefly when the
    ORIGINAL request is still executing (a retry racing its own first
    attempt waits for the recorded result instead of double-applying),
    and returns None when the id is fresh — the caller executes and must
    then ``commit`` (success) or ``abort`` (failed before applying, so a
    retry may execute)."""

    def __init__(self, max_entries: int = 8192):
        self.max_entries = max(16, int(max_entries))
        self._done: "OrderedDict[str, dict]" = OrderedDict()
        self._pending: dict = {}       # sid -> threading.Event
        self._lock = locks.named_lock("reliability.dedup")

    def begin(self, sid: str, wait_s: float = 60.0) -> Optional[dict]:
        deadline = time.monotonic() + wait_s
        while True:
            with self._lock:
                if sid in self._done:
                    self._done.move_to_end(sid)
                    return self._done[sid]
                ev = self._pending.get(sid)
                if ev is None:
                    self._pending[sid] = threading.Event()
                    return None
            # the original attempt is mid-flight: wait it out, then
            # re-check (either its result landed, or its abort freed
            # the id for this retry to execute)
            ev.wait(timeout=max(0.0, deadline - time.monotonic()))
            if time.monotonic() >= deadline:
                # pathological wedge (original hung forever): fail the
                # retry loudly rather than risk a double-apply
                raise TimeoutError(
                    f"statement {sid} still executing after {wait_s}s; "
                    f"retry refused (double-apply guard)")

    def commit(self, sid: str, payload: dict) -> None:
        with self._lock:
            self._done[sid] = payload
            self._done.move_to_end(sid)
            while len(self._done) > self.max_entries:
                self._done.popitem(last=False)
            ev = self._pending.pop(sid, None)
        if ev is not None:
            ev.set()

    def abort(self, sid: str) -> None:
        """The attempt failed BEFORE applying — release the id so a
        retry may execute it for real."""
        with self._lock:
            ev = self._pending.pop(sid, None)
        if ev is not None:
            ev.set()

    def record(self, sid: str, payload: dict) -> None:
        """Recovery-replay path: seed the window directly (the record
        provably applied — it came out of the WAL)."""
        self.commit(sid, payload)

    def __len__(self) -> int:
        with self._lock:
            return len(self._done)


_DEDUP_LOCK = locks.named_lock("reliability.dedup_registry")


def dedup_for(catalog) -> MutationDedup:
    """Per-catalog window (shared across the `for_user` per-request
    sessions of one server, like the plan cache)."""
    d = getattr(catalog, "_mutation_dedup", None)
    if d is None:
        with _DEDUP_LOCK:
            d = getattr(catalog, "_mutation_dedup", None)
            if d is None:
                from snappydata_tpu import config

                d = MutationDedup(int(
                    config.global_properties().mutation_dedup_entries))
                catalog._mutation_dedup = d
    return d


# -----------------------------------------------------------------------
# the typed retryable contract
# -----------------------------------------------------------------------

def is_retryable(exc: BaseException) -> bool:
    """The error contract clients can rely on: True means the request
    may be safely re-issued (connection-shaped failures; mutations are
    covered by the dedup window), False means retrying is wrong or
    pointless — a deadline expiry (XCL52 CancelException: the caller
    gave up), an application error, or an auth failure."""
    from snappydata_tpu.resource.context import CancelException

    if isinstance(exc, CancelException):
        return False
    try:
        import pyarrow.flight as _flight

        if isinstance(exc, _flight.FlightTimedOutError):
            return False
        if isinstance(exc, (_flight.FlightUnavailableError,)):
            return True
        if isinstance(exc, _flight.FlightCancelledError):
            # a transport-level CANCELLED from the SERVER side (hard
            # kill mid-stream: "Server never sent a data message") is a
            # connection-shaped death, safe to re-issue — deadline
            # cancellations never reach here raw, they convert to
            # CancelException (XCL52, handled above) first
            return True
    except ImportError:          # pragma: no cover - pyarrow is baked in
        pass
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return True
    # disk-tier EIO (memmap page-in / tier-file read): transient device
    # errors are re-issuable — the tier layer already does one bounded
    # re-read before giving up (tier_read_retries); a retry at statement
    # scope re-drives promotion, which quarantines + rebuilds on
    # persistent damage.  Same classification shape as the PR 9
    # FlightCancelledError fix: a connection/device-shaped death is
    # retryable, a semantic failure is not.
    import errno as _errno

    if isinstance(exc, OSError) \
            and getattr(exc, "errno", None) == _errno.EIO:
        return True
    # DistributedError carries failover context — the lead already
    # retried internally; another round trip may still succeed
    from snappydata_tpu.cluster.distributed import DistributedError

    return isinstance(exc, DistributedError)
