"""Kafka micro-batch source with exactly-once offset tracking.

Reference parity: DirectKafkaStreamSource (core/src/main/scala/org/apache/
spark/sql/streaming/DirectKafkaStreamSource.scala:29-40) — direct (no
receiver) per-partition offset-range consumption — combined with the
structured-streaming offset-log protocol the reference gets from Spark's
checkpoint: the offset RANGES of a batch are durably logged BEFORE the
batch is processed, so a crash between logging and sink-apply replays the
exact same batch, which the exactly-once sink then applies once
(SnappySinkCallback.scala:196-216 possible-duplicate handling).

Layout here:

* `snappysys_internal____kafka_offsets(query_id, batch_id, ranges)` row
  table — the offset log. `ranges` is JSON {partition: [from, to)}.
  PK (query_id, batch_id); rows are written before a batch is returned
  to the streaming loop and pruned after the sink records the batch.
* consumer lag = Σ_p (end_offset(p) − consumed(p)), surfaced through
  `StreamingQuery.progress()` via the source's `extra_progress()` hook.

Transport is pluggable: `Broker` is the minimal consumer surface
(partitions / fetch / end_offset). `InProcessBroker` implements it for
tests and single-process pipelines (the image has no Kafka client
library or reachable broker — a confluent/kafka-python adapter slots in
behind the same three methods when one exists).
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

OFFSETS_TABLE = "snappysys_internal____kafka_offsets"


class Broker:
    """Minimal consumer-side broker surface."""

    def partitions(self, topic: str) -> List[int]:
        raise NotImplementedError

    def fetch(self, topic: str, partition: int, offset: int,
              max_records: int) -> List[dict]:
        """Records at [offset, offset+n); may return fewer. Empty list =
        nothing past `offset`."""
        raise NotImplementedError

    def end_offset(self, topic: str, partition: int) -> int:
        raise NotImplementedError


class InProcessBroker(Broker):
    """Thread-safe in-memory broker: topic → partition → record list.
    Stands in for an embedded Kafka in tests (the reference's sink suite
    runs against embedded Kafka the same way)."""

    def __init__(self, num_partitions: int = 4):
        self.num_partitions = num_partitions
        self._topics: Dict[str, List[List[dict]]] = {}
        self._lock = threading.Lock()

    def _topic(self, topic: str) -> List[List[dict]]:
        with self._lock:
            return self._topics.setdefault(
                topic, [[] for _ in range(self.num_partitions)])

    def produce(self, topic: str, records: Sequence[dict],
                key_field: Optional[str] = None) -> None:
        import zlib

        parts = self._topic(topic)
        with self._lock:
            for i, r in enumerate(records):
                if key_field is not None:
                    kb = str(r.get(key_field)).encode("utf-8")
                    p = zlib.crc32(kb) % len(parts)
                else:
                    p = i % len(parts)
                parts[p].append(dict(r))

    def partitions(self, topic: str) -> List[int]:
        return list(range(len(self._topic(topic))))

    def fetch(self, topic, partition, offset, max_records):
        log = self._topic(topic)[partition]
        with self._lock:
            return [dict(r) for r in log[offset:offset + max_records]]

    def end_offset(self, topic, partition) -> int:
        log = self._topic(topic)[partition]
        with self._lock:
            return len(log)


class FileBroker(Broker):
    """Durable broker over append-only JSONL partition logs — survives
    consumer-process death, which is what the SIGKILL exactly-once
    battery needs (stand-in for an external Kafka cluster's durability).
    One file per partition; a record's offset is its line number."""

    def __init__(self, directory: str, num_partitions: int = 4):
        import os

        self.directory = directory
        self.num_partitions = num_partitions
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        # path -> (file size at parse time, parsed lines); the poll loop
        # hits end_offset for every partition every tick — re-parsing the
        # whole append-only log each time is O(log bytes) per 50ms
        self._cache: Dict[str, tuple] = {}

    def _path(self, topic: str, partition: int) -> str:
        import os

        return os.path.join(self.directory, f"{topic}.p{partition}.jsonl")

    def produce(self, topic: str, records: Sequence[dict],
                key_field: Optional[str] = None) -> None:
        import zlib

        with self._lock:
            handles = {}
            try:
                for i, r in enumerate(records):
                    if key_field is not None:
                        # stable across processes (builtin hash() is
                        # salted per interpreter — the same key would
                        # migrate partitions across producer restarts)
                        kb = str(r.get(key_field)).encode("utf-8")
                        p = zlib.crc32(kb) % self.num_partitions
                    else:
                        p = i % self.num_partitions
                    if p not in handles:
                        handles[p] = open(self._path(topic, p), "a")
                    handles[p].write(json.dumps(r) + "\n")
            finally:
                for h in handles.values():
                    h.flush()
                    h.close()

    def partitions(self, topic: str) -> List[int]:
        return list(range(self.num_partitions))

    def _lines(self, topic: str, partition: int) -> List[str]:
        import os

        path = self._path(topic, partition)
        try:
            size = os.path.getsize(path)
        except OSError:
            return []
        with self._lock:
            hit = self._cache.get(path)
            if hit is not None and hit[0] == size:
                return hit[1]
        with open(path) as fh:
            lines = [ln for ln in fh.read().splitlines() if ln.strip()]
        with self._lock:
            self._cache[path] = (size, lines)
        return lines

    def fetch(self, topic, partition, offset, max_records):
        lines = self._lines(topic, partition)
        return [json.loads(ln)
                for ln in lines[offset:offset + max_records]]

    def end_offset(self, topic, partition) -> int:
        return len(self._lines(topic, partition))


# named in-process brokers so CREATE STREAM TABLE ... OPTIONS
# (brokers 'inproc://name') can reach one (test/demo wiring)
_named_brokers: Dict[str, InProcessBroker] = {}


def register_broker(name: str, broker: InProcessBroker) -> None:
    _named_brokers[name] = broker


def resolve_broker(brokers: str) -> Broker:
    if brokers.startswith("inproc://"):
        b = _named_brokers.get(brokers[len("inproc://"):])
        if b is None:
            raise ValueError(f"no in-process broker registered as "
                             f"{brokers!r}")
        return b
    if brokers.startswith("file://"):
        return FileBroker(brokers[len("file://"):])
    raise ImportError(
        "no Kafka client library is available in this environment; "
        "network brokers need kafka-python/confluent-kafka installed, or "
        "use an in-process (brokers 'inproc://<name>') / file-backed "
        "(brokers 'file:///path') broker")


class KafkaSource:
    """Source implementation for StreamingQuery: batch ids map to durable
    per-partition offset ranges."""

    def __init__(self, session, query_name: str, broker: Broker,
                 topic: str, schema_names: Sequence[str],
                 max_records_per_batch: int = 10_000):
        self.session = session
        self.query_name = query_name
        self.broker = broker
        self.topic = topic
        self.names = list(schema_names)
        self.max_records = max_records_per_batch
        self._ensure_offsets_table()

    # -- durable offset log -------------------------------------------

    def _ensure_offsets_table(self) -> None:
        self.session.sql(
            f"CREATE TABLE IF NOT EXISTS {OFFSETS_TABLE} "
            f"(query_id STRING, batch_id BIGINT, ranges STRING, "
            f"PRIMARY KEY (query_id, batch_id)) USING row")

    def _log_ranges(self, batch_id: int, ranges: Dict[int, List[int]]
                    ) -> None:
        self.session.put(OFFSETS_TABLE,
                         (self.query_name, batch_id, json.dumps(ranges)))

    def _logged_ranges(self, batch_id: int) -> Optional[Dict[int, List[int]]]:
        row = self.session.get(OFFSETS_TABLE, (self.query_name, batch_id))
        if row is None:
            return None
        return {int(k): v for k, v in json.loads(row[2]).items()}

    def _last_logged(self) -> Optional[int]:
        r = self.session.sql(
            f"SELECT max(batch_id) FROM {OFFSETS_TABLE} "
            f"WHERE query_id = ?", [self.query_name]).rows()
        return None if not r or r[0][0] is None else int(r[0][0])

    def prune_log(self, upto_batch_id: int) -> None:
        """Drop ranges the sink has durably recorded (all < upto)."""
        self.session.sql(
            f"DELETE FROM {OFFSETS_TABLE} WHERE query_id = ? "
            f"AND batch_id < ?", [self.query_name, upto_batch_id])

    # -- Source contract ----------------------------------------------

    def next_batch(self, batch_id: int):
        ranges = self._logged_ranges(batch_id)
        if ranges is None:
            ranges = self._plan_new_batch(batch_id)
            if ranges is None:
                return None
            # WAL-first: the range is durable before any row reaches the
            # sink, so a crash anywhere after this point replays THIS
            # exact batch
            self._log_ranges(batch_id, ranges)
        records: List[dict] = []
        for p, (lo, hi) in sorted(ranges.items()):
            if hi > lo:
                got = self.broker.fetch(self.topic, p, lo, hi - lo)
                if len(got) < hi - lo:
                    raise RuntimeError(
                        f"kafka replay gap: partition {p} lost records "
                        f"[{lo + len(got)}, {hi}) (retention expired?)")
                records.extend(got)
        self._consumed = {p: hi for p, (lo, hi) in ranges.items()}
        # dtype inference like FileSource: ints/floats become numeric
        # arrays (the sink encodes by column dtype), mixed/None → object
        cols = {n: np.array([r.get(n) for r in records])
                for n in self.names}
        for extra in ("_eventType",):
            if records and extra in records[0]:
                cols[extra] = np.array([r[extra] for r in records])
        return cols, batch_id + 1

    def _plan_new_batch(self, batch_id: int) -> Optional[Dict[int, List[int]]]:
        prev = self._logged_ranges(batch_id - 1)
        if prev is not None:
            start = {p: hi for p, (_lo, hi) in prev.items()}
        else:
            start = {}
        parts = self.broker.partitions(self.topic)
        budget = self.max_records
        ranges: Dict[int, List[int]] = {}
        got_any = False
        for p in parts:
            lo = start.get(p, 0)
            end = self.broker.end_offset(self.topic, p)
            take = min(max(0, end - lo), max(1, budget // len(parts)))
            hi = lo + take
            ranges[p] = [lo, hi]
            got_any = got_any or hi > lo
        return ranges if got_any else None

    # -- progress -------------------------------------------------------

    def lag(self) -> int:
        consumed = getattr(self, "_consumed", None)
        if consumed is None:
            last = self._last_logged()
            consumed = {}
            if last is not None:
                consumed = {p: hi for p, (_lo, hi)
                            in (self._logged_ranges(last) or {}).items()}
        total = 0
        for p in self.broker.partitions(self.topic):
            total += max(0, self.broker.end_offset(self.topic, p)
                         - consumed.get(p, 0))
        return total

    def extra_progress(self) -> dict:
        return {"topic": self.topic, "consumer_lag": self.lag()}
