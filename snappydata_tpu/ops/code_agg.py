"""Compressed-domain aggregate primitives (r07): SUM in DICTIONARY
space and SUM/COUNT in RUN space.

The "GPU Acceleration of SQL Analytics on Compressed Data" formulation:
a SUM over a dictionary-encoded column equals Σ_c count[c]·dict[c], so
the O(N) work touches only the small integer codes (a bincount) and the
O(D) dot over the tiny dictionary replaces N value gathers.  Per-batch
dictionaries make the cell space (group, batch, code); the dot then
contracts the (batch, code) axes against the per-batch dictionary
stack.  RLE goes further: with a per-run boolean mask the filter and
the reduction are both O(runs) arithmetic over (value, length) pairs —
see storage/device_decode.rle_masked_sum_count for the single-plate
form this generalizes.

Accumulation is float64 throughout, the same accumulator the packed
fsum family uses; only summation ORDER differs (per-code partials
instead of per-row), so results agree with the decoded path to f64
reassociation — well inside the 1e-9 relative band the equivalence
tests and the bench assert.  Exact int64 accumulators (exact decimals,
integer sums) must NOT use these: Σ count·value in f64 rounds above
2^53.  Callers gate on the accumulator dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# static cell budget for the (group, batch, code) bincount space: past
# this the scatter output outweighs what the lane saves, so callers
# keep the gather path
DICT_SPACE_MAX_CELLS = 1 << 22


def dict_space_cells(nseg: int, codes_shape, dicts_shape) -> int:
    """Cell count of the joint (group, batch, code) space — the static
    engagement bound (all three factors are trace-time constants)."""
    return int(nseg) * int(codes_shape[0]) * int(dicts_shape[1])


def dict_space_sum(codes, dicts, gidx, w, nseg: int):
    """SUM over a VALUE_DICT column in dictionary space.

    codes: [B, cap] uint8/uint16 plate codes; dicts: [B, Dp] per-batch
    dictionaries (device dtype); gidx: [N] int32 flat group index with
    invalid rows already pointing at the dump segment; w: [N] bool row
    weights (valid & not-null).  Returns [nseg] float64 group sums.

    One O(N) scatter of 0/1 into (group, batch, code) cells, then an
    O(nseg·B·Dp) contraction with the dictionary stack — the decoded
    value plate is never gathered.  Counts are exact in f64 below 2^53
    rows per cell.
    """
    b, cap = codes.shape
    dp = dicts.shape[1]
    code = codes.reshape(-1).astype(jnp.int32)
    batch = (jnp.arange(b * cap, dtype=jnp.int32) // cap)
    joint = (gidx.astype(jnp.int32) * b + batch) * dp + code
    counts = jax.ops.segment_sum(
        jnp.where(w, 1.0, 0.0), joint, num_segments=nseg * b * dp)
    counts = counts.reshape(nseg, b, dp)
    return jnp.einsum("gbd,bd->g", counts, dicts.astype(jnp.float64))


def run_space_sum_count(values, ends, run_mask):
    """Global SUM + COUNT over an RLE plate in run space.

    values/ends: [B, R] run values and cumulative end offsets; run_mask:
    [B, R] bool per-run survivors (the whole filter conjunction reduced
    in run space — the caller's alignment proof).  Returns (total
    float64 scalar, count int64 scalar): count = Σ len·mask, total =
    Σ value·len·mask — O(runs) arithmetic, no row-space expansion.
    Padded runs repeat the last end, so their length is exactly 0 and
    they contribute nothing regardless of their mask bit.
    """
    from snappydata_tpu.storage.device_decode import rle_run_lengths

    lens = rle_run_lengths(ends)
    lm = jnp.where(run_mask, lens, jnp.zeros_like(lens))
    count = jnp.sum(lm).astype(jnp.int64)
    total = jnp.sum(values.astype(jnp.float64) * lm)
    return total, count
