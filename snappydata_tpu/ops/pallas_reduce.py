"""Pallas kernel: masked compensated (Kahan) reduction.

Motivation (the numerics/bandwidth trade the aggregate accumulators
face): DOUBLE aggregates need ~1e-6-grade accuracy, so the XLA path
widens the accumulator to float64 — which TPUs EMULATE in software at a
large per-op cost. This kernel instead runs ONE pass over the f32
plates keeping a per-lane Kahan compensation term in VMEM: each of the
8x128 vector lanes owns an independent compensated chain over its
~rows/8 elements (error ~eps, not ~n*eps), and the tiny [8,128]
(sum, compensation) partials combine in exact-enough float64 OUTSIDE
the kernel. Accuracy matches the f64 path to <=1e-6 relative while the
hot loop stays entirely in native f32 vector ops.

Used for global (ungrouped) SUM/AVG over float32 plates — the TPC-H
Q6 shape — behind `properties.pallas_reduce` (**default OFF** until
measured on hardware; bench.py records the side-by-side timing when a
TPU is reachable). Scope caveats the gate enforces and the docs own:
only float32 inputs qualify (an f64 input would be truncated — the TPU
storage contract already stores DOUBLE as f32 plates, so on TPU this
loses nothing), and compensated summation bounds error relative to
Σ|v|, not |Σv| — under heavy cancellation (Σ|v| >> |Σv|) the emulated-
f64 segment path remains the accurate choice. CPU runs use the
interpreter (no Mosaic lowering) and exist for correctness tests only.

Ref parity note: the reference leans on JVM codegen'd loops with
double accumulators (SnappyHashAggregateExec); this is the TPU-native
equivalent of "accumulate wider than the data".
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_LANES = 128
_SUBLANES = 8


# rows per grid step: 2048x128 f32 block = 1MB data + 256KB mask in
# VMEM — far under the ~16MB budget, so arbitrarily long columns
# stream block by block instead of requiring the whole array resident
_BLOCK_ROWS = 2048


def _kahan_kernel(x_ref, m_ref, sum_ref, comp_ref):
    """One grid step = one [_BLOCK_ROWS, LANES] f32 block + bool mask.
    Per-lane-element Kahan accumulation over the row axis via
    lax.fori_loop, writing this block's [SUBLANES, LANES] sum +
    compensation tiles."""
    steps = _BLOCK_ROWS // _SUBLANES

    def body(i, carry):
        s, c = carry
        blk = x_ref[pl.ds(i * _SUBLANES, _SUBLANES), :]
        msk = m_ref[pl.ds(i * _SUBLANES, _SUBLANES), :]
        v = jnp.where(msk, blk, 0.0)
        # Kahan: y = v - c; t = s + y; c = (t - s) - y; s = t
        y = v - c
        t = s + y
        c_new = (t - s) - y
        return t, c_new

    zero = jnp.zeros((_SUBLANES, _LANES), dtype=jnp.float32)
    s, c = jax.lax.fori_loop(0, steps, body, (zero, zero))
    sum_ref[:, :, :] = s[None]
    comp_ref[:, :, :] = c[None]


try:  # pallas import is cheap; actual lowering happens at first call
    from jax.experimental import pallas as pl
    _PALLAS = True
except ImportError:  # pragma: no cover - pallas always ships with jax
    _PALLAS = False


@functools.partial(jax.jit, static_argnames=("interpret",))
def _kahan_call(x2d: jnp.ndarray, mask2d: jnp.ndarray,
                interpret: bool = False):
    rows = x2d.shape[0]
    nblocks = rows // _BLOCK_ROWS
    sums, comps = pl.pallas_call(
        _kahan_kernel,
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, _SUBLANES, _LANES), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, _SUBLANES, _LANES), lambda i: (i, 0, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((nblocks, _SUBLANES, _LANES),
                                 jnp.float32),
            jax.ShapeDtypeStruct((nblocks, _SUBLANES, _LANES),
                                 jnp.float32),
        ),
        interpret=interpret,
    )(x2d, mask2d)
    # exact f64 combine of the small per-block partials. Kahan's
    # c = (t - s) - y holds the EXCESS already folded into s, so the
    # true chain total is s - c (review finding: + doubled the residual
    # instead of cancelling it)
    return (jnp.sum(sums.astype(jnp.float64))
            - jnp.sum(comps.astype(jnp.float64)))


def masked_kahan_sum(values: jnp.ndarray, mask: jnp.ndarray,
                     interpret=None) -> jnp.ndarray:
    """Compensated sum of values[mask] -> float64 scalar.

    `values`: any-shape f32/f64 array; `mask`: same-shape bool. The
    flattened data pads to a [rows, 128] layout with rows a multiple of
    8 (TPU native tiling). `interpret=None` auto-selects: compiled on
    TPU, interpreter elsewhere (CPU has no Mosaic lowering)."""
    if not _PALLAS:   # degrade gracefully: plain f64 reduction
        return jnp.sum(jnp.where(mask, values, 0).astype(jnp.float64))
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    flat = values.reshape(-1).astype(jnp.float32)
    m = mask.reshape(-1)
    n = flat.shape[0]
    tile = _BLOCK_ROWS * _LANES
    padded = ((n + tile - 1) // tile) * tile
    if padded != n:
        flat = jnp.pad(flat, (0, padded - n))
        m = jnp.pad(m, (0, padded - n))
    x2d = flat.reshape(-1, _LANES)
    m2d = m.reshape(-1, _LANES)
    return _kahan_call(x2d, m2d, interpret=interpret)


def pallas_reduce_available() -> bool:
    """True when the TPU lowering path is usable on this backend."""
    if not _PALLAS:
        return False
    return jax.default_backend() == "tpu"
