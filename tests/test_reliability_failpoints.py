"""The NEW deterministic failpoint registry (reliability/failpoints.py):
grammar parsing, count/prob triggers with seeded determinism, the
data-plane mangle hooks (corrupt_bytes / short_write), the typed
exception families, env-var arming, and the zero-cost-unarmed
guarantee the production seams rely on."""

import os
import time

import pytest

from snappydata_tpu import reliability
from snappydata_tpu.observability.metrics import global_registry
from snappydata_tpu.reliability import failpoints as rfail

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean():
    rfail.clear()
    rfail.reseed(1234)
    yield
    rfail.clear()


def _c(name):
    return global_registry().counter(name)


# -- arming / grammar ------------------------------------------------------

def test_arm_and_fire_counts():
    rfail.arm("wal.append", "raise", count=2)
    f0 = _c("failpoint_fires")
    for _ in range(2):
        with pytest.raises(rfail.InjectedFault):
            rfail.hit("wal.append")
    rfail.hit("wal.append")          # count exhausted: no-op
    assert _c("failpoint_fires") == f0 + 2
    assert rfail.fired_counts() == {"wal.append": 2}


def test_spec_grammar():
    specs = rfail.arm_from_spec(
        "wal.append=raise:3;tier.write=corrupt_bytes(8):0.5;"
        "checkpoint.publish=sleep(12)")
    by_name = {s.name: s for s in specs}
    assert by_name["wal.append"].action == "raise"
    assert by_name["wal.append"].count == 3
    assert by_name["tier.write"].action == "corrupt_bytes"
    assert by_name["tier.write"].param == 8
    assert by_name["tier.write"].prob == 0.5
    assert by_name["checkpoint.publish"].action == "sleep"
    assert by_name["checkpoint.publish"].param == 12


def test_env_arming(monkeypatch):
    monkeypatch.setenv("SNAPPY_FAILPOINTS", "flight.send=raise:1")
    rfail._arm_env()
    with pytest.raises(rfail.InjectedFault):
        rfail.hit("flight.send")
    rfail.hit("flight.send")         # single-shot


def test_unknown_action_rejected():
    with pytest.raises(ValueError):
        rfail.arm("wal.append", "explode")


# -- determinism -----------------------------------------------------------

def test_prob_trigger_is_seed_deterministic():
    def pattern(seed):
        rfail.clear()
        rfail.reseed(seed)
        rfail.arm("wal.append", "raise", prob=0.5)
        out = []
        for _ in range(40):
            try:
                rfail.hit("wal.append")
                out.append(0)
            except rfail.InjectedFault:
                out.append(1)
        return out

    a, b = pattern(77), pattern(77)
    assert a == b, "same seed must replay the identical fault schedule"
    assert 0 < sum(a) < 40, "prob=0.5 should fire sometimes, not always"
    assert pattern(78) != a, "a different seed should reshuffle"


def test_corrupt_bytes_deterministic_and_crc_visible():
    buf = bytes(range(256)) * 8
    rfail.arm("tier.write", "corrupt_bytes", param=4, count=1)
    w1 = rfail.mangle("tier.write", buf)
    rfail.clear()
    rfail.reseed(1234)
    rfail.arm("tier.write", "corrupt_bytes", param=4, count=1)
    w2 = rfail.mangle("tier.write", buf)
    assert w1 == w2, "seeded corruption must be byte-identical"
    assert w1 != buf and len(w1) == len(buf)
    assert w1[:8] == buf[:8], "frame header stays parseable (CRC's job)"


def test_short_write_truncates():
    buf = b"x" * 1000
    rfail.arm("tier.write", "short_write", param=64, count=1)
    w = rfail.mangle("tier.write", buf)
    assert w == buf[:-64]
    assert rfail.mangle("tier.write", buf) == buf  # exhausted


def test_data_plane_never_fires_in_hit():
    rfail.arm("tier.write", "corrupt_bytes", param=4)
    rfail.hit("tier.write")          # control-plane hook: must no-op
    assert rfail.fired_counts() == {}


# -- typed failures / retry contract ---------------------------------------

def test_return_errno_is_retryable_eio():
    rfail.arm("tier.memmap_read", "return_errno", count=1)
    with pytest.raises(OSError) as ei:
        rfail.hit("tier.memmap_read")
    import errno

    assert ei.value.errno == errno.EIO
    assert reliability.is_retryable(ei.value)


def test_exception_families():
    rfail.arm("flight.recv", "raise", exc="conn", count=1)
    with pytest.raises(ConnectionError) as ei:
        rfail.hit("flight.recv")
    assert reliability.is_retryable(ei.value)
    rfail.arm("prefetch.worker", "kill_worker", count=1)
    with pytest.raises(rfail.WorkerKilled):
        rfail.hit("prefetch.worker")


def test_sleep_action_delays():
    rfail.arm("mesh.dispatch", "sleep", param=30, count=1)
    t0 = time.perf_counter()
    rfail.hit("mesh.dispatch")
    assert time.perf_counter() - t0 >= 0.025


# -- zero-cost unarmed -----------------------------------------------------

def test_unarmed_hit_is_noop_and_cheap():
    assert not rfail.snapshot()
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        rfail.hit("wal.append")
    per_hit = (time.perf_counter() - t0) / n
    # a falsy-dict check + call overhead: generous bound, but orders of
    # magnitude under any IO the seams sit next to
    assert per_hit < 5e-6, f"unarmed hit cost {per_hit * 1e9:.0f}ns"
    buf = b"y" * 4096
    assert rfail.mangle("tier.write", buf) is buf, \
        "unarmed mangle must return the identical object (no copy)"


def test_snapshot_and_disarm():
    rfail.arm("wal.fsync", "return_errno")
    snap = rfail.snapshot()
    assert snap and snap[0]["name"] == "wal.fsync"
    assert rfail.disarm("wal.fsync")
    assert not rfail.disarm("wal.fsync")
    assert not rfail.snapshot()


def test_known_points_cover_the_seams():
    for pt in ("wal.append", "wal.fsync", "wal.salvage",
               "checkpoint.write", "checkpoint.publish",
               "tier.write", "tier.demote", "tier.promote",
               "tier.memmap_read", "flight.send", "flight.recv",
               "broker.admit", "prefetch.worker", "mesh.dispatch"):
        assert pt in rfail.KNOWN_POINTS
