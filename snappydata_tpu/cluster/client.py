"""Client: failover-aware Flight connection (the snappydata JDBC-driver
analogue — jdbc:snappydata://host:port with locator-based failover,
jdbc/.../Constant.scala:29-33)."""

from __future__ import annotations

import json
import random
import time
import uuid
from typing import Dict, List, Optional, Sequence

import numpy as np
import pyarrow as pa
import pyarrow.flight as flight

from snappydata_tpu import config, reliability
from snappydata_tpu.cluster.retry import CircuitBreaker, ExponentialBackoff
from snappydata_tpu.fault import failpoints
from snappydata_tpu.observability import tracing
from snappydata_tpu.resource.context import CancelException


class SnappyClient:
    def __init__(self, address: Optional[str] = None,
                 locator: Optional[str] = None,
                 token: Optional[str] = None,
                 user: Optional[str] = None,
                 password: Optional[str] = None,
                 timeout_s: Optional[float] = None):
        """Connect directly (`address`='host:port') or discover query
        servers through a locator ('host:port' of the locator service).
        `token` authenticates every request when the server has
        auth_tokens configured; `user`+`password` instead log in against
        the server's auth provider (BUILTIN/LDAP) for an ephemeral token —
        re-acquired automatically after a failover, since tokens are
        per-server (ref: JDBC user/password connection properties).
        `timeout_s`: default per-request deadline — enforced client-side
        via Flight call options (a hung-but-connected member cannot
        block the caller forever; expiry raises CancelException XCL52)
        and shipped in the request body so the server stops work
        cooperatively. None falls back to `client_timeout_s`; an
        ambient `reliability.deadline_scope` (the lead's scatter budget)
        overrides both with its shrinking remainder."""
        self._token = token
        self._timeout_s = timeout_s
        self._conn_addr: Optional[str] = None   # address of _conn
        self._pin_addr: Optional[str] = None    # mutation-retry pin
        self._user = user
        self._password = password
        self._catalog_cache: Optional[dict] = None
        self._catalog_fetched_at = 0.0
        self._addresses: List[str] = []
        if address:
            self._addresses.append(address)
        self._locator = locator
        self._conn: Optional[flight.FlightClient] = None
        props = config.global_properties()
        # per-address circuit breakers: a member that failed establishment
        # breaker_failures times in a row is SKIPPED during failover while
        # its breaker is open (no connect-timeout tax per request), probed
        # again half-open after breaker_reset_s — and always retried as a
        # last resort when no other member connects
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._backoff = ExponentialBackoff(
            props.retry_backoff_base_s, props.retry_backoff_max_s,
            jitter=props.retry_jitter,
            rng=random.Random(props.fault_seed))
        if locator and not address:
            self._refresh_from_locator()

    def _refresh_from_locator(self) -> None:
        from snappydata_tpu.cluster.locator import LocatorClient

        lc = LocatorClient(self._locator, member_id="client", role="client")
        try:
            members = lc.members()
        finally:
            lc.close()
        self._addresses = [f"{m.host}:{m.port}" for m in members
                           if m.port and m.role in ("server", "lead")]

    def _login(self, conn: flight.FlightClient) -> None:
        """Exchange user/password for a per-server ephemeral token."""
        if self._user is None or self._password is None:
            return
        body = json.dumps({"user": self._user,
                           "password": self._password}).encode("utf-8")
        results = list(conn.do_action(flight.Action("login", body)))
        self._token = json.loads(
            results[0].body.to_pybytes().decode("utf-8"))["token"]

    def _establish(self, addr: str) -> flight.FlightClient:
        conn = flight.connect(f"grpc://{addr}")
        list(conn.do_action(flight.Action("ping", b"")))
        self._login(conn)
        return conn

    def _breaker(self, addr: str) -> CircuitBreaker:
        br = self._breakers.get(addr)
        if br is None:
            props = config.global_properties()
            br = self._breakers[addr] = CircuitBreaker(
                props.breaker_failures, props.breaker_reset_s)
        return br

    def _try_establish(self, addr: str) -> Optional[flight.FlightClient]:
        """Attempt one address, recording the outcome in its breaker.
        Returns None on (non-auth) failure; re-raises auth errors."""
        br = self._breaker(addr)
        try:
            conn = self._establish(addr)
        except flight.FlightUnauthenticatedError:
            raise   # bad credentials — failover can't fix that
        except Exception as e:  # failover to the next member
            br.record_failure()
            self._last_establish_err = e
            return None
        br.record_success()
        return conn

    def _client(self) -> flight.FlightClient:
        if self._conn is not None:
            return self._conn
        self._last_establish_err: Optional[Exception] = None
        pin = getattr(self, "_pin_addr", None)
        if pin is not None:
            # mutation-retry pin: a stmt_id re-send is at-most-once only
            # on the server that may have applied it (dedup windows are
            # per-server) — never fail over to a different member here
            conn = self._try_establish(pin)
            if conn is None:
                raise ConnectionError(
                    f"pinned member {pin} unreachable for mutation "
                    f"retry: {self._last_establish_err}")
            self._conn, self._conn_addr = conn, pin
            return conn
        skipped: List[str] = []
        for addr in list(self._addresses):
            if not self._breaker(addr).allow():
                skipped.append(addr)   # breaker open: known-dead, skip
                continue
            conn = self._try_establish(addr)
            if conn is not None:
                self._conn, self._conn_addr = conn, addr
                return conn
        if self._locator:
            self._refresh_from_locator()
            for addr in self._addresses:
                if addr in skipped:
                    continue
                conn = self._try_establish(addr)
                if conn is not None:
                    self._conn, self._conn_addr = conn, addr
                    return conn
        # last resort: open breakers never REDUCE availability — when no
        # healthy member connected, try the skipped ones anyway
        for addr in skipped:
            conn = self._try_establish(addr)
            if conn is not None:
                self._conn, self._conn_addr = conn, addr
                return conn
        raise ConnectionError(
            f"no reachable member: {self._last_establish_err}")

    def _invalidate(self) -> None:
        self._conn = None
        # a mutation retry pins to the member that MAY have applied the
        # first send — that is only meaningful for the connection the
        # request actually went out on; a stale address from an earlier
        # request must not pin a retry whose first send reached nobody
        self._conn_addr = None

    def _effective_timeout(self, timeout_s: Optional[float]
                           ) -> Optional[float]:
        """Per-request deadline resolution: explicit argument (0 = NO
        deadline, even under an ambient scope — the repair plane passes
        it so a caller's expiring budget can't cut a replica promotion
        mid-copy) > ambient deadline remainder (the lead's shrinking
        scatter budget) > this client's default > `client_timeout_s`."""
        if timeout_s is not None:
            t = float(timeout_s)
            return t if t > 0 else None
        rem = reliability.remaining()
        if rem is not None:
            # expired budgets surface as an immediate Flight timeout →
            # CancelException, not a hang on a dead deadline
            return max(0.001, rem)
        t = self._timeout_s
        if t is None:
            t = config.global_properties().client_timeout_s
        t = float(t or 0.0)
        return t if t > 0 else None

    @staticmethod
    def _call_opts(eff: Optional[float]):
        return flight.FlightCallOptions(timeout=eff) \
            if eff is not None else None

    def _deadline_expired(self, e) -> CancelException:
        """Typed XCL52 conversion for a Flight timeout: drop the (maybe
        wedged) connection, count it, and hand back the NON-retryable
        error — every guarded() call site must route timeouts through
        this so a retry-path timeout can't leak as a raw Flight error
        (which the lead's fan-out would mistake for member death)."""
        from snappydata_tpu.observability.metrics import global_registry

        self._invalidate()
        global_registry().inc("client_deadline_exceeded")
        return CancelException(f"request exceeded its deadline: {e}")

    def _request(self, once, retry: bool,
                 retry_metric: str = "failover_retries",
                 pin_retry: bool = False):
        """Run `once` (which must connect via _client() before building
        its payload — the token may only exist after login, and a
        failover re-login mints a fresh per-server token). Retries once
        on connection loss when `retry` (idempotent requests, plus
        mutations carrying a dedup stmt_id — the server-side window
        makes their re-send at-most-once), and once on an expired login
        token (re-login via reconnect). A Flight TIMEOUT is different:
        the caller's deadline expired, so retrying would only extend the
        wait — it surfaces as CancelException (SQLSTATE XCL52).
        `pin_retry` (mutations): the re-send must reconnect to the SAME
        member that may have applied the first send — dedup windows are
        per-server, and a locator failover to a different member would
        re-apply there (double-apply across members); if that member is
        unreachable the original error surfaces instead."""
        def guarded():
            # flight.rpc failpoint: `before` simulates a request that
            # never reached the server; `after` simulates a response
            # lost AFTER the server applied (the lost-ack case the
            # stmt_id dedup window exists for).  The reliability
            # registry's flight.send/flight.recv pair covers the same
            # two seams for seeded storm schedules.
            from snappydata_tpu.reliability import failpoints as rfail

            failpoints.hit("flight.rpc")
            rfail.hit("flight.send")
            out = once()
            rfail.hit("flight.recv")
            failpoints.hit("flight.rpc", phase="after")
            return out

        def retried():
            try:
                return guarded()
            except flight.FlightTimedOutError as e2:
                raise self._deadline_expired(e2) from e2

        from snappydata_tpu.observability.metrics import global_registry

        try:
            return guarded()
        except flight.FlightTimedOutError as e:
            raise self._deadline_expired(e) from e
        except flight.FlightUnauthenticatedError:
            if self._user is None or self._token is None:
                raise
            self._invalidate()   # reconnect → fresh login
            return retried()
        except (flight.FlightUnavailableError, ConnectionError):
            # ALWAYS drop the dead connection so the next call fails over;
            # only re-issuing this request is gated on retry-safety
            applied_addr = self._conn_addr
            self._invalidate()
            if not retry:
                raise
            # locklint: metric-dynamic retry_metric is one of the two
            # declared names "failover_retries"/"mutation_retries"
            # (keyword default + explicit call sites in this file)
            global_registry().inc(retry_metric)
            d = self._backoff.delay(0)
            rem = reliability.remaining()
            if rem is not None:
                # never sleep past the caller's deadline — and if it
                # already expired, the retry cannot possibly help
                if rem <= 0:
                    global_registry().inc("client_deadline_exceeded")
                    raise CancelException(
                        "request deadline expired during "
                        "connection-loss retry")
                d = min(d, max(rem - 0.001, 0.0))
            time.sleep(d)
            if pin_retry and applied_addr is not None:
                self._pin_addr = applied_addr
                try:
                    return retried()
                finally:
                    self._pin_addr = None
            return retried()

    def _action(self, name: str, body: dict, retry: bool = True,
                timeout_s: Optional[float] = None,
                retry_metric: str = "failover_retries",
                pin_retry: bool = False) -> dict:
        def once():
            conn = self._client()
            eff = self._effective_timeout(timeout_s)
            payload = self._with_token(dict(body))
            if eff is not None:
                # the server reads this on statement actions and arms
                # the QueryContext deadline — cooperative server-side
                # enforcement next to the hard client-side cutoff
                payload.setdefault("timeout_s", eff)
            tid = tracing.wire_id()
            if tid is not None:
                # trace propagation: the server opens its own trace
                # under the SAME id, so client and server rings join
                payload.setdefault("trace_id", tid)
            raw = json.dumps(payload).encode("utf-8")
            with tracing.span("flight_action", action=name,
                              addr=self._conn_addr):
                results = list(conn.do_action(flight.Action(name, raw),
                                              self._call_opts(eff)))
            return json.loads(results[0].body.to_pybytes().decode("utf-8"))

        return self._request(once, retry, retry_metric=retry_metric,
                             pin_retry=pin_retry)

    def sql(self, sql: str, params: Sequence = (),
            prepared: bool = False,
            timeout_s: Optional[float] = None) -> pa.Table:
        """Query → Arrow table (record-batch paged by Flight).
        `prepared` routes through the server's serving executor —
        repeated statements skip parse/plan on the server and concurrent
        requests of one shape fuse into a single device dispatch.
        `timeout_s` bounds THIS request (see _effective_timeout)."""
        def once():
            conn = self._client()
            eff = self._effective_timeout(timeout_s)
            body = {"sql": sql, "params": list(params)}
            if prepared:
                body["prepared"] = True
            if eff is not None:
                body["timeout_s"] = eff
            tid = tracing.wire_id()
            if tid is not None:
                body["trace_id"] = tid
            ticket = flight.Ticket(json.dumps(
                self._with_token(body)).encode("utf-8"))
            with tracing.span("flight_sql", addr=self._conn_addr):
                return conn.do_get(ticket, self._call_opts(eff)).read_all()

        # the client IS a front door: with no ambient trace (a direct
        # SnappyClient user) this mints the request's trace id; under
        # the lead's scatter it joins the ambient trace instead
        with tracing.request_scope(sql, user=self._user or "",
                                   kind="client"):
            return self._request(once, retry=True)

    # leading keywords whose statements MUTATE state: they are stamped
    # with a statement id so the server's dedup window makes a lost-ack
    # re-send at-most-once (before that window existed, these were
    # raise-to-caller: a blind retry would have double-applied)
    _NON_IDEMPOTENT = ("insert", "put", "update", "delete", "exec")

    def execute(self, sql: str, params: Sequence = (),
                stmt_id: Optional[str] = None,
                timeout_s: Optional[float] = None) -> dict:
        """DDL/DML via action (no result paging needed). Queries and DDL
        retry across failover; mutations are stamped with `stmt_id` (one
        is minted when not given) and retry too — the server remembers
        (stmt_id → result) in a WAL-persisted window, so a retry whose
        first send actually applied returns the recorded result instead
        of double-applying (`mutation_retries`/`mutation_dedup_hits`)."""
        head = sql.lstrip().split(None, 1)[0].lower() if sql.strip() else ""
        mutating = head in self._NON_IDEMPOTENT
        if mutating and stmt_id is None:
            stmt_id = uuid.uuid4().hex
        body = {"sql": sql, "params": list(params)}
        if stmt_id is not None:
            body["stmt_id"] = stmt_id
        with tracing.request_scope(sql, user=self._user or "",
                                   kind="client"):
            return self._action(
                "sql", body, retry=True, timeout_s=timeout_s,
                retry_metric="mutation_retries" if mutating
                else "failover_retries",
                pin_retry=mutating)

    def insert(self, table: str, columns: dict,
               stmt_id: Optional[str] = None,
               timeout_s: Optional[float] = None) -> None:
        """Bulk columnar ingest via do_put. `columns` is a name → array
        dict or a ready pyarrow Table. Stamped with a statement id like
        execute(): a connection lost after the server applied is retried
        and deduped server-side instead of duplicating rows."""
        arrow = columns if isinstance(columns, pa.Table) else \
            pa.table(columns)
        if stmt_id is None:
            stmt_id = uuid.uuid4().hex

        def once():
            conn = self._client()   # may log in and mint self._token
            eff = self._effective_timeout(timeout_s)
            cmd = {"table": table, "stmt_id": stmt_id}
            tid = tracing.wire_id()
            if tid is not None:
                cmd["trace_id"] = tid
            if self._token is not None:
                cmd["token"] = self._token
            descriptor = flight.FlightDescriptor.for_command(
                json.dumps(cmd).encode("utf-8"))
            with tracing.span("flight_put", table=table,
                              addr=self._conn_addr):
                writer, _ = conn.do_put(descriptor, arrow.schema,
                                        self._call_opts(eff))
                writer.write_table(arrow)
                writer.close()

        with tracing.request_scope(f"<insert {table}>",
                                   user=self._user or "", kind="client"):
            self._request(once, retry=True,
                          retry_metric="mutation_retries",
                          pin_retry=True)

    def repartition(self, body: dict) -> dict:
        """Ask this server to hash-repartition its shard of body['table']
        by body['key'] into body['dest'] across body['servers'] (the
        shuffle-exchange fan-out). Repair/exchange-plane calls pass
        timeout_s=0: a caller's expiring query deadline must not cut a
        data movement mid-copy (the query fails with XCL52 on its own
        calls; the exchange either completes or fails whole)."""
        return self._action("repartition", body, retry=False, timeout_s=0)

    def plan(self, plan_payload, params: Sequence = (),
             timeout_s: Optional[float] = None):
        """Execute a serialized logical plan fragment on this server and
        return the Arrow result (the plan-shipping twin of sql() —
        idempotent read, so failover/re-login retry applies the same)."""
        def once():
            conn = self._client()
            eff = self._effective_timeout(timeout_s)
            body = self._with_token({"plan": plan_payload,
                                     "params": list(params)})
            if eff is not None:
                body["timeout_s"] = eff
            tid = tracing.wire_id()
            if tid is not None:
                body["trace_id"] = tid
            with tracing.span("flight_plan", addr=self._conn_addr):
                return conn.do_get(flight.Ticket(
                    json.dumps(body).encode("utf-8")),
                    self._call_opts(eff)).read_all()

        with tracing.request_scope("<shipped plan>",
                                   user=self._user or "", kind="client"):
            return self._request(once, retry=True)

    def move_buckets(self, body: dict) -> dict:
        """Rebalance: this server copies its primary rows of
        body['buckets'] (table body['table']) to body['target'] and
        deletes them locally."""
        return self._action("move_buckets", body, retry=False, timeout_s=0)

    def export(self, body: dict) -> dict:
        """Ask this server to STREAM its local shard of body['table']
        into body['dest'] on every body['targets'] address, one scan
        unit at a time (the broadcast exchange data plane)."""
        return self._action("export", body, retry=False, timeout_s=0)

    def scan_table(self, name: str):
        """Stream a table's full content as record batches (server-side
        memory bounded by one column batch)."""
        conn = self._client()
        body = self._with_token({"scan_table": name})
        import json as _json

        return conn.do_get(flight.Ticket(
            _json.dumps(body).encode("utf-8"))).to_reader()

    def ping(self, timeout_s: Optional[float] = None) -> None:
        """Liveness probe (raises if the member is unreachable). Always
        deadline-bounded: a probe against a wedged member must answer
        within a bounded interval, not a full connect/read timeout —
        under an ambient request deadline it uses the remainder (capped),
        so 'deadline + one probe interval' bounds the caller's wait."""
        eff = timeout_s
        if eff is None:
            rem = reliability.remaining()
            eff = 5.0 if rem is None else max(0.1, min(rem, 5.0))
        list(self._client().do_action(flight.Action("ping", b""),
                                      self._call_opts(eff)))

    def promote(self, body: dict) -> dict:
        """Failover re-hosting: move this server's replica-shadow rows of
        body['buckets'] into its primary table (body['table'])."""
        return self._action("promote", body, retry=False, timeout_s=0)

    def replicate(self, body: dict) -> dict:
        """Redundancy restoration: this server copies its CURRENT rows of
        body['buckets'] (table body['table']) into body['target']'s
        replica shadow."""
        return self._action("replicate", body, retry=False, timeout_s=0)

    def purge_replica(self, body: dict) -> dict:
        """Drop body['buckets'] rows from this server's replica shadow of
        body['table'] (pre-copy cleanup for idempotent re-replication)."""
        return self._action("purge_replica", body, timeout_s=0)

    def purge_buckets(self, body: dict) -> dict:
        """Drop body['buckets'] rows from this server's PRIMARY copy of
        body['table'] (rejoin resync: a restarted member's stale rows
        of re-homed buckets are removed before re-admission; journaled
        server-side, so recovery never resurrects them)."""
        return self._action("purge_buckets", body, retry=False,
                            timeout_s=0)

    def demote(self, body: dict) -> dict:
        """Inverse of promote(): move this server's PRIMARY rows of
        body['buckets'] into its local replica shadow. The rejoin path
        uses it when a restarted member's recovered copy of a bucket is
        provably current (WAL-seq watermark) — the survivor's promoted
        copy turns back into its redundant shadow with zero network
        copy."""
        return self._action("demote", body, retry=False, timeout_s=0)

    def _with_token(self, body: dict) -> dict:
        if self._token is not None:
            body["token"] = self._token
        return body

    def stats(self) -> dict:
        return self._action("stats", {})

    # -- thin-client catalog (ref: ConnectorExternalCatalog's cached
    # catalog tables keyed on catalog version, invalidated wholesale on
    # any DDL — SmartConnectorExternalCatalog.invalidate) ---------------

    # catalog snapshots are trusted this long before refetching — remote
    # DDL (a bumped server generation) is observed within the TTL, like
    # SmartConnectorExternalCatalog's version check per access
    CATALOG_TTL_S = 5.0

    def catalog(self, refresh: bool = False) -> dict:
        """Full catalog metadata in ONE round trip: {generation, tables:
        {name: {columns, provider, partition_by, buckets, ...}}, views}.
        Served from cache within CATALOG_TTL_S; `refresh=True` or
        `invalidate_catalog()` forces a refetch."""
        import time

        now = time.monotonic()
        if self._catalog_cache is None or refresh or \
                now - self._catalog_fetched_at > self.CATALOG_TTL_S:
            self._catalog_cache = self._action("catalog", {})
            self._catalog_fetched_at = now
        return self._catalog_cache

    def invalidate_catalog(self) -> None:
        self._catalog_cache = None

    def tables(self, refresh: bool = False) -> dict:
        """table name → metadata (schema columns, provider, placement)."""
        return self.catalog(refresh=refresh)["tables"]

    def describe(self, table: str, refresh: bool = False) -> dict:
        """One table's metadata; a miss refetches once before raising —
        the cached snapshot may simply predate the table's DDL."""
        name = table.lower().removeprefix("app.")
        tables = self.tables(refresh=refresh)
        if name not in tables and not refresh:
            tables = self.tables(refresh=True)
        if name not in tables:
            raise KeyError(f"no such table: {table}")
        return tables[name]

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None
