"""Out-of-core value parity under the HTAP chaos schedule (satellite of
PR 16): the PR 11 committer/pinned-reader schedule re-runs with the
device budget constricted far below the working set, so the tier
ladder demotes (HBM→host→disk) and the tile prefetcher streams windows
back up MID-QUERY.  Every pinned read is still value-asserted against
the serialized replay log — out-of-core execution must be invisible to
answers — and the final table state matches a fully in-HBM re-read
after promotion.  A crash (kill→rejoin) lands while batches sit
memmapped in the disk tier: recovery replays from WAL, so no acked row
depends on tier files surviving."""

import random
import threading

import numpy as np
import pytest

from snappydata_tpu import SnappySession, config
from snappydata_tpu.observability.metrics import global_registry
from snappydata_tpu.storage import mvcc, tier

pytestmark = [pytest.mark.chaos, pytest.mark.outofcore]


@pytest.fixture
def constricted():
    """Budgets far below the working set: tiny batches so tables span
    many batches, tiny tiles so scans go down the tiled lane, a tier
    device cap that evicts everything unpinned, and a host cap that
    pushes batches to the CRC-framed disk tier."""
    props = config.global_properties()
    old = (props.column_batch_rows, props.column_max_delta_rows,
           props.scan_tile_bytes,
           props.device_cache_bytes, props.tier_device_bytes,
           props.tier_host_bytes, props.tier_prefetch_depth)
    props.column_batch_rows = 128
    props.column_max_delta_rows = 128  # fold deltas into column batches
    props.scan_tile_bytes = 2 * 128 * 32
    props.device_cache_bytes = 64 * 1024
    props.tier_device_bytes = 32 * 1024
    props.tier_host_bytes = 48 * 1024
    props.tier_prefetch_depth = 2
    yield props
    (props.column_batch_rows, props.column_max_delta_rows,
     props.scan_tile_bytes,
     props.device_cache_bytes, props.tier_device_bytes,
     props.tier_host_bytes, props.tier_prefetch_depth) = old


def _c(name):
    return global_registry().counter(name)


def test_outofcore_htap_chaos_parity(tmp_path, constricted):
    rng = random.Random(1616)
    dirn = str(tmp_path / "store")
    s = SnappySession(data_dir=dirn)
    s.sql("CREATE TABLE h (k INT, v DOUBLE) USING column")
    data = s.catalog.describe("h").data

    # seed enough rows that every scan spans multiple batches AND tiles
    seed_vals = [float(i % 10) for i in range(1500)]
    s.insert("h", *[(i, v) for i, v in enumerate(seed_vals)])

    expected = {data.snapshot().version: (1500, sum(seed_vals))}
    acked_rows = [1500]
    acked_sum = [sum(seed_vals)]
    log_lock = threading.Lock()
    stop = threading.Event()
    errs = []

    def committer(sess):
        try:
            # bounded: with the device tier evicted to cap after every
            # statement, reads slow to streaming speed — an unbounded
            # committer would grow the table (and the scan time) without
            # limit while the readers crawl
            for _ in range(40):
                if stop.is_set():
                    break
                n = rng.randint(20, 160)
                vals = [float(rng.randint(0, 9)) for _ in range(n)]
                sess.insert("h", *[(i, v) for i, v in enumerate(vals)])
                with log_lock:
                    acked_rows[0] += n
                    acked_sum[0] += sum(vals)
                    expected[data.snapshot().version] = (
                        acked_rows[0], acked_sum[0])
        except Exception as e:
            errs.append(e)

    def reader(sess, n_reads):
        import time as _time

        try:
            for _ in range(n_reads):
                with mvcc.pinned_scope(sess.catalog, ["h"]) as pin:
                    ver = pin.manifest_for(data).version
                    got = sess.sql(
                        "SELECT count(*), sum(v) FROM h").rows()[0]
                want = None
                for _spin in range(200):
                    with log_lock:
                        want = expected.get(ver)
                    if want is not None:
                        break
                    _time.sleep(0.01)
                assert want is not None, \
                    f"pinned version {ver} missing from the commit log"
                cnt = int(got[0])
                sm = float(got[1]) if got[1] is not None else 0.0
                assert (cnt, round(sm, 6)) == (want[0], round(want[1], 6)), \
                    f"out-of-core snapshot@v{ver} read {got}, " \
                    f"serialized replay says {want}"
        except Exception as e:
            errs.append(e)

    d0 = _c("tier_demotions_hbm") + _c("tier_demotions_host")
    p0 = _c("prefetch_windows_warmed")
    w = threading.Thread(target=committer, args=(s,), daemon=True)
    readers = [threading.Thread(target=reader, args=(s, 6), daemon=True)
               for _ in range(2)]
    w.start()
    for r in readers:
        r.start()
    for r in readers:
        r.join(timeout=180)
    stop.set()
    w.join(timeout=30)
    assert not errs, errs
    assert not w.is_alive() and not any(r.is_alive() for r in readers)

    # one more full tiled scan guarantees a maybe_demote pass against
    # the now-large table, then prove the schedule really ran out of
    # core: the ladder demoted and the prefetcher streamed windows
    final = s.sql("SELECT count(*), sum(v) FROM h").rows()[0]
    assert int(final[0]) == acked_rows[0]
    assert round(float(final[1]), 6) == round(acked_sum[0], 6)
    assert _c("tier_demotions_hbm") + _c("tier_demotions_host") > d0, \
        "constricted budgets never triggered the demotion ladder"
    assert _c("prefetch_windows_warmed") > p0, \
        "tiled chaos scans never exercised the prefetcher"

    # ---- kill → rejoin while batches sit memmapped in the disk tier:
    # recovery replays from WAL; answers must not depend on tier files
    final_acked, final_sum = acked_rows[0], acked_sum[0]
    s2 = SnappySession(data_dir=dirn)
    got = s2.sql("SELECT count(*), sum(v) FROM h").rows()[0]
    assert int(got[0]) == final_acked, \
        f"acked rows lost across the crash: {got[0]} != {final_acked}"
    assert round(float(got[1]), 6) == round(final_sum, 6)

    # ---- in-HBM parity: lift the caps, promote everything resident,
    # and the answer is bit-identical to the constricted run's
    props = constricted
    props.tier_device_bytes = 0
    props.tier_host_bytes = 0
    props.device_cache_bytes = 0
    data2 = s2.catalog.describe("h").data
    tier.promote_table(data2)
    assert not any(isinstance(vw.batch.columns[1].data, np.memmap)
                   for vw in data2._manifest.views)
    hbm = s2.sql("SELECT count(*), sum(v) FROM h").rows()[0]
    assert int(hbm[0]) == final_acked
    assert float(hbm[1]) == float(got[1]), \
        "out-of-core answer diverged from the in-HBM answer"
    s2.disk_store.close()
