"""Packed validity bitmaps (Arrow little-endian bit order).

Replaces the reference's unsafe long-array null bitset
(encoders/.../encoding/BitSet.scala, ColumnEncoding.scala:37-53 nulls
header). Packed form is the at-rest/persistence format; on device nulls are
bool masks (TPU vector units want lanes, not bit twiddling).
"""

from __future__ import annotations

import numpy as np


def pack(mask: np.ndarray) -> np.ndarray:
    """bool[n] -> uint8[ceil(n/8)] with little-endian bit order."""
    return np.packbits(mask.astype(np.uint8), bitorder="little")


def unpack(packed: np.ndarray, n: int) -> np.ndarray:
    """uint8[ceil(n/8)] -> bool[n]."""
    return np.unpackbits(packed, count=n, bitorder="little").astype(np.bool_)


def popcount(packed: np.ndarray, n: int) -> int:
    return int(unpack(packed, n).sum())
