"""Error-bounded approximate aggregates — the HAC surface (ref example:
the airline WITH ERROR queries in docs/sde/hac_contracts.md and
docs/aqp.md; job analogue AirlineDataJob.scala).

Run: PYTHONPATH=. python examples/error_bounded_aggregates.py
"""

import time

import numpy as np

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog


def main():
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE airline (carrier STRING, arr_delay DOUBLE, "
          "month_ INT) USING column")
    rng = np.random.default_rng(7)
    n = 2_000_000
    s.insert_arrays("airline", [
        np.array(["AA", "UA", "DL", "WN", "B6"],
                 dtype=object)[rng.integers(0, 5, n)],
        rng.normal(9.0, 25.0, n),
        rng.integers(1, 13, n).astype(np.int32)])
    s.sql("CREATE SAMPLE TABLE airline_sample ON airline OPTIONS "
          "(baseTable 'airline', qcs 'carrier', reservoir_size '400')")

    q = ("SELECT carrier, avg(arr_delay) AS ad, absolute_error(ad) AS ae, "
         "relative_error(ad) AS re, lower_bound(ad) AS lb, "
         "upper_bound(ad) AS ub FROM airline GROUP BY carrier "
         "ORDER BY carrier WITH ERROR 0.1 CONFIDENCE 0.95")
    t0 = time.time()
    approx = s.sql(q)
    t_approx = time.time() - t0
    t0 = time.time()
    exact = s.sql("SELECT carrier, avg(arr_delay) FROM airline "
                  "GROUP BY carrier ORDER BY carrier")
    t_exact = time.time() - t0

    exact_by = dict(exact.rows())
    print(f"approx ({t_approx * 1e3:.1f} ms) vs exact "
          f"({t_exact * 1e3:.1f} ms):")
    for carrier, ad, ae, re, lb, ub in approx.rows():
        inside = "ok" if lb <= exact_by[carrier] <= ub else "MISS"
        print(f"  {carrier}: {ad:8.3f} ± {ae:.3f}  "
              f"[{lb:.3f}, {ub:.3f}]  exact {exact_by[carrier]:8.3f}  "
              f"{inside}")

    # behaviors: strict raises when a group misses the contract
    s.sql("SELECT carrier, avg(arr_delay) AS ad FROM airline "
          "GROUP BY carrier WITH ERROR 0.5 BEHAVIOR 'run_on_full_table'")
    print("run_on_full_table behavior: exact values substituted on "
          "violation")


if __name__ == "__main__":
    main()
