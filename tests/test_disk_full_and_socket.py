"""Round-4 parity gaps: ENOSPC during WAL append (the hydra
diskFullTests tier had no analogue here — round-3 verdict Weak #6) and
the socket stream source (ref: socketTextStream demos)."""

import errno
import json
import socket
import socketserver
import threading
import time

import numpy as np
import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.storage import persistence


class TestDiskFull:
    def test_enospc_mid_ingest_fails_clean_and_recovers(self, tmp_path,
                                                        monkeypatch):
        """ENOSPC during a WAL append: the INSERT fails with the OS
        error, previously-committed data stays intact and readable, and
        once space frees up the store accepts writes again — exactly the
        WAL-then-apply contract under the hydra disk-full battery."""
        d = str(tmp_path / "store")
        s = SnappySession(data_dir=d)
        s.sql("CREATE TABLE ev (k BIGINT, v DOUBLE) USING column")
        for i in range(5):
            s.insert_arrays("ev", [
                np.arange(i * 100, (i + 1) * 100, dtype=np.int64),
                np.ones(100)])

        # frame_record is the seam the WAL append goes through (the
        # group-commit path frames before buffering; a failure here must
        # surface BEFORE the mutation applies)
        real_frame = persistence.frame_record
        state = {"full": True}

        def failing_frame(header, arrays):
            if state["full"]:
                raise OSError(errno.ENOSPC, "No space left on device")
            return real_frame(header, arrays)

        monkeypatch.setattr(persistence, "frame_record", failing_frame)
        with pytest.raises(OSError, match="No space left"):
            s.insert_arrays("ev", [np.arange(500, 600, dtype=np.int64),
                                   np.ones(100)])
        # WAL-first: the failed chunk must not be half-applied
        assert s.sql("SELECT count(*) FROM ev").rows()[0][0] == 500

        # space freed: ingest resumes on the SAME store
        state["full"] = False
        s.insert_arrays("ev", [np.arange(500, 600, dtype=np.int64),
                               np.ones(100)])
        assert s.sql("SELECT count(*) FROM ev").rows()[0][0] == 600
        s.checkpoint()
        s.disk_store.close()

        # recovery sees a consistent store: the acknowledged 600 rows
        s2 = SnappySession(data_dir=d)
        assert s2.sql("SELECT count(*) FROM ev").rows()[0][0] == 600
        assert s2.sql("SELECT count(DISTINCT k) FROM ev").rows()[0][0] \
            == 600
        s2.disk_store.close()

    def test_enospc_during_checkpoint_keeps_store_consistent(
            self, tmp_path, monkeypatch):
        d = str(tmp_path / "store")
        s = SnappySession(data_dir=d)
        s.sql("CREATE TABLE cv (k BIGINT) USING column")
        s.insert_arrays("cv", [np.arange(1000, dtype=np.int64)])
        # cut a real batch so the checkpoint writes batch files
        s.catalog.describe("cv").data.force_rollover()

        real_write = persistence.write_record

        def failing_write(fh, header, arrays):
            raise OSError(errno.ENOSPC, "No space left on device")

        monkeypatch.setattr(persistence, "write_record", failing_write)
        with pytest.raises(OSError):
            s.checkpoint()
        monkeypatch.setattr(persistence, "write_record", real_write)
        # the half-written checkpoint must not poison recovery: WAL
        # replay still reconstructs every acknowledged row
        s.disk_store.close()
        s2 = SnappySession(data_dir=d)
        assert s2.sql("SELECT count(*) FROM cv").rows()[0][0] == 1000
        s2.disk_store.close()


class _LineServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True   # sleeping handlers must not delay exit


def test_socket_stream_source():
    rows = [{"id": i, "tag": f"t{i % 3}"} for i in range(500)]
    conns = []

    class H(socketserver.StreamRequestHandler):
        def handle(self):
            conns.append(True)
            for r in rows:
                self.wfile.write((json.dumps(r) + "\n").encode())
            self.wfile.flush()
            time.sleep(30)   # hold the connection open

    srv = _LineServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    port = srv.server_address[1]
    from snappydata_tpu.catalog import Catalog

    s = SnappySession(catalog=Catalog())
    try:
        s.sql(f"CREATE STREAM TABLE sk (id BIGINT, tag STRING) "
              f"USING socket_stream OPTIONS (hostname '127.0.0.1', "
              f"port '{port}', key_columns 'id', interval '0.02')")
        deadline = time.time() + 20
        while time.time() < deadline:
            if s.sql("SELECT count(*) FROM sk").rows()[0][0] == 500:
                break
            time.sleep(0.05)
        assert s.sql("SELECT count(*) FROM sk").rows()[0][0] == 500
        r = s.sql("SELECT tag, count(*) FROM sk GROUP BY tag "
                  "ORDER BY tag")
        assert [row[1] for row in r.rows()] == [167, 167, 166]
    finally:
        s.stop()
        srv.shutdown()
