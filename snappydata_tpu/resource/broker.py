"""Process-wide resource broker: unified ledger + admission control.

Reference: SnappyUnifiedMemoryManager meters every storage/execution
allocation against eviction/critical heap percentages and fails new
work with LowMemoryException instead of dying
(SnappyUnifiedMemoryManager.scala:379-401, docs/best_practices/
memory_management.md:86-103). This module is the TPU-first analogue:

- **accounting**: one ledger over host bytes (resident encoded batches,
  row-delta buffers, spill files) and device bytes (cached decoded
  plates) per table, unifying the previously scattered `nbytes` /
  `_DeviceCacheBudget` bookkeeping behind `ledger()` with high/low
  watermarks;
- **admission control**: `admit()` either admits, queues (bounded FIFO
  with per-principal fair slots), or rejects with a SnappyData-style
  `LowMemoryException` (SQLSTATE XCL54). Crossing the high watermark
  triggers graceful degradation in order: evict compiled-plan caches,
  spill cold batches to disk, then cancel the hungriest admitted query;
- **cancellation**: `cancel(query_id)` flags the query's context; the
  cooperative checks threaded through the engine stop it at the next
  batch/tile boundary.
"""

from __future__ import annotations

import threading
from snappydata_tpu.utils import locks
import time
import weakref
from typing import Dict, List, Optional, Tuple

from snappydata_tpu import config
from snappydata_tpu.observability.metrics import global_registry
from snappydata_tpu.resource.context import (CancelException,
                                             LowMemoryException,
                                             QueryContext)


def _host_table_bytes(data) -> int:
    """Resident host bytes of one table: encoded batch arrays that are
    actually in RAM (memmapped spill pages count 0 — the OS page cache
    owns them) plus the row-delta buffer; row tables charge their live
    rows at decoded width (they hold Python-object lists, so this is an
    estimate — but ZERO would hide them from the ledger entirely)."""
    total = 0
    manifest = getattr(data, "_manifest", None)
    if manifest is not None and hasattr(manifest, "views"):
        from snappydata_tpu.storage.hoststore import batch_resident_bytes

        for v in manifest.views:
            try:
                total += batch_resident_bytes(v.batch)
            except Exception:
                pass
        for a in manifest.row_arrays or ():
            if a is not None and getattr(a, "dtype", None) is not None \
                    and a.dtype != object:
                total += int(a.nbytes)
        return total
    live = getattr(data, "_live", None)
    if live is not None and getattr(data, "schema", None) is not None:
        from snappydata_tpu.resource.estimate import _decoded_row_width

        try:
            # count(True) — tombstoned update slots must not double the
            # charge (an updated row flags the old slot dead)
            return live.count(True) * _decoded_row_width(data.schema)
        except Exception:
            return 0
    return total


_pressure_warned = [False]


def _pressure_log_once() -> None:
    if not _pressure_warned[0]:
        _pressure_warned[0] = True
        import logging

        logging.getLogger("snappydata_tpu.broker").warning(
            "background pressure-demotion pass failed; synchronous "
            "high-watermark degradation remains in force",
            exc_info=True)


class ResourceBroker:
    """One broker per process (see `global_broker()`); multi-node setups
    run one per member, exactly like the reference's per-JVM memory
    manager."""

    def __init__(self, props=None):
        self.props = props or config.global_properties()
        self._cond = locks.named_condition("resource.broker_cond")
        self._active: Dict[str, QueryContext] = {}
        self._queue: List[QueryContext] = []
        self._inflight_bytes = 0
        # the table registry gets its OWN lock: metrics gauges walk it
        # while the metrics registry lock is held, and admission bumps
        # metrics counters while _cond is held — sharing _cond here
        # would be a lock-order inversion (snapshot deadlock)
        self._tables_lock = locks.named_lock("resource.broker_tables")
        # keyed (owner, name): one process holds many Catalog instances
        # (per-test sessions, scratch merges) — name-only keys let a
        # same-named table in another catalog silently replace this one's
        # ledger line
        self._tables: Dict[Tuple[int, str], "weakref.ref"] = {}
        self._executors: "weakref.WeakSet" = weakref.WeakSet()
        # submitted-but-not-yet-admitted contexts (jobserver): visible
        # and cancellable from the moment of submission
        self._watched: Dict[str, QueryContext] = {}
        self._measured_cache: Tuple[float, int, int] = (0.0, 0, 0)
        # pressure-demotion watcher (ROADMAP 4(c)): one background
        # thread at a time; the leaf lock guards only the running flag
        self._pressure_lock = locks.named_lock("resource.pressure")
        self._pressure_running = False
        reg = global_registry()
        reg.gauge("governor_inflight_bytes",
                  lambda: float(self._inflight_bytes))
        reg.gauge("governor_active_queries", lambda: float(len(self._active)))
        reg.gauge("governor_queued_queries", lambda: float(len(self._queue)))
        # one cached ledger walk serves both gauges per scrape window
        reg.gauge("governor_host_bytes",
                  lambda: float(self.measured_bytes(max_age_s=1.0)[0]))
        reg.gauge("governor_device_bytes",
                  lambda: float(self.measured_bytes(max_age_s=1.0)[1]))

    # -- knobs (read live so SET takes effect without a restart) --------

    def _limit(self) -> int:
        return int(self.props.memory_limit_bytes or 0)

    def accounting_enabled(self) -> bool:
        return self._limit() > 0

    def _high_bytes(self, limit: int) -> float:
        return limit * float(self.props.memory_high_watermark)

    def _low_bytes(self, limit: int) -> float:
        return limit * float(self.props.memory_low_watermark)

    def _pressure_bytes(self, limit: int) -> float:
        wm = float(getattr(self.props, "tier_pressure_watermark", 0.0)
                   or 0.0)
        # 0 disables the watcher: the threshold sits above the high
        # watermark so admission never crosses it first
        return limit * wm if wm > 0 else float("inf")

    # -- pressure-driven background demotion (ROADMAP 4(c)) -------------

    def _kick_pressure_demote(self, limit: int) -> None:
        """Start ONE background ladder pass toward the low watermark if
        none is running.  Admission latency pays a flag check, never the
        demotion itself."""
        with self._pressure_lock:
            if self._pressure_running:
                return
            self._pressure_running = True
        global_registry().inc("tier_pressure_wakeups")
        # relief target: UNDER the pressure watermark (the low watermark
        # can legitimately sit above current residency when the pressure
        # knob is set aggressively — demoting "up to" it would be a
        # no-op exactly when the operator asked for early relief)
        target = min(self._low_bytes(limit), self._pressure_bytes(limit))
        threading.Thread(target=self._pressure_demote_body,
                         args=(int(target),),
                         name="snappy-pressure-demote",
                         daemon=True).start()

    def _pressure_demote_body(self, target_bytes: int) -> None:
        from snappydata_tpu.storage import tier

        try:
            tier.pressure_demote(self, target_bytes)
        # locklint: swallowed-exception the watcher is advisory relief —
        # a failed background pass leaves the synchronous high-watermark
        # degrade (and its loud LowMemoryException path) fully in force
        except Exception:
            _pressure_log_once()
        finally:
            with self._pressure_lock:
                self._pressure_running = False

    # -- ledger ---------------------------------------------------------

    def register_table(self, name: str, data, owner: int = 0) -> None:
        with self._tables_lock:
            self._tables[(owner, name.lower())] = weakref.ref(data)

    def unregister_table(self, name: str, owner: int = 0) -> None:
        """DROP TABLE must drop the ledger line too: plan caches can
        keep the data object alive (strong refs in compiled relations),
        and a dropped table still counting toward memory pressure would
        trigger degradation to free bytes the user already released."""
        with self._tables_lock:
            self._tables.pop((owner, name.lower()), None)

    def register_executor(self, executor) -> None:
        self._executors.add(executor)

    def _iter_tables(self) -> List[Tuple[str, object]]:
        out = []
        with self._tables_lock:
            dead = []
            for (owner, nm), ref in self._tables.items():
                # locklint: callback-under-lock weakref deref, not a
                # callback: it runs no user code and touches no locks
                data = ref()
                if data is None:
                    dead.append((owner, nm))
                else:
                    out.append((nm, data))
            for k in dead:
                self._tables.pop(k, None)
        return out

    def ledger(self) -> dict:
        """Point-in-time unified ledger: per-table host/device bytes,
        spill-file bytes, and per-query admitted estimates."""
        from snappydata_tpu.storage import hoststore, tier
        from snappydata_tpu.storage.device import device_cache_bytes_by_table

        tables = self._iter_tables()
        host: Dict[str, int] = {}
        for nm, data in tables:   # same-named tables in two catalogs SUM
            host[nm] = host.get(nm, 0) + _host_table_bytes(data)
        device = device_cache_bytes_by_table(tables)
        from snappydata_tpu.engine.executor import gidx_cache_nbytes
        from snappydata_tpu.ops.join import join_build_cache_nbytes
        from snappydata_tpu.serving import serving_registry_nbytes
        from snappydata_tpu.views.matview import matview_state_nbytes

        gidx_bytes = gidx_cache_nbytes()
        join_bytes = join_build_cache_nbytes()
        view_bytes = matview_state_nbytes()
        serving_bytes = serving_registry_nbytes()
        from snappydata_tpu.engine.mesh_exec import \
            mesh_layout_cache_nbytes

        mesh_bytes = mesh_layout_cache_nbytes()
        from snappydata_tpu.storage.mvcc import \
            retained_epoch_bytes_by_table

        retained = retained_epoch_bytes_by_table(tables)
        retained_total = sum(retained.values())
        with self._cond:
            queries = {qid: int(ctx.estimate_bytes)
                       for qid, ctx in self._active.items()}
        # this walk IS the measurement — refresh the gauge cache so a
        # metrics scrape right after a ledger read can't serve a value
        # staler than the ledger it's compared against
        host_total = sum(host.values()) + serving_bytes + retained_total
        device_total = sum(device.values()) + gidx_bytes + join_bytes \
            + view_bytes + mesh_bytes
        self._measured_cache = (time.monotonic(), host_total, device_total)
        return {
            "host": host,
            "device": device,
            "spill_file_bytes": hoststore.spill_file_bytes(),
            # CRC-framed disk-tier files (storage/tier.py): batches the
            # demotion ladder pushed host -> disk; like spill files,
            # their memmapped pages belong to the OS cache, so they are
            # ledgered here but never counted into host_total
            "tier_file_bytes": tier.tier_file_bytes(),
            "host_total": host_total,
            # prepared-plan registry (serving/): analyzed+tokenized plan
            # shapes held for compile-once executes — LRU-capped by
            # serving_max_handles, evicted entries re-prepare on next use
            "serving_registry_bytes": serving_bytes,
            # group-index cache entries are device arrays too (valid +
            # gidx + matmul one-hot, up to gidx_cache_bytes) — reclaimed
            # with plan caches by the degradation ladder (clear_cache);
            # same story for the join build-artifact cache and the
            # materialized-view [G] accumulator state (evicted to STALE
            # under pressure, rebuilt by re-aggregation at next read)
            "gidx_cache_bytes": gidx_bytes,
            "join_build_cache_bytes": join_bytes,
            "matview_state_bytes": view_bytes,
            # mesh shuffle/broadcast bind layouts (engine/mesh_exec):
            # exchanged/replicated device copies of join sides, LRU-
            # bounded by mesh_shuffle_cache_entries
            "mesh_layout_cache_bytes": mesh_bytes,
            # MVCC retained epochs (storage/mvcc): host bytes old
            # manifests hold beyond the current one — row-buffer
            # snapshot copies + diverged delete/update deltas — while
            # pinned readers (or the short unpinned history) keep them
            # alive; trimmed by the degradation ladder, drains to ~0
            # once readers release
            "retained_epoch_bytes": retained_total,
            "device_total": device_total,
            "queries": queries,
            "inflight_bytes": int(self._inflight_bytes),
        }

    def measured_bytes(self, max_age_s: float = 0.0) -> Tuple[int, int]:
        """(host_bytes, device_bytes) actually in use. `max_age_s` lets
        cheap consumers (metrics gauges) reuse a recent walk instead of
        re-summing every table's batches per scrape."""
        if max_age_s > 0:
            ts, h, d = self._measured_cache
            if time.monotonic() - ts <= max_age_s:
                return h, d
        from snappydata_tpu.storage.device import device_cache_bytes_by_table

        from snappydata_tpu.engine.executor import gidx_cache_nbytes
        from snappydata_tpu.ops.join import join_build_cache_nbytes
        from snappydata_tpu.serving import serving_registry_nbytes
        from snappydata_tpu.views.matview import matview_state_nbytes

        from snappydata_tpu.storage.mvcc import \
            retained_epoch_bytes_by_table

        tables = self._iter_tables()
        host = sum(_host_table_bytes(d) for _, d in tables) \
            + serving_registry_nbytes() \
            + sum(retained_epoch_bytes_by_table(tables).values())
        from snappydata_tpu.engine.mesh_exec import \
            mesh_layout_cache_nbytes

        device = sum(device_cache_bytes_by_table(tables).values()) \
            + gidx_cache_nbytes() + join_build_cache_nbytes() \
            + matview_state_nbytes() + mesh_layout_cache_nbytes()
        self._measured_cache = (time.monotonic(), host, device)
        return host, device

    # -- admission ------------------------------------------------------

    def _has_room(self, ctx: QueryContext, limit: int) -> bool:
        return self._inflight_bytes + ctx.estimate_bytes <= limit

    def _fair_slot_free(self, ctx: QueryContext) -> bool:
        slots = int(self.props.admission_slots_per_user or 0)
        if slots <= 0:
            return True
        held = sum(1 for c in self._active.values() if c.user == ctx.user)
        return held < slots

    def admit(self, ctx: QueryContext, estimate_bytes: int = 0,
              timeout_s: float = 0.0) -> QueryContext:
        """Admit, queue, or reject `ctx`. On admit the context is
        registered (visible to `queries()`/`cancel()`) and its statement
        deadline starts. Callers MUST pair with `release(ctx)`."""
        reg = global_registry()
        ctx.estimate_bytes = int(estimate_bytes or 0)
        if ctx.cancelled:
            # cancelled in the submit→admit window (watched jobserver
            # contexts): never start running
            raise CancelException(
                f"query {ctx.query_id} "
                f"{ctx.cancel_reason or 'cancelled'} before admission")
        from snappydata_tpu.reliability import failpoints as rfail

        # admission entry seam — ahead of the limit check so the fault
        # fires whether or not governor accounting is on
        rfail.hit("broker.admit")
        # background-compaction kick (storage/compact.py): a flag check
        # under a leaf lock; the rewrite itself never runs on the
        # admission path
        from snappydata_tpu.storage import compact

        compact.maybe_kick(self)
        limit = self._limit()
        if limit <= 0:
            # governor accounting off: admit unconditionally, but still
            # register so CANCEL / timeouts / REST visibility work
            with self._cond:
                self._active[ctx.query_id] = ctx
                self._inflight_bytes += ctx.estimate_bytes
            ctx.start(timeout_s)
            reg.inc("governor_admitted")
            return ctx
        if ctx.estimate_bytes > limit:
            reg.inc("governor_rejected")
            raise LowMemoryException(
                f"query estimate {ctx.estimate_bytes} bytes exceeds "
                f"memory_limit_bytes={limit}; rejected before execution "
                f"(raise the limit or narrow the scan)")
        # memory pressure (measured, not just planned): degrade first.
        # A short-lived cache bounds the per-admission ledger walk under
        # concurrent short queries; watermark staleness of 0.25s is noise
        host, device = self.measured_bytes(max_age_s=0.25)
        if host + device > self._high_bytes(limit):
            self._degrade(int(self._low_bytes(limit)), requester=ctx)
        elif host + device > self._pressure_bytes(limit):
            # below the high watermark but above the PRESSURE watermark:
            # start background tier demotion NOW, while this statement
            # still fits — by the time residency would hit the high
            # watermark the ladder has already freed the cheap rungs
            # (ROADMAP 4(c): relief before allocation fails mid-stmt)
            self._kick_pressure_demote(limit)
        # a statement timeout covers queue time too (the reference's
        # query-cancel timer starts at submission, not first row):
        # the deadline is pinned NOW so ctx.start() cannot re-arm it
        stmt_deadline = None
        wait_s = float(self.props.admission_wait_s)
        if timeout_s and timeout_s > 0:
            stmt_deadline = time.monotonic() + float(timeout_s)
            ctx.deadline = stmt_deadline
            wait_s = min(wait_s, float(timeout_s))
        deadline = time.monotonic() + wait_s
        queued = False
        with self._cond:
            while True:
                if ctx.cancelled:
                    if queued:
                        self._queue.remove(ctx)
                    raise CancelException(
                        f"query {ctx.query_id} "
                        f"{ctx.cancel_reason or 'cancelled'} while queued")
                # FIFO over MEMORY, but a head blocked purely by its
                # principal's fair slot must not starve other users
                # (head-of-line): ctx may go when it fits and everything
                # ahead of it is fair-slot-blocked
                ahead = self._queue[:self._queue.index(ctx)] if queued \
                    else list(self._queue)
                if self._has_room(ctx, limit) \
                        and self._fair_slot_free(ctx) \
                        and all(not self._fair_slot_free(e)
                                for e in ahead):
                    if queued:
                        self._queue.remove(ctx)
                    self._active[ctx.query_id] = ctx
                    self._inflight_bytes += ctx.estimate_bytes
                    ctx.start(timeout_s)
                    reg.inc("governor_admitted")
                    self._cond.notify_all()
                    return ctx
                if not queued:
                    depth = int(self.props.admission_queue_depth)
                    if len(self._queue) >= max(0, depth):
                        reg.inc("governor_rejected")
                        raise LowMemoryException(
                            f"admission queue full ({len(self._queue)} "
                            f"waiting, depth {depth}); query rejected")
                    self._queue.append(ctx)
                    ctx.state = "queued"
                    queued = True
                    reg.inc("governor_queued")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._queue.remove(ctx)
                    if stmt_deadline is not None \
                            and time.monotonic() >= stmt_deadline:
                        # the STATEMENT timeout expired while queued:
                        # that is a cancellation (XCL52), not a
                        # memory rejection
                        ctx.cancel("timed out (query_timeout_s) "
                                   "while queued for admission")
                        reg.inc("governor_timeouts")
                        raise CancelException(
                            f"query {ctx.query_id} exceeded its "
                            f"statement timeout while queued")
                    reg.inc("governor_rejected")
                    raise LowMemoryException(
                        f"query {ctx.query_id} waited {wait_s:.1f}s "
                        f"for admission ({self._inflight_bytes} of "
                        f"{limit} bytes in flight); rejected")
                self._cond.wait(min(remaining, 0.25))

    def watch(self, ctx: QueryContext) -> QueryContext:
        """Register a context BEFORE admission (jobserver submissions):
        it shows in `queries()` and `cancel()` finds it from the moment
        of submission — a cancel landing in the submit→admit window
        makes `admit()` raise instead of being dropped with a 404.
        Cleared by `release()` (call release even on failed admits)."""
        with self._cond:
            self._watched[ctx.query_id] = ctx
        return ctx

    def release(self, ctx: QueryContext) -> None:
        with self._cond:
            self._watched.pop(ctx.query_id, None)
            if self._active.pop(ctx.query_id, None) is not None:
                self._inflight_bytes -= ctx.estimate_bytes
                if self._inflight_bytes < 0:
                    self._inflight_bytes = 0
            ctx.state = "finished"
            self._cond.notify_all()

    # -- degradation ----------------------------------------------------

    def _degrade(self, target_bytes: int,
                 requester: Optional[QueryContext] = None) -> None:
        """Graceful pressure relief, cheapest first (ref: evict → spill →
        cancel ordering of SnappyStorageEvictor + CancelException):
        1. drop compiled-plan caches, 2. spill cold batches to disk,
        3. cancel the hungriest admitted query (never the requester)."""
        reg = global_registry()
        host, device = self.measured_bytes()
        if host + device <= target_bytes:
            return
        for ex in list(self._executors):
            try:
                ex.clear_cache()
            except Exception:
                pass
        # prepared-plan registries are caches too: evicted statements
        # transparently re-prepare on next execute
        from snappydata_tpu.serving.prepared import _REGISTRIES

        for r in list(_REGISTRIES):
            try:
                r.clear()
            except Exception:
                pass
        reg.inc("governor_degrade_plan_evictions")
        host, device = self.measured_bytes()
        if host + device <= target_bytes:
            return
        # materialized-view [G] states are caches too: evictable to
        # STALE (rebuilt by one re-aggregation at next read) — cheaper
        # than spilling hot table batches every scan re-decodes
        from snappydata_tpu.views.matview import evict_all_states

        if evict_all_states():
            reg.inc("governor_degrade_view_evictions")
        host, device = self.measured_bytes()
        if host + device <= target_bytes:
            return
        # trim MVCC retained epochs nobody pins (and stale device-cache
        # plates of old versions) — cheaper than spilling hot batches;
        # pinned epochs are untouchable mid-scan by design
        from snappydata_tpu.storage import mvcc

        if mvcc.trim_unpinned(self._iter_tables()):
            reg.inc("governor_degrade_epoch_trims")
        host, device = self.measured_bytes()
        if host + device <= target_bytes:
            return
        # walk the tier ladder (storage/tier.py): drop cold UNPINNED
        # device plates back to the host pool, then frame the oldest
        # host batches into CRC-checked disk-tier files — both rungs
        # rebuild transparently on the next bind/scan
        from snappydata_tpu.storage import tier

        if tier.demote(self._iter_tables(),
                       host + device - target_bytes):
            reg.inc("governor_degrade_tier_demotions")
        host, device = self.measured_bytes()
        if host + device <= target_bytes:
            return
        from snappydata_tpu.storage import hoststore

        for _nm, data in self._iter_tables():
            host, device = self.measured_bytes()
            excess = host + device - target_bytes
            if excess <= 0:
                return
            if hasattr(data, "_manifest"):
                # spill only down to the deficit — a marginal watermark
                # crossing must not flush a whole hot table to disk
                # (every later scan would re-decode it)
                keep = max(0, _host_table_bytes(data) - excess)
                try:
                    if hoststore.spill_to_budget(data, keep):
                        reg.inc("governor_degrade_spills")
                except Exception:
                    pass
        host, device = self.measured_bytes()
        if host + device <= target_bytes:
            return
        with self._cond:
            victims = [c for c in self._active.values() if c is not requester]
        if victims:
            hungriest = max(victims, key=lambda c: c.estimate_bytes)
            hungriest.cancel("cancelled by resource broker (low memory)")
            reg.inc("governor_degrade_kills")
            reg.inc("governor_cancelled")
            with self._cond:
                self._cond.notify_all()

    # -- cancellation / visibility --------------------------------------

    def cancel(self, query_id: str, reason: str = "cancelled by request",
               user: Optional[str] = None) -> bool:
        """Flag a running or queued query. `user` (when given and not
        admin) may only cancel their own queries."""
        with self._cond:
            ctx = self._lookup_locked(query_id)
            if ctx is None:
                return False
            if user is not None and user != "admin" and ctx.user != user:
                raise PermissionError(
                    f"user {user!r} may not cancel query {query_id} "
                    f"owned by {ctx.user!r}")
            ctx.cancel(reason)
            self._cond.notify_all()
        global_registry().inc("governor_cancelled")
        return True

    def queries(self) -> List[dict]:
        with self._cond:
            seen = {c.query_id: c for c in self._watched.values()}
            seen.update({c.query_id: c for c in self._queue})
            seen.update({c.query_id: c for c in self._active.values()})
            out = [c.describe() for c in seen.values()]
        out.sort(key=lambda d: d["submitted_ts"])
        return out

    def _lookup_locked(self, query_id: str) -> Optional[QueryContext]:
        return self._active.get(query_id) \
            or next((c for c in self._queue if c.query_id == query_id),
                    None) \
            or self._watched.get(query_id)

    def lookup(self, query_id: str) -> Optional[QueryContext]:
        with self._cond:
            return self._lookup_locked(query_id)


_global_broker: Optional[ResourceBroker] = None
_global_lock = locks.named_lock("resource.broker_global")


def global_broker() -> ResourceBroker:
    global _global_broker
    if _global_broker is None:
        with _global_lock:
            if _global_broker is None:
                _global_broker = ResourceBroker()
    return _global_broker
