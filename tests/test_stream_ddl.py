"""CREATE STREAM TABLE DDL (ref: SnappyDDLParser createStream:716 + file/
memory stream sources) — a queryable table continuously fed by a
micro-batch source with exactly-once semantics."""

import json
import time

import numpy as np
import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog


@pytest.fixture()
def s():
    sess = SnappySession(catalog=Catalog())
    yield sess
    for q in getattr(sess.catalog, "_streams", {}).values():
        q.stop()
    sess.stop()


def _wait_rows(s, table, expect, timeout=8.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        got = s.sql(f"SELECT count(*) FROM {table}").rows()[0][0]
        if got >= expect:
            return got
        time.sleep(0.05)
    return s.sql(f"SELECT count(*) FROM {table}").rows()[0][0]


def test_memory_stream_table(s):
    s.sql("CREATE STREAM TABLE events (id INT PRIMARY KEY, v DOUBLE) "
          "USING memory_stream OPTIONS (interval '0.02')")
    src = s.stream_source("events")
    src.add_batch({"id": np.array([1, 2]), "v": np.array([0.5, 1.5])})
    src.add_batch({"id": np.array([3]), "v": np.array([2.5])})
    assert _wait_rows(s, "events", 3) == 3
    assert s.sql("SELECT sum(v) FROM events").rows()[0][0] == \
        pytest.approx(4.5)
    # upsert semantics via key_columns (duplicate id updates, not dups)
    src.add_batch({"id": np.array([3]), "v": np.array([9.0])})
    deadline = time.time() + 8
    while time.time() < deadline:
        if s.sql("SELECT max(v) FROM events").rows()[0][0] == 9.0:
            break
        time.sleep(0.05)
    assert s.sql("SELECT count(*) FROM events").rows()[0][0] == 3


def test_file_stream_table(tmp_path, s):
    d = tmp_path / "in"
    d.mkdir()
    (d / "00.json").write_text("\n".join(
        json.dumps({"k": i, "name": f"row{i}"}) for i in range(5)))
    s.sql(f"CREATE STREAM TABLE filetab (k INT, name STRING) "
          f"USING file_stream OPTIONS (directory '{d}', interval '0.02')")
    assert _wait_rows(s, "filetab", 5) == 5
    (d / "01.json").write_text(json.dumps({"k": 99, "name": "late"}))
    assert _wait_rows(s, "filetab", 6) == 6
    assert s.sql("SELECT name FROM filetab WHERE k = 99").rows() == \
        [("late",)]


def test_failed_stream_create_leaves_no_orphan(s):
    with pytest.raises(ValueError, match="directory"):
        s.sql("CREATE STREAM TABLE bad (k INT) USING file_stream")
    assert s.catalog.lookup_table("bad") is None


def test_if_not_exists_keeps_running_query(s):
    s.sql("CREATE STREAM TABLE ms2 (a INT) USING memory_stream")
    q1 = s.catalog._streams["ms2"]
    s.sql("CREATE STREAM TABLE IF NOT EXISTS ms2 (a INT) "
          "USING memory_stream")
    assert s.catalog._streams["ms2"] is q1  # no leaked second feeder


def test_poison_file_does_not_wedge(tmp_path, s):
    d = tmp_path / "poison"
    d.mkdir()
    (d / "00.json").write_text(json.dumps({"k": 1}))
    (d / "01.json").write_text("{not json at all")
    (d / "02.json").write_text(json.dumps({"k": 3}))
    s.sql(f"CREATE STREAM TABLE pz (k INT) USING file_stream "
          f"OPTIONS (directory '{d}', interval '0.02')")
    assert _wait_rows(s, "pz", 2) == 2  # poison skipped, stream advanced


def test_stream_survives_restart(tmp_path):
    d = tmp_path / "data"
    fd = tmp_path / "feed"
    fd.mkdir()
    (fd / "0.json").write_text(json.dumps({"k": 1}))
    s = SnappySession(catalog=Catalog(), data_dir=str(d), recover=False)
    s.sql(f"CREATE STREAM TABLE fs (k INT) USING file_stream "
          f"OPTIONS (directory '{fd}', interval '0.02')")
    assert _wait_rows(s, "fs", 1) == 1
    s.checkpoint()
    for q in s.catalog._streams.values():
        q.stop()
    s.disk_store.close()
    s2 = SnappySession(data_dir=str(d))
    (fd / "1.json").write_text(json.dumps({"k": 2}))
    try:
        assert _wait_rows(s2, "fs", 2) == 2  # feed re-registered
    finally:
        for q in s2.catalog._streams.values():
            q.stop()


def test_drop_table_clears_topk_for_recovery(tmp_path):
    s = SnappySession(catalog=Catalog(), data_dir=str(tmp_path),
                      recover=False)
    s.sql("CREATE TABLE t (k INT) USING column")
    s.create_topk("tk", "t", "k")
    s.sql("DROP TABLE t")
    s.disk_store.close()
    s2 = SnappySession(data_dir=str(tmp_path))  # must not crash
    assert s2.catalog.list_tables() == []


def test_drop_stream_table_stops_query(s):
    s.sql("CREATE STREAM TABLE st (a INT) USING memory_stream")
    q = s.catalog._streams["st"]
    assert q.is_active
    s.sql("DROP TABLE st")
    assert not q.is_active
    with pytest.raises(ValueError):
        s.stream_source("st")


def test_windowed_sql_over_stream(session):
    """DStream-style sliding window (ref: WindowLogicalPlan): WINDOW
    (DURATION n SECONDS) restricts the query to recently-arrived rows."""
    import time

    session.sql("CREATE STREAM TABLE ws (k INT PRIMARY KEY, v DOUBLE) "
                "USING memory_stream OPTIONS (interval '0.01')")
    src = session.stream_source("ws")
    q = session.catalog._streams["ws"]
    src.add_batch({"k": np.array([1, 2], dtype=np.int32),
                   "v": np.array([1.0, 2.0])})
    q.process_available()
    # warm the plan shapes NOW: the wall-clock window below must not
    # race first-compile latency (flaked whenever module import/trace
    # cost pushed the batch past the window before the query ran)
    session.sql("SELECT count(*) FROM ws")
    session.sql("SELECT k, v FROM ws WINDOW (DURATION 0.3 SECONDS) "
                "ORDER BY k")
    time.sleep(0.35)
    src.add_batch({"k": np.array([3], dtype=np.int32),
                   "v": np.array([30.0])})
    q.process_available()

    # full table sees everything; the window only the recent batch
    assert session.sql("SELECT count(*) FROM ws").rows()[0][0] == 3
    recent = session.sql(
        "SELECT k, v FROM ws WINDOW (DURATION 0.3 SECONDS) ORDER BY k"
    ).rows()
    assert recent == [(3, 30.0)]
    both = session.sql(
        "SELECT count(*), sum(v) FROM ws WINDOW (DURATION 1 MINUTES)"
    ).rows()[0]
    assert both == (3, 33.0)
    # aggregate over the window with slide quantization parses + runs
    session.sql("SELECT k, count(*) FROM ws WINDOW (DURATION 10 SECONDS, "
                "SLIDE 5 SECONDS) GROUP BY k")

    # the hidden arrival column stays hidden
    assert all(not n.startswith("__") for n in
               session.sql("SELECT * FROM ws").names)
    d = session.sql("DESCRIBE ws").rows()
    assert all(not r[0].startswith("__") for r in d)
    # plain INSERT works without mentioning the hidden column and is
    # visible to windows immediately
    session.sql("INSERT INTO ws VALUES (9, 90.0)")
    r = session.sql("SELECT k FROM ws WINDOW (DURATION 0.5 SECONDS) "
                    "ORDER BY k").rows()
    assert (9,) in r

    # WINDOW on a non-stream table errors clearly
    session.sql("CREATE TABLE plain_t (a INT) USING column")
    with pytest.raises(Exception, match="STREAM"):
        session.sql("SELECT * FROM plain_t WINDOW (DURATION 5 SECONDS)")


def test_streaming_progress_and_rest_endpoint(s):
    """StreamingQueryManager parity: progress snapshots via the session
    API and the /status/api/v1/streaming REST route (ref: the
    structured-streaming UI tab reads batches/rows/rates)."""
    import urllib.request

    from snappydata_tpu.cluster.rest import RestService

    s.sql("CREATE STREAM TABLE prog (id INT PRIMARY KEY, v DOUBLE) "
          "USING memory_stream OPTIONS (interval '0.02')")
    src = s.stream_source("prog")
    src.add_batch({"id": np.array([1, 2, 3]),
                   "v": np.array([0.5, 1.5, 2.5])})
    assert _wait_rows(s, "prog", 3) == 3

    progress = s.streaming_queries()
    assert len(progress) == 1
    p = progress[0]
    assert p["name"] == "stream_prog" and p["table"] == "prog"
    assert p["active"] is True
    assert p["batches_processed"] >= 1
    assert p["rows_processed"] == 3
    assert p["last_batch_id"] >= 0 and p["last_error"] is None

    svc = RestService(s, None, host="127.0.0.1", port=0).start()
    try:
        got = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{svc.port}/status/api/v1/streaming").read())
        assert got and got[0]["rows_processed"] == 3
    finally:
        svc.stop()
