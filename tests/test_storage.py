"""Storage format tests (ref analogue: encoders project unit coverage —
ColumnEncoding/Dictionary/RunLength round-trips, delta merge, delete mask,
snapshot visibility per ValidateMVCCDUnitTest semantics)."""

import numpy as np
import pytest

from snappydata_tpu import types as T
from snappydata_tpu.storage import bitmask
from snappydata_tpu.storage.encoding import (
    Encoding, encode_column, decode_to_numpy, decode_validity)
from snappydata_tpu.storage.table_store import ColumnTableData, RowTableData
from snappydata_tpu.storage.device import build_device_table


def test_bitmask_roundtrip():
    rng = np.random.default_rng(0)
    m = rng.random(1000) < 0.3
    assert (bitmask.unpack(bitmask.pack(m), 1000) == m).all()
    assert bitmask.popcount(bitmask.pack(m), 1000) == m.sum()


def test_plain_roundtrip_and_stats():
    vals = np.arange(100, dtype=np.int64) * 3
    col = encode_column(vals, T.LONG)
    assert col.encoding == Encoding.PLAIN
    assert (decode_to_numpy(col) == vals).all()
    assert col.stats.min == 0 and col.stats.max == 297
    padded = decode_to_numpy(col, capacity=128)
    assert padded.shape == (128,) and (padded[:100] == vals).all()


def test_rle_selected_for_low_cardinality():
    vals = np.repeat(np.array([5, 9, 5], dtype=np.int32), 200)
    col = encode_column(vals, T.INT)
    assert col.encoding == Encoding.RUN_LENGTH
    assert col.data.shape == (3,)
    assert (decode_to_numpy(col) == vals).all()


def test_dictionary_strings():
    vals = np.array(["A", "F", "A", "N", "F"], dtype=object)
    col = encode_column(vals, T.STRING)
    assert col.encoding == Encoding.DICTIONARY
    assert (decode_to_numpy(col, strings=True) == vals).all()
    assert decode_to_numpy(col).dtype == np.int32


def test_dictionary_shared_hint():
    hint = np.array(["N", "A", "F"], dtype=object)
    vals = np.array(["A", "F", "A"], dtype=object)
    col = encode_column(vals, T.STRING, dictionary_hint=hint)
    assert (col.data == np.array([1, 2, 1])).all()


def test_boolean_bitset():
    vals = np.array([True, False, True] * 50)
    col = encode_column(vals, T.BOOLEAN)
    assert col.encoding == Encoding.BOOLEAN_BITSET
    assert (decode_to_numpy(col) == vals).all()


def test_nulls():
    vals = np.array([1.0, 2.0, 3.0, 4.0])
    validity = np.array([True, False, True, False])
    col = encode_column(vals, T.DOUBLE, validity)
    assert col.stats.null_count == 2
    assert (decode_validity(col) == validity).all()


def _make_table(n=1000, capacity=256, max_delta=100):
    schema = T.Schema([
        T.Field("k", T.LONG), T.Field("v", T.DOUBLE), T.Field("s", T.STRING)])
    data = ColumnTableData(schema, capacity=capacity, max_delta_rows=max_delta)
    rng = np.random.default_rng(1)
    k = np.arange(n, dtype=np.int64)
    v = rng.random(n)
    s = np.array([["x", "y", "z"][i % 3] for i in range(n)], dtype=object)
    data.insert_arrays([k, v, s])
    return schema, data, (k, v, s)


def test_bulk_insert_cuts_batches():
    schema, data, (k, v, s) = _make_table()
    m = data.snapshot()
    assert m.total_rows() == 1000
    assert len(m.views) >= 3  # bulk path cut real batches
    dt = build_device_table(data, m, [0, 1, 2])
    valid = np.asarray(dt.valid)
    assert int(valid.sum()) == 1000
    kk = np.asarray(dt.columns[0])[valid]
    assert sorted(kk.tolist()) == k.tolist()


def test_small_insert_row_buffer_and_rollover():
    schema = T.Schema([T.Field("a", T.INT)])
    data = ColumnTableData(schema, capacity=64, max_delta_rows=50)
    for i in range(4):
        data.insert_arrays([np.arange(10, dtype=np.int32) + i * 10])
    m = data.snapshot()
    assert m.row_count == 40 and len(m.views) == 0
    data.insert_arrays([np.arange(10, dtype=np.int32) + 40])
    m = data.snapshot()
    assert m.row_count == 0 and len(m.views) == 1  # rollover fired at 50
    assert m.total_rows() == 50


def test_update_delete_and_snapshot_isolation():
    schema, data, (k, v, s) = _make_table()
    before = data.snapshot()
    n_upd = data.update(lambda c: c["k"] < 10, {"v": lambda c: c["v"] * 0 + 7.0})
    assert n_upd == 10
    n_del = data.delete(lambda c: c["k"] >= 990)
    assert n_del == 10
    after = data.snapshot()
    # old snapshot still sees original data (MVCC)
    dt_old = build_device_table(data, before, [0, 1])
    # note: device cache was invalidated by new version; rebuild old is fine
    valid_old = np.asarray(dt_old.valid)
    assert int(valid_old.sum()) == 1000
    dt_new = build_device_table(data, after, [0, 1])
    valid_new = np.asarray(dt_new.valid)
    assert int(valid_new.sum()) == 990
    vv = np.asarray(dt_new.columns[1])
    kk = np.asarray(dt_new.columns[0])
    assert (vv[(kk < 10) & valid_new] == 7.0).all()


def test_row_table_pk_and_put():
    schema = T.Schema([T.Field("id", T.INT), T.Field("name", T.STRING)])
    rt = RowTableData(schema, key_columns=["id"])
    rt.insert_arrays([np.array([1, 2, 3]), np.array(["a", "b", "c"], dtype=object)])
    assert rt.get((2,)) == (2, "b")
    with pytest.raises(ValueError):
        rt.insert_arrays([np.array([1]), np.array(["dup"], dtype=object)])
    rt.put_arrays([np.array([2, 4]), np.array(["B", "d"], dtype=object)])
    assert rt.get((2,)) == (2, "B")
    assert rt.count() == 4
    rt.delete(lambda c: c["id"] == 1)
    assert rt.get((1,)) is None
    assert rt.count() == 3


def test_host_store_spill_and_transparent_reload():
    """Above host_store_bytes the coldest batches spill to disk-backed
    memmaps; queries keep returning exact results (transparent reload
    through the page cache). Ref: SnappyUnifiedMemoryManager eviction."""
    from snappydata_tpu import SnappySession, config
    from snappydata_tpu.catalog import Catalog
    from snappydata_tpu.observability.metrics import global_registry
    from snappydata_tpu.storage import hoststore

    gp = config.global_properties()
    old_budget = gp.host_store_bytes
    old_rows = gp.column_batch_rows
    gp.host_store_bytes = 256 * 1024     # tiny budget → force spilling
    gp.column_batch_rows = 8192
    try:
        s = SnappySession(catalog=Catalog())
        s.sql("CREATE TABLE hs (k BIGINT, v DOUBLE) USING column")
        n = 200_000
        k = np.arange(n, dtype=np.int64)
        v = np.sqrt(k.astype(np.float64))
        for lo in range(0, n, 50_000):
            s.insert_arrays("hs", [k[lo:lo + 50_000], v[lo:lo + 50_000]])
        data = s.catalog.describe("hs").data
        m = data.snapshot()
        resident = sum(hoststore.batch_resident_bytes(x.batch)
                       for x in m.views)
        assert resident <= gp.host_store_bytes, resident
        spilled = global_registry().snapshot()["counters"].get(
            "host_batches_spilled", 0)
        assert spilled > 0
        # exactness straight through the memmapped batches
        r = s.sql("SELECT count(*), sum(v), max(k) FROM hs").rows()[0]
        assert r[0] == n
        assert r[1] == pytest.approx(float(v.sum()), rel=1e-12)
        assert r[2] == n - 1
        # mutation over spilled batches still works (delta on the view)
        upd = s.sql("UPDATE hs SET v = 0.0 WHERE k < 100").rows()[0][0]
        assert upd == 100
        r2 = s.sql("SELECT sum(v) FROM hs").rows()[0][0]
        assert r2 == pytest.approx(float(v[100:].sum()), rel=1e-12)
    finally:
        gp.host_store_bytes = old_budget
        gp.column_batch_rows = old_rows


def test_checkpoint_compression_on_by_default(tmp_path):
    """Checkpoint/WAL bytes are zstd-compressed by default (ref: LZ4
    default codec, Constant.scala:150) and recover exactly."""
    import os as _os

    from snappydata_tpu import SnappySession, config

    d1 = str(tmp_path / "zstd")
    d2 = str(tmp_path / "raw")
    n = 120_000
    k = np.arange(n, dtype=np.int64) % 1000   # compressible
    v = np.ones(n)

    assert config.global_properties().compression_codec == "zstd"
    s1 = SnappySession(data_dir=d1)
    s1.sql("CREATE TABLE ct (k BIGINT, v DOUBLE) USING column")
    s1.insert_arrays("ct", [k, v])
    s1.checkpoint()
    s1.disk_store.close()

    old = config.global_properties().compression_codec
    config.global_properties().compression_codec = "none"
    try:
        s2 = SnappySession(data_dir=d2)
        s2.sql("CREATE TABLE ct (k BIGINT, v DOUBLE) USING column")
        s2.insert_arrays("ct", [k, v])
        s2.checkpoint()
        s2.disk_store.close()
    finally:
        config.global_properties().compression_codec = old

    def tree_bytes(root):
        total = 0
        for base, _dirs, files in _os.walk(root):
            for f in files:
                total += _os.path.getsize(_os.path.join(base, f))
        return total

    assert tree_bytes(d1) < tree_bytes(d2) * 0.6, \
        (tree_bytes(d1), tree_bytes(d2))

    s3 = SnappySession(data_dir=d1)
    r = s3.sql("SELECT count(*), sum(k) FROM ct").rows()[0]
    assert r[0] == n and r[1] == int(k.sum())
    s3.disk_store.close()
