import os

# Honor JAX_PLATFORMS for CLI-launched processes even when a site
# bootstrap (e.g. an accelerator plugin's sitecustomize) force-set the
# platform list at interpreter start: cluster members must be able to run
# CPU-only (several per host, none monopolizing the accelerator).
if os.environ.get("JAX_PLATFORMS"):
    import jax

    try:
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    except Exception:
        pass  # backend already initialized; leave it be

from snappydata_tpu.cli import main

raise SystemExit(main())
