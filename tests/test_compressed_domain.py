"""Compressed-domain execution (r06 tentpole): predicates and aggregate
inputs evaluate directly over ENCODED batches — VALUE_DICT columns stay
resident as uint8/uint16 code plates (literals translate to code
thresholds through the sorted per-batch dictionaries), RLE columns stay
as runs (per-run predicate evaluation), bitset columns stay packed —
decoding only what survives, in-trace, fused by XLA.  Every result here
is value-asserted against the decoded path (scan_compressed_domain=off),
across encodings × NULLs × empty batches × out-of-dictionary literals ×
prepared-statement `?` binds."""

import numpy as np
import pytest

from snappydata_tpu import SnappySession, config
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.observability.metrics import global_registry
from snappydata_tpu.storage import device_decode
from snappydata_tpu.storage.encoding import Encoding


def _props():
    return config.global_properties()


@pytest.fixture(autouse=True)
def _restore_knob():
    saved = _props().get("scan_compressed_domain")
    yield
    _props().set("scan_compressed_domain", saved)


def _mixed_session(n=60_000, with_nulls=True):
    """One table exercising every encoding: PLAIN (v), DICTIONARY
    (name), VALUE_DICT uint8 (qty), VALUE_DICT uint16 (wide),
    RUN_LENGTH (grp), BOOLEAN_BITSET (flag)."""
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE mx (k BIGINT, qty DOUBLE, wide DOUBLE, "
          "grp BIGINT, flag BOOLEAN, name STRING, v DOUBLE) USING column")
    rng = np.random.default_rng(17)
    k = np.arange(n, dtype=np.int64)
    qty = np.floor(rng.random(n) * 50) + 1.0
    wide = rng.integers(0, 5000, n).astype(np.float64) * 0.5
    grp = np.sort(rng.integers(0, 6, n)).astype(np.int64)
    flag = (k % 3 == 0)
    name = np.array([f"n{i % 7}" for i in range(n)], dtype=object)
    v = rng.random(n) * 1000
    s.insert_arrays("mx", [k, qty, wide, grp, flag, name, v])
    if with_nulls:
        # NULL rows ride the row buffer, then roll into the batch with a
        # validity mask — nulls over every compressible column
        for i in range(8):
            s.sql(f"INSERT INTO mx VALUES ({n + i}, NULL, NULL, NULL, "
                  f"NULL, NULL, {float(i)})")
    data = s.catalog.describe("mx").data
    data.force_rollover()
    return s, dict(k=k, qty=qty, wide=wide, grp=grp, flag=flag,
                   name=name, v=v), data


def _both(s, sql, params=None):
    """(compressed rows, decoded rows) of one query — the equivalence
    harness.  The knob rides the STATIC key: no cache flush between."""
    _props().set("scan_compressed_domain", "auto")
    on = s.sql(sql, params).rows() if params else s.sql(sql).rows()
    _props().set("scan_compressed_domain", "off")
    off = s.sql(sql, params).rows() if params else s.sql(sql).rows()
    _props().set("scan_compressed_domain", "auto")
    return on, off


def _assert_rows_equal(a, b):
    assert len(a) == len(b), (a, b)
    for ra, rb in zip(a, b):
        assert len(ra) == len(rb), (ra, rb)
        for x, y in zip(ra, rb):
            if isinstance(x, float) and isinstance(y, float):
                assert x == pytest.approx(y, rel=1e-12, abs=1e-12), (ra, rb)
            else:
                assert x == y, (ra, rb)


def test_encodings_at_rest_are_what_the_suite_assumes():
    s, cols, data = _mixed_session()
    m = data.snapshot()
    enc = {i: m.views[0].batch.columns[i].encoding for i in range(7)}
    assert enc[1] == Encoding.VALUE_DICT          # qty
    assert enc[2] == Encoding.VALUE_DICT          # wide (uint16)
    assert m.views[0].batch.columns[2].data.dtype == np.uint16
    assert m.views[0].batch.columns[1].data.dtype == np.uint8
    assert enc[3] == Encoding.RUN_LENGTH          # grp
    assert enc[4] == Encoding.BOOLEAN_BITSET      # flag
    assert enc[5] == Encoding.DICTIONARY          # name
    assert enc[6] == Encoding.PLAIN               # v
    s.stop()


def test_property_matrix_code_vs_decoded():
    """The core equivalence sweep: every comparison op × in/out-of-
    dictionary/boundary literals × every encoding × NULL rows, each
    value-asserted compressed == decoded."""
    s, cols, _ = _mixed_session()
    queries = []
    for op in ("=", "!=", "<", "<=", ">", ">="):
        for lit in ("24", "24.5", "-1", "999"):   # in-dict, miss, edges
            queries.append(f"SELECT count(*), sum(v) FROM mx "
                           f"WHERE qty {op} {lit}")
        queries.append(f"SELECT count(*) FROM mx WHERE wide {op} 1250.0")
        queries.append(f"SELECT count(*) FROM mx WHERE grp {op} 3")
    queries += [
        "SELECT count(*), sum(v) FROM mx WHERE qty BETWEEN 10 AND 20",
        "SELECT count(*) FROM mx WHERE qty = 10 AND grp >= 2",
        "SELECT count(*) FROM mx WHERE flag",
        "SELECT count(*) FROM mx WHERE NOT flag",
        "SELECT count(*) FROM mx WHERE name = 'n3'",
        "SELECT count(*) FROM mx WHERE name = 'absent'",
        "SELECT grp, count(*), sum(qty), min(wide), max(qty) FROM mx "
        "GROUP BY grp ORDER BY grp",
        "SELECT count(*) FROM mx WHERE qty IS NULL",
        "SELECT count(*), sum(qty) FROM mx WHERE qty IS NOT NULL",
        "SELECT sum(qty * v), avg(wide) FROM mx WHERE grp <= 4",
    ]
    for q in queries:
        on, off = _both(s, q)
        _assert_rows_equal(on, off)
    s.stop()


def test_decimal_literal_takes_the_generic_lane():
    """Exact-decimal literals (scaled-int64 representation from scalar
    subquery substitution) must NOT enter the code-compare lane — the
    threshold would be off by 10^scale."""
    s, cols, _ = _mixed_session(with_nulls=False)
    s.sql("CREATE TABLE dlim (d DECIMAL(6,2)) USING row")
    s.sql("INSERT INTO dlim VALUES (24.05)")
    q = "SELECT count(*) FROM mx WHERE qty < (SELECT max(d) FROM dlim)"
    on, off = _both(s, q)
    _assert_rows_equal(on, off)
    assert on[0][0] == int((cols["qty"] < 24.05).sum())
    s.stop()


def test_out_of_dictionary_equality_skips_batches():
    s, cols, _ = _mixed_session(with_nulls=False)
    reg = global_registry()
    c0 = reg.snapshot()["counters"].get("batches_skipped_dict", 0)
    r = s.sql("SELECT count(*) FROM mx WHERE qty = 24.5")
    assert r.rows()[0][0] == 0
    c1 = global_registry().snapshot()["counters"].get(
        "batches_skipped_dict", 0)
    assert c1 > c0, "out-of-dictionary equality must skip whole batches"
    # a string equality literal absent from the table dictionary skips
    # the whole relation the same way
    c2 = c1
    assert s.sql("SELECT count(*) FROM mx "
                 "WHERE name = 'nope'").rows()[0][0] == 0
    c3 = global_registry().snapshot()["counters"].get(
        "batches_skipped_dict", 0)
    assert c3 > c2
    s.stop()


def test_prepared_binds_take_the_same_lanes():
    """`?` binds from the PR 7 serving path: code-domain compares AND
    dictionary-domain batch skipping both read the bind value."""
    s, cols, _ = _mixed_session(with_nulls=False)
    h = s.prepare("SELECT count(*), sum(v) FROM mx WHERE qty = ?")
    qty, v = cols["qty"], cols["v"]
    for lit in (10.0, 24.5, -3.0, 50.0):
        got = h.execute((lit,)).rows()[0]
        mm = qty == lit
        assert got[0] == int(mm.sum()), (lit, got)
        if got[0]:
            assert got[1] == pytest.approx(float(v[mm].sum()))
    # range over the uint16-widened column via bind
    h2 = s.prepare("SELECT count(*) FROM mx WHERE wide >= ?")
    for lit in (0.0, 1250.0, 99999.0):
        assert h2.execute((lit,)).rows()[0][0] == \
            int((cols["wide"] >= lit).sum())
    reg = global_registry().snapshot()["counters"]
    assert reg.get("code_domain_predicates", 0) > 0
    s.stop()


def test_empty_table_and_empty_batches():
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE emx (a DOUBLE, b BIGINT) USING column")
    on, off = _both(s, "SELECT count(*), sum(a) FROM emx WHERE a < 5")
    _assert_rows_equal(on, off)
    # rows arrive, roll over, then are all deleted: batch exists, all dead
    s.insert_arrays("emx", [np.full(1000, 7.0), np.arange(1000,
                                                          dtype=np.int64)])
    s.catalog.describe("emx").data.force_rollover()
    s.sql("DELETE FROM emx WHERE b >= 0")
    on, off = _both(s, "SELECT count(*), sum(a) FROM emx WHERE a = 7.0")
    _assert_rows_equal(on, off)
    assert on[0][0] == 0
    s.stop()


def test_code_plates_stay_resident_and_small():
    """The capacity lever: a code-bound column's device-cache entry
    holds uint8 codes + a tiny dictionary, not an f64 plate."""
    from snappydata_tpu.storage.device import (build_device_table,
                                               device_cache_bytes_by_table)
    from snappydata_tpu.storage.device_decode import CodePlate

    s, cols, data = _mixed_session(with_nulls=False)
    device_decode.reset_counters()
    data._device_cache.clear()
    dt = build_device_table(data, None, [1])   # qty
    assert isinstance(dt.columns[1], CodePlate)
    assert np.dtype(dt.columns[1].codes.dtype) == np.uint8
    c = device_decode.counters()
    assert c["batches_code_bound"] >= 1
    resident = device_cache_bytes_by_table([("mx", data)])["mx"]
    rows = data.snapshot().total_rows()
    # uint8 codes + valid bitmap ≈ 2 B/row; the decoded f64 plate would
    # be 8 B/row for the column alone
    assert resident < rows * 8, (resident, rows)
    # decoded path for comparison
    _props().set("scan_compressed_domain", "off")
    data._device_cache.clear()
    build_device_table(data, None, [1])
    decoded = device_cache_bytes_by_table([("mx", data)])["mx"]
    assert decoded > resident, (decoded, resident)
    s.stop()


def test_no_implicit_transfers_on_code_domain_predicates():
    """A code-domain predicate query runs end to end without any
    IMPLICIT device↔host transfer: encoded arrays go up explicitly at
    bind, results come home through one explicit device_get — no decoded
    plate ever crosses to host."""
    import jax

    s, cols, _ = _mixed_session(with_nulls=False)
    q = ("SELECT count(*), sum(v) FROM mx "
         "WHERE qty < 24 AND grp >= 1 AND grp <= 4")
    expect = s.sql(q).rows()   # warm: compile + bind outside the guard
    with jax.transfer_guard("disallow"):
        got = s.sql(q).rows()
    _assert_rows_equal(got, expect)
    s.stop()


def test_update_deltas_and_mixed_encodings_fall_back_counted():
    s, cols, data = _mixed_session(with_nulls=False)
    reg = global_registry()
    s.sql("UPDATE mx SET qty = 3.0 WHERE k < 10")
    c0 = dict(reg.snapshot()["counters"])
    on, off = _both(s, "SELECT count(*), sum(qty) FROM mx WHERE qty = 3.0")
    _assert_rows_equal(on, off)
    c1 = reg.snapshot()["counters"]
    assert c1.get("compressed_fallback_deltas", 0) \
        > c0.get("compressed_fallback_deltas", 0)
    # a second batch with different encodings (constant qty -> RLE or
    # value-dict with different profile is fine; force PLAIN by high
    # cardinality) makes the column mixed -> counted fallback
    n2 = 40_000
    rng = np.random.default_rng(5)
    s.insert_arrays("mx", [
        np.arange(n2, dtype=np.int64) + 10_000_000,
        rng.random(n2) * 1e9,                     # qty: now PLAIN here
        rng.random(n2) * 1e9,                     # wide: PLAIN here
        rng.integers(0, 1 << 40, n2),             # grp: PLAIN here
        rng.random(n2) < 0.5,
        np.array(["zz"] * n2, dtype=object),
        rng.random(n2)])
    data.force_rollover()
    c2 = dict(reg.snapshot()["counters"])
    on, off = _both(s, "SELECT count(*) FROM mx WHERE wide >= 1250.0")
    _assert_rows_equal(on, off)
    c3 = reg.snapshot()["counters"]
    assert c3.get("compressed_fallback_mixed_encoding", 0) \
        > c2.get("compressed_fallback_mixed_encoding", 0)
    s.stop()


def test_knob_off_and_join_relations_decode():
    s, cols, data = _mixed_session(with_nulls=False)
    reg = global_registry()
    _props().set("scan_compressed_domain", "off")
    c0 = dict(reg.snapshot()["counters"])
    data._device_cache.clear()
    s.sql("SELECT count(*) FROM mx WHERE qty < 10")
    c1 = dict(reg.snapshot()["counters"])
    assert c1.get("compressed_fallback_disabled", 0) \
        > c0.get("compressed_fallback_disabled", 0)
    _props().set("scan_compressed_domain", "auto")
    # join relations bind decoded (cached build artifacts read flat
    # layouts): counted, and values still exact
    s.sql("CREATE TABLE dim (grp BIGINT, label STRING) USING column")
    s.insert_arrays("dim", [np.arange(6, dtype=np.int64),
                            np.array([f"g{i}" for i in range(6)],
                                     dtype=object)])
    got = s.sql("SELECT d.label, count(*) FROM mx m JOIN dim d "
                "ON m.grp = d.grp GROUP BY d.label ORDER BY d.label").rows()
    grp = cols["grp"]
    for label, cnt in got:
        g = int(label[1:])
        assert cnt == int((grp == g).sum()), (label, cnt)
    c2 = reg.snapshot()["counters"]
    assert c2.get("compressed_fallback_join_key", 0) > 0
    s.stop()


def test_static_key_respecializes_without_cache_flush():
    """Flipping the knob must re-specialize (different STATIC key), not
    serve a stale trace — and must not clear the plan cache."""
    s, cols, _ = _mixed_session(with_nulls=False)
    reg = global_registry()
    q = "SELECT count(*) FROM mx WHERE qty < 24"
    _props().set("scan_compressed_domain", "auto")
    r1 = s.sql(q).rows()[0][0]
    c0 = reg.snapshot()["counters"].get("plan_cache_evictions", 0)
    _props().set("scan_compressed_domain", "off")
    r2 = s.sql(q).rows()[0][0]
    _props().set("scan_compressed_domain", "auto")
    r3 = s.sql(q).rows()[0][0]
    assert r1 == r2 == r3 == int((cols["qty"] < 24).sum())
    c1 = reg.snapshot()["counters"].get("plan_cache_evictions", 0)
    assert c1 == c0, "knob flip must not evict plans"
    s.stop()


def test_rle_run_arithmetic_matches_expansion():
    """O(runs) filter/count/sum arithmetic == the expanded O(rows)
    answer: mask runs, multiply values by run lengths."""
    import jax.numpy as jnp

    from snappydata_tpu.storage.device_decode import (
        RlePlate, rle_cmp_mask, rle_masked_sum_count, rle_run_lengths,
        rle_values)

    rng = np.random.default_rng(3)
    vals = np.array([[5.0, 2.0, 9.0, 9.0], [1.0, 1.0, 1.0, 1.0]])
    ends = np.array([[10, 25, 40, 40], [7, 7, 7, 7]])  # padded runs
    plate = RlePlate(jnp.asarray(vals), jnp.asarray(ends))
    cap = 64
    expanded = np.asarray(rle_values(plate, cap))
    # run lengths: padded runs are zero-length
    lens = np.asarray(rle_run_lengths(plate.ends))
    assert lens.tolist() == [[10, 15, 15, 0], [7, 0, 0, 0]]
    run_mask = np.asarray(vals) >= 5.0
    total, count = rle_masked_sum_count(plate, jnp.asarray(run_mask))
    exp_cnt, exp_sum = 0, 0.0
    for b in range(2):
        n_real = int(ends[b, -1])
        rowvals = expanded[b, :n_real]
        m = rowvals >= 5.0
        exp_cnt += int(m.sum())
        exp_sum += float(rowvals[m].sum())
    assert int(count) == exp_cnt
    assert float(total) == pytest.approx(exp_sum)
    # per-run predicate + expansion == expanded predicate
    mask_rows = np.asarray(rle_cmp_mask(
        lambda v, lit: v >= lit, plate, jnp.asarray(5.0), cap))
    assert (mask_rows == (expanded >= 5.0)).all()


def test_fused_pallas_kernels_match_engine(tmp_path):
    """The fused decode+filter+aggregate kernels (interpret mode on
    CPU) against the engine's answers on a small TPC-H load — the
    Q6 and Q1 shapes the bench lane times."""
    import jax

    from snappydata_tpu.ops.pallas_group import grouped_code_reduce
    from snappydata_tpu.ops.pallas_reduce import fused_code_filter_sum
    from snappydata_tpu.storage.device import build_device_table
    from snappydata_tpu.storage.device_decode import CodePlate
    from snappydata_tpu.utils import tpch

    saved = _props().column_batch_rows
    _props().column_batch_rows = 1 << 14
    try:
        s = SnappySession(catalog=Catalog())
        tpch.load_tpch(s, sf=0.02, seed=11)
        data = s.catalog.lookup_table("lineitem").data
        data.force_rollover()   # tail rows leave the row buffer
        QTY, PRICE, DISC, TAX, RF, LS, SHIP = 4, 5, 6, 7, 8, 9, 10
        dt = build_device_table(data, None,
                                [QTY, PRICE, DISC, TAX, RF, LS, SHIP])
        qp, dp, tp = dt.columns[QTY], dt.columns[DISC], dt.columns[TAX]
        assert isinstance(qp, CodePlate) and isinstance(dp, CodePlate)
        B = int(dt.valid.shape[0])

        def thresh(ci, lit, side):
            dom, sizes = dt.dict_domains[ci]
            out = np.zeros(B, dtype=np.int32)
            for i in range(B):
                sz = int(sizes[i])
                out[i] = np.searchsorted(dom[i, :sz], lit, side) \
                    if sz else 0
            return out

        days = tpch._days
        total, count = fused_code_filter_sum(
            qp.codes, dp.codes, dt.columns[SHIP], dt.columns[PRICE],
            dt.valid, dp.dicts,
            thresh(QTY, 24.0, "left"),
            thresh(DISC, 0.05, "left"),
            thresh(DISC, 0.07, "right") - 1,
            days("1994-01-01"), days("1995-01-01"))
        exp_cnt = s.sql(
            "SELECT count(*) FROM lineitem "
            "WHERE l_shipdate >= DATE '1994-01-01' "
            "AND l_shipdate < DATE '1995-01-01' "
            "AND l_discount BETWEEN 0.05 AND 0.07 "
            "AND l_quantity < 24").rows()[0][0]
        exp_rev = s.sql(tpch.Q6).rows()[0][0]
        assert int(count) == int(exp_cnt)
        assert float(total) == pytest.approx(exp_rev, rel=5e-5)

        rf, ls = dt.columns[RF], dt.columns[LS]
        rfd, lsd = dt.dictionaries[RF], dt.dictionaries[LS]
        nls = len(lsd)
        G = len(rfd) * nls
        gidx = rf * nls + ls
        mask = dt.valid & (dt.columns[SHIP] <= days("1998-12-01") - 90)
        qdom, _ = dt.dict_domains[QTY]
        ddom, _ = dt.dict_domains[DISC]
        tdom, _ = dt.dict_domains[TAX]
        outs = jax.block_until_ready(grouped_code_reduce(
            gidx, mask,
            [("count",),
             ("sum", None, [(qp.codes, qdom)]),
             ("sum", dt.columns[PRICE], []),
             ("sum", dt.columns[PRICE], [(dp.codes, 1.0 - ddom)]),
             ("sum", dt.columns[PRICE], [(dp.codes, 1.0 - ddom),
                                         (tp.codes, 1.0 + tdom)])],
            G))
        engine = {(r[0], r[1]): r for r in s.sql(tpch.Q1).rows()}
        matched = 0
        for g in range(G):
            key = (str(rfd[g // nls]), str(lsd[g % nls]))
            cnt = int(outs[0][g])
            if key not in engine:
                assert cnt == 0, (key, cnt)
                continue
            matched += 1
            row = engine[key]
            assert cnt == int(row[9]), (key, cnt, row[9])
            for got, exp in ((float(outs[1][g]), row[2]),
                             (float(outs[2][g]), row[3]),
                             (float(outs[3][g]), row[4]),
                             (float(outs[4][g]), row[5])):
                assert got == pytest.approx(exp, rel=5e-5), (key, got, exp)
        assert matched == len(engine)
        s.stop()
    finally:
        _props().column_batch_rows = saved


def test_scan_snapshot_and_rest_surface():
    import json
    import urllib.request

    s, cols, _ = _mixed_session(with_nulls=False)
    s.sql("SELECT count(*) FROM mx WHERE qty < 24")
    from snappydata_tpu.observability.stats_service import (encoding_mix,
                                                            scan_snapshot)

    snap = scan_snapshot(s.catalog)
    assert snap["scan_compressed_domain"] == "auto"
    assert snap["code_domain_predicates"] > 0
    assert snap["batches_code_bound"] > 0
    assert "compressed_fallback_reasons" in snap
    mx = snap["tables"]["mx"]
    assert mx["encoding_mix"].get("VALUE_DICT", 0) >= 2
    assert mx["at_rest_bytes"] < mx["decoded_bytes"]
    assert mx["resident_bytes_per_row"] is not None
    mix = encoding_mix(s.catalog)["mx"]
    assert mix["at_rest_ratio"] < 1.0
    # REST endpoint carries the same block
    from snappydata_tpu.cluster.rest import RestService
    from snappydata_tpu.observability.stats_service import \
        TableStatsService

    srv = RestService(s, TableStatsService(s.catalog), port=0).start()
    try:
        with urllib.request.urlopen(
                f"http://{srv.host}:{srv.port}/status/api/v1/scan",
                timeout=10) as resp:
            body = json.loads(resp.read())
        assert body["code_domain_predicates"] > 0
        assert "tables" in body and "mx" in body["tables"]
        with urllib.request.urlopen(
                f"http://{srv.host}:{srv.port}/dashboard",
                timeout=10) as resp:
            html = resp.read().decode()
        assert "Scan &amp; decode" in html
    finally:
        srv.stop()
    s.stop()


def test_bench_check_guards_compressed_axes():
    import bench

    base = {"value": 1e6, "detail": {
        "load_s": 10,
        "device_decode": {"batches_device_decoded": 5},
        "compressed": {"code_domain_predicates": 9,
                       "resident_bytes_per_row": 10.0}}}
    good = {"value": 1e6, "detail": {
        "load_s": 10,
        "device_decode": {"batches_device_decoded": 7},
        "compressed": {"code_domain_predicates": 4,
                       "resident_bytes_per_row": 11.0}}}
    assert bench.check_regression(good, base) == []
    dead = {"value": 1e6, "detail": {
        "load_s": 10,
        "device_decode": {"batches_device_decoded": 0},
        "compressed": {"code_domain_predicates": 0,
                       "resident_bytes_per_row": 10.0}}}
    fails = bench.check_regression(dead, base)
    assert any("batches_device_decoded" in f for f in fails)
    assert any("code_domain_predicates" in f for f in fails)
    fat = {"value": 1e6, "detail": {
        "load_s": 10,
        "device_decode": {"batches_device_decoded": 5},
        "compressed": {"code_domain_predicates": 9,
                       "resident_bytes_per_row": 40.0}}}
    assert any("resident_bytes_per_row" in f
               for f in bench.check_regression(fat, base))
    # records predating the section stay comparable (no spurious fails)
    old = {"value": 1e6, "detail": {"load_s": 10}}
    assert bench.check_regression(old, base) == []
