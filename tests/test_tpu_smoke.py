"""Real-accelerator smoke: one query compiled + executed + value-asserted
on the machine's actual (non-CPU) backend, in a SUBPROCESS (the test
suite itself forces a CPU mesh at import). Skips FAST and explicitly
when no accelerator is reachable — TPU-only regressions (f32
accumulation, scatter cliffs) surface in the round record instead of
only in the headline bench (round-1 gap: nothing in the test tier ever
touched the chip)."""

import json
import os
import subprocess
import sys

import pytest

_SMOKE = r"""
import json, sys
import numpy as np
import jax
devs = jax.devices()
if devs[0].platform == "cpu":
    print(json.dumps({"skip": "no accelerator (cpu backend)"}))
    sys.exit(0)
from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog
s = SnappySession(catalog=Catalog())
s.sql("CREATE TABLE sm (g BIGINT, v DOUBLE) USING column")
s.insert_arrays("sm", [np.arange(4096, dtype=np.int64) % 8,
                       np.ones(4096)])
rows = s.sql("SELECT g, count(*), sum(v) FROM sm GROUP BY g ORDER BY g"
             ).rows()
ok = ([r[0] for r in rows] == list(range(8))
      and all(r[1] == 512 and abs(r[2] - 512.0) < 1e-3 for r in rows))
print(json.dumps({"platform": devs[0].platform, "ok": ok,
                  "rows": [[int(r[0]), int(r[1]), float(r[2])]
                           for r in rows]}))
sys.exit(0 if ok else 1)
"""


def test_accelerator_smoke():
    timeout = float(os.environ.get("SNAPPY_TPU_SMOKE_TIMEOUT", "90"))
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _SMOKE], capture_output=True,
            text=True, timeout=timeout,
            env={k: v for k, v in os.environ.items()
                 if k not in ("JAX_PLATFORMS",)})
    except subprocess.TimeoutExpired:
        pytest.skip(f"accelerator backend init exceeded {timeout}s "
                    f"(relay down) — smoke skipped, not failed")
    if proc.returncode != 0 or not proc.stdout.strip():
        pytest.skip("accelerator unavailable: "
                    f"{(proc.stderr or '').strip()[-300:]}")
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    if "skip" in out:
        pytest.skip(out["skip"])
    assert out["ok"], out
