"""Column/row storage format (TPU equivalent of the reference `encoders/` project).

Physical layout is designed for XLA, not for JVM Unsafe (contrast
encoders/.../encoding/ColumnEncoding.scala:37-53): fixed row-capacity
column plates so every batch shares one compiled kernel shape; null bitmaps
Arrow-packed on host, expanded to masks on device; dictionary/RLE encodings
decodable on device with static output shapes
(`jnp.repeat(..., total_repeat_length)` / gather).
"""

from snappydata_tpu.storage.encoding import (  # noqa: F401
    Encoding, EncodedColumn, ColumnStats, encode_column, decode_to_numpy,
)
from snappydata_tpu.storage.batch import ColumnBatch  # noqa: F401
from snappydata_tpu.storage.table_store import (  # noqa: F401
    ColumnTableData, RowBuffer, Manifest, BatchView,
)
