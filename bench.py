"""Headline benchmark: TPC-H Q1 + Q6 scan+aggregate throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline context (BASELINE.md): the reference's headline claim is the
quickstart scan+group-by over a 100M-row column table at 16-20x a Spark
2.1.1 cached DataFrame on a laptop-class JVM (docs/quickstart/
performance_apache_spark.md:2-6). No absolute rows/sec is published
in-repo; we peg the baseline at 66M rows/s (100M rows in ~1.5s, the
midpoint implied by that scenario) and report vs_baseline against it.

Scale via SNAPPY_BENCH_SF (default 16.0 → 96M lineitem rows, matching the
reference's 100M-row quickstart scenario; ~2.7GB of touched columns in
HBM, ~2min load through the native ingest path).

Round-1 result on one v5e chip: 1.02B rows/s geomean (Q1 827M, Q6 1.25B),
vs_baseline 15.4.
"""

import json
import os
import sys
import time

import numpy as np


def _probe_backend(timeout_s: float, attempts: int):
    """Verify the accelerator backend ONCE, up front, in a SUBPROCESS —
    never lazily mid-ingest (round-1 failure mode: the axon TPU relay went
    'Unavailable' ~2min into the load and a per-query backend probe crashed
    the run; a sick relay can also HANG backend init >300s while holding
    jax's global backend lock, which would poison this process too).
    Returns the platform name, or None if the accelerator is unreachable."""
    import subprocess

    code = ("import jax, json, jax.numpy as jnp; d = jax.devices(); "
            "jax.device_get(jnp.arange(4) + 1); "
            "print(json.dumps({'platform': d[0].platform, 'n': len(d)}))")
    for attempt in range(1, attempts + 1):
        try:
            proc = subprocess.run([sys.executable, "-c", code],
                                  capture_output=True, timeout=timeout_s,
                                  text=True)
        except subprocess.TimeoutExpired:
            print(f"bench: backend probe attempt {attempt}/{attempts} hung "
                  f">{timeout_s}s (accelerator relay down?)",
                  file=sys.stderr, flush=True)
            continue
        if proc.returncode == 0 and proc.stdout.strip():
            info = json.loads(proc.stdout.strip().splitlines()[-1])
            print(f"bench: backend ready — {info['n']}x {info['platform']}",
                  file=sys.stderr, flush=True)
            return info["platform"]
        print(f"bench: backend probe attempt {attempt}/{attempts} failed: "
              f"{(proc.stderr or '').strip()[-400:]}",
              file=sys.stderr, flush=True)
        time.sleep(min(10.0, 2.0 * attempt))
    return None


def main() -> None:
    repeats = int(os.environ.get("SNAPPY_BENCH_REPEATS", "5"))

    platform = _probe_backend(
        timeout_s=float(os.environ.get("SNAPPY_BENCH_INIT_TIMEOUT", "120")),
        attempts=int(os.environ.get("SNAPPY_BENCH_INIT_ATTEMPTS", "3")))
    tpu_unreachable = platform is None
    if tpu_unreachable:
        # The record must still be green and honest: run on CPU, say so.
        print("bench: WARNING — accelerator unreachable; falling back to "
              "CPU (result will carry tpu_unreachable=true)",
              file=sys.stderr, flush=True)
        import jax

        jax.config.update("jax_platforms", "cpu")
        platform = "cpu"
    sf_default = "4.0" if platform == "cpu" else "16.0"
    sf = float(os.environ.get("SNAPPY_BENCH_SF", sf_default))

    from snappydata_tpu import SnappySession, config
    from snappydata_tpu.catalog import Catalog
    from snappydata_tpu.utils import tpch

    # pin the dtype policy NOW so nothing re-queries backend state mid-run
    config.global_properties().decimal_as_float64 = platform == "cpu"

    # TPU smoke: one small query compiled + executed + VALUE-ASSERTED on
    # the real backend before the big load, so numeric regressions surface
    # here with a clear message instead of as a wrong headline number
    smoke = SnappySession(catalog=Catalog())
    smoke.sql("CREATE TABLE smoke (g BIGINT, v DOUBLE) USING column")
    smoke.insert_arrays("smoke", [
        np.arange(1000, dtype=np.int64) % 4,
        np.arange(1000, dtype=np.float64)])
    row = smoke.sql("SELECT g, count(*), sum(v) FROM smoke GROUP BY g "
                    "ORDER BY g").rows()
    assert [r[0] for r in row] == [0, 1, 2, 3], row
    assert all(r[1] == 250 for r in row), row
    exp = [float(sum(range(g, 1000, 4))) for g in range(4)]
    for r, e in zip(row, exp):
        assert abs(r[2] - e) <= 1e-6 * e, (r, e)
    print(f"bench: {platform} smoke OK (grouped agg value-asserted)",
          file=sys.stderr, flush=True)

    s = SnappySession(catalog=Catalog())
    t0 = time.time()
    tpch.load_tpch(s, sf=sf, seed=17)
    load_s = time.time() - t0
    n_rows = s.catalog.lookup_table("lineitem").data.snapshot().total_rows()

    timings = {}
    for name, q in (("q1", tpch.Q1), ("q6", tpch.Q6)):
        s.sql(q)  # compile + first run
        best = float("inf")
        for _ in range(repeats):
            t0 = time.time()
            s.sql(q)
            best = min(best, time.time() - t0)
        timings[name] = best

    rows_per_s = {k: n_rows / v for k, v in timings.items()}
    geomean = float(np.sqrt(rows_per_s["q1"] * rows_per_s["q6"]))
    baseline = 66e6  # see module docstring
    print(json.dumps({
        "metric": "rows/sec scanned+aggregated (TPC-H Q1/Q6 geomean, "
                  f"{n_rows}-row column table)",
        "value": round(geomean, 1),
        "unit": "rows/s",
        "vs_baseline": round(geomean / baseline, 3),
        "detail": {
            "platform": platform,
            "tpu_unreachable": tpu_unreachable,
            "sf": sf,
            "rows": n_rows,
            "load_s": round(load_s, 2),
            "q1_s": round(timings["q1"], 4),
            "q6_s": round(timings["q6"], 4),
            "q1_rows_per_s": round(rows_per_s["q1"], 1),
            "q6_rows_per_s": round(rows_per_s["q6"], 1),
        },
    }))


if __name__ == "__main__":
    main()
