"""Closed-form stratified error estimation — the "A" in AQP.

Implements the reference's High-level Accuracy Contract surface
(docs/sde/hac_contracts.md:38-82; hook surface
core/src/main/scala/org/apache/spark/sql/SnappyContextFunctions.scala:42-85):

* projection error functions `absolute_error(alias)`,
  `relative_error(alias)`, `lower_bound(alias)`, `upper_bound(alias)`
  for SUM / AVG / COUNT aggregates;
* the `WITH ERROR <frac> [CONFIDENCE <p>] [BEHAVIOR <b>]` clause with
  behaviors do_nothing / local_omit / strict / run_on_full_table /
  partial_run_on_base_table;
* `sample_`-aliased aggregates returning TRUE sample-table answers.

Estimator: classic stratified-SRS closed forms. The sample keeps, per
stratum h (one QCS combination), n_h rows of the N_h observed, each with
weight w_h = N_h / n_h. For an aggregate over x with a WHERE/GROUP
qualification, let y = x·1(row qualifies) and (m, Σx, Σx²) be the
qualifying-row moments within the stratum. Then

    T̂(sum)  = Σ_h w_h·Σx                         (Horvitz-Thompson)
    Var(T̂)  = Σ_h n_h·w_h·(w_h−1)·s²_h,  s²_h = (Σx² − (Σx)²/n_h)/(n_h−1)

(the n_h·w_h·(w_h−1) factor is N_h²·(1−n_h/N_h)/n_h rewritten — the
finite-population-corrected SRS variance). COUNT is the same with the
0/1 qualification indicator; AVG = S/C uses the delta-method ratio
variance (Var S − 2R·Cov(S,C) + R²·Var C)/C² with the per-stratum
covariance Cov_h = n_h·w_h·(w_h−1)·(Σx − Σx·m/n_h)/(n_h−1).

TPU-first layout: the per-(group, stratum) moment reduction is a regular
engine aggregate — ONE compiled XLA program over the sample's device
plates; only the tiny strata-merge (#groups × #strata rows) runs
host-side in numpy.
"""

from __future__ import annotations

import dataclasses
import math
from statistics import NormalDist
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from snappydata_tpu import types as T
from snappydata_tpu.engine.result import Result
from snappydata_tpu.sql import ast

ERROR_FUNCS = ("absolute_error", "relative_error", "lower_bound",
               "upper_bound")
_ESTIMABLE = ("sum", "avg", "count")


@dataclasses.dataclass
class _ExecCtx:
    """How the estimation phases execute. Single-node: one session, one
    piece per phase. Distributed: each phase fans to every data server
    and returns one piece per shard — per-server reservoirs are valid
    strata of the GLOBAL population, so the combine simply namespaces
    stratum ids by shard index (the HT/variance algebra is unchanged).
    """
    catalog: object
    run_phases: object    # [plans] -> List[List[Result]] per shard —
                          # ALL phases per shard execute in one call so
                          # shard indices stay aligned across failover
    run_exact: object     # plan -> Result (exact, full data)
    refresh: object       # () -> None


class AQPUnsupported(ValueError):
    """Query shape outside the AQP error-estimation scope (the reference
    limits error functions to SUM/AVG/COUNT over a sampled FROM — the
    same scope applies here)."""


class HACViolation(RuntimeError):
    """BEHAVIOR strict: an output row missed the accuracy contract."""


def query_has_error_surface(stmt: ast.Query) -> bool:
    """True when the statement needs the AQP error path: a WITH ERROR
    clause or any error function in the select list."""
    if stmt.with_error is not None:
        return True
    for node in _walk_plan(stmt.plan):
        for e in ast.plan_exprs(node):
            for x in ast.walk(e):
                if isinstance(x, ast.Func) and x.name in ERROR_FUNCS:
                    return True
    return False


def _walk_plan(p):
    yield p
    for k in p.children():
        yield from _walk_plan(k)


@dataclasses.dataclass
class _Item:
    """One select-list output column."""
    kind: str                    # group | agg | errfunc
    name: str                    # output column name
    expr: ast.Expr = None
    agg_name: str = ""           # sum/avg/count/min/max (kind=agg)
    arg: Optional[ast.Expr] = None
    sample_true: bool = False    # `sample_` alias: unscaled sample answer
    err_kind: str = ""           # absolute_error/... (kind=errfunc)
    target: int = -1             # index of the agg item it refers to
    group_idx: int = -1          # (kind=group)


def _unwrap_aggregate(stmt: ast.Query):
    """Peel Sort/Limit/HAVING and validate the supported shape →
    (aggregate, outer_orders, limit_n, having). HAVING applies
    POST-HOC on the ESTIMATES (docs/sde: predicates over aggregate
    estimates filter the estimated groups)."""
    outer_orders = None
    limit_n = None
    having = None
    node = stmt.plan
    while isinstance(node, (ast.Sort, ast.Limit)):
        if isinstance(node, ast.Sort):
            outer_orders = node.orders
        else:
            limit_n = node.n
        node = node.children()[0]
    if isinstance(node, ast.Filter) and isinstance(node.child,
                                                   ast.Aggregate):
        having = node.condition
        node = node.child
    if not isinstance(node, ast.Aggregate):
        raise AQPUnsupported(
            "error estimation applies to plain aggregate queries "
            "(SUM/AVG/COUNT [GROUP BY ...]) over a sampled table")
    return node, outer_orders, limit_n, having


def execute_error_query(session, stmt: ast.Query, user_params=()):
    """Entry: run `stmt` with error estimation / HAC enforcement."""
    agg, outer_orders, limit_n, having = _unwrap_aggregate(stmt)
    user_params = tuple(user_params)

    ctx = _ExecCtx(
        catalog=session.catalog,
        run_phases=lambda ps: [[session._run_query(p, user_params)
                                for p in ps]],
        run_exact=lambda p: session._run_query(p, user_params),
        refresh=session._refresh_samples)
    return _execute_with_ctx(ctx, stmt, agg, outer_orders, limit_n,
                             having)


def execute_error_query_distributed(ds, stmt: ast.Query):
    """Cluster entry: the phase aggregates fan to every data server —
    one piece per shard, BOTH phases in a single per-server call so a
    mid-estimation failover can't pair one shard's moments with another
    shard's stratum totals (review finding); exact re-runs go through
    the normal distributed query path."""
    from snappydata_tpu.cluster.distributed import _arrow_to_result

    agg, outer_orders, limit_n, having = _unwrap_aggregate(stmt)

    def run_phases(ps):
        fns = [ds._partial_exec(p) for p in ps]

        def both(srv):
            return [fn(srv) for fn in fns]

        return [[_arrow_to_result(t, ds.planner) for t in piece]
                for piece in ds._fan(both)]

    ctx = _ExecCtx(catalog=ds.planner.catalog,
                   run_phases=run_phases,
                   run_exact=lambda p: ds._query(p),
                   refresh=lambda: None)   # servers refresh in-query
    return _execute_with_ctx(ctx, stmt, agg, outer_orders, limit_n,
                             having)


def _execute_with_ctx(ctx: _ExecCtx, stmt: ast.Query,
                      agg: ast.Aggregate, outer_orders, limit_n,
                      having=None):
    if agg.grouping_sets:
        return _execute_grouping_sets(ctx, stmt, agg, outer_orders,
                                      limit_n, having)
    clause = stmt.with_error
    samples: Dict[str, List[str]] = {}
    for info in ctx.catalog.list_tables():
        if info.provider == "sample" and info.base_table:
            samples.setdefault(info.base_table.lower(),
                               []).append(info.name)

    items, agg_items = _classify_select(agg)

    sampled_name = _find_sampled_relation(agg.child, samples)
    if sampled_name is None:
        # contract: on the base table the error functions answer 0 and
        # the bounds NULL (docs/sde/hac_contracts.md:62-64)
        exact = _run_exact(ctx, agg)
        rows = _exact_to_rows(exact, items, agg_items)
        if having is not None:
            rows = _filter_having(rows, having, items, agg_items)
        return _finalize(rows, items, exact, outer_orders, limit_n,
                         z=0.0)

    ctx.refresh()
    sample_rel = _select_sample(ctx, agg, having,
                                samples[sampled_name])

    conf = clause.confidence if clause is not None else 0.95
    z = NormalDist().inv_cdf(0.5 + conf / 2.0)

    est = _estimate(ctx, agg, items, agg_items, sampled_name,
                    sample_rel, z)

    if having is not None:
        # POST-HOC on the estimates, BEFORE behavior enforcement:
        # strict/rerun behaviors must judge only the OUTPUT groups —
        # a group HAVING excludes cannot violate the error contract
        # (review finding)
        est.rows = _filter_having(est.rows, having, items, agg_items)

    if clause is not None and clause.error < 1.0:
        est = _apply_behavior(ctx, est, clause, agg, items, agg_items)

    rows = est.rows
    if having is not None:
        # re-filter ONLY rows rebuilt from the exact answer
        # (run_on_full_table repopulates unfiltered, and exact values
        # may move a group across the HAVING boundary); estimate rows
        # already passed the pre-behavior filter — local_omit may have
        # NULLed their aggregates since, and an omitted row must stay
        # in the output with NULLs, not vanish (review finding)
        exact_rows = [r for r in rows if r.get("from_base")]
        kept_exact = _filter_having(exact_rows, having, items, agg_items)
        dropped = {id(r) for r in exact_rows} - {id(r)
                                                 for r in kept_exact}
        rows = [r for r in rows if id(r) not in dropped]
    return _finalize(rows, items, est.proto, outer_orders, limit_n,
                     z=est.z)


def _execute_grouping_sets(ctx: _ExecCtx, stmt: ast.Query,
                           agg: ast.Aggregate, outer_orders, limit_n,
                           having):
    """WITH ERROR over ROLLUP / CUBE / GROUPING SETS: one estimation
    per grouping set — the same per-set expansion the exact engine's
    analyzer performs (_expand_grouping_sets) — with absent keys
    NULL-padded, then the union sorted/limited once. Error bounds are
    per-variant, exactly as if each set ran as its own query."""
    from snappydata_tpu.sql.analyzer import _expr_name

    pieces: List[Tuple[Result, List[int]]] = []
    dtypes_of: Dict[int, T.DataType] = {}
    for sset in agg.grouping_sets:
        keep = set(sset)

        def absent_idx(e):
            b = e.child if isinstance(e, ast.Alias) else e
            for gi, g in enumerate(agg.group_exprs):
                if b == g and gi not in keep:
                    return gi
            return None

        kept_pos = [i for i, e in enumerate(agg.agg_exprs)
                    if absent_idx(e) is None]

        def repl(e):
            """Absent group refs read NULL — including INSIDE kept
            aggregates: count(carrier) in the () variant must count
            NULLs (i.e. zero), exactly like the exact analyzer's
            expansion (review finding). This runs PRE-analysis on raw
            exprs (the exact path's _expand_grouping runs on resolved
            plans with typed Cast(NULL) — here the engine's untyped
            NULL literal lowers fine and _filter_having evaluates it),
            which is why the two expansions can't share code."""
            for gi, g in enumerate(agg.group_exprs):
                if e == g and gi not in keep:
                    return ast.Lit(None)
            return e.map_children(repl)

        v_agg = dataclasses.replace(
            agg, grouping_sets=None,
            group_exprs=tuple(agg.group_exprs[i] for i in sset),
            agg_exprs=tuple(repl(agg.agg_exprs[i]) for i in kept_pos))
        v_having = repl(having) if having is not None else None
        res = _execute_with_ctx(ctx, stmt, v_agg, None, None, v_having)
        pieces.append((res, kept_pos))
        for ci, p in enumerate(kept_pos):
            dtypes_of.setdefault(p, res.dtypes[ci])

    arity = len(agg.agg_exprs)
    names = [_expr_name(e) for e in agg.agg_exprs]
    dtypes = [dtypes_of.get(i, T.STRING) for i in range(arity)]
    cols: List[List[np.ndarray]] = [[] for _ in range(arity)]
    nulls: List[List[np.ndarray]] = [[] for _ in range(arity)]
    for res, kept_pos in pieces:
        nrows = res.num_rows
        kept = dict(zip(kept_pos, range(len(kept_pos))))
        for i in range(arity):
            ci = kept.get(i)
            if ci is None:  # absent key: all-NULL pad
                dt = dtypes[i]
                fill = np.array([""] * nrows, dtype=object) \
                    if dt.name == "string" \
                    else np.zeros(nrows, dtype=dt.np_dtype)
                cols[i].append(fill)
                nulls[i].append(np.ones(nrows, dtype=bool))
            else:
                cols[i].append(np.asarray(res.columns[ci]))
                nulls[i].append(np.asarray(res.nulls[ci])
                                if res.nulls[ci] is not None
                                else np.zeros(nrows, dtype=bool))
    out_cols, out_nulls = [], []
    for i in range(arity):
        parts = cols[i]
        if len({p.dtype for p in parts}) > 1:
            parts = [p.astype(object) for p in parts]
        out_cols.append(np.concatenate(parts) if parts
                        else np.zeros(0, dtype=dtypes[i].np_dtype))
        nm = np.concatenate(nulls[i]) if nulls[i] \
            else np.zeros(0, dtype=bool)
        out_nulls.append(nm if nm.any() else None)
    res = Result(names, out_cols, out_nulls, dtypes)
    if outer_orders:
        res = _host_sort(res, outer_orders)
    if limit_n is not None:
        res = Result(res.names, [c[:limit_n] for c in res.columns],
                     [m[:limit_n] if m is not None else None
                      for m in res.nulls], res.dtypes)
    return res


def _filter_having(rows: List[dict], having: ast.Expr, items,
                   agg_items) -> List[dict]:
    """HAVING over the per-group records: aggregate references resolve
    to their ESTIMATED values (post-hoc filtering on estimates), group
    references to the group key. Shapes beyond literals / select-list
    references / and-or-not / comparisons / + - * / raise
    AQPUnsupported with a clear message."""

    def norm(e):
        """Case-normalized copy: identifier resolution is
        case-insensitive engine-wide, so HAVING sum(DELAY) must match
        select-list sum(delay) (review finding)."""
        if isinstance(e, ast.Col) and e.name:
            return dataclasses.replace(e, name=e.name.lower())
        return e.map_children(norm)

    agg_norm = [norm(a.expr) for a in agg_items]
    grp_norm = [(it, norm(it.expr)) for it in items
                if it.kind == "group"]

    def value(e, rec):
        if isinstance(e, ast.Alias):
            return value(e.child, rec)
        en = norm(e)
        for j, an in enumerate(agg_norm):
            if en == an:
                return rec["est"][j]
        for it, gn in grp_norm:
            if en == gn:
                return rec["groups"][it.group_idx]
        if isinstance(e, ast.Col):
            want = (e.name or "").lower()
            for j, a in enumerate(agg_items):
                if a.name.lower() == want:
                    return rec["est"][j]
            for it in items:
                if it.kind == "group" and it.name.lower() == want:
                    return rec["groups"][it.group_idx]
        if isinstance(e, ast.Lit):
            return e.value
        if isinstance(e, ast.UnaryOp):
            v = value(e.child, rec)
            if v is None:
                return None
            return (not v) if e.op == "not" else -v
        if isinstance(e, ast.BinOp):
            lv = value(e.left, rec)
            rv = value(e.right, rec)
            if e.op == "and":
                return bool(lv) and bool(rv)
            if e.op == "or":
                return bool(lv) or bool(rv)
            if lv is None or rv is None:
                return None
            ops = {"=": lambda a, b: a == b, "!=": lambda a, b: a != b,
                   "<": lambda a, b: a < b, "<=": lambda a, b: a <= b,
                   ">": lambda a, b: a > b, ">=": lambda a, b: a >= b,
                   "+": lambda a, b: a + b, "-": lambda a, b: a - b,
                   "*": lambda a, b: a * b,
                   "/": lambda a, b: a / b if b else None}
            if e.op in ops:
                return ops[e.op](lv, rv)
        raise AQPUnsupported(
            f"HAVING with error estimation supports comparisons over "
            f"the select-list aggregates/groups and literals; got {e}")

    return [rec for rec in rows if bool(value(having, rec))]


def _select_sample(ctx: _ExecCtx, agg: ast.Aggregate, having,
                   candidates: List[str]) -> str:
    """Best-QCS-match sample selection (docs/sde/sample_selection.md):
    query QCS = columns in WHERE / GROUP BY / HAVING; exact QCS match >
    sample-QCS-superset > most-matching-columns subset, ties broken by
    the largest sample."""
    if len(candidates) == 1:
        return candidates[0]

    qcols = set()

    def collect(e):
        if isinstance(e, ast.Func) and e.name in ast.AGG_FUNCS:
            # aggregate MEASURE columns are not grouping columns: a
            # HAVING sum(v) > 10 must not pull v into the query QCS
            # and mis-rank a measure-stratified sample (review finding)
            return
        if isinstance(e, ast.Col) and e.name:
            qcols.add(e.name.lower())
        for c in e.children():
            collect(c)

    for g in agg.group_exprs:
        collect(g)
    if having is not None:
        collect(having)
    for node in _walk_plan(agg.child):
        if isinstance(node, ast.Filter):
            collect(node.condition)

    scored = []
    for pos, name in enumerate(candidates):
        info = ctx.catalog.lookup_table(name)
        opts = dict(getattr(info, "options", {}) or {})
        opts.update(getattr(info, "sample_options", {}) or {})
        qcs = {c.strip().lower()
               for c in (opts.get("qcs", "") or "").split(",")
               if c.strip()}
        try:
            size = info.data.snapshot().total_rows()
        except Exception:
            size = 0
        if qcs and qcs == qcols:
            rank = 3
        elif qcs and qcs >= qcols and qcols:
            rank = 2
        elif qcs and qcs <= qcols:
            rank = 1
        else:
            rank = 0
        overlap = len(qcs & qcols)
        # -pos: stable preference for the earliest candidate on full ties
        scored.append(((rank, overlap, size, -pos), name))
    return max(scored)[1]


# ---------------------------------------------------------------------
# select-list classification
# ---------------------------------------------------------------------

def _classify_select(agg: ast.Aggregate):
    groups = list(agg.group_exprs)
    items: List[_Item] = []
    agg_items: List[_Item] = []
    out_names: List[str] = []
    for e in agg.agg_exprs:
        alias = None
        inner = e
        if isinstance(inner, ast.Alias):
            alias, inner = inner.name, inner.child
        gi = next((i for i, g in enumerate(groups) if g == inner), -1)
        if gi >= 0:
            nm = alias or (inner.name if isinstance(inner, ast.Col)
                           else f"_c{len(items)}")
            items.append(_Item("group", nm, expr=inner, group_idx=gi))
            out_names.append(nm.lower())
            continue
        if isinstance(inner, ast.Func) and inner.name in ERROR_FUNCS:
            if len(inner.args) != 1 or not isinstance(inner.args[0],
                                                      ast.Col):
                raise AQPUnsupported(
                    f"{inner.name} expects the alias of an aggregate "
                    f"in this select list")
            nm = alias or f"{inner.name}({inner.args[0].name})"
            items.append(_Item("errfunc", nm, err_kind=inner.name,
                               expr=inner.args[0]))
            out_names.append(nm.lower())
            continue
        fn = inner
        if isinstance(fn, ast.Func) and fn.name == "count_distinct":
            raise AQPUnsupported(
                "count(DISTINCT) has no closed-form sample estimator; "
                "run the exact query")
        if not (isinstance(fn, ast.Func)
                and fn.name in ("sum", "avg", "count", "min", "max")):
            raise AQPUnsupported(
                "error estimation supports bare SUM/AVG/COUNT/MIN/MAX "
                f"aggregates in the select list, got {e}")
        arg = fn.args[0] if fn.args else None
        nm = alias or f"{fn.name}"
        it = _Item("agg", nm, expr=inner, agg_name=fn.name, arg=arg,
                   sample_true=bool(alias)
                   and alias.lower().startswith("sample_"))
        items.append(it)
        agg_items.append(it)
        out_names.append(nm.lower())

    # resolve error-function targets against the aggregate aliases
    for it in items:
        if it.kind != "errfunc":
            continue
        want = it.expr.name.lower()
        tgt = next((j for j, a in enumerate(agg_items)
                    if a.name.lower() == want), None)
        if tgt is None:
            raise AQPUnsupported(
                f"{it.err_kind}({want}): no aggregate aliased {want!r} "
                f"in this select list")
        if agg_items[tgt].agg_name not in _ESTIMABLE:
            raise AQPUnsupported(
                f"{it.err_kind} applies to SUM/AVG/COUNT aggregates, "
                f"not {agg_items[tgt].agg_name}")
        it.target = tgt
    return items, agg_items


def _find_sampled_relation(p: ast.Plan, samples) -> Optional[str]:
    for node in _walk_plan(p):
        if isinstance(node, ast.UnresolvedRelation) and \
                node.name.lower() in samples:
            return node.name.lower()
    return None


def _swap_to_sample(p: ast.Plan, base: str, sample: str) -> ast.Plan:
    def rec(n):
        if isinstance(n, ast.UnresolvedRelation) and \
                n.name.lower() == base:
            return ast.UnresolvedRelation(
                sample, alias=n.alias or n.name.split(".")[-1])
        kids = n.children()
        if not kids:
            return n
        if isinstance(n, (ast.Join, ast.Union, ast.SetOp)):
            return dataclasses.replace(n, left=rec(n.left),
                                       right=rec(n.right))
        return dataclasses.replace(n, child=rec(kids[0]))

    return rec(p)


# ---------------------------------------------------------------------
# estimation
# ---------------------------------------------------------------------

@dataclasses.dataclass
class _Estimate:
    """Per-group estimation state: rows maps group-key tuple → dict with
    'groups' (values), per-agg 'est', 'var', and the z scale."""
    rows: List[dict]
    z: float
    proto: Result           # phase-A result (dtype source for groups)


def _estimate(ctx: _ExecCtx, agg, items, agg_items, base_name,
              sample_rel, z) -> _Estimate:
    from snappydata_tpu.aqp.sampling import (RESERVOIR_WEIGHT_COLUMN,
                                             STRATUM_ID_COLUMN)

    groups = list(agg.group_exprs)
    child = _swap_to_sample(agg.child, base_name, sample_rel)

    # ---- phase A: per-(group, stratum) moments — one engine program
    a_exprs: List[ast.Expr] = [ast.Alias(g, f"__g{i}")
                               for i, g in enumerate(groups)]
    a_exprs.append(ast.Alias(ast.Col(STRATUM_ID_COLUMN), "__h"))
    slots: List[Tuple[str, Optional[ast.Expr]]] = []

    def slot(kind, arg) -> int:
        for i, (k, a) in enumerate(slots):
            if k == kind and a == arg:
                return i
        slots.append((kind, arg))
        return len(slots) - 1

    for it in agg_items:
        if it.agg_name == "count" and it.arg is None:
            it._slot = slot("cstar", None)
        elif it.agg_name in ("sum", "avg", "count"):
            it._slot = slot("moments", it.arg)
        else:                      # min / max
            it._slot = slot(it.agg_name, it.arg)

    for si, (kind, arg) in enumerate(slots):
        if kind == "cstar":
            a_exprs.append(ast.Alias(ast.Func("count", ()), f"__s{si}_m"))
        elif kind == "moments":
            a_exprs.append(ast.Alias(ast.Func("count", (arg,)),
                                     f"__s{si}_m"))
            a_exprs.append(ast.Alias(ast.Func("sum", (arg,)),
                                     f"__s{si}_sx"))
            a_exprs.append(ast.Alias(
                ast.Func("sum", (ast.BinOp("*", arg, arg),)),
                f"__s{si}_sxx"))
        else:
            a_exprs.append(ast.Alias(ast.Func(kind, (arg,)),
                                     f"__s{si}_{kind}"))

    phase_a = ast.Aggregate(
        child, tuple(groups) + (ast.Col(STRATUM_ID_COLUMN),),
        tuple(a_exprs))

    # ---- phase B: UNFILTERED per-stratum totals (n_h, w_h) — the
    # stratum size is a property of the sample, not of the query
    phase_b = ast.Aggregate(
        ast.UnresolvedRelation(sample_rel),
        (ast.Col(STRATUM_ID_COLUMN),),
        (ast.Alias(ast.Col(STRATUM_ID_COLUMN), "__h"),
         ast.Alias(ast.Func("count", ()), "__n"),
         ast.Alias(ast.Func("max", (ast.Col(RESERVOIR_WEIGHT_COLUMN),)),
                   "__w")))
    shards = ctx.run_phases([phase_a, phase_b])
    pieces_a = [pa for pa, _pb in shards]
    pieces_b = [pb for _pa, pb in shards]
    # stratum identity is (shard index, local stratum id): per-shard
    # reservoirs assign ids independently, and the same QCS value on two
    # shards IS two strata of the global population
    n_of: Dict[tuple, float] = {}
    w_of: Dict[tuple, float] = {}
    for pi, res_b in enumerate(pieces_b):
        for h, n, w in res_b.rows():
            n_of[(pi, int(h))] = float(n)
            w_of[(pi, int(h))] = float(w)

    # ---- host combine: strata → per-group estimate + variance
    ng = len(groups)
    out_rows = _combine_strata(pieces_a, agg_items, n_of, w_of, ng)

    # a grouped query with an empty sample yields no rows; a GLOBAL
    # aggregate still answers one row (count 0 / sum NULL)
    if not out_rows and ng == 0:
        rec = {"groups": (), "est": [], "var": [], "violate": [],
               "from_base": False}
        for it in agg_items:
            rec["est"].append(0.0 if it.agg_name == "count" else None)
            rec["var"].append(0.0 if it.agg_name == "count" else None)
        out_rows.append(rec)

    est = _Estimate(out_rows, z, pieces_a[0])
    return est


def _combine_strata(pieces_a, agg_items, n_of, w_of, ng: int
                    ) -> List[dict]:
    """VECTORIZED strata -> per-group combine: one numpy group-by over
    the concatenated phase-A pieces. The previous per-group Python
    loop re-walked every (group, stratum) row per aggregate item —
    fine at 4 groups, pathological at 100k (round-4 verdict task 7).
    The math is identical: stratified Horvitz-Thompson totals with
    per-stratum sample variances, avg as a self-normalized ratio."""
    nrows = sum(r.num_rows for r in pieces_a)
    if nrows == 0:
        out_rows: List[dict] = []
    else:
        col_idx = {nm.lower(): i
                   for i, nm in enumerate(pieces_a[0].names)}
        pi_arr = np.concatenate([np.full(r.num_rows, pi, dtype=np.int64)
                                 for pi, r in enumerate(pieces_a)])

        def num_col(i, fill=0.0):
            parts = []
            for r in pieces_a:
                c = np.asarray(r.columns[i], dtype=np.float64)
                if r.nulls[i] is not None:
                    c = np.where(np.asarray(r.nulls[i]), fill, c)
                parts.append(c)
            return np.concatenate(parts)

        # group identity: per-key factorize (nulls get their own code),
        # then a row-wise unique over the stacked codes
        key_vals: List[np.ndarray] = []   # python-object values for output
        codes = []
        for ki in range(ng):
            vparts, nparts = [], []
            for r in pieces_a:
                c = np.asarray(r.columns[ki])
                nm = np.asarray(r.nulls[ki]) if r.nulls[ki] is not None \
                    else np.zeros(r.num_rows, dtype=bool)
                if c.dtype == object:
                    nm = nm | np.array([v is None for v in c])
                vparts.append(c)
                nparts.append(nm)
            vals = np.concatenate(vparts)
            nulls = np.concatenate(nparts)
            if vals.dtype == object:
                safe = vals.copy()
                safe[nulls] = ""
            else:
                safe = np.where(nulls, 0, vals)
            uq, inv = np.unique(safe, return_inverse=True)
            inv = inv.astype(np.int64) + 1
            inv[nulls] = 0
            codes.append(inv)
            out_vals = vals.astype(object)
            out_vals[nulls] = None
            key_vals.append(out_vals)
        if ng:
            stacked = np.stack(codes, axis=1)
            _uq, first_idx, ginv = np.unique(
                stacked, axis=0, return_index=True, return_inverse=True)
            ginv = ginv.reshape(-1)
            G = len(first_idx)
        else:
            ginv = np.zeros(nrows, dtype=np.int64)
            first_idx = np.array([0])
            G = 1

        # per-row stratum parameters via the (piece, h) lookup
        h_arr = num_col(col_idx["__h"]).astype(np.int64)
        upair, pinv = np.unique(np.stack([pi_arr, h_arr], axis=1),
                                axis=0, return_inverse=True)
        n_u = np.array([n_of[(int(p), int(h))] for p, h in upair])
        w_u = np.array([w_of[(int(p), int(h))] for p, h in upair])
        n_h = n_u[pinv.reshape(-1)]
        w_h = w_u[pinv.reshape(-1)]
        fpc = n_h * w_h * (w_h - 1.0)
        multi = n_h > 1
        inv_n1 = np.where(multi, 1.0 / np.maximum(n_h - 1.0, 1.0), 0.0)

        def by_group(weights):
            return np.bincount(ginv, weights=weights, minlength=G)

        est_cols: List[np.ndarray] = []
        var_cols: List[np.ndarray] = []
        for it in agg_items:
            si = it._slot
            if it.agg_name in ("min", "max"):
                ci = col_idx[f"__s{si}_{it.agg_name}"]
                if any(np.asarray(r.columns[ci]).dtype == object
                       for r in pieces_a):
                    # non-numeric (string) min/max: python per-row pass
                    # for this item only
                    acc: Dict[int, object] = {}
                    pos = 0
                    for r in pieces_a:
                        cvals = r.columns[ci]
                        cnull = r.nulls[ci]
                        for j in range(r.num_rows):
                            if (cnull is not None and cnull[j]) \
                                    or cvals[j] is None:
                                pos += 1
                                continue
                            g = int(ginv[pos])
                            cur = acc.get(g)
                            v = cvals[j]
                            if cur is None or (
                                    v < cur if it.agg_name == "min"
                                    else v > cur):
                                acc[g] = v
                            pos += 1
                    est_cols.append(np.array(
                        [acc.get(g) for g in range(G)], dtype=object))
                    var_cols.append(np.full(G, np.nan))
                    continue
                filler = np.inf if it.agg_name == "min" else -np.inf
                vals = num_col(ci, fill=filler)
                out = np.full(G, filler)
                if it.agg_name == "min":
                    np.minimum.at(out, ginv, vals)
                else:
                    np.maximum.at(out, ginv, vals)
                # emptiness is tracked via the null masks, NOT by
                # checking for the +/-inf sentinel — a column really
                # containing inf must answer inf, not NULL (review
                # finding)
                nn_parts = []
                for r in pieces_a:
                    nm = r.nulls[ci]
                    nn_parts.append(
                        ~np.asarray(nm) if nm is not None
                        else np.ones(r.num_rows, dtype=bool))
                seen = by_group(
                    np.concatenate(nn_parts).astype(np.float64)) > 0
                est_cols.append(np.where(seen, out, np.nan))
                var_cols.append(np.full(G, np.nan))
                continue
            m = num_col(col_idx[f"__s{si}_m"])
            if it.agg_name == "count" and it.arg is None:
                sx = sxx = m
            else:
                sx = num_col(col_idx[f"__s{si}_sx"])
                sxx = num_col(col_idx[f"__s{si}_sxx"])
            true_cnt = by_group(m)
            true_sum = by_group(sx)
            S = by_group(w_h * sx)
            C = by_group(w_h * m)
            s2x = np.maximum(0.0, (sxx - sx * sx / n_h) * inv_n1)
            s2c = np.maximum(0.0, (m - m * m / n_h) * inv_n1)
            sxy = (sx - sx * m / n_h) * inv_n1
            var_s = by_group(np.where(multi, fpc * s2x, 0.0))
            var_c = by_group(np.where(multi, fpc * s2c, 0.0))
            cov_sc = by_group(np.where(multi, fpc * sxy, 0.0))
            if it.agg_name == "sum":
                est, var = (true_sum, np.zeros(G)) if it.sample_true \
                    else (S, var_s)
            elif it.agg_name == "count":
                est, var = (true_cnt, np.zeros(G)) if it.sample_true \
                    else (C, var_c)
            else:  # avg — self-normalized ratio
                with np.errstate(divide="ignore", invalid="ignore"):
                    if it.sample_true:
                        est = np.where(true_cnt > 0,
                                       true_sum / np.maximum(true_cnt, 1),
                                       np.nan)
                        var = np.where(true_cnt > 0, 0.0, np.nan)
                    else:
                        R = np.where(C > 0, S / np.maximum(C, 1e-300),
                                     np.nan)
                        var = np.maximum(
                            0.0, var_s - 2.0 * R * cov_sc
                            + R * R * var_c) / np.maximum(C, 1e-300) ** 2
                        var = np.where(C > 0, var, np.nan)
                        est = R
            est_cols.append(est)
            var_cols.append(var)

        out_rows = []
        for g in range(G):
            rec = {"groups": tuple(key_vals[k][first_idx[g]]
                                   for k in range(ng)),
                   "est": [], "var": [], "violate": [],
                   "from_base": False}
            for it, e_arr, v_arr in zip(agg_items, est_cols, var_cols):
                ev = e_arr[g]
                vv = v_arr[g]
                if e_arr.dtype == object:   # string min/max
                    rec["est"].append(ev)
                else:
                    rec["est"].append(None if np.isnan(ev) else float(ev))
                rec["var"].append(None if np.isnan(vv) else float(vv))
            out_rows.append(rec)
    return out_rows


# ---------------------------------------------------------------------
# behavior enforcement
# ---------------------------------------------------------------------

def _rel_error(est_v, var_v, z) -> Optional[float]:
    if est_v is None or var_v is None:
        return None
    abs_err = z * math.sqrt(var_v)
    if est_v == 0:
        return math.inf if abs_err > 0 else 0.0
    return abs_err / abs(est_v)


def _apply_behavior(ctx: _ExecCtx, est: _Estimate, clause, agg, items,
                    agg_items) -> _Estimate:
    violating: List[int] = []
    for ri, rec in enumerate(est.rows):
        bad = []
        for ai, it in enumerate(agg_items):
            if it.agg_name not in _ESTIMABLE or it.sample_true:
                bad.append(False)
                continue
            rel = _rel_error(rec["est"][ai], rec["var"][ai], est.z)
            bad.append(rel is not None and rel > clause.error)
        rec["violate"] = bad
        if any(bad):
            violating.append(ri)

    if not violating or clause.behavior == "do_nothing":
        return est
    if clause.behavior == "strict":
        raise HACViolation(
            f"{len(violating)} output row(s) exceed relative error "
            f"{clause.error} at confidence {clause.confidence}")
    if clause.behavior == "local_omit":
        for ri in violating:
            rec = est.rows[ri]
            for ai, bad in enumerate(rec["violate"]):
                if bad:
                    rec["est"][ai] = None
                    rec["var"][ai] = None
        return est

    # run_on_full_table / partial_run_on_base_table
    groups = list(agg.group_exprs)
    partial = clause.behavior == "partial_run_on_base_table" and groups \
        and all(isinstance(g, ast.Col) for g in groups)
    exact_agg = agg
    if partial:
        keys = [est.rows[ri]["groups"] for ri in violating]
        disj = []
        for kt in keys:
            conj = []
            for g, v in zip(groups, kt):
                if v is None:
                    conj.append(ast.IsNull(g))
                else:
                    conj.append(ast.BinOp("=", g, ast.Lit(v)))
            c = conj[0]
            for x in conj[1:]:
                c = ast.BinOp("and", c, x)
            disj.append(c)
        cond = disj[0]
        for x in disj[1:]:
            cond = ast.BinOp("or", cond, x)
        exact_agg = dataclasses.replace(
            agg, child=ast.Filter(agg.child, cond))
    exact = _run_exact(ctx, exact_agg)
    exact_rows = _exact_to_rows(exact, items, agg_items)

    ng = len(groups)
    if not partial:
        for r in exact_rows:
            r["from_base"] = True
        return _Estimate(exact_rows, est.z, est.proto)
    by_key = {tuple(r["groups"][:ng]): r for r in exact_rows}
    for ri in violating:
        key = tuple(est.rows[ri]["groups"])
        hit = by_key.get(key)
        if hit is not None:
            hit["from_base"] = True
            est.rows[ri] = hit
    return est


def _run_exact(ctx: _ExecCtx, agg: ast.Aggregate) -> Result:
    """The original aggregate with error functions stripped, on base."""
    keep = tuple(e for e in agg.agg_exprs
                 if not (isinstance(
                     e.child if isinstance(e, ast.Alias) else e, ast.Func)
                     and (e.child if isinstance(e, ast.Alias) else e).name
                     in ERROR_FUNCS))
    return ctx.run_exact(dataclasses.replace(agg, agg_exprs=keep))


def _exact_to_rows(exact: Result, items, agg_items) -> List[dict]:
    """Map an exact engine result into estimation rows: errors 0,
    bounds NULL (docs/sde/hac_contracts.md:62-64). `groups` is indexed
    by GROUP BY position (matching _estimate's phase-A tuples), NOT by
    select-list order — SELECT b, a ... GROUP BY a, b would otherwise
    swap columns in _finalize and break the partial-run key match."""
    rows = exact.rows()
    out: List[dict] = []
    nongroup = [it for it in items if it.kind != "errfunc"]
    ng = max((it.group_idx + 1 for it in items if it.kind == "group"),
             default=0)
    for row in rows:
        gvals: List = [None] * ng
        evals = []
        for it, v in zip(nongroup, row):
            if it.kind == "group":
                gvals[it.group_idx] = v
            else:
                evals.append(v)
        out.append({"groups": tuple(gvals), "est": evals,
                    "var": [0.0 if it.agg_name in _ESTIMABLE else None
                            for it in agg_items],
                    "violate": [], "from_base": True})
    return out


# ---------------------------------------------------------------------
# result assembly
# ---------------------------------------------------------------------

def _finalize(rows: List[dict], items, proto: Result, orders,
              limit_n, z: float) -> Result:
    names: List[str] = [it.name for it in items]
    cols: List[list] = [[] for _ in range(len(items))]

    for rec in rows:
        for ci, it in enumerate(items):
            if it.kind == "group":
                cols[ci].append(rec["groups"][it.group_idx]
                                if it.group_idx < len(rec["groups"])
                                else None)
            elif it.kind == "agg":
                v = rec["est"][_agg_index(items, it)]
                if it.agg_name == "count" and v is not None:
                    v = int(round(v))
                cols[ci].append(v)
            else:  # errfunc
                t = it.target
                cols[ci].append(_error_value(
                    it.err_kind, rec["est"][t], rec["var"][t], rec, z))

    # dtypes: groups from the phase-A/exact proto result, aggregates by
    # kind (count → LONG, others → DOUBLE), error funcs DOUBLE
    dtypes: List[T.DataType] = []
    proto_types = {nm.lower(): dt
                   for nm, dt in zip(proto.names, proto.dtypes)}
    for i, it in enumerate(items):
        if it.kind == "group":
            dtypes.append(proto_types.get(f"__g{it.group_idx}")
                          or proto_types.get(it.name.lower()) or T.STRING)
        elif it.kind == "agg":
            dtypes.append(T.LONG if it.agg_name == "count" else T.DOUBLE)
        else:
            dtypes.append(T.DOUBLE)

    np_cols: List[np.ndarray] = []
    nulls: List[Optional[np.ndarray]] = []
    for ci, dt in enumerate(dtypes):
        vals = cols[ci]
        mask = np.array([v is None for v in vals], dtype=bool)
        if dt.name == "string":
            np_cols.append(np.array(
                ["" if v is None else v for v in vals], dtype=object))
        else:
            npdt = dt.np_dtype
            np_cols.append(np.array(
                [0 if v is None else v for v in vals], dtype=npdt))
        nulls.append(mask if mask.any() else None)

    res = Result(names, np_cols, nulls, dtypes)
    if orders:
        res = _host_sort(res, orders)
    if limit_n is not None:
        res = Result(res.names,
                     [c[:limit_n] for c in res.columns],
                     [m[:limit_n] if m is not None else None
                      for m in res.nulls], res.dtypes)
    return res


def _agg_index(items, it) -> int:
    k = 0
    for other in items:
        if other.kind == "agg":
            if other is it:
                return k
            k += 1
    raise AssertionError


def _error_value(kind: str, est_v, var_v, rec, z: float):
    """absolute/relative error and bounds for one cell. Base-table rows
    answer 0 / 0 / NULL / NULL per the contract."""
    if rec.get("from_base"):
        return 0.0 if kind in ("absolute_error", "relative_error") \
            else None
    if est_v is None or var_v is None:
        return None
    abs_err = z * math.sqrt(var_v)
    if kind == "absolute_error":
        return abs_err
    if kind == "relative_error":
        return abs_err / abs(est_v) if est_v != 0 else (
            0.0 if abs_err == 0 else None)
    if kind == "lower_bound":
        return est_v - abs_err
    return est_v + abs_err


def _host_sort(res: Result, orders) -> Result:
    """ORDER BY over output columns (names or group aliases) — the
    result is #groups rows, so a host lexsort is exact and cheap."""
    keys = []
    lower = [n.lower() for n in res.names]
    for expr, asc, nulls_first in reversed(list(orders)):
        if not isinstance(expr, ast.Col):
            raise AQPUnsupported(
                "ORDER BY with error estimation supports plain output "
                "columns")
        try:
            ci = lower.index(expr.name.lower())
        except ValueError:
            raise AQPUnsupported(
                f"ORDER BY column {expr.name!r} is not in the output")
        col = res.columns[ci]
        mask = res.nulls[ci]
        if col.dtype == object:
            ranks = np.argsort(
                np.argsort([("" if v is None else str(v)) for v in col]))
            key = ranks.astype(np.float64)
        else:
            key = col.astype(np.float64)
        if mask is not None:
            nf = nulls_first if nulls_first is not None else asc
            key = key.copy()
            key[mask] = -np.inf if nf else np.inf
        keys.append(key if asc else -key)
    order = np.lexsort(keys) if keys else np.arange(res.num_rows)
    return Result(res.names,
                  [c[order] for c in res.columns],
                  [m[order] if m is not None else None
                   for m in res.nulls],
                  res.dtypes)
