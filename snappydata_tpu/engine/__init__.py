"""Jitted physical operators + plan cache.

The TPU replacement for the reference's whole-stage Janino codegen
(ColumnTableScan / SnappyHashAggregateExec / HashJoinExec): a resolved
logical plan compiles to ONE traced JAX function over stacked column-batch
arrays — scan, filter, project, hash join (sort+searchsorted) and
aggregation (segment ops) all fuse inside a single XLA executable, cached
against the tokenized plan + table shape signature.
"""

from snappydata_tpu.engine.executor import Executor  # noqa: F401
from snappydata_tpu.engine.result import Result  # noqa: F401
