"""Metrics: counters, gauges, timers with a JSON snapshot surface.

Reference equivalents: per-operator SQLMetrics (ColumnTableScan.getMetrics
:115-130 — columnBatchesSeen/Skipped, numRowsBuffer), the Spark
MetricsSystem JSON servlet (docs/monitoring/metrics.md:8 — lead:5050/
metrics/json), and SnappyMetricsSystem's 5s gauge push
(cluster/.../metrics/SnappyMetricsSystem.scala:36-212).
"""

from __future__ import annotations

import json
import threading
import time
from collections import defaultdict
from typing import Callable, Dict, Optional


class Timer:
    __slots__ = ("count", "total_s", "min_s", "max_s")

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.min_s = float("inf")
        self.max_s = 0.0

    def record(self, seconds: float) -> None:
        self.count += 1
        self.total_s += seconds
        self.min_s = min(self.min_s, seconds)
        self.max_s = max(self.max_s, seconds)

    def to_dict(self) -> dict:
        return {
            "count": self.count,
            "total_s": round(self.total_s, 6),
            "mean_s": round(self.total_s / self.count, 6) if self.count else 0,
            "min_s": round(self.min_s, 6) if self.count else 0,
            "max_s": round(self.max_s, 6),
        }


class _TimeCtx:
    __slots__ = ("registry", "name", "t0")

    def __init__(self, registry, name):
        self.registry = registry
        self.name = name

    def __enter__(self):
        self.t0 = time.time()
        return self

    def __exit__(self, *exc):
        self.registry.record_time(self.name, time.time() - self.t0)
        return False


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = defaultdict(int)
        self._gauges: Dict[str, Callable[[], float]] = {}
        self._timers: Dict[str, Timer] = defaultdict(Timer)

    def inc(self, name: str, value: int = 1) -> None:
        with self._lock:
            self._counters[name] += value

    def gauge(self, name: str, fn: Callable[[], float]) -> None:
        with self._lock:
            self._gauges[name] = fn

    def time(self, name: str):
        # one prebuilt context class: defining it per call cost ~20µs of
        # __build_class__ on every timed query (visible on the serving
        # short-query profile)
        return _TimeCtx(self, name)

    def record_time(self, name: str, seconds: float) -> None:
        with self._lock:
            self._timers[name].record(seconds)

    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def snapshot(self) -> dict:
        with self._lock:
            gauges = {}
            for name, fn in self._gauges.items():
                try:
                    gauges[name] = fn()
                except Exception:
                    gauges[name] = None
            return {
                "counters": dict(self._counters),
                "gauges": gauges,
                "timers": {k: t.to_dict() for k, t in self._timers.items()},
                "ts": time.time(),
            }

    def to_json(self) -> str:
        return json.dumps(self.snapshot())

    def to_prometheus(self) -> str:
        """Prometheus text exposition (the modern sink next to the
        reference's JSON/JMX/CSV/Graphite list)."""
        snap = self.snapshot()
        lines = []
        for k, v in snap["counters"].items():
            lines.append(f"snappy_tpu_{_sanitize(k)}_total {v}")
        for k, v in snap["gauges"].items():
            if v is not None:
                lines.append(f"snappy_tpu_{_sanitize(k)} {v}")
        for k, t in snap["timers"].items():
            lines.append(f"snappy_tpu_{_sanitize(k)}_seconds_count "
                         f"{t['count']}")
            lines.append(f"snappy_tpu_{_sanitize(k)}_seconds_sum "
                         f"{t['total_s']}")
        return "\n".join(lines) + "\n"


def _sanitize(name: str) -> str:
    return "".join(c if c.isalnum() else "_" for c in name)


_global = MetricsRegistry()


def global_registry() -> MetricsRegistry:
    return _global
