"""Out-of-core tiered storage (storage/tier.py + storage/prefetch.py):

* CRC-framed host→disk demotion round-trips values (memmap views over
  raw record parts; promote re-reads CRC-verified)
* a corrupted tier record fails LOUDLY at promote (never replays bits)
* the demotion ladder walks HBM→host→disk and queries stay value-exact
  over fully demoted tables (pages fault back, plates rebuild)
* MVCC-pinned epochs are never demoted out from under a live scan
  (counter-asserted — the acceptance criterion)
* the double-buffered tile prefetcher warms windows ahead of the
  consumer, keeps values exact, and restores the ≤1-windowed-entry
  invariant at close
* tier knobs (`tier_device_bytes`) enforce steady-state caps after a
  tiled pass
"""

import numpy as np
import pytest

from snappydata_tpu import SnappySession, config
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.observability.metrics import global_registry
from snappydata_tpu.storage import mvcc, tier

pytestmark = pytest.mark.outofcore


@pytest.fixture
def small_batches():
    props = config.global_properties()
    old = (props.column_batch_rows, props.column_max_delta_rows,
           props.scan_tile_bytes,
           props.tier_device_bytes, props.tier_host_bytes,
           props.tier_prefetch_depth)
    props.column_batch_rows = 256
    props.column_max_delta_rows = 256  # fold deltas into column batches
    yield props
    (props.column_batch_rows, props.column_max_delta_rows,
     props.scan_tile_bytes,
     props.tier_device_bytes, props.tier_host_bytes,
     props.tier_prefetch_depth) = old


def _load(sess, n=4000, seed=7):
    rng = np.random.default_rng(seed)
    sess.sql("CREATE TABLE big (k STRING, v DOUBLE, w BIGINT) USING column")
    k = rng.choice(np.array(["a", "b", "c", "d"], dtype=object), n)
    v = rng.normal(100.0, 10.0, n)
    w = rng.integers(0, 1000, n, dtype=np.int64)
    sess.catalog.describe("big").data.insert_arrays([k, v, w])
    return k, v, w


def _c(name):
    return global_registry().counter(name)


# -- disk tier: framed demotion / promotion --------------------------------

def test_framed_demote_promote_roundtrip(small_batches):
    sess = SnappySession(catalog=Catalog())
    _, v, w = _load(sess, n=1500)
    data = sess.catalog.describe("big").data
    m = data._manifest
    batch = m.views[0].batch
    before = {ci: np.asarray(col.data).copy()
              for ci, col in enumerate(batch.columns)
              if col.data is not None and col.data.dtype != object}
    f0, files0 = _c("tier_demotions_host"), tier.tier_file_bytes()
    freed, nb = tier.demote_batch(batch, "big")
    assert freed > 0
    assert tier.tier_file_bytes() > files0
    assert _c("tier_demotions_host") == f0 + 1
    # the demoted batch reads IDENTICAL values through memmap views
    demoted = 0
    for ci, col in enumerate(nb.columns):
        if ci in before:
            assert isinstance(col.data, np.memmap)
            np.testing.assert_array_equal(np.asarray(col.data), before[ci])
            demoted += 1
    assert demoted > 0
    c0, p0 = _c("tier_crc_verifies"), _c("tier_promotions")
    loaded, rb = tier.promote_batch(nb)
    assert loaded > 0
    assert _c("tier_crc_verifies") > c0 and _c("tier_promotions") == p0 + 1
    for ci, col in enumerate(rb.columns):
        if ci in before:
            assert not isinstance(col.data, np.memmap)
            np.testing.assert_array_equal(np.asarray(col.data), before[ci])


def test_corrupt_tier_record_quarantined_and_healed(small_batches):
    """A corrupted tier record no longer fails the query: promotion's
    CRC catches it, the file is quarantined aside, and the batch is
    REBUILT from the retained pre-demotion epoch — values exact (the
    no-surviving-source case raises the typed TierQuarantinedError;
    see test_self_healing.py)."""
    import os

    sess = SnappySession(catalog=Catalog())
    _load(sess, n=1200)
    data = sess.catalog.describe("big").data
    q = "SELECT count(*), sum(v) FROM big"
    expected = sess.sql(q).rows()
    n0 = tier.demote_host([("big", data)], 1 << 40)
    assert n0 > 0
    col = data._manifest.views[0].batch.columns[1]  # v DOUBLE
    assert isinstance(col.data, np.memmap)
    path = str(col.data.filename)
    with open(path, "r+b") as fh:  # flip one part byte under the CRC
        fh.seek(col.data.offset)
        b = fh.read(1)
        fh.seek(col.data.offset)
        fh.write(bytes([b[0] ^ 0xFF]))
    q0, r0 = _c("tier_quarantined_files"), _c("tier_rebuilds")
    assert tier.promote_table(data) > 0
    assert _c("tier_quarantined_files") == q0 + 1
    assert _c("tier_rebuilds") == r0 + 1
    assert os.path.exists(path + ".quarantined")
    got = sess.sql(q).rows()
    assert int(got[0][0]) == int(expected[0][0])
    assert float(got[0][1]) == pytest.approx(float(expected[0][1]),
                                             rel=1e-9)


# -- the ladder ------------------------------------------------------------

def test_demote_ladder_values_survive(small_batches):
    sess = SnappySession(catalog=Catalog())
    _, v, w = _load(sess)
    q = "SELECT k, count(*), sum(v), min(w) FROM big GROUP BY k ORDER BY k"
    expected = sess.sql(q).rows()
    data = sess.catalog.describe("big").data
    assert data._device_cache, "warm plates expected before demotion"
    d0, h0 = _c("tier_demotions_hbm"), _c("tier_demotions_host")
    n = tier.demote([("big", data)], 1 << 40)
    assert n > 0
    assert _c("tier_demotions_hbm") > d0, "device rung should demote"
    assert _c("tier_demotions_host") > h0, "host rung should demote"
    assert tier.tier_file_bytes() > 0
    # every batch's numeric arrays now live in the disk tier
    assert all(isinstance(vw.batch.columns[1].data, np.memmap)
               for vw in data._manifest.views)
    got = sess.sql(q).rows()  # faults pages back + rebuilds plates
    assert got == expected
    # promote pulls them resident again, CRC-verified
    c0 = _c("tier_crc_verifies")
    assert tier.promote_table(data) > 0
    assert _c("tier_crc_verifies") > c0
    assert sess.sql(q).rows() == expected
    snap = tier.tier_snapshot()
    assert set(snap) == {"device_bytes", "host_pool_bytes",
                         "tier_file_bytes", "quarantined_files",
                         "rebuilds", "rebuild_failures", "read_retries",
                         "pressure_demotions"}


def test_demotion_respects_mvcc_pins(small_batches):
    """A pinned epoch's plates are NEVER demoted out from under a live
    scan — the ladder skips them (counter-asserted) and the pinned read
    stays value-exact after an aggressive demotion."""
    sess = SnappySession(catalog=Catalog())
    _, v, _ = _load(sess, n=2000)
    data = sess.catalog.describe("big").data
    with mvcc.pinned_scope(sess.catalog, ["big"]) as pin:
        expected = sess.sql("SELECT count(*), sum(v) FROM big").rows()[0]
        ver = pin.manifest_for(data).version
        assert any(k[0] == ver for k in data._device_cache), \
            "pinned scan should have warmed plates at its epoch"
        s0 = _c("tier_pinned_skips")
        tier.demote([("big", data)], 1 << 40)
        assert _c("tier_pinned_skips") > s0, \
            "the ladder must COUNT its refusals to demote pinned plates"
        assert any(k[0] == ver for k in data._device_cache), \
            "pinned epoch's plates were demoted out from under the scan"
        got = sess.sql("SELECT count(*), sum(v) FROM big").rows()[0]
        assert int(got[0]) == int(expected[0])
        assert float(got[1]) == pytest.approx(float(expected[1]),
                                              rel=1e-9)


def test_budget_eviction_respects_pins(small_batches):
    """The device-cache byte budget's LRU must ALSO skip pinned epochs
    (it evicts through the same tier contract)."""
    props = small_batches
    old_budget = props.device_cache_bytes
    sess = SnappySession(catalog=Catalog())
    _load(sess, n=2000)
    data = sess.catalog.describe("big").data
    try:
        with mvcc.pinned_scope(sess.catalog, ["big"]) as pin:
            sess.sql("SELECT sum(v) FROM big")
            ver = pin.manifest_for(data).version
            assert any(k[0] == ver for k in data._device_cache)
            # a 1-byte budget wants to evict EVERYTHING on next touch
            props.device_cache_bytes = 1
            sess.sql("SELECT sum(w) FROM big")
            assert any(k[0] == ver for k in data._device_cache), \
                "budget LRU evicted a pinned epoch's plates"
    finally:
        props.device_cache_bytes = old_budget


# -- prefetcher ------------------------------------------------------------

def test_prefetch_values_and_invariant(small_batches):
    sess = SnappySession(catalog=Catalog())
    _load(sess)
    q = ("SELECT k, count(*), sum(v), avg(v), min(w), max(w) "
         "FROM big GROUP BY k ORDER BY k")
    expected = sess.sql(q).rows()
    small_batches.scan_tile_bytes = 2 * 256 * 32
    w0, t0 = _c("prefetch_windows_warmed"), _c("scan_tiles")
    got = sess.sql(q).rows()
    assert _c("scan_tiles") > t0, "expected the tiled path"
    assert _c("prefetch_windows_warmed") > w0, \
        "the background worker should have warmed look-ahead windows"
    assert len(got) == len(expected) == 4
    for e, g in zip(expected, got):
        assert e[0] == g[0] and e[1] == g[1] and e[4] == g[4] \
            and e[5] == g[5]
        assert g[2] == pytest.approx(e[2], rel=1e-9)
        assert g[3] == pytest.approx(e[3], rel=1e-9)
    # the pass must not leave its look-ahead tiles resident
    data = sess.catalog.describe("big").data
    windowed = [k for k in data._device_cache if k[2] is not None]
    assert len(windowed) <= 1, windowed
    from snappydata_tpu.storage.prefetch import keep_windows

    assert not keep_windows(data), "keep-registry must drain at close"


def test_prefetch_disabled_by_knob(small_batches):
    small_batches.tier_prefetch_depth = 0
    sess = SnappySession(catalog=Catalog())
    _load(sess, n=3000)
    q = "SELECT count(*), sum(v) FROM big"
    expected = sess.sql(q).rows()
    small_batches.scan_tile_bytes = 2 * 256 * 32
    w0 = _c("prefetch_windows_warmed")
    assert sess.sql(q).rows() == expected
    assert _c("prefetch_windows_warmed") == w0


def test_prefetch_worker_death_falls_back_inline(small_batches,
                                                 monkeypatch):
    """A worker that dies on its first build must not wedge or corrupt
    the pass — the consumer binds inline and values stay exact."""
    from snappydata_tpu.storage.prefetch import TilePrefetcher

    def boom(self):
        raise RuntimeError("injected prefetch-worker death")

    monkeypatch.setattr(TilePrefetcher, "_loop", boom)
    sess = SnappySession(catalog=Catalog())
    _load(sess, n=3000)
    q = "SELECT k, count(*), sum(v) FROM big GROUP BY k ORDER BY k"
    expected = sess.sql(q).rows()
    small_batches.scan_tile_bytes = 2 * 256 * 32
    e0 = _c("prefetch_errors")
    got = sess.sql(q).rows()
    assert len(got) == len(expected)
    for e, g in zip(expected, got):
        assert g[0] == e[0] and g[1] == e[1]
        assert g[2] == pytest.approx(e[2], rel=1e-9)
    assert _c("prefetch_errors") > e0


# -- steady-state knobs ----------------------------------------------------

def test_tier_device_knob_enforced_after_pass(small_batches):
    sess = SnappySession(catalog=Catalog())
    _load(sess)
    sess.sql("SELECT sum(v) FROM big")   # warm unwindowed plates
    data = sess.catalog.describe("big").data
    assert data._device_cache
    small_batches.tier_device_bytes = 1  # everything is over-cap
    small_batches.scan_tile_bytes = 2 * 256 * 32
    d0 = _c("tier_demotions_hbm")
    sess.sql("SELECT count(*), sum(v) FROM big")
    assert _c("tier_demotions_hbm") > d0, \
        "maybe_demote should walk the HBM rung after the tiled pass"
