"""Aggregate-on-codes (PR 19 tentpole): GROUP BY keys and SUM/AVG/COUNT
inputs consume ENCODED plates directly — dict-encoded group keys map to
group indices by pure code arithmetic (no gather, no decode), dict
measures reduce in dictionary space (bincount the codes, dot the
dictionary), RLE measures reduce in run space (value x run-length).
Every lane is value-asserted against the decoded path
(`agg_on_codes=off`) across op x encoding x NULL group keys x
out-of-dictionary literals x `?` binds x empty batches, on the
single-device, tiled, and mesh execution lanes."""

import numpy as np
import pytest

from snappydata_tpu import SnappySession, config
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.observability.metrics import global_registry


def _props():
    return config.global_properties()


@pytest.fixture(autouse=True)
def _restore_knobs():
    saved = (_props().get("agg_on_codes"),
             _props().get("scan_compressed_domain"))
    yield
    _props().set("agg_on_codes", saved[0])
    _props().set("scan_compressed_domain", saved[1])


def _counters():
    return dict(global_registry().snapshot()["counters"])


def _delta(c0, key):
    return _counters().get(key, 0) - c0.get(key, 0)


def _agg_session(n=20_000, with_nulls=True, seed=23):
    """One table exercising every aggregate lane: g (shuffled low-card
    BIGINT -> VALUE_DICT group key), q (low-card DOUBLE -> VALUE_DICT
    measure), r (sorted low-card DOUBLE -> RUN_LENGTH measure), name
    (STRING dictionary key), v (PLAIN measure)."""
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE ac (k BIGINT, g BIGINT, q DOUBLE, r DOUBLE, "
          "name STRING, v DOUBLE) USING column")
    rng = np.random.default_rng(seed)
    k = np.arange(n, dtype=np.int64)
    g = rng.integers(0, 6, n).astype(np.int64)
    q = rng.choice(np.array([0.5, 1.25, 2.0, 3.75, 8.5]), n)
    r = np.sort(rng.choice(np.array([1.0, 2.0, 5.0, 9.0]), n))
    name = np.array([f"n{i % 7}" for i in range(n)], dtype=object)
    v = rng.random(n) * 1000
    s.insert_arrays("ac", [k, g, q, r, name, v])
    if with_nulls:
        # NULL group keys AND NULL measures ride the row buffer, then
        # roll into a batch with validity masks
        for i in range(8):
            s.sql(f"INSERT INTO ac VALUES ({n + i}, NULL, NULL, NULL, "
                  f"NULL, {float(i)})")
    data = s.catalog.describe("ac").data
    data.force_rollover()
    return s, dict(k=k, g=g, q=q, r=r, name=name, v=v), data


def _both(s, sql, params=None):
    """(code-domain rows, decoded rows) of one query — the equivalence
    harness.  The knob rides the STATIC key: no cache flush between."""
    _props().set("agg_on_codes", "on")
    on = s.sql(sql, params).rows() if params else s.sql(sql).rows()
    _props().set("agg_on_codes", "off")
    off = s.sql(sql, params).rows() if params else s.sql(sql).rows()
    _props().set("agg_on_codes", "auto")
    return on, off


def _assert_rows_equal(a, b):
    assert len(a) == len(b), (a, b)
    for ra, rb in zip(a, b):
        assert len(ra) == len(rb), (ra, rb)
        for x, y in zip(ra, rb):
            if isinstance(x, float) and isinstance(y, float):
                assert x == pytest.approx(y, rel=1e-9, abs=1e-9), (ra, rb)
            else:
                assert x == y, (ra, rb)


def test_grouped_matrix_code_vs_decoded():
    """The core equivalence sweep: every aggregate op x numeric/string/
    NULL-bearing group keys x dict/RLE/plain measures x in- and out-of-
    dictionary filter literals, each value-asserted on == off."""
    s, cols, _ = _agg_session()
    queries = [
        "SELECT g, count(*), sum(q), avg(q), min(q), max(q) FROM ac "
        "GROUP BY g ORDER BY g",
        "SELECT g, sum(v), count(q) FROM ac GROUP BY g ORDER BY g",
        "SELECT name, count(*), sum(q) FROM ac GROUP BY name ORDER BY name",
        "SELECT g, name, sum(q), count(*) FROM ac GROUP BY g, name "
        "ORDER BY g, name",
        "SELECT sum(q), count(q), avg(q) FROM ac",
        "SELECT sum(r), count(r) FROM ac",
        "SELECT sum(r), count(*) FROM ac WHERE r < 5.0",
        "SELECT g, sum(q) FROM ac WHERE q = 1.25 GROUP BY g ORDER BY g",
        # out-of-dictionary literals: equality miss and between-codes edge
        "SELECT g, count(*) FROM ac WHERE q = 24.5 GROUP BY g ORDER BY g",
        "SELECT g, sum(q) FROM ac WHERE q > 2.1 GROUP BY g ORDER BY g",
        "SELECT g, count(*) FROM ac WHERE q IS NULL GROUP BY g ORDER BY g",
        "SELECT g, sum(q) FROM ac WHERE q IS NOT NULL GROUP BY g "
        "ORDER BY g",
        "SELECT count(*), sum(v) FROM ac WHERE g = 3",
    ]
    for qy in queries:
        on, off = _both(s, qy)
        _assert_rows_equal(on, off)
    s.stop()


def test_lane_counters_fire_with_exact_values():
    """All three lane counters fire, and each lane's answer equals the
    decoded answer AND the numpy ground truth."""
    s, cols, _ = _agg_session(with_nulls=False)
    g, q, r = cols["g"], cols["q"], cols["r"]

    c0 = _counters()
    on, off = _both(s, "SELECT g, sum(q), count(*) FROM ac "
                       "GROUP BY g ORDER BY g")
    _assert_rows_equal(on, off)
    assert _delta(c0, "agg_code_domain") > 0, \
        "numeric dict key must take the code-domain group-by lane"
    assert _delta(c0, "agg_dict_space") > 0, \
        "dict measure sum must take the dictionary-space lane"
    for gv, sq, cnt in on:
        m = g == int(gv)
        assert cnt == int(m.sum())
        assert sq == pytest.approx(float(q[m].sum()), rel=1e-9)

    c1 = _counters()
    on, off = _both(s, "SELECT sum(r), count(r) FROM ac WHERE r < 5.0")
    _assert_rows_equal(on, off)
    assert _delta(c1, "agg_rle_runs") > 0, \
        "run-aligned global sum/count must take the run-space lane"
    m = r < 5.0
    assert on[0][0] == pytest.approx(float(r[m].sum()), rel=1e-9)
    assert on[0][1] == int(m.sum())
    s.stop()


def test_misaligned_rle_filter_falls_back_counted():
    """A filter on a DIFFERENT column than the RLE measure breaks the
    run-alignment proof: the lane must decline COUNTED
    (compressed_fallback_rle_agg), never silently, and the decoded
    answer must be exact."""
    s, cols, _ = _agg_session(with_nulls=False)
    _props().set("agg_on_codes", "on")
    c0 = _counters()
    got = s.sql("SELECT sum(r), count(r) FROM ac WHERE v < 500.0").rows()
    assert _delta(c0, "compressed_fallback_rle_agg") > 0, \
        "misaligned run filter must be a counted fallback"
    m = cols["v"] < 500.0
    assert got[0][0] == pytest.approx(float(cols["r"][m].sum()), rel=1e-9)
    assert got[0][1] == int(m.sum())
    s.stop()


def test_prepared_binds_take_the_same_lanes():
    """`?` binds (PR 7 serving path) through the grouped code-domain
    lanes: bound literals translate to codes exactly like inline ones,
    including out-of-dictionary bind values."""
    s, cols, _ = _agg_session(with_nulls=False)
    g, q, v = cols["g"], cols["q"], cols["v"]
    _props().set("agg_on_codes", "on")
    h = s.prepare("SELECT g, count(*), sum(v) FROM ac WHERE q = ? "
                  "GROUP BY g ORDER BY g")
    for lit in (1.25, 24.5, -3.0, 8.5):
        got = h.execute((lit,)).rows()
        mm = q == lit
        exp = sorted(set(g[mm]))
        assert [int(row[0]) for row in got] == [int(x) for x in exp]
        for gv, cnt, sv in got:
            m = mm & (g == int(gv))
            assert cnt == int(m.sum())
            assert sv == pytest.approx(float(v[m].sum()), rel=1e-9)
    s.stop()


def test_null_group_keys_match_decoded():
    """NULL keys form their own group on both paths; a declined key
    domain (NaN rows in the numeric domain scan) degrades to the
    generic hash lane, never a wrong group."""
    s, cols, _ = _agg_session(with_nulls=True)
    on, off = _both(
        s, "SELECT g, count(*), sum(v) FROM ac GROUP BY g ORDER BY g")
    _assert_rows_equal(on, off)
    # the 8 NULL-key rows land in exactly one NULL group
    nulls = [row for row in on if row[0] is None]
    assert len(nulls) == 1 and nulls[0][1] == 8
    s.stop()


def test_empty_table_and_all_deleted_batches():
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE eac (g BIGINT, x DOUBLE) USING column")
    on, off = _both(s, "SELECT g, count(*), sum(x) FROM eac "
                       "GROUP BY g ORDER BY g")
    _assert_rows_equal(on, off)
    assert on == []
    # rows arrive, roll over, then all die: batch exists, zero live rows
    s.insert_arrays("eac", [np.repeat(np.arange(4, dtype=np.int64), 250),
                            np.full(1000, 2.5)])
    s.catalog.describe("eac").data.force_rollover()
    s.sql("DELETE FROM eac WHERE g >= 0")
    on, off = _both(s, "SELECT g, sum(x) FROM eac GROUP BY g ORDER BY g")
    _assert_rows_equal(on, off)
    assert on == []
    s.stop()


def test_tiled_lane_matches_untiled():
    """The tiled scan merges per-tile partials ON DEVICE for numeric
    dict keys (the table-global domain is data-independent, so partial
    group vectors align across tiles)."""
    props = _props()
    old_rows, old_tile = props.column_batch_rows, props.scan_tile_bytes
    props.column_batch_rows = 256
    try:
        s, cols, _ = _agg_session(n=4000, with_nulls=False)
        qy = ("SELECT g, count(*), sum(q), sum(v) FROM ac "
              "GROUP BY g ORDER BY g")
        _props().set("agg_on_codes", "on")
        untiled = s.sql(qy).rows()
        props.scan_tile_bytes = 3 * 256 * 32
        reg = global_registry()
        t0 = reg.counter("scan_tiles")
        tiled = s.sql(qy).rows()
        assert reg.counter("scan_tiles") > t0, "tiled path must engage"
        _assert_rows_equal(tiled, untiled)
        props.scan_tile_bytes = old_tile
        on, off = _both(s, qy)
        _assert_rows_equal(on, off)
        s.stop()
    finally:
        props.column_batch_rows = old_rows
        props.scan_tile_bytes = old_tile


def test_bench_check_guards_code_agg_lane():
    """--check: dead lane counters and a measured (auto) rate below
    SNAPPY_BENCH_CODE_AGG_RATIO x the decode-throughput-law prediction
    both fail; records predating the lane stay comparable."""
    import bench

    ca = {"grouped_rows_per_s_auto": 100.0, "predicted_rows_per_s": 100.0,
          "lane_counters": {"agg_code_domain": 2, "agg_dict_space": 2,
                            "agg_rle_runs": 2}}
    rec = {"value": 1e6, "detail": {
        "load_s": 10,
        "device_decode": {"batches_device_decoded": 5},
        "compressed": {"code_domain_predicates": 9,
                       "resident_bytes_per_row": 10.0,
                       "code_agg": dict(ca)}}}
    assert bench.check_regression(rec, rec) == []
    dead = {"value": 1e6, "detail": {
        "load_s": 10,
        "device_decode": {"batches_device_decoded": 5},
        "compressed": {"code_domain_predicates": 9,
                       "resident_bytes_per_row": 10.0,
                       "code_agg": {**ca, "lane_counters":
                                    {"agg_code_domain": 2,
                                     "agg_dict_space": 0,
                                     "agg_rle_runs": 2}}}}}
    assert any("agg_dict_space" in f
               for f in bench.check_regression(dead, rec))
    slow = {"value": 1e6, "detail": {
        "load_s": 10,
        "device_decode": {"batches_device_decoded": 5},
        "compressed": {"code_domain_predicates": 9,
                       "resident_bytes_per_row": 10.0,
                       "code_agg": {**ca,
                                    "grouped_rows_per_s_auto": 70.0}}}}
    assert any("decode-throughput-law" in f
               for f in bench.check_regression(slow, rec))
    old = {"value": 1e6, "detail": {
        "load_s": 10,
        "device_decode": {"batches_device_decoded": 5},
        "compressed": {"code_domain_predicates": 9,
                       "resident_bytes_per_row": 10.0}}}
    assert bench.check_regression(old, rec) == []


@pytest.mark.mesh
def test_mesh_lane_matches_single_device():
    from snappydata_tpu.parallel import MeshContext, data_mesh

    s, cols, _ = _agg_session(n=16_000, with_nulls=False)
    ctx = MeshContext(data_mesh(8))
    for qy in ("SELECT g, count(*), sum(q), sum(v) FROM ac "
               "GROUP BY g ORDER BY g",
               "SELECT sum(q), count(q) FROM ac WHERE q > 2.1",
               "SELECT name, sum(q) FROM ac GROUP BY name ORDER BY name"):
        _props().set("agg_on_codes", "on")
        single = s.sql(qy).rows()
        with ctx:
            mesh_on = s.sql(qy).rows()
            _props().set("agg_on_codes", "off")
            mesh_off = s.sql(qy).rows()
            _props().set("agg_on_codes", "auto")
        _assert_rows_equal(mesh_on, single)
        _assert_rows_equal(mesh_off, single)
    s.stop()
