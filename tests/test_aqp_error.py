"""AQP error estimation: the HAC contract surface.

Validates against the reference contract (docs/sde/hac_contracts.md:38-82):
error functions absolute_error/relative_error/lower_bound/upper_bound,
WITH ERROR <frac> [CONFIDENCE <p>] [BEHAVIOR <b>], sample_-aliased true
answers, and base-table execution answering 0/NULL. The Monte-Carlo test
is the statistical ground truth: across independently-seeded samples, the
[lower_bound, upper_bound] interval must cover the exact answer at
roughly the stated confidence.
"""

import numpy as np
import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.aqp.error_estimation import AQPUnsupported, HACViolation
from snappydata_tpu.sql import ast
from snappydata_tpu.sql.parser import parse, SQLSyntaxError


def _make_base(s, n=20000, seed=0):
    s.sql("CREATE TABLE airline (carrier STRING, delay DOUBLE, "
          "month_ INT) USING column")
    rng = np.random.default_rng(seed)
    carriers = np.array(["AA", "UA", "DL", "WN"],
                        dtype=object)[rng.integers(0, 4, n)]
    delay = rng.normal(10, 5, n)
    month = rng.integers(1, 13, n).astype(np.int32)
    s.insert_arrays("airline", [carriers, delay, month])
    return carriers, delay, month


@pytest.fixture(scope="module")
def sess():
    s = SnappySession(catalog=Catalog())
    carriers, delay, month = _make_base(s)
    s.sql("CREATE SAMPLE TABLE airline_sample ON airline OPTIONS "
          "(baseTable 'airline', qcs 'carrier', reservoir_size '200')")
    yield s, carriers, delay, month
    s.stop()


# ------------------------------------------------------------------
# parsing
# ------------------------------------------------------------------

def test_with_error_clause_parses():
    q = parse("SELECT sum(x) FROM t WITH ERROR 0.1 CONFIDENCE 0.9 "
              "BEHAVIOR 'local_omit'")
    assert q.with_error.error == pytest.approx(0.1)
    assert q.with_error.confidence == pytest.approx(0.9)
    assert q.with_error.behavior == "local_omit"


def test_with_error_defaults():
    q = parse("SELECT sum(x) FROM t WITH ERROR 0.2")
    assert q.with_error.confidence == pytest.approx(0.95)
    assert q.with_error.behavior == "do_nothing"


def test_with_error_rejects_bad_behavior():
    with pytest.raises(SQLSyntaxError):
        parse("SELECT sum(x) FROM t WITH ERROR 0.1 BEHAVIOR 'explode'")


def test_with_error_rejects_bad_fraction():
    with pytest.raises(SQLSyntaxError):
        parse("SELECT sum(x) FROM t WITH ERROR 1.5")


def test_plain_with_cte_still_parses():
    q = parse("WITH c AS (SELECT 1 AS a) SELECT a FROM c")
    assert q.with_error is None


# ------------------------------------------------------------------
# error functions + estimates
# ------------------------------------------------------------------

def test_error_functions_shape_and_consistency(sess):
    s, carriers, delay, _ = sess
    r = s.sql("SELECT carrier, avg(delay) AS ad, absolute_error(ad) AS ae, "
              "relative_error(ad) AS re, lower_bound(ad) AS lb, "
              "upper_bound(ad) AS ub FROM airline GROUP BY carrier "
              "ORDER BY carrier WITH ERROR 0.5 CONFIDENCE 0.95")
    rows = r.rows()
    assert len(rows) == 4
    assert [row[0] for row in rows] == ["AA", "DL", "UA", "WN"]
    for _, ad, ae, re, lb, ub in rows:
        assert ae > 0
        assert re == pytest.approx(ae / abs(ad))
        assert lb == pytest.approx(ad - ae)
        assert ub == pytest.approx(ad + ae)
        assert lb < ad < ub


def test_count_star_no_filter_is_exact(sess):
    s, carriers, _, _ = sess
    # stratified HT: Σ_h N_h is known exactly — zero-width interval
    r = s.sql("SELECT count(*) AS c, absolute_error(c) AS ae, "
              "lower_bound(c) AS lb, upper_bound(c) AS ub "
              "FROM airline WITH ERROR 0.5")
    c, ae, lb, ub = r.rows()[0]
    assert c == len(carriers)
    assert ae == pytest.approx(0.0)
    assert lb == pytest.approx(c) and ub == pytest.approx(c)


def test_filtered_estimates_near_exact(sess):
    s, carriers, delay, month = sess
    r = s.sql("SELECT count(*) AS c, sum(delay) AS sd, "
              "lower_bound(c) AS clb, upper_bound(c) AS cub, "
              "lower_bound(sd) AS slb, upper_bound(sd) AS sub "
              "FROM airline WHERE month_ <= 6 WITH ERROR 0.5")
    c, sd, clb, cub, slb, sub = r.rows()[0]
    m = month <= 6
    assert clb < cub and slb < sub
    # generous 3-sigma-ish sanity: the exact answer is inside a widened
    # interval (the Monte-Carlo test below checks the calibration)
    width_c, width_s = (cub - clb) / 2, (sub - slb) / 2
    assert abs(c - m.sum()) < 4 * max(width_c, 1)
    assert abs(sd - delay[m].sum()) < 4 * max(width_s, 1)


def test_sample_alias_returns_true_sample_answer(sess):
    s, carriers, _, _ = sess
    r = s.sql("SELECT count(*) AS c, count(*) AS sample_c FROM airline "
              "WITH ERROR 0.5")
    c, sample_c = r.rows()[0]
    n_sample = s.sql("SELECT count(*) FROM airline_sample").rows()[0][0]
    assert sample_c == n_sample
    assert c == len(carriers)
    assert sample_c < c


def test_unsampled_table_runs_exact_with_zero_errors(sess):
    s, _, _, _ = sess
    s.sql("DROP TABLE IF EXISTS plain_t")
    s.sql("CREATE TABLE plain_t (v DOUBLE) USING column")
    s.sql("INSERT INTO plain_t VALUES (1.0), (2.0), (3.0)")
    r = s.sql("SELECT sum(v) AS sv, absolute_error(sv) AS ae, "
              "relative_error(sv) AS re, lower_bound(sv) AS lb "
              "FROM plain_t WITH ERROR 0.1")
    sv, ae, re, lb = r.rows()[0]
    assert sv == pytest.approx(6.0)
    assert ae == 0.0 and re == 0.0
    assert lb is None   # bounds are NULL on base-table execution


def test_unsupported_shapes_raise(sess):
    s, _, _, _ = sess
    with pytest.raises(AQPUnsupported):
        s.sql("SELECT count(DISTINCT month_) FROM airline WITH ERROR 0.1")
    with pytest.raises(AQPUnsupported):
        s.sql("SELECT absolute_error(nope) FROM airline WITH ERROR 0.1")
    with pytest.raises(AQPUnsupported):
        # HAVING shapes beyond select-list refs/comparisons still raise
        s.sql("SELECT carrier, sum(delay) AS sd FROM airline "
              "GROUP BY carrier HAVING length(carrier) > 1 "
              "WITH ERROR 0.1")


def test_having_filters_on_estimates(sess):
    """HAVING with WITH ERROR filters groups on their ESTIMATED
    aggregate values post-hoc (round-4 verdict task 7; ref
    docs/sde/sample_selection.md query-QCS incl. Having columns)."""
    s, carriers, delays, _ = sess
    all_rows = s.sql(
        "SELECT carrier, sum(delay) AS sd FROM airline "
        "GROUP BY carrier WITH ERROR 0.5").rows()
    assert len(all_rows) >= 2
    cutoff = sorted(r[1] for r in all_rows)[len(all_rows) // 2]
    kept = s.sql(
        f"SELECT carrier, sum(delay) AS sd FROM airline "
        f"GROUP BY carrier HAVING sum(delay) > {cutoff} "
        f"WITH ERROR 0.5").rows()
    assert {r[0] for r in kept} \
        == {r[0] for r in all_rows if r[1] > cutoff}
    # alias references work too
    kept2 = s.sql(
        f"SELECT carrier, sum(delay) AS sd FROM airline "
        f"GROUP BY carrier HAVING sd > {cutoff} WITH ERROR 0.5").rows()
    assert {r[0] for r in kept2} == {r[0] for r in kept}


# ------------------------------------------------------------------
# behaviors
# ------------------------------------------------------------------

@pytest.fixture(scope="module")
def behavior_sess():
    """One noisy group (mean ≈ 0 → huge relative error) among stable
    ones — exactly the shape the per-group behaviors differentiate."""
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE m (g STRING, v DOUBLE) USING column")
    rng = np.random.default_rng(5)
    n = 8000
    g = np.array(["a", "b", "c", "noisy"], dtype=object)[
        rng.integers(0, 4, n)]
    v = np.where(g == "noisy", rng.normal(0.02, 50, n),
                 rng.normal(100, 1, n))
    s.insert_arrays("m", [g, v])
    s.sql("CREATE SAMPLE TABLE m_sample ON m OPTIONS (baseTable 'm', "
          "qcs 'g', reservoir_size '150')")
    df = {"g": g, "v": v}
    yield s, df
    s.stop()


def test_behavior_do_nothing_returns_estimates(behavior_sess):
    s, _ = behavior_sess
    r = s.sql("SELECT g, avg(v) AS av FROM m GROUP BY g "
              "WITH ERROR 0.05 BEHAVIOR 'do_nothing'")
    assert len(r.rows()) == 4
    assert all(row[1] is not None for row in r.rows())


def test_behavior_strict_raises(behavior_sess):
    s, _ = behavior_sess
    with pytest.raises(HACViolation):
        s.sql("SELECT g, avg(v) AS av FROM m GROUP BY g "
              "WITH ERROR 0.05 BEHAVIOR 'strict'")


def test_behavior_local_omit_nulls_violators(behavior_sess):
    s, _ = behavior_sess
    r = s.sql("SELECT g, avg(v) AS av FROM m GROUP BY g "
              "WITH ERROR 0.05 BEHAVIOR 'local_omit'")
    got = {row[0]: row[1] for row in r.rows()}
    assert got["noisy"] is None
    for k in ("a", "b", "c"):
        assert got[k] == pytest.approx(100, rel=0.1)


def test_behavior_run_on_full_table_gives_exact(behavior_sess):
    s, df = behavior_sess
    r = s.sql("SELECT g, avg(v) AS av, absolute_error(av) AS ae, "
              "lower_bound(av) AS lb FROM m GROUP BY g "
              "WITH ERROR 0.05 BEHAVIOR 'run_on_full_table'")
    for g_, av, ae, lb in r.rows():
        exact = df["v"][df["g"] == g_].mean()
        assert av == pytest.approx(exact)
        assert ae == 0.0
        assert lb is None


def test_behavior_partial_run_replaces_only_violators(behavior_sess):
    s, df = behavior_sess
    r = s.sql("SELECT g, avg(v) AS av, absolute_error(av) AS ae "
              "FROM m GROUP BY g "
              "WITH ERROR 0.05 BEHAVIOR 'partial_run_on_base_table'")
    got = {row[0]: (row[1], row[2]) for row in r.rows()}
    # the noisy group came from the base table: exact value, zero error
    exact_noisy = df["v"][df["g"] == "noisy"].mean()
    assert got["noisy"][0] == pytest.approx(exact_noisy)
    assert got["noisy"][1] == 0.0
    # stable groups are still estimates with a real error surface
    assert any(got[k][1] > 0 for k in ("a", "b", "c"))


# ------------------------------------------------------------------
# statistical calibration (the "done" criterion from the verdict)
# ------------------------------------------------------------------

@pytest.mark.slow
def test_monte_carlo_interval_coverage():
    """Across K independently-seeded samples, the 90% interval for a
    FILTERED sum (nonzero sampling variance) must cover the exact
    answer ≈90% of the time. Binomial(30, 0.9): P(X < 22) < 0.004 —
    the 22/30 floor fails with <0.4% probability on a calibrated
    estimator."""
    s = SnappySession(catalog=Catalog())
    carriers, delay, month = _make_base(s, n=12000, seed=42)
    m = month <= 4
    exact_sum = float(delay[m].sum())
    exact_cnt = int(m.sum())

    K, cover_sum, cover_cnt = 30, 0, 0
    ests = []
    for i in range(K):
        s.sql("DROP TABLE IF EXISTS airline_sample")
        s.sql("CREATE SAMPLE TABLE airline_sample ON airline OPTIONS "
              f"(baseTable 'airline', qcs 'carrier', "
              f"reservoir_size '250', seed '{i}')")
        r = s.sql("SELECT sum(delay) AS sd, lower_bound(sd) AS slb, "
                  "upper_bound(sd) AS sub, count(*) AS c, "
                  "lower_bound(c) AS clb, upper_bound(c) AS cub "
                  "FROM airline WHERE month_ <= 4 "
                  "WITH ERROR 0.9 CONFIDENCE 0.9")
        sd, slb, sub, c, clb, cub = r.rows()[0]
        ests.append(sd)
        if slb <= exact_sum <= sub:
            cover_sum += 1
        if clb <= exact_cnt <= cub:
            cover_cnt += 1
    s.stop()
    assert cover_sum >= 22, f"sum coverage {cover_sum}/{K}"
    assert cover_cnt >= 22, f"count coverage {cover_cnt}/{K}"
    # unbiasedness sanity: the mean estimate sits near the truth
    assert np.mean(ests) == pytest.approx(exact_sum, rel=0.05)


def test_group_order_differs_from_select_order():
    """Review follow-up: SELECT lists groups in a different order than
    GROUP BY; the exact/base paths must not swap group columns."""
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE g2 (a STRING, b STRING, x DOUBLE) USING column")
    rng = np.random.default_rng(2)
    n = 4000
    a = np.array(["a1", "a2"], dtype=object)[rng.integers(0, 2, n)]
    b = np.array(["b1", "b2"], dtype=object)[rng.integers(0, 2, n)]
    x = rng.normal(50, 2, n)
    s.insert_arrays("g2", [a, b, x])
    s.sql("CREATE SAMPLE TABLE g2_s ON g2 OPTIONS (baseTable 'g2', "
          "qcs 'a', reservoir_size '100')")
    # tiny tolerance forces the violation → full-table re-run path,
    # which is where the select-order/group-order mapping used to swap
    r = s.sql("SELECT b, a, avg(x) AS ax FROM g2 GROUP BY a, b "
              "WITH ERROR 0.00001 BEHAVIOR 'run_on_full_table'")
    for bv, av, ax in r.rows():
        assert av.startswith("a") and bv.startswith("b")
        exact = x[(a == av) & (b == bv)].mean()
        assert ax == pytest.approx(exact)
    s.stop()


def test_empty_sample_global_aggregate_contract():
    """SUM over an empty sample answers NULL, COUNT answers 0."""
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE empt (x DOUBLE) USING column")
    s.sql("CREATE SAMPLE TABLE empt_s ON empt OPTIONS (baseTable 'empt', "
          "reservoir_size '50')")
    r = s.sql("SELECT sum(x) AS sx, count(*) AS c FROM empt "
              "WITH ERROR 0.5")
    sx, c = r.rows()[0]
    assert sx is None and c == 0
    s.stop()


def test_base_table_underscore_spelling():
    """base_table (with underscore) registers the sample for estimation
    just like baseTable."""
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE bt (x DOUBLE) USING column")
    s.insert_arrays("bt", [np.arange(1000, dtype=np.float64)])
    s.sql("CREATE SAMPLE TABLE bt_s ON bt OPTIONS (base_table 'bt', "
          "reservoir_size '100')")
    r = s.sql("SELECT sum(x) AS sx, absolute_error(sx) AS ae FROM bt "
              "WITH ERROR 0.9")
    sx, ae = r.rows()[0]
    assert ae is not None and ae > 0   # estimated, not the exact path
    s.stop()


def test_best_qcs_sample_selection():
    """Multiple samples on one base: the estimator picks the sample
    whose QCS best matches the query's WHERE/GROUP BY/HAVING columns —
    exact match > superset > largest-overlap subset, largest sample on
    ties (round-4 verdict task 7; ref docs/sde/sample_selection.md)."""
    from snappydata_tpu.aqp.error_estimation import (_ExecCtx,
                                                     _select_sample)
    from snappydata_tpu.sql.parser import parse as _parse

    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE ms (a STRING, b STRING, v DOUBLE) USING column")
    rng = np.random.default_rng(4)
    n = 3000
    s.insert_arrays("ms", [
        rng.choice(np.array(["x", "y", "z"], dtype=object), n),
        rng.choice(np.array(["p", "q"], dtype=object), n),
        rng.random(n)])
    s.sql("CREATE SAMPLE TABLE ms_a ON ms OPTIONS (baseTable 'ms', "
          "qcs 'a', reservoir_size '60')")
    s.sql("CREATE SAMPLE TABLE ms_ab ON ms OPTIONS (baseTable 'ms', "
          "qcs 'a,b', reservoir_size '60')")
    s.sql("CREATE SAMPLE TABLE ms_b ON ms OPTIONS (baseTable 'ms', "
          "qcs 'b', reservoir_size '60')")
    ctx = _ExecCtx(catalog=s.catalog, run_phases=None, run_exact=None,
                   refresh=lambda: None)
    cands = ["ms_a", "ms_ab", "ms_b"]

    def pick(sql_text):
        stmt = _parse(sql_text)
        node = stmt.plan
        while not isinstance(node, ast.Aggregate):
            node = node.children()[0]
        return _select_sample(ctx, node, None, cands)

    # exact QCS match
    assert pick("SELECT a, sum(v) FROM ms GROUP BY a") == "ms_a"
    assert pick("SELECT b, sum(v) FROM ms GROUP BY b") == "ms_b"
    assert pick("SELECT a, b, sum(v) FROM ms GROUP BY a, b") == "ms_ab"
    # superset beats subset: grouping by b with a WHERE on a -> {a,b}
    assert pick("SELECT b, sum(v) FROM ms WHERE a = 'x' GROUP BY b") \
        == "ms_ab"
    # and the full estimation path still runs with several samples
    r = s.sql("SELECT a, sum(v) AS sv, absolute_error(sv) FROM ms "
              "GROUP BY a WITH ERROR 0.9").rows()
    assert len(r) == 3
    s.stop()


@pytest.mark.slow
def test_100k_group_with_error_completes_fast():
    """The vectorized strata combine at scale: a 100k-group WITH ERROR
    query must complete in seconds (the per-group Python loop was
    pathological here — round-4 verdict task 7)."""
    import time as _t

    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE wide (g BIGINT, v DOUBLE) USING column")
    n = 200_000
    rng = np.random.default_rng(5)
    g = np.arange(n, dtype=np.int64) % 100_000
    v = rng.random(n)
    s.insert_arrays("wide", [g, v])
    s.sql("CREATE SAMPLE TABLE wide_s ON wide OPTIONS (baseTable "
          "'wide', qcs 'g', reservoir_size '2')")
    t0 = _t.time()
    rows = s.sql("SELECT g, sum(v) AS sv, absolute_error(sv) "
                 "FROM wide GROUP BY g WITH ERROR 0.99").rows()
    combine_s = _t.time() - t0
    assert len(rows) == 100_000
    assert combine_s < 60, combine_s   # loop impl took many minutes
    # spot-check: estimates are the per-stratum exact sums (reservoir
    # holds every row of a 2-row stratum -> weight 1, variance 0)
    got = {int(r[0]): r[1] for r in rows[:1000]}
    for gi, sv in list(got.items())[:20]:
        exact = float(v[g == gi].sum())
        assert sv == pytest.approx(exact, rel=1e-9), gi
    s.stop()


def test_rollup_with_error(sess):
    """WITH ERROR over ROLLUP: one estimation per grouping set, absent
    keys NULL, bounds per variant (round-5 scope widening; the exact
    engine expands grouping sets the same way)."""
    s, carriers, delay, _ = sess
    rows = s.sql(
        "SELECT carrier, sum(delay) AS sd, absolute_error(sd) AS ae "
        "FROM airline GROUP BY ROLLUP(carrier) WITH ERROR 0.5").rows()
    per_carrier = [r for r in rows if r[0] is not None]
    grand = [r for r in rows if r[0] is None]
    assert len(per_carrier) == 4 and len(grand) == 1
    assert all(r[2] is not None and r[2] >= 0 for r in rows)
    # the grand total estimate is consistent with the per-group ones
    assert grand[0][1] == pytest.approx(
        sum(r[1] for r in per_carrier), rel=0.2)
    # plain rollup over the base (no sample registered) path also works
    plain = s.sql("SELECT month_, count(*) FROM airline "
                  "GROUP BY ROLLUP(month_)").rows()
    assert len(plain) == 13
