"""Exactly-once streaming ingest with CDC events (ref example:
examples/.../structuredstreaming/CDCExample.scala and the snappysink
provider).

Run: PYTHONPATH=. python examples/streaming_exactly_once.py
"""

import numpy as np

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.streaming import (EventType, MemorySource,
                                      StreamingQuery)


def main():
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE positions (account INT PRIMARY KEY, qty INT) "
          "USING row")

    source = MemorySource()
    query = StreamingQuery(s, "positions_feed", source, "positions",
                           conflation=True)

    # CDC micro-batches: insert, update, delete events
    source.add_batch({
        "account": np.array([1, 2, 3]),
        "qty": np.array([100, 200, 300]),
        "_eventType": np.array([EventType.INSERT] * 3)})
    source.add_batch({
        "account": np.array([2, 3]),
        "qty": np.array([250, 0]),
        "_eventType": np.array([EventType.UPDATE, EventType.DELETE])})

    applied = query.process_available()
    print(f"applied {applied} batches")
    print(s.sql("SELECT * FROM positions ORDER BY account").to_pandas())

    # a replayed batch is a no-op (exactly-once via the sink state table)
    source._batches.append(source._batches[1])
    print("replay applied:", query.process_available(), "(duplicate-safe)")
    print(s.sql("SELECT * FROM positions ORDER BY account").to_pandas())


if __name__ == "__main__":
    main()
