"""SnappySession — the user entry point.

Mirrors the reference's session surface (core/.../SnappySession.scala:
sql:179, createTable:1049, insert:1983, put:2024, update:2047, delete:2112,
truncateTable, dropTable) and its execution pipeline (sqlPlan:2571 →
parse → analyze → plan-cache lookup keyed on tokenized plan → execute).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from snappydata_tpu import config
from snappydata_tpu import types as T
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.engine.executor import Executor
from snappydata_tpu.engine.result import Result, empty_result
from snappydata_tpu.engine import hosteval
from snappydata_tpu.sql import ast
from snappydata_tpu.sql.analyzer import Analyzer, AnalysisError, tokenize_plan
from snappydata_tpu.sql.parser import parse
from snappydata_tpu.storage.table_store import ColumnTableData, RowTableData


class SnappySession:
    """One user session. Sessions share the catalog/storage of their
    SnappyCluster (or a process-local default), mirroring embedded mode."""

    _default_catalog: Optional[Catalog] = None
    _default_lock = threading.Lock()

    def __init__(self, catalog: Optional[Catalog] = None, conf=None):
        if catalog is None:
            with SnappySession._default_lock:
                if SnappySession._default_catalog is None:
                    SnappySession._default_catalog = Catalog()
                catalog = SnappySession._default_catalog
        self.catalog = catalog
        self.conf = conf or config.global_properties()
        self.analyzer = Analyzer(catalog)
        self.executor = Executor(catalog, self.conf)

    # ------------------------------------------------------------------
    # SQL entry (ref SnappySession.sql:179)
    # ------------------------------------------------------------------

    def sql(self, sql_text: str, params: Sequence[Any] = ()) -> Result:
        stmt = parse(sql_text)
        return self.execute_statement(stmt, tuple(params))

    def execute_statement(self, stmt: ast.Statement, user_params=()) -> Result:
        if isinstance(stmt, ast.Query):
            return self._run_query(stmt.plan, user_params)
        if isinstance(stmt, ast.CreateTable):
            return self._create_table(stmt)
        if isinstance(stmt, ast.DropTable):
            self.catalog.drop_table(stmt.name, stmt.if_exists)
            return _status()
        if isinstance(stmt, ast.TruncateTable):
            self.catalog.describe(stmt.name).data.truncate()
            return _status()
        if isinstance(stmt, ast.CreateView):
            plan, _ = self.analyzer.analyze_plan(stmt.query)
            self.catalog.create_view(stmt.name, plan, stmt.or_replace)
            return _status()
        if isinstance(stmt, ast.DropView):
            self.catalog.drop_view(stmt.name, stmt.if_exists)
            return _status()
        if isinstance(stmt, ast.InsertInto):
            n = self._insert(stmt, user_params)
            return _count_result(n)
        if isinstance(stmt, ast.UpdateStmt):
            return _count_result(self._update(stmt, user_params))
        if isinstance(stmt, ast.DeleteStmt):
            return _count_result(self._delete(stmt, user_params))
        if isinstance(stmt, ast.ShowTables):
            infos = self.catalog.list_tables()
            return Result(
                ["tableName", "provider", "rowCount"],
                [np.array([i.name for i in infos], dtype=object),
                 np.array([i.provider for i in infos], dtype=object),
                 np.array([_row_count(i) for i in infos], dtype=np.int64)],
                [None, None, None], [T.STRING, T.STRING, T.LONG])
        if isinstance(stmt, ast.DescribeTable):
            info = self.catalog.describe(stmt.name)
            return Result(
                ["col_name", "data_type", "nullable"],
                [np.array(info.schema.names(), dtype=object),
                 np.array([str(f.dtype) for f in info.schema.fields],
                          dtype=object),
                 np.array([f.nullable for f in info.schema.fields])],
                [None, None, None], [T.STRING, T.STRING, T.BOOLEAN])
        if isinstance(stmt, ast.SetConf):
            self.conf.set(stmt.key, stmt.value)
            return _status()
        raise ValueError(f"unsupported statement {type(stmt).__name__}")

    def _run_query(self, plan: ast.Plan, user_params=()) -> Result:
        from snappydata_tpu.sql.optimizer import optimize

        plan = optimize(plan, self.catalog)
        resolved, _ = self.analyzer.analyze_plan(plan)
        if self.conf.tokenize and self.conf.plan_caching:
            tokenized, lit_params = tokenize_plan(resolved)
        else:
            from snappydata_tpu.sql.analyzer import assign_param_positions

            tokenized, lit_params = assign_param_positions(resolved, 0), ()
        params = tuple(lit_params) + tuple(user_params)
        return self.executor.execute(tokenized, params)

    # ------------------------------------------------------------------
    # Programmatic API (ref SnappySession.createTable/insert/put/...)
    # ------------------------------------------------------------------

    def create_table(self, name: str, schema, provider: str = "column",
                     options: Optional[Dict[str, str]] = None,
                     if_not_exists: bool = False,
                     key_columns: Sequence[str] = ()):
        if not isinstance(schema, T.Schema):
            schema = T.Schema([T.Field(n, dt) for n, dt in schema])
        return self.catalog.create_table(name, schema, provider,
                                         options or {}, if_not_exists,
                                         key_columns)

    def table_rows(self, name: str) -> Result:
        return self.sql(f"SELECT * FROM {name}")

    def insert(self, table: str, *rows) -> int:
        info = self.catalog.describe(table)
        arrays, nulls = _rows_to_arrays(info.schema, rows)
        if isinstance(info.data, RowTableData):
            return info.data.insert_arrays(arrays)
        return info.data.insert_arrays(arrays, nulls=nulls)

    def insert_arrays(self, table: str, arrays: Sequence[np.ndarray]) -> int:
        return self.catalog.describe(table).data.insert_arrays(list(arrays))

    def put(self, table: str, *rows) -> int:
        info = self.catalog.describe(table)
        arrays, _ = _rows_to_arrays(info.schema, rows)
        if isinstance(info.data, RowTableData):
            return info.data.put_arrays(arrays)
        return self._column_put(info, arrays)

    def update(self, table: str, where_sql: str, new_values: Dict[str, Any]
               ) -> int:
        assigns = tuple((k, ast.Lit(v)) for k, v in new_values.items())
        where = None
        if where_sql:
            where = parse(f"SELECT 1 FROM {table} WHERE {where_sql}")
            where = where.plan.children()[0].condition \
                if isinstance(where.plan, ast.Project) else None
        stmt = ast.UpdateStmt(table, assigns, where)
        return self._update(stmt, ())

    def delete(self, table: str, where_sql: str) -> int:
        stmt = parse(f"DELETE FROM {table}" +
                     (f" WHERE {where_sql}" if where_sql else ""))
        return self._delete(stmt, ())

    def get(self, table: str, key: tuple):
        """Point lookup on a row table's primary key — never enters the
        query engine (ref: ExecutionEngineArbiter fast path)."""
        info = self.catalog.describe(table)
        if not isinstance(info.data, RowTableData):
            raise ValueError("get() requires a row table with a primary key")
        return info.data.get(key)

    def stop(self):
        self.executor.clear_cache()

    def clear_plan_cache(self):
        self.executor.clear_cache()

    # ------------------------------------------------------------------
    # DML internals
    # ------------------------------------------------------------------

    def _create_table(self, stmt: ast.CreateTable) -> Result:
        if stmt.as_select is not None:
            if stmt.if_not_exists and \
                    self.catalog.lookup_table(stmt.name) is not None:
                return _status()  # no-op, do NOT re-append (review finding)
            result = self._run_query(stmt.as_select)
            schema = T.Schema([
                T.Field(n, dt) for n, dt in zip(result.names, result.dtypes)])
            info = self.catalog.create_table(stmt.name, schema, stmt.provider,
                                             stmt.options, stmt.if_not_exists)
            if result.num_rows:
                arrays, nulls = _result_to_arrays(result, schema)
                if isinstance(info.data, RowTableData):
                    info.data.insert_arrays(arrays)
                else:
                    info.data.insert_arrays(arrays, nulls=nulls)
            return _status()
        schema = T.Schema([T.Field(c.name, c.dtype, c.nullable)
                           for c in stmt.columns])
        keys = tuple(c.name for c in stmt.columns if c.primary_key)
        self.catalog.create_table(stmt.name, schema, stmt.provider,
                                  stmt.options, stmt.if_not_exists,
                                  key_columns=keys)
        return _status()

    def _insert(self, stmt: ast.InsertInto, user_params) -> int:
        info = self.catalog.describe(stmt.table)
        target_schema = info.schema
        if isinstance(stmt.source, ast.Values):
            resolved, _ = self.analyzer.analyze_plan(stmt.source)
            src = hosteval.eval_values(resolved, user_params)
        else:
            src = self._run_query(stmt.source, user_params)
        if stmt.columns:
            name_to_src = {c.lower(): i for i, c in enumerate(stmt.columns)}
            if len(stmt.columns) != len(src.columns):
                raise ValueError("INSERT column count mismatch")
        else:
            if len(src.columns) != len(target_schema):
                raise ValueError(
                    f"INSERT arity mismatch: {len(src.columns)} vs "
                    f"{len(target_schema)}")
            name_to_src = {f.name.lower(): i
                           for i, f in enumerate(target_schema.fields)}
        arrays = []
        null_masks = []
        n = src.num_rows
        for f in target_schema.fields:
            i = name_to_src.get(f.name.lower())
            if i is None:  # unmentioned column → all NULL
                arrays.append(np.zeros(n, dtype=f.dtype.np_dtype)
                              if f.dtype.name != "string"
                              else np.full(n, None, dtype=object))
                null_masks.append(np.ones(n, dtype=np.bool_))
                continue
            arr, nmask = _coerce(src.columns[i], src.nulls[i], f.dtype)
            arrays.append(arr)
            null_masks.append(nmask)
        if stmt.overwrite:
            info.data.truncate()
        if stmt.put:
            if isinstance(info.data, RowTableData):
                return info.data.put_arrays(arrays)
            return self._column_put(info, arrays)
        if isinstance(info.data, RowTableData):
            return info.data.insert_arrays(arrays)
        return info.data.insert_arrays(arrays, nulls=null_masks)

    def _column_put(self, info, arrays) -> int:
        """PUT INTO a column table: upsert join on key_columns (ref:
        ColumnPutIntoExec = update-matched + insert-rest)."""
        keys = info.key_columns
        if not keys:
            return info.data.insert_arrays(arrays)
        key_idx = [info.schema.index(k) for k in keys]
        incoming = {tuple(np.asarray(arrays[i])[r] for i in key_idx): r
                    for r in range(len(np.asarray(arrays[0])))}

        def pred(cols):
            stacked = np.stack([_key_col(cols, info, i) for i in key_idx])
            hits = np.zeros(stacked.shape[1], dtype=bool)
            for r, key in enumerate(zip(*stacked)):
                hits[r] = tuple(key) in incoming
            return hits

        def _key_col(cols, info, i):
            return np.asarray(cols[info.schema.fields[i].name])

        # delete matched, then insert everything (same visible effect as
        # update+insert under the single-statement snapshot)
        info.data.delete(pred)
        return info.data.insert_arrays(arrays)

    def _resolve_where(self, table_info, where, user_params):
        scope_entries = []
        from snappydata_tpu.sql.analyzer import Scope, ScopeEntry

        alias = table_info.name.split(".")[-1]
        scope = Scope([ScopeEntry(alias, f.name, f.dtype, f.nullable)
                       for f in table_info.schema.fields])
        resolved = self.analyzer.resolve_expr(where, scope)
        from snappydata_tpu.sql.analyzer import fold_constants

        return fold_constants(resolved)

    def _update(self, stmt: ast.UpdateStmt, user_params) -> int:
        info = self.catalog.describe(stmt.table)
        where = self._resolve_where(info, stmt.where, user_params) \
            if stmt.where is not None else ast.Lit(True, T.BOOLEAN)
        assigns = {}
        for name, e in stmt.assignments:
            resolved = self._resolve_where(info, e, user_params)
            assigns[name] = self._host_value_fn(info, resolved, user_params)
        pred = self._host_pred_fn(info, where, user_params)
        return info.data.update(pred, assigns)

    def _delete(self, stmt: ast.DeleteStmt, user_params) -> int:
        info = self.catalog.describe(stmt.table)
        where = self._resolve_where(info, stmt.where, user_params) \
            if stmt.where is not None else ast.Lit(True, T.BOOLEAN)
        pred = self._host_pred_fn(info, where, user_params)
        return info.data.delete(pred)

    def _host_pred_fn(self, info, resolved_where, user_params):
        names = info.schema.names()

        def pred(cols: Dict[str, np.ndarray]) -> np.ndarray:
            arrays = _ColsByIndex(cols, names)  # decode only touched cols
            n = arrays.num_rows(resolved_where)
            v, nl = hosteval.eval_expr(resolved_where, arrays,
                                       _NoneSeq(), tuple(user_params), n)
            out = np.broadcast_to(v, (n,)).astype(bool)
            if nl is not None:
                out = out & ~np.broadcast_to(nl, (n,))
            return out

        return pred

    def _host_value_fn(self, info, resolved_expr, user_params):
        names = info.schema.names()

        def value(cols: Dict[str, np.ndarray]):
            if isinstance(resolved_expr, ast.Lit):
                return resolved_expr.value  # incl. None = SQL NULL
            arrays = _ColsByIndex(cols, names)
            n = arrays.num_rows(resolved_expr)
            v, _ = hosteval.eval_expr(resolved_expr, arrays,
                                      _NoneSeq(), tuple(user_params), n)
            return v if np.shape(v) == () else np.broadcast_to(v, (n,))

        return value


class _ColsByIndex:
    """Ordinal-indexed view over a {name: values} mapping that fetches (and
    therefore decodes, when backed by LazyBatchColumns) only the columns an
    expression actually touches (review finding)."""

    def __init__(self, cols, names):
        self._cols = cols
        self._names = names

    def __getitem__(self, i: int) -> np.ndarray:
        return np.asarray(self._cols[self._names[i]])

    def __len__(self):
        return len(self._names)

    def num_rows(self, expr: ast.Expr) -> int:
        for node in ast.walk(expr):
            if isinstance(node, ast.Col):
                return int(self[node.index].shape[0])
        # no column refs (e.g. WHERE 1=1): any column's length works
        return int(self[0].shape[0]) if self._names else 0


class _NoneSeq:
    def __getitem__(self, i):
        return None


def _status() -> Result:
    return empty_result(["status"], [T.STRING])


def _count_result(n: int) -> Result:
    return Result(["count"], [np.array([n], dtype=np.int64)], [None], [T.LONG])


def _row_count(info) -> int:
    if isinstance(info.data, RowTableData):
        return info.data.count()
    return info.data.snapshot().total_rows()


def _rows_to_arrays(schema: T.Schema, rows):
    if len(rows) == 1 and isinstance(rows[0], (list, tuple)) and rows[0] \
            and isinstance(rows[0][0], (list, tuple)):
        rows = rows[0]
    arrays, nulls = [], []
    for i, f in enumerate(schema.fields):
        vals = [r[i] for r in rows]
        nmask = np.array([v is None for v in vals])
        if f.dtype.name == "string":
            arrays.append(np.array(vals, dtype=object))
        else:
            arrays.append(np.array(
                [0 if v is None else v for v in vals], dtype=f.dtype.np_dtype))
        nulls.append(nmask if nmask.any() else None)
    return arrays, nulls


def _result_to_arrays(result: Result, schema: T.Schema):
    arrays, nulls = [], []
    for i, f in enumerate(schema.fields):
        arr, nmask = _coerce(result.columns[i], result.nulls[i], f.dtype)
        arrays.append(arr)
        nulls.append(nmask)
    return arrays, nulls


def _coerce(col: np.ndarray, nmask, dtype: T.DataType):
    """→ (storage array, null mask | None): NULLs become fillers + mask
    instead of being silently written as 0 (review finding)."""
    if dtype.name == "string":
        out = np.array([_s(v) for v in col], dtype=object)
        if nmask is not None:
            out[nmask] = None
        return out, (np.asarray(nmask) if nmask is not None else None)
    arr = np.asarray(col)
    obj_nulls = None
    if arr.dtype == object:
        obj_nulls = np.array([v is None for v in arr])
        arr = np.array([0 if v is None else v for v in arr])
    combined = nmask
    if obj_nulls is not None and obj_nulls.any():
        combined = obj_nulls if combined is None else (combined | obj_nulls)
    return arr.astype(dtype.np_dtype), \
        (np.asarray(combined) if combined is not None else None)


def _s(v):
    return None if v is None else str(v)
