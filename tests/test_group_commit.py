"""Group-commit write path (tentpole of the batched-WAL-fsync PR):

- zero-copy record framing: one contiguous buffer, single CRC pass,
  byte-identical to what write_record streams to disk;
- CI perf guard: an N-append burst in `group` mode costs O(groups)
  fsyncs, not O(records) — the per-append-fsync regression (BENCH_r05
  load_s 30.6s → 119.8s) cannot silently return;
- ack semantics per wal_fsync_mode: `group` acks only after the
  covering fsync (bytes provably on disk before the statement returns),
  `always` pays one fsync per record, `interval:<ms>` acks early and
  the flusher closes the window;
- mid-group torn writes: the unacked group tail is truncated cleanly
  as a crash tear (never quarantined as corruption), records fully
  inside the fsynced prefix keep their acks;
- a failed group drain poisons every covered waiter (acks RAISE, never
  hang) and the store heals for subsequent appends.
"""

import io
import os
import threading

import numpy as np
import pytest

from snappydata_tpu import config, fault
from snappydata_tpu.observability.metrics import global_registry
from snappydata_tpu.storage.persistence import (DiskStore, frame_record,
                                                read_records, write_record)


@pytest.fixture(autouse=True)
def _wal_knobs():
    """Restore the WAL policy knobs and the failpoint registry."""
    props = config.global_properties()
    saved = {k: props.get(k) for k in
             ("wal_fsync_mode", "wal_buffer_bytes", "wal_group_ms")}
    fault.clear()
    yield props
    for k, v in saved.items():
        props.set(k, v)
    fault.clear()


def _wal_seqs(path):
    with open(path, "rb") as fh:
        return [h["seq"] for h, _ in read_records(fh)]


# -----------------------------------------------------------------------
# zero-copy framing
# -----------------------------------------------------------------------

def test_frame_record_single_buffer_matches_write_record():
    header = {"kind": "insert", "table": "t", "seq": 7, "ncols": 2}
    arrays = [np.arange(1000, dtype=np.int64),
              np.array(["a", None, "b"] * 333 + ["a"], dtype=object)]
    framed = frame_record(header, arrays)
    buf = io.BytesIO()
    write_record(buf, header, arrays)
    assert buf.getvalue() == framed          # write_record IS the frame
    buf.seek(0)
    (got_h, got_arrays), = list(read_records(buf))
    assert got_h == header
    np.testing.assert_array_equal(got_arrays[0], arrays[0])
    assert list(got_arrays[1]) == list(arrays[1])


# -----------------------------------------------------------------------
# fsync accounting per mode (the CI perf guard)
# -----------------------------------------------------------------------

def test_group_mode_burst_fsync_count_is_o_groups(tmp_path, _wal_knobs):
    """300 buffered appends + one sync must cost a HANDFUL of fsyncs.
    This is the guard against the r05 regression: per-append fsync made
    ingest 4x slower; group commit amortizes records into groups."""
    props = _wal_knobs
    props.set("wal_fsync_mode", "group")
    props.set("wal_group_ms", 500.0)        # flusher stays out of the way
    ds = DiskStore(str(tmp_path))
    before = global_registry().counter("wal_fsync_count")
    n = 300
    for i in range(n):
        ds.wal_append("t", "sql", sql=f"INSERT INTO t VALUES ({i})")
    ds.wal_sync()                            # ONE covering drain
    fsyncs = global_registry().counter("wal_fsync_count") - before
    assert fsyncs <= 8, \
        f"{fsyncs} fsyncs for {n} records — group commit not grouping"
    # nothing was lost to the batching: every record is on disk
    assert _wal_seqs(os.path.join(str(tmp_path), "wal.log")) == \
        list(range(1, n + 1))
    ds.close()


def test_always_mode_pays_one_fsync_per_record(tmp_path, _wal_knobs):
    props = _wal_knobs
    props.set("wal_fsync_mode", "always")
    ds = DiskStore(str(tmp_path))
    before = global_registry().counter("wal_fsync_count")
    for i in range(20):
        ds.wal_append("t", "sql", sql=f"stmt {i}")
    assert global_registry().counter("wal_fsync_count") - before == 20
    ds.close()


def test_buffer_bound_applies_backpressure(tmp_path, _wal_knobs):
    """Appends past wal_buffer_bytes drain inline — the commit buffer
    is bounded, not an unbounded memory sink."""
    props = _wal_knobs
    props.set("wal_fsync_mode", "group")
    props.set("wal_group_ms", 10_000.0)
    props.set("wal_buffer_bytes", 4096)
    ds = DiskStore(str(tmp_path))
    # incompressible payload: the at-rest codec must not shrink it back
    # under the buffer bound
    big = np.random.default_rng(0).integers(0, 1 << 62, 600)
    before = global_registry().counter("wal_fsync_count")
    for _ in range(5):
        ds.wal_append("t", "insert", arrays=[big])
    assert global_registry().counter("wal_fsync_count") - before >= 4
    ds.close()


# -----------------------------------------------------------------------
# ack semantics
# -----------------------------------------------------------------------

def test_group_ack_means_bytes_on_disk_before_return(tmp_path, _wal_knobs):
    """After a session statement returns (the ack), its WAL record is
    already fsync-covered ON DISK — verified by parsing wal.log without
    any close/flush, then by crash-shaped recovery (old store never
    closed)."""
    from snappydata_tpu import SnappySession
    from snappydata_tpu.catalog import Catalog

    props = _wal_knobs
    props.set("wal_fsync_mode", "group")
    d = str(tmp_path)
    s = SnappySession(catalog=Catalog(), data_dir=d, recover=False)
    s.sql("CREATE TABLE t (k BIGINT) USING column")
    for i in range(5):
        s.sql(f"INSERT INTO t VALUES ({i})")
    # the ack gate: all five records are parseable from disk RIGHT NOW
    assert len(_wal_seqs(os.path.join(d, "wal.log"))) == 5
    # crash shape: recover in a fresh session without closing the old one
    s2 = SnappySession(data_dir=d, recover=True)
    assert [r[0] for r in s2.sql("SELECT k FROM t ORDER BY k").rows()] \
        == [0, 1, 2, 3, 4]
    s2.disk_store.close()
    s.disk_store.close()


def test_interval_mode_relaxed_ack_then_flusher_covers(tmp_path,
                                                       _wal_knobs):
    import time as _time

    props = _wal_knobs
    props.set("wal_fsync_mode", "interval:40")
    ds = DiskStore(str(tmp_path))
    ds.wal_append("t", "sql", sql="one")
    ds.wal_sync()        # relaxed: returns without draining
    # within ~10x the interval the background flusher must have synced
    wal = os.path.join(str(tmp_path), "wal.log")
    deadline = _time.time() + 2.0
    while _time.time() < deadline:
        if os.path.exists(wal) and _wal_seqs(wal):
            break
        _time.sleep(0.02)
    assert _wal_seqs(wal) == [1], "flusher never closed the interval"
    # force=True is the hard barrier network surfaces use
    ds.wal_append("t", "sql", sql="two")
    ds.wal_sync(force=True)
    assert _wal_seqs(wal) == [1, 2]
    ds.close()


def test_close_drains_interval_mode_tail(tmp_path, _wal_knobs):
    props = _wal_knobs
    props.set("wal_fsync_mode", "interval:60000")   # flusher won't fire
    ds = DiskStore(str(tmp_path))
    ds.wal_append("t", "sql", sql="tail")
    ds.close()           # clean shutdown must not lose the acked tail
    assert _wal_seqs(os.path.join(str(tmp_path), "wal.log")) == [1]


# -----------------------------------------------------------------------
# mid-group torn writes + drain failure
# -----------------------------------------------------------------------

def test_mid_group_torn_tail_truncates_cleanly(tmp_path, _wal_knobs):
    """A torn group write: records fully inside the fsynced prefix keep
    their acks; the torn tail is truncated on reboot as a crash tear —
    NOT counted as corruption (wal_corrupt_records untouched)."""
    props = _wal_knobs
    props.set("wal_fsync_mode", "group")
    props.set("wal_group_ms", 10_000.0)      # keep the group buffered
    d = str(tmp_path)
    ds = DiskStore(d)
    for i in range(3):
        ds.wal_append("t", "sql", sql=f"stmt {i}")
    fault.arm("wal.group_commit", "torn_write", param=5, count=1)
    corrupt_before = global_registry().counter("wal_corrupt_records")
    with pytest.raises(IOError):
        ds.wal_sync()                        # drain tears the tail
    # seqs 1..2 were fully inside the written prefix: durable, acked
    ds.wal_sync(seq=2)                       # must NOT raise
    with pytest.raises(IOError):
        ds.wal_sync(seq=3)                   # the torn record's ack fails
    ds.close()
    # reboot: salvage truncates the tear; the fsynced prefix survives
    ds2 = DiskStore(d)
    assert _wal_seqs(os.path.join(d, "wal.log")) == [1, 2]
    assert global_registry().counter("wal_corrupt_records") == \
        corrupt_before, "a clean crash tear was miscounted as corruption"
    # the store accepts appends again and they land after the prefix
    ds2.wal_append("t", "sql", sql="post-crash")
    ds2.wal_sync()
    assert _wal_seqs(os.path.join(d, "wal.log"))[-1] > 2
    ds2.close()


def test_failed_group_drain_poisons_every_waiter(tmp_path, _wal_knobs):
    """An IO error mid-drain must RAISE every covered ack (never hang)
    and the store must heal for subsequent appends."""
    props = _wal_knobs
    props.set("wal_fsync_mode", "group")
    props.set("wal_group_ms", 10_000.0)
    ds = DiskStore(str(tmp_path))
    seqs = [ds.wal_append("t", "sql", sql="a"),
            ds.wal_append("t", "sql", sql="b")]
    fault.arm("wal.group_commit", "raise", count=1)
    errors = []

    def sync(seq):
        try:
            ds.wal_sync(seq)
        except Exception as e:
            errors.append(e)

    threads = [threading.Thread(target=sync, args=(q,)) for q in seqs]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert not any(t.is_alive() for t in threads), "a waiter hung"
    assert len(errors) == 2, f"both acks must fail, got {errors}"
    # healed: the next append+sync succeeds
    seq = ds.wal_append("t", "sql", sql="after")
    ds.wal_sync(seq)
    assert seq in _wal_seqs(os.path.join(str(tmp_path), "wal.log"))
    ds.close()


def test_torn_record_failpoint_still_fires_per_record(tmp_path,
                                                      _wal_knobs):
    """wal.append torn_write keeps its PER-RECORD semantics under group
    mode: earlier acked rows survive, the torn statement is lost, the
    store reopens like a real crash — chaos coverage is not weakened."""
    from snappydata_tpu import SnappySession
    from snappydata_tpu.catalog import Catalog

    props = _wal_knobs
    props.set("wal_fsync_mode", "group")
    d = str(tmp_path)
    s = SnappySession(catalog=Catalog(), data_dir=d, recover=False)
    s.sql("CREATE TABLE t (k BIGINT) USING column")
    s.sql("INSERT INTO t VALUES (1)")
    fault.arm("wal.append", "torn_write", param=9, count=1)
    with pytest.raises(IOError):
        s.sql("INSERT INTO t VALUES (2)")
    s2 = SnappySession(data_dir=d, recover=True)
    assert [r[0] for r in s2.sql("SELECT k FROM t ORDER BY k").rows()] \
        == [1]
    s2.disk_store.close()
    s.disk_store.close()


def test_failed_drain_fences_checkpoint_until_reopen(tmp_path,
                                                     _wal_knobs):
    """After a failed group drain the statement RAISED but its rows were
    already applied in memory (journal→apply→ack order). A checkpoint
    must refuse to fold that crash-shaped state into durable artifacts
    — otherwise rows the client was told FAILED silently become
    durable. Reopen/recovery rebuilds memory from the journal alone and
    clears the fence."""
    from snappydata_tpu import SnappySession
    from snappydata_tpu.catalog import Catalog

    props = _wal_knobs
    props.set("wal_fsync_mode", "group")
    d = str(tmp_path)
    s = SnappySession(catalog=Catalog(), data_dir=d, recover=False)
    s.sql("CREATE TABLE t (k BIGINT) USING column")
    s.sql("INSERT INTO t VALUES (1)")
    fault.arm("wal.group_commit", "raise", count=1)
    with pytest.raises(IOError):
        s.sql("INSERT INTO t VALUES (2)")    # applied, never journaled
    with pytest.raises(IOError, match="reopen"):
        s.checkpoint()                        # the fence
    s.disk_store.close()
    # recovery: only the acked row — and checkpoints work again
    s2 = SnappySession(data_dir=d, recover=True)
    assert [r[0] for r in s2.sql("SELECT k FROM t ORDER BY k").rows()] \
        == [1]
    s2.checkpoint()
    s2.disk_store.close()


def test_stale_poison_does_not_wedge_barriers(tmp_path, _wal_knobs):
    """A single torn append must not fail every later durability
    barrier: the torn record is gone (its own ack raised), so
    wal_sync(force=True) with no seq — and checkpoint(), which uses it
    — must succeed immediately afterwards; only the torn seq's OWN ack
    keeps raising."""
    from snappydata_tpu import SnappySession
    from snappydata_tpu.catalog import Catalog

    props = _wal_knobs
    props.set("wal_fsync_mode", "group")
    d = str(tmp_path)
    s = SnappySession(catalog=Catalog(), data_dir=d, recover=False)
    s.sql("CREATE TABLE t (k BIGINT) USING column")
    s.sql("INSERT INTO t VALUES (1)")
    fault.arm("wal.append", "torn_write", param=9, count=1)
    with pytest.raises(IOError):
        s.sql("INSERT INTO t VALUES (2)")     # torn: never applied
    ds = s.disk_store
    torn_seq = ds.current_wal_seq()
    ds.wal_sync(force=True)                   # barrier: must NOT raise
    with pytest.raises(IOError):
        ds.wal_sync(seq=torn_seq)             # the torn record's own ack
    s.checkpoint()                            # memory == journal: allowed
    s.sql("INSERT INTO t VALUES (3)")
    s.disk_store.close()
    s2 = SnappySession(data_dir=d, recover=True)
    assert [r[0] for r in s2.sql("SELECT k FROM t ORDER BY k").rows()] \
        == [1, 3]
    s2.disk_store.close()


def test_checkpoint_drains_before_folding(tmp_path, _wal_knobs):
    """checkpoint() must fsync the commit buffer BEFORE folding state:
    a failed drain aborts the checkpoint with no durable artifact
    touched (folding first would durably persist a record whose ack
    later raises)."""
    from snappydata_tpu import SnappySession
    from snappydata_tpu.catalog import Catalog

    props = _wal_knobs
    props.set("wal_fsync_mode", "group")
    props.set("wal_group_ms", 10_000.0)
    d = str(tmp_path)
    s = SnappySession(catalog=Catalog(), data_dir=d, recover=False)
    s.sql("CREATE TABLE t (k BIGINT) USING column")
    s.sql("INSERT INTO t VALUES (1)")            # acked, durable
    # leave an un-drained record in the commit buffer
    s.disk_store.wal_append("t", "sql", sql="INSERT INTO t VALUES (99)")
    fault.arm("wal.group_commit", "raise", count=1)
    with pytest.raises(IOError):
        s.checkpoint()
    # the abort happened before any TABLE state was folded (catalog.json
    # exists from the CREATE TABLE DDL itself, not from this checkpoint)
    assert not os.path.exists(os.path.join(d, "tables", "t",
                                           "manifest.json"))
    s.disk_store.close()


def test_rest_wal_status_and_flush(tmp_path, _wal_knobs):
    """GET /status/api/v1/wal surfaces the group-commit counters and
    knobs; POST /wal/flush is the durability barrier that closes the
    interval-mode relaxed-ack window; the dashboard renders the
    Durability section."""
    import json
    import urllib.request

    from snappydata_tpu import SnappySession
    from snappydata_tpu.catalog import Catalog
    from snappydata_tpu.cluster.rest import RestService
    from snappydata_tpu.observability import TableStatsService

    props = _wal_knobs
    props.set("wal_fsync_mode", "interval:60000")   # flusher won't fire
    s = SnappySession(catalog=Catalog(), data_dir=str(tmp_path),
                      recover=False)
    s.sql("CREATE TABLE t (k BIGINT) USING column")
    svc = RestService(s, TableStatsService(s.catalog)).start()
    try:
        s.sql("INSERT INTO t VALUES (1)")   # relaxed ack: not synced yet
        base = f"http://{svc.host}:{svc.port}"
        wal = json.loads(urllib.request.urlopen(
            base + "/status/api/v1/wal").read())
        assert wal["wal_fsync_mode"].startswith("interval")
        for key in ("wal_fsync_count", "wal_group_commit_batches",
                    "wal_bytes_written", "wal_group_flush_ms"):
            assert key in wal, key
        req = urllib.request.Request(
            base + "/wal/flush", data=b"{}",
            headers={"Content-Type": "application/json"})
        out = json.loads(urllib.request.urlopen(req).read())
        assert out == {"flushed_members": 1, "durable_members": 1}
        # the barrier closed the window: the record is on disk NOW
        assert _wal_seqs(os.path.join(str(tmp_path), "wal.log"))
        html = urllib.request.urlopen(base + "/dashboard").read().decode()
        assert "Durability (WAL group commit)" in html
    finally:
        svc.stop()
        s.disk_store.close()


def test_concurrent_committers_coalesce_and_recover(tmp_path, _wal_knobs):
    """4 committer threads through a real session: every acked row is
    fsync-covered, groups coalesce (fewer fsyncs than statements), and
    recovery returns exactly the acked set."""
    from snappydata_tpu import SnappySession
    from snappydata_tpu.catalog import Catalog

    props = _wal_knobs
    props.set("wal_fsync_mode", "group")
    d = str(tmp_path)
    s = SnappySession(catalog=Catalog(), data_dir=d, recover=False)
    s.sql("CREATE TABLE t (k BIGINT) USING column")
    acked = []
    lock = threading.Lock()

    def committer(base):
        for i in range(base, base + 25):
            s.sql(f"INSERT INTO t VALUES ({i})")
            with lock:
                acked.append(i)

    threads = [threading.Thread(target=committer, args=(w * 100,))
               for w in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    s.disk_store.close()
    s2 = SnappySession(data_dir=d, recover=True)
    got = sorted(r[0] for r in s2.sql("SELECT k FROM t").rows())
    assert got == sorted(acked)
    s2.disk_store.close()
