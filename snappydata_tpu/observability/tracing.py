"""End-to-end request tracing: one trace id per request, span trees
across client → locator failover/hedge → server → engine.

Reference: the SnappyData SQL UI stitches per-operator SQLMetrics into
one plan view per query (SnappySQLListener + CachedDataFrame's
`withNewExecutionId`), and its cluster dashboard joins client-visible
latency to server-side execution through the statement id.  Here the
same join key is an explicit **trace id**, minted at whichever front
door a request enters (REST ``POST /sql``, Flight/FlightSQL tickets,
``SnappyClient``, ``DistributedSession``, a plain
``SnappySession.sql``) and propagated exactly the way the PR 8 deadline
rides: a contextvar locally, a ``trace_id`` request-body/ticket field
across the wire.  A server receiving a traced request opens its OWN
trace under the SAME id, so the per-process trace rings are joinable —
one distributed query shows up as a lead trace (with per-member fan-out
leg spans) plus one server trace per member, all carrying one id.

Span tree invariants:

- ``request_scope`` mints at most one trace per logical request — an
  ambient trace absorbs nested scopes (tile partials, matview-sync
  scratch queries, the serving path re-entering session.sql), so the
  whole request is ONE tree.
- ``span(name)`` is ~free when no trace is active (one contextvar read,
  no allocation) — the tracing-disabled overhead guard in bench.py
  leans on this.
- Spans cap their direct children (`_MAX_CHILDREN`) so a 10k-tile scan
  can't balloon a trace; truncation is visible
  (``children_truncated`` on the parent).
- Worker threads do not inherit contextvars: a thread acting for a
  traced request re-enters with ``attach(trace, span)`` (the hedged
  replica-read workers in cluster/distributed.py do).

Completed traces land in a bounded in-process ring
(``trace_ring_entries``) served by ``GET /status/api/v1/traces``; any
trace slower than ``slow_query_ms`` is ALSO kept in a separate
slow-query ring so one burst of fast queries can't wash an outlier out
of the evidence.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import threading
from snappydata_tpu.utils import locks
import time
import uuid
from collections import deque
from typing import Dict, List, Optional

from snappydata_tpu import config

# children per span beyond which further same-level spans collapse into
# a truncation counter (a per-tile bind span tree must stay bounded)
_MAX_CHILDREN = 256

# trace ids: one random process prefix + a counter — uuid4 per trace
# costs ~4µs of urandom on every short serving request, and ids only
# need to be unique across the processes sharing a monitoring surface
_ID_PREFIX = uuid.uuid4().hex[:8]
_ID_COUNTER = itertools.count(1)


class Span:
    """One timed phase. `attrs` carries the phase's evidence (batch
    counts, cache verdicts, member addresses); children nest."""

    __slots__ = ("name", "attrs", "children", "_t0", "duration_s")

    def __init__(self, name: str, attrs: Optional[dict] = None):
        self.name = name
        self.attrs: dict = attrs or {}
        self.children: List["Span"] = []
        self._t0 = time.perf_counter()
        self.duration_s: Optional[float] = None

    def set(self, key: str, value) -> None:
        self.attrs[key] = value

    def add(self, key: str, value) -> None:
        self.attrs[key] = self.attrs.get(key, 0) + value

    def close(self) -> None:
        if self.duration_s is None:
            self.duration_s = time.perf_counter() - self._t0

    def to_dict(self) -> dict:
        out = {"name": self.name,
               "ms": round((self.duration_s or 0.0) * 1e3, 4)}
        if self.attrs:
            # defensive copy: a straggling worker (a losing hedge leg)
            # may still be inserting attrs while the ring serializes —
            # retry the copy through the resize, degrade rather than
            # let a RuntimeError escape into the REST handler
            for _ in range(4):
                try:
                    out["attrs"] = dict(self.attrs)
                    break
                except RuntimeError:
                    continue
            else:
                out["attrs"] = {"attrs_unstable": True}
        if self.children:
            out["children"] = [c.to_dict() for c in list(self.children)]
        return out


class Trace:
    """One request's span tree plus its identity (trace id, sql, user,
    kind, origin). `kind` names the front door that minted it —
    session | client | lead | server | rest | job | explain."""

    __slots__ = ("trace_id", "sql", "user", "kind", "origin", "ts",
                 "root", "status", "error", "duration_s")

    def __init__(self, sql: str, user: str, kind: str,
                 trace_id: Optional[str] = None,
                 origin: Optional[str] = None):
        self.trace_id = trace_id or \
            f"{_ID_PREFIX}{next(_ID_COUNTER):08x}"
        # truncate at construction: the ring retains up to
        # trace_ring_entries+SLOW_ENTRIES traces, and a bulk INSERT's
        # multi-MB literal list must not pin memory until eviction
        # (summaries cap at 200 chars anyway; 2000 keeps detail useful)
        self.sql = sql if len(sql) <= 2000 else sql[:2000] + "…"
        self.user = user
        self.kind = kind
        self.origin = origin
        self.ts = time.time()
        self.root = Span("request")
        self.status = "ok"
        self.error: Optional[str] = None
        self.duration_s: Optional[float] = None

    def finish(self) -> None:
        self.root.close()
        self.duration_s = self.root.duration_s

    def span_count(self) -> int:
        n = 0
        stack = [self.root]
        while stack:
            sp = stack.pop()
            n += 1
            stack.extend(sp.children)
        return n

    def phase_seconds(self) -> Dict[str, float]:
        """Total seconds per span NAME across the whole tree — the
        per-phase breakdown EXPLAIN ANALYZE and bench.py report.  Spans
        still open (crashed mid-phase) are skipped."""
        out: Dict[str, float] = {}
        stack = list(self.root.children)
        while stack:
            sp = stack.pop()
            if sp.duration_s is not None:
                out[sp.name] = out.get(sp.name, 0.0) + sp.duration_s
            stack.extend(sp.children)
        return out

    def summary(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "kind": self.kind,
            "origin": self.origin,
            "sql": (self.sql or "")[:200],
            "user": self.user,
            "ts": self.ts,
            "ms": round((self.duration_s or 0.0) * 1e3, 3),
            "status": self.status,
            "error": self.error,
            "spans": self.span_count() - 1,
        }

    def to_dict(self) -> dict:
        out = self.summary()
        out["root"] = self.root.to_dict()
        out["phases_ms"] = {k: round(v * 1e3, 4)
                            for k, v in sorted(self.phase_seconds().items())}
        return out


# -----------------------------------------------------------------------
# ambient trace/span (contextvars; threads re-enter via attach())
# -----------------------------------------------------------------------

_trace: contextvars.ContextVar = contextvars.ContextVar(
    "snappy_trace", default=None)
_span: contextvars.ContextVar = contextvars.ContextVar(
    "snappy_trace_span", default=None)


def enabled() -> bool:
    return bool(config.global_properties().tracing_enabled)


def current() -> Optional[Trace]:
    return _trace.get()


def current_span() -> Optional[Span]:
    return _span.get()


def current_trace_id() -> Optional[str]:
    tr = _trace.get()
    return tr.trace_id if tr is not None else None


def wire_id() -> Optional[str]:
    """The trace id to ship in a request body/ticket, or None (no
    active trace).  Kept as its own helper so call sites read as wire
    propagation, not introspection."""
    return current_trace_id()


class request_scope:
    """Mint (or join) the request's trace.  An ambient trace absorbs
    the scope (nested executions stay one tree); otherwise a new trace
    starts when tracing is enabled (or `force`, which EXPLAIN ANALYZE
    uses so it works with tracing off).  On exit the trace finalizes
    into the ring + slow-query log.  Enters to the active Trace or
    None.  Class-based CM: the @contextmanager generator machinery cost
    ~4µs per request on the serving point-lookup profile."""

    __slots__ = ("sql", "user", "kind", "trace_id", "origin", "force",
                 "_tr", "_tok_t", "_tok_s")

    def __init__(self, sql: str = "", user: str = "",
                 kind: str = "session", trace_id: Optional[str] = None,
                 origin: Optional[str] = None, force: bool = False):
        self.sql = sql
        self.user = user
        self.kind = kind
        self.trace_id = trace_id
        self.origin = origin
        self.force = force
        self._tr = None

    def __enter__(self):
        ambient = _trace.get()
        if ambient is not None:
            return ambient
        if not (self.force or enabled()):
            return None
        tr = Trace(self.sql, self.user, self.kind,
                   trace_id=self.trace_id, origin=self.origin)
        self._tr = tr
        self._tok_t = _trace.set(tr)
        self._tok_s = _span.set(tr.root)
        return tr

    def __exit__(self, et, ev, tb):
        tr = self._tr
        if tr is None:
            return False
        if et is not None:
            tr.status = "error"
            tr.error = f"{et.__name__}: {ev}"[:300]
        _span.reset(self._tok_s)
        _trace.reset(self._tok_t)
        tr.finish()
        _RING.record(tr)
        return False


class _NoopSpan:
    __slots__ = ()

    def set(self, key, value):
        pass

    def add(self, key, value):
        pass


_NOOP = _NoopSpan()


class span:
    """A timed child span of the current span — a no-op (one contextvar
    read, no allocation) when no trace is active.  Enters to the span
    so callers can `.set()` evidence on it."""

    __slots__ = ("name", "attrs", "_sp", "_tok")

    def __init__(self, name: str, **attrs):
        self.name = name
        self.attrs = attrs
        self._sp = None

    def __enter__(self):
        parent = _span.get()
        if parent is None:
            return _NOOP
        if len(parent.children) >= _MAX_CHILDREN:
            parent.attrs["children_truncated"] = \
                parent.attrs.get("children_truncated", 0) + 1
            return _NOOP
        sp = Span(self.name, self.attrs or None)
        parent.children.append(sp)
        self._sp = sp
        self._tok = _span.set(sp)
        return sp

    def __exit__(self, et, ev, tb):
        sp = self._sp
        if sp is not None:
            _span.reset(self._tok)
            sp.close()
        return False


def annotate(key: str, value) -> None:
    """Attach evidence to the CURRENT span (no-op untraced)."""
    sp = _span.get()
    if sp is not None:
        sp.attrs[key] = value


@contextlib.contextmanager
def attach(trace: Optional[Trace], at_span: Optional[Span] = None):
    """Re-enter a trace from a worker thread (contextvars do not cross
    threads).  Spans opened under it append to `at_span` (default: the
    trace root); list append is GIL-atomic, so concurrent workers may
    share a parent.  A trace that already FINISHED (the primary won and
    the request returned while this worker — a losing hedge leg — was
    still running) is not re-entered: its tree is published to the ring
    and must stop changing."""
    if trace is None or trace.duration_s is not None:
        yield
        return
    tok_t = _trace.set(trace)
    tok_s = _span.set(at_span or trace.root)
    try:
        yield
    finally:
        _span.reset(tok_s)
        _trace.reset(tok_t)


# -----------------------------------------------------------------------
# completed-trace ring + slow-query log
# -----------------------------------------------------------------------

class TraceRing:
    """Bounded ring of completed traces plus the separate slow-query
    ring (`slow_query_ms`) — a burst of fast queries can't evict the
    over-threshold outlier an operator is hunting."""

    SLOW_ENTRIES = 64

    def __init__(self):
        self._lock = locks.named_lock("tracing.rings")
        self._ring: "deque[Trace]" = deque()
        self._slow: "deque[Trace]" = deque(maxlen=self.SLOW_ENTRIES)
        self.recorded = 0
        self.slow_recorded = 0

    def record(self, trace: Trace) -> None:
        props = config.global_properties()
        cap = max(1, int(props.trace_ring_entries or 1))
        slow_ms = float(props.slow_query_ms or 0.0)
        is_slow = slow_ms > 0 and (trace.duration_s or 0.0) * 1e3 >= slow_ms
        with self._lock:
            self._ring.append(trace)
            while len(self._ring) > cap:
                self._ring.popleft()
            self.recorded += 1
            if is_slow:
                self._slow.append(trace)
                self.slow_recorded += 1
        if is_slow:
            from snappydata_tpu.observability.metrics import global_registry

            global_registry().inc("slow_queries")

    def traces(self, limit: int = 50) -> List[dict]:
        with self._lock:
            items = list(self._ring)[-max(1, limit):]
        return [t.summary() for t in reversed(items)]

    def get(self, trace_id: str) -> List[dict]:
        """Every local trace carrying `trace_id` (a distributed query
        in one process — the test cluster — may record a lead trace AND
        per-server traces under one id), full span trees."""
        with self._lock:
            items = [t for t in self._ring if t.trace_id == trace_id]
        return [t.to_dict() for t in items]

    def slow(self) -> List[dict]:
        with self._lock:
            items = list(self._slow)
        return [t.to_dict() for t in reversed(items)]

    def last(self) -> Optional[Trace]:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._slow.clear()


_RING = TraceRing()


def ring() -> TraceRing:
    return _RING


def tracing_snapshot() -> dict:
    """Knobs + ring state for `GET /status/api/v1/traces` and the
    dashboard's Tracing section."""
    props = config.global_properties()
    r = _RING
    with r._lock:
        held = len(r._ring)
        slow_held = len(r._slow)
    return {
        "tracing_enabled": bool(props.tracing_enabled),
        "trace_ring_entries": int(props.trace_ring_entries),
        "slow_query_ms": float(props.slow_query_ms or 0.0),
        "traces_recorded": r.recorded,
        "traces_held": held,
        "slow_queries_recorded": r.slow_recorded,
        "slow_queries_held": slow_held,
    }
