"""Probabilistic sketches: Count-Min and TopK (space-saving).

Re-provides the clearspring utilities the reference vendors for its AQP
TopK support (core/src/main/java/io/snappydata/util/com/clearspring —
CountMinSketch, StreamSummary; TopK trait core/.../execution/TopK.scala:23;
SnappyContextFunctions.createTopK/queryTopK :42-62). Vectorized numpy:
updates are O(rows × depth) array ops, so sketch maintenance keeps pace
with ingest.
"""

from __future__ import annotations

import math
import threading
from snappydata_tpu.utils import locks
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from snappydata_tpu.parallel.hashing import murmur3_hash_np


class CountMinSketch:
    """Count-Min with conservative point queries (min over rows)."""

    def __init__(self, depth: int = 5, width: int = 2048, seed: int = 7):
        self.depth = depth
        self.width = width
        self.seeds = np.arange(seed, seed + depth, dtype=np.uint32)
        self.table = np.zeros((depth, width), dtype=np.int64)
        self.total = 0

    def _indices(self, keys: np.ndarray) -> np.ndarray:
        """[depth, n] bucket indices."""
        out = np.empty((self.depth, len(keys)), dtype=np.int64)
        for d in range(self.depth):
            h = murmur3_hash_np(np.asarray(keys), seed=self.seeds[d])
            out[d] = (h.astype(np.int64) % self.width + self.width) \
                % self.width
        return out

    def add(self, keys: np.ndarray, counts: Optional[np.ndarray] = None
            ) -> None:
        keys = np.asarray(keys)
        counts = np.ones(len(keys), dtype=np.int64) if counts is None \
            else np.asarray(counts, dtype=np.int64)
        idx = self._indices(keys)
        for d in range(self.depth):
            np.add.at(self.table[d], idx[d], counts)
        self.total += int(counts.sum())

    def estimate(self, keys: np.ndarray) -> np.ndarray:
        keys = np.asarray(keys)
        idx = self._indices(keys)
        ests = np.stack([self.table[d][idx[d]] for d in range(self.depth)])
        return ests.min(axis=0)

    def merge(self, other: "CountMinSketch") -> "CountMinSketch":
        assert self.table.shape == other.table.shape
        out = CountMinSketch(self.depth, self.width)
        out.seeds = self.seeds
        out.table = self.table + other.table
        out.total = self.total + other.total
        return out


class TimeDecayedTopK:
    """Time-axis TopK (ref: the reference pairs TopK with Hokusai for its
    time dimension; TopK trait core/.../execution/TopK.scala:23 exposes
    start/end-time queries). One CMS + space-saving summary per time
    bucket; memory is bounded by evicting the oldest buckets past
    `max_buckets`. (Hokusai's width-halving ladder — degrading old
    buckets instead of dropping them — is a later refinement.)"""

    def __init__(self, k: int = 50, bucket_seconds: int = 60,
                 max_buckets: int = 64, cms_width: int = 2048):
        self.k = k
        self.bucket_seconds = bucket_seconds
        self.max_buckets = max_buckets
        self.cms_width = cms_width
        self._buckets: Dict[int, TopKSummary] = {}
        self._lock = locks.named_lock("aqp.decayed_topk")

    def _bucket_of(self, ts: float) -> int:
        return int(ts // self.bucket_seconds)

    def observe(self, keys: Sequence, timestamps: Sequence,
                counts: Optional[Sequence] = None) -> None:
        keys = np.asarray(keys)
        ts = np.asarray(timestamps, dtype=np.float64)
        cnt = np.ones(len(keys), dtype=np.int64) if counts is None \
            else np.asarray(counts, dtype=np.int64)
        buckets = (ts // self.bucket_seconds).astype(np.int64)
        with self._lock:
            for b in np.unique(buckets):
                mask = buckets == b
                summ = self._buckets.get(int(b))
                if summ is None:
                    summ = TopKSummary(k=self.k, cms_width=self.cms_width)
                    self._buckets[int(b)] = summ
                summ.observe(keys[mask], cnt[mask])
            # bound memory: drop buckets beyond max_buckets (oldest first)
            if len(self._buckets) > self.max_buckets:
                for b in sorted(self._buckets)[:-self.max_buckets]:
                    del self._buckets[b]

    def top(self, n: Optional[int] = None, start_time: Optional[float] = None,
            end_time: Optional[float] = None) -> List[Tuple[object, int]]:
        """TopK over a time range (ref queryTopK(name, start, end))."""
        n = n or self.k
        lo = self._bucket_of(start_time) if start_time is not None else None
        hi = self._bucket_of(end_time) if end_time is not None else None
        merged: Dict = {}
        with self._lock:
            for b, summ in self._buckets.items():
                if lo is not None and b < lo:
                    continue
                if hi is not None and b > hi:
                    continue
                for key, c in summ.top(summ.k * 4):
                    merged[key] = merged.get(key, 0) + c
        return sorted(merged.items(), key=lambda kv: -kv[1])[:n]


class TopKSummary:
    """Space-saving top-K over a key column, CMS-backed counts for keys
    evicted from the monitored set (the reference pairs StreamSummary with
    CountMinSketch the same way)."""

    def __init__(self, k: int = 50, cms_depth: int = 5, cms_width: int = 2048):
        self.k = k
        self.cms_width = cms_width
        self.cms = CountMinSketch(cms_depth, cms_width)
        self._counts: Dict = {}
        self._lock = locks.named_lock("aqp.topk")

    def observe(self, keys: Sequence, counts: Optional[Sequence] = None
                ) -> None:
        keys_arr = np.asarray(keys)
        cnt = np.ones(len(keys_arr), dtype=np.int64) if counts is None \
            else np.asarray(counts, dtype=np.int64)
        numeric = keys_arr if np.issubdtype(keys_arr.dtype, np.number) \
            else murmur3_hash_np(
                np.array([hash(x) & 0x7FFFFFFF for x in keys_arr.tolist()],
                         dtype=np.int32)).astype(np.int64)
        self.cms.add(np.asarray(numeric, dtype=np.int64), cnt)
        with self._lock:
            for key, c in zip(keys_arr.tolist(), cnt.tolist()):
                if key in self._counts:
                    self._counts[key] += c
                elif len(self._counts) < self.k * 4:
                    self._counts[key] = c
                else:
                    # space-saving eviction: displace the current minimum,
                    # inheriting its count (overestimate, never under)
                    mk = min(self._counts, key=self._counts.get)
                    mv = self._counts.pop(mk)
                    self._counts[key] = mv + c

    def top(self, n: Optional[int] = None) -> List[Tuple[object, int]]:
        n = n or self.k
        with self._lock:
            items = sorted(self._counts.items(), key=lambda kv: -kv[1])
        return items[:n]
