"""Device mesh execution: shard stacked column batches, let GSPMD insert
the collectives.

TPU-first replacement for the reference's executor fan-out + GemFire P2P
exchange (SURVEY.md §5 "Distributed communication backend"): instead of
shipping serialized rows between JVMs, the stacked [num_batches, capacity]
column arrays are laid out across a `jax.sharding.Mesh` along the batch
axis (batch ≈ bucket: the unit of data placement). The SAME compiled
query function then runs under jit with sharded inputs — XLA GSPMD
partitions the scan/filter locally and inserts psum/all_gather for the
aggregate/join exchange, which is exactly the CollectAggregateExec partial
merge and the replicated-table HashJoinExec build-side broadcast
(SnappyStrategies.scala:347, joins/HashJoinExec.scala:63) done by the
compiler instead of hand-written messaging.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
from snappydata_tpu.utils import locks
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class MeshContext:
    """Process-wide data mesh. When active, device tables bind with their
    batch axis sharded over 'data' and query jits produce SPMD programs.

    Each context carries a process-unique `token` (monotonic counter) used
    by device caches instead of id(mesh) — ids get reused after GC, which
    would let a 4-device run hit arrays placed for a dead 8-device mesh.

    `placement` is the bucket→device map (parallel/placement.py): the
    batch axis splits into logical buckets owned by devices, so a mesh
    resize is a bucket REBALANCE (storage/device.migrate_mesh_cache moves
    resident plates device-to-device) instead of a cache invalidation."""

    # the active-context stack is PER-THREAD (a contextvar): concurrent
    # sessions each enter their own context, and a class-global stack
    # would let thread A's __exit__ pop thread B's context mid-query —
    # the first concurrent-mesh workload (the PR 13 rebalance-under-
    # traffic test) deadlocked/bound-wrong exactly there.  `activate()`
    # still sets a process-wide default that current() falls back to.
    _ctx_stack: "object" = None   # initialized below (contextvar)
    _default: Optional["MeshContext"] = None
    _lock = locks.named_lock("parallel.mesh")
    _next_token = 0

    def __init__(self, mesh: Mesh, placement=None):
        from snappydata_tpu.parallel.placement import ShardPlacement

        self.mesh = mesh
        self.batch_sharding = NamedSharding(mesh, P("data", None))
        self.replicated = NamedSharding(mesh, P())
        self.placement = placement if placement is not None \
            else ShardPlacement.balanced(mesh.devices.size)
        with MeshContext._lock:
            MeshContext._next_token += 1
            self.token = MeshContext._next_token

    def sharding_for(self, leaf) -> NamedSharding:
        """Batch-axis NamedSharding matching a host/device array's rank
        (axis 0 = the batch/bucket axis, everything else replicated)."""
        import numpy as _np

        return NamedSharding(
            self.mesh, P("data", *([None] * (_np.ndim(leaf) - 1))))

    @property
    def num_devices(self) -> int:
        return self.mesh.devices.size

    @classmethod
    def current(cls) -> Optional["MeshContext"]:
        stack = cls._ctx_stack.get()
        return stack[-1] if stack else cls._default

    @classmethod
    def activate(cls, mesh: Optional[Mesh]) -> Optional["MeshContext"]:
        with cls._lock:
            cls._default = MeshContext(mesh) if mesh is not None else None
            return cls._default

    def __enter__(self):
        # plain push/pop on the per-thread stack VALUE (no contextvar
        # tokens: one shared context object entered by many threads
        # would mix tokens across threads)
        MeshContext._ctx_stack.set(
            MeshContext._ctx_stack.get() + (self,))
        return self

    def __exit__(self, *exc):
        stack = MeshContext._ctx_stack.get()
        if stack and stack[-1] is self:
            MeshContext._ctx_stack.set(stack[:-1])
        return False


MeshContext._ctx_stack = contextvars.ContextVar("mesh_ctx_stack",
                                                default=())

# Process-wide serialization of MULTI-DEVICE dispatches.  XLA's CPU
# collectives rendezvous by (global devices, op id): two threads
# concurrently executing 8-participant programs interleave their
# participant threads into each other's rendezvous and deadlock (the
# rebalance-under-traffic test hung exactly there, with
# collective_ops_utils.h "waiting for all participants" spew).  Every
# sharded dispatch — shard_map lane, plain GSPMD jit under a mesh, and
# the shuffle exchange's bucketed gathers — holds this RLock across
# dispatch + completion; single-device execution never touches it.
# Reentrant: a mesh query's host-side finalize may nest another sharded
# read.  EAGER ops on sharded arrays are dispatches too and fence the
# same way: join-artifact argsorts and expansion-bound searchsorteds at
# GSPMD bind time run inside `eager_fence()` (ops/join.py), and the tile
# prefetcher's background `device_put`s — multi-device placements from a
# non-query thread — fence through `prefetch_fence()` below.  The lock
# is a declared LEAF of the hierarchy: nothing may be acquired while it
# is held, so fenced regions are pure dispatch (cache probes, metric
# increments and lock-taking callbacks all happen outside the fence).
dispatch_lock = locks.named_rlock("parallel.mesh_dispatch")

# set inside a prefetch worker (storage/prefetch.py): makes
# shard_batches wrap its device_put in dispatch_lock — the ONLY fenced
# instruction of the background upload, so the worker never holds the
# leaf across cache/lock-taking code
_prefetch_fencing = contextvars.ContextVar("mesh_prefetch_fencing",
                                           default=False)


@contextlib.contextmanager
def prefetch_fence():
    """Mark this thread's placements as background prefetch uploads:
    every `shard_batches` device_put inside runs under dispatch_lock so
    it cannot interleave with a foreground collective's rendezvous."""
    tok = _prefetch_fencing.set(True)
    try:
        yield
    finally:
        _prefetch_fencing.reset(tok)


@contextlib.contextmanager
def eager_fence():
    """Fence a region of EAGER multi-device ops (bind-time argsorts,
    searchsorteds, device_gets on sharded arrays) exactly like a
    compiled dispatch.  No-op outside a mesh — single-device eager ops
    have no rendezvous to interleave.  The region must acquire NOTHING:
    dispatch_lock is a declared leaf, so hoist cache stores and metric
    increments out of the fence."""
    if MeshContext.current() is None:
        yield
        return
    # mesh_dispatch ENTRY seam — fired BEFORE acquiring the leaf lock
    # (fenced regions must acquire nothing): a sleep here widens the
    # dispatch-interleave window a storm schedule probes, a raise fails
    # the statement before any collective rendezvous starts
    from snappydata_tpu.reliability import failpoints as rfail

    rfail.hit("mesh.dispatch")
    # locklint: blocking-under-lock the fenced eager ops block on device
    # completion while holding the dispatch fence BY DESIGN — identical
    # to the compiled-dispatch holds above (the serialization IS the fix
    # for the rendezvous-interleave deadlock)
    with dispatch_lock:
        yield


class _NoMesh:
    """Escape hatch: `with no_mesh():` masks any ambient MeshContext —
    used by the mesh lane's scratch finalize so a [G]-row merge table
    never binds sharded over 8 devices."""

    def __enter__(self):
        MeshContext._ctx_stack.set(
            MeshContext._ctx_stack.get() + (None,))
        return self

    def __exit__(self, *exc):
        stack = MeshContext._ctx_stack.get()
        if stack and stack[-1] is None:
            MeshContext._ctx_stack.set(stack[:-1])
        return False


def no_mesh() -> _NoMesh:
    return _NoMesh()


def data_mesh(num_devices: Optional[int] = None) -> Mesh:
    devices = jax.devices()
    n = num_devices or len(devices)
    return Mesh(np.array(devices[:n]), ("data",))


def submesh(device_indices) -> Mesh:
    """Mesh over an explicit device subset — the composed topology's
    per-server plane (each ServerNode owns a disjoint slice of the
    host's chips; ref: one embedded executor per store JVM,
    ExecutorInitiator.scala:45-105)."""
    devices = jax.devices()
    return Mesh(np.array([devices[i] for i in device_indices]), ("data",))


def shard_batches(array, ctx: Optional[MeshContext]):
    """Place a stacked [B, C] array: batch-sharded under a mesh, default
    placement otherwise. B is padded to a multiple of the mesh size by the
    device builder (pow2 bucketing covers pow2 meshes)."""
    if ctx is None:
        return array
    if _prefetch_fencing.get():
        # background prefetch upload: a multi-device placement from a
        # non-query thread must not interleave with a foreground
        # collective's rendezvous (see dispatch_lock)
        # locklint: blocking-under-lock the placement blocks on the
        # transfer while holding the dispatch fence BY DESIGN
        with dispatch_lock:
            return jax.device_put(array, ctx.batch_sharding)
    return jax.device_put(array, ctx.batch_sharding)


def round_up_to(n: int, mult: int) -> int:
    return ((n + mult - 1) // mult) * mult


def shard_bucket(n: int, num_shards: int) -> int:
    """Padded batch-axis size for a MESH bind: the smallest value of the
    storage layer's {2^k, 1.5·2^k} batch-bucket ladder that is >= n AND
    divisible by `num_shards`.

    The divisibility constraint is what NamedSharding needs (equal
    blocks per device); staying ON the ladder is what keeps compiled
    executables shared — a table bound at 1/2/4/8 devices must land on
    the same handful of padded sizes the single-device ladder already
    produced, or every reshard would re-specialize every static key.
    For shard counts the ladder never divides (e.g. 5), falls back to
    the nearest multiple — off-ladder but still shape-stable."""
    n = max(1, n, num_shards)
    v = _ladder(n)
    # the ladder doubles every two steps; 8 steps ≈ 16x headroom, far
    # past any divisible hit for pow2/3·pow2 shard counts
    for _ in range(8):
        if v % num_shards == 0:
            return v
        v = _ladder(v + 1)
    return round_up_to(_ladder(n), num_shards)


def _ladder(n: int) -> int:
    """Smallest {2^k, 1.5·2^k} >= n (storage/device.batch_bucket's
    ladder, duplicated here to avoid a parallel→storage import cycle —
    the unit test pins the two against each other)."""
    if n <= 1:
        return 1
    p = 1 << (n - 1).bit_length()
    return p * 3 // 4 if p * 3 // 4 >= n else p
