from snappydata_tpu.cli import main

raise SystemExit(main())
