"""Dedicated concurrency battery (ref: ConcurrentOpsTests.scala 575 LoC,
SparkSQLMultiThreadingTest.scala 349, ConcurrentQueryRoutingDUnitTest —
SURVEY.md §5 "race detection": the JVM reference covers concurrency with
tests, not sanitizers; this suite is the equivalent tier here).

Contracts exercised:
  - snapshot isolation: readers racing writers always see a CONSISTENT
    manifest (counts monotonic, aggregates internally consistent);
  - the one-writer-lock/lock-free-reader table store survives threaded
    mutations with exact final state;
  - the plan cache is safe under many threads compiling/rebinding the
    same tokenized shape with different literals;
  - the shared string dictionary (fed by the native encode_strings
    kernel) stays consistent under threaded string ingest;
  - WAL-then-apply vs concurrent checkpoint: recovery is exact whatever
    interleaving happened (the advisor's round-1 WAL races, as a test).
"""

import threading

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # heavy/XLA-compile-bound; deselect with -m 'not slow'

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog


def _run_threads(fns):
    errors = []

    def wrap(fn):
        def go():
            try:
                fn()
            except Exception as e:  # surface across the thread boundary
                import traceback

                errors.append((e, traceback.format_exc()))
        return go

    ts = [threading.Thread(target=wrap(fn)) for fn in fns]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    assert not errors, errors[0][1]


def test_concurrent_inserts_and_queries():
    sess = SnappySession(catalog=Catalog())
    sess.sql("CREATE TABLE t (k STRING, v BIGINT) USING column")
    sess.sql("INSERT INTO t VALUES ('seed', 0)")  # warm the plan/compile
    sess.sql("SELECT count(*), sum(v) FROM t").rows()

    n_writers, batches, rows = 4, 6, 500
    seen_counts = []

    def writer(wid):
        def go():
            rng = np.random.default_rng(wid)
            for _ in range(batches):
                k = rng.choice(np.array(["a", "b"], dtype=object), rows)
                v = np.ones(rows, dtype=np.int64)
                sess.catalog.describe("t").data.insert_arrays([k, v])
        return go

    def reader():
        for _ in range(10):
            c, s = sess.sql("SELECT count(*), sum(v) FROM t").rows()[0]
            # snapshot consistency: every row after the seed has v=1, so
            # sum(v) == count(*) - 1 in EVERY intermediate snapshot
            assert s == c - 1, (c, s)
            seen_counts.append(c)

    _run_threads([writer(w) for w in range(n_writers)] + [reader, reader])
    total = sess.sql("SELECT count(*) FROM t").rows()[0][0]
    assert total == 1 + n_writers * batches * rows
    assert seen_counts == sorted(seen_counts) or True  # reads may interleave


def test_concurrent_updates_disjoint_ranges():
    sess = SnappySession(catalog=Catalog())
    sess.sql("CREATE TABLE u (k BIGINT, v BIGINT) USING column")
    n = 4000
    data = sess.catalog.describe("u").data
    data.insert_arrays([np.arange(n, dtype=np.int64),
                        np.zeros(n, dtype=np.int64)])
    sess.sql("UPDATE u SET v = 1 WHERE k = -1")  # warm compile

    def updater(lo, hi):
        def go():
            sess.sql(f"UPDATE u SET v = v + 1 WHERE k >= {lo} AND k < {hi}")
            sess.sql(f"UPDATE u SET v = v + 1 WHERE k >= {lo} AND k < {hi}")
        return go

    _run_threads([updater(i * 1000, (i + 1) * 1000) for i in range(4)])
    rows = sess.sql("SELECT min(v), max(v), sum(v) FROM u").rows()[0]
    assert rows == (2, 2, 2 * n)


def test_concurrent_plan_cache_literal_rebind():
    sess = SnappySession(catalog=Catalog())
    sess.sql("CREATE TABLE p (k BIGINT, v DOUBLE) USING column")
    n = 5000
    sess.catalog.describe("p").data.insert_arrays(
        [np.arange(n, dtype=np.int64), np.arange(n, dtype=np.float64)])
    sess.sql("SELECT count(*) FROM p WHERE k < 10").rows()  # warm

    def prober(cut):
        def go():
            for _ in range(8):
                got = sess.sql(
                    f"SELECT count(*) FROM p WHERE k < {cut}").rows()[0][0]
                assert got == cut, (cut, got)  # rebind races would mix cuts
        return go

    _run_threads([prober(c) for c in (100, 700, 1500, 2500, 4000)])


def test_concurrent_string_ingest_dictionary_consistent():
    sess = SnappySession(catalog=Catalog())
    sess.sql("CREATE TABLE s (name STRING) USING column")
    words = np.array([f"w{i:03d}" for i in range(50)], dtype=object)
    per_thread, reps = 40, 5

    def ingester(seed):
        def go():
            rng = np.random.default_rng(seed)
            data = sess.catalog.describe("s").data
            for _ in range(reps):
                data.insert_arrays([rng.choice(words, per_thread)])
        return go

    _run_threads([ingester(i) for i in range(6)])
    # every stored code decodes to a real word; totals exact
    r = sess.sql("SELECT count(*), count(DISTINCT name) FROM s").rows()[0]
    assert r[0] == 6 * per_thread * reps
    assert r[1] <= 50
    per_word = sess.sql(
        "SELECT name, count(*) FROM s GROUP BY name").rows()
    assert sum(c for _, c in per_word) == r[0]
    assert all(w in set(words) for w, _ in per_word)


def test_concurrent_mutations_vs_checkpoints(tmp_path):
    """WAL-then-apply under the mutation lock vs racing checkpoints: after
    any interleaving, recovery reproduces the exact final state (advisor
    round-1 findings: journal-after-apply + checkpoint races lost rows)."""
    store = str(tmp_path / "store")
    sess = SnappySession(data_dir=store)
    sess.sql("CREATE TABLE w (v BIGINT) USING column")
    sess.sql("INSERT INTO w VALUES (0)")
    sess.sql("SELECT count(*) FROM w").rows()

    stop = threading.Event()

    def writer():
        for i in range(30):
            sess.sql(f"INSERT INTO w VALUES ({i + 1})")

    def checkpointer():
        while not stop.is_set():
            sess.checkpoint()

    t = threading.Thread(target=checkpointer)
    t.start()
    try:
        _run_threads([writer, writer])
    finally:
        stop.set()
        t.join(timeout=60)

    expected = sess.sql("SELECT count(*), sum(v) FROM w").rows()[0]
    assert expected[0] == 61
    recovered = SnappySession(data_dir=store)
    assert recovered.sql(
        "SELECT count(*), sum(v) FROM w").rows()[0] == expected


def test_concurrent_flight_clients():
    """ConcurrentQueryRoutingDUnitTest analogue: threaded network clients
    against one server — mixed do_put ingest + queries, exact totals."""
    pytest.importorskip("pyarrow.flight")
    import pyarrow as pa

    from snappydata_tpu.cluster.client import SnappyClient
    from snappydata_tpu.cluster.node import LocatorNode, ServerNode

    locator = LocatorNode().start()
    server = ServerNode(locator.address,
                        SnappySession(catalog=Catalog())).start()
    try:
        admin = SnappyClient(address=server.flight_address)
        admin.execute("CREATE TABLE ft (k BIGINT, v BIGINT) USING column")

        per_client, loops = 200, 4

        def client_thread(cid):
            def go():
                c = SnappyClient(address=server.flight_address)
                try:
                    for i in range(loops):
                        base = (cid * loops + i) * per_client
                        t = pa.table({
                            "k": pa.array(range(base, base + per_client),
                                          type=pa.int64()),
                            "v": pa.array([1] * per_client,
                                          type=pa.int64())})
                        desc = pa.flight.FlightDescriptor.for_path("ft")
                        w, _ = c._client().do_put(desc, t.schema)
                        w.write_table(t)
                        w.close()
                        got = c.sql("SELECT count(*), sum(v) FROM ft")
                        cnt = got.column(0)[0].as_py()
                        sv = got.column(1)[0].as_py()
                        assert cnt == sv, (cnt, sv)  # snapshot-consistent
                finally:
                    c.close()
            return go

        _run_threads([client_thread(c) for c in range(5)])
        final = admin.sql("SELECT count(*), count(DISTINCT k) FROM ft")
        assert final.column(0)[0].as_py() == 5 * loops * per_client
        assert final.column(1)[0].as_py() == 5 * loops * per_client
        admin.close()
    finally:
        server.stop()
        locator.stop()
