// Native ingest kernels (C++, CPython C API — no pybind11 in this image).
//
// The reference's ingest hot path is JVM whole-stage codegen writing
// off-heap buffers (ColumnInsertExec + ColumnEncoder, encoders/...).
// Ours is this module: a single fused pass over a numpy object array of
// strings that interns against the table's shared dictionary, emits int32
// codes and the null mask in one sweep — the dominant CPU cost of
// columnar ingest once numeric columns are memcpy'd.
//
// Exposed functions:
//   encode_strings(values: np.ndarray[object], lookup: dict, store: list)
//       -> (codes: np.ndarray[int32], nulls: np.ndarray[bool] | None)
//
// Built by snappydata_tpu/native/__init__.py with the system compiler;
// a vectorized pandas fallback keeps everything working without it.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#define NPY_NO_DEPRECATED_API NPY_1_7_API_VERSION
#include <numpy/arrayobject.h>

extern "C" {

static PyObject *encode_strings(PyObject *self, PyObject *args) {
    PyObject *values_obj, *lookup, *store;
    if (!PyArg_ParseTuple(args, "OO!O!", &values_obj, &PyDict_Type, &lookup,
                          &PyList_Type, &store)) {
        return nullptr;
    }
    PyArrayObject *values = (PyArrayObject *)PyArray_FROM_OTF(
        values_obj, NPY_OBJECT, NPY_ARRAY_IN_ARRAY);
    if (values == nullptr) {
        return nullptr;
    }
    const npy_intp n = PyArray_SIZE(values);

    npy_intp dims[1] = {n};
    PyArrayObject *codes =
        (PyArrayObject *)PyArray_SimpleNew(1, dims, NPY_INT32);
    PyArrayObject *nulls =
        (PyArrayObject *)PyArray_SimpleNew(1, dims, NPY_BOOL);
    if (codes == nullptr || nulls == nullptr) {
        Py_XDECREF(codes);
        Py_XDECREF(nulls);
        Py_DECREF(values);
        return nullptr;
    }
    int32_t *codes_data = (int32_t *)PyArray_DATA(codes);
    npy_bool *nulls_data = (npy_bool *)PyArray_DATA(nulls);
    PyObject **items = (PyObject **)PyArray_DATA(values);

    bool any_null = false;
    PyObject *prev = nullptr;  // run-of-equal-pointers fast path
    int32_t prev_code = 0;

    for (npy_intp i = 0; i < n; i++) {
        PyObject *v = items[i];
        if (v == Py_None) {
            codes_data[i] = 0;
            nulls_data[i] = NPY_TRUE;
            any_null = true;
            prev = nullptr;
            continue;
        }
        nulls_data[i] = NPY_FALSE;
        if (v == prev) {  // identical object repeated (common for
                          // low-cardinality columns)
            codes_data[i] = prev_code;
            continue;
        }
        PyObject *idx = PyDict_GetItemWithError(lookup, v);  // borrowed
        int32_t code;
        if (idx != nullptr) {
            code = (int32_t)PyLong_AsLong(idx);
        } else {
            if (PyErr_Occurred()) {
                goto fail;
            }
            code = (int32_t)PyList_GET_SIZE(store);
            PyObject *code_obj = PyLong_FromLong(code);
            if (code_obj == nullptr ||
                PyDict_SetItem(lookup, v, code_obj) < 0 ||
                PyList_Append(store, v) < 0) {
                Py_XDECREF(code_obj);
                goto fail;
            }
            Py_DECREF(code_obj);
        }
        codes_data[i] = code;
        prev = v;
        prev_code = code;
    }

    Py_DECREF(values);
    if (!any_null) {
        Py_DECREF(nulls);
        return Py_BuildValue("(NO)", codes, Py_None);
    }
    return Py_BuildValue("(NN)", codes, nulls);

fail:
    Py_DECREF(codes);
    Py_DECREF(nulls);
    Py_DECREF(values);
    return nullptr;
}

static PyMethodDef Methods[] = {
    {"encode_strings", encode_strings, METH_VARARGS,
     "Fused intern + dictionary-encode + null-mask pass over an object "
     "array of strings."},
    {nullptr, nullptr, 0, nullptr},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_fastingest",
    "Native ingest kernels for snappydata_tpu", -1, Methods,
};

PyMODINIT_FUNC PyInit__fastingest(void) {
    import_array();
    return PyModule_Create(&moduledef);
}

}  // extern "C"
