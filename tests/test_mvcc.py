"""MVCC snapshot isolation (storage/mvcc.py): statement-pinned storage
epochs decouple scans from ingest — a query reads ONE consistent
cross-table cut while ingest/DML/compaction publish freely, DDL racing
a pin either bumps the epoch cleanly or fails typed, matview syncs pin
the outer statement's epoch (base==view to the row), retained-epoch
bytes are ledgered and drain when readers release, and the WAL seq is
the commit timestamp recovery rebuilds the vector from.
"""

import json
import random
import threading
import urllib.request

import numpy as np
import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.observability.metrics import global_registry
from snappydata_tpu.storage import mvcc

pytestmark = pytest.mark.mvcc


def _counter(name: str) -> int:
    return global_registry().counter(name)


def _mk():
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE t (k INT, v DOUBLE) USING column")
    s.insert("t", (1, 1.0), (2, 2.0), (3, 3.0))
    return s


def _rows(s, sql):
    return s.sql(sql).rows()


# -- the core isolation contract ------------------------------------------

def test_pinned_reads_isolated_from_concurrent_ingest():
    """A pinned statement scope sees the epoch it pinned — inserts
    committed meanwhile are invisible until release, then visible."""
    s = _mk()
    with mvcc.pinned_scope(s.catalog, ["t"]) as pin:
        assert pin is not None and pin.epoch >= 1
        assert _rows(s, "SELECT count(*), sum(v) FROM t") == [(3, 6.0)]
        done = []

        def ingest():
            w = SnappySession(catalog=s.catalog)
            w.insert("t", (4, 4.0))
            done.append(True)

        th = threading.Thread(target=ingest)
        th.start()
        th.join(timeout=30)
        assert done, "ingest blocked behind a pinned reader"
        # repeated reads inside the pin: same epoch, same answer
        assert _rows(s, "SELECT count(*), sum(v) FROM t") == [(3, 6.0)]
        assert _rows(s, "SELECT sum(v) FROM t WHERE k >= 1") == [(6.0,)]
    assert _rows(s, "SELECT count(*), sum(v) FROM t") == [(4, 10.0)]
    s.stop()


def test_delete_and_update_invisible_to_pinned_reader():
    s = _mk()
    with mvcc.pinned_scope(s.catalog, ["t"]):
        assert _rows(s, "SELECT sum(v) FROM t") == [(6.0,)]
        w = SnappySession(catalog=s.catalog)
        w.sql("DELETE FROM t WHERE k = 1")
        w.sql("UPDATE t SET v = 100.0 WHERE k = 2")
        # the pinned epoch predates both mutations
        assert _rows(s, "SELECT sum(v) FROM t") == [(6.0,)]
        assert _rows(s, "SELECT v FROM t WHERE k = 2") == [(2.0,)]
    assert _rows(s, "SELECT sum(v) FROM t") == [(103.0,)]
    s.stop()


def test_cross_table_cut_is_atomic():
    """A join pins BOTH tables in one clock hold: commits land entirely
    before or entirely after the cut, never half."""
    s = _mk()
    s.sql("CREATE TABLE u (k INT, w DOUBLE) USING column")
    s.insert("u", (1, 10.0), (2, 20.0))
    with mvcc.pinned_scope(s.catalog, ["t", "u"]):
        w = SnappySession(catalog=s.catalog)
        w.insert("t", (9, 9.0))
        w.insert("u", (9, 90.0))
        assert _rows(s, "SELECT count(*) FROM t JOIN u ON t.k = u.k") \
            == [(2,)]
        assert _rows(s, "SELECT count(*) FROM t") == [(3,)]
        assert _rows(s, "SELECT count(*) FROM u") == [(2,)]
    assert _rows(s, "SELECT count(*) FROM t JOIN u ON t.k = u.k") == [(3,)]
    s.stop()


def test_row_table_repeatable_reads_under_pin():
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE r (k INT PRIMARY KEY, v DOUBLE) USING row")
    s.insert("r", (1, 1.0), (2, 2.0))
    with mvcc.pinned_scope(s.catalog, ["r"]):
        assert _rows(s, "SELECT sum(v) FROM r") == [(3.0,)]
        w = SnappySession(catalog=s.catalog)
        w.sql("UPDATE r SET v = 50.0 WHERE k = 1")
        w.insert("r", (3, 3.0))
        # the first pinned read captured the host snapshot: repeatable
        assert _rows(s, "SELECT sum(v) FROM r") == [(3.0,)]
    assert _rows(s, "SELECT sum(v) FROM r") == [(55.0,)]
    s.stop()


def test_every_statement_pins_by_default():
    """Plain session.sql pins without any explicit scope (the counters
    prove it), and snapshot_isolation=False turns pinning off."""
    s = _mk()
    p0 = _counter("mvcc_pins")
    _rows(s, "SELECT count(*) FROM t")
    assert _counter("mvcc_pins") == p0 + 1
    assert _counter("mvcc_pin_releases") >= 1
    s.conf.set("snapshot_isolation", "false")
    try:
        p1 = _counter("mvcc_pins")
        _rows(s, "SELECT count(*) FROM t")
        assert _counter("mvcc_pins") == p1
    finally:
        s.conf.set("snapshot_isolation", "true")
    s.stop()


# -- DDL vs pinned snapshots (satellite) ----------------------------------

def test_truncate_bumps_epoch_cleanly_under_pin():
    s = _mk()
    with mvcc.pinned_scope(s.catalog, ["t"]):
        assert _rows(s, "SELECT count(*) FROM t") == [(3,)]
        SnappySession(catalog=s.catalog).sql("TRUNCATE TABLE t")
        # pinned reader keeps its immutable epoch; no error, no torn read
        assert _rows(s, "SELECT count(*) FROM t") == [(3,)]
    assert _rows(s, "SELECT count(*) FROM t") == [(0,)]
    s.stop()


def test_add_column_and_drop_table_safe_under_pin():
    s = _mk()
    info = s.catalog.describe("t")
    with mvcc.pinned_scope(s.catalog, ["t"]):
        assert _rows(s, "SELECT sum(v) FROM t") == [(6.0,)]
        SnappySession(catalog=s.catalog).sql(
            "ALTER TABLE t ADD COLUMN extra DOUBLE")
        assert _rows(s, "SELECT sum(v) FROM t") == [(6.0,)]
        # DROP TABLE: catalog entry goes, the pinned manifest stays alive
        SnappySession(catalog=s.catalog).sql("DROP TABLE t")
        m = mvcc.current_pin().manifest_for(info.data)
        assert m.total_rows() == 3
    s.stop()


def test_drop_column_conflict_is_typed_sqlstate_40001():
    s = _mk()
    c0 = _counter("mvcc_ddl_conflicts")
    with mvcc.pinned_scope(s.catalog, ["t"]):
        _rows(s, "SELECT count(*) FROM t")
        with pytest.raises(mvcc.SnapshotConflictError) as ei:
            s.sql("ALTER TABLE t DROP COLUMN v")
        assert "40001" in str(ei.value)
        assert ei.value.sqlstate == "40001"
    assert _counter("mvcc_ddl_conflicts") == c0 + 1
    # readers drained: the retried DDL succeeds
    s.sql("ALTER TABLE t DROP COLUMN v")
    assert [f.name for f in s.catalog.describe("t").schema.fields] == ["k"]
    s.stop()


def test_drop_column_conflict_never_reaches_the_wal(tmp_path):
    """The typed conflict fires BEFORE journaling: recovery must not
    replay a DDL that never applied."""
    dirn = str(tmp_path / "store")
    s = SnappySession(data_dir=dirn)
    s.sql("CREATE TABLE d (a INT, b DOUBLE) USING column")
    s.sql("INSERT INTO d VALUES (1, 1.0)")
    with mvcc.pinned_scope(s.catalog, ["d"]):
        _rows(s, "SELECT count(*) FROM d")
        with pytest.raises(mvcc.SnapshotConflictError):
            s.sql("ALTER TABLE d DROP COLUMN b")
    s.disk_store.close()
    s2 = SnappySession(data_dir=dirn)
    assert [f.name for f in s2.catalog.describe("d").schema.fields] == \
        ["a", "b"], "a refused DDL leaked into the WAL"
    assert _rows(s2, "SELECT b FROM d") == [(1.0,)]
    s2.disk_store.close()


# -- matview sync under the outer epoch (satellite) ------------------------

def test_matview_sync_pins_same_epoch_as_outer_statement(tmp_path):
    """base and view read under ONE pinned epoch: the count of base rows
    and the view's folded count(*) agree EXACTLY in every statement,
    even with a committer hammering single-row inserts throughout."""
    s = SnappySession(data_dir=str(tmp_path / "store"))
    s.sql("CREATE TABLE base (k INT, v DOUBLE) USING column")
    s.insert("base", (1, 1.0), (2, 2.0))
    s.sql("CREATE MATERIALIZED VIEW mv AS SELECT k, count(*) AS c, "
          "sum(v) AS sv FROM base GROUP BY k")
    stop = threading.Event()
    errs = []

    def writer():
        w = SnappySession(catalog=s.catalog)
        w.disk_store = s.disk_store
        i = 0
        try:
            while not stop.is_set():
                w.insert("base", (i % 5, 1.0))
                i += 1
        except Exception as e:  # pragma: no cover
            errs.append(e)

    th = threading.Thread(target=writer, daemon=True)
    th.start()
    try:
        for _ in range(15):
            rows = _rows(s, "SELECT (SELECT count(*) FROM base) - "
                            "(SELECT sum(c) FROM mv) AS skew")
            assert rows == [(0,)], f"base-vs-view skew: {rows}"
    finally:
        stop.set()
        th.join(timeout=30)
    assert not errs, errs
    s.disk_store.close()


def test_stale_refresh_reads_under_outer_epoch(tmp_path):
    """The stale-exit full refresh rescans the base WITHOUT stalling
    committers, and the rebuilt view still matches the outer pinned
    epoch exactly (pending-fold journal replays raced commits)."""
    s = SnappySession(data_dir=str(tmp_path / "store"))
    s.sql("CREATE TABLE base (k INT, v DOUBLE) USING column")
    s.insert("base", *[(i % 7, float(i)) for i in range(500)])
    s.sql("CREATE MATERIALIZED VIEW mv AS SELECT k, count(*) AS c "
          "FROM base GROUP BY k")
    from snappydata_tpu.views import matviews

    mv = matviews(s.catalog)["mv"]
    stop = threading.Event()
    errs = []

    def writer():
        w = SnappySession(catalog=s.catalog)
        w.disk_store = s.disk_store
        i = 0
        try:
            while not stop.is_set():
                w.insert("base", (i % 7, 1.0))
                i += 1
        except Exception as e:  # pragma: no cover
            errs.append(e)

    th = threading.Thread(target=writer, daemon=True)
    th.start()
    try:
        for _ in range(5):
            mv.mark_stale("test")   # force the refresh_full path
            rows = _rows(s, "SELECT (SELECT count(*) FROM base) - "
                            "(SELECT sum(c) FROM mv) AS skew")
            assert rows == [(0,)], f"refresh left skew: {rows}"
    finally:
        stop.set()
        th.join(timeout=30)
    assert not errs, errs
    s.disk_store.close()


# -- review-round regressions ---------------------------------------------

def test_matview_folds_read_live_scratch_under_ambient_pin():
    """Two folds inside ONE pinned scope: the per-view scratch table is
    truncated + re-filled per fold, so it must read LIVE (an outer pin
    capturing it would serve fold #1's manifest to fold #2, silently
    double-counting the first delta and dropping the second)."""
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE fb (k INT, v DOUBLE) USING column")
    s.sql("CREATE MATERIALIZED VIEW fmv AS SELECT k, count(*) AS c, "
          "sum(v) AS sv FROM fb GROUP BY k")
    with mvcc.pinned_scope(s.catalog, ["fb"]):
        s.insert("fb", (1, 10.0))
        s.insert("fb", (1, 20.0))
        s.insert("fb", (2, 5.0))
    assert sorted(_rows(s, "SELECT k, c, sv FROM fmv")) \
        == [(1, 2, 30.0), (2, 1, 5.0)]
    assert _rows(s, "SELECT (SELECT count(*) FROM fb) - "
                    "(SELECT sum(c) FROM fmv)") == [(0,)]
    s.stop()


def test_matview_fold_then_reread_inside_one_pin():
    """Read view → fold → read view again, all under one pin: the sync
    repins base AND backing forward together, so the second read agrees
    with the base to the row (no internal base-vs-view skew)."""
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE rb (k INT, v DOUBLE) USING column")
    s.insert("rb", (1, 1.0))
    s.sql("CREATE MATERIALIZED VIEW rmv AS SELECT k, count(*) AS c "
          "FROM rb GROUP BY k")
    with mvcc.pinned_scope(s.catalog, ["rb"]):
        assert _rows(s, "SELECT sum(c) FROM rmv") == [(1,)]
        s.insert("rb", (1, 2.0))
        assert _rows(s, "SELECT (SELECT count(*) FROM rb) - "
                        "(SELECT sum(c) FROM rmv)") == [(0,)]
    s.stop()


def test_released_pin_extension_holds_nothing():
    """A straggler thread extending a RELEASED pin (copied context
    outliving the statement) reads live state and leaks no refcount —
    a leaked ref would block DROP COLUMN forever (40001) and keep
    retained-epoch bytes on the ledger."""
    s = _mk()
    data = s.catalog.describe("t").data
    pin = mvcc.SnapshotPin()
    pin.pin_many([data])
    assert mvcc.has_pins(data)
    pin.release()
    assert not mvcc.has_pins(data)
    # post-release extensions: live manifest, no refs taken
    m = pin.manifest_for(data)
    assert m is data.snapshot()
    assert not mvcc.has_pins(data)
    pin.release()   # idempotent
    s.sql("ALTER TABLE t DROP COLUMN v")   # no lingering 40001
    s.stop()


def test_ddl_scope_blocks_new_pins_during_remap():
    """The pin-admission side of the DDL fence: while an in-place remap
    is mid-flight (ddl_scope held), pin capture fails typed-and-
    retryable instead of traversing half-shifted state."""
    s = _mk()
    data = s.catalog.describe("t").data
    with mvcc.ddl_scope(data, "ALTER TABLE DROP COLUMN"):
        with pytest.raises(mvcc.SnapshotConflictError) as ei:
            with mvcc.pinned_scope(s.catalog, ["t"]):
                pass   # pragma: no cover
        assert ei.value.sqlstate == "40001"
        assert not mvcc.has_pins(data), "aborted capture must not leak refs"
    # gate released: pinning works again
    with mvcc.pinned_scope(s.catalog, ["t"]):
        assert _rows(s, "SELECT count(*) FROM t") == [(3,)]
    s.stop()


def test_row_snapshot_cache_makes_warm_pinned_binds_cheap():
    """The per-version host-snapshot cache: a second pinned statement
    over an unchanged row table must NOT re-materialize the whole table
    (O(table) Python-loop conversion per statement was the regression)."""
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE rc (k INT PRIMARY KEY, v DOUBLE) USING row")
    s.insert("rc", (1, 1.0), (2, 2.0))
    data = s.catalog.describe("rc").data
    assert _rows(s, "SELECT sum(v) FROM rc") == [(3.0,)]   # warm the cache
    calls = [0]
    orig = data.to_arrays_with_nulls

    def counting():
        calls[0] += 1
        return orig()

    data.to_arrays_with_nulls = counting
    try:
        assert _rows(s, "SELECT sum(v) FROM rc") == [(3.0,)]
        assert _rows(s, "SELECT sum(v) FROM rc") == [(3.0,)]
        assert calls[0] == 0, \
            f"warm pinned binds re-materialized the row table {calls[0]}x"
        # a mutation bumps the version: exactly one fresh capture
        s.sql("UPDATE rc SET v = 10.0 WHERE k = 1")
        assert _rows(s, "SELECT sum(v) FROM rc") == [(12.0,)]
        assert calls[0] >= 1
    finally:
        data.to_arrays_with_nulls = orig
    s.stop()


def test_pinned_row_bind_spares_live_device_cache_entry():
    """A pinned statement binding an OLDER captured row-table version
    must not evict the live version's cached DeviceTable — concurrent
    unpinned traffic would pay the O(table) rebuild on its next bind."""
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE lv (k INT PRIMARY KEY, v DOUBLE) USING row")
    s.insert("lv", (1, 1.0), (2, 2.0))
    data = s.catalog.describe("lv").data
    def unpinned(sql, out):
        # pins are contextvar-scoped: a fresh thread reads live
        w = SnappySession(catalog=s.catalog)
        out.append(w.sql(sql).rows())

    with mvcc.pinned_scope(s.catalog, ["lv"]):
        assert _rows(s, "SELECT sum(v) FROM lv") == [(3.0,)]   # pin @ v
        SnappySession(catalog=s.catalog).insert("lv", (3, 4.0))  # live moves
        got = []
        th = threading.Thread(target=unpinned,
                              args=("SELECT sum(v) FROM lv", got))
        th.start()
        th.join(timeout=60)
        assert got == [[(7.0,)]], got
        live_ver = data.version
        assert any(k[0] == live_ver for k in data._device_cache)
        # the pinned re-bind at the OLD captured version...
        assert _rows(s, "SELECT sum(v) FROM lv") == [(3.0,)]
        # ...leaves the live entry in place
        assert any(k[0] == live_ver for k in data._device_cache), \
            "pinned bind evicted the live version's device-cache entry"
    s.stop()


# -- retained epochs: ledger + degradation --------------------------------

def test_retained_epoch_bytes_ledgered_and_drain_on_release():
    from snappydata_tpu.observability.stats_service import mvcc_snapshot
    from snappydata_tpu.resource import global_broker

    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE big (k INT, v DOUBLE) USING column")
    s.insert("big", *[(i, float(i)) for i in range(50)])
    with mvcc.pinned_scope(s.catalog, ["big"]):
        _rows(s, "SELECT count(*) FROM big")
        w = SnappySession(catalog=s.catalog)
        w.sql("DELETE FROM big WHERE k < 10")          # delete-mask delta
        w.insert("big", *[(100 + i, 1.0) for i in range(40)])
        snap = mvcc_snapshot(s.catalog)
        assert snap["active_pins"] >= 1
        assert snap["retained_epoch_bytes"] > 0, \
            "a pinned old epoch must show on the ledger"
        assert "big" in snap["tables"]
        assert any(e["pins"] > 0
                   for e in snap["tables"]["big"]["retained_epochs"])
        # the broker ledger is PROCESS-wide (it sums every registered
        # catalog's tables), the snapshot is catalog-scoped: the ledger
        # line must carry at least this catalog's retained bytes
        ledger = global_broker().ledger()
        assert ledger["retained_epoch_bytes"] >= \
            snap["retained_epoch_bytes"]
    # readers drained: the degradation trim drains retained bytes to 0
    mvcc.trim_unpinned([("big", s.catalog.describe("big").data)])
    snap = mvcc_snapshot(s.catalog)
    assert snap["retained_epoch_bytes"] == 0
    s.stop()


def test_degradation_trim_counts_and_respects_pins():
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE tr (k INT) USING column")
    data = s.catalog.describe("tr").data
    for i in range(4):
        s.insert("tr", (i,))
    t0 = _counter("mvcc_epoch_trims")
    with mvcc.pinned_scope(s.catalog, ["tr"]) as pin:
        pinned = pin.manifest_for(data)
        SnappySession(catalog=s.catalog).insert("tr", (99,))
        trimmed = mvcc.trim_unpinned([("tr", data)])
        # the pinned manifest must survive the trim
        assert pinned.version in data._retained_epochs
        assert _rows(s, "SELECT count(*) FROM tr") == [(4,)]
    assert trimmed >= 0 and _counter("mvcc_epoch_trims") >= t0
    # unpinned history obeys the cap
    cap = int(s.conf.get("mvcc_retained_epochs", 2))
    unpinned = [v for v in data._retained_epochs
                if v != data.snapshot().version]
    assert len(unpinned) <= cap + 1
    s.stop()


# -- recovery: the WAL seq is the commit timestamp -------------------------

def test_recovery_rebuilds_epoch_fences(tmp_path):
    dirn = str(tmp_path / "store")
    s = SnappySession(data_dir=dirn)
    s.sql("CREATE TABLE f (k INT, v DOUBLE) USING column")
    s.sql("INSERT INTO f VALUES (1, 1.0)")
    s.sql("INSERT INTO f VALUES (2, 2.0)")
    m0 = s.catalog.describe("f").data.snapshot()
    assert m0.wal_seq > 0, "durable commits stamp their WAL seq"
    assert m0.epoch > 0
    s.checkpoint()
    s.disk_store.close()
    s2 = SnappySession(data_dir=dirn)
    m1 = s2.catalog.describe("f").data.snapshot()
    # the recovered manifest carries the checkpoint's fence, and the
    # epoch clock resumed PAST the pre-crash epochs
    assert m1.wal_seq >= m0.wal_seq
    assert mvcc.current_epoch() >= m0.epoch
    s2.sql("INSERT INTO f VALUES (3, 3.0)")
    m2 = s2.catalog.describe("f").data.snapshot()
    assert m2.epoch > m0.epoch, "post-recovery epochs stay monotone"
    assert m2.wal_seq > m1.wal_seq
    assert _rows(s2, "SELECT sum(v) FROM f") == [(6.0,)]
    s2.disk_store.close()


# -- HTAP chaos schedule (satellite) --------------------------------------

@pytest.mark.chaos
def test_htap_chaos_schedule(tmp_path):
    """Seeded HTAP schedule on a durable store: one committer sustains
    ingest while readers take pinned snapshot scans, with a kill→rejoin
    (crash-recovery) window in the middle.  Every snapshot read is
    value-asserted against a serialized replay (the cumulative log at
    the pinned version), no acked row is lost across the crash, and
    retained-epoch bytes return to baseline once readers drain."""
    from snappydata_tpu.observability.stats_service import mvcc_snapshot

    rng = random.Random(4242)
    dirn = str(tmp_path / "store")
    s = SnappySession(data_dir=dirn)
    s.sql("CREATE TABLE h (k INT, v DOUBLE) USING column")
    data = s.catalog.describe("h").data

    # serialized replay log: manifest version -> cumulative (count, sum)
    # (single committer => publishes are totally ordered)
    expected = {data.snapshot().version: (0, 0.0)}
    acked_rows = [0]
    acked_sum = [0.0]
    log_lock = threading.Lock()
    stop = threading.Event()
    errs = []

    def committer(sess):
        try:
            while not stop.is_set():
                n = rng.randint(1, 40)
                vals = [float(rng.randint(0, 9)) for _ in range(n)]
                sess.insert("h", *[(i, v) for i, v in enumerate(vals)])
                with log_lock:
                    acked_rows[0] += n
                    acked_sum[0] += sum(vals)
                    expected[data.snapshot().version] = (
                        acked_rows[0], acked_sum[0])
        except Exception as e:
            errs.append(e)

    def reader(sess, n_reads):
        import time as _time

        try:
            for _ in range(n_reads):
                with mvcc.pinned_scope(sess.catalog, ["h"]) as pin:
                    ver = pin.manifest_for(data).version
                    got = sess.sql(
                        "SELECT count(*), sum(v) FROM h").rows()[0]
                # the committer logs AFTER its insert returns — a pin
                # taken in that gap needs one beat for the log entry
                want = None
                for _spin in range(200):
                    with log_lock:
                        want = expected.get(ver)
                    if want is not None:
                        break
                    _time.sleep(0.01)
                assert want is not None, \
                    f"pinned version {ver} missing from the commit log"
                cnt = int(got[0])
                sm = float(got[1]) if got[1] is not None else 0.0
                assert (cnt, round(sm, 6)) == (want[0], round(want[1], 6)), \
                    f"snapshot@v{ver} read {got}, serialized replay " \
                    f"says {want}"
        except Exception as e:
            errs.append(e)

    w = threading.Thread(target=committer, args=(s,), daemon=True)
    readers = [threading.Thread(target=reader, args=(s, 8), daemon=True)
               for _ in range(2)]
    w.start()
    for r in readers:
        r.start()
    for r in readers:
        r.join(timeout=120)
    stop.set()
    w.join(timeout=30)
    assert not errs, errs
    assert not w.is_alive() and not any(r.is_alive() for r in readers)
    # ---- kill → rejoin window: abandon the session (no checkpoint, no
    # graceful close) and recover from WAL alone
    final_acked, final_sum = acked_rows[0], acked_sum[0]
    s2 = SnappySession(data_dir=dirn)
    got = s2.sql("SELECT count(*), sum(v) FROM h").rows()[0]
    assert int(got[0]) == final_acked, \
        f"acked rows lost across the crash: {got[0]} != {final_acked}"
    assert round(float(got[1]), 6) == round(final_sum, 6)
    # ---- post-rejoin: the schedule keeps running on the recovered store
    data2 = s2.catalog.describe("h").data
    expected.clear()
    expected[data2.snapshot().version] = (final_acked, final_sum)
    acked_rows[0], acked_sum[0] = final_acked, final_sum
    stop.clear()
    data = data2          # committer/reader closures read `data`
    w2 = threading.Thread(target=committer, args=(s2,), daemon=True)
    r2 = threading.Thread(target=reader, args=(s2, 5), daemon=True)
    w2.start()
    r2.start()
    r2.join(timeout=120)
    stop.set()
    w2.join(timeout=30)
    assert not errs, errs
    # ---- readers drained: retained-epoch bytes return to baseline
    mvcc.trim_unpinned([("h", data2)])
    snap = mvcc_snapshot(s2.catalog)
    assert snap["retained_epoch_bytes"] == 0, snap["retained_epoch_bytes"]
    assert snap["active_pins"] == 0
    s2.disk_store.close()


# -- observability surfaces -----------------------------------------------

def test_mvcc_snapshot_rest_and_dashboard():
    from snappydata_tpu.cluster.rest import RestService
    from snappydata_tpu.observability.stats_service import (
        TableStatsService, mvcc_snapshot)

    s = _mk()
    _rows(s, "SELECT count(*) FROM t")
    snap = mvcc_snapshot(s.catalog)
    assert snap["enabled"] and snap["current_epoch"] >= 1
    assert snap["pins"] >= 1
    assert "t" in snap["tables"]
    assert snap["tables"]["t"]["version"] >= 1
    svc = RestService(s, TableStatsService(s.catalog), port=0).start()
    try:
        with urllib.request.urlopen(
                f"http://{svc.host}:{svc.port}/status/api/v1/mvcc",
                timeout=5) as resp:
            body = json.loads(resp.read())
        assert body["enabled"] is True
        assert {"current_epoch", "active_pins", "pins", "ddl_conflicts",
                "retained_epoch_bytes", "tables"} <= set(body)
        assert "t" in body["tables"]
        with urllib.request.urlopen(
                f"http://{svc.host}:{svc.port}/dashboard",
                timeout=5) as resp:
            html = resp.read().decode()
        assert "Snapshot isolation" in html
    finally:
        svc.stop()
        s.stop()


def test_trace_annotates_pinned_epoch():
    from snappydata_tpu.observability import tracing

    s = _mk()
    with tracing.request_scope("SELECT count(*) FROM t", user="admin",
                               kind="test", force=True) as tr:
        _rows(s, "SELECT count(*) FROM t")
    attrs = tr.root.attrs
    assert "pinned_epoch" in attrs and int(attrs["pinned_epoch"]) >= 1
    s.stop()


# -- bench guard logic (satellite: the htap axis cannot silently slide) ---

def test_bench_htap_guard_logic():
    import bench

    base = {"value": 100.0, "detail": {
        "load_s": 10.0,
        "htap": {"concurrent": {"scan_p50_ms": 10.0},
                 "serialized": {"scan_p50_ms": 8.0},
                 "value_mismatches": 0}}}
    ok = {"value": 100.0, "detail": {
        "load_s": 10.0,
        "htap": {"concurrent": {"scan_p50_ms": 20.0},
                 "serialized": {"scan_p50_ms": 8.0},
                 "value_mismatches": 0}}}
    assert bench.check_regression(ok, base) == []
    bad_value = {"value": 100.0, "detail": {
        "load_s": 10.0,
        "htap": {"concurrent": {"scan_p50_ms": 9.0},
                 "serialized": {"scan_p50_ms": 8.0},
                 "value_mismatches": 3}}}
    msgs = bench.check_regression(bad_value, base)
    assert any("htap" in m for m in msgs), msgs
    blowup = {"value": 100.0, "detail": {
        "load_s": 10.0,
        "htap": {"concurrent": {"scan_p50_ms": 900.0},
                 "serialized": {"scan_p50_ms": 8.0},
                 "value_mismatches": 0}}}
    msgs = bench.check_regression(blowup, base)
    assert any("htap" in m for m in msgs), msgs
    # records predating the htap axis stay comparable
    assert bench.check_regression(
        {"value": 100.0, "detail": {"load_s": 10.0}}, base) == []
