"""ARRAY<T> column support (host-evaluated; ref: complex types surface,
ComplexTypeSerializer) — storage, literals, size/contains/element_at,
subscripts, NULLs, persistence."""

import numpy as np
import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog


@pytest.fixture()
def s():
    sess = SnappySession(catalog=Catalog())
    yield sess
    sess.stop()


def test_array_create_insert_select(s):
    s.sql("CREATE TABLE t (id INT, tags ARRAY<STRING>) USING column")
    s.sql("INSERT INTO t VALUES (1, array('a', 'b')), (2, array('c')), "
          "(3, NULL)")
    rows = s.sql("SELECT id, tags FROM t ORDER BY id").rows()
    assert rows[0] == (1, ["a", "b"])
    assert rows[1] == (2, ["c"])
    assert rows[2][1] is None


def test_array_functions(s):
    s.sql("CREATE TABLE t (id INT, v ARRAY<INT>) USING column")
    s.sql("INSERT INTO t VALUES (1, array(10, 20, 30)), (2, array(5))")
    assert s.sql("SELECT id, size(v) FROM t ORDER BY id").rows() == \
        [(1, 3), (2, 1)]
    assert s.sql("SELECT id FROM t WHERE array_contains(v, 20)").rows() == \
        [(1,)]
    # subscript (0-based) and element_at (1-based)
    assert s.sql("SELECT v[0], element_at(v, 2) FROM t WHERE id = 1"
                 ).rows() == [(10, 20)]
    # out-of-bounds → NULL
    assert s.sql("SELECT element_at(v, 9) FROM t WHERE id = 2"
                 ).rows()[0][0] is None


def test_array_rollover_and_nonarray_queries_stay_on_device(s):
    from snappydata_tpu.observability.metrics import global_registry

    s.sql("CREATE TABLE t (k INT, v ARRAY<INT>) USING column "
          "OPTIONS (column_max_delta_rows '4')")
    for i in range(10):
        s.sql(f"INSERT INTO t VALUES ({i}, array({i}, {i + 1}))")
    assert s.sql("SELECT size(v) FROM t WHERE k = 7").rows() == [(2,)]
    # a query not touching the array column still runs on device
    before = global_registry().counter("host_fallbacks")
    assert s.sql("SELECT sum(k) FROM t").rows()[0][0] == sum(range(10))
    assert global_registry().counter("host_fallbacks") == before


def test_array_contains_null_needle(s):
    # a NULL needle yields NULL (filtered out), not a match (review fix)
    s.sql("CREATE TABLE t (id INT, v ARRAY<INT>, nn INT) USING column")
    s.sql("INSERT INTO t VALUES (1, array(1, 2), 1), (2, array(3), NULL)")
    assert s.sql("SELECT id FROM t WHERE array_contains(v, nn)").rows() == \
        [(1,)]


def test_group_by_and_distinct_on_arrays(s):
    # unhashable list cells must not crash GROUP BY/DISTINCT (review fix)
    s.sql("CREATE TABLE t (id INT, v ARRAY<INT>) USING column")
    s.sql("INSERT INTO t VALUES (1, array(1, 2)), (2, array(1, 2)), "
          "(3, array(9))")
    assert s.sql("SELECT v, count(*) FROM t GROUP BY v ORDER BY 2 DESC"
                 ).rows() == [([1, 2], 2), ([9], 1)]
    assert len(s.sql("SELECT DISTINCT v FROM t").rows()) == 2


def test_numpy_array_cells_persist(tmp_path):
    # numpy values inside array cells serialize to the WAL (review fix)
    import numpy as np

    s = SnappySession(catalog=Catalog(), data_dir=str(tmp_path),
                      recover=False)
    s.sql("CREATE TABLE t (id INT, v ARRAY<INT>) USING column")
    s.insert("t", (1, np.array([1, 2])), (2, np.array([3, 4])))
    s.disk_store.close()
    s2 = SnappySession(data_dir=str(tmp_path))
    assert s2.sql("SELECT id, v FROM t ORDER BY id").rows() == \
        [(1, [1, 2]), (2, [3, 4])]


def test_array_persistence(tmp_path):
    s = SnappySession(catalog=Catalog(), data_dir=str(tmp_path),
                      recover=False)
    s.sql("CREATE TABLE t (id INT, v ARRAY<INT>) USING column")
    s.sql("INSERT INTO t VALUES (1, array(1, 2)), (2, NULL)")
    s.checkpoint()
    s.sql("INSERT INTO t VALUES (3, array(9))")  # WAL tail
    s.disk_store.close()
    s2 = SnappySession(data_dir=str(tmp_path))
    rows = s2.sql("SELECT id, v FROM t ORDER BY id").rows()
    assert rows == [(1, [1, 2]), (2, None), (3, [9])]


# --------------------------------------------------------------------------
# STRUCT type (ref: SerializedRow/ComplexTypeSerializer)
# --------------------------------------------------------------------------

def test_struct_ddl_insert_select(tmp_path):
    from snappydata_tpu import SnappySession

    s = SnappySession(data_dir=str(tmp_path / "st"))
    s.sql("CREATE TABLE pts (id INT, p STRUCT<x: DOUBLE, y: DOUBLE, "
          "label: STRING>) USING column")
    s.sql("INSERT INTO pts VALUES "
          "(1, named_struct('x', 1.5, 'y', 2.5, 'label', 'a')), "
          "(2, named_struct('x', 3.0, 'y', 4.0, 'label', 'b'))")
    rows = s.sql("SELECT id, p FROM pts ORDER BY id").rows()
    assert rows[0][1] == {"x": 1.5, "y": 2.5, "label": "a"}
    # field access via element_at, typed from the struct schema
    r = s.sql("SELECT id, element_at(p, 'x') + element_at(p, 'y') AS m "
              "FROM pts ORDER BY id").rows()
    assert r == [(1, 4.0), (2, 7.0)]
    # filters over struct fields
    r = s.sql("SELECT id FROM pts WHERE element_at(p, 'label') = 'b'"
              ).rows()
    assert r == [(2,)]
    # durability: checkpoint + recover preserves structs and their schema
    s.checkpoint()
    s.disk_store.close()
    s2 = SnappySession(data_dir=str(tmp_path / "st"))
    info = s2.catalog.describe("pts")
    assert info.schema.fields[1].dtype.name == "struct"
    assert info.schema.fields[1].dtype.field_type("label").name == "string"
    rows = s2.sql("SELECT id, element_at(p, 'label') FROM pts "
                  "ORDER BY id").rows()
    assert rows == [(1, "a"), (2, "b")]
    s2.disk_store.close()


# --------------------------------------------------------------------------
# device lowering of size/element_at/array_contains on numeric arrays
# --------------------------------------------------------------------------

def test_array_ops_on_device_no_fallback(session):
    from snappydata_tpu.observability.metrics import global_registry

    session.sql("CREATE TABLE av (id BIGINT, xs ARRAY<INT>) USING column")
    n = 20_000
    ids = np.arange(n, dtype=np.int64)
    xs = np.empty(n, dtype=object)
    for i in range(n):
        xs[i] = [int(i % 7), int(i % 3), int(i % 5)][: (i % 3) + 1]
    session.insert_arrays("av", [ids, xs])
    before = global_registry().snapshot()["counters"].get(
        "host_fallbacks", 0)
    r1 = session.sql("SELECT count(*) FROM av WHERE size(xs) = 2"
                     ).rows()[0][0]
    r2 = session.sql("SELECT sum(element_at(xs, 1)) FROM av").rows()[0][0]
    r3 = session.sql("SELECT count(*) FROM av WHERE array_contains(xs, 4)"
                     ).rows()[0][0]
    after = global_registry().snapshot()["counters"].get(
        "host_fallbacks", 0)
    assert after == before, "array ops fell back to host"
    exp1 = sum(1 for v in xs if len(v) == 2)
    exp2 = sum(v[0] for v in xs)
    exp3 = sum(1 for v in xs if 4 in v)
    assert r1 == exp1 and r2 == exp2 and r3 == exp3


def test_array_ops_device_null_semantics(session):
    session.sql("CREATE TABLE avn (id INT, xs ARRAY<DOUBLE>) USING column")
    xs = np.empty(4, dtype=object)
    xs[0] = [1.0, None, 3.0]
    xs[1] = [4.0]
    xs[2] = None
    xs[3] = []
    session.catalog.describe("avn").data.insert_arrays(
        [np.arange(4, dtype=np.int32), xs],
        nulls=[None, np.array([False, False, True, False])])
    rows = session.sql(
        "SELECT id, size(xs), element_at(xs, 2), "
        "array_contains(xs, 3.0) FROM avn ORDER BY id").rows()
    assert rows[0][1] == 3 and rows[0][2] is None and rows[0][3] is True
    assert rows[1][1] == 1 and rows[1][2] is None and rows[1][3] is False
    assert rows[2][2] is None
    assert rows[3][1] == 0 and rows[3][2] is None and rows[3][3] is False


def test_struct_bulk_insert_large(session):
    """Regression: batch stats tried to order dict values on bulk inserts
    (>1024 rows took the pandas min/max path and crashed)."""
    session.sql("CREATE TABLE stl (id BIGINT, m STRUCT<a: INT>) "
                "USING column")
    n = 20_000
    ms = np.empty(n, dtype=object)
    for i in range(n):
        ms[i] = {"a": i % 10}
    session.insert_arrays("stl", [np.arange(n, dtype=np.int64), ms])
    r = session.sql("SELECT count(*), sum(element_at(m, 'a')) FROM stl"
                    ).rows()[0]
    assert r[0] == n and r[1] == sum(i % 10 for i in range(n))


def test_string_array_device_ops(s):
    """ARRAY<STRING> columns bind as element-dictionary CODE plates:
    size / array_contains(lit) / element_at run ON DEVICE (round-5
    widening of the numeric-only fast path; ref SerializedArray)."""
    from snappydata_tpu.observability.metrics import global_registry

    s.sql("CREATE TABLE st (id INT, tags ARRAY<STRING>) USING column")
    s.sql("INSERT INTO st VALUES "
          "(1, array('red', 'green')), (2, array('blue')), "
          "(3, array('green', 'green', 'red')), (4, NULL)")
    before = global_registry().counter("host_fallbacks")
    rows = s.sql("SELECT id, size(tags), array_contains(tags, 'green'), "
                 "element_at(tags, 1) FROM st ORDER BY id").rows()
    assert rows[0] == (1, 2, True, "red")
    assert rows[1] == (2, 1, False, "blue")
    assert rows[2] == (3, 3, True, "green")
    assert rows[3][1] is None and rows[3][3] is None   # NULL array
    cnt = s.sql("SELECT count(*) FROM st "
                "WHERE array_contains(tags, 'red')").rows()[0][0]
    assert cnt == 2
    # absent needle: matches nothing (code -1)
    assert s.sql("SELECT count(*) FROM st WHERE "
                 "array_contains(tags, 'nope')").rows()[0][0] == 0
    assert global_registry().counter("host_fallbacks") == before

    # growth after bind: new element values re-dictionary cleanly
    s.sql("INSERT INTO st VALUES (5, array('cyan', 'red'))")
    rows2 = s.sql("SELECT element_at(tags, 1) FROM st WHERE id = 5").rows()
    assert rows2 == [("cyan",)]
    assert s.sql("SELECT count(*) FROM st "
                 "WHERE array_contains(tags, 'red')").rows()[0][0] == 3
    # non-literal needle / unsupported shapes still answer via host
    r = s.sql("SELECT id FROM st WHERE element_at(tags, 1) = 'red' "
              "ORDER BY id").rows()
    assert [x[0] for x in r] == [1]


def test_string_array_element_nulls_device(s):
    s.sql("CREATE TABLE sn (id INT, tags ARRAY<STRING>) USING column")
    s.sql("INSERT INTO sn VALUES (1, array('a', NULL, 'c'))")
    rows = s.sql("SELECT size(tags), element_at(tags, 2), "
                 "array_contains(tags, 'c') FROM sn").rows()
    assert rows[0][0] == 3
    assert rows[0][1] is None          # NULL element
    assert rows[0][2] is True


def test_string_array_null_needle_and_code_stability(s):
    from snappydata_tpu.catalog import Catalog as _C

    s.sql("CREATE TABLE nn2 (id INT, tags ARRAY<STRING>) USING column")
    s.sql("INSERT INTO nn2 VALUES (1, array('None', 'b'))")
    # NULL needle -> NULL result (NOT a match against the string 'None')
    r = s.sql("SELECT array_contains(tags, NULL) FROM nn2").rows()
    assert r == [(None,)]
    # append-only codes: lexically-earlier values arriving later must
    # not shift existing codes (the sorted-dictionary design did)
    s.sql("CREATE TABLE cs2 (id INT, tags ARRAY<STRING>) USING column")
    s.sql("INSERT INTO cs2 VALUES (1, array('zebra'))")
    assert s.sql("SELECT count(*) FROM cs2 WHERE "
                 "array_contains(tags, 'zebra')").rows()[0][0] == 1
    s.sql("INSERT INTO cs2 VALUES (2, array('apple'))")
    assert s.sql("SELECT element_at(tags, 1) FROM cs2 "
                 "ORDER BY id").rows() == [("zebra",), ("apple",)]
    assert s.sql("SELECT count(*) FROM cs2 WHERE "
                 "array_contains(tags, 'zebra')").rows()[0][0] == 1


def test_string_array_device_ops_survive_recovery(tmp_path):
    d = str(tmp_path / "store")
    s = SnappySession(data_dir=d)
    s.sql("CREATE TABLE ra (id INT, tags ARRAY<STRING>) USING column")
    s.sql("INSERT INTO ra VALUES (1, array('x', 'y')), (2, array('y'))")
    s.checkpoint()
    s.stop()
    s2 = SnappySession(data_dir=d)
    rows = s2.sql("SELECT id, size(tags), element_at(tags, 1) FROM ra "
                  "ORDER BY id").rows()
    assert rows == [(1, 2, "x"), (2, 1, "y")]
    assert s2.sql("SELECT count(*) FROM ra WHERE "
                  "array_contains(tags, 'y')").rows()[0][0] == 2
    s2.stop()
