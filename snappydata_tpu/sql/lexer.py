"""SQL tokenizer (hand-rolled; the reference rolls its own grammar too —
parboiled2 PEG, core/.../SnappyBaseParser.scala:26)."""

from __future__ import annotations

import dataclasses
from typing import List, Optional


class SQLSyntaxError(Exception):
    pass


@dataclasses.dataclass(frozen=True)
class Token:
    kind: str   # KW, IDENT, NUM, STR, OP, EOF
    value: str
    pos: int


KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "as", "and", "or", "not", "in", "between", "like", "is", "null",
    "case", "when", "then", "else", "end", "cast", "distinct", "all",
    "join", "inner", "left", "right", "full", "outer", "cross", "semi",
    "anti", "natural", "on", "using", "union", "intersect",
    "except", "minus", "asc", "desc", "nulls",
    "first", "last", "exists", "create", "table", "drop", "truncate",
    "insert", "put", "overwrite", "into", "values", "update", "set",
    "delete", "if", "temporary", "view", "replace", "show", "tables",
    "describe", "interval", "date", "timestamp", "true", "false",
    "primary", "key", "options", "external", "sample", "stream", "policy",
    "index", "alter", "add", "column", "deploy", "undeploy", "grant",
    "revoke", "with", "to", "exec", "scala", "over", "explain",
    "function", "returns", "materialized", "refresh",
}

_TWO_CHAR_OPS = {"<=", ">=", "<>", "!=", "||"}


def tokenize(sql: str) -> List[Token]:
    out: List[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
            continue
        if c == "-" and i + 1 < n and sql[i + 1] == "-":  # line comment
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if c == "/" and i + 1 < n and sql[i + 1] == "*":  # block comment
            j = sql.find("*/", i + 2)
            if j < 0:
                raise SQLSyntaxError(f"unterminated comment at {i}")
            i = j + 2
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            seen_dot = seen_exp = False
            while j < n:
                ch = sql[j]
                if ch.isdigit():
                    j += 1
                elif ch == "." and not seen_dot and not seen_exp:
                    seen_dot = True
                    j += 1
                elif ch in "eE" and not seen_exp and j > i:
                    seen_exp = True
                    j += 1
                    if j < n and sql[j] in "+-":
                        j += 1
                else:
                    break
            out.append(Token("NUM", sql[i:j], i))
            i = j
            continue
        if c == "'":
            j = i + 1
            buf = []
            while j < n:
                if sql[j] == "'" and j + 1 < n and sql[j + 1] == "'":
                    buf.append("'")
                    j += 2
                elif sql[j] == "'":
                    break
                else:
                    buf.append(sql[j])
                    j += 1
            if j >= n:
                raise SQLSyntaxError(f"unterminated string at {i}")
            out.append(Token("STR", "".join(buf), i))
            i = j + 1
            continue
        if c == '"' or c == "`":  # quoted identifier
            close = c
            j = sql.find(close, i + 1)
            if j < 0:
                raise SQLSyntaxError(f"unterminated identifier at {i}")
            out.append(Token("IDENT", sql[i + 1:j], i))
            i = j + 1
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            word = sql[i:j]
            kind = "KW" if word.lower() in KEYWORDS else "IDENT"
            out.append(Token(kind, word, i))
            i = j
            continue
        two = sql[i:i + 2]
        if two in _TWO_CHAR_OPS:
            out.append(Token("OP", two, i))
            i += 2
            continue
        if c in "+-*/%(),.=<>?;[]:":
            out.append(Token("OP", c, i))
            i += 1
            continue
        raise SQLSyntaxError(f"unexpected character {c!r} at {i}")
    out.append(Token("EOF", "", n))
    return out
