"""Widened SQL surface: CTEs, INTERSECT/EXCEPT, ROLLUP/CUBE/GROUPING SETS,
date/time functions, string functions, EXTRACT/position special forms.

Reference dialect: SnappyParser.scala (full Spark 2.1 function library via
Catalyst). Date math is integer civil-calendar arithmetic on device
(days-since-epoch int32, Hinnant's algorithms) — no datetime objects in
the hot path.
"""

import datetime

import numpy as np
import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog


@pytest.fixture
def sess():
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE t (k STRING, v BIGINT, d DATE) USING column")
    s.sql("INSERT INTO t VALUES ('a', 1, DATE '2020-01-15'), "
          "('b', 2, DATE '2020-06-30'), ('a', 3, DATE '2021-02-28')")
    return s


# -- CTEs -------------------------------------------------------------------

def test_cte_basic(sess):
    r = sess.sql("WITH x AS (SELECT k, sum(v) AS s FROM t GROUP BY k) "
                 "SELECT * FROM x ORDER BY k").rows()
    assert r == [("a", 4), ("b", 2)]


def test_cte_chained_and_joined(sess):
    r = sess.sql(
        "WITH big AS (SELECT k, v FROM t WHERE v >= 2), "
        "     agg AS (SELECT k, count(*) AS n FROM big GROUP BY k) "
        "SELECT t.k, agg.n FROM t JOIN agg ON t.k = agg.k "
        "ORDER BY t.k, agg.n").rows()
    assert r == [("a", 1), ("a", 1), ("b", 1)]


def test_cte_shadows_table(sess):
    r = sess.sql("WITH t AS (SELECT 99 AS v) SELECT v FROM t").rows()
    assert r == [(99,)]


# -- set operations ---------------------------------------------------------

def test_intersect_except(sess):
    assert sess.sql("SELECT k FROM t INTERSECT SELECT 'a'").rows() == \
        [("a",)]
    assert sess.sql("SELECT k FROM t EXCEPT SELECT 'a'").rows() == [("b",)]
    assert sess.sql("SELECT k FROM t MINUS SELECT 'a'").rows() == [("b",)]


def test_set_op_null_semantics(sess):
    # set ops treat NULLs as equal (unlike joins)
    sess.sql("CREATE TABLE n1 (x BIGINT) USING column")
    sess.sql("CREATE TABLE n2 (x BIGINT) USING column")
    sess.sql("INSERT INTO n1 VALUES (1), (NULL), (NULL)")
    sess.sql("INSERT INTO n2 VALUES (NULL), (2)")
    assert sess.sql("SELECT x FROM n1 INTERSECT SELECT x FROM n2").rows() \
        == [(None,)]
    r = sess.sql("SELECT x FROM n1 EXCEPT SELECT x FROM n2").rows()
    assert r == [(1,)]


def test_set_op_precedence_and_order(sess):
    # INTERSECT binds tighter than UNION; ORDER BY applies to the result
    r = sess.sql("SELECT k FROM t INTERSECT SELECT k FROM t "
                 "UNION SELECT 'z' ORDER BY k").rows()
    assert r == [("a",), ("b",), ("z",)]


def test_order_by_binds_to_union_not_right_arm(sess):
    r = sess.sql("SELECT k FROM t UNION SELECT 'z' ORDER BY k").rows()
    assert r == [("a",), ("b",), ("z",)]


# -- grouping sets ----------------------------------------------------------

def test_rollup(sess):
    r = sess.sql("SELECT k, count(*), sum(v) FROM t "
                 "GROUP BY ROLLUP(k) ORDER BY k").rows()
    assert r == [(None, 3, 6), ("a", 2, 4), ("b", 1, 2)]


def test_cube_two_level():
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE sales (region STRING, product STRING, amt BIGINT) "
          "USING column")
    s.sql("INSERT INTO sales VALUES ('e','x',10),('e','y',20),('w','x',5)")
    r = set(s.sql("SELECT region, product, sum(amt) FROM sales "
                  "GROUP BY CUBE(region, product)").rows())
    assert r == {(None, None, 35), ("e", None, 30), ("w", None, 5),
                 ("e", "x", 10), ("e", "y", 20), ("w", "x", 5),
                 (None, "x", 15), (None, "y", 20)}


def test_grouping_sets_with_having(sess):
    r = sess.sql("SELECT k, sum(v) FROM t "
                 "GROUP BY GROUPING SETS((k), ()) "
                 "HAVING sum(v) > 2 ORDER BY k").rows()
    assert r == [(None, 6), ("a", 4)]


# -- date/time functions ----------------------------------------------------

def _days(iso: str) -> int:
    return (datetime.date.fromisoformat(iso)
            - datetime.date(1970, 1, 1)).days


def test_date_functions_scalar(sess):
    one = lambda q: sess.sql(q).rows()[0][0]  # noqa: E731
    assert one("SELECT date_add(DATE '2020-01-01', 31)") == \
        _days("2020-02-01")
    assert one("SELECT date_sub(DATE '2020-01-01', 1)") == \
        _days("2019-12-31")
    assert one("SELECT datediff(DATE '2020-03-01', DATE '2020-02-01')") == 29
    assert one("SELECT add_months(DATE '2020-01-31', 1)") == \
        _days("2020-02-29")  # leap-year clamp
    assert one("SELECT last_day(DATE '2021-02-03')") == _days("2021-02-28")
    assert one("SELECT trunc(DATE '2020-02-15', 'MM')") == \
        _days("2020-02-01")
    assert one("SELECT trunc(DATE '2020-02-15', 'YEAR')") == \
        _days("2020-01-01")
    assert one("SELECT months_between(DATE '2020-03-15', "
               "DATE '2020-01-15')") == 2.0
    assert one("SELECT to_date('2020-07-04')") == _days("2020-07-04")
    assert one("SELECT unix_timestamp(TIMESTAMP '1970-01-02 00:00:00')") \
        == 86400
    assert one("SELECT extract(year FROM DATE '2020-01-02')") == 2020
    assert one("SELECT quarter(DATE '2020-05-15')") == 2
    assert one("SELECT dayofweek(DATE '2020-02-15')") == 7   # Saturday
    assert one("SELECT dayofyear(DATE '2020-03-01')") == 61  # leap year
    assert one("SELECT weekofyear(DATE '2021-01-01')") == 53  # ISO
    assert one("SELECT hour(TIMESTAMP '2020-01-01 10:30:05')") == 10
    assert one("SELECT minute(TIMESTAMP '2020-01-01 10:30:05')") == 30
    assert one("SELECT second(TIMESTAMP '2020-01-01 10:30:05')") == 5
    assert one("SELECT current_date() IS NOT NULL")
    assert one("SELECT current_timestamp() IS NOT NULL")


def test_date_functions_on_columns_device(sess):
    """Columnar date math runs through the device path (civil-calendar
    integer arithmetic) — verify against python datetime per row."""
    r = sess.sql("SELECT k, year(d), month(d), day(d), quarter(d), "
                 "dayofweek(d), date_add(d, 10) FROM t ORDER BY k, d").rows()
    expect_dates = {("a", "2020-01-15"), ("a", "2021-02-28"),
                    ("b", "2020-06-30")}
    got = set()
    for k, y, m, dd, q, dow, plus10 in r:
        date = datetime.date(y, m, dd)
        got.add((k, date.isoformat()))
        assert q == (m + 2) // 3
        assert dow == date.isoweekday() % 7 + 1
        assert plus10 == _days(date.isoformat()) + 10
    assert got == expect_dates


def test_group_by_date_part(sess):
    r = sess.sql("SELECT year(d), count(*) FROM t GROUP BY year(d) "
                 "ORDER BY year(d)").rows()
    assert r == [(2020, 2), (2021, 1)]


# -- string functions -------------------------------------------------------

def test_string_functions_scalar(sess):
    one = lambda q: sess.sql(q).rows()[0]  # noqa: E731
    assert one("SELECT lpad('x', 3, '0'), rpad('x', 3, '0')") == \
        ("00x", "x00")
    assert one("SELECT lpad('abcdef', 3, '0')") == ("abc",)  # truncates
    assert one("SELECT initcap('hello wORLD')") == ("Hello World",)
    assert one("SELECT repeat('ab', 3), reverse('abc')") == \
        ("ababab", "cba")
    assert one("SELECT split_part('a,b,c', ',', 2)") == ("b",)
    assert one("SELECT split_part('a,b,c', ',', -1)") == ("c",)
    assert one("SELECT split_part('a,b,c', ',', 9)") == ("",)
    assert one("SELECT translate('abcba', 'ab', 'x')") == ("xcx",)
    assert one("SELECT position('b' IN 'abc')") == (2,)
    assert one("SELECT ascii('A')") == (65,)


def test_string_functions_on_columns(sess):
    """String column transforms ride derived dictionaries — codes never
    leave the device."""
    r = sess.sql("SELECT DISTINCT initcap(repeat(k, 2)) FROM t "
                 "ORDER BY 1").rows()
    assert r == [("Aa",), ("Bb",)]
    r2 = sess.sql("SELECT count(*) FROM t WHERE ascii(k) = 97").rows()
    assert r2 == [(2,)]


def test_to_date_string_column_device():
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE logs (ts STRING) USING column")
    s.sql("INSERT INTO logs VALUES ('2020-01-01'), ('2020-01-01'), "
          "('2021-12-31')")
    r = s.sql("SELECT to_date(ts), count(*) FROM logs GROUP BY to_date(ts) "
              "ORDER BY 1").rows()
    assert r == [(_days("2020-01-01"), 2), (_days("2021-12-31"), 1)]


def test_current_date_not_baked_into_plan_cache(sess):
    """current_date folds per EXECUTION: the cached plan must rebind, not
    bake a stale clock (ref: tokenized-literal rebinding)."""
    r1 = sess.sql("SELECT count(*) FROM t WHERE d < current_date()").rows()
    r2 = sess.sql("SELECT count(*) FROM t WHERE d < current_date()").rows()
    assert r1 == r2 == [(3,)]
