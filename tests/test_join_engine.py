"""Device join engine (engine/executor._emit_join + ops/join.py):
one-to-many expansion, right/full outer NULL-extension, cached build
artifacts, reasoned host fallbacks — all verified by device-vs-host
bit-equivalence (the host pandas join is the oracle, reached through the
`device_join` knob's per-bind check), plus the bench Q3-class CI guards
(zero host fallbacks, O(1) build sorts across repeated executions)."""

import json
import urllib.request
import zlib

import numpy as np
import pytest

from snappydata_tpu import SnappySession, config
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.observability.metrics import global_registry


def _counter(name: str) -> int:
    return global_registry().counter(name)


@pytest.fixture()
def s():
    sess = SnappySession(catalog=Catalog())
    yield sess
    sess.stop()


@pytest.fixture()
def props():
    p = config.global_properties()
    saved = (p.get("device_join"), p.join_expand_max_bytes,
             p.join_build_cache_bytes, p.column_batch_rows,
             p.scan_tile_bytes)
    yield p
    (dj, cap, cache, rows, tile) = saved
    p.set("device_join", dj)
    p.join_expand_max_bytes = cap
    p.join_build_cache_bytes = cache
    p.column_batch_rows = rows
    p.scan_tile_bytes = tile


def _both_paths(sess, q):
    """(device rows, host-oracle rows, device-run fallback delta)."""
    p = config.global_properties()
    p.set("device_join", False)
    try:
        host = sess.sql(q).rows()
    finally:
        p.set("device_join", True)
    f0 = _counter("join_host_fallbacks")
    dev = sess.sql(q).rows()
    return dev, host, _counter("join_host_fallbacks") - f0


def _assert_rows_equal(dev, host):
    assert len(dev) == len(host), (dev, host)
    for d, h in zip(dev, host):
        assert len(d) == len(h), (d, h)
        for dv, hv in zip(d, h):
            if isinstance(hv, float) and isinstance(dv, float):
                assert dv == pytest.approx(hv, rel=1e-9, abs=1e-9), (d, h)
            else:
                assert dv == hv, (d, h)


# --- property tests: every join kind x non-unique builds x NULLs ---------

def _load_pair(sess, key_sql_type, keys_l, keys_r):
    """Two tables with (possibly NULL, possibly duplicate) join keys and
    a unique per-row payload so ORDER BY gives a total order."""
    sess.sql(f"CREATE TABLE tl (k {key_sql_type}, lv INT) USING column")
    sess.sql(f"CREATE TABLE tr (k {key_sql_type}, rv INT) USING column")
    for i, k in enumerate(keys_l):
        sess.insert("tl", (k, i))
    for i, k in enumerate(keys_r):
        sess.insert("tr", (k, 1000 + i))


def _keyset(rng, dtype, n):
    """Keys with duplicates on BOTH sides, misses, and ~15% NULLs."""
    if dtype == "BIGINT":
        pool = [int(v) for v in rng.integers(0, 8, 64)]
    elif dtype == "DOUBLE":
        pool = [float(v) * 0.5 for v in rng.integers(0, 8, 64)]
    else:  # VARCHAR
        pool = [f"k{v}" for v in rng.integers(0, 8, 64)]
    out = []
    for i in range(n):
        out.append(None if rng.random() < 0.15 else pool[i % len(pool)])
    return out


HOWS = ["JOIN", "LEFT JOIN", "RIGHT JOIN", "FULL JOIN"]


@pytest.mark.parametrize("how", HOWS)
@pytest.mark.parametrize("dtype", ["BIGINT", "DOUBLE", "VARCHAR"])
def test_join_device_matches_host(s, props, how, dtype):
    # crc32, not hash(): PYTHONHASHSEED randomizes str hashes per
    # process, which would make a caught mismatch non-reproducible
    rng = np.random.default_rng(zlib.crc32(f"{how}/{dtype}".encode()))
    _load_pair(s, dtype, _keyset(rng, dtype, 37), _keyset(rng, dtype, 23))
    q = (f"SELECT a.lv, b.rv FROM tl a {how} tr b ON a.k = b.k "
         f"ORDER BY a.lv NULLS LAST, b.rv NULLS LAST")
    dev, host, fallbacks = _both_paths(s, q)
    assert fallbacks == 0, "expected the device join path"
    _assert_rows_equal(dev, host)


@pytest.mark.parametrize("how", HOWS)
def test_join_empty_sides(s, props, how):
    _load_pair(s, "BIGINT", [1, 2, 2, None], [])
    q = (f"SELECT a.lv, b.rv FROM tl a {how} tr b ON a.k = b.k "
         f"ORDER BY a.lv NULLS LAST, b.rv NULLS LAST")
    dev, host, fallbacks = _both_paths(s, q)
    assert fallbacks == 0
    _assert_rows_equal(dev, host)
    # empty probe, non-empty build
    s.sql("DELETE FROM tl")
    dev, host, fallbacks = _both_paths(s, q)
    assert fallbacks == 0
    _assert_rows_equal(dev, host)


def test_semi_anti_non_unique_build(s, props):
    _load_pair(s, "BIGINT", [1, 2, 2, 3, None], [2, 2, 4, None])
    for shape in ("EXISTS", "NOT EXISTS"):
        q = (f"SELECT lv FROM tl a WHERE {shape} "
             f"(SELECT 1 FROM tr b WHERE b.k = a.k) ORDER BY lv")
        dev, host, fallbacks = _both_paths(s, q)
        assert fallbacks == 0
        _assert_rows_equal(dev, host)


def test_mixed_int_float_keys_small_values_stay_device(s, props):
    s.sql("CREATE TABLE fi (k DOUBLE, lv INT) USING column")
    s.sql("CREATE TABLE ii (k BIGINT, rv INT) USING column")
    s.sql("INSERT INTO fi VALUES (1.0, 1), (2.5, 2), (3.0, 3), (NULL, 4)")
    s.sql("INSERT INTO ii VALUES (1, 10), (3, 30), (3, 31), (4, 40)")
    q = ("SELECT a.lv, b.rv FROM fi a LEFT JOIN ii b ON a.k = b.k "
         "ORDER BY a.lv, b.rv NULLS LAST")
    dev, host, fallbacks = _both_paths(s, q)
    assert fallbacks == 0
    _assert_rows_equal(dev, host)


def test_mixed_int_float_key_2p53_routes_to_host(s, props):
    """int64 keys at |v| >= 2^53 are inexact in the float64 key domain
    (2^53+1 casts to 2^53.0): the bind check must reroute such joins to
    the host path with a REASONED counter, and the result must be
    bit-identical to the host oracle — the device must never silently
    diverge at the boundary."""
    big = 1 << 53
    s.sql("CREATE TABLE fk (k DOUBLE, lv INT) USING column")
    s.sql("CREATE TABLE ik (k BIGINT, rv INT) USING column")
    s.sql(f"INSERT INTO fk VALUES ({float(big)}, 1), (2.0, 2)")
    # big+1 is NOT representable in float64
    s.sql(f"INSERT INTO ik VALUES ({big + 1}, 10), (2, 20)")
    r0 = _counter("join_fallback_int_float_key_2p53")
    dev, host, fallbacks = _both_paths(
        s, "SELECT a.lv, b.rv FROM fk a JOIN ik b ON a.k = b.k "
           "ORDER BY a.lv")
    assert fallbacks > 0
    assert _counter("join_fallback_int_float_key_2p53") > r0
    _assert_rows_equal(dev, host)


def test_mixed_int_float_below_2p53_exact_on_device(s, props):
    v = (1 << 53) - 1
    s.sql("CREATE TABLE fk2 (k DOUBLE, lv INT) USING column")
    s.sql("CREATE TABLE ik2 (k BIGINT, rv INT) USING column")
    s.sql(f"INSERT INTO fk2 VALUES ({float(v)}, 1)")
    s.sql(f"INSERT INTO ik2 VALUES ({v}, 10), ({v - 2}, 20)")
    dev, host, fallbacks = _both_paths(
        s, "SELECT a.lv, b.rv FROM fk2 a JOIN ik2 b ON a.k = b.k")
    assert fallbacks == 0
    _assert_rows_equal(dev, host)
    assert dev == [(1, 10)]


def test_residual_on_inner_expansion(s, props):
    _load_pair(s, "BIGINT", [1, 2, 2, 3], [2, 2, 3, 3])
    q = ("SELECT a.lv, b.rv FROM tl a JOIN tr b "
         "ON a.k = b.k AND b.rv > 1001 ORDER BY a.lv, b.rv")
    dev, host, fallbacks = _both_paths(s, q)
    assert fallbacks == 0
    _assert_rows_equal(dev, host)


def test_residual_on_outer_falls_back_reasoned(s, props):
    _load_pair(s, "BIGINT", [1, 2], [2, 2])
    r0 = _counter("join_fallback_residual_outer")
    dev, host, _ = _both_paths(
        s, "SELECT a.lv, b.rv FROM tl a LEFT JOIN tr b "
           "ON a.k = b.k AND b.rv > 1000 "
           "ORDER BY a.lv, b.rv NULLS LAST")
    assert _counter("join_fallback_residual_outer") > r0
    _assert_rows_equal(dev, host)


# --- expansion buckets + caches ------------------------------------------

def test_expansion_bucket_recompiles_as_duplicates_grow(s, props):
    """Growing build duplication crosses {2^k, 1.5*2^k} bucket edges:
    each growth step must stay correct (fresh statics re-specialize the
    executable, no stale-shape reuse)."""
    s.sql("CREATE TABLE gp (k BIGINT, lv INT) USING column")
    s.sql("CREATE TABLE gb (k BIGINT, rv INT) USING column")
    for i in range(8):
        s.insert("gp", (i % 4, i))
    out0 = _counter("join_expand_out_rows")
    total = 0
    for step in range(4):
        for i in range(6 * (step + 1)):
            s.insert("gb", (i % 4, total + i))
        total += 6 * (step + 1)
        q = ("SELECT a.lv, b.rv FROM gp a JOIN gb b ON a.k = b.k "
             "ORDER BY a.lv, b.rv")
        dev, host, fallbacks = _both_paths(s, q)
        assert fallbacks == 0
        _assert_rows_equal(dev, host)
    assert _counter("join_expand_out_rows") > out0


def test_build_cache_hits_and_invalidation_on_mutation(s, props):
    s.sql("CREATE TABLE cp (k BIGINT, lv INT) USING column")
    s.sql("CREATE TABLE cb (k BIGINT, rv INT) USING column")
    for i in range(10):
        s.insert("cp", (i % 5, i))
    for i in range(12):
        s.insert("cb", (i % 5, i))
    q = ("SELECT a.lv, b.rv FROM cp a JOIN cb b ON a.k = b.k "
         "ORDER BY a.lv, b.rv")
    s.sql(q)  # first run pays the ONE build argsort
    s0 = _counter("join_build_sorts")
    h0 = _counter("join_build_cache_hits")
    for _ in range(3):
        s.sql(q)
    assert _counter("join_build_sorts") == s0, \
        "repeated executions must reuse the cached build artifact"
    assert _counter("join_build_cache_hits") > h0
    # build-side mutation rotates the bind identity -> fresh sort
    s.insert("cb", (1, 99))
    before = s.sql(q).rows()
    assert _counter("join_build_sorts") == s0 + 1
    # correctness after invalidation
    dev, host, _ = _both_paths(s, q)
    _assert_rows_equal(dev, host)
    assert before == dev


def test_expand_bound_not_shared_across_probe_key_columns(s, props):
    """Two queries probing the SAME build snapshot on DIFFERENT probe
    key columns must not share a memoized expansion bound (regression:
    the bound memo used to key on probe identity alone) — a stale
    too-small bound trips the in-trace overflow and silently reroutes
    every execution of the second query to the host path."""
    s.sql("CREATE TABLE pb (few BIGINT, many BIGINT, lv INT) "
          "USING column")
    s.sql("CREATE TABLE bb (k BIGINT, rv INT) USING column")
    for i in range(8):
        s.insert("pb", (100 + i, i % 2, i))   # `few` matches NOTHING
        s.insert("bb", (i % 2, 10 + i))       # hot keys 0/1: 4 dups each
    q_few = ("SELECT a.lv, b.rv FROM pb a JOIN bb b ON a.few = b.k "
             "ORDER BY a.lv, b.rv")
    q_many = ("SELECT a.lv, b.rv FROM pb a JOIN bb b ON a.many = b.k "
              "ORDER BY a.lv, b.rv")
    props.set("device_join", False)
    host_few = s.sql(q_few).rows()
    host_many = s.sql(q_many).rows()
    props.set("device_join", True)
    g0 = _counter("host_fallbacks")
    dev_few = s.sql(q_few).rows()       # bound 0: bucket stays minimal
    dev_many = s.sql(q_many).rows()     # needs its OWN (32-row) bound
    assert _counter("host_fallbacks") == g0, \
        "a stale shared expansion bound tripped the overflow reroute"
    _assert_rows_equal(dev_few, host_few)
    _assert_rows_equal(dev_many, host_many)


def test_build_cache_disabled_still_joins_on_device(s, props):
    props.join_build_cache_bytes = 0
    s.sql("CREATE TABLE dp (k BIGINT, lv INT) USING column")
    s.sql("CREATE TABLE db (k BIGINT, rv INT) USING column")
    for i in range(6):
        s.insert("dp", (i % 3, i))
        s.insert("db", (i % 3, 10 + i))
    q = ("SELECT a.lv, b.rv FROM dp a JOIN db b ON a.k = b.k "
         "ORDER BY a.lv, b.rv")
    s0 = _counter("join_build_sorts")
    dev, host, fallbacks = _both_paths(s, q)
    assert fallbacks == 0
    _assert_rows_equal(dev, host)
    s.sql(q)
    # no cache: ONE re-sort per bind (exactly — the aux builder shares
    # its artifact with the mode provider within a bind)
    assert _counter("join_build_sorts") == s0 + 2


def test_expand_cap_falls_back_loud_and_correct(s, props):
    props.join_expand_max_bytes = 64  # absurdly small: force the cap
    s.sql("CREATE TABLE xp (k BIGINT, lv INT) USING column")
    s.sql("CREATE TABLE xb (k BIGINT, rv INT) USING column")
    for i in range(8):
        s.insert("xp", (i % 2, i))
        s.insert("xb", (i % 2, 10 + i))
    r0 = _counter("join_fallback_expand_bytes")
    dev, host, fallbacks = _both_paths(
        s, "SELECT a.lv, b.rv FROM xp a JOIN xb b ON a.k = b.k "
           "ORDER BY a.lv, b.rv")
    assert fallbacks > 0
    assert _counter("join_fallback_expand_bytes") > r0
    _assert_rows_equal(dev, host)


def test_expand_cap_covers_right_outer_build_extension(s, props):
    """Right/full outer appends one output slot per build flat row;
    those extension slots count against join_expand_max_bytes even on
    a UNIQUE build (regression: the unique fast path used to skip the
    cap entirely, so a huge build could OOM the device instead of
    taking the documented loud host fallback)."""
    props.join_expand_max_bytes = 64  # absurdly small: force the cap
    s.sql("CREATE TABLE yp (k BIGINT, lv INT) USING column")
    s.sql("CREATE TABLE yb (k BIGINT, rv INT) USING column")
    for i in range(8):
        s.insert("yp", (i, i))
        s.insert("yb", (i, 10 + i))   # unique build keys
    r0 = _counter("join_fallback_expand_bytes")
    dev, host, fallbacks = _both_paths(
        s, "SELECT a.lv, b.rv FROM yp a RIGHT JOIN yb b ON a.k = b.k "
           "ORDER BY b.rv")
    assert fallbacks > 0
    assert _counter("join_fallback_expand_bytes") > r0
    _assert_rows_equal(dev, host)


# --- join-aware tiled probe ----------------------------------------------

def test_tiled_probe_join_aggregate(s, props):
    """A join+aggregate over an oversized fact table tiles the PROBE
    side while the build side stays device-resident; per-tile partials
    merge on device (dict group key)."""
    props.column_batch_rows = 256
    rng = np.random.default_rng(11)
    n = 4000
    s.sql("CREATE TABLE fact (fk BIGINT, v DOUBLE) USING column")
    s.catalog.describe("fact").data.insert_arrays(
        [rng.integers(1, 40, n, dtype=np.int64),
         rng.normal(10.0, 2.0, n)])
    s.sql("CREATE TABLE dim (id BIGINT, seg STRING) USING column")
    s.catalog.describe("dim").data.insert_arrays(
        [np.arange(1, 40, dtype=np.int64),
         np.array([f"s{i % 3}" for i in range(1, 40)], dtype=object)])
    q = ("SELECT seg, count(*), sum(v) FROM fact JOIN dim ON fk = id "
         "GROUP BY seg ORDER BY seg")
    untiled = s.sql(q).rows()
    props.scan_tile_bytes = 3 * 256 * 32
    t0 = _counter("scan_tiles")
    d0 = _counter("scan_tile_device_merges")
    got = s.sql(q).rows()
    tiles = _counter("scan_tiles") - t0
    assert tiles > 1, "expected the tiled join-probe pass"
    assert _counter("scan_tile_device_merges") - d0 == tiles - 1
    assert len(got) == len(untiled)
    for (ek, ec, es), (gk, gc, gs) in zip(untiled, got):
        assert ek == gk and ec == gc
        assert gs == pytest.approx(es, rel=1e-9)


def test_tiled_probe_never_tiles_right_or_full(s, props):
    """Tiling the probe of a right/full join would re-emit unmatched
    build rows per tile — the shape probe must refuse."""
    props.column_batch_rows = 256
    s.sql("CREATE TABLE f2 (fk BIGINT, v DOUBLE) USING column")
    s.catalog.describe("f2").data.insert_arrays(
        [np.arange(3000, dtype=np.int64) % 7,
         np.ones(3000)])
    s.sql("CREATE TABLE d2 (id BIGINT, w DOUBLE) USING column")
    s.catalog.describe("d2").data.insert_arrays(
        [np.arange(9, dtype=np.int64), np.ones(9)])
    q = ("SELECT count(*), sum(w) FROM f2 RIGHT JOIN d2 ON fk = id")
    untiled = s.sql(q).rows()
    props.scan_tile_bytes = 3 * 256 * 32
    t0 = _counter("scan_tiles")
    got = s.sql(q).rows()
    assert _counter("scan_tiles") == t0, "right joins must not tile"
    assert got[0][0] == untiled[0][0]


# --- bench Q3-class CI guards --------------------------------------------

def test_bench_q3_class_stays_on_device_with_o1_sorts(s, props):
    """The bench's Q3-class query (tpch.Q3C) must compile to the DEVICE
    join — zero host fallbacks — and repeated executions must reuse the
    cached build artifact (exactly ONE argsort across all runs)."""
    from snappydata_tpu.utils import tpch

    tpch.load_tpch(s, sf=0.002, seed=3)
    f0 = _counter("join_host_fallbacks")
    s0 = _counter("join_build_sorts")
    d0 = _counter("join_device_joins")
    first = s.sql(tpch.Q3C).rows()
    for _ in range(3):
        assert s.sql(tpch.Q3C).rows() == first
    assert _counter("join_host_fallbacks") - f0 == 0, \
        "Q3-class bench query left the device path"
    assert _counter("join_build_sorts") - s0 == 1, \
        "build sorts must be O(1) across repeated executions"
    assert _counter("join_device_joins") - d0 == 4
    # full value assertion against the host join
    p = config.global_properties()
    p.set("device_join", False)
    try:
        host = s.sql(tpch.Q3C).rows()
    finally:
        p.set("device_join", True)
    _assert_rows_equal(first, host)


def test_string_translation_lut_cached_and_vectorized(s, props):
    """String-key joins translate probe codes via the vectorized LUT;
    repeated binds hit the (left-version, right-version) cache."""
    s.sql("CREATE TABLE sl (k VARCHAR, lv INT) USING column")
    s.sql("CREATE TABLE sr (k VARCHAR, rv INT) USING column")
    for i in range(20):
        s.insert("sl", (f"s{i % 6}", i))
    for i in range(15):
        s.insert("sr", (f"s{i % 9}", 100 + i))
    q = ("SELECT a.lv, b.rv FROM sl a JOIN sr b ON a.k = b.k "
         "ORDER BY a.lv, b.rv")
    dev, host, fallbacks = _both_paths(s, q)
    assert fallbacks == 0
    _assert_rows_equal(dev, host)
    t0 = _counter("join_trans_cache_hits")
    s.sql(q)
    assert _counter("join_trans_cache_hits") > t0
    # dictionary growth (append-only: length is the version) must
    # invalidate the LUT, not serve a stale one
    s.insert("sr", ("s5", 990))
    dev2, host2, _ = _both_paths(s, q)
    _assert_rows_equal(dev2, host2)


def test_rest_join_endpoint_and_dashboard(s, props):
    from snappydata_tpu.cluster.rest import RestService
    from snappydata_tpu.observability.stats_service import \
        TableStatsService

    s.sql("CREATE TABLE ja (k BIGINT, v INT) USING column")
    s.sql("CREATE TABLE jb (k BIGINT, w INT) USING column")
    s.sql("INSERT INTO ja VALUES (1, 1), (2, 2)")
    s.sql("INSERT INTO jb VALUES (1, 10), (1, 11)")
    s.sql("SELECT a.v, b.w FROM ja a JOIN jb b ON a.k = b.k")
    svc = RestService(s, TableStatsService(s.catalog), port=0).start()
    try:
        with urllib.request.urlopen(
                f"http://{svc.host}:{svc.port}/status/api/v1/join",
                timeout=5) as resp:
            body = json.loads(resp.read())
        assert body["join_device_joins"] > 0
        assert isinstance(body["join_fallback_reasons"], dict)
        assert {"join_build_cache_hits", "join_build_sorts",
                "join_expand_out_rows", "join_host_fallbacks",
                "join_build_cache_nbytes"} <= set(body)
        with urllib.request.urlopen(
                f"http://{svc.host}:{svc.port}/dashboard",
                timeout=5) as resp:
            html = resp.read().decode()
        assert "Join engine" in html
    finally:
        svc.stop()


def test_broker_ledger_carries_join_cache_bytes(s, props):
    from snappydata_tpu.resource import global_broker

    s.sql("CREATE TABLE la (k BIGINT, v INT) USING column")
    s.sql("CREATE TABLE lb (k BIGINT, w INT) USING column")
    for i in range(50):
        s.insert("la", (i % 10, i))
        s.insert("lb", (i % 10, i))
    s.sql("SELECT a.v, b.w FROM la a JOIN lb b ON a.k = b.k LIMIT 1")
    ledger = global_broker().ledger()
    assert "join_build_cache_bytes" in ledger
    assert ledger["device_total"] >= ledger["join_build_cache_bytes"]
