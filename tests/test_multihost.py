"""Multi-host wiring (SURVEY §7 step 4): jax.distributed argument
plumbing and local-device submesh selection. These unit tests pin the
single-host no-op path and env/flag precedence with a monkeypatched
initialize; the REAL two-process `jax.distributed.initialize` bring-up
(executed, not mocked) lives in tests/test_multihost_real.py."""

import importlib

import pytest

from snappydata_tpu.parallel import multihost


@pytest.fixture(autouse=True)
def fresh(monkeypatch):
    importlib.reload(multihost)
    yield


def test_noop_without_coordinator(monkeypatch):
    monkeypatch.delenv("SNAPPY_COORDINATOR", raising=False)
    assert multihost.initialize_multihost() is False


def test_env_configuration(monkeypatch):
    calls = {}

    def fake_init(coordinator_address, num_processes, process_id):
        calls.update(coordinator=coordinator_address,
                     n=num_processes, pid=process_id)

    import jax

    monkeypatch.setattr(jax.distributed, "initialize", fake_init)
    monkeypatch.setenv("SNAPPY_COORDINATOR", "10.0.0.1:8476")
    monkeypatch.setenv("SNAPPY_NUM_PROCESSES", "4")
    monkeypatch.setenv("SNAPPY_PROCESS_ID", "2")
    assert multihost.initialize_multihost() is True
    assert calls == {"coordinator": "10.0.0.1:8476", "n": 4, "pid": 2}
    # second call: no-op, no re-init
    calls.clear()
    assert multihost.initialize_multihost() is True
    assert calls == {}


def test_flag_overrides_env(monkeypatch):
    calls = {}
    import jax

    monkeypatch.setattr(
        jax.distributed, "initialize",
        lambda coordinator_address, num_processes, process_id:
        calls.update(c=coordinator_address, n=num_processes,
                     p=process_id))
    monkeypatch.setenv("SNAPPY_COORDINATOR", "env:1")
    assert multihost.initialize_multihost("flag:2", 8, 3) is True
    assert calls == {"c": "flag:2", "n": 8, "p": 3}


def test_local_device_indices_single_host():
    # on one host, local == global (the 8 virtual CPU devices)
    idx = multihost.local_device_indices()
    import jax

    assert idx == list(range(len(jax.devices())))
    from snappydata_tpu.parallel.mesh import submesh

    m = submesh(idx[:4])
    assert m.devices.size == 4
